"""Byte-golden wire fixtures (regression pins) + decode fuzzing.

The reference pins its wire format with byte-exact fixtures (SURVEY §4.2);
these goldens freeze ours so layout drift is loud. The fuzz check asserts
the parser's total failure mode is DecodeError — never a crash.
"""

import random


from xaynet_tpu.core.crypto.prng import uniform_ints
from xaynet_tpu.core.crypto.sign import SigningKeyPair
from xaynet_tpu.core.mask import (
    BoundType,
    DataType,
    GroupType,
    MaskConfig,
    MaskObject,
    ModelType,
)
from xaynet_tpu.core.mask.serialization import serialize_mask_object
from xaynet_tpu.core.message import DecodeError, Message, Sum, Tag

CFG = MaskConfig(GroupType.PRIME, DataType.F32, BoundType.B0, ModelType.M3)
KEYS = SigningKeyPair.derive_from_seed(b"\x01" * 32)


def test_mask_object_golden_bytes():
    ints = uniform_ints(b"\x02" * 32, 3, CFG.order)
    obj = MaskObject.new(CFG.pair(), ints[1:], ints[0])
    wire = serialize_mask_object(obj)
    # config(01 00 00 03) ‖ count(00000002 BE) ‖ 2x 6-byte LE ‖ config ‖ 6-byte LE
    assert wire.hex() == (
        "0100000300000002"  # vect config + count
        + ints[1].to_bytes(6, "little").hex()
        + ints[2].to_bytes(6, "little").hex()
        + "01000003"  # unit config
        + ints[0].to_bytes(6, "little").hex()
    )


def test_sum_message_golden_layout():
    msg = Message(
        participant_pk=KEYS.public,
        coordinator_pk=b"\x09" * 32,
        payload=Sum(sum_signature=b"\x0a" * 64, ephm_pk=b"\x0b" * 32),
    )
    wire = msg.to_bytes(KEYS.secret)
    assert len(wire) == 136 + 96
    assert wire[64:96] == KEYS.public  # participant pk
    assert wire[96:128] == b"\x09" * 32  # coordinator pk
    assert wire[128:132] == (232).to_bytes(4, "big")  # length field
    assert wire[132] == int(Tag.SUM) and wire[133] == 0  # tag, flags
    assert wire[136 : 136 + 64] == b"\x0a" * 64  # sum signature
    assert wire[200:232] == b"\x0b" * 32  # ephemeral pk
    # deterministic (ed25519 signatures are deterministic)
    assert msg.to_bytes(KEYS.secret) == wire


def test_decode_fuzz_never_crashes():
    msg = Message(
        participant_pk=KEYS.public,
        coordinator_pk=b"\x09" * 32,
        payload=Sum(sum_signature=b"\x0a" * 64, ephm_pk=b"\x0b" * 32),
    )
    wire = bytearray(msg.to_bytes(KEYS.secret))
    rng = random.Random(0)
    for _ in range(300):
        mutated = bytearray(wire)
        for _ in range(rng.randint(1, 8)):
            mutated[rng.randrange(len(mutated))] = rng.randrange(256)
        try:
            Message.from_bytes(bytes(mutated))
        except DecodeError:
            pass  # the only acceptable failure mode
    for n in (0, 1, 64, 135, 137):
        try:
            Message.from_bytes(bytes(wire[:n]))
        except DecodeError:
            pass
