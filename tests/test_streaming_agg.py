"""Streaming aggregation pipeline (parallel.streaming) correctness.

The pipeline moves staging, folding, and acceptance syncs off the caller's
critical path; these tests pin the property everything rests on —
**byte-identity with the sequential path** — across fold kernels
(including the native host kernel), for both planar and raw-wire submits,
under dispatch-ahead schedules where the producer runs several batches
ahead of late-completing folds, plus the batch-prevalidation single-
dispatch contract and the settings/metrics surface.
"""

import time

import numpy as np
import pytest

import jax

from xaynet_tpu.core.mask import (
    Aggregation,
    BoundType,
    DataType,
    GroupType,
    Masker,
    MaskConfig,
    ModelType,
    Scalar,
)
from xaynet_tpu.core.mask.serialization import serialize_mask_vect, vect_element_block
from xaynet_tpu.parallel.aggregator import ShardedAggregator
from xaynet_tpu.parallel.mesh import make_mesh
from xaynet_tpu.parallel.streaming import (
    BATCHES_TOTAL,
    INFLIGHT_FOLDS,
    STAGING_DEPTH,
    StreamingAggregator,
    StreamingError,
)

CFG = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M6)

# these tests pin device 0 explicitly (the conftest forces 8 virtual CPU
# devices) to exercise the SINGLE-WORKER pipeline; the shard-parallel
# multi-device mode has its own suite in tests/test_shard_parallel.py
KERNELS = ("xla", "native-u64", "auto")


def _mesh1():
    return make_mesh(jax.devices()[:1])


def _updates(n, total, seed=0):
    rng = np.random.default_rng(seed)
    host = Aggregation(CFG.pair(), n)
    stacks, raws = [], []
    for _ in range(total):
        w = rng.uniform(-1, 1, size=n).astype(np.float32)
        _, masked = Masker(CFG.pair()).mask(Scalar(1, total), w)
        host.aggregate(masked)
        stacks.append(masked.vect.data)
        raws.append(
            np.frombuffer(
                vect_element_block(serialize_mask_vect(masked.vect)), dtype=np.uint8
            )
        )
    return stacks, raws, host


@pytest.mark.parametrize("kernel", KERNELS)
def test_streaming_planar_byte_identical_to_sequential(kernel):
    n, total, bs = 103, 13, 4
    stacks, _, host = _updates(n, total)
    seq = ShardedAggregator(CFG, n, mesh=_mesh1(), kernel=kernel)
    for i in range(0, total, bs):
        seq.add_batch(np.stack(stacks[i : i + bs]))

    agg = ShardedAggregator(CFG, n, mesh=_mesh1(), kernel=kernel)
    stream = StreamingAggregator(agg, staging_buffers=3, dispatch_ahead=2, max_batch=bs)
    for i in range(0, total, bs):
        stream.submit_batch(np.stack(stacks[i : i + bs]))
    stream.drain()

    assert np.array_equal(agg.snapshot(), seq.snapshot())
    assert agg.nb_models == seq.nb_models == total
    # both equal the host oracle, not merely each other
    assert np.array_equal(agg.snapshot(), host.object.vect.data)
    assert agg.kernel_used == seq.kernel_used
    stream.close()


@pytest.mark.parametrize("kernel", KERNELS)
def test_streaming_wire_deferred_acceptance_matches_sequential(kernel):
    """Raw-wire streaming: accumulator, nb_models AND the per-member
    acceptance vectors (fetched in one deferred sync at drain) must equal
    the sequential add_wire_batch path, invalid members included."""
    n, total, bs = 57, 11, 4
    _, raws, _ = _updates(n, total, seed=3)
    bad = raws[5].copy()
    bad[: CFG.bytes_per_number] = 0xFF  # element >= order -> member rejected
    wires = raws[:5] + [bad] + raws[6:]

    seq = ShardedAggregator(CFG, n, mesh=_mesh1(), kernel=kernel)
    seq_oks = [
        seq.add_wire_batch(np.stack(wires[i : i + bs])) for i in range(0, total, bs)
    ]

    agg = ShardedAggregator(CFG, n, mesh=_mesh1(), kernel=kernel)
    stream = StreamingAggregator(agg, staging_buffers=3, dispatch_ahead=2, max_batch=bs)
    tickets = [
        stream.submit_wire_batch(np.stack(wires[i : i + bs]))
        for i in range(0, total, bs)
    ]
    # deferred: before drain no ticket has resolved acceptance
    stream.drain()

    assert np.array_equal(agg.snapshot(), seq.snapshot())
    assert agg.nb_models == seq.nb_models == total - 1
    got = np.concatenate([t.accepted for t in tickets])
    assert np.array_equal(got, np.concatenate(seq_oks))
    assert not got[5] and int(got.sum()) == total - 1
    stream.close()


def test_dispatch_ahead_out_of_order_completion_stress():
    """Producer races several batches ahead of folds that complete late and
    with jittered timing: the ring/queue bounds must hold (gauges return to
    zero), every batch must fold exactly once, and the aggregate must stay
    byte-identical to the sequential schedule."""
    n, total, bs = 64, 36, 3
    stacks, _, host = _updates(n, total, seed=7)
    seq = ShardedAggregator(CFG, n, mesh=_mesh1(), kernel="xla")
    for i in range(0, total, bs):
        seq.add_batch(np.stack(stacks[i : i + bs]))

    agg = ShardedAggregator(CFG, n, mesh=_mesh1(), kernel="xla")
    stream = StreamingAggregator(agg, staging_buffers=4, dispatch_ahead=3, max_batch=bs)
    # resolve the kernel on the first batch, then wrap the fold with jitter
    stream.submit_batch(np.stack(stacks[0:bs]))
    stream.drain()
    # packed staging is the default layout, so the worker folds through
    # _packed_fold_fn — wrap whichever entry the pipeline actually uses
    packed = stream._packed
    real_fold = agg._packed_fold_fn if packed else agg._fold_fn
    jitter = iter(np.random.default_rng(1).uniform(0.0, 0.004, size=total))
    folded_sizes = []

    def slow_fold(acc, staged):
        time.sleep(float(next(jitter)))
        folded_sizes.append(int(staged.shape[0]))
        return real_fold(acc, staged)

    if packed:
        agg._packed_fold_fn = slow_fold
    else:
        agg._fold_fn = slow_fold
    staged_before = BATCHES_TOTAL.labels(stage="staged").value
    for i in range(bs, total, bs):
        stream.submit_batch(np.stack(stacks[i : i + bs]))
    stream.drain()

    assert np.array_equal(agg.snapshot(), seq.snapshot())
    assert np.array_equal(agg.snapshot(), host.object.vect.data)
    assert agg.nb_models == seq.nb_models == total
    # every submitted batch folded exactly once, none dropped or duplicated
    assert sum(folded_sizes) == total - bs
    assert (
        BATCHES_TOTAL.labels(stage="staged").value - staged_before
        == (total - bs) / bs
    )
    # bounds released: nothing left in flight, no ring buffer leaked
    assert INFLIGHT_FOLDS.value == 0
    assert STAGING_DEPTH.value == 0
    stream.close()


def test_worker_failure_surfaces_at_drain():
    n, bs = 32, 2
    stacks, _, _ = _updates(n, 4, seed=9)
    agg = ShardedAggregator(CFG, n, mesh=_mesh1(), kernel="xla")
    stream = StreamingAggregator(agg, staging_buffers=2, dispatch_ahead=1, max_batch=bs)
    stream.submit_batch(np.stack(stacks[0:bs]))
    stream.drain()

    def boom(acc, staged):
        raise RuntimeError("fold died (stand-in)")

    agg._fold_fn = boom
    agg._packed_fold_fn = boom  # packed staging is the default layout
    stream.submit_batch(np.stack(stacks[bs : 2 * bs]))
    with pytest.raises(StreamingError):
        stream.drain()
    # the poison is PERMANENT: a later drain (the finalize/close path) must
    # keep failing rather than hand out a snapshot whose accumulator and
    # nb_models no longer describe the same update set
    with pytest.raises(StreamingError):
        stream.drain()
    stream.close()  # cleanup still works on a poisoned pipeline


def test_prevalidate_wire_batch_one_dispatch_per_group():
    """StagedAggregator.prevalidate_wire_batch: one wire_unpack dispatch +
    one acceptance fetch for the whole micro-batch; validate_aggregation
    then consumes the cached per-member verdicts (invalid member rejected,
    valid members staged) with NO further device round-trips."""
    from xaynet_tpu.core.mask.masking import AggregationError
    from xaynet_tpu.core.mask.object import LazyWireMaskVect, MaskObject
    from xaynet_tpu.server.aggregation import StagedAggregator
    from xaynet_tpu.telemetry import profiling

    n, k = 57, 5
    rng = np.random.default_rng(11)
    host = StagedAggregator(CFG.pair(), n, device=False, batch_size=8)
    dev = StagedAggregator(CFG.pair(), n, device=True, batch_size=8, kernel="xla")
    objs = []
    for i in range(k):
        w = rng.uniform(-1, 1, n).astype(np.float32)
        _, masked = Masker(CFG.pair()).mask(Scalar(1, k), w)
        raw = np.array(vect_element_block(serialize_mask_vect(masked.vect)))
        if i == 2:
            raw[: CFG.bytes_per_number] = 0xFF  # invalid member
        else:
            host.validate_aggregation(masked)
            host.aggregate(masked)
        objs.append(MaskObject(LazyWireMaskVect(CFG, raw, n), masked.unit))

    unpacks = profiling.KERNEL_CALLS.labels(op="wire_unpack")
    before = unpacks.value
    dev.prevalidate_wire_batch(objs)
    assert unpacks.value - before == 1  # ONE dispatch for the group
    for i, obj in enumerate(objs):
        if i == 2:
            with pytest.raises(AggregationError):
                dev.validate_aggregation(obj)
        else:
            dev.validate_aggregation(obj)
            assert obj.vect._staged_planar is not None
            dev.aggregate(obj)
    assert unpacks.value - before == 1  # cached verdicts, no re-dispatch
    a, b = host.finalize(), dev.finalize()
    assert a.nb_models == b.nb_models == k - 1
    assert a.object == b.object


def test_staged_aggregator_flush_is_submit_drain_is_sync():
    """flush() submits without losing updates; nb_models counts staged +
    in-flight + folded at every point; drain() is the synchronization."""
    from xaynet_tpu.server.aggregation import StagedAggregator

    n, k = 40, 6
    rng = np.random.default_rng(13)
    host = StagedAggregator(CFG.pair(), n, device=False, batch_size=2)
    dev = StagedAggregator(
        CFG.pair(), n, device=True, batch_size=2, kernel="xla",
        dispatch_ahead=2, staging_buffers=3,
    )
    for _ in range(k):
        w = rng.uniform(-1, 1, n).astype(np.float32)
        _, masked = Masker(CFG.pair()).mask(Scalar(1, k), w)
        for s in (host, dev):
            s.validate_aggregation(masked)
            s.aggregate(masked)
        assert dev.nb_models == host.nb_models  # staged/in-flight included
    dev.drain()
    assert dev.nb_models == host.nb_models == k
    a, b = host.finalize(), dev.finalize()
    assert a.nb_models == b.nb_models == k
    assert a.object == b.object


def test_streaming_settings_surface():
    from xaynet_tpu.server.settings import Settings, SettingsError

    s = Settings.load(env={"XAYNET__AGGREGATION__DISPATCH_AHEAD": "4",
                           "XAYNET__AGGREGATION__STAGING_BUFFERS": "5",
                           "XAYNET__AGGREGATION__KERNEL": "native-u64"})
    assert s.aggregation.dispatch_ahead == 4
    assert s.aggregation.staging_buffers == 5
    assert s.aggregation.kernel == "native-u64"
    with pytest.raises(SettingsError):
        Settings.load(env={"XAYNET__AGGREGATION__DISPATCH_AHEAD": "0"})
    with pytest.raises(SettingsError):
        Settings.load(env={"XAYNET__AGGREGATION__STAGING_BUFFERS": "1"})


def test_prevalidate_skips_count_mismatched_member():
    """A member whose declared count mismatches the round's model length
    must be SKIPPED by batch prevalidation (ragged np.stack would otherwise
    abort the whole micro-batch with an internal error) and rejected alone
    by the per-member ModelMismatch check, exactly like the sequential
    path."""
    from xaynet_tpu.core.mask.masking import AggregationError
    from xaynet_tpu.core.mask.object import LazyWireMaskVect, MaskObject
    from xaynet_tpu.server.aggregation import StagedAggregator

    n = 57
    rng = np.random.default_rng(17)
    dev = StagedAggregator(CFG.pair(), n, device=True, batch_size=8, kernel="xla")
    w = rng.uniform(-1, 1, n).astype(np.float32)
    _, good_masked = Masker(CFG.pair()).mask(Scalar(1, 2), w)
    good = MaskObject(
        LazyWireMaskVect(
            CFG,
            np.array(vect_element_block(serialize_mask_vect(good_masked.vect))),
            n,
        ),
        good_masked.unit,
    )
    w_short = rng.uniform(-1, 1, n - 3).astype(np.float32)
    _, short_masked = Masker(CFG.pair()).mask(Scalar(1, 2), w_short)
    short = MaskObject(
        LazyWireMaskVect(
            CFG,
            np.array(vect_element_block(serialize_mask_vect(short_masked.vect))),
            n - 3,
        ),
        short_masked.unit,
    )

    dev.prevalidate_wire_batch([good, short])  # must not raise on ragged rows
    dev.validate_aggregation(good)
    assert good.vect._staged_planar is not None
    dev.aggregate(good)
    with pytest.raises(AggregationError):  # ModelMismatch for THAT member only
        dev.validate_aggregation(short)
    assert dev.nb_models == 1
