"""PRNG conformance: bit-exact ChaCha20 + rejection sampling.

Golden values pinned from the reference
(rust/xaynet-core/src/crypto/prng.rs:36-80); the vectorized sampler must
consume the keystream identically to the sequential oracle.
"""

import numpy as np
import pytest

from xaynet_tpu.core.crypto.chacha import ChaChaStream, keystream_blocks
from xaynet_tpu.core.crypto.prng import StreamSampler, generate_integer, uniform_ints
from xaynet_tpu.core.mask.config import BoundType, DataType, GroupType, MaskConfig, ModelType
from xaynet_tpu.ops import limbs as limb_ops

GOLDEN_MAX = (2**128 - 1) ** 2
GOLDEN = [
    90034050956742099321159087842304570510687605373623064829879336909608119744630,
    60790020689334235010238064028215988394112077193561636249125918224917556969946,
    107415344426328791036720294006773438815099086866510488084511304829720271980447,
    50343610553303623842889112417183549658912134525854625844144939347139411162921,
    42382469383990928111449714288937630103705168010724718767641573929365517895981,
]


def test_chacha20_zero_key_keystream():
    # djb-variant ChaCha20, zero key, zero nonce, counter 0 (well-known vector)
    ks = bytes(keystream_blocks(b"\x00" * 32, 0, 1))
    assert ks[:32].hex() == (
        "76b8e0ada0f13d90405d6ae55386bd28bdd219b8a08ded1aa836efcc8b770dc7"
    )


def test_chacha20_block_counter_continuity():
    one = bytes(keystream_blocks(b"\x01" * 32, 0, 4))
    a = bytes(keystream_blocks(b"\x01" * 32, 0, 2))
    b = bytes(keystream_blocks(b"\x01" * 32, 2, 2))
    assert one == a + b


def test_generate_integer_golden():
    s = ChaChaStream(b"\x00" * 32)
    for expected in GOLDEN:
        assert generate_integer(s, GOLDEN_MAX) == expected


def test_vectorized_matches_golden():
    assert uniform_ints(b"\x00" * 32, 5, GOLDEN_MAX) == GOLDEN


@pytest.mark.parametrize(
    "order",
    [
        20_000_000_000_001,  # Integer/F32/B0/M3
        20_000_000_000_021,  # Prime/F32/B0/M3
        2**45,  # Power2/F32/B0/M3
        2**88,  # Power2/F32/B4/M12: order bytes > element bytes
        2**96,  # Power2/I32/Bmax/M9: order needs an extra limb
        MaskConfig(GroupType.PRIME, DataType.F64, BoundType.BMAX, ModelType.M3).order,
        255,  # single byte draws
    ],
)
def test_vectorized_matches_sequential(order):
    seed = bytes(range(32))
    stream = ChaChaStream(seed)
    expected = [generate_integer(stream, order) for _ in range(100)]
    assert uniform_ints(seed, 100, order) == expected


def test_stream_sampler_mixed_orders():
    """derive_mask draws 1 unit element then N vector elements from ONE stream."""
    seed = b"\x2a" * 32
    order_1 = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M3).order
    order_n = MaskConfig(GroupType.PRIME, DataType.F32, BoundType.B2, ModelType.M6).order

    stream = ChaChaStream(seed)
    expected_unit = generate_integer(stream, order_1)
    expected_vect = [generate_integer(stream, order_n) for _ in range(50)]

    sampler = StreamSampler(seed)
    unit = sampler.draw_limbs(1, order_1)
    vect = sampler.draw_limbs(50, order_n)
    assert limb_ops.limbs_to_ints(unit)[0] == expected_unit
    assert limb_ops.limbs_to_ints(vect) == expected_vect


def test_sampler_determinism_and_range():
    order = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M3).order
    a = uniform_ints(b"\x07" * 32, 1000, order)
    b = uniform_ints(b"\x07" * 32, 1000, order)
    assert a == b
    assert all(0 <= v < order for v in a)
    # uniformity smoke: mean within 5% of order/2 over 1000 draws
    assert abs(np.mean([v / order for v in a]) - 0.5) < 0.05
