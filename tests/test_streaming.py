"""Streaming multipart reassembly and chunk-level send retry.

Reference behaviors covered:
- streaming re-parse of reassembled multipart payloads without a second
  contiguous copy (rust/xaynet-core/src/message/utils/chunkable_iterator.rs,
  multipart/service.rs:26-117);
- chunk-level send retry: only the failed part is re-sent
  (rust/xaynet-sdk/src/state_machine/phases/sending.rs:96-113).
"""

import asyncio
import tracemalloc
from fractions import Fraction

import numpy as np
import pytest

from xaynet_tpu.core.crypto.sign import SigningKeyPair
from xaynet_tpu.core.mask.config import (
    BoundType,
    DataType,
    GroupType,
    MaskConfig,
    ModelType,
)
from xaynet_tpu.core.mask.masking import Masker
from xaynet_tpu.core.mask.model import Scalar
from xaynet_tpu.core.mask.seed import MaskSeed
from xaynet_tpu.core.message import Message, Sum2, Tag, Update
from xaynet_tpu.core.message.encoder import ChunkReader, MessageBuilder, MessageEncoder
from xaynet_tpu.core.message.payloads import Chunk, parse_payload_stream

CFG = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M3)


def _masked(length: int):
    masker = Masker(CFG.pair(), MaskSeed(b"\x31" * 32))
    weights = np.linspace(-0.5, 0.5, length, dtype=np.float32)
    _, obj = masker.mask(Scalar.unit(), weights)
    return obj


def _chunks_of(message: Message, sk, max_size: int) -> list[Chunk]:
    parts = list(MessageEncoder(message, sk, max_size))
    out = []
    for raw in parts:
        m = Message.from_bytes(raw, verify=True)
        assert m.is_multipart
        out.append(m.payload)
    return out


def _roundtrip_stream(payload, tag: Tag, max_size: int = 512):
    keys = SigningKeyPair.generate()
    msg = Message(
        participant_pk=keys.public,
        coordinator_pk=b"\x02" * 32,
        payload=payload,
        tag=tag,
    )
    builder = MessageBuilder()
    chunks = _chunks_of(msg, keys.secret, max_size)
    # deliver out of order: odd ids first, then even
    for c in sorted(chunks, key=lambda c: (c.id % 2 == 0, c.id)):
        complete = builder.add(c)
    assert complete
    return parse_payload_stream(tag, builder.take_reader())


def test_stream_parse_update_matches_direct():
    obj = _masked(300)
    seeds = {bytes([i]) * 32: b"\x07" * 80 for i in range(5)}
    from xaynet_tpu.core.mask.seed import EncryptedMaskSeed

    seeds = {k: EncryptedMaskSeed(v) for k, v in seeds.items()}
    payload = Update(
        sum_signature=b"\x0a" * 64,
        update_signature=b"\x0b" * 64,
        masked_model=obj,
        local_seed_dict=seeds,
    )
    got = _roundtrip_stream(payload, Tag.UPDATE)
    assert isinstance(got, Update)
    assert got.sum_signature == payload.sum_signature
    assert got.update_signature == payload.update_signature
    assert np.array_equal(got.masked_model.vect.data, obj.vect.data)
    assert np.array_equal(got.masked_model.unit.data, obj.unit.data)
    assert {k: v.as_bytes() for k, v in got.local_seed_dict.items()} == {
        k: v.as_bytes() for k, v in seeds.items()
    }


def test_stream_parse_sum2_matches_direct():
    obj = _masked(200)
    payload = Sum2(sum_signature=b"\x0c" * 64, model_mask=obj)
    got = _roundtrip_stream(payload, Tag.SUM2)
    assert isinstance(got, Sum2)
    assert np.array_equal(got.model_mask.vect.data, obj.vect.data)


def test_stream_parse_frees_chunks_progressively():
    reader = ChunkReader([b"ab", b"cdef", b"g"])
    assert reader.remaining == 7
    assert reader.read(3) == b"abc"
    assert len(reader._chunks) == 2
    out = np.empty(3, dtype=np.uint8)
    reader.read_into(out)
    assert bytes(out) == b"def"
    assert len(reader._chunks) == 1
    assert reader.read(1) == b"g"
    assert reader.remaining == 0
    with pytest.raises(ValueError):
        reader.read(1)


def test_stream_parse_peak_memory_bounded():
    """A large reassembled payload must not be concatenated a second time."""
    obj = _masked(2_000_000)  # 12 MB of wire bytes at 6 B/element
    payload = Sum2(sum_signature=b"\x0d" * 64, model_mask=obj)
    raw = payload.to_bytes()
    budget = 1 << 16
    chunks = [
        Chunk(id=i + 1, message_id=7, last=(i == (len(raw) - 1) // budget),
              data=raw[i * budget : (i + 1) * budget])
        for i in range(-(-len(raw) // budget))
    ]
    builder = MessageBuilder()
    for c in chunks:
        builder.add(c)
    del raw, chunks

    tracemalloc.start()
    parsed = parse_payload_stream(Tag.SUM2, builder.take_reader())
    current, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    wire = 2_000_000 * CFG.bytes_per_number
    # the retained result is the limb tensor (~1.33x wire); the *transient*
    # overhead above it must stay below one wire copy — a concat-then-parse
    # would allocate the full joined payload (1x wire) plus a full-size
    # padded conversion buffer (1.33x wire) on top.
    assert peak - current < wire, f"transient {peak - current} vs wire {wire}"
    assert np.array_equal(parsed.model_mask.vect.data, obj.vect.data)


# --- chunk-level send retry -------------------------------------------------


class _FlakyClient:
    """In-memory client whose Nth send fails once; records every send."""

    def __init__(self, params, fail_at: int):
        self.params = params
        self.fail_at = fail_at
        self.sent: list[bytes] = []
        self.attempts = 0

    async def get_round_params(self):
        return self.params

    async def get_sums(self):
        return {}

    async def get_seeds(self, pk):
        return {}

    async def get_model(self):
        return None

    async def send_message(self, data: bytes) -> None:
        self.attempts += 1
        if self.attempts == self.fail_at:
            raise ConnectionError("simulated chunk drop")
        self.sent.append(data)


def test_chunk_level_send_retry():
    from xaynet_tpu.core.common import RoundParameters, RoundSeed
    from xaynet_tpu.core.crypto.encrypt import EncryptKeyPair
    from xaynet_tpu.sdk.state_machine import (
        PetSettings,
        PhaseKind,
        StateMachine,
        TransitionOutcome,
    )
    from xaynet_tpu.sdk.traits import ModelStore

    class _NoModel(ModelStore):
        async def load_model(self):
            return None

    coord = EncryptKeyPair.generate()
    params = RoundParameters(
        pk=coord.public.as_bytes(),
        sum=Fraction(1),  # everyone is a sum participant
        update=Fraction(0),
        seed=RoundSeed(b"\x05" * 32),
        mask_config=CFG.pair(),
        model_length=256,  # the sum2 mask spans several 400-byte chunks
    )
    machine = StateMachine(
        PetSettings(keys=SigningKeyPair.generate(), max_message_size=400),
        _FlakyClient(params, fail_at=10**9),
        _NoModel(),
    )
    client = machine.client

    async def drive(n):
        outcomes = []
        for _ in range(n):
            outcomes.append(await machine.transition())
        return outcomes

    asyncio.run(drive(2))  # NewRound -> Sum (sends ephm key)
    assert machine.phase is PhaseKind.SUM2
    sum_parts = len(client.sent)
    assert sum_parts >= 1

    # force the sum2 step to produce a multipart message and drop one part:
    # seeds response with one seed; mask of length 64 with max_message_size
    # 400 gives several chunks
    seed = MaskSeed(b"\x2a" * 32)
    enc = seed.encrypt(machine.ephm_keys.public)
    client.get_seeds = lambda pk: _async(enc)
    client.fail_at = client.attempts + 3  # third part of the sum2 message fails

    async def _drive_until_awaiting(limit=10):
        outcomes = []
        for _ in range(limit):
            out = await machine.transition()
            outcomes.append(out)
            if machine.phase is PhaseKind.AWAITING and machine._pending is None:
                break
        return outcomes

    outcomes = asyncio.run(_drive_until_awaiting())
    assert TransitionOutcome.PENDING in outcomes  # the dropped part paused us
    assert machine.phase is PhaseKind.AWAITING
    assert machine._pending is None
    # every part was delivered exactly once, in order: reassembling them
    # yields a complete message (delivered = sent list after the sum parts)
    delivered = client.sent[sum_parts:]
    opened = [coord.secret.decrypt(p) for p in delivered]
    msgs = [Message.from_bytes(r, verify=True) for r in opened]
    assert all(m.is_multipart for m in msgs)
    ids = [m.payload.id for m in msgs]
    assert ids == sorted(ids) and len(set(ids)) == len(ids)
    builder = MessageBuilder()
    complete = False
    for m in msgs:
        complete = builder.add(m.payload)
    assert complete


def _async(value):
    async def _inner():
        return {b"\x01" * 32: value} if value is not None else None

    return _inner()


def test_pending_send_survives_save_restore():
    """An in-flight multipart send serializes as ONE payload copy + cursor
    and resumes from the exact part it stopped at."""
    from xaynet_tpu.core.common import RoundParameters, RoundSeed
    from xaynet_tpu.core.crypto.encrypt import EncryptKeyPair
    from xaynet_tpu.sdk.state_machine import PetSettings, PhaseKind, StateMachine
    from xaynet_tpu.sdk.traits import ModelStore

    class _NoModel(ModelStore):
        async def load_model(self):
            return None

    class _FailingClient(_FlakyClient):
        pass

    coord = EncryptKeyPair.generate()
    params = RoundParameters(
        pk=coord.public.as_bytes(),
        sum=1.0,
        update=0.0,
        seed=RoundSeed(b"\x06" * 32),
        mask_config=CFG.pair(),
        model_length=256,
    )
    keys = SigningKeyPair.generate()
    machine = StateMachine(
        PetSettings(keys=keys, max_message_size=400),
        _FailingClient(params, fail_at=10**9),
        _NoModel(),
    )
    client = machine.client

    asyncio.run(_drive_n(machine, 2))  # -> SUM2
    # produce a multipart sum2 message and fail on its third part
    seed = MaskSeed(b"\x2b" * 32)
    enc = seed.encrypt(machine.ephm_keys.public)
    client.get_seeds = lambda pk: _async(enc)
    client.fail_at = client.attempts + 3
    asyncio.run(_drive_n(machine, 1))
    assert machine._pending is not None
    delivered_before = len(client.sent)
    next_before = machine._pending.next_index
    assert next_before == 2  # two parts through, third failed

    state = machine.save()
    assert len(state) < 64 * 1024  # cursor + one payload copy, not part list
    restored = StateMachine.restore(state, client, _NoModel())
    assert restored._pending is not None
    assert restored._pending.next_index == next_before
    client.fail_at = 10**9  # network healthy again
    asyncio.run(_drive_n(restored, 2))
    assert restored._pending is None
    assert restored.phase is PhaseKind.AWAITING

    # the full message reassembles from pre-save + post-restore parts
    opened = [coord.secret.decrypt(p) for p in client.sent[1:]]  # skip the sum msg
    msgs = [Message.from_bytes(r, verify=True) for r in opened]
    builder = MessageBuilder()
    complete = False
    for m in msgs:
        complete = builder.add(m.payload)
    assert complete


async def _drive_n(machine, n):
    for _ in range(n):
        await machine.transition()
