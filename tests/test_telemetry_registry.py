"""Telemetry registry semantics: counters/gauges/histograms, label escaping
in the exposition output, and concurrent-increment thread safety."""

import math
import re
import threading

import pytest

from xaynet_tpu.telemetry.registry import DEFAULT_BUCKETS, MetricError, MetricsRegistry


def test_counter_semantics():
    reg = MetricsRegistry()
    c = reg.counter("jobs_total", "jobs")
    c.inc()
    c.inc(4)
    assert c.value == 5
    with pytest.raises(MetricError):
        c.inc(-1)
    # labeled children are independent
    by_kind = reg.counter("by_kind_total", "k", ("kind",))
    by_kind.labels(kind="a").inc()
    by_kind.labels(kind="b").inc(2)
    assert by_kind.labels(kind="a").value == 1
    assert by_kind.labels(kind="b").value == 2
    assert reg.sample_value("by_kind_total", {"kind": "b"}) == 2
    # unlabeled access on a labeled family is an error
    with pytest.raises(MetricError):
        by_kind.inc()
    # wrong label set is an error
    with pytest.raises(MetricError):
        by_kind.labels(nope="x")


def test_gauge_semantics():
    reg = MetricsRegistry()
    g = reg.gauge("depth", "queue depth")
    g.set(7)
    g.inc()
    g.dec(3)
    assert g.value == 5
    g.set(-2.5)
    assert g.value == -2.5


def test_histogram_semantics():
    reg = MetricsRegistry()
    h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    assert h.count == 5
    assert abs(h.sum - 56.05) < 1e-9
    cumulative = h.bucket_counts()
    assert cumulative[0.1] == 1
    assert cumulative[1.0] == 3
    assert cumulative[10.0] == 4
    assert cumulative[math.inf] == 5
    # timer context manager records one observation
    with h.time():
        pass
    assert h.count == 6


def test_histogram_exposition_is_cumulative_with_inf():
    reg = MetricsRegistry()
    h = reg.histogram("h_seconds", "h", ("op",), buckets=(1.0,))
    h.labels(op="fold").observe(0.5)
    h.labels(op="fold").observe(2.0)
    text = reg.render()
    assert 'h_seconds_bucket{op="fold",le="1"} 1' in text
    assert 'h_seconds_bucket{op="fold",le="+Inf"} 2' in text
    assert 'h_seconds_sum{op="fold"} 2.5' in text
    assert 'h_seconds_count{op="fold"} 2' in text


def test_label_escaping_in_exposition():
    reg = MetricsRegistry()
    c = reg.counter("events_total", "events", ("detail",))
    c.labels(detail='quote " backslash \\ newline \n end').inc()
    text = reg.render()
    assert '{detail="quote \\" backslash \\\\ newline \\n end"}' in text
    # no raw newline may survive inside a sample line
    for line in text.splitlines():
        assert "\n" not in line


def test_exposition_well_formed():
    reg = MetricsRegistry()
    reg.counter("a_total", "with some help").inc()
    reg.gauge("b", "gauge help", ("x",)).labels(x="1").set(2)
    reg.histogram("c_seconds", "hist").observe(0.2)
    text = reg.render()
    assert text.endswith("\n")
    sample_re = re.compile(r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? \S+$')
    for line in text.splitlines():
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            continue
        assert sample_re.match(line), line
    # HELP/TYPE precede each family's samples
    assert text.index("# HELP a_total") < text.index("a_total 1")


def test_family_idempotent_and_type_conflicts():
    reg = MetricsRegistry()
    a = reg.counter("same_total", "h", ("k",))
    b = reg.counter("same_total", "other help", ("k",))
    assert a is b
    a.labels(k="x").inc()
    assert b.labels(k="x").value == 1
    with pytest.raises(MetricError):
        reg.gauge("same_total")
    with pytest.raises(MetricError):
        reg.counter("same_total", "h", ("different",))
    # histograms: same name with different buckets is a conflict, not a
    # silent wrong-buckets reuse
    reg.histogram("lat_seconds", "h", buckets=(0.1, 1.0))
    assert reg.histogram("lat_seconds", "h", buckets=(0.1, 1.0)) is not None
    with pytest.raises(MetricError):
        reg.histogram("lat_seconds", "h", buckets=(0.5,))


def test_default_buckets_cover_phase_windows():
    assert DEFAULT_BUCKETS[0] <= 0.005
    assert DEFAULT_BUCKETS[-1] >= 600.0


def test_concurrent_increments_are_not_lost():
    reg = MetricsRegistry()
    c = reg.counter("hot_total", "contended", ("who",))
    h = reg.histogram("hot_seconds", "contended", buckets=(0.5,))
    n_threads, per_thread = 8, 10_000

    def worker():
        child = c.labels(who="all")
        for _ in range(per_thread):
            child.inc()
            h.observe(0.1)

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.labels(who="all").value == n_threads * per_thread
    assert h.count == n_threads * per_thread
    assert h.bucket_counts()[0.5] == n_threads * per_thread
