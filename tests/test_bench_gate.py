"""tools/bench_gate.py: the tier-2 bench regression gate (BENCH.md).

Replays a BENCH_HISTORY-shaped JSONL and must exit 1 exactly when the
latest headline round regresses more than the threshold vs the best PRIOR
round of the SAME series — mixed metric variants, torn lines and alien
records must neither crash the gate nor pollute the comparison.
"""

from __future__ import annotations

import importlib.util
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

spec = importlib.util.spec_from_file_location("xn_bench_gate", REPO / "tools" / "bench_gate.py")
bench_gate = importlib.util.module_from_spec(spec)
sys.modules["xn_bench_gate"] = spec.loader.exec_module(bench_gate) or bench_gate

HEADLINE = "masked-update aggregation throughput @25M params"


def _write(tmp_path, records) -> str:
    path = tmp_path / "history.jsonl"
    lines = [json.dumps(r) if isinstance(r, dict) else r for r in records]
    path.write_text("\n".join(lines) + "\n")
    return str(path)


def _run(path, *extra) -> int:
    argv = sys.argv
    sys.argv = ["bench_gate.py", "--history", path, *extra]
    try:
        return bench_gate.main()
    finally:
        sys.argv = argv


def _rec(ts, value, metric=HEADLINE, unit="updates/s", nested=True):
    if nested:
        return {"ts": ts, "parsed": {"metric": metric, "value": value, "unit": unit}}
    return {"ts": ts, "metric": metric, "value": value, "unit": unit}


def test_gate_passes_when_latest_holds_the_line(tmp_path, capsys):
    path = _write(
        tmp_path,
        [_rec(1, 20.0), _rec(2, 30.0, nested=False), _rec(3, 29.0)],
    )
    assert _run(path) == 0
    verdict = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert verdict["result"] == "ok"
    assert verdict["best_prior"] == 30.0


def test_gate_fails_on_regression_beyond_threshold(tmp_path, capsys):
    path = _write(tmp_path, [_rec(1, 30.0), _rec(2, 31.0), _rec(3, 26.0)])
    assert _run(path) == 1  # 26 < 31 * 0.9
    verdict = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert verdict["result"] == "REGRESSION"


def test_gate_threshold_is_configurable(tmp_path):
    path = _write(tmp_path, [_rec(1, 31.0), _rec(2, 26.0)])
    assert _run(path) == 1
    assert _run(path, "--threshold", "0.2") == 0  # 26 > 31 * 0.8


def test_gate_compares_within_one_exact_series(tmp_path):
    """A @200k-params round must not set the bar for the @25M series."""
    path = _write(
        tmp_path,
        [
            _rec(1, 900.0, metric="masked-update aggregation throughput @200000 params"),
            _rec(2, 30.0),
            _rec(3, 31.0),
        ],
    )
    assert _run(path) == 0


def test_gate_survives_torn_lines_and_alien_records(tmp_path):
    path = _write(
        tmp_path,
        [
            '{"ts": 1, "parsed": {"metric": "',  # torn append
            {"ts": 2, "note": "no metric at all"},
            _rec(3, 30.0),
            _rec(4, 5.0, unit="rounds/s"),  # different unit: not headline
            _rec(5, 29.5),
        ],
    )
    assert _run(path) == 0


def test_gate_with_nothing_to_compare_is_a_soft_pass(tmp_path):
    assert _run(_write(tmp_path, [_rec(1, 30.0)])) == 0
    assert _run(_write(tmp_path, [{"ts": 1, "note": "empty"}])) == 0


def test_gate_runs_clean_on_the_real_history():
    """The repo's own BENCH_HISTORY must parse and currently pass."""
    assert _run(str(REPO / "BENCH_HISTORY.jsonl")) == 0


# --- kernel/thread-config series identity ----------------------------------


def _cfg_rec(ts, value, metric=HEADLINE, **config):
    parsed = {"metric": metric, "value": value, "unit": "updates/s"}
    parsed.update(config)
    return {"ts": ts, "parsed": parsed}


def test_gate_treats_thread_config_change_as_new_series(tmp_path, capsys):
    """BENCH_r05's 29.46 vs r03's ~49 on the same code path came from an
    implicit thread-default shift: with the config recorded, the gate must
    start a NEW series instead of flagging a 40% regression."""
    path = _write(
        tmp_path,
        [
            _cfg_rec(1, 49.0, kernel="native-u64", native_threads=16),
            _cfg_rec(2, 48.2, kernel="native-u64", native_threads=16),
            _cfg_rec(3, 29.5, kernel="native-u64", native_threads=4),
        ],
    )
    assert _run(path) == 0
    assert "NEW series" in capsys.readouterr().err


def test_gate_kernel_change_is_a_new_series(tmp_path):
    path = _write(
        tmp_path,
        [_cfg_rec(1, 49.0, kernel="native-u64"), _cfg_rec(2, 20.0, kernel="xla")],
    )
    assert _run(path) == 0


def test_gate_still_fails_within_one_config_series(tmp_path, capsys):
    path = _write(
        tmp_path,
        [
            _cfg_rec(1, 49.0, kernel="native-u64", native_threads=16),
            _cfg_rec(2, 30.0, kernel="native-u64", native_threads=16),
        ],
    )
    assert _run(path) == 1
    verdict = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert verdict["result"] == "REGRESSION"
    assert "native_threads=16" in verdict["config"]


def test_gate_mesh8_series_is_gated_independently(tmp_path, capsys):
    """The mesh=8 shard-parallel headline is its own series: its first
    round soft-passes against a taller single-device history, and a later
    mesh=8 regression fails against the mesh=8 best only."""
    mesh_metric = HEADLINE + ", mesh=8 CPU fallback (PET update phase)"
    base = [
        _cfg_rec(1, 49.0, kernel="native-u64", native_threads=16),
        _cfg_rec(2, 48.0, kernel="native-u64", native_threads=16),
    ]
    first_mesh = _cfg_rec(
        3, 34.0, metric=mesh_metric, kernel="native-u64", native_threads=4,
        shard_threads=4, mesh=8,
    )
    path = _write(tmp_path, base + [first_mesh])
    assert _run(path) == 0  # first mesh=8 round: nothing to compare

    regressed = _cfg_rec(
        4, 20.0, metric=mesh_metric, kernel="native-u64", native_threads=4,
        shard_threads=4, mesh=8,
    )
    path = _write(tmp_path, base + [first_mesh, regressed])
    assert _run(path) == 1  # 20 < 34 * 0.9, within the mesh=8 series


# --- sim headline family (participants/s) -----------------------------------


SIM_METRIC = "sim round throughput @1000 params (in-graph federated round)"


def _sim_rec(ts, value, metric=SIM_METRIC, **config):
    parsed = {"metric": metric, "value": value, "unit": "participants/s"}
    parsed.update(config)
    return {"ts": ts, "parsed": parsed}


def test_sim_series_gates_independently_of_fold_headline(tmp_path):
    """A healthy fold headline must not mask a sim regression (and vice
    versa): the two families gate as separate series in one default run."""
    fold_ok = [_rec(1, 30.0), _rec(2, 31.0)]
    sim_ok = [
        _sim_rec(3, 500.0, participants=2048, block=256, mesh=1),
        _sim_rec(4, 520.0, participants=2048, block=256, mesh=1),
    ]
    assert _run(_write(tmp_path, fold_ok + sim_ok)) == 0

    sim_bad = _sim_rec(5, 100.0, participants=2048, block=256, mesh=1)
    assert _run(_write(tmp_path, fold_ok + sim_ok + [sim_bad])) == 1

    # and a fold regression still fails even with a healthy sim series
    fold_bad = _rec(6, 10.0)
    assert _run(_write(tmp_path, fold_ok + sim_ok + [fold_bad])) == 1


def test_sim_population_shape_change_is_a_new_series(tmp_path, capsys):
    """participants/block/mesh are series identity for the sim headline —
    doubling the population is a different experiment, not a regression."""
    path = _write(
        tmp_path,
        [
            _sim_rec(1, 500.0, participants=2048, block=256, mesh=1),
            _sim_rec(2, 180.0, participants=8192, block=512, mesh=1),
        ],
    )
    assert _run(path) == 0
    assert "NEW series" in capsys.readouterr().err


def test_explicit_metric_prefix_gates_single_family(tmp_path):
    """--metric-prefix keeps the old single-family behavior: a sim
    regression is invisible when only the fold family is requested."""
    records = [
        _rec(1, 30.0),
        _rec(2, 31.0),
        _sim_rec(3, 500.0, participants=2048, block=256),
        _sim_rec(4, 100.0, participants=2048, block=256),
    ]
    path = _write(tmp_path, records)
    assert _run(path, "--metric-prefix", bench_gate.HEADLINE_PREFIX) == 0
    assert (
        _run(path, "--metric-prefix", bench_gate.SIM_PREFIX, "--unit", "participants/s")
        == 1
    )


def test_metric_prefix_infers_unit_for_known_families(tmp_path):
    """A bare --metric-prefix for the sim family must infer participants/s
    (not fall back to updates/s, match nothing, and soft-pass a regression)."""
    records = [
        _sim_rec(1, 500.0, participants=2048, block=256),
        _sim_rec(2, 100.0, participants=2048, block=256),
    ]
    path = _write(tmp_path, records)
    assert _run(path, "--metric-prefix", bench_gate.SIM_PREFIX) == 1


def test_unknown_metric_prefix_without_unit_is_an_error(tmp_path):
    """An unknown family must demand --unit, not silently default to
    updates/s, match zero records, and soft-pass a regression."""
    import pytest

    path = _write(tmp_path, [_rec(1, 10.0, metric="long-haul soak", unit="rounds/s")])
    with pytest.raises(SystemExit) as exc:
        _run(path, "--metric-prefix", "long-haul soak")
    assert exc.value.code == 2  # argparse usage error
    assert _run(path, "--metric-prefix", "long-haul soak", "--unit", "rounds/s") == 0


# --- bytes-moved family: lower is better (round 13, packed reduction) -------

BYTES_METRIC = "bytes moved per fold @25M params (packed staging)"


def test_bytes_family_lower_is_better_pass_and_fail(tmp_path, capsys):
    # moving FEWER bytes than the best prior round is an improvement
    path = _write(
        tmp_path,
        [
            _rec(1, 1000.0, metric=BYTES_METRIC, unit="bytes/fold"),
            _rec(2, 800.0, metric=BYTES_METRIC, unit="bytes/fold"),
        ],
    )
    assert _run(path, "--metric-prefix", "bytes moved per fold") == 0
    # moving MORE than threshold above the best (smallest) prior fails
    path = _write(
        tmp_path,
        [
            _rec(1, 800.0, metric=BYTES_METRIC, unit="bytes/fold"),
            _rec(2, 1000.0, metric=BYTES_METRIC, unit="bytes/fold"),
        ],
    )
    assert _run(path, "--metric-prefix", "bytes moved per fold") == 1
    out = capsys.readouterr()
    assert "lower-is-better" in out.out


def test_bytes_family_within_threshold_passes(tmp_path):
    path = _write(
        tmp_path,
        [
            _rec(1, 1000.0, metric=BYTES_METRIC, unit="bytes/fold"),
            _rec(2, 1050.0, metric=BYTES_METRIC, unit="bytes/fold"),
        ],
    )
    assert _run(path, "--metric-prefix", "bytes moved per fold") == 0


def test_bytes_family_unit_inferred_and_gated_by_default(tmp_path):
    # unit inference for the new family (no --unit needed)
    path = _write(
        tmp_path,
        [
            _rec(1, 500.0, metric=BYTES_METRIC, unit="bytes/fold"),
            _rec(2, 499.0, metric=BYTES_METRIC, unit="bytes/fold"),
        ],
    )
    assert _run(path, "--metric-prefix", "bytes moved per fold @25M params") == 0
    # and the default (no-prefix) run gates the family alongside the others
    path = _write(
        tmp_path,
        [
            _rec(1, 20.0),
            _rec(2, 21.0),
            _rec(3, 500.0, metric=BYTES_METRIC, unit="bytes/fold"),
            _rec(4, 900.0, metric=BYTES_METRIC, unit="bytes/fold"),
        ],
    )
    assert _run(path) == 1


# --- host core count in the series fingerprint (PR 18) ----------------------

WALL_METRIC = "round wall @25000000 params"


def test_gate_cpu_count_change_starts_new_rate_series(tmp_path, capsys):
    """A 1-cpu container re-measuring a 4-cpu record is the BENCH_r05
    thread-shift incident in hardware form: the rate series must split on
    the recorded core count instead of flagging a regression."""
    path = _write(
        tmp_path,
        [
            _cfg_rec(1, 45.0, kernel="host", cpus=4),
            _cfg_rec(2, 44.0, kernel="host", cpus=4),
            _cfg_rec(3, 23.0, kernel="host", cpus=1),
        ],
    )
    assert _run(path) == 0
    assert "NEW series" in capsys.readouterr().err


def test_gate_still_fails_within_one_cpu_series(tmp_path, capsys):
    path = _write(
        tmp_path,
        [
            _cfg_rec(1, 45.0, kernel="host", cpus=4),
            _cfg_rec(2, 23.0, kernel="host", cpus=4),
        ],
    )
    assert _run(path) == 1
    verdict = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "cpus=4" in verdict["config"]


def test_gate_legacy_records_without_cpus_keep_their_series(tmp_path):
    # older writers never recorded cpus: their series fingerprints (and
    # regressions) must be unaffected by the new field
    path = _write(
        tmp_path,
        [_cfg_rec(1, 45.0, kernel="host"), _cfg_rec(2, 23.0, kernel="host")],
    )
    assert _run(path) == 1


def test_gate_round_wall_splits_on_cpu_count_too(tmp_path, capsys):
    """Walls scale with cores exactly like rates: a wall measured on a
    different core count starts a NEW s/round series (soft pass), while a
    regression within one core count still fails with the inverted floor."""
    moved = _write(
        tmp_path,
        [
            _cfg_rec(1, 60.0, metric=WALL_METRIC, unit="s/round", kernel="host", cpus=4),
            _cfg_rec(2, 90.0, metric=WALL_METRIC, unit="s/round", kernel="host", cpus=1),
        ],
    )
    assert _run(moved, "--metric-prefix", "round wall") == 0
    assert "NEW series" in capsys.readouterr().err
    same_box = _write(
        tmp_path,
        [
            _cfg_rec(1, 60.0, metric=WALL_METRIC, unit="s/round", kernel="host", cpus=1),
            _cfg_rec(2, 90.0, metric=WALL_METRIC, unit="s/round", kernel="host", cpus=1),
        ],
    )
    assert _run(same_box, "--metric-prefix", "round wall") == 1
    verdict = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert verdict["direction"] == "lower-is-better"
    assert verdict["best_prior"] == 60.0
