"""The deploy artifacts wire up real, working configuration.

Docker cannot run in the build image, so these tests verify the composed
stack the honest way available: feed the EXACT environment from
``deploy/docker-compose.yml`` into ``Settings.load`` and assert the runner
would build the Redis dictionary storage, the S3 model storage and the
Influx metrics sink from it. An env-var typo in the compose file (or a
renamed settings key) fails here.
"""

import os

import yaml

from xaynet_tpu.server.runner import init_metrics, init_store
from xaynet_tpu.server.settings import Settings

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
COMPOSE = os.path.join(REPO, "deploy", "docker-compose.yml")


def _compose_env(service: str) -> dict:
    with open(COMPOSE) as f:
        doc = yaml.safe_load(f)
    env = doc["services"][service]["environment"]
    assert isinstance(env, dict)
    return {k: str(v) for k, v in env.items()}


def test_full_stack_env_builds_redis_s3_influx():
    settings = Settings.load(path=None, env=_compose_env("coordinator-full"))

    assert settings.storage.coordinator == "redis"
    assert settings.storage.redis_host == "redis"
    assert settings.storage.redis_port == 6379
    assert settings.storage.backend == "s3"
    assert settings.storage.s3_endpoint == "http://minio:9000"
    assert settings.storage.s3_bucket == "global-models"
    assert settings.metrics.enable and settings.metrics.sink == "influx-http"
    assert settings.metrics.url == "http://influxdb:8086"
    assert settings.restore.enable

    # the smoke drive's contract: 2 sum + 18 update participants, len 1000
    assert settings.pet.sum.count.min == settings.pet.sum.count.max == 2
    assert settings.pet.update.count.min == settings.pet.update.count.max == 18
    assert settings.pet.sum2.count.min == settings.pet.sum2.count.max == 2
    assert settings.model.length == 1000

    store = init_store(settings)
    from xaynet_tpu.storage.redis import RedisCoordinatorStorage
    from xaynet_tpu.storage.s3 import S3ModelStorage

    assert isinstance(store.coordinator, RedisCoordinatorStorage)
    assert isinstance(store.models, S3ModelStorage)

    from xaynet_tpu.server.metrics import InfluxHttpMetrics

    assert isinstance(init_metrics(settings), InfluxHttpMetrics)


def test_default_service_env_builds_filesystem_jsonl():
    settings = Settings.load(path=None, env=_compose_env("coordinator"))
    assert settings.storage.backend == "filesystem"
    assert settings.metrics.sink == "jsonl"
    assert settings.restore.enable

    store = init_store(settings)
    from xaynet_tpu.storage.memory import FilesystemModelStorage

    assert isinstance(store.models, FilesystemModelStorage)


def test_k8s_full_overlay_env_matches_settings_keys():
    """Every XAYNET__* env var in the k8s overlays must resolve to a real
    settings key (guard against renames drifting the manifests)."""
    import glob

    baseline = Settings.load(path=None, env={})
    for manifest in glob.glob(os.path.join(REPO, "deploy", "k8s", "**", "*.yaml"), recursive=True):
        for doc in yaml.safe_load_all(open(manifest)):
            if not doc or doc.get("kind") != "Deployment":
                continue
            for container in doc["spec"]["template"]["spec"].get("containers", []):
                env_list = container.get("env", [])
                env = {
                    e["name"]: str(e.get("value", "x"))
                    for e in env_list
                    if e["name"].startswith("XAYNET__")
                }
                if not env:
                    continue
                loaded = Settings.load(path=None, env=env)
                for name in env:
                    # resolve XAYNET__SECTION__KEY on the loaded settings;
                    # an unknown key would leave the default untouched AND
                    # not exist as an attribute path
                    node = loaded
                    parts = [p.lower() for p in name.split("__")[1:]]
                    for part in parts:
                        assert hasattr(node, part), f"{manifest}: {name} has no settings field"
                        node = getattr(node, part)
    assert baseline.storage.backend == "memory"  # library default unchanged
