"""Phase overlap & speculation (docs/DESIGN.md §22, ISSUE 18).

The overlap engines shrink the round wall below the serial sum of phase
walls; everything rests on **byte-identity with the serial path**. These
tests pin:

- speculative sum2 mask derivation (`ops.speculation`): hit / miss /
  discard reconciliation byte-identical to `sum_masks`, including
  mis-speculation (a speculated participant dropping before sum2),
  across mesh={1,8} and the host/device derive routes;
- eager per-shard unmask (`parallel.streaming._UnmaskJob`): identical to
  the drain-then-subtract serial pass on the native and XLA shard
  routes, correct fallback on a single-device mesh, and two tenants
  pipelined through the shared scheduler concurrently;
- `TenantScheduler.try_acquire_idle`: never blocks, never starves a
  real waiter, never distorts the fairness split;
- the `[overlap]` settings surface (defaults, env override, master
  gate);
- persisted calibration verdicts (`utils.calibcache`): cold→warm
  round-trip, fingerprint invalidation, corrupt-file fail-soft, and a
  warm verdict short-circuiting the mask probe race;
- the `xaynet_round_wall_seconds` log bucket ladder over a live render;
- `tools/trace_report.py --overlap`: concurrency lanes + the timeline
  identity assertion on synthetic traces.
"""

import json
import os
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

import jax

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from xaynet_tpu.core.mask import (
    Aggregation,
    BoundType,
    DataType,
    GroupType,
    Masker,
    MaskConfig,
    ModelType,
    Scalar,
)
from xaynet_tpu.ops import limbs as host_limbs
from xaynet_tpu.ops import masking_jax
from xaynet_tpu.ops.speculation import SpeculativeMaskSession
from xaynet_tpu.parallel.aggregator import ShardedAggregator
from xaynet_tpu.parallel.mesh import make_mesh
from xaynet_tpu.parallel.streaming import StreamingAggregator
from xaynet_tpu.server.settings import OverlapSettings, Settings
from xaynet_tpu.tenancy.scheduler import TenantScheduler
from xaynet_tpu.utils import calibcache

CFG = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M6)

LEN = 257  # odd on purpose: uneven shard slices + a padded tail


def _seeds(n, tag=0):
    return [bytes([i & 0xFF, i >> 8, tag]) + b"\x5a" * 29 for i in range(n)]


def _settle_all(spec, n, deadline_s=60.0):
    """Wait until the background worker folded all n offered seeds (the
    deterministic all-hit setup; compile time makes a fixed sleep flaky)."""
    t0 = time.monotonic()
    while spec.speculated() < n:
        if time.monotonic() - t0 > deadline_s:
            pytest.fail(f"speculation folded {spec.speculated()}/{n} seeds")
        time.sleep(0.01)


# --- speculative mask derivation ------------------------------------------


def test_speculation_all_hits_byte_identical():
    seeds = _seeds(6)
    unit_ref, vect_ref = masking_jax.sum_masks(seeds, LEN, CFG.pair())
    spec = SpeculativeMaskSession(LEN, CFG.pair())
    spec.offer(seeds)
    _settle_all(spec, len(seeds))
    unit, vect = spec.settle(seeds)
    np.testing.assert_array_equal(np.asarray(vect), np.asarray(vect_ref))
    np.testing.assert_array_equal(np.asarray(unit), np.asarray(unit_ref))


def test_speculation_settle_without_worker_progress_is_serial():
    # settle may run before the worker derives anything (or after it only
    # got part way): every un-folded seed is a miss = the serial path
    seeds = _seeds(5, tag=1)
    unit_ref, vect_ref = masking_jax.sum_masks(seeds, LEN, CFG.pair())
    spec = SpeculativeMaskSession(LEN, CFG.pair())
    spec.offer(seeds)
    unit, vect = spec.settle(seeds)  # immediately: any mix of hit/miss
    np.testing.assert_array_equal(np.asarray(vect), np.asarray(vect_ref))
    np.testing.assert_array_equal(np.asarray(unit), np.asarray(unit_ref))


@pytest.mark.parametrize("kernel", ["host-threaded", "batch"])
@pytest.mark.parametrize("mesh_devices", [1, 8])
def test_misspeculation_discard_byte_identical(kernel, mesh_devices):
    """PR-5 churn as mis-speculation: a speculated sum participant drops
    before sum2 — its folded mask must be subtracted back out exactly, on
    host and device derive routes, single-device and 8-device meshes."""
    mesh = make_mesh(jax.devices()[:mesh_devices]) if mesh_devices > 1 else None
    offered = _seeds(5, tag=2)
    dropped = offered[2]
    actual = [s for s in offered if s != dropped]  # + one never-offered miss
    actual.append(_seeds(1, tag=3)[0])
    unit_ref, vect_ref = masking_jax.sum_masks(
        actual, LEN, CFG.pair(), kernel=kernel, mesh=mesh
    )
    spec = SpeculativeMaskSession(LEN, CFG.pair(), kernel=kernel, mesh=mesh)
    spec.offer(offered)
    _settle_all(spec, len(offered))  # the dropped seed IS folded -> discard
    unit, vect = spec.settle(actual)
    np.testing.assert_array_equal(np.asarray(vect), np.asarray(vect_ref))
    np.testing.assert_array_equal(np.asarray(unit), np.asarray(unit_ref))


def test_speculation_records_outcomes(monkeypatch):
    from xaynet_tpu.telemetry import timeline

    recorded = []
    monkeypatch.setattr(
        "xaynet_tpu.ops.speculation.record_spec_outcomes",
        lambda hits=0, misses=0, discards=0: recorded.append(
            (hits, misses, discards)
        ),
    )
    offered = _seeds(4, tag=4)
    actual = offered[:3] + _seeds(1, tag=5)
    spec = SpeculativeMaskSession(LEN, CFG.pair())
    spec.offer(offered)
    _settle_all(spec, len(offered))
    spec.settle(actual)
    assert recorded == [(3, 1, 1)]
    # and the real counter exists with the registered outcome labels
    assert timeline.SPEC_DERIVE is not None


def test_speculation_idle_slots_only():
    """A busy scheduler (waiter pending) denies the worker; every seed
    becomes a miss and settle still returns the exact aggregate."""
    sched = TenantScheduler(max_inflight=1)
    blocker = sched.new_owner()
    sched.acquire("real", blocker)  # the mesh is busy for the whole test
    try:
        seeds = _seeds(4, tag=6)
        unit_ref, vect_ref = masking_jax.sum_masks(seeds, LEN, CFG.pair())
        spec = SpeculativeMaskSession(
            LEN, CFG.pair(), tenant="spec", scheduler=sched
        )
        spec.offer(seeds)
        time.sleep(0.2)  # give the worker a chance to (wrongly) grab a slot
        assert spec.speculated() == 0
        unit, vect = spec.settle(seeds)
        np.testing.assert_array_equal(np.asarray(vect), np.asarray(vect_ref))
        np.testing.assert_array_equal(np.asarray(unit), np.asarray(unit_ref))
        assert "spec" not in sched.split()  # idle grants never charge fairness
    finally:
        sched.release_owner(blocker)


# --- scheduler idle slots --------------------------------------------------


def test_try_acquire_idle_semantics():
    sched = TenantScheduler(max_inflight=2)
    a, b, c = sched.new_owner(), sched.new_owner(), sched.new_owner()
    # idle mesh: granted, but NOT charged to the fairness split
    assert sched.try_acquire_idle("bg", a)
    assert sched.split() == {}
    # at capacity: denied
    sched.acquire("fg", b)
    assert not sched.try_acquire_idle("bg", a)
    sched.release(a)
    # capacity free but a regular waiter pending: denied (never starve)
    waited = threading.Event()

    def waiter():
        sched.acquire("fg", c)
        waited.set()

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    deadline = time.monotonic() + 5.0
    while not sched._waiting and not waited.is_set():
        if time.monotonic() > deadline:
            pytest.fail("waiter never queued")
        time.sleep(0.005)
    if not waited.is_set():
        assert not sched.try_acquire_idle("bg", a)
    sched.release(b)
    t.join(timeout=5.0)
    assert waited.is_set()
    assert sched.split() == {"fg": 2}
    sched.release_owner(c)
    sched.release_owner(a)


# --- eager per-shard unmask ------------------------------------------------


def _updates(n, total, seed=0):
    rng = np.random.default_rng(seed)
    host = Aggregation(CFG.pair(), n)
    stacks = []
    for _ in range(total):
        w = rng.uniform(-1, 1, size=n).astype(np.float32)
        _, masked = Masker(CFG.pair()).mask(Scalar(1, total), w)
        host.aggregate(masked)
        stacks.append(masked.vect.data)
    return stacks, host


def _random_mask_vect(n, seed=7):
    rng = np.random.default_rng(seed)
    n_limb = host_limbs.n_limbs_for_order(CFG.order)
    top = int(CFG.order >> (32 * (n_limb - 1)))
    vect = rng.integers(0, 1 << 32, size=(n, n_limb), dtype=np.uint32)
    vect[:, n_limb - 1] = rng.integers(0, top, size=n, dtype=np.uint32)
    return vect


@pytest.mark.parametrize("kernel", ["xla", "native-u64"])
def test_eager_unmask_byte_identical_sharded(kernel):
    stacks, host = _updates(LEN, 9)
    mask_vect = _random_mask_vect(LEN)
    ol = host_limbs.order_limbs_for(CFG.order)
    expected = host_limbs.mod_sub(host.object.vect.data, mask_vect, ol)

    agg = ShardedAggregator(CFG, LEN, mesh=make_mesh(jax.devices()), kernel=kernel)
    stream = StreamingAggregator(agg, max_batch=4)
    for i in range(0, len(stacks), 4):
        stream.submit_batch(np.stack(stacks[i : i + 4]))
    job = stream.stage_unmask(agg.mask_planar(mask_vect))
    assert job is not None, "sharded pipeline must take the eager path"
    stream.drain()
    out = stream.finish_unmask(job)
    assert out is not None, "no shard error -> the eager result must land"
    np.testing.assert_array_equal(out, expected)
    stream.close()


def test_eager_unmask_single_device_falls_back():
    stacks, host = _updates(LEN, 5)
    agg = ShardedAggregator(CFG, LEN, mesh=make_mesh(jax.devices()[:1]), kernel="xla")
    stream = StreamingAggregator(agg, max_batch=4)
    for i in range(0, len(stacks), 4):
        stream.submit_batch(np.stack(stacks[i : i + 4]))
    mask_vect = _random_mask_vect(LEN)
    assert stream.stage_unmask(agg.mask_planar(mask_vect)) is None
    stream.drain()
    # the serial pass the caller falls back to is still exact
    ol = host_limbs.order_limbs_for(CFG.order)
    expected = host_limbs.mod_sub(host.object.vect.data, mask_vect, ol)
    np.testing.assert_array_equal(agg.unmask_limbs(mask_vect), expected)
    stream.close()


def test_eager_unmask_failure_falls_back_serial(monkeypatch):
    """A shard failure during the eager subtract must surface as a None
    from finish_unmask (fall back to the serial pass), never a wrong
    array and never a poisoned pipeline."""
    stacks, host = _updates(LEN, 4)
    agg = ShardedAggregator(CFG, LEN, mesh=make_mesh(jax.devices()), kernel="xla")
    stream = StreamingAggregator(agg, max_batch=4)
    stream.submit_batch(np.stack(stacks))
    real = ShardedAggregator.unmask_shard

    def boom(self, plan, d, mask_planar, out):
        if d == 1:
            raise RuntimeError("injected shard fault")
        return real(self, plan, d, mask_planar, out)

    monkeypatch.setattr(ShardedAggregator, "unmask_shard", boom)
    mask_vect = _random_mask_vect(LEN)
    job = stream.stage_unmask(agg.mask_planar(mask_vect))
    assert job is not None
    stream.drain()
    assert stream.finish_unmask(job) is None
    monkeypatch.setattr(ShardedAggregator, "unmask_shard", real)
    ol = host_limbs.order_limbs_for(CFG.order)
    expected = host_limbs.mod_sub(host.object.vect.data, mask_vect, ol)
    np.testing.assert_array_equal(agg.unmask_limbs(mask_vect), expected)
    stream.close()


def test_two_tenant_pipelined_eager_unmask_byte_identical():
    """Two tenants' rounds pipelined through the SHARED deficit-round-robin
    scheduler, each finishing with an eager per-shard unmask — both
    byte-identical to their serial controls."""
    sched = TenantScheduler(max_inflight=4)
    mesh = make_mesh(jax.devices())
    cases = {}
    for tag, tenant in ((10, "a"), (11, "b")):
        stacks, host = _updates(LEN, 8, seed=tag)
        mask_vect = _random_mask_vect(LEN, seed=tag)
        agg = ShardedAggregator(CFG, LEN, mesh=mesh, kernel="xla")
        stream = StreamingAggregator(
            agg, max_batch=4, tenant=tenant, scheduler=sched
        )
        cases[tenant] = (stacks, host, mask_vect, agg, stream)

    def run(tenant):
        stacks, _, mask_vect, agg, stream = cases[tenant]
        for i in range(0, len(stacks), 4):
            stream.submit_batch(np.stack(stacks[i : i + 4]))
        job = stream.stage_unmask(agg.mask_planar(mask_vect))
        stream.drain()
        return stream.finish_unmask(job) if job is not None else None

    results = {}
    errs = []

    def worker(tenant):
        try:
            results[tenant] = run(tenant)
        except BaseException as e:  # surfaced below
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(t,)) for t in cases]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120.0)
    assert not errs, errs
    ol = host_limbs.order_limbs_for(CFG.order)
    for tenant, (_, host, mask_vect, agg, stream) in cases.items():
        expected = host_limbs.mod_sub(host.object.vect.data, mask_vect, ol)
        got = results[tenant]
        if got is None:  # eager leg unavailable -> serial fallback is exact
            got = agg.unmask_limbs(mask_vect)
        np.testing.assert_array_equal(got, expected)
        stream.close()
    # both tenants' fold batches went through the shared fairness split
    split = sched.split()
    assert split.get("a", 0) > 0 and split.get("b", 0) > 0


# --- [overlap] settings ----------------------------------------------------


def test_overlap_settings_defaults_and_master_gate():
    o = OverlapSettings()
    assert o.enabled and o.spec_group == 8
    for f in ("speculative_derive", "eager_unmask", "sum2_drain"):
        assert o.feature(f)
    o.enabled = False
    for f in ("speculative_derive", "eager_unmask", "sum2_drain"):
        assert not o.feature(f)
    with pytest.raises(Exception):
        OverlapSettings(spec_group=0).validate()


def test_overlap_settings_config_and_env():
    s = Settings.load(str(REPO / "configs" / "config.toml"))
    assert s.overlap.enabled and s.overlap.eager_unmask
    s2 = Settings.load(
        str(REPO / "configs" / "config.toml"),
        env={"XAYNET__OVERLAP__EAGER_UNMASK": "false"},
    )
    assert not s2.overlap.feature("eager_unmask")
    assert s2.overlap.feature("sum2_drain")
    s3 = Settings.load(
        str(REPO / "configs" / "config.toml"),
        env={"XAYNET__OVERLAP__ENABLED": "false"},
    )
    assert not s3.overlap.feature("sum2_drain")


# --- persisted calibration verdicts ---------------------------------------


@pytest.fixture
def calib_path(tmp_path):
    path = str(tmp_path / "calib.json")
    yield path
    calibcache.configure(None)  # never leak a cache into other tests


def test_calibcache_cold_warm_roundtrip(calib_path):
    calibcache.configure(calib_path)
    key = ("cpu", 123, "cfg", 8, None)
    assert calibcache.get("fold", key) is None  # cold
    calibcache.put("fold", key, "native-u64")
    calibcache.put("mask", key, "host-threaded")
    # a fresh "process": reload from disk
    calibcache.configure(calib_path)
    assert calibcache.get("fold", key) == "native-u64"
    assert calibcache.get("mask", key) == "host-threaded"
    raw = json.loads(Path(calib_path).read_text())
    assert raw["fingerprint"] == calibcache.fingerprint()


def test_calibcache_fingerprint_invalidates(calib_path, monkeypatch):
    calibcache.configure(calib_path)
    key = ("cpu", 1, None)
    calibcache.put("fold", key, "xla")
    monkeypatch.setattr(calibcache, "fingerprint", lambda: "other-machine")
    calibcache.configure(calib_path)
    assert calibcache.get("fold", key) is None


def test_calibcache_corrupt_file_fail_soft(calib_path):
    Path(calib_path).write_text("{not json")
    calibcache.configure(calib_path)  # must not raise
    assert calibcache.get("fold", ("k",)) is None
    calibcache.put("fold", ("k",), "xla")  # and recovers by rewriting
    calibcache.configure(calib_path)
    assert calibcache.get("fold", ("k",)) == "xla"


def test_calibcache_disabled_is_inert(calib_path):
    calibcache.configure(None)
    calibcache.put("fold", ("k",), "xla")
    assert calibcache.get("fold", ("k",)) is None
    assert not os.path.exists(calib_path)


def test_warm_mask_verdict_skips_probe_race(calib_path, monkeypatch):
    """A persisted verdict must short-circuit `_resolve_mask_kernel` —
    no probe race (sum_masks during resolution would be a cold race)."""
    seeds = _seeds(4, tag=9)
    length = LEN * 3
    calibcache.configure(calib_path)
    # cold race once to learn the exact verdict key + winner
    monkeypatch.setattr(masking_jax, "_MASK_KERNEL_CACHE", {})
    winner = masking_jax.calibrate_mask_kernel(seeds, length, CFG.pair())
    raw = json.loads(Path(calib_path).read_text())
    assert winner in raw["verdicts"]["mask"].values()
    # fresh process: empty in-process memo, warm disk tier; every probe
    # candidate runs through _mask_route -> spy it to prove none ran
    monkeypatch.setattr(masking_jax, "_MASK_KERNEL_CACHE", {})
    calibcache.configure(calib_path)
    calls = []
    real_route = masking_jax._mask_route

    def spy(*a, **k):
        calls.append(a[0])
        return real_route(*a, **k)

    monkeypatch.setattr(masking_jax, "_mask_route", spy)
    got = masking_jax.calibrate_mask_kernel(seeds, length, CFG.pair())
    assert got == winner
    assert calls == [], f"probe race ran despite a warm verdict: {calls}"


# --- round-wall bucket ladder ---------------------------------------------


def test_round_wall_buckets_log_ladder_live_render():
    from xaynet_tpu.telemetry.registry import get_registry
    from xaynet_tpu.telemetry.timeline import ROUND_WALL, ROUND_WALL_BUCKETS

    assert ROUND_WALL_BUCKETS[0] == 0.05 and ROUND_WALL_BUCKETS[-1] == 120.0
    # a log ladder: every step multiplies by at most ~2.5x — the seed's
    # sparse default tail (30 -> +Inf) put a 61s round in a bucket with
    # no resolution; this pins the regression shut
    for lo, hi in zip(ROUND_WALL_BUCKETS, ROUND_WALL_BUCKETS[1:]):
        assert 1.0 < hi / lo <= 2.5
    ROUND_WALL.labels(tenant="bucket-test").observe(61.0)
    text = get_registry().render()
    lines = [
        l
        for l in text.splitlines()
        if l.startswith("xaynet_round_wall_seconds_bucket")
        and 'tenant="bucket-test"' in l
    ]
    rendered_les = {l.split('le="')[1].split('"')[0] for l in lines}
    for b in ROUND_WALL_BUCKETS:
        assert any(float(le) == b for le in rendered_les - {"+Inf"}), b
    # the 61s observation lands between 60 and 90 — real resolution there
    by_le = {
        float(le): float(l.rsplit(" ", 1)[1])
        for l in lines
        for le in [l.split('le="')[1].split('"')[0]]
        if le != "+Inf"
    }
    assert by_le[60.0] == 0.0 and by_le[90.0] == 1.0


# --- trace_report --overlap ------------------------------------------------


def _span(name, ts_us, dur_us, **attrs):
    return {"name": name, "ph": "X", "ts": ts_us, "dur": dur_us, "args": attrs}


def _round_events(with_overlap):
    # idle closes at 1.0s; serial phases sum=1s update=1s sum2=1s
    # unmask=0.2s; the overlap span is 0.5s of update-work under sum2
    ev = [
        _span("phase.idle", 0, 1_000_000, round_id=1),
        _span("round", 900_000, 3_400_000, round_id=1),
        _span("phase.sum", 1_000_000, 1_000_000, round_id=1),
        _span("phase.update", 2_000_000, 1_000_000, round_id=1),
        _span("phase.sum2", 3_000_000, 1_000_000, round_id=1),
        _span("phase.unmask", 4_000_000, 200_000, round_id=1),
    ]
    if with_overlap:
        ev.append(
            _span("overlap.drain", 3_100_000, 500_000, phase="update", tenant="t")
        )
    return ev


def test_trace_report_overlap_identity_balances():
    from tools import trace_report

    lanes, problems = trace_report.overlap_report(_round_events(True))
    assert problems == []
    assert "overlap.drain" in lanes and "under sum2" in lanes
    # update's wall grew by the reattributed 0.5s -> sum(walls) > wall,
    # negative slack measured
    assert "phase update: wall 1.5000s" in lanes
    assert "negative slack: -0.5000s" in lanes


def test_trace_report_overlap_serial_round_no_slack():
    from tools import trace_report

    lanes, problems = trace_report.overlap_report(_round_events(False))
    assert problems == []
    assert "no overlap.* spans" in lanes
    assert "negative slack: +0.0000s" in lanes


def test_trace_report_overlap_flags_missing_phase_attr():
    from tools import trace_report

    ev = _round_events(False)
    ev.append(_span("overlap.eager_unmask", 3_000_000, 100_000, shard=0))
    lanes, problems = trace_report.overlap_report(ev)
    assert any("without a work-phase" in p for p in problems)


def _mk_span(name, start, dur, **attrs):
    from xaynet_tpu.telemetry.tracing import Span

    s = Span(name, "deadbeef", f"s{start}", None, start, attrs)
    s.duration = dur
    return s


def _fold_input():
    t = 100.0
    return [
        _mk_span("phase.idle", t, 1.0, round_id=1, tenant="t"),
        _mk_span("round", t + 0.9, 3.3, round_id=1),
        _mk_span("phase.sum", t + 1.0, 1.0, round_id=1, tenant="t"),
        _mk_span("phase.update", t + 2.0, 1.0, round_id=1, tenant="t"),
        _mk_span("phase.sum2", t + 3.0, 1.0, round_id=1, tenant="t"),
        # 0.6s of update-phase work (the drain) ran INSIDE sum2's window
        _mk_span("overlap.drain", t + 3.1, 0.6, phase="update", tenant="t"),
        _mk_span("phase.unmask", t + 4.0, 0.2, round_id=1, tenant="t"),
    ]


def test_trace_report_overlap_cli_on_exported_trace(tmp_path):
    """End to end: a round's Chrome-trace export through the --overlap CLI
    (the CI trace-step invocation) — exit 0, identity balanced."""
    from xaynet_tpu.telemetry.tracing import to_chrome_trace

    from tools import trace_report

    doc = to_chrome_trace(_fold_input(), anchor=100.0)
    path = tmp_path / "round.trace.json"
    path.write_text(json.dumps(doc))
    assert trace_report.main(["--overlap", str(path)]) == 0


# --- server round: the phase machine engages the overlap engines ----------


@pytest.mark.parametrize("enabled", [True, False])
def test_server_round_overlap_engines(enabled, monkeypatch):
    """A real device-aggregation PET round end to end. With `[overlap]`
    enabled (the default) the unmask phase must go through the eager
    per-shard path (stage_unmask on the still-live stream) and the update
    phase must exit via flush (the drain rides into sum2); disabled, the
    round is fully serial — and both produce the exact mean."""
    import asyncio
    from fractions import Fraction

    from xaynet_tpu.sdk.client import InProcessClient
    from xaynet_tpu.sdk.simulation import keys_for_task
    from xaynet_tpu.sdk.state_machine import (
        PetSettings,
        StateMachine as ParticipantSM,
    )
    from xaynet_tpu.sdk.traits import ModelStore
    from xaynet_tpu.server.aggregation import StagedAggregator
    from xaynet_tpu.server.services import Fetcher, PetMessageHandler
    from xaynet_tpu.server.settings import (
        CountSettings,
        PhaseSettings,
        PetSettings as ServerPet,
        Settings as ServerSettings,
        Sum2Settings,
        TimeSettings,
    )
    from xaynet_tpu.server.state_machine import StateMachineInitializer
    from xaynet_tpu.storage.memory import (
        InMemoryCoordinatorStorage,
        InMemoryModelStorage,
        NoOpTrustAnchor,
    )
    from xaynet_tpu.storage.traits import Store

    staged, drained = [], []
    real_stage = StreamingAggregator.stage_unmask
    real_drain = StagedAggregator.drain

    def stage_spy(self, mask_planar):
        job = real_stage(self, mask_planar)
        staged.append(job is not None)
        return job

    def drain_spy(self):
        drained.append(threading.current_thread().name)
        return real_drain(self)

    monkeypatch.setattr(StreamingAggregator, "stage_unmask", stage_spy)
    monkeypatch.setattr(StagedAggregator, "drain", drain_spy)

    class ArrayModelStore(ModelStore):
        def __init__(self, model):
            self.model = model

        async def load_model(self):
            return self.model

    n_sum, n_update, model_len = 2, 3, 600

    async def run():
        settings = ServerSettings(
            pet=ServerPet(
                sum=PhaseSettings(
                    prob=0.4,
                    count=CountSettings(min=n_sum, max=n_sum),
                    time=TimeSettings(min=0.0, max=20.0),
                ),
                update=PhaseSettings(
                    prob=0.5,
                    count=CountSettings(min=n_update, max=n_update),
                    time=TimeSettings(min=0.0, max=20.0),
                ),
                sum2=Sum2Settings(
                    count=CountSettings(min=n_sum, max=n_sum),
                    time=TimeSettings(min=0.0, max=20.0),
                ),
            )
        )
        settings.model.length = model_len
        settings.aggregation.device = True
        settings.aggregation.batch_size = 2
        settings.aggregation.kernel = "xla"
        settings.overlap.enabled = enabled
        settings.validate()
        store = Store(
            InMemoryCoordinatorStorage(), InMemoryModelStorage(), NoOpTrustAnchor()
        )
        machine, request_tx, events = await StateMachineInitializer(
            settings, store
        ).init()
        handler = PetMessageHandler(events, request_tx)
        fetcher = Fetcher(events)
        machine_task = asyncio.create_task(machine.run())
        try:
            while fetcher.phase().value != "sum":
                await asyncio.sleep(0.01)
            seed = fetcher.round_params().seed.as_bytes()
            rng = np.random.default_rng(5)
            expected = np.zeros(model_len)
            participants = []
            for i in range(n_sum):
                keys = keys_for_task(seed, 0.4, 0.5, "sum", start=i * 1000)
                participants.append(
                    ParticipantSM(
                        PetSettings(keys=keys, max_message_size=1024),
                        InProcessClient(fetcher, handler),
                        ArrayModelStore(None),
                    )
                )
            for i in range(n_update):
                keys = keys_for_task(seed, 0.4, 0.5, "update", start=(10 + i) * 1000)
                local = rng.uniform(-1, 1, model_len).astype(np.float32)
                expected += local.astype(np.float64) / n_update
                participants.append(
                    ParticipantSM(
                        PetSettings(
                            keys=keys,
                            scalar=Fraction(1, n_update),
                            max_message_size=1024,
                        ),
                        InProcessClient(fetcher, handler),
                        ArrayModelStore(local),
                    )
                )

            async def drive(sm):
                for _ in range(500):
                    try:
                        await sm.transition()
                    except Exception:
                        pass
                    if fetcher.model() is not None and sm.phase.value == "awaiting":
                        return
                    await asyncio.sleep(0.01)

            await asyncio.gather(*(drive(p) for p in participants))
            while fetcher.model() is None:
                await asyncio.sleep(0.01)
            return np.asarray(fetcher.model()), expected
        finally:
            machine_task.cancel()
            try:
                await machine_task
            except (asyncio.CancelledError, Exception):
                pass

    got, expected = asyncio.run(asyncio.wait_for(run(), timeout=180))
    np.testing.assert_allclose(got, expected, atol=1e-9)
    if enabled:
        assert staged and staged[-1], "unmask did not take the eager path"
        # the sum2-window drain ran OFF the event loop (executor thread)
        assert any(name != "MainThread" for name in drained)
    else:
        assert not staged, "disabled overlap must stay fully serial"


# --- negative slack through the in-process timeline fold -------------------


def test_timeline_fold_negative_slack_from_overlap_spans():
    """The tentpole's measured identity: an `overlap.*` retro span merged
    into its home phase makes wall < sum(phase walls), and the §20
    identity still balances."""
    from xaynet_tpu.telemetry.timeline import fold_spans

    decomp = fold_spans(1, _fold_input())
    assert decomp is not None
    walls = sum(p["wall_s"] for p in decomp["phases"].values())
    wall = decomp["wall_s"]
    overlap = decomp["overlap_s"]
    gap = decomp["gap_s"]
    assert decomp["phases"]["update"]["wall_s"] == pytest.approx(1.6, abs=1e-6)
    assert overlap == pytest.approx(0.6, abs=1e-6)
    assert wall < walls  # negative slack: the identity's measured win
    assert walls - overlap + gap == pytest.approx(wall, abs=1e-6)
