"""Round-wall timeline fold (ISSUE 16, docs/DESIGN.md §20).

Covers the streaming fold's contracts: the Idle-close→Unmask-complete
bracket, the exact decomposition identity ``sum(phase walls) - overlap +
gap == wall``, the degraded flag, the top-k heap's exclusions, the
per-tenant accumulation across interleaved multi-tenant flush windows
(a tenant's round may span several shared-tracer windows), the
``xaynet_round_wall_seconds`` histogram, and the flight recorder's
histogram ``_sum``/``_count`` delta regression (round-wall latency
evidence must survive into forensic bundles).
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from xaynet_tpu.telemetry import recorder as recorder_mod  # noqa: E402
from xaynet_tpu.telemetry import timeline as timeline_mod  # noqa: E402
from xaynet_tpu.telemetry.registry import get_registry  # noqa: E402
from xaynet_tpu.telemetry.timeline import (  # noqa: E402
    ROUND_WALL,
    RoundTimeline,
    fold_spans,
)
from xaynet_tpu.telemetry.tracing import Span  # noqa: E402


def _span(name, start, duration, **attrs):
    s = Span(name, "t", f"s{name}-{start}", None, start, attrs)
    s.duration = duration
    return s


def _round_spans(tenant="default", round_id=7, base=100.0, outcome="full"):
    """idle + the four work phases back to back, with a root span."""
    spans = [_span("phase.idle", base, 1.0, tenant=tenant, round_id=round_id)]
    t = base + 1.0
    for phase, dur in (("sum", 2.0), ("update", 3.0), ("sum2", 1.5), ("unmask", 0.5)):
        spans.append(
            _span(
                f"phase.{phase}", t, dur,
                tenant=tenant, round_id=round_id, outcome=outcome,
            )
        )
        t += dur
    root = _span("round", base, t - base, round_id=round_id)
    spans.append(root)
    return spans


def _wall_count(tenant: str) -> float:
    return float(ROUND_WALL.labels(tenant=tenant).count)


# --- fold_spans --------------------------------------------------------------


def test_fold_bracket_is_idle_close_to_unmask_complete():
    decomp = fold_spans(7, _round_spans())
    # idle ends at 101.0, unmask ends at 101 + 2 + 3 + 1.5 + 0.5 = 108.0
    assert decomp["wall_s"] == pytest.approx(7.0, abs=1e-6)
    assert decomp["round_id"] == 7
    assert decomp["tenant"] == "default"
    assert set(decomp["phases"]) == {"sum", "update", "sum2", "unmask"}
    assert decomp["degraded"] is False


def test_fold_identity_exact_with_overlap_and_gap():
    # sum [1,3], update [2,5] (1s overlap), sum2 [6,7] (1s gap), unmask [7,8]
    spans = [
        _span("phase.idle", 0.0, 1.0, tenant="default"),
        _span("phase.sum", 1.0, 2.0, tenant="default", outcome="full"),
        _span("phase.update", 2.0, 3.0, tenant="default", outcome="full"),
        _span("phase.sum2", 6.0, 1.0, tenant="default", outcome="full"),
        _span("phase.unmask", 7.0, 1.0, tenant="default"),
        _span("round", 0.0, 8.0, round_id=3),
    ]
    decomp = fold_spans(3, spans)
    assert decomp["wall_s"] == pytest.approx(7.0, abs=1e-6)
    assert decomp["overlap_s"] == pytest.approx(1.0, abs=1e-6)
    assert decomp["gap_s"] == pytest.approx(1.0, abs=1e-6)
    total = sum(p["wall_s"] for p in decomp["phases"].values())
    # the §20 identity: phase walls minus overlap plus gap IS the wall
    assert total - decomp["overlap_s"] + decomp["gap_s"] == pytest.approx(
        decomp["wall_s"], abs=5e-6
    )
    # self time: sum has 1s of its 2s overlapped by update
    assert decomp["phases"]["sum"]["self_s"] == pytest.approx(1.0, abs=1e-6)
    assert decomp["phases"]["update"]["self_s"] == pytest.approx(2.0, abs=1e-6)


def test_fold_degraded_flag_from_span_outcome():
    assert fold_spans(1, _round_spans(outcome="degraded"))["degraded"] is True
    assert fold_spans(1, _round_spans(outcome="timeout"))["degraded"] is True
    assert fold_spans(1, _round_spans(outcome="full"))["degraded"] is False


def test_fold_topk_excludes_idle_and_root_and_ranks():
    spans = _round_spans()
    # a slow streaming child must outrank the phases in the top-k
    spans.insert(3, _span("stream.fold", 103.0, 6.0, batch=1))
    decomp = fold_spans(7, spans)
    names = [entry["span"] for entry in decomp["slowest"]]
    assert names[0] == "stream.fold"
    assert "phase.idle" not in names
    assert "round" not in names
    assert len(names) <= 5
    durations = [entry["seconds"] for entry in decomp["slowest"]]
    assert durations == sorted(durations, reverse=True)


def test_fold_falls_back_to_root_when_no_phases():
    root = _span("round", 10.0, 4.0, round_id=9)
    decomp = fold_spans(9, [root])
    assert decomp["wall_s"] == pytest.approx(4.0, abs=1e-6)
    assert decomp["phases"] == {}


def test_fold_no_usable_spans_returns_none():
    assert fold_spans(1, []) is None
    assert fold_spans(1, [_span("stream.fold", 0.0, 1.0)]) is None


# --- RoundTimeline: per-tenant accumulation ---------------------------------


def test_timeline_folds_on_unmask_and_observes_histogram():
    tl = RoundTimeline()
    before = _wall_count("tl-t1")
    tl.on_round(7, _round_spans(tenant="tl-t1"))
    assert _wall_count("tl-t1") == before + 1
    last = tl.last("tl-t1")
    assert last is not None and last["round_id"] == 7
    assert last["wall_s"] == pytest.approx(7.0, abs=1e-6)
    assert tl.recent_walls("tl-t1") == [(7, last["wall_s"])]
    assert tl.rounds_folded() == 1
    assert tl.tenants() == ["tl-t1"]


def test_timeline_multi_tenant_interleaved_windows():
    """A shared flush window carries both tenants' spans; tenant B's round
    completes only in the NEXT window — its wall must still bracket the
    idle from the first window."""
    tl = RoundTimeline()
    a = _round_spans(tenant="tl-a", round_id=4, base=0.0)
    # B: idle + sum land in window 1, the rest in window 2
    b_early = [
        _span("phase.idle", 0.0, 2.0, tenant="tl-b", round_id=9),
        _span("phase.sum", 2.0, 1.0, tenant="tl-b", round_id=9, outcome="full"),
    ]
    b_late = [
        _span("phase.update", 3.0, 1.0, tenant="tl-b", round_id=9, outcome="full"),
        _span("phase.sum2", 4.0, 1.0, tenant="tl-b", round_id=9, outcome="full"),
        _span("phase.unmask", 5.0, 1.0, tenant="tl-b", round_id=9),
    ]
    tl.on_round(4, a + b_early)
    assert tl.last("tl-a") is not None  # A folded from window 1
    assert tl.last("tl-b") is None  # B still pending
    tl.on_round(5, b_late)
    last_b = tl.last("tl-b")
    assert last_b is not None
    assert last_b["round_id"] == 9  # rid from the unmask span, not the window
    assert last_b["wall_s"] == pytest.approx(4.0, abs=1e-6)  # idle end 2 -> 6


def test_timeline_spans_after_unmask_seed_next_window():
    tl = RoundTimeline()
    spans = _round_spans(tenant="tl-seed", round_id=1, base=0.0)
    # the next round's idle flushes in the same window
    spans.append(_span("phase.idle", 9.0, 1.0, tenant="tl-seed", round_id=2))
    tl.on_round(1, spans)
    assert tl.last("tl-seed")["round_id"] == 1
    pending = tl._pending.get("tl-seed", [])
    assert [s.name for s in pending] == ["phase.idle"]


def test_timeline_untagged_spans_join_single_tenant_window():
    tl = RoundTimeline()
    spans = _round_spans(tenant="tl-solo")
    spans.insert(2, _span("stream.fold", 102.0, 5.0, batch=0))  # no tenant attr
    tl.on_round(7, spans)
    names = [e["span"] for e in tl.last("tl-solo")["slowest"]]
    assert names[0] == "stream.fold"


def test_timeline_pending_cap_bounds_memory():
    tl = RoundTimeline()
    spans = [
        _span("phase.sum", float(i), 0.5, tenant="tl-cap", outcome="full")
        for i in range(timeline_mod._PENDING_CAP + 100)
    ]
    tl.on_round(1, spans)  # no unmask: everything pends, trimmed to the cap
    assert len(tl._pending["tl-cap"]) == timeline_mod._PENDING_CAP


def test_fold_for_report_falls_back_to_last_fold():
    tl = RoundTimeline()
    tl.on_round(7, _round_spans(tenant="tl-report"))
    decomp = tl.fold_for_report("tl-report", 7)
    assert decomp is not None and decomp["round_id"] == 7
    assert tl.fold_for_report("tl-report", 99) is None


def test_module_singleton_is_registered_flush_hook():
    from xaynet_tpu.telemetry.timeline import get_timeline
    from xaynet_tpu.telemetry.tracing import get_tracer

    assert get_timeline().on_round in get_tracer()._flush_hooks


# --- flight recorder: histogram deltas (satellite regression) ---------------

H_DELTA = get_registry().histogram(
    "test_timeline_delta_seconds",
    "test-only histogram for the flight-dump delta regression",
    ("tenant",),
)


def test_flight_dump_carries_histogram_sum_count_deltas(tmp_path):
    rec = recorder_mod.FlightRecorder(directory=str(tmp_path))
    H_DELTA.labels(tenant="fd").observe(1.0)
    rec.on_round(1)  # baseline AFTER the first observation
    H_DELTA.labels(tenant="fd").observe(2.5)
    path = rec.dump("test-histo-delta", "delta regression")
    assert path is not None
    bundle = json.loads(Path(path).read_text())
    deltas = bundle["metrics_delta"]
    sum_key = 'test_timeline_delta_seconds_sum{fd}'
    count_key = 'test_timeline_delta_seconds_count{fd}'
    assert deltas[sum_key] == {"before": 1.0, "now": 3.5}
    assert deltas[count_key] == {"before": 1.0, "now": 2.0}
    # per-bucket vectors stay OUT of the bundle (size discipline)
    assert not any("_bucket" in key for key in deltas)
