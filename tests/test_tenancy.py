"""Multi-tenancy: paged accumulator pool, tenant scheduler, and the
two-tenant byte-identity acceptance (docs/DESIGN.md §19).

The structural criterion of the multi-tenant coordinator: every tenant's
round is BYTE-IDENTICAL to its single-tenant control run while other
tenants — with different mask configs and model sizes — run concurrent
rounds on the same mesh, with the pool's page accounting exactly
balanced at round end (zero leaked leases).
"""

from __future__ import annotations

import asyncio
import threading
import time
from fractions import Fraction

import numpy as np
import pytest

from xaynet_tpu.core.mask.config import GroupType
from xaynet_tpu.tenancy import (
    PagePool,
    PoolExhausted,
    TenantAdmissionBudget,
    TenantScheduler,
    validate_tenant_id,
)
from xaynet_tpu.tenancy.pool import get_pool

SUM_PROB = 0.4
UPDATE_PROB = 0.5
N_SUM = 1
N_UPDATE = 3


# --------------------------------------------------------------------------
# PagePool units
# --------------------------------------------------------------------------


def test_pool_lease_release_roundtrip_and_accounting():
    pool = PagePool(page_bytes=4096, slab_pages=8)
    lease = pool.lease_host("a", (16, 64), np.uint32)
    assert lease.array.shape == (16, 64)
    assert lease.array.dtype == np.uint32
    assert not lease.array.any()  # zeroed on lease
    assert lease.pages == pool.pages_for(16 * 64 * 4)
    assert not pool.balanced("a")
    table = pool.page_table("a")
    assert table[lease.lease_id]["pages"] == lease.pages
    assert table[lease.lease_id]["arena"] == "host"
    pool.release(lease)
    pool.release(lease)  # idempotent
    assert pool.balanced("a")
    stats = pool.stats()
    assert stats["host_pages_in_use"] == 0
    assert stats["leases"] == 0


def test_pool_contiguous_reuse_and_coalescing():
    pool = PagePool(page_bytes=4096, slab_pages=8)
    a = pool.lease_host("a", (4096,), np.uint8)  # 1 page
    b = pool.lease_host("a", (4096,), np.uint8)  # 1 page
    c = pool.lease_host("a", (4096,), np.uint8)  # 1 page
    assert pool.stats()["slabs"] == 1  # all pack into one slab
    pool.release(a)
    pool.release(b)  # adjacent runs coalesce
    big = pool.lease_host("a", (2 * 4096,), np.uint8)  # needs the merged run
    assert pool.stats()["slabs"] == 1
    assert big.offset == 0  # reused the coalesced front run
    pool.release(big)
    pool.release(c)
    assert pool.balanced("a")


def test_pool_zeroes_cross_tenant_reuse():
    pool = PagePool(page_bytes=4096, slab_pages=4)
    a = pool.lease_host("a", (1024,), np.uint32)
    a.array[:] = 0xDEADBEEF  # tenant A's masked bytes
    pool.release(a)
    b = pool.lease_host("b", (1024,), np.uint32)  # same physical pages
    assert b.offset == 0 and b.slab == 0
    assert not b.array.any()  # never leaked across tenants
    pool.release(b)


def test_pool_capacity_cap_and_overflow():
    pool = PagePool(page_bytes=4096, slab_pages=4, host_pages=4)
    lease = pool.lease_host("a", (3 * 4096,), np.uint8)
    with pytest.raises(PoolExhausted):
        pool.lease_host("b", (2 * 4096,), np.uint8)
    pool.release(lease)
    ok = pool.lease_host("b", (2 * 4096,), np.uint8)  # fits after release
    pool.release(ok)


def test_pool_device_ledger_and_reclaim():
    pool = PagePool(page_bytes=4096, device_pages=8)
    d = pool.lease_device("a", 5 * 4096)
    assert d.pages == 5
    with pytest.raises(PoolExhausted):
        pool.lease_device("b", 4 * 4096)
    # a crashed round leaks the lease; reclaim force-releases and counts
    assert pool.reclaim("a") == 1
    assert pool.balanced("a")
    assert pool.reclaim("a") == 0  # healthy path reclaims nothing
    d2 = pool.lease_device("b", 4 * 4096)
    pool.release(d2)


def test_pool_grows_by_slabs_and_big_leases_get_dedicated_slabs():
    pool = PagePool(page_bytes=4096, slab_pages=2)
    small = pool.lease_host("a", (4096,), np.uint8)
    big = pool.lease_host("a", (5 * 4096,), np.uint8)  # > slab_pages
    assert pool.stats()["slabs"] == 2
    assert big.pages == 5
    pool.release(small)
    pool.release(big)
    assert pool.balanced("a")


def test_tenant_id_validation():
    assert validate_tenant_id("alpha-1") == "alpha-1"
    for bad in ("", "UPPER", "has space", "x" * 33, "-lead", "a/b"):
        with pytest.raises(ValueError):
            validate_tenant_id(bad)


# --------------------------------------------------------------------------
# TenantScheduler
# --------------------------------------------------------------------------


def _wait_for(predicate, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.005)
    return False


def test_scheduler_backpressure_bound():
    sched = TenantScheduler(max_inflight=2)
    owner = sched.new_owner()
    sched.acquire("a", owner)
    sched.acquire("a", owner)
    blocked = threading.Event()
    acquired = threading.Event()

    def third():
        blocked.set()
        sched.acquire("a", owner)
        acquired.set()

    t = threading.Thread(target=third, daemon=True)
    t.start()
    assert blocked.wait(2.0)
    time.sleep(0.05)
    assert not acquired.is_set()  # bounded: the third slot waits
    sched.release(owner)
    assert acquired.wait(2.0)
    sched.release_owner(owner)


def test_scheduler_fairness_least_served_wins():
    sched = TenantScheduler(max_inflight=1)
    owner_a = sched.new_owner()
    owner_b = sched.new_owner()
    sched.acquire("a", owner_a)  # a holds the only slot (served: a=1)
    order: list[str] = []

    def waiter(tenant, owner):
        sched.acquire(tenant, owner)
        order.append(tenant)

    # a's SECOND request arrives BEFORE b's first...
    ta = threading.Thread(target=waiter, args=("a", owner_a), daemon=True)
    ta.start()
    assert _wait_for(lambda: len(sched._waiting) == 1)
    tb = threading.Thread(target=waiter, args=("b", owner_b), daemon=True)
    tb.start()
    assert _wait_for(lambda: len(sched._waiting) == 2)
    # ...but the freed slot goes to b: fewest slots served wins over FIFO
    sched.release(owner_a)
    assert _wait_for(lambda: order == ["b"])
    sched.release(owner_b)
    assert _wait_for(lambda: order == ["b", "a"])
    sched.release(owner_a)
    split = sched.split()
    assert split["a"] == 2 and split["b"] == 1
    sched.release_owner(owner_a)
    sched.release_owner(owner_b)


def test_scheduler_release_owner_returns_held_slots():
    sched = TenantScheduler(max_inflight=2)
    owner = sched.new_owner()
    sched.acquire("a", owner)
    sched.acquire("a", owner)
    sched.release_owner(owner)  # abandoned pipeline: both slots return
    other = sched.new_owner()
    sched.acquire("b", other)  # would deadlock if slots leaked
    sched.acquire("b", other)
    sched.release_owner(other)
    sched.release_owner(owner)  # idempotent


# --------------------------------------------------------------------------
# TenantAdmissionBudget
# --------------------------------------------------------------------------


def test_admission_budget_caps_one_tenants_share():
    budget = TenantAdmissionBudget(capacity=4, max_share=0.5)
    assert budget.charge("a") and budget.charge("a")
    assert not budget.charge("a")  # over a's 50% share
    assert budget.charge("b")  # b unaffected
    budget.discharge("a", 1)
    assert budget.charge("a")  # drain restores headroom
    budget.discharge("a", 99)  # over-discharge clamps
    assert budget.held("a") == 0


# --------------------------------------------------------------------------
# Streaming pipeline page accounting
# --------------------------------------------------------------------------


def test_streaming_pipeline_leases_and_releases_pool_pages():
    from xaynet_tpu.core.mask.config import (
        BoundType, DataType, MaskConfig, ModelType,
    )
    from xaynet_tpu.parallel.aggregator import ShardedAggregator
    from xaynet_tpu.parallel.streaming import StreamingAggregator

    config = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M3)
    pool = PagePool(page_bytes=4096, slab_pages=16)
    sched = TenantScheduler(max_inflight=4)
    agg = ShardedAggregator(config.pair().vect, 64, kernel="auto")
    stream = StreamingAggregator(
        agg, staging_buffers=2, dispatch_ahead=1, max_batch=4,
        tenant="tenant-x", pool=pool, scheduler=sched,
    )
    rng = np.random.default_rng(0)
    stack = rng.integers(
        0, 2**16, size=(3, 64, agg.n_limbs), dtype=np.uint32
    )
    stream.submit_batch(stack)
    stream.drain()
    assert agg.nb_models == 3
    assert not pool.balanced("tenant-x")  # rings (+ plan) hold leases
    stream.close()
    agg.release_plan_pages()  # the unmask-tail release
    assert pool.balanced("tenant-x")  # leases == releases at round end
    assert sched.split().get("tenant-x", 0) >= 1


# --------------------------------------------------------------------------
# Two-tenant concurrent rounds: byte-identity vs single-tenant controls
# --------------------------------------------------------------------------


def _tenant_settings(model_length: int, group_type: GroupType):
    from xaynet_tpu.server.settings import (
        CountSettings,
        PhaseSettings,
        PetSettings as ServerPet,
        Settings,
        Sum2Settings,
        TimeSettings,
    )

    settings = Settings(
        pet=ServerPet(
            sum=PhaseSettings(
                prob=SUM_PROB,
                count=CountSettings(min=N_SUM, max=N_SUM),
                time=TimeSettings(min=0.0, max=60.0),
            ),
            update=PhaseSettings(
                prob=UPDATE_PROB,
                count=CountSettings(min=N_UPDATE, max=N_UPDATE),
                time=TimeSettings(min=0.0, max=60.0),
            ),
            sum2=Sum2Settings(
                count=CountSettings(min=N_SUM, max=N_SUM),
                time=TimeSettings(min=0.0, max=60.0),
            ),
        )
    )
    settings.model.length = model_length
    settings.mask.group_type = group_type
    settings.aggregation.device = True  # the pool/scheduler path
    settings.aggregation.batch_size = 2
    return settings


async def _drive_tenant_round(tenant: str, settings, seed: int) -> bytes:
    """One full in-process PET round for ``tenant`` (the oracle's driver
    shape, tenant-scoped); returns the float64 global model bytes."""
    from xaynet_tpu.sdk.client import InProcessClient
    from xaynet_tpu.sdk.simulation import keys_for_task
    from xaynet_tpu.sdk.state_machine import PetSettings, StateMachine as ParticipantSM
    from xaynet_tpu.sdk.traits import ModelStore
    from xaynet_tpu.server.services import Fetcher, PetMessageHandler
    from xaynet_tpu.server.state_machine import StateMachineInitializer
    from xaynet_tpu.storage.memory import (
        InMemoryCoordinatorStorage,
        InMemoryModelStorage,
        NoOpTrustAnchor,
    )
    from xaynet_tpu.storage.traits import Store

    class _ArrayModelStore(ModelStore):
        def __init__(self, model):
            self.model = model

        async def load_model(self):
            return self.model

    rng = np.random.default_rng(seed)
    mask_seeds = [rng.bytes(32) for _ in range(N_UPDATE)]
    weights = rng.uniform(
        -1, 1, (N_UPDATE, settings.model.length)
    ).astype(np.float32)

    store = Store(InMemoryCoordinatorStorage(), InMemoryModelStorage(), NoOpTrustAnchor())
    machine, request_tx, events = await StateMachineInitializer(
        settings, store, tenant=tenant
    ).init()
    handler = PetMessageHandler(events, request_tx)
    fetcher = Fetcher(events)
    machine_task = asyncio.create_task(machine.run())
    try:
        while fetcher.phase().value != "sum":
            await asyncio.sleep(0.01)
        round_seed = fetcher.round_params().seed.as_bytes()
        participants = []
        for i in range(N_SUM):
            keys = keys_for_task(round_seed, SUM_PROB, UPDATE_PROB, "sum", start=i * 1000)
            participants.append(
                ParticipantSM(
                    PetSettings(keys=keys),
                    InProcessClient(fetcher, handler),
                    _ArrayModelStore(None),
                )
            )
        for i in range(N_UPDATE):
            keys = keys_for_task(
                round_seed, SUM_PROB, UPDATE_PROB, "update", start=(10 + i) * 1000
            )
            participants.append(
                ParticipantSM(
                    PetSettings(
                        keys=keys,
                        scalar=Fraction(1, N_UPDATE),
                        mask_seed=mask_seeds[i],
                    ),
                    InProcessClient(fetcher, handler),
                    _ArrayModelStore(weights[i]),
                )
            )

        async def drive(sm):
            for _ in range(2000):
                try:
                    await sm.transition()
                except asyncio.CancelledError:
                    raise
                except Exception:
                    pass
                if fetcher.model() is not None and sm.phase.value == "awaiting":
                    return
                await asyncio.sleep(0.01)

        await asyncio.gather(*(drive(p) for p in participants))
        while fetcher.model() is None:
            await asyncio.sleep(0.01)
        return np.asarray(fetcher.model(), dtype=np.float64).tobytes()
    finally:
        machine_task.cancel()
        try:
            await machine_task
        except (asyncio.CancelledError, Exception):  # lint: swallow-ok (teardown)
            pass


_TENANT_CASES = {
    # different mask configs AND model sizes on the one mesh
    "alpha": (37, GroupType.INTEGER, 11),
    "beta": (64, GroupType.PRIME, 22),
}


def _control(tenant: str) -> bytes:
    length, group, seed = _TENANT_CASES[tenant]
    return asyncio.run(
        asyncio.wait_for(
            _drive_tenant_round(tenant, _tenant_settings(length, group), seed),
            timeout=180.0,
        )
    )


@pytest.mark.filterwarnings("ignore::DeprecationWarning")
def test_two_tenants_concurrent_rounds_byte_identical_to_controls():
    controls = {t: _control(t) for t in _TENANT_CASES}

    async def concurrent():
        return await asyncio.gather(
            *(
                _drive_tenant_round(
                    t, _tenant_settings(c[0], c[1]), c[2]
                )
                for t, c in _TENANT_CASES.items()
            )
        )

    results = asyncio.run(asyncio.wait_for(concurrent(), timeout=300.0))
    for (tenant, _case), model in zip(_TENANT_CASES.items(), results):
        assert model == controls[tenant], (
            f"tenant {tenant} diverged from its single-tenant control"
        )
    # pool page accounting exactly balanced: zero leaked leases per tenant
    pool = get_pool()
    for tenant in _TENANT_CASES:
        assert pool.balanced(tenant), (
            f"tenant {tenant} leaked pool leases: {pool.page_table(tenant)}"
        )


# --------------------------------------------------------------------------
# Tenant-scoped durable storage: the round checkpoint never crosses tenants
# --------------------------------------------------------------------------


def test_round_checkpoint_storage_is_tenant_scoped(tmp_path):
    """Regression for the elastic-lifecycle PR: the PR-4 mid-round
    checkpoint must live under the tenant's scoped key space (file backends
    get a ``t-<tenant>`` subtree, redis a ``t:<tenant>:`` prefix), so a
    tenant's kill-and-restore can never resume into ANOTHER tenant's
    round — the resume entry point for tenant B sees no checkpoint at all
    when only tenant A saved one."""
    from xaynet_tpu.resilience import checkpoint as ckpt_mod
    from xaynet_tpu.server.runner import init_store

    async def run():
        settings = _tenant_settings(32, GroupType.INTEGER)
        settings.storage.coordinator = "file"
        settings.storage.model_dir = str(tmp_path)
        store_a = init_store(settings, "alpha")
        store_b = init_store(settings, "beta")
        blob = b"alpha mid-update aggregate"
        await store_a.coordinator.set_round_checkpoint(blob)
        # tenant A round-trips its own checkpoint; tenant B's restart sees
        # nothing to resume — checkpoint.load degrades it to a round restart
        assert await store_a.coordinator.round_checkpoint() == blob
        assert await store_b.coordinator.round_checkpoint() is None
        assert await ckpt_mod.load(store_b) is None
        # on disk the blob lives only under alpha's t- subtree
        holders = {
            p.relative_to(tmp_path).parts[0]
            for p in tmp_path.rglob("*")
            if p.is_file() and p.read_bytes() == blob
        }
        assert holders == {"t-alpha"}
        # deletion is scoped the same way
        await store_a.coordinator.delete_round_checkpoint()
        assert await store_a.coordinator.round_checkpoint() is None

    asyncio.run(run())
