"""Limb arithmetic vs python big-int oracle."""

import random

import numpy as np
import pytest

from xaynet_tpu.ops import limbs as limb_ops

ORDERS = [
    20_000_000_000_001,
    2**45,
    2**96,
    200_000_000_000_000_000_000_000_000_017,  # Prime F64 B6 M3
    (2**128 - 159),  # arbitrary large modulus
]


@pytest.mark.parametrize("order", ORDERS)
def test_roundtrip_ints(order):
    rng = random.Random(42)
    values = [rng.randrange(order) for _ in range(64)]
    n_limb = limb_ops.n_limbs_for_order(order)
    arr = limb_ops.ints_to_limbs(values, n_limb)
    assert limb_ops.limbs_to_ints(arr) == values


@pytest.mark.parametrize("order", ORDERS)
def test_bytes_roundtrip(order):
    rng = random.Random(1)
    values = [rng.randrange(order) for _ in range(32)]
    bpn = ((order - 1).bit_length() + 7) // 8
    n_limb = limb_ops.n_limbs_for_order(order)
    arr = limb_ops.ints_to_limbs(values, n_limb)
    wire = limb_ops.limbs_to_bytes_le(arr, bpn)
    assert wire == b"".join(v.to_bytes(bpn, "little") for v in values)
    back = limb_ops.bytes_le_to_limbs(wire, 32, bpn)
    assert limb_ops.limbs_to_ints(back) == values


@pytest.mark.parametrize("order", ORDERS)
def test_mod_add_sub(order):
    rng = random.Random(7)
    a = [rng.randrange(order) for _ in range(128)]
    b = [rng.randrange(order) for _ in range(128)]
    n_limb = limb_ops.n_limbs_for_order(order)
    ol = limb_ops.order_limbs_for(order)
    aa = limb_ops.ints_to_limbs(a, n_limb)
    bb = limb_ops.ints_to_limbs(b, n_limb)

    s = limb_ops.mod_add(aa, bb, ol)
    assert limb_ops.limbs_to_ints(s) == [(x + y) % order for x, y in zip(a, b)]

    d = limb_ops.mod_sub(aa, bb, ol)
    assert limb_ops.limbs_to_ints(d) == [(x - y) % order for x, y in zip(a, b)]


@pytest.mark.parametrize("order", ORDERS)
@pytest.mark.parametrize("k", [1, 2, 3, 8, 17])
def test_batch_mod_sum(order, k):
    rng = random.Random(k)
    n_limb = limb_ops.n_limbs_for_order(order)
    ol = limb_ops.order_limbs_for(order)
    rows = [[rng.randrange(order) for _ in range(16)] for _ in range(k)]
    stack = np.stack([limb_ops.ints_to_limbs(r, n_limb) for r in rows])
    got = limb_ops.limbs_to_ints(limb_ops.batch_mod_sum(stack, ol))
    want = [sum(rows[i][j] for i in range(k)) % order for j in range(16)]
    assert got == want


def test_edge_values():
    order = 2**64 - 59
    n_limb = limb_ops.n_limbs_for_order(order)
    ol = limb_ops.order_limbs_for(order)
    a = limb_ops.ints_to_limbs([order - 1, 0, order - 1], n_limb)
    b = limb_ops.ints_to_limbs([order - 1, 0, 1], n_limb)
    assert limb_ops.limbs_to_ints(limb_ops.mod_add(a, b, ol)) == [order - 2, 0, 0]
    assert limb_ops.limbs_to_ints(limb_ops.mod_sub(b, a, ol)) == [0, 0, 2 % order]


def test_fold_planar_batch_host_matches_bigint_oracle():
    """Native single-pass u64 fold == python big-int result (1 and 2 limb
    orders, prime / integer / power2-boundary, elements at order-1)."""
    import numpy as np

    from xaynet_tpu.ops import limbs as L

    cases = [
        (2**48 - 59, 9),          # prime-ish, 2 limbs
        ((1 << 45) * 10**3, 16),  # integer-style composite, 2 limbs
        (1 << 64, 5),             # power2 boundary: natural u64 wrap
        (1 << 32, 7),             # power2 boundary: one limb
        (2**31 - 1, 12),          # one limb, odd order
    ]
    rng = np.random.default_rng(3)
    for order, k in cases:
        nl = L.n_limbs_for_order(order)
        ol = L.order_limbs_for(order)
        n = 257
        vals = [[int(rng.integers(0, min(order, 2**63))) % order for _ in range(n)]]
        vals += [[order - 1] * n]  # a row of maximal elements
        vals += [[int(rng.integers(0, min(order, 2**63))) % order for _ in range(n)]
                 for _ in range(k - 1)]
        acc_planar = np.ascontiguousarray(L.ints_to_limbs(vals[0], nl).T)
        stack_planar = np.stack([np.ascontiguousarray(L.ints_to_limbs(v, nl).T) for v in vals[1:]])
        out = L.fold_planar_batch_host(acc_planar, stack_planar, ol)
        want = [sum(v[i] for v in vals) % order for i in range(n)]
        got = [L.limbs_to_int(np.ascontiguousarray(out[:, i])) for i in range(n)]
        assert got == want, (order, k)

        # wire-layout variant agrees (or declines when unsupported)
        acc_wire = np.ascontiguousarray(acc_planar.T)
        stack_wire = np.ascontiguousarray(stack_planar.transpose(0, 2, 1))
        wire_out = L.fold_wire_batch_host(acc_wire, stack_wire, ol)
        if wire_out is not None:
            got_w = [L.limbs_to_int(wire_out[i]) for i in range(n)]
            assert got_w == want, (order, k, "wire")


def test_fold_host_oversized_batch_uses_generic_kernel():
    """(K+1) * order over the u64 bound routes to the generic n-limb
    kernel (round 3) and stays exact."""
    import numpy as np

    from xaynet_tpu.ops import limbs as L

    order = 1 << 62
    nl, ol = L.n_limbs_for_order(order), L.order_limbs_for(order)
    n, k = 33, 8  # (8+1) * 2^62 > 2^64 -> no u64 fast path
    rng = np.random.default_rng(4)
    vals = [[int(rng.integers(0, 2**62)) for _ in range(n)] for _ in range(k + 1)]
    acc = np.ascontiguousarray(L.ints_to_limbs(vals[0], nl).T)
    stack = np.stack([np.ascontiguousarray(L.ints_to_limbs(v, nl).T) for v in vals[1:]])
    out = L.fold_planar_batch_host(acc, stack, ol)
    want = [sum(v[i] for v in vals) % order for i in range(n)]
    got = [L.limbs_to_int(np.ascontiguousarray(out[:, i])) for i in range(n)]
    assert got == want
    wire_out = L.fold_wire_batch_host(
        np.ascontiguousarray(acc.T), np.ascontiguousarray(stack.transpose(0, 2, 1)), ol
    )
    if wire_out is not None:  # native present: the generic kernel must agree
        assert L.limbs_to_ints(wire_out) == want


def test_fold_host_nlimb_matches_bigint_oracle():
    """Generic n-limb single-pass fold: exact vs the big-int oracle across
    multi-limb orders (f64 families through a Bmax-scale 1384-bit order),
    batch sizes, and the pow2-boundary wraparound case."""
    import numpy as np

    from xaynet_tpu.ops import limbs as L
    from xaynet_tpu.utils import native

    if native.load() is None:
        import pytest

        pytest.skip("native library unavailable")
    rng = np.random.default_rng(11)
    orders = [2**65 + 7, 2**96, 2**96 - 17, 2**127 - 1, (1 << 192) - 237,
              (1 << 1384) - 1234567]
    for order in orders:
        nl, ol = L.n_limbs_for_order(order), L.order_limbs_for(order)
        for k in (1, 8, 31):
            n = 17

            def big():
                b = 0
                for _ in range(nl):
                    b = (b << 32) | int(rng.integers(0, 2**32))
                return b % order

            vals = [[big() for _ in range(n)] for _ in range(k + 1)]
            acc = L.ints_to_limbs(vals[0], nl)
            stack = np.stack([L.ints_to_limbs(v, nl) for v in vals[1:]])
            out = L.fold_wire_batch_host(acc, stack, ol)
            assert out is not None, (order.bit_length(), k)
            want = [sum(v[i] for v in vals) % order for i in range(n)]
            assert L.limbs_to_ints(out) == want, (order.bit_length(), k)


def test_wire_codec_native_matches_numpy_oracle():
    """Native wire<->limb codecs: exact vs the numpy pad/slice path across
    the wire-width grid (incl. the bytewise tail element and the 173-byte
    f64/Bmax worst case), plus serialize round-trip."""
    import numpy as np

    from xaynet_tpu.ops import limbs as L

    rng = np.random.default_rng(7)
    for bpn in [1, 2, 3, 4, 5, 6, 7, 8, 9, 12, 16, 21, 173]:
        n_limb = max(1, (bpn + 3) // 4)
        for count in (1, 2, 57):  # count=1 exercises the tail-only path
            buf = rng.integers(0, 256, size=count * bpn, dtype=np.uint8).tobytes()
            got = L.bytes_le_to_limbs(buf, count, bpn)
            raw = np.frombuffer(buf, dtype=np.uint8, count=count * bpn)
            padded = np.zeros((count, n_limb * 4), dtype=np.uint8)
            padded[:, :bpn] = raw.reshape(count, bpn)
            want = padded.view("<u4")
            assert np.array_equal(got, want), (bpn, count)
            assert L.limbs_to_bytes_le(got, bpn) == buf, (bpn, count)


def test_all_lt_order_matches_elementwise():
    """Scalar validity count == np.all over the per-element compare, incl.
    the 2^(32L) boundary orders and exact order-1/order edge values."""
    import numpy as np

    from xaynet_tpu.ops import limbs as L

    rng = np.random.default_rng(8)
    for order in [251, 2**20 + 7, 2**32, 2**52 - 47, 2**64 - 59, 2**64, 2**96]:
        nl = L.n_limbs_for_order(order)
        data = rng.integers(0, 2**32, size=(500, nl), dtype=np.uint32)
        assert L.all_lt_order(data, order) == bool(
            np.all(L.elements_lt_order(data, order))
        ), order
        ok = L.ints_to_limbs([0, order // 2, order - 1], nl)
        assert L.all_lt_order(ok, order) is True, order
        if order != 1 << (32 * nl):
            mixed = np.vstack([ok, L.ints_to_limbs([order], nl)])
            assert L.all_lt_order(mixed, order) is False, order
