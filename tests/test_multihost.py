"""Multi-host sharded ingest: real 2-process jax.distributed CPU test.

Two worker processes each own 4 virtual CPU devices of one 8-device global
mesh, stage only their slice of every update batch, run the SPMD fold, and
verify their slice of the unmasked aggregate against the host oracle —
the sharded-ingest design of docs/DESIGN.md §3 executed for real (VERDICT
round-1 item 9).
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_sharded_ingest(n_procs: int, devs_per_proc: int, timeout: float = 240):
    port = _free_port()
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "JAX_NUM_PROCESSES")
    }
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(port), str(i), str(n_procs), str(devs_per_proc)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for i in range(n_procs)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multi-host workers timed out:\n" + "\n---\n".join(outs))
    # jax CPU backends (<= 0.4.x) cannot run multiprocess collectives at
    # all — the workers die with this exact capability error before any
    # assertion of OURS can run. Skip (not fail): the test is about the
    # sharded-ingest protocol, which needs a backend that has the feature.
    unsupported = "Multiprocess computations aren't implemented on the CPU backend"
    if any(p.returncode != 0 and unsupported in out for p, out in zip(procs, outs)):
        pytest.skip(f"jax backend capability missing: {unsupported}")
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} rc={p.returncode}\n{out[-3000:]}"
        assert f"WORKER {i} OK" in out, out[-3000:]


def test_two_process_sharded_ingest():
    _run_sharded_ingest(2, 4)


@pytest.mark.skipif(
    not os.environ.get("XAYNET_STRESS"),
    reason="4 concurrent jax processes; run with XAYNET_STRESS=1",
)
def test_four_process_sharded_ingest():
    """Pod-scale shape: 4 hosts x 2 devices over the same 8-device mesh
    (roadmap item 'multi-host beyond 2 processes')."""
    _run_sharded_ingest(4, 2, timeout=480)


def test_single_process_multihost_aggregator_matches_oracle():
    """The same MultiHostAggregator API on a single process (full slice)."""
    from xaynet_tpu.core.mask.config import (
        BoundType,
        DataType,
        GroupType,
        MaskConfig,
        ModelType,
    )
    from xaynet_tpu.ops import limbs as host_limbs
    from xaynet_tpu.parallel.multihost import MultiHostAggregator

    config = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M6)
    n_limb = host_limbs.n_limbs_for_order(config.order)
    ol = host_limbs.order_limbs_for(config.order)
    rng = np.random.default_rng(9)
    model_len, k = 333, 4
    top = int(config.order >> 32)
    wire = rng.integers(0, 1 << 32, size=(k, model_len, n_limb), dtype=np.uint32)
    wire[:, :, n_limb - 1] = rng.integers(0, top, size=(k, model_len), dtype=np.uint32)
    mask = rng.integers(0, 1 << 32, size=(model_len, n_limb), dtype=np.uint32)
    mask[:, n_limb - 1] = rng.integers(0, top, size=model_len, dtype=np.uint32)

    agg = MultiHostAggregator(config, model_len)
    lo, hi = agg.local_slice
    assert (lo, hi) == (0, model_len)
    agg.add_local_batch(wire)
    out = agg.unmask_local(mask)

    expected = host_limbs.mod_sub(host_limbs.batch_mod_sum(wire, ol), mask, ol)
    assert np.array_equal(out, expected)
    assert np.array_equal(agg.snapshot_local(), host_limbs.batch_mod_sum(wire, ol))
