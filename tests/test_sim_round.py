"""xaynet_tpu/sim: the in-graph federated round program.

Golden-vector coverage pins the batched/vmap-compatible ops entry points
(in-graph ChaCha rejection sampling, cursor handoff, batched mask
derivation, population encode) byte-identical to the scalar
``core/mask/*`` reference path; the round-level tests pin ``SimRound``
byte-identical to the production host aggregation
(``Masker``/``Aggregation``/``unmask_array``) across block shapes, fused
and re-derived sum-mask phases, and the multi-device mesh.
"""

from __future__ import annotations

from fractions import Fraction

import jax.numpy as jnp
import numpy as np
import pytest

from xaynet_tpu.core.crypto.prng import StreamSampler
from xaynet_tpu.core.mask.config import (
    BoundType,
    DataType,
    GroupType,
    MaskConfig,
    ModelType,
)
from xaynet_tpu.core.mask.encode import clamp_scalar, encode_unit, encode_vect_limbs
from xaynet_tpu.core.mask.masking import Aggregation, Masker
from xaynet_tpu.core.mask.model import Scalar
from xaynet_tpu.core.mask.seed import MaskSeed
from xaynet_tpu.ops import chacha_jax, limbs as host_limbs
from xaynet_tpu.ops.masking_jax import (
    derive_mask_limbs_batch,
    encode_models_batch,
    seed_words,
)
from xaynet_tpu.parallel.mesh import make_mesh
from xaynet_tpu.sim import SimRound, SimSpec, seeds_for

CFG_INT = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M3).pair()

GROUPS = [GroupType.INTEGER, GroupType.PRIME, GroupType.POWER2]


def _pair(group_type) -> "MaskConfig":
    return MaskConfig(group_type, DataType.F32, BoundType.B0, ModelType.M3).pair()


def _host_reference(cfg_pair, seeds, weights, scalar):
    """The production host path: mask every model, aggregate, reconstruct
    the sum mask, unmask — the function the sim must reproduce exactly."""
    p, n = weights.shape
    model_agg = Aggregation(cfg_pair, n)
    mask_agg = Aggregation(cfg_pair, n)
    for i in range(p):
        masker = Masker(cfg_pair, seed=MaskSeed(seeds[i]))
        seed, masked = masker.mask(Scalar.from_fraction(scalar), weights[i])
        model_agg.validate_aggregation(masked)
        model_agg.aggregate(masked)
        mask = seed.derive_mask(n, cfg_pair)
        mask_agg.validate_aggregation(mask)
        mask_agg.aggregate(mask)
    return np.asarray(model_agg.unmask_array(mask_agg.object), dtype=np.float64)


# --- golden vectors: ops entry points vs the scalar reference ---------------


def test_rolled_keystream_is_bit_identical_to_unrolled():
    kw = jnp.asarray(np.frombuffer(np.random.default_rng(1).bytes(32), "<u4"))
    a = chacha_jax.keystream_words(kw, jnp.uint32(7), 19)
    b = chacha_jax.keystream_words_rolled(kw, jnp.uint32(7), 19)
    assert np.array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("group_type", GROUPS)
def test_ingraph_derivation_matches_stream_sampler_with_cursor_handoff(group_type):
    """The in-graph unit draw, its byte-cursor handoff, and the vector
    draw from that cursor — all bit-identical to the host sampler, with
    deliberately tiny chunks so the while_loop runs multiple trips."""
    import jax

    cfg = _pair(group_type)
    order_u, order_v = cfg.unit.order, cfg.vect.order
    rng = np.random.default_rng(11)
    seeds = [rng.bytes(32) for _ in range(3)]
    count = 29

    for seed in seeds:
        smp = StreamSampler(seed)
        ref_unit = smp.draw_limbs(1, order_u)[0]
        ref_off = smp.consumed_bytes
        ref_vect = smp.draw_limbs(count, order_v)

        kw = jnp.asarray(np.frombuffer(seed, "<u4"))
        unit, off = jax.jit(
            lambda k: chacha_jax.derive_uniform_limbs_ingraph(k, jnp.int32(0), 1, order_u, 8)
        )(kw)
        assert np.array_equal(np.asarray(unit)[0], ref_unit)
        assert int(off) == ref_off
        vect, _ = jax.jit(
            lambda k, o: chacha_jax.derive_uniform_limbs_ingraph(k, o, count, order_v, 16)
        )(kw, off)
        assert np.array_equal(np.asarray(vect), ref_vect)


@pytest.mark.parametrize("group_type", GROUPS)
def test_batched_mask_derivation_golden(group_type):
    """derive_mask_limbs_batch rows == MaskSeed.derive_mask, byte for byte."""
    cfg = _pair(group_type)
    rng = np.random.default_rng(5)
    seeds = [rng.bytes(32) for _ in range(5)]
    n = 41
    units, vects = derive_mask_limbs_batch(seeds, n, cfg)
    units, vects = np.asarray(units), np.asarray(vects)
    for i, s in enumerate(seeds):
        ref = MaskSeed(s).derive_mask(n, cfg)
        assert np.array_equal(units[i], ref.unit.data), f"unit row {i}"
        assert np.array_equal(vects[i], ref.vect.data), f"vect row {i}"


def test_encode_models_batch_golden():
    """Population encode rows == the per-participant production encode."""
    cfg = CFG_INT
    rng = np.random.default_rng(6)
    weights = rng.uniform(-1, 1, (4, 23)).astype(np.float32)
    scalar = Fraction(1, 4)
    unit, vect = encode_models_batch(weights, scalar, cfg)
    s_clamped = clamp_scalar(scalar, cfg.unit)
    for i in range(4):
        ref = encode_vect_limbs(weights[i], s_clamped, cfg.vect)
        assert np.array_equal(vect[i], ref), f"row {i}"
    ref_unit_int = encode_unit(s_clamped, cfg.unit)
    n_limb_u = host_limbs.n_limbs_for_order(cfg.unit.order)
    assert np.array_equal(unit, host_limbs.int_to_limbs(ref_unit_int, n_limb_u))
    with pytest.raises(ValueError):
        encode_models_batch(weights[0], scalar, cfg)  # 1-D input


def test_seed_words_roundtrip():
    rng = np.random.default_rng(7)
    seeds = [rng.bytes(32) for _ in range(3)]
    words = seed_words(seeds)
    assert words.shape == (3, 8) and words.dtype == np.uint32
    for i, s in enumerate(seeds):
        assert words[i].tobytes() == s


# --- the whole-round program vs the production host path --------------------


@pytest.mark.parametrize("group_type", GROUPS)
def test_sim_round_byte_identical_to_host_aggregation(group_type):
    cfg = _pair(group_type)
    p, n = 5, 33
    rng = np.random.default_rng(20)
    seeds = [rng.bytes(32) for _ in range(p)]
    weights = rng.uniform(-1, 1, (p, n)).astype(np.float32)
    scalar = Fraction(1, p)
    ref = _host_reference(cfg, seeds, weights, scalar)

    sim = SimRound(SimSpec(cfg, n, block_size=4))  # p=5 pads the last block
    res = sim.run(seeds, weights, scalar=scalar)
    assert res.global_model.tobytes() == ref.tobytes()
    assert res.nb_models == p
    assert sim.program_calls == 1


def test_sim_round_block_shapes_and_rederived_sum_mask_agree():
    """Block size never changes the bytes, and re-deriving the sum mask in
    a standalone phase (fuse_mask_sum=False) matches the fused fold."""
    cfg = CFG_INT
    p, n = 7, 19
    rng = np.random.default_rng(21)
    seeds = [rng.bytes(32) for _ in range(p)]
    weights = rng.uniform(-1, 1, (p, n)).astype(np.float32)
    scalar = Fraction(1, p)
    ref = _host_reference(cfg, seeds, weights, scalar)

    for spec in (
        SimSpec(cfg, n, block_size=7),
        SimSpec(cfg, n, block_size=3),
        SimSpec(cfg, n, block_size=4, fuse_mask_sum=False),
    ):
        res = SimRound(spec).run(seeds, weights, scalar=scalar)
        assert res.global_model.tobytes() == ref.tobytes(), spec


def test_sim_round_mesh_sharded_byte_identical():
    """The participant-axis mesh shard produces the same bytes as the
    single-device program (modular partial sums commute)."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs the multi-device virtual mesh")
    cfg = CFG_INT
    p, n = 9, 27
    rng = np.random.default_rng(22)
    seeds = [rng.bytes(32) for _ in range(p)]
    weights = rng.uniform(-1, 1, (p, n)).astype(np.float32)
    scalar = Fraction(1, p)

    single = SimRound(SimSpec(cfg, n, block_size=4)).run(seeds, weights, scalar=scalar)
    sim = SimRound(SimSpec(cfg, n, block_size=2), mesh=make_mesh())
    meshed = sim.run(seeds, weights, scalar=scalar)
    assert meshed.global_model.tobytes() == single.global_model.tobytes()
    assert sim.program_calls == 1


def test_sim_round_internals_expose_consistent_aggregates():
    """return_internals surfaces the pre-unmask sums; masked - mask must
    equal the returned unmasked model (in the group)."""
    cfg = CFG_INT
    p, n = 4, 11
    rng = np.random.default_rng(23)
    seeds = [rng.bytes(32) for _ in range(p)]
    weights = rng.uniform(-1, 1, (p, n)).astype(np.float32)
    res = SimRound(SimSpec(cfg, n, block_size=4, return_internals=True)).run(
        seeds, weights, scalar=Fraction(1, p)
    )
    assert res.internals is not None
    ol = host_limbs.order_limbs_for(cfg.vect.order)
    recon = host_limbs.mod_sub(
        res.internals["masked_vect_sum"], res.internals["mask_vect_sum"], ol
    )
    assert np.array_equal(recon, res.model_vect_limbs)


def test_sim_round_thousand_participants_single_program_call():
    """Scale smoke (the DrJAX workload shape): >=1k participants in ONE
    program invocation, global model equal to the quantized mean."""
    cfg = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M6).pair()
    p, n = 1024, 64
    sim = SimRound(SimSpec(cfg, n, block_size=128))
    seeds = seeds_for(p, root=3)
    rng = np.random.default_rng(4)
    weights = rng.uniform(-1, 1, (p, n)).astype(np.float32)
    res = sim.run(seeds, weights, scalar=Fraction(1, p))
    assert sim.program_calls == 1
    assert res.nb_models == p
    # fixed-point quantization: each update adds <= 1/exp_shift encode
    # error, so the mean carries ~P/E before the 1/P scalar — bound 1e-6
    expected = weights.astype(np.float64).mean(axis=0)
    np.testing.assert_allclose(res.global_model, expected, atol=1e-6)


def test_sim_round_input_validation():
    cfg = CFG_INT
    sim = SimRound(SimSpec(cfg, 8, block_size=4))
    seeds = seeds_for(3)
    weights = np.zeros((3, 8), np.float32)
    with pytest.raises(ValueError, match="weights"):
        sim.run(seeds, np.zeros((3, 9), np.float32))
    with pytest.raises(ValueError, match="participant"):
        sim.run([], np.zeros((0, 8), np.float32))
    with pytest.raises(ValueError, match="seeds"):
        sim.run(np.zeros((3, 4), np.uint32), weights)
    with pytest.raises(ValueError, match="TooManyModels"):
        # M3 caps at 10^3 models
        big = 1001
        SimRound(SimSpec(cfg, 8, block_size=512)).run(
            seeds_for(big), np.zeros((big, 8), np.float32)
        )
    with pytest.raises(ValueError):
        SimSpec(cfg, 0)
    with pytest.raises(ValueError):
        SimSpec(cfg, 8, block_size=0)


@pytest.mark.slow  # sweep over bigger populations x mesh; minutes on CPU
def test_sim_round_scale_sweep_byte_identity():
    """Larger-population sweep: single-device vs mesh vs odd blocks stay
    byte-identical on a 4k-element model."""
    cfg = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M6).pair()
    p, n = 512, 4096
    seeds = seeds_for(p, root=9)
    rng = np.random.default_rng(10)
    weights = rng.uniform(-1, 1, (p, n)).astype(np.float32)
    scalar = Fraction(1, p)
    base = SimRound(SimSpec(cfg, n, block_size=64)).run(seeds, weights, scalar=scalar)
    alt = SimRound(SimSpec(cfg, n, block_size=96)).run(seeds, weights, scalar=scalar)
    assert alt.global_model.tobytes() == base.global_model.tobytes()
    import jax

    if len(jax.devices()) > 1:
        meshed = SimRound(SimSpec(cfg, n, block_size=64), mesh=make_mesh()).run(
            seeds, weights, scalar=scalar
        )
        assert meshed.global_model.tobytes() == base.global_model.tobytes()
