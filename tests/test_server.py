"""Coordinator phase tests: event snapshots, timeout, failure, restore.

Mirrors the reference's phase-test strategy (SURVEY §4.3): drive transitions
one at a time, assert which events changed, exercise the failure/timeout
paths and the checkpoint/restore cycle.
"""

import asyncio

import numpy as np
import pytest

from xaynet_tpu.core.crypto.encrypt import PublicEncryptKey
from xaynet_tpu.core.message import Message, Sum
from xaynet_tpu.server.services import Fetcher, PetMessageHandler, ServiceError
from xaynet_tpu.server.requests import RequestError
from xaynet_tpu.server.settings import (
    CountSettings,
    PhaseSettings,
    PetSettings,
    Settings,
    SettingsError,
    Sum2Settings,
    TimeSettings,
)
from xaynet_tpu.server.state_machine import StateMachineInitializer
from xaynet_tpu.sdk.simulation import keys_for_task
from xaynet_tpu.storage.memory import (
    InMemoryCoordinatorStorage,
    InMemoryModelStorage,
    NoOpTrustAnchor,
)
from xaynet_tpu.storage.traits import Store


def _settings(sum_max_time=0.3):
    s = Settings(
        pet=PetSettings(
            sum=PhaseSettings(prob=0.5, count=CountSettings(1, 2), time=TimeSettings(0, sum_max_time)),
            update=PhaseSettings(prob=0.4, count=CountSettings(3, 5), time=TimeSettings(0, 0.3)),
            sum2=Sum2Settings(count=CountSettings(1, 2), time=TimeSettings(0, 0.3)),
        )
    )
    s.model.length = 4
    return s


def _store():
    return Store(InMemoryCoordinatorStorage(), InMemoryModelStorage(), NoOpTrustAnchor())


def test_idle_phase_bootstraps_round():
    async def run():
        store = _store()
        machine, _, events = await StateMachineInitializer(_settings(), store).init()
        params_before = events.params.get_latest()
        assert params_before.round_id == 0

        assert await machine.next()  # runs Idle -> Sum
        assert machine.phase.NAME.value == "sum"

        params = events.params.get_latest()
        assert params.round_id == 1
        assert params.event.seed.as_bytes() != params_before.event.seed.as_bytes()
        keys = events.keys.get_latest()
        assert keys.round_id == 1
        assert keys.event.public.as_bytes() == params.event.pk
        # state persisted
        assert await store.coordinator.coordinator_state() is not None

    asyncio.run(run())


def test_sum_timeout_routes_to_failure_then_idle():
    async def run():
        store = _store()
        machine, _, events = await StateMachineInitializer(_settings(0.2), store).init()
        assert await machine.next()  # Idle -> Sum
        assert await machine.next()  # Sum times out -> Failure
        assert machine.phase.NAME.value == "failure"
        assert await machine.next()  # Failure -> Idle (round restart)
        assert machine.phase.NAME.value == "idle"
        assert await machine.next()  # Idle -> Sum of round 2
        assert events.params.get_latest().round_id == 2

    asyncio.run(run())


def test_phase_filter_drops_wrong_tag():
    async def run():
        store = _store()
        machine, tx, events = await StateMachineInitializer(_settings(5.0), store).init()
        handler = PetMessageHandler(events, tx)
        machine_task = asyncio.create_task(machine.run())
        try:
            while events.phase.get_latest().event.value != "sum":
                await asyncio.sleep(0.01)
            params = events.params.get_latest().event
            # craft an *update*-task participant but send a Sum message —
            # phase filter passes (tag matches) but eligibility fails
            keys = keys_for_task(params.seed.as_bytes(), params.sum, params.update, "update")
            payload = Sum(
                sum_signature=keys.sign(params.seed.as_bytes() + b"sum").as_bytes(),
                ephm_pk=b"\x01" * 32,
            )
            msg = Message(participant_pk=keys.public, coordinator_pk=params.pk, payload=payload)
            encrypted = PublicEncryptKey(params.pk).encrypt(msg.to_bytes(keys.secret))
            with pytest.raises(ServiceError):
                await handler.handle_message(encrypted)
            # garbage bytes are dropped at the decrypt stage
            with pytest.raises(ServiceError):
                await handler.handle_message(b"\x00" * 200)
        finally:
            machine_task.cancel()
            try:
                await machine_task
            except (asyncio.CancelledError, Exception):
                pass

    asyncio.run(run())


def test_duplicate_sum_rejected():
    async def run():
        store = _store()
        settings = _settings(5.0)
        settings.pet.sum.count = CountSettings(2, 2)  # keep the phase open
        machine, tx, events = await StateMachineInitializer(settings, store).init()
        handler = PetMessageHandler(events, tx)
        machine_task = asyncio.create_task(machine.run())
        try:
            while events.phase.get_latest().event.value != "sum":
                await asyncio.sleep(0.01)
            params = events.params.get_latest().event
            keys = keys_for_task(params.seed.as_bytes(), params.sum, params.update, "sum")
            payload = Sum(
                sum_signature=keys.sign(params.seed.as_bytes() + b"sum").as_bytes(),
                ephm_pk=b"\x02" * 32,
            )
            msg = Message(participant_pk=keys.public, coordinator_pk=params.pk, payload=payload)
            wire = msg.to_bytes(keys.secret)
            await handler.handle_message(PublicEncryptKey(params.pk).encrypt(wire))
            with pytest.raises(RequestError):
                await handler.handle_message(PublicEncryptKey(params.pk).encrypt(wire))
        finally:
            machine_task.cancel()
            try:
                await machine_task
            except (asyncio.CancelledError, Exception):
                pass

    asyncio.run(run())


def test_checkpoint_restore_resumes_round_and_model():
    async def run():
        store = _store()
        settings = _settings()
        machine, _, events = await StateMachineInitializer(settings, store).init()
        assert await machine.next()  # Idle -> Sum: persists state at round 1

        # simulate a completed round having stored a global model
        model = np.arange(4, dtype=np.float64)
        seed = events.params.get_latest().event.seed.as_bytes()
        model_id = await store.models.set_global_model(1, seed, model.tobytes())
        await store.coordinator.set_latest_global_model_id(model_id)

        # "crash" and restore
        settings2 = _settings()
        settings2.restore.enable = True
        machine2, _, events2 = await StateMachineInitializer(settings2, store).init()
        assert events2.params.get_latest().round_id == 1
        restored = events2.model.get_latest().event.model
        assert restored is not None
        np.testing.assert_array_equal(np.asarray(restored), model)

        # restart continues with round 2
        assert await machine2.next()
        assert events2.params.get_latest().round_id == 2

    asyncio.run(run())


def test_restore_fails_on_dangling_model_id():
    async def run():
        from xaynet_tpu.server.state_machine import RestoreError

        store = _store()
        machine, _, _ = await StateMachineInitializer(_settings(), store).init()
        assert await machine.next()
        await store.coordinator.set_latest_global_model_id("1_deadbeef")

        settings2 = _settings()
        settings2.restore.enable = True
        with pytest.raises(RestoreError):
            await StateMachineInitializer(settings2, store).init()

    asyncio.run(run())


def test_settings_validation_and_env_overrides(tmp_path, monkeypatch):
    cfg = tmp_path / "config.toml"
    cfg.write_text(
        """
[pet.sum]
prob = 0.02
[pet.sum.count]
min = 5
max = 10
[model]
length = 42
[mask]
group_type = "integer"
bound_type = "b2"
"""
    )
    monkeypatch.setenv("XAYNET__MODEL__LENGTH", "99")
    monkeypatch.setenv("XAYNET__PET__SUM__PROB", "0.5")
    s = Settings.load(str(cfg))
    assert s.model.length == 99
    assert s.pet.sum.prob == 0.5
    assert s.pet.sum.count.min == 5
    assert s.mask.to_config().group_type.name == "INTEGER"

    bad = _settings()
    bad.pet.update.count = CountSettings(min=2, max=10)  # below protocol floor (3)
    with pytest.raises(SettingsError):
        bad.validate()

    bad = _settings()
    bad.aggregation.kernel = "mosaic"  # not a valid fold kernel name
    with pytest.raises(SettingsError):
        bad.validate()


@pytest.mark.parametrize("kernel", ["xla", "pallas-interpret"])
def test_staged_aggregator_device_matches_host(kernel):
    """Device (mesh) aggregation path == host path, including unmask."""
    import numpy as np

    from xaynet_tpu.core.mask import (
        BoundType,
        DataType,
        GroupType,
        Masker,
        MaskConfig,
        ModelType,
        Scalar,
    )
    from xaynet_tpu.server.aggregation import StagedAggregator

    cfg = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M6)
    n, k = 57, 7
    rng = np.random.default_rng(9)
    host = StagedAggregator(cfg.pair(), n, device=False, batch_size=3)
    dev = StagedAggregator(cfg.pair(), n, device=True, batch_size=3, kernel=kernel)
    for _ in range(k):
        w = rng.uniform(-1, 1, n).astype(np.float32)
        _, masked = Masker(cfg.pair()).mask(Scalar(1, k), w)
        host.validate_aggregation(masked)
        host.aggregate(masked)
        dev.validate_aggregation(masked)
        dev.aggregate(masked)
    a, b = host.finalize(), dev.finalize()
    assert a.nb_models == b.nb_models == k
    assert a.object == b.object
    assert host.kernel_used == "host"
    assert dev.kernel_used == kernel


def test_staged_aggregator_lazy_wire_vect_device_validate_and_reject():
    """Lazy wire vects (aggregation.wire_ingest): validate_aggregation runs
    the device unpack+validity and caches the planar; an invalid element is
    rejected BEFORE the caller's seed-dict insert (AggregationError, like
    the eager parse's DecodeError one stage earlier); the staged fold
    matches the eager-parse host path exactly."""
    import numpy as np
    import pytest as _pytest

    from xaynet_tpu.core.mask import (
        BoundType,
        DataType,
        GroupType,
        Masker,
        MaskConfig,
        ModelType,
        Scalar,
    )
    from xaynet_tpu.core.mask.masking import AggregationError
    from xaynet_tpu.core.mask.object import LazyWireMaskVect, MaskObject
    from xaynet_tpu.core.mask.serialization import serialize_mask_vect, vect_element_block
    from xaynet_tpu.server.aggregation import StagedAggregator

    cfg = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M6)
    bpn = cfg.bytes_per_number
    n, k = 57, 5
    rng = np.random.default_rng(4)
    host = StagedAggregator(cfg.pair(), n, device=False, batch_size=3)
    dev = StagedAggregator(cfg.pair(), n, device=True, batch_size=3, kernel="xla")
    for _ in range(k):
        w = rng.uniform(-1, 1, n).astype(np.float32)
        _, masked = Masker(cfg.pair()).mask(Scalar(1, k), w)
        host.validate_aggregation(masked)
        host.aggregate(masked)
        raw = vect_element_block(serialize_mask_vect(masked.vect))
        lazy = MaskObject(LazyWireMaskVect(cfg, raw, n), masked.unit)
        dev.validate_aggregation(lazy)
        assert lazy.vect._staged_planar is not None  # device validated + cached
        assert not lazy.vect.materialized  # host parse never ran
        dev.aggregate(lazy)
    a, b = host.finalize(), dev.finalize()
    assert a.nb_models == b.nb_models == k
    assert a.object == b.object

    # an invalid element must be rejected at validate time (before any
    # seed-dict insert), not silently folded
    _, masked = Masker(cfg.pair()).mask(Scalar(1, k), np.zeros(n, dtype=np.float32))
    raw = np.array(vect_element_block(serialize_mask_vect(masked.vect)))
    raw[:bpn] = 0xFF  # element >= order
    bad = MaskObject(LazyWireMaskVect(cfg, raw, n), masked.unit)
    dev2 = StagedAggregator(cfg.pair(), n, device=True, batch_size=3, kernel="xla")
    with _pytest.raises(AggregationError):
        dev2.validate_aggregation(bad)
    assert dev2.pending == 0 and dev2.nb_models == 0

    # host access to a lazy vect materializes identically to the eager parse
    lazy2 = LazyWireMaskVect(
        cfg, vect_element_block(serialize_mask_vect(masked.vect)), n
    )
    assert np.array_equal(lazy2.data, masked.vect.data)
    assert lazy2.materialized and lazy2.is_valid() and len(lazy2) == n


def test_sdk_sum2_device_path_matches_host(monkeypatch):
    """SDK mask aggregation: device kernels == host path."""
    import numpy as np

    from xaynet_tpu.core.mask import (
        BoundType,
        DataType,
        GroupType,
        MaskConfig,
        MaskSeed,
        ModelType,
    )
    from xaynet_tpu.sdk.state_machine import StateMachine
    from xaynet_tpu.sdk.simulation import keys_for_task

    cfg = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M3)
    sm = StateMachine.__new__(StateMachine)
    seeds = [MaskSeed(bytes([i]) * 32) for i in range(1, 5)]

    sm.device_sum2 = False
    host_obj = StateMachine._aggregate_masks(sm, seeds, 64, cfg.pair())
    # force the device branch: enable the flag and drop the size threshold
    sm.device_sum2 = True
    monkeypatch.setattr(StateMachine, "DEVICE_SUM2_THRESHOLD", 1)
    dev_obj = StateMachine._aggregate_masks(sm, seeds, 64, cfg.pair())
    assert host_obj == dev_obj

    # device_sum2=None is "auto": the device path turns on exactly when the
    # default JAX backend is an accelerator (VERDICT r03 item 8)
    import xaynet_tpu.sdk.state_machine as smod
    from xaynet_tpu.ops import masking_jax

    calls = []
    real = masking_jax.sum_masks

    def spy(s, n, c):
        calls.append(n)
        return real(s, n, c)

    monkeypatch.setattr(masking_jax, "sum_masks", spy)
    sm.device_sum2 = None
    monkeypatch.setattr(smod, "_ACCEL_DEFAULT", False)  # CPU-only edge
    StateMachine._aggregate_masks(sm, seeds, 64, cfg.pair())
    assert not calls
    monkeypatch.setattr(smod, "_ACCEL_DEFAULT", True)  # device-equipped
    auto_obj = StateMachine._aggregate_masks(sm, seeds, 64, cfg.pair())
    assert calls == [64]
    assert auto_obj == host_obj


def test_sdk_sum2_batched_fold_keeps_count_cap():
    """The batched host fold enforces max_nb_models with the incremental
    loop's error kind: one seed over M3's cap raises TooManyModels."""
    import pytest

    from xaynet_tpu.core.mask import (
        AggregationError,
        BoundType,
        DataType,
        GroupType,
        MaskConfig,
        MaskSeed,
        ModelType,
    )
    from xaynet_tpu.sdk.state_machine import StateMachine

    cfg = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M3)
    cap = cfg.max_nb_models
    sm = StateMachine.__new__(StateMachine)
    sm.device_sum2 = False
    seeds = [MaskSeed(i.to_bytes(32, "little")) for i in range(1, cap + 2)]
    with pytest.raises(AggregationError, match="TooManyModels"):
        StateMachine._aggregate_masks(sm, seeds, 8, cfg.pair())


def test_round_failure_then_successful_round():
    """A timed-out round restarts; the next round completes end to end."""
    import numpy as np
    from fractions import Fraction

    from xaynet_tpu.sdk.client import InProcessClient
    from xaynet_tpu.sdk.state_machine import PetSettings as SdkPet, StateMachine as P
    from xaynet_tpu.sdk.traits import ModelStore

    class MS(ModelStore):
        def __init__(self, m):
            self.m = m

        async def load_model(self):
            return self.m

    async def run():
        settings = _settings()
        settings.pet.sum.time = TimeSettings(0, 0.3)  # round 1 will time out
        settings.pet.update.count = CountSettings(3, 3)
        settings.pet.update.time = TimeSettings(0, 20.0)
        settings.pet.sum2.time = TimeSettings(0, 20.0)
        store = _store()

        from xaynet_tpu.server.metrics import Metrics

        class PhaseRecorder(Metrics):
            def __init__(self):
                self.phases = []

            def phase(self, round_id, phase):
                self.phases.append((round_id, phase))

        recorder = PhaseRecorder()
        machine, tx, events = await StateMachineInitializer(settings, store, recorder).init()
        handler = PetMessageHandler(events, tx)
        machine_task = asyncio.create_task(machine.run())
        from xaynet_tpu.server.services import Fetcher

        fetcher = Fetcher(events)
        try:
            # round 1: nobody participates -> PhaseTimeout -> Failure -> Idle
            while events.params.get_latest().round_id < 2:
                await asyncio.sleep(0.02)
            assert (1, "failure") in recorder.phases, recorder.phases

            # restore the sum window so round 2 can complete
            settings.pet.sum.time = TimeSettings(0, 20.0)

            while fetcher.phase().value != "sum":
                await asyncio.sleep(0.02)
            params = fetcher.round_params()
            seed = params.seed.as_bytes()
            rng = np.random.default_rng(1)
            parts = []
            keys = keys_for_task(seed, params.sum, params.update, "sum")
            parts.append(P(SdkPet(keys=keys), InProcessClient(fetcher, handler), MS(None)))
            expected = np.zeros(4)
            for i in range(3):
                keys = keys_for_task(seed, params.sum, params.update, "update", start=(5 + i) * 1000)
                local = rng.uniform(-1, 1, 4).astype(np.float32)
                expected += local.astype(np.float64) / 3
                parts.append(
                    P(
                        SdkPet(keys=keys, scalar=Fraction(1, 3)),
                        InProcessClient(fetcher, handler),
                        MS(local),
                    )
                )

            async def drive(sm):
                for _ in range(500):
                    try:
                        await sm.transition()
                    except Exception:
                        pass
                    if fetcher.model() is not None:
                        return
                    await asyncio.sleep(0.01)

            await asyncio.gather(*(drive(p) for p in parts))
            while fetcher.model() is None:
                await asyncio.sleep(0.01)
            np.testing.assert_allclose(np.asarray(fetcher.model()), expected, atol=1e-9)
        finally:
            machine_task.cancel()
            try:
                await machine_task
            except (asyncio.CancelledError, Exception):
                pass

    asyncio.run(asyncio.wait_for(run(), timeout=60))


def test_storage_fault_routes_to_failure_and_recovers():
    """A storage backend outage fails the round; the machine recovers once
    the store is healthy again (reference: failure.rs wait_for_store_readiness)."""

    class FlakyStorage(InMemoryCoordinatorStorage):
        def __init__(self):
            super().__init__()
            self.broken = False

        async def add_sum_participant(self, pk, ephm_pk):
            if self.broken:
                raise RuntimeError("backend down")
            return await super().add_sum_participant(pk, ephm_pk)

        async def is_ready(self):
            if self.broken:
                from xaynet_tpu.storage.traits import StorageError

                raise StorageError("backend down")

    async def run():
        flaky = FlakyStorage()
        store = Store(flaky, InMemoryModelStorage(), NoOpTrustAnchor())
        settings = _settings(5.0)
        # keep the Failure phase's readiness backoff snappy for the test
        # (the probe cadence comes from [resilience] retry settings now)
        settings.resilience.retry_base_ms = 5.0
        settings.resilience.retry_max_ms = 50.0
        machine, tx, events = await StateMachineInitializer(settings, store).init()
        handler = PetMessageHandler(events, tx)
        machine_task = asyncio.create_task(machine.run())
        try:
            while events.phase.get_latest().event.value != "sum":
                await asyncio.sleep(0.01)
            params = events.params.get_latest().event
            keys = keys_for_task(params.seed.as_bytes(), params.sum, params.update, "sum")
            payload = Sum(
                sum_signature=keys.sign(params.seed.as_bytes() + b"sum").as_bytes(),
                ephm_pk=b"\x02" * 32,
            )
            msg = Message(participant_pk=keys.public, coordinator_pk=params.pk, payload=payload)
            wire = PublicEncryptKey(params.pk).encrypt(msg.to_bytes(keys.secret))

            flaky.broken = True
            with pytest.raises(Exception):
                await handler.handle_message(wire)
            # the failing handler crashed the sum phase -> failure -> waits
            # for store readiness; heal the store and watch the next round
            start_round = events.params.get_latest().round_id
            await asyncio.sleep(0.2)
            flaky.broken = False
            deadline = asyncio.get_running_loop().time() + 10
            while events.params.get_latest().round_id <= start_round:
                assert asyncio.get_running_loop().time() < deadline, "no recovery"
                await asyncio.sleep(0.02)
            assert events.phase.get_latest().event.value in ("idle", "sum")
        finally:
            machine_task.cancel()
            try:
                await machine_task
            except (asyncio.CancelledError, Exception):
                pass

    asyncio.run(asyncio.wait_for(run(), timeout=30))


def test_file_coordinator_storage_survives_restart(tmp_path):
    """Durable state (coordinator state + model pointer) survives a new
    process generation; round dictionaries are volatile by design."""
    from xaynet_tpu.storage.memory import FileCoordinatorStorage

    path = str(tmp_path / "state.json")

    async def run():
        a = FileCoordinatorStorage(path)
        await a.set_coordinator_state(b"gen1-state")
        await a.set_latest_global_model_id("5_abc")
        await a.add_sum_participant(b"p" * 32, b"e" * 32)

        b = FileCoordinatorStorage(path)  # "new process"
        assert await b.coordinator_state() == b"gen1-state"
        assert await b.latest_global_model_id() == "5_abc"
        assert await b.sum_dict() is None  # volatile

        await b.delete_coordinator_data()
        c = FileCoordinatorStorage(path)
        assert await c.coordinator_state() is None

    asyncio.run(run())
