"""S3 model storage against a faithful fake S3 server at the HTTP layer.

The fake implements the S3 REST subset the backend uses (PUT bucket,
HEAD/PUT/GET object) and — crucially — *recomputes and verifies the AWS
SigV4 signature* of every request with the shared secret, so the from-
scratch signing implementation is actually validated, not just exercised
(reference matrix: rust/xaynet-server/src/storage/model_storage/s3.rs).

Set ``XAYNET_S3=host:port`` (plus ``XAYNET_S3_ACCESS``/``XAYNET_S3_SECRET``,
default minioadmin) to additionally run the data-model tests against a real
S3-compatible server — the CI ``test-live-minio`` job does this with a
pinned `minio/minio` container (started via docker run; the official image
needs its `server /data` command), the way the reference tests against
Minio (.github/workflows/rust.yml:212-227). That run validates the SigV4
signer against an implementation we did not write.
"""

import asyncio
import hashlib
import os
import uuid

import pytest

from xaynet_tpu.storage.s3 import S3ModelStorage, sign_v4
from xaynet_tpu.storage.traits import StorageError

ACCESS, SECRET, REGION = "minio-access", "minio-secret", "us-east-1"


class FakeS3:
    """Minimal S3-compatible HTTP server with SigV4 verification."""

    def __init__(self):
        self.buckets: dict[str, dict[str, bytes]] = {}
        self._server = None
        self.reject_signatures = False

    async def start(self, port: int = 0):
        self._server = await asyncio.start_server(self._conn, "127.0.0.1", port)
        return self._server.sockets[0].getsockname()[1]

    async def stop(self):
        self._server.close()
        await self._server.wait_closed()

    async def _conn(self, reader, writer):
        try:
            request = await reader.readline()
            method, path, _ = request.decode().split(" ", 2)
            headers = {}
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                k, _, v = line.decode().partition(":")
                headers[k.strip().lower()] = v.strip()
            body = b""
            if "content-length" in headers:
                body = await reader.readexactly(int(headers["content-length"]))

            status, resp_body = self._dispatch(method, path, headers, body)
            writer.write(
                (
                    f"HTTP/1.1 {status} X\r\ncontent-length: {len(resp_body)}\r\n"
                    "connection: close\r\n\r\n"
                ).encode()
                + resp_body
            )
            await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            writer.close()

    def _signature_ok(self, method, path, headers, body) -> bool:
        auth = headers.get("authorization", "")
        if not auth.startswith("AWS4-HMAC-SHA256"):
            return False
        # recompute with the same signer the client used — inverted check
        expected = sign_v4(
            method,
            headers["host"],
            path,
            access_key=ACCESS,
            secret_key=SECRET,
            region=REGION,
            payload_hash=headers.get("x-amz-content-sha256", ""),
            amz_date=headers.get("x-amz-date", ""),
        )["authorization"]
        if auth != expected:
            return False
        # and the payload hash must match the actual body
        return headers.get("x-amz-content-sha256") == hashlib.sha256(body).hexdigest()

    def _dispatch(self, method, path, headers, body):
        if self.reject_signatures or not self._signature_ok(method, path, headers, body):
            return 403, b"<Error><Code>SignatureDoesNotMatch</Code></Error>"
        parts = path.lstrip("/").split("/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else None

        if key is None:
            if method == "PUT":
                if bucket in self.buckets:
                    return 409, b"<Error><Code>BucketAlreadyOwnedByYou</Code></Error>"
                self.buckets[bucket] = {}
                return 200, b""
            if method == "HEAD":
                return (200, b"") if bucket in self.buckets else (404, b"")
        else:
            objs = self.buckets.get(bucket)
            if objs is None:
                return 404, b"<Error><Code>NoSuchBucket</Code></Error>"
            if method == "HEAD":
                return (200, b"") if key in objs else (404, b"")
            if method == "GET":
                return (200, objs[key]) if key in objs else (404, b"")
            if method == "PUT":
                # honor the atomic conditional create (S3 If-None-Match: *)
                if headers.get("if-none-match") == "*" and key in objs:
                    return 412, b"<Error><Code>PreconditionFailed</Code></Error>"
                objs[key] = body
                return 200, b""
        return 400, b"bad request"


class _S3Backend:
    """One S3 endpoint for a data-model test: the in-process SigV4-verifying
    fake, or a live server at ``XAYNET_S3=host:port``. Buckets are
    uniquified per test so live runs don't see earlier state."""

    def __init__(self, kind: str):
        self.kind = kind
        self.fake = None

    async def __aenter__(self) -> "_S3Backend":
        if self.kind == "live":
            host, _, port = os.environ["XAYNET_S3"].partition(":")
            self.endpoint = f"http://{host}:{int(port or 9000)}"
            self.access = os.environ.get("XAYNET_S3_ACCESS", "minioadmin")
            self.secret = os.environ.get("XAYNET_S3_SECRET", "minioadmin")
        else:
            self.fake = FakeS3()
            port = await self.fake.start()
            self.endpoint = f"http://127.0.0.1:{port}"
            self.access, self.secret = ACCESS, SECRET
        self.bucket = f"xn-test-{uuid.uuid4().hex[:12]}"
        return self

    async def __aexit__(self, *exc):
        if self.fake is not None:
            await self.fake.stop()
        else:
            # best-effort: don't leak uuid buckets on a shared live server
            try:
                store = self.store()
                listing = await store._request("GET", f"/{self.bucket}")
                if listing.status == 200:
                    import re

                    for key in re.findall(rb"<Key>([^<]+)</Key>", listing.body):
                        await store._request("DELETE", f"/{self.bucket}/{key.decode()}")
                await store._request("DELETE", f"/{self.bucket}")
            except Exception:
                pass

    def store(self, secret_key: str | None = None) -> S3ModelStorage:
        return S3ModelStorage(
            endpoint=self.endpoint,
            bucket=self.bucket,
            access_key=self.access,
            secret_key=secret_key or self.secret,
            region=REGION,
        )


def _backend_params():
    params = ["fake"]
    if os.environ.get("XAYNET_S3"):
        params.append("live")
    return params


@pytest.fixture(params=_backend_params())
def s3_kind(request):
    return request.param


def test_s3_full_cycle_with_signature_verification(s3_kind):
    async def run():
        async with _S3Backend(s3_kind) as be:
            store = be.store()
            # bucket lifecycle: create, idempotent re-create, readiness
            with pytest.raises(StorageError):
                await store.is_ready()  # bucket doesn't exist yet
            await store.create_bucket()
            await store.create_bucket()  # 409 already-owned is not an error
            await store.is_ready()

            # store + fetch with the canonical id
            seed = b"\x5a" * 32
            model_id = await store.set_global_model(7, seed, b"model-bytes-7")
            assert model_id == f"7_{seed.hex()}"
            assert await store.global_model(model_id) == b"model-bytes-7"
            assert await store.global_model("0_" + "00" * 32) is None

            # refuse overwrite (reference s3.rs behavior)
            with pytest.raises(StorageError, match="already exists"):
                await store.set_global_model(7, seed, b"other-bytes")
            assert await store.global_model(model_id) == b"model-bytes-7"

    asyncio.run(run())


def test_s3_bad_credentials_rejected(s3_kind):
    async def run():
        async with _S3Backend(s3_kind) as be:
            bad = be.store(secret_key="wrong-secret")
            with pytest.raises(StorageError, match="403|failed"):
                await bad.create_bucket()

    asyncio.run(run())


def test_s3_unreachable_raises_typed_error():
    async def run():
        fake = FakeS3()
        port = await fake.start()
        await fake.stop()  # nothing listening
        store = S3ModelStorage(
            endpoint=f"http://127.0.0.1:{port}",
            bucket="global-models",
            access_key=ACCESS,
            secret_key=SECRET,
            region=REGION,
        )
        with pytest.raises(StorageError, match="unreachable"):
            await store.is_ready()

    asyncio.run(run())


def test_s3_conditional_put_closes_head_put_race(s3_kind):
    """Even if the HEAD pre-check is bypassed (two concurrent writers), the
    conditional PUT refuses the second write atomically. Minio supports
    `If-None-Match: *` since RELEASE.2024-08; the CI service container is
    recent enough."""

    async def run():
        async with _S3Backend(s3_kind) as be:
            store = be.store()
            await store.create_bucket()
            seed = b"\x11" * 32
            await store.set_global_model(3, seed, b"first")
            # simulate the racing writer: skip HEAD, PUT directly
            model_id = store.create_global_model_id(3, seed)
            resp = await store._request(
                "PUT", f"/{store.bucket}/{model_id}", b"second", {"if-none-match": "*"}
            )
            assert resp.status == 412
            assert await store.global_model(model_id) == b"first"

    asyncio.run(run())
