"""S3 model storage against a faithful fake S3 server at the HTTP layer.

The fake implements the S3 REST subset the backend uses (PUT bucket,
HEAD/PUT/GET object) and — crucially — *recomputes and verifies the AWS
SigV4 signature* of every request with the shared secret, so the from-
scratch signing implementation is actually validated, not just exercised
(reference matrix: rust/xaynet-server/src/storage/model_storage/s3.rs).
"""

import asyncio
import hashlib

import pytest

from xaynet_tpu.storage.s3 import S3ModelStorage, sign_v4
from xaynet_tpu.storage.traits import StorageError

ACCESS, SECRET, REGION = "minio-access", "minio-secret", "us-east-1"


class FakeS3:
    """Minimal S3-compatible HTTP server with SigV4 verification."""

    def __init__(self):
        self.buckets: dict[str, dict[str, bytes]] = {}
        self._server = None
        self.reject_signatures = False

    async def start(self, port: int = 0):
        self._server = await asyncio.start_server(self._conn, "127.0.0.1", port)
        return self._server.sockets[0].getsockname()[1]

    async def stop(self):
        self._server.close()
        await self._server.wait_closed()

    async def _conn(self, reader, writer):
        try:
            request = await reader.readline()
            method, path, _ = request.decode().split(" ", 2)
            headers = {}
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                k, _, v = line.decode().partition(":")
                headers[k.strip().lower()] = v.strip()
            body = b""
            if "content-length" in headers:
                body = await reader.readexactly(int(headers["content-length"]))

            status, resp_body = self._dispatch(method, path, headers, body)
            writer.write(
                (
                    f"HTTP/1.1 {status} X\r\ncontent-length: {len(resp_body)}\r\n"
                    "connection: close\r\n\r\n"
                ).encode()
                + resp_body
            )
            await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            writer.close()

    def _signature_ok(self, method, path, headers, body) -> bool:
        auth = headers.get("authorization", "")
        if not auth.startswith("AWS4-HMAC-SHA256"):
            return False
        # recompute with the same signer the client used — inverted check
        expected = sign_v4(
            method,
            headers["host"],
            path,
            access_key=ACCESS,
            secret_key=SECRET,
            region=REGION,
            payload_hash=headers.get("x-amz-content-sha256", ""),
            amz_date=headers.get("x-amz-date", ""),
        )["authorization"]
        if auth != expected:
            return False
        # and the payload hash must match the actual body
        return headers.get("x-amz-content-sha256") == hashlib.sha256(body).hexdigest()

    def _dispatch(self, method, path, headers, body):
        if self.reject_signatures or not self._signature_ok(method, path, headers, body):
            return 403, b"<Error><Code>SignatureDoesNotMatch</Code></Error>"
        parts = path.lstrip("/").split("/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else None

        if key is None:
            if method == "PUT":
                if bucket in self.buckets:
                    return 409, b"<Error><Code>BucketAlreadyOwnedByYou</Code></Error>"
                self.buckets[bucket] = {}
                return 200, b""
            if method == "HEAD":
                return (200, b"") if bucket in self.buckets else (404, b"")
        else:
            objs = self.buckets.get(bucket)
            if objs is None:
                return 404, b"<Error><Code>NoSuchBucket</Code></Error>"
            if method == "HEAD":
                return (200, b"") if key in objs else (404, b"")
            if method == "GET":
                return (200, objs[key]) if key in objs else (404, b"")
            if method == "PUT":
                # honor the atomic conditional create (S3 If-None-Match: *)
                if headers.get("if-none-match") == "*" and key in objs:
                    return 412, b"<Error><Code>PreconditionFailed</Code></Error>"
                objs[key] = body
                return 200, b""
        return 400, b"bad request"


def _store(port):
    return S3ModelStorage(
        endpoint=f"http://127.0.0.1:{port}",
        bucket="global-models",
        access_key=ACCESS,
        secret_key=SECRET,
        region=REGION,
    )


def test_s3_full_cycle_with_signature_verification():
    async def run():
        fake = FakeS3()
        port = await fake.start()
        store = _store(port)
        try:
            # bucket lifecycle: create, idempotent re-create, readiness
            with pytest.raises(StorageError):
                await store.is_ready()  # bucket doesn't exist yet
            await store.create_bucket()
            await store.create_bucket()  # 409 already-owned is not an error
            await store.is_ready()

            # store + fetch with the canonical id
            seed = b"\x5a" * 32
            model_id = await store.set_global_model(7, seed, b"model-bytes-7")
            assert model_id == f"7_{seed.hex()}"
            assert await store.global_model(model_id) == b"model-bytes-7"
            assert await store.global_model("0_" + "00" * 32) is None

            # refuse overwrite (reference s3.rs behavior)
            with pytest.raises(StorageError, match="already exists"):
                await store.set_global_model(7, seed, b"other-bytes")
            assert await store.global_model(model_id) == b"model-bytes-7"
        finally:
            await fake.stop()

    asyncio.run(run())


def test_s3_bad_credentials_rejected():
    async def run():
        fake = FakeS3()
        port = await fake.start()
        bad = S3ModelStorage(
            endpoint=f"http://127.0.0.1:{port}",
            bucket="global-models",
            access_key=ACCESS,
            secret_key="wrong-secret",
            region=REGION,
        )
        try:
            with pytest.raises(StorageError, match="403|failed"):
                await bad.create_bucket()
        finally:
            await fake.stop()

    asyncio.run(run())


def test_s3_unreachable_raises_typed_error():
    async def run():
        fake = FakeS3()
        port = await fake.start()
        await fake.stop()  # nothing listening
        store = _store(port)
        with pytest.raises(StorageError, match="unreachable"):
            await store.is_ready()

    asyncio.run(run())


def test_s3_conditional_put_closes_head_put_race():
    """Even if the HEAD pre-check is bypassed (two concurrent writers), the
    conditional PUT refuses the second write atomically."""

    async def run():
        fake = FakeS3()
        port = await fake.start()
        store = _store(port)
        try:
            await store.create_bucket()
            seed = b"\x11" * 32
            await store.set_global_model(3, seed, b"first")
            # simulate the racing writer: skip HEAD, PUT directly
            model_id = store.create_global_model_id(3, seed)
            resp = await store._request(
                "PUT", f"/{store.bucket}/{model_id}", b"second", {"if-none-match": "*"}
            )
            assert resp.status == 412
            assert await store.global_model(model_id) == b"first"
        finally:
            await fake.stop()

    asyncio.run(run())
