"""TLS: coordinator serves HTTPS; client verifies against a private CA."""

import asyncio
import datetime
import ssl

import pytest

# certificate generation needs the real wheel (x509 is not covered by the
# pure-python fallback primitives)
pytest.importorskip("cryptography")

from xaynet_tpu.server.rest import RestServer
from xaynet_tpu.server.services import Fetcher, PetMessageHandler
from xaynet_tpu.server.settings import Settings
from xaynet_tpu.server.state_machine import StateMachineInitializer
from xaynet_tpu.sdk.client import HttpClient
from xaynet_tpu.storage.memory import (
    InMemoryCoordinatorStorage,
    InMemoryModelStorage,
    NoOpTrustAnchor,
)
from xaynet_tpu.storage.traits import Store


def _self_signed(tmp_path):
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "127.0.0.1")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(
            x509.SubjectAlternativeName([x509.IPAddress(__import__("ipaddress").ip_address("127.0.0.1"))]),
            critical=False,
        )
        .sign(key, hashes.SHA256())
    )
    cert_path = tmp_path / "server.pem"
    key_path = tmp_path / "server.key"
    cert_path.write_bytes(cert.public_bytes(serialization.Encoding.PEM))
    key_path.write_bytes(
        key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption(),
        )
    )
    return str(cert_path), str(key_path)


def test_https_round_params(tmp_path):
    cert_path, key_path = _self_signed(tmp_path)

    async def run():
        settings = Settings.default()
        settings.model.length = 4
        store = Store(InMemoryCoordinatorStorage(), InMemoryModelStorage(), NoOpTrustAnchor())
        machine, tx, events = await StateMachineInitializer(settings, store).init()
        rest = RestServer(Fetcher(events), PetMessageHandler(events, tx))

        server_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        server_ctx.load_cert_chain(cert_path, key_path)
        host, port = await rest.start("127.0.0.1", 0, tls=server_ctx)
        machine_task = asyncio.create_task(machine.run())
        try:
            client_ctx = ssl.create_default_context(cafile=cert_path)
            client = HttpClient(f"https://{host}:{port}", tls_context=client_ctx)
            while events.phase.get_latest().event.value != "sum":
                await asyncio.sleep(0.01)
            params = await client.get_round_params()
            assert params.model_length == 4

            # plaintext to the TLS port must fail
            plain = HttpClient(f"http://{host}:{port}", timeout=3.0)
            with pytest.raises(Exception):
                await plain.get_round_params()

            # wrong CA must fail the handshake
            bad_ctx = ssl.create_default_context()
            bad = HttpClient(f"https://{host}:{port}", tls_context=bad_ctx, timeout=3.0)
            with pytest.raises(Exception):
                await bad.get_round_params()
        finally:
            machine_task.cancel()
            await rest.stop()
            try:
                await machine_task
            except (asyncio.CancelledError, Exception):
                pass

    asyncio.run(asyncio.wait_for(run(), timeout=60))
