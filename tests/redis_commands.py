"""Dict-backed handlers for the Redis commands the Lua scripts use.

Shared by the fake RESP server (``test_redis_storage.FakeRedis``) and the
direct script tests (``test_lua_mini``) so both suites exercise one set of
command semantics. Handlers return RESP-style Python values (int / bytes /
None / list) which ``xaynet_tpu.utils.lua_mini`` converts with the Redis
EVAL conversion rules.
"""

from __future__ import annotations


class DictRedisCommands:
    """State + the single-command subset ``redis.call`` needs."""

    def __init__(self):
        self.hashes: dict[bytes, dict[bytes, bytes]] = {}
        self.sets: dict[bytes, set] = {}
        self.zsets: dict[bytes, dict[bytes, float]] = {}

    def __call__(self, *parts: bytes):
        cmd = parts[0].upper()
        if cmd == b"HSETNX":
            h = self.hashes.setdefault(parts[1], {})
            if parts[2] in h:
                return 0
            h[parts[2]] = parts[3]
            return 1
        if cmd == b"HSET":
            h = self.hashes.setdefault(parts[1], {})
            added = int(parts[2] not in h)
            h[parts[2]] = parts[3]
            return added
        if cmd == b"HLEN":
            return len(self.hashes.get(parts[1], {}))
        if cmd == b"HEXISTS":
            return int(parts[2] in self.hashes.get(parts[1], {}))
        if cmd == b"SISMEMBER":
            return int(parts[2] in self.sets.get(parts[1], set()))
        if cmd == b"SADD":
            s = self.sets.setdefault(parts[1], set())
            added = sum(1 for m in parts[2:] if m not in s)
            s.update(parts[2:])
            return added
        if cmd == b"ZINCRBY":
            z = self.zsets.setdefault(parts[1], {})
            z[parts[3]] = z.get(parts[3], 0.0) + float(parts[2])
            score = z[parts[3]]
            # real Redis replies with the score as a bulk string
            return (b"%d" % int(score)) if float(score).is_integer() else repr(score).encode()
        raise AssertionError(f"unsupported command in Lua script: {cmd!r}")
