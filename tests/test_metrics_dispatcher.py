"""Network metrics dispatcher: live HTTP sink, backpressure, outage behavior.

Reference analogue: the InfluxDB recorder's dedicated background dispatcher
channel (rust/xaynet-server/src/metrics/recorders/influxdb/dispatcher.rs).
The contract under test: recording never blocks, lines reach a live sink in
batches, and a down/slow sink costs bounded memory (drop + count), never
coordinator latency.
"""

import json
import os
import threading
import time
import urllib.parse
import urllib.request
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from xaynet_tpu.server.metrics import InfluxHttpMetrics


class _FakeInflux(ThreadingHTTPServer):
    daemon_threads = True

    def __init__(self):
        self.lines: list[str] = []
        self.lock = threading.Lock()
        super().__init__(("127.0.0.1", 0), _Handler)


class _Handler(BaseHTTPRequestHandler):
    def do_POST(self):
        assert self.path.startswith("/write?db=")
        body = self.rfile.read(int(self.headers["Content-Length"])).decode()
        with self.server.lock:
            self.server.lines.extend(x for x in body.splitlines() if x)
        self.send_response(204)
        self.end_headers()

    def log_message(self, *a):  # quiet
        pass


def test_dispatcher_delivers_to_live_sink():
    srv = _FakeInflux()
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        m = InfluxHttpMetrics(f"http://127.0.0.1:{srv.server_address[1]}", "metrics")
        m.phase(1, "sum")
        m.message_accepted(1, "sum")
        m.masks_total(1, 3)
        m.event(1, "phase_error", 'timeout "quoted"')
        deadline = time.time() + 10
        while time.time() < deadline:
            with srv.lock:
                if len(srv.lines) >= 4:
                    break
            time.sleep(0.05)
        m.close()
        with srv.lock:
            lines = list(srv.lines)
        assert len(lines) == 4
        assert any(ln.startswith("xaynet_phase,round_id=1,phase=sum ") for ln in lines)
        assert any("xaynet_message_accepted" in ln for ln in lines)
        assert any('value="timeout \\"quoted\\""' in ln for ln in lines)
        assert m.dropped == 0
    finally:
        srv.shutdown()


def test_dispatcher_never_blocks_when_sink_is_down():
    # nothing listens on this port: every POST fails
    m = InfluxHttpMetrics("http://127.0.0.1:9", "metrics", queue_size=32)
    t0 = time.perf_counter()
    for i in range(10_000):
        m.message_accepted(1, "update")
    elapsed = time.perf_counter() - t0
    # 10k records against a dead sink must cost microseconds each, not
    # connect timeouts; memory is bounded by the queue
    assert elapsed < 2.0, elapsed
    assert m._queue.qsize() <= 32
    assert m.dropped > 0  # overflow was counted, not silently lost
    m.close()


@pytest.mark.skipif(
    not os.environ.get("XAYNET_INFLUX"),
    reason="set XAYNET_INFLUX=host:port to test against a live InfluxDB",
)
def test_dispatcher_against_live_influxdb():
    """The line protocol we emit parses in a REAL InfluxDB: write the full
    measurement families through the production sink, then query the points
    back over /query and check tags/values survived the round trip.
    (CI `test-live-influxdb` job, influxdb:1.8 service container — the
    reference's equivalent: .github/workflows/rust.yml:212-227.)"""
    host, _, port = os.environ["XAYNET_INFLUX"].partition(":")
    base = f"http://{host}:{int(port or 8086)}"
    db = f"xn_test_{uuid.uuid4().hex[:12]}"

    def query(q, use_db=True):
        params = {"q": q}
        if use_db:
            params["db"] = db
        encoded = urllib.parse.urlencode(params)
        # InfluxDB 1.x: SELECT/SHOW go over GET; management statements
        # (CREATE/DROP DATABASE) must be POSTed
        if q.split()[0].upper() in ("SELECT", "SHOW"):
            req = urllib.request.Request(f"{base}/query?{encoded}")
        else:
            req = urllib.request.Request(
                f"{base}/query",
                data=encoded.encode(),
                headers={"Content-Type": "application/x-www-form-urlencoded"},
                method="POST",
            )
        with urllib.request.urlopen(req, timeout=10) as r:
            return json.loads(r.read())

    query(f'CREATE DATABASE "{db}"', use_db=False)
    try:
        m = InfluxHttpMetrics(base, db, flush_interval=0.05)
        m.phase(3, "sum")
        m.round_total(3)
        m.message_accepted(3, "sum")
        m.message_rejected(3, "sum")
        m.message_discarded(3, "sum")
        m.masks_total(3, 7)
        m.phase_duration(3, "sum", 1.25)
        m.event(3, "phase_error", 'timeout "quoted"')
        deadline = time.time() + 15
        series = {}
        want = {
            "xaynet_phase",
            "xaynet_round_total_number",
            "xaynet_message_accepted",
            "xaynet_message_rejected",
            "xaynet_message_discarded",
            "xaynet_masks_total_number",
            "xaynet_phase_duration_seconds",
            "xaynet_event_phase_error",
        }
        while time.time() < deadline and set(series) != want:
            res = query("SHOW MEASUREMENTS")
            names = {
                v[0]
                for s in res["results"][0].get("series", [])
                for v in s.get("values", [])
            }
            for name in names & want - set(series):
                pts = query(f'SELECT * FROM "{name}"')
                series[name] = pts["results"][0].get("series", [])
            time.sleep(0.1)
        m.close()
        assert set(series) == want, f"missing measurements: {want - set(series)}"
        phase_series = series["xaynet_phase"][0]
        cols = phase_series["columns"]
        row = phase_series["values"][0]
        point = dict(zip(cols, row))
        assert point["round_id"] == "3"
        assert point["phase"] == "sum"
        dur = dict(
            zip(
                series["xaynet_phase_duration_seconds"][0]["columns"],
                series["xaynet_phase_duration_seconds"][0]["values"][0],
            )
        )
        assert abs(float(dur["value"]) - 1.25) < 1e-9
        ev = dict(
            zip(
                series["xaynet_event_phase_error"][0]["columns"],
                series["xaynet_event_phase_error"][0]["values"][0],
            )
        )
        assert ev["value"] == 'timeout "quoted"'
    finally:
        query(f'DROP DATABASE "{db}"', use_db=False)


def test_registry_bridge_emits_reference_measurements():
    """The registry bridge forwards the same eight reference measurements to
    the Influx sink byte-for-byte, while the registry records them too."""
    from xaynet_tpu.telemetry.bridge import BridgedMetrics
    from xaynet_tpu.telemetry.registry import MetricsRegistry

    srv = _FakeInflux()
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        reg = MetricsRegistry()
        sink = InfluxHttpMetrics(
            f"http://127.0.0.1:{srv.server_address[1]}", "metrics", flush_interval=0.05
        )
        m = BridgedMetrics(sink=sink, registry=reg)
        m.phase(3, "sum")
        m.round_total(3)
        m.message_accepted(3, "sum")
        m.message_rejected(3, "sum")
        m.message_discarded(3, "sum")
        m.masks_total(3, 7)
        m.phase_duration(3, "sum", 1.25)
        m.event(3, "phase_error", "boom")
        deadline = time.time() + 10
        while time.time() < deadline:
            with srv.lock:
                if len(srv.lines) >= 8:
                    break
            time.sleep(0.05)
        m.close()
        with srv.lock:
            lines = list(srv.lines)
        measurements = {ln.split(",")[0] for ln in lines}
        assert measurements == {
            "xaynet_phase",
            "xaynet_round_total_number",
            "xaynet_message_accepted",
            "xaynet_message_rejected",
            "xaynet_message_discarded",
            "xaynet_masks_total_number",
            "xaynet_phase_duration_seconds",
            "xaynet_event_phase_error",
        }
        assert any(ln.startswith("xaynet_phase,round_id=3,phase=sum ") for ln in lines)
        # ... and the registry holds the same facts
        assert reg.sample_value("xaynet_round_id") == 3
        assert reg.sample_value("xaynet_masks_total") == 7
        for outcome in ("accepted", "rejected", "discarded"):
            assert (
                reg.sample_value(
                    "xaynet_messages_total", {"phase": "sum", "outcome": outcome}
                )
                == 1
            )
        hist = reg.get("xaynet_phase_duration_seconds").labels(phase="sum")
        assert hist.count == 1 and abs(hist.sum - 1.25) < 1e-9
        assert reg.sample_value("xaynet_events_total", {"kind": "phase_error"}) == 1
    finally:
        srv.shutdown()


def test_dispatcher_close_flushes_tail():
    srv = _FakeInflux()
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        m = InfluxHttpMetrics(
            f"http://127.0.0.1:{srv.server_address[1]}", "metrics", flush_interval=0.05
        )
        for i in range(20):
            m.round_total(i)
        time.sleep(0.5)  # let the dispatcher drain
        m.close()
        with srv.lock:
            assert len(srv.lines) == 20
    finally:
        srv.shutdown()
