"""Network metrics dispatcher: live HTTP sink, backpressure, outage behavior.

Reference analogue: the InfluxDB recorder's dedicated background dispatcher
channel (rust/xaynet-server/src/metrics/recorders/influxdb/dispatcher.rs).
The contract under test: recording never blocks, lines reach a live sink in
batches, and a down/slow sink costs bounded memory (drop + count), never
coordinator latency.
"""

import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from xaynet_tpu.server.metrics import InfluxHttpMetrics


class _FakeInflux(ThreadingHTTPServer):
    daemon_threads = True

    def __init__(self):
        self.lines: list[str] = []
        self.lock = threading.Lock()
        super().__init__(("127.0.0.1", 0), _Handler)


class _Handler(BaseHTTPRequestHandler):
    def do_POST(self):
        assert self.path.startswith("/write?db=")
        body = self.rfile.read(int(self.headers["Content-Length"])).decode()
        with self.server.lock:
            self.server.lines.extend(x for x in body.splitlines() if x)
        self.send_response(204)
        self.end_headers()

    def log_message(self, *a):  # quiet
        pass


def test_dispatcher_delivers_to_live_sink():
    srv = _FakeInflux()
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        m = InfluxHttpMetrics(f"http://127.0.0.1:{srv.server_address[1]}", "metrics")
        m.phase(1, "sum")
        m.message_accepted(1, "sum")
        m.masks_total(1, 3)
        m.event(1, "phase_error", 'timeout "quoted"')
        deadline = time.time() + 10
        while time.time() < deadline:
            with srv.lock:
                if len(srv.lines) >= 4:
                    break
            time.sleep(0.05)
        m.close()
        with srv.lock:
            lines = list(srv.lines)
        assert len(lines) == 4
        assert any(ln.startswith("xaynet_phase,round_id=1,phase=sum ") for ln in lines)
        assert any("xaynet_message_accepted" in ln for ln in lines)
        assert any('value="timeout \\"quoted\\""' in ln for ln in lines)
        assert m.dropped == 0
    finally:
        srv.shutdown()


def test_dispatcher_never_blocks_when_sink_is_down():
    # nothing listens on this port: every POST fails
    m = InfluxHttpMetrics("http://127.0.0.1:9", "metrics", queue_size=32)
    t0 = time.perf_counter()
    for i in range(10_000):
        m.message_accepted(1, "update")
    elapsed = time.perf_counter() - t0
    # 10k records against a dead sink must cost microseconds each, not
    # connect timeouts; memory is bounded by the queue
    assert elapsed < 2.0, elapsed
    assert m._queue.qsize() <= 32
    assert m.dropped > 0  # overflow was counted, not silently lost
    m.close()


def test_dispatcher_close_flushes_tail():
    srv = _FakeInflux()
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        m = InfluxHttpMetrics(
            f"http://127.0.0.1:{srv.server_address[1]}", "metrics", flush_interval=0.05
        )
        for i in range(20):
            m.round_total(i)
        time.sleep(0.5)  # let the dispatcher drain
        m.close()
        with srv.lock:
            assert len(srv.lines) == 20
    finally:
        srv.shutdown()
