"""Chaos suite for the resilience subsystem (xaynet_tpu.resilience).

Pins the four PR-4 contracts:

1. **transient-fault transparency** — a full PET round with seeded
   transient storage faults injected into every phase's coordinator calls
   completes with a global model byte-identical to the fault-free run;
2. **kill-and-restore** — a coordinator killed mid-update-phase resumes
   from the persisted checkpoint with the aggregate intact and finishes
   the round without the pre-kill participants resending;
3. **breaker lifecycle** — closed → open (fail-fast) → half-open probe →
   closed, plus the ResilientStore integration;
4. **fault-plan determinism** — same seed + spec → same schedule, across
   plan instances.

Plus the streaming degradation ladder, poisoning diagnostics, checkpoint
serialization/validation, the unmask pointer retry, and ingest worker
supervision.
"""

import asyncio
from fractions import Fraction

import numpy as np
import pytest

from xaynet_tpu.resilience import (
    BreakerOpen,
    CircuitBreaker,
    FaultPlan,
    ResilientStore,
    RetryPolicy,
    clear_plan,
    install_plan,
)
from xaynet_tpu.resilience import checkpoint as ckpt_mod
from xaynet_tpu.resilience.policy import RETRIES, is_transient
from xaynet_tpu.server.settings import (
    CountSettings,
    PhaseSettings,
    PetSettings as ServerPet,
    Settings,
    Sum2Settings,
    TimeSettings,
)
from xaynet_tpu.server.state_machine import StateMachineInitializer
from xaynet_tpu.storage.memory import (
    InMemoryCoordinatorStorage,
    InMemoryModelStorage,
    NoOpTrustAnchor,
)
from xaynet_tpu.storage.traits import StorageError, Store, TransientStorageError


@pytest.fixture(autouse=True)
def _no_leftover_fault_plan():
    clear_plan()
    yield
    clear_plan()


def _fast_policy(attempts: int = 4) -> RetryPolicy:
    import random

    return RetryPolicy(
        max_attempts=attempts,
        base_delay_s=0.001,
        max_delay_s=0.01,
        deadline_s=10.0,
        rng=random.Random(7),
    )


def _settings(n_sum=2, n_update=3, model_len=13) -> Settings:
    s = Settings(
        pet=ServerPet(
            sum=PhaseSettings(
                prob=0.4,
                count=CountSettings(min=n_sum, max=n_sum),
                time=TimeSettings(min=0.0, max=30.0),
            ),
            update=PhaseSettings(
                prob=0.5,
                count=CountSettings(min=n_update, max=n_update),
                time=TimeSettings(min=0.0, max=30.0),
            ),
            sum2=Sum2Settings(
                count=CountSettings(min=n_sum, max=n_sum),
                time=TimeSettings(min=0.0, max=30.0),
            ),
        )
    )
    s.model.length = model_len
    # fast in-test retries
    s.resilience.retry_base_ms = 1.0
    s.resilience.retry_max_ms = 20.0
    return s


# --------------------------------------------------------------------------
# RetryPolicy
# --------------------------------------------------------------------------


def test_retry_policy_schedule_deterministic_and_capped():
    import random

    mk = lambda: RetryPolicy(  # noqa: E731
        max_attempts=6,
        base_delay_s=0.01,
        max_delay_s=0.2,
        deadline_s=30.0,
        rng=random.Random(42),
    )
    a, b = list(mk().delays()), list(mk().delays())
    assert a == b  # seeded → reproducible
    assert len(a) == 5  # attempts - 1 retries
    assert all(0.01 <= d <= 0.2 for d in a)


def test_retry_policy_retries_transient_then_succeeds():
    calls = []

    async def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise TransientStorageError("injected transient blip")
        return "ok"

    out = asyncio.run(_fast_policy().call_async(flaky, site="t.flaky"))
    assert out == "ok" and len(calls) == 3


def test_retry_policy_permanent_error_not_retried():
    calls = []

    async def broken():
        calls.append(1)
        err = StorageError("schema corrupt")
        err.transient = False
        raise err

    with pytest.raises(StorageError):
        asyncio.run(_fast_policy().call_async(broken, site="t.broken"))
    assert len(calls) == 1


def test_retry_policy_exhaustion_raises_last_error():
    async def always():
        raise TransientStorageError("connection reset")

    with pytest.raises(TransientStorageError):
        asyncio.run(_fast_policy(attempts=3).call_async(always, site="t.always"))


def test_is_transient_classification():
    assert is_transient(TransientStorageError("x"))
    assert is_transient(ConnectionError("refused"))
    assert is_transient(StorageError("redis connection lost mid-command"))
    assert not is_transient(StorageError("global model 1_ab already exists"))
    marked = StorageError("weird")
    marked.transient = False
    assert not is_transient(marked)
    assert not is_transient(ValueError("nope"))
    # the explicit marker beats the message sniff: a maybe-executed command
    # (reply lost mid-flight) must NEVER be retried even though its message
    # smells transient — replaying a landed conditional insert would desync
    # the seed dict from the aggregate
    mid_command = StorageError("redis connection lost mid-command (not replayed): x")
    mid_command.transient = False
    assert not is_transient(mid_command)


# --------------------------------------------------------------------------
# CircuitBreaker lifecycle
# --------------------------------------------------------------------------


def test_breaker_open_half_open_close_lifecycle():
    now = [0.0]
    br = CircuitBreaker(
        component="t-lifecycle",
        failure_threshold=3,
        reset_timeout_s=5.0,
        clock=lambda: now[0],
    )
    assert br.state == "closed"
    for _ in range(3):
        br.guard()
        br.record(success=False)
    assert br.state == "open"
    with pytest.raises(BreakerOpen):
        br.guard()
    # probes bypass the gate even while open
    br.guard(probe=True)
    # after the reset timeout: half-open, one probe allowed through
    now[0] = 5.1
    assert br.state == "half-open"
    br.guard()
    with pytest.raises(BreakerOpen):  # half_open_max=1: second call rejected
        br.guard()
    # probe failure → open again
    br.record(success=False)
    assert br.state == "open"
    now[0] = 10.3
    br.guard()  # half-open again
    br.record(success=True)
    assert br.state == "closed"
    br.guard()  # closed lets everything through


def test_breaker_probes_cannot_free_half_open_slots():
    now = [0.0]
    br = CircuitBreaker(
        component="t-slots",
        failure_threshold=1,
        reset_timeout_s=1.0,
        half_open_max=1,
        clock=lambda: now[0],
    )
    br.guard()
    br.record(success=False)  # open
    now[0] = 1.1
    assert br.state == "half-open"
    held = br.guard()
    assert held  # the one half-open slot is taken
    # a probe bypasses the gate WITHOUT a slot; finishing it must not free
    # the slot the in-flight call still holds
    assert br.guard(probe=True) is False
    br.record(success=False, held_slot=False)  # probe verdict (reopens)
    now[0] = 2.2
    assert br.state == "half-open"
    assert br.guard()  # slot pool was reset on re-entry, not leaked negative


def test_breaker_resets_failure_streak_on_success():
    br = CircuitBreaker(component="t-streak", failure_threshold=3)
    for _ in range(2):
        br.record(success=False)
    br.record(success=True)
    for _ in range(2):
        br.record(success=False)
    assert br.state == "closed"  # never 3 consecutive


# --------------------------------------------------------------------------
# FaultPlan determinism
# --------------------------------------------------------------------------


def test_fault_plan_same_seed_same_schedule():
    spec = "seed=42;storage.coordinator.*:error,rate=0.3;streaming.fold:error,nth=2/4"
    a = FaultPlan.parse(spec)
    b = FaultPlan.parse(spec)
    site = "storage.coordinator.seed_dict"
    sched_a = [x.kind if x else None for x in a.schedule(site, 50)]
    sched_b = [x.kind if x else None for x in b.schedule(site, 50)]
    assert sched_a == sched_b
    assert any(sched_a)  # rate 0.3 over 50 calls fires at least once
    # different seed → different schedule (overwhelmingly likely at 50 draws)
    c = FaultPlan.parse(spec.replace("seed=42", "seed=43"))
    assert sched_a != [x.kind if x else None for x in c.schedule(site, 50)]


def test_fault_plan_nth_and_max_exact():
    plan = FaultPlan.parse("seed=1;s.x:error,nth=2/5;s.y:latency,rate=1.0,max=2,delay=0.5")
    xs = plan.schedule("s.x", 6)
    assert [bool(x) for x in xs] == [False, True, False, False, True, False]
    ys = plan.schedule("s.y", 4)
    assert [bool(y) for y in ys] == [True, True, False, False]  # max=2
    assert ys[0].delay_s == 0.5
    # decide() and schedule() agree (schedule must not mutate the plan)
    assert plan.decide("s.x") is None and plan.decide("s.x") is not None


def test_fault_plan_rule_without_trigger_fires_every_call_bounded_by_max():
    # the docstring's "fire once" form: no nth/rate → every call, max-bounded
    plan = FaultPlan.parse("seed=0;s.z:error,max=1")
    assert [bool(x) for x in plan.schedule("s.z", 3)] == [True, False, False]
    unbounded = FaultPlan.parse("seed=0;s.z:latency,delay=0.1")
    assert all(unbounded.schedule("s.z", 5))


def test_fault_plan_parse_errors():
    with pytest.raises(ValueError):
        FaultPlan.parse("no-colon-here,rate=1")
    with pytest.raises(ValueError):
        FaultPlan.parse("s.x:explode")
    with pytest.raises(ValueError):
        FaultPlan.parse("s.x:error,unknown=1")


# --------------------------------------------------------------------------
# ResilientStore
# --------------------------------------------------------------------------


def _mem_store() -> Store:
    return Store(InMemoryCoordinatorStorage(), InMemoryModelStorage(), NoOpTrustAnchor())


def test_resilient_store_retries_injected_transient_fault():
    install_plan(FaultPlan.parse("storage.coordinator.set_coordinator_state:error,nth=1"))
    rs = ResilientStore(_mem_store(), policy=_fast_policy())
    before = RETRIES.labels(site="storage.coordinator.set_coordinator_state").value

    async def run():
        await rs.coordinator.set_coordinator_state(b"state-bytes")
        return await rs.coordinator.coordinator_state()

    assert asyncio.run(run()) == b"state-bytes"
    after = RETRIES.labels(site="storage.coordinator.set_coordinator_state").value
    assert after == before + 1


def test_resilient_store_partial_write_lands_and_retry_converges():
    install_plan(FaultPlan.parse("storage.coordinator.set_coordinator_state:partial,nth=1"))
    inner = _mem_store()
    rs = ResilientStore(inner, policy=_fast_policy())

    async def run():
        await rs.coordinator.set_coordinator_state(b"v1")
        return await inner.coordinator.coordinator_state()

    # first attempt landed AND raised; the retry (idempotent SET) converges
    assert asyncio.run(run()) == b"v1"


def test_resilient_store_permanent_fault_fails_fast():
    install_plan(FaultPlan.parse("storage.models.global_model:error,nth=1,perm=1"))
    rs = ResilientStore(_mem_store(), policy=_fast_policy())

    async def run():
        await rs.models.global_model("some-id")

    with pytest.raises(StorageError, match="permanent"):
        asyncio.run(run())


def test_resilient_store_breaker_opens_and_fails_fast():
    class DeadCoordinator(InMemoryCoordinatorStorage):
        def __init__(self):
            super().__init__()
            self.calls = 0

        async def sum_dict(self):
            self.calls += 1
            raise TransientStorageError("connection refused")

    dead = DeadCoordinator()
    rs = ResilientStore(
        Store(dead, InMemoryModelStorage(), None),
        policy=_fast_policy(attempts=1),
        breaker_threshold=3,
        breaker_reset_s=60.0,
    )

    async def run():
        for _ in range(3):
            with pytest.raises(TransientStorageError):
                await rs.coordinator.sum_dict()
        with pytest.raises(BreakerOpen):
            await rs.coordinator.sum_dict()

    asyncio.run(run())
    assert dead.calls == 3  # the open breaker never touched the backend

    # component breakers are independent: the model store still answers
    async def models_ok():
        return await rs.models.global_model("nope")

    assert asyncio.run(models_ok()) is None


# --------------------------------------------------------------------------
# Checkpoint serialization + validation
# --------------------------------------------------------------------------


def _ckpt(**kw) -> ckpt_mod.RoundCheckpoint:
    rng = np.random.default_rng(3)
    base = dict(
        round_id=4,
        phase="update",
        round_seed=b"\x11" * 32,
        mask_config=[["PRIME", "F32", "B0", "M3"], ["PRIME", "F32", "B0", "M3"]],
        model_length=7,
        nb_models=2,
        seed_watermark=2,
        vect=rng.integers(0, 2**32, size=(7, 6), dtype=np.uint32),
        unit=rng.integers(0, 2**32, size=(6,), dtype=np.uint32),
    )
    base.update(kw)
    return ckpt_mod.RoundCheckpoint(**base)


def test_checkpoint_roundtrip_byte_exact():
    ck = _ckpt()
    again = ckpt_mod.RoundCheckpoint.from_bytes(ck.to_bytes())
    assert again.round_id == 4 and again.phase == "update"
    assert again.round_seed == ck.round_seed
    assert again.nb_models == 2 and again.seed_watermark == 2
    assert np.array_equal(again.vect, ck.vect)
    assert np.array_equal(again.unit, ck.unit)


def test_checkpoint_corruption_detected():
    blob = bytearray(_ckpt().to_bytes())
    blob[-3] ^= 0xFF  # flip a payload byte → digest mismatch
    with pytest.raises(ckpt_mod.CheckpointError):
        ckpt_mod.RoundCheckpoint.from_bytes(bytes(blob))
    with pytest.raises(ckpt_mod.CheckpointError):
        ckpt_mod.RoundCheckpoint.from_bytes(b"garbage")
    truncated = _ckpt().to_bytes()[:-5]
    with pytest.raises(ckpt_mod.CheckpointError):
        ckpt_mod.RoundCheckpoint.from_bytes(truncated)


def test_checkpoint_validation_rejects_inconsistency():
    from xaynet_tpu.server.coordinator import CoordinatorState

    settings = _settings(model_len=7)
    state = CoordinatorState.from_settings(settings)
    state.round_id = 4
    store = _mem_store()
    names = ckpt_mod.mask_config_names(state.round_params.mask_config)
    seed = state.round_params.seed.as_bytes()

    async def check(ck):
        return await ckpt_mod.validate(ck, state, store)

    good = _ckpt(round_seed=seed, mask_config=names, nb_models=0, seed_watermark=0)
    assert asyncio.run(check(good)) is None
    assert "round" in asyncio.run(check(_ckpt(round_id=9, round_seed=seed, mask_config=names)))
    assert "seed" in asyncio.run(check(_ckpt(mask_config=names)))  # wrong round seed
    # sum2 is a RESUMABLE phase since the whole-round journal (§9) — only
    # non-window phases are rejected outright now
    assert "phase" in asyncio.run(
        check(_ckpt(phase="idle", round_seed=seed, mask_config=names))
    )
    # a v1 (XNCKPT1) blob predates the journal: update-only resume
    v1_sum2 = _ckpt(
        phase="sum2", round_seed=seed, mask_config=names, version=1,
        nb_models=0, seed_watermark=0,
    )
    assert "phase" in asyncio.run(check(v1_sum2))
    # watermark mismatch: checkpoint claims 2 models but the store has none
    stale = _ckpt(round_seed=seed, mask_config=names, nb_models=2, seed_watermark=2)
    assert "watermark" in asyncio.run(check(stale))


# --------------------------------------------------------------------------
# Chaos round: per-phase transient storage faults, byte-identical model
# --------------------------------------------------------------------------


async def _drive_full_round(settings: Settings, store: Store):
    """One full PET round over the in-process service pipeline; returns the
    unmasked global model bytes."""
    from xaynet_tpu.sdk.client import InProcessClient
    from xaynet_tpu.sdk.simulation import keys_for_task
    from xaynet_tpu.sdk.state_machine import PetSettings, StateMachine as ParticipantSM
    from xaynet_tpu.sdk.traits import ModelStore
    from xaynet_tpu.server.services import Fetcher, PetMessageHandler

    class ArrayModelStore(ModelStore):
        def __init__(self, model):
            self.model = model

        async def load_model(self):
            return self.model

    n_sum = settings.pet.sum.count.min
    n_update = settings.pet.update.count.min
    machine, request_tx, events = await StateMachineInitializer(settings, store).init()
    handler = PetMessageHandler(events, request_tx)
    fetcher = Fetcher(events)
    machine_task = asyncio.create_task(machine.run())
    try:
        while fetcher.phase().value != "sum":
            await asyncio.sleep(0.01)
        params = fetcher.round_params()
        seed = params.seed.as_bytes()
        rng = np.random.default_rng(1234)
        participants = []
        for i in range(n_sum):
            keys = keys_for_task(seed, params.sum, params.update, "sum", start=i * 1000)
            participants.append(
                ParticipantSM(
                    PetSettings(keys=keys),
                    InProcessClient(fetcher, handler),
                    ArrayModelStore(None),
                )
            )
        expected = np.zeros(settings.model.length)
        for i in range(n_update):
            keys = keys_for_task(
                seed, params.sum, params.update, "update", start=(10 + i) * 1000
            )
            local = rng.uniform(-1, 1, settings.model.length).astype(np.float32)
            expected += local.astype(np.float64) / n_update
            participants.append(
                ParticipantSM(
                    PetSettings(keys=keys, scalar=Fraction(1, n_update)),
                    InProcessClient(fetcher, handler),
                    ArrayModelStore(local),
                )
            )

        async def drive(sm):
            for _ in range(800):
                try:
                    await sm.transition()
                except Exception:
                    pass
                if fetcher.model() is not None and sm.phase.value == "awaiting":
                    return
                await asyncio.sleep(0.01)

        await asyncio.gather(*(drive(p) for p in participants))
        while fetcher.model() is None:
            await asyncio.sleep(0.01)
        return np.asarray(fetcher.model()), expected
    finally:
        machine_task.cancel()
        try:
            await machine_task
        except (asyncio.CancelledError, Exception):
            pass


# one transient fault in every phase's storage traffic: idle (state write),
# sum (participant insert + dict read), update (seed-dict insert + read),
# sum2 (mask score), unmask (best-masks read, model write, pointer write)
_CHAOS_SPEC = (
    "seed=11;"
    "storage.coordinator.set_coordinator_state:error,nth=1;"
    "storage.coordinator.add_sum_participant:error,nth=1;"
    "storage.coordinator.sum_dict:error,nth=2;"
    "storage.coordinator.add_local_seed_dict:error,nth=2;"
    "storage.coordinator.seed_dict:error,nth=1;"
    "storage.coordinator.incr_mask_score:error,nth=1;"
    "storage.coordinator.best_masks:error,nth=1;"
    "storage.models.set_global_model:error,nth=1;"
    "storage.coordinator.set_latest_global_model_id:error,nth=1;"
    "storage.coordinator.*:latency,rate=0.05,delay=0.002,max=20"
)


def test_chaos_round_transient_faults_byte_identical_model():
    settings = _settings()

    clean_model, expected = asyncio.run(
        asyncio.wait_for(_drive_full_round(settings, _mem_store()), timeout=90)
    )
    np.testing.assert_allclose(clean_model, expected, atol=1e-9)

    install_plan(FaultPlan.parse(_CHAOS_SPEC))
    try:
        store = ResilientStore(_mem_store(), policy=_fast_policy(attempts=5))
        chaos_model, _ = asyncio.run(
            asyncio.wait_for(_drive_full_round(settings, store), timeout=90)
        )
    finally:
        clear_plan()
    # BYTE-identical: masks cancel exactly in the group, the fixed-point
    # decode is deterministic, and every injected fault was absorbed by an
    # in-place retry (no round restart — a restart would change the round
    # seed but not the model; identity here proves the same round completed)
    assert chaos_model.tobytes() == clean_model.tobytes()


# --------------------------------------------------------------------------
# Kill-and-restore: resume mid-update-phase from the checkpoint
# --------------------------------------------------------------------------


def test_kill_and_restore_resumes_update_phase_from_checkpoint():
    from xaynet_tpu.sdk.client import InProcessClient
    from xaynet_tpu.sdk.simulation import keys_for_task
    from xaynet_tpu.sdk.state_machine import PetSettings, StateMachine as ParticipantSM
    from xaynet_tpu.sdk.traits import ModelStore
    from xaynet_tpu.server.phases.update import UpdatePhase
    from xaynet_tpu.server.services import Fetcher, PetMessageHandler

    class ArrayModelStore(ModelStore):
        def __init__(self, model):
            self.model = model

        async def load_model(self):
            return self.model

    n_update = 4
    settings = _settings(n_sum=1, n_update=n_update)
    settings.restore.enable = True
    settings.resilience.checkpoint_enabled = True
    settings.resilience.checkpoint_every_batches = 1
    settings.aggregation.batch_size = 1  # checkpoint after every update
    model_len = settings.model.length
    store = _mem_store()
    rng = np.random.default_rng(7)
    locals_ = [
        rng.uniform(-1, 1, model_len).astype(np.float32) for _ in range(n_update)
    ]
    expected = sum(w.astype(np.float64) / n_update for w in locals_)

    async def phase_one():
        """Sum + first half of update, then KILL the machine."""
        machine, request_tx, events = await StateMachineInitializer(settings, store).init()
        handler = PetMessageHandler(events, request_tx)
        fetcher = Fetcher(events)
        machine_task = asyncio.create_task(machine.run())
        try:
            while fetcher.phase().value != "sum":
                await asyncio.sleep(0.01)
            params = fetcher.round_params()
            seed = params.seed.as_bytes()
            summer = ParticipantSM(
                PetSettings(keys=keys_for_task(seed, params.sum, params.update, "sum")),
                InProcessClient(fetcher, handler),
                ArrayModelStore(None),
            )
            # drive the summer through Sum (it submits its ephemeral key)
            for _ in range(20):
                await summer.transition()
                if summer.phase.value == "sum2":
                    break
                await asyncio.sleep(0.01)
            assert summer.phase.value == "sum2"
            summer_blob = summer.save()
            while fetcher.phase().value != "update":
                await asyncio.sleep(0.01)
            # two of four updates arrive, then the coordinator dies
            for i in range(2):
                sm = ParticipantSM(
                    PetSettings(
                        keys=keys_for_task(
                            seed, params.sum, params.update, "update", start=(10 + i) * 1000
                        ),
                        scalar=Fraction(1, n_update),
                    ),
                    InProcessClient(fetcher, handler),
                    ArrayModelStore(locals_[i]),
                )
                for _ in range(40):
                    await sm.transition()
                    if sm.phase.value == "awaiting":
                        break
                    await asyncio.sleep(0.01)
            # wait for the post-update-2 checkpoint to be durable
            for _ in range(200):
                blob = await store.coordinator.round_checkpoint()
                if blob is not None:
                    ck = ckpt_mod.RoundCheckpoint.from_bytes(blob)
                    if ck.nb_models == 2:
                        return seed, summer_blob, ck
                await asyncio.sleep(0.01)
            raise AssertionError("no checkpoint with 2 models appeared")
        finally:
            machine_task.cancel()
            try:
                await machine_task
            except (asyncio.CancelledError, Exception):
                pass

    async def phase_two(seed, summer_blob, pre_kill):
        """Restart from the same store: resume + finish the round."""
        machine, request_tx, events = await StateMachineInitializer(settings, store).init()
        # the machine must start INSIDE the update phase, aggregate restored
        phase = machine.phase
        assert isinstance(phase, UpdatePhase)
        vect, unit, nb = phase.aggregator.snapshot_state()
        assert nb == 2
        assert np.array_equal(vect, pre_kill.vect)
        assert np.array_equal(unit, pre_kill.unit)

        handler = PetMessageHandler(events, request_tx)
        fetcher = Fetcher(events)
        params = fetcher.round_params()
        assert params.seed.as_bytes() == seed  # same round, not restarted
        machine_task = asyncio.create_task(machine.run())
        try:
            participants = [
                ParticipantSM.restore(
                    summer_blob, InProcessClient(fetcher, handler), ArrayModelStore(None)
                )
            ]
            for i in range(2, n_update):
                participants.append(
                    ParticipantSM(
                        PetSettings(
                            keys=keys_for_task(
                                seed, params.sum, params.update, "update",
                                start=(10 + i) * 1000,
                            ),
                            scalar=Fraction(1, n_update),
                        ),
                        InProcessClient(fetcher, handler),
                        ArrayModelStore(locals_[i]),
                    )
                )

            async def drive(sm):
                for _ in range(800):
                    try:
                        await sm.transition()
                    except Exception:
                        pass
                    if fetcher.model() is not None:
                        return
                    await asyncio.sleep(0.01)

            await asyncio.gather(*(drive(p) for p in participants))
            while fetcher.model() is None:
                await asyncio.sleep(0.01)
            # the journal's lifetime is the round: it retires after the
            # model publishes (Unmask deletes it, Idle sweeps as backstop).
            # The model becomes visible a beat before the delete lands, so
            # poll with a bound instead of asserting instantly.
            for _ in range(200):
                if await store.coordinator.round_checkpoint() is None:
                    break
                await asyncio.sleep(0.01)
            assert await store.coordinator.round_checkpoint() is None
            return np.asarray(fetcher.model())
        finally:
            machine_task.cancel()
            try:
                await machine_task
            except (asyncio.CancelledError, Exception):
                pass

    async def run():
        seed, summer_blob, pre_kill = await phase_one()
        return await phase_two(seed, summer_blob, pre_kill)

    model = asyncio.run(asyncio.wait_for(run(), timeout=120))
    # the 2 pre-kill updates were NOT resent: the final model containing all
    # 4 proves the restored aggregate carried them across the restart
    np.testing.assert_allclose(model, expected, atol=1e-9)


# --------------------------------------------------------------------------
# Streaming degradation ladder
# --------------------------------------------------------------------------


def _streaming_fixture(total=12, n=103, bs=4, seed=5):
    import jax

    from xaynet_tpu.core.mask import (
        Aggregation,
        BoundType,
        DataType,
        GroupType,
        Masker,
        MaskConfig,
        ModelType,
        Scalar,
    )
    from xaynet_tpu.parallel.aggregator import ShardedAggregator
    from xaynet_tpu.parallel.mesh import make_mesh
    from xaynet_tpu.parallel.streaming import StreamingAggregator

    cfg = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M6)
    rng = np.random.default_rng(seed)
    host = Aggregation(cfg.pair(), n)
    stacks = []
    for _ in range(total):
        w = rng.uniform(-1, 1, size=n).astype(np.float32)
        _, masked = Masker(cfg.pair()).mask(Scalar(1, total), w)
        host.aggregate(masked)
        stacks.append(masked.vect.data)
    agg = ShardedAggregator(cfg, n, mesh=make_mesh(jax.devices()[:1]), kernel="xla")
    stream = StreamingAggregator(agg, staging_buffers=2, dispatch_ahead=1, max_batch=bs)
    return stacks, host, agg, stream, bs


def test_streaming_fold_failure_degrades_to_sync_and_round_completes():
    from xaynet_tpu.parallel.streaming import DEGRADATIONS

    stacks, host, agg, stream, bs = _streaming_fixture()
    # one injected failure on the second fold; the third batch then takes
    # the synchronous path
    install_plan(FaultPlan.parse("streaming.fold:error,nth=2"))
    degr_before = DEGRADATIONS.value
    try:
        for i in range(0, len(stacks), bs):
            stream.submit_batch(np.stack(stacks[i : i + bs]))
        stream.drain()
    finally:
        clear_plan()
    assert stream.degraded
    assert DEGRADATIONS.value == degr_before + 1
    # byte-identical aggregate: the failed batch was retried synchronously,
    # nothing lost or double-folded
    assert agg.nb_models == len(stacks)
    assert np.array_equal(agg.snapshot(), host.object.vect.data)
    stream.close()


def test_streaming_poisoning_names_batch_and_cause():
    stacks, _, agg, stream, bs = _streaming_fixture(total=8)
    stream.submit_batch(np.stack(stacks[0:bs]))
    stream.drain()

    def boom(acc, staged):
        raise RuntimeError("fold died (stand-in)")

    agg._fold_fn = boom  # both the streaming fold AND the sync retry die
    agg._packed_fold_fn = boom  # packed staging is the default layout
    stream.submit_batch(np.stack(stacks[bs : 2 * bs]))
    from xaynet_tpu.parallel.streaming import StreamingError

    with pytest.raises(StreamingError, match=r"batch 2.*RuntimeError.*fold died"):
        stream.drain()
    # subsequent submits carry the same root cause, not a bare message
    with pytest.raises(StreamingError, match=r"batch 2.*fold died") as exc_info:
        stream.submit_batch(np.stack(stacks[0:bs]))
    assert isinstance(exc_info.value.__cause__, RuntimeError)
    stream.close()


# --------------------------------------------------------------------------
# Unmask pointer retry (satellite)
# --------------------------------------------------------------------------


def test_unmask_pointer_update_retried_and_counted():
    from xaynet_tpu.core.mask.masking import Aggregation as MaskAggregation
    from xaynet_tpu.server.coordinator import CoordinatorState
    from xaynet_tpu.server.events import EventPublisher, PhaseName
    from xaynet_tpu.server.phases.base import Shared
    from xaynet_tpu.server.phases.unmask import POINTER_UPDATE_FAILURES, Unmask
    from xaynet_tpu.server.requests import RequestReceiver

    class FlakyPointer(InMemoryCoordinatorStorage):
        def __init__(self, fail_times):
            super().__init__()
            self.fail_times = fail_times
            self.calls = 0

        async def set_latest_global_model_id(self, model_id):
            self.calls += 1
            if self.calls <= self.fail_times:
                raise TransientStorageError("pointer write lost")
            await super().set_latest_global_model_id(model_id)

    def make_phase(coord):
        settings = _settings()
        state = CoordinatorState.from_settings(settings)
        # the retry lives in the ResilientStore layer (the phase adds the
        # failure COUNT on top) — wrap like production does
        store = ResilientStore(
            Store(coord, InMemoryModelStorage(), None), policy=_fast_policy()
        )
        shared = Shared(
            state=state,
            request_rx=RequestReceiver(),
            events=EventPublisher(
                round_id=0, keys=state.keys, params=state.round_params,
                phase=PhaseName.IDLE,
            ),
            store=store,
            settings=settings,
            metrics=None,
        )
        phase = Unmask(shared, MaskAggregation(state.round_params.mask_config, 4))
        phase.global_model = np.zeros(settings.model.length)
        return phase, coord

    # two transient failures → retried → pointer lands
    phase, coord = make_phase(FlakyPointer(fail_times=2))
    asyncio.run(phase._save_global_model())
    assert coord.calls == 3
    assert asyncio.run(coord.latest_global_model_id()) is not None

    # permanently broken → phase still completes, failure COUNTED
    before = POINTER_UPDATE_FAILURES.value
    phase, coord = make_phase(FlakyPointer(fail_times=10**9))
    asyncio.run(phase._save_global_model())
    assert POINTER_UPDATE_FAILURES.value == before + 1
    assert asyncio.run(coord.latest_global_model_id()) is None


# --------------------------------------------------------------------------
# Ingest worker supervision (worker-death injection)
# --------------------------------------------------------------------------


def test_ingest_worker_death_restarted_by_supervisor():
    from xaynet_tpu.ingest.pipeline import WORKER_RESTARTS, IngestPipeline
    from xaynet_tpu.server.settings import IngestSettings

    class _Phase:
        def __init__(self):
            from xaynet_tpu.server.events import PhaseName

            self.event = PhaseName.SUM

    class _Watch:
        def get_latest(self):
            return _Phase()

    class _Events:
        phase = _Watch()

    install_plan(FaultPlan.parse("ingest.worker.0:error,nth=1"))
    before = WORKER_RESTARTS.labels(shard="0", tenant="default").value

    async def run():
        pipeline = IngestPipeline(
            handler=None,  # never reached: no messages are submitted
            request_tx=None,
            events=_Events(),
            settings=IngestSettings(enabled=True, shards=1),
        )
        await pipeline.start()
        for _ in range(100):
            if WORKER_RESTARTS.labels(shard="0", tenant="default").value > before:
                break
            await asyncio.sleep(0.02)
        assert pipeline.running
        await pipeline.stop()

    asyncio.run(asyncio.wait_for(run(), timeout=30))
    assert WORKER_RESTARTS.labels(shard="0", tenant="default").value == before + 1
