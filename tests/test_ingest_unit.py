"""Ingest subsystem unit coverage: admission hysteresis, bounded shards,
linger batching, update coalescing, settings validation, pre-filter."""

import asyncio
from types import SimpleNamespace

import pytest

from xaynet_tpu.ingest import (
    AdmissionController,
    IngestPipeline,
    IntakeShard,
    ShardedIntake,
    UpdateCoalescer,
    Verdict,
)
from xaynet_tpu.ingest.intake import ShardFull
from xaynet_tpu.server.events import PhaseName
from xaynet_tpu.server.requests import CoalescedUpdates, RequestError, UpdateRequest
from xaynet_tpu.server.settings import IngestSettings, SettingsError


# --- admission ---------------------------------------------------------------


def test_admission_watermark_hysteresis():
    ctl = AdmissionController(capacity=10, high_watermark=0.8, low_watermark=0.5)
    assert ctl.high_mark == 8 and ctl.low_mark == 5
    assert ctl.admit(7).verdict is Verdict.ADMITTED
    assert not ctl.saturated
    # crossing high flips into shedding ...
    assert ctl.admit(8).shed
    assert ctl.saturated
    # ... and stays shedding between low and high (hysteresis)
    assert ctl.admit(6).shed
    # draining to the low watermark clears the state without a new arrival
    ctl.observe(5)
    assert not ctl.saturated
    assert ctl.admit(5).verdict is Verdict.ADMITTED


def test_admission_full_capacity_always_sheds():
    ctl = AdmissionController(capacity=4, high_watermark=1.0, low_watermark=1.0)
    assert ctl.high_mark == 4  # 1.0 means "full", never capacity+1
    assert ctl.admit(3).verdict is Verdict.ADMITTED
    assert ctl.admit(4).shed


def test_admission_retry_after_scales_with_overload():
    ctl = AdmissionController(
        capacity=100, high_watermark=0.5, low_watermark=0.25, retry_after_seconds=2.0
    )
    shallow = ctl.retry_after(50)
    deep = ctl.retry_after(100)
    assert shallow >= 2.0
    assert deep > shallow


def test_admission_validates_arguments():
    with pytest.raises(ValueError):
        AdmissionController(capacity=0)
    with pytest.raises(ValueError):
        AdmissionController(capacity=10, high_watermark=0.3, low_watermark=0.6)


# --- intake ------------------------------------------------------------------


def test_shard_hard_bound_and_max_occupancy():
    async def run():
        shard = IntakeShard(0, bound=2)
        shard.put_nowait(b"a")
        shard.put_nowait(b"b")
        with pytest.raises(ShardFull):
            shard.put_nowait(b"c")
        assert shard.occupancy == 2
        assert shard.max_occupancy == 2

    asyncio.run(run())


def test_sharded_intake_spreads_and_fails_only_when_all_full():
    async def run():
        intake = ShardedIntake(2, bound_per_shard=2)
        for i in range(4):
            intake.put_nowait(bytes([i]))
        assert intake.occupancy == 4
        assert [s.occupancy for s in intake.shards] == [2, 2]  # round robin
        with pytest.raises(ShardFull):
            intake.put_nowait(b"x")
        assert intake.max_occupancy == 2  # never above the per-shard bound

    asyncio.run(run())


def test_get_batch_linger_and_cap():
    async def run():
        shard = IntakeShard(0, bound=16)
        for i in range(5):
            shard.put_nowait(bytes([i]))
        batch = await shard.get_batch(max_batch=3, linger_s=0.0)
        assert len(batch) == 3  # capped
        batch = await shard.get_batch(max_batch=8, linger_s=0.01)
        assert len(batch) == 2  # linger expires with what's there

        async def late_put():
            await asyncio.sleep(0.01)
            shard.put_nowait(b"late")

        asyncio.ensure_future(late_put())
        batch = await asyncio.wait_for(shard.get_batch(max_batch=2, linger_s=1.0), 5)
        assert batch == [b"late"]  # blocks for the first item, then returns

    asyncio.run(run())


# --- coalescer ---------------------------------------------------------------


class _ChannelStub:
    """Records coalesced envelopes; resolves member futures like a phase."""

    def __init__(self, member_error=None, batch_error=None):
        self.batches = []
        self.member_error = member_error
        self.batch_error = batch_error

    async def request(self, req):
        assert isinstance(req, CoalescedUpdates)
        self.batches.append(req)
        if self.batch_error is not None:
            raise self.batch_error
        for fut in req.responses:
            if self.member_error is None:
                fut.set_result(None)
            else:
                fut.set_exception(self.member_error)


def _update(i: int) -> UpdateRequest:
    return UpdateRequest(participant_pk=bytes([i]) * 32, local_seed_dict={}, masked_model=None)


def test_coalescer_batches_at_max_batch():
    async def run():
        tx = _ChannelStub()
        co = UpdateCoalescer(tx, max_batch=3, linger_s=60.0)
        for i in range(7):
            await co.add(_update(i))
        assert [len(b) for b in tx.batches] == [3, 3]
        assert co.pending == 1
        await co.flush()
        assert [len(b) for b in tx.batches] == [3, 3, 1]
        assert co.batches_sent == 3 and co.members_sent == 7

    asyncio.run(run())


def test_coalescer_linger_flush():
    async def run():
        tx = _ChannelStub()
        co = UpdateCoalescer(tx, max_batch=100, linger_s=0.01)
        await co.add(_update(0))
        await co.add(_update(1))
        assert tx.batches == []
        await asyncio.sleep(0.1)
        assert [len(b) for b in tx.batches] == [2]

    asyncio.run(run())


def test_coalescer_batch_rejection_reaches_members():
    async def run():
        err = RequestError(RequestError.Kind.MESSAGE_REJECTED, "phase ended")
        tx = _ChannelStub(batch_error=err)
        co = UpdateCoalescer(tx, max_batch=2, linger_s=60.0)
        f1 = await co.add(_update(0))
        f2 = await co.add(_update(1))  # triggers the flush that is rejected
        for fut in (f1, f2):
            assert fut.done()
            with pytest.raises(RequestError, match="phase ended"):
                fut.result()

    asyncio.run(run())


def test_coalescer_close_after_channel_shutdown_does_not_hang():
    """pipeline.stop() after the runner closed the request channel (cancel
    path) must reject the buffered members promptly, never await a state
    machine that will not answer."""
    from xaynet_tpu.server.requests import RequestReceiver

    async def run():
        rx = RequestReceiver()
        co = UpdateCoalescer(rx.sender(), max_batch=10, linger_s=60.0)
        fut = await co.add(_update(0))
        rx.close()
        await asyncio.wait_for(co.close(), timeout=1.0)
        assert fut.done()
        with pytest.raises(RequestError, match="shut down"):
            fut.result()

    asyncio.run(run())


# --- settings + pre-filter ---------------------------------------------------


def test_ingest_settings_validation():
    IngestSettings().validate()
    with pytest.raises(SettingsError):
        IngestSettings(shards=0).validate()
    with pytest.raises(SettingsError):
        IngestSettings(queue_bound=0).validate()
    with pytest.raises(SettingsError):
        IngestSettings(high_watermark=0.4, low_watermark=0.6).validate()
    with pytest.raises(SettingsError):
        IngestSettings(max_batch=0).validate()
    with pytest.raises(SettingsError):
        IngestSettings(retry_after_seconds=0).validate()


def _stub_events(phase: PhaseName):
    latest = SimpleNamespace(event=phase)
    return SimpleNamespace(phase=SimpleNamespace(get_latest=lambda: latest))


def test_pipeline_prefilter_drops_before_any_queue_slot():
    async def run():
        pipe = IngestPipeline(
            handler=None,
            request_tx=None,
            events=_stub_events(PhaseName.IDLE),
            settings=IngestSettings(enabled=True, shards=1, queue_bound=4),
        )
        # no phase accepts messages: dropped pre-decrypt, nothing enqueued
        verdict = await pipe.submit(b"\x00" * 400)
        assert verdict.verdict is Verdict.DROPPED
        assert pipe.intake.occupancy == 0

        pipe.events = _stub_events(PhaseName.SUM)
        # structurally impossible ciphertext: shorter than seal + header
        verdict = await pipe.submit(b"\x00" * 16)
        assert verdict.verdict is Verdict.DROPPED
        assert pipe.intake.occupancy == 0

        verdict = await pipe.submit(b"\x00" * 400)
        assert verdict.verdict is Verdict.ADMITTED
        assert pipe.intake.occupancy == 1

    asyncio.run(run())


# --------------------------------------------------------------------------
# Cross-tenant isolation (docs/DESIGN.md §19): one tenant's close/purge
# paths must never strand another tenant's in-flight requests or budget.
# --------------------------------------------------------------------------


def test_request_channel_close_is_scoped_to_its_tenant():
    from xaynet_tpu.server.requests import RequestReceiver, SumRequest
    from xaynet_tpu.telemetry.registry import get_registry

    def depth(tenant):
        return get_registry().sample_value(
            "xaynet_request_queue_depth", {"tenant": tenant}
        )

    async def run():
        rx_a = RequestReceiver(tenant="iso-a")
        rx_b = RequestReceiver(tenant="iso-b")
        tx_a, tx_b = rx_a.sender(), rx_b.sender()
        req = SumRequest(participant_pk=b"\x01" * 32, ephm_pk=b"\x02" * 32)
        fut_a = asyncio.ensure_future(tx_a.request(req))
        futs_b = [asyncio.ensure_future(tx_b.request(req)) for _ in range(2)]
        await asyncio.sleep(0)
        assert depth("iso-a") == 1 and depth("iso-b") == 2

        rx_a.close()  # tenant A shuts down...
        await asyncio.sleep(0)
        # ...A's queued request is rejected (never hangs on a dead machine)
        with pytest.raises(RequestError):
            await fut_a
        # ...but tenant B's requests are STILL PENDING, and only A's depth
        # gauge child zeroed
        assert all(not f.done() for f in futs_b)
        assert depth("iso-a") == 0
        assert depth("iso-b") == 2

        env = await rx_b.next_request()
        env.response.set_result(None)
        await futs_b[0]
        assert depth("iso-b") == 1
        rx_b.close()
        with pytest.raises(RequestError):
            await futs_b[1]
        assert depth("iso-b") == 0

    asyncio.run(run())


def test_pipeline_stop_returns_tenant_budget_without_touching_others():
    from xaynet_tpu.tenancy import TenantAdmissionBudget

    async def run():
        budget = TenantAdmissionBudget(capacity=8, max_share=0.5)
        pipe_a = IngestPipeline(
            handler=None,
            request_tx=None,
            events=_stub_events(PhaseName.SUM),
            settings=IngestSettings(enabled=True, shards=1, queue_bound=4),
            tenant="bud-a",
            budget=budget,
        )
        pipe_b = IngestPipeline(
            handler=None,
            request_tx=None,
            events=_stub_events(PhaseName.SUM),
            settings=IngestSettings(enabled=True, shards=1, queue_bound=4),
            tenant="bud-b",
            budget=budget,
        )
        # workers are NOT started: messages sit queued in the intakes
        for _ in range(3):
            assert (await pipe_a.submit(b"\x00" * 400)).verdict is Verdict.ADMITTED
        assert (await pipe_b.submit(b"\x00" * 400)).verdict is Verdict.ADMITTED
        assert budget.held("bud-a") == 3 and budget.held("bud-b") == 1
        # tenant A is at its 50% share (4): one more sheds with Retry-After
        assert (await pipe_a.submit(b"\x00" * 400)).verdict is Verdict.ADMITTED
        shed = await pipe_a.submit(b"\x00" * 400)
        assert shed.verdict is Verdict.SHED and shed.retry_after > 0
        # ...while tenant B still has budget
        assert (await pipe_b.submit(b"\x00" * 400)).verdict is Verdict.ADMITTED

        await pipe_a.stop()  # tenant A dies with messages still queued
        # A's entire held share returns to the process budget; B untouched
        assert budget.held("bud-a") == 0
        assert budget.held("bud-b") == 2
        assert (await pipe_b.submit(b"\x00" * 400)).verdict is Verdict.ADMITTED
        await pipe_b.stop()
        assert budget.held("bud-b") == 0

    asyncio.run(run())
