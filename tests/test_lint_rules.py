"""The lint gate's coordinator-queue rule: bare unbounded asyncio.Queue()
under xaynet_tpu/server/ and xaynet_tpu/ingest/ is rejected unless the line
carries the '# lint: unbounded-ok' allowlist comment."""

import importlib.util
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

spec = importlib.util.spec_from_file_location("xn_lint", REPO / "tools" / "lint.py")
xn_lint = importlib.util.module_from_spec(spec)
sys.modules["xn_lint"] = spec.loader.exec_module(xn_lint) or xn_lint


def _check(tmp_path, monkeypatch, rel: str, source: str) -> list[str]:
    monkeypatch.setattr(xn_lint, "REPO", tmp_path)
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(source)
    return xn_lint.check_file(path)


def test_unbounded_queue_rejected_in_server_tree(tmp_path, monkeypatch):
    problems = _check(
        tmp_path,
        monkeypatch,
        "xaynet_tpu/server/foo.py",
        "import asyncio\nq = asyncio.Queue()\n",
    )
    assert any("unbounded asyncio.Queue()" in p for p in problems)


def test_unbounded_queue_rejected_in_ingest_tree(tmp_path, monkeypatch):
    problems = _check(
        tmp_path,
        monkeypatch,
        "xaynet_tpu/ingest/foo.py",
        "from asyncio import Queue\nq = Queue()\n",
    )
    assert any("unbounded asyncio.Queue()" in p for p in problems)


def test_literal_zero_maxsize_counts_as_unbounded(tmp_path, monkeypatch):
    source = (
        "import asyncio\n"
        "a = asyncio.Queue(0)\n"
        "b = asyncio.Queue(maxsize=0)\n"
        "c = asyncio.Queue(maxsize=-1)\n"
    )
    problems = _check(tmp_path, monkeypatch, "xaynet_tpu/ingest/foo.py", source)
    assert sum("unbounded asyncio.Queue()" in p for p in problems) == 3


def test_bounded_and_allowlisted_queues_pass(tmp_path, monkeypatch):
    source = (
        "import asyncio\n"
        "a = asyncio.Queue(maxsize=8)\n"
        "b = asyncio.Queue(16)\n"
        "c = asyncio.Queue()  # lint: unbounded-ok\n"
    )
    problems = _check(tmp_path, monkeypatch, "xaynet_tpu/server/foo.py", source)
    assert not any("unbounded" in p for p in problems)


def test_rule_scoped_to_coordinator_trees(tmp_path, monkeypatch):
    problems = _check(
        tmp_path,
        monkeypatch,
        "xaynet_tpu/sdk/foo.py",
        "import asyncio\nq = asyncio.Queue()\n",
    )
    assert not any("unbounded" in p for p in problems)


def test_repo_tree_is_clean():
    """The real tree passes its own gate (same assertion CI would make)."""
    targets = [REPO / "xaynet_tpu" / "server", REPO / "xaynet_tpu" / "ingest"]
    problems = []
    for target in targets:
        for path in sorted(target.rglob("*.py")):
            problems.extend(xn_lint.check_file(path))
    assert problems == []


# --- the device_put staging rule ---------------------------------------------


def test_device_put_rejected_in_server_tree(tmp_path, monkeypatch):
    problems = _check(
        tmp_path,
        monkeypatch,
        "xaynet_tpu/server/foo.py",
        "import jax\nx = jax.device_put(batch)\n",
    )
    assert any("device_put" in p for p in problems)


def test_device_put_rejected_in_ingest_tree_bare_name(tmp_path, monkeypatch):
    problems = _check(
        tmp_path,
        monkeypatch,
        "xaynet_tpu/ingest/foo.py",
        "from jax import device_put\nx = device_put(batch, sharding)\n",
    )
    assert any("device_put" in p for p in problems)


def test_device_put_allowlisted_and_out_of_tree_pass(tmp_path, monkeypatch):
    allow = _check(
        tmp_path,
        monkeypatch,
        "xaynet_tpu/server/foo.py",
        "import jax\nx = jax.device_put(tiny)  # lint: device-put-ok\n",
    )
    assert not any("device_put" in p for p in allow)
    # the parallel tree (the pipeline itself) is exempt by scope
    elsewhere = _check(
        tmp_path,
        monkeypatch,
        "xaynet_tpu/parallel/foo.py",
        "import jax\nx = jax.device_put(batch)\n",
    )
    assert not any("device_put" in p for p in elsewhere)


# --- silent broad-exception swallow rule (server/ + storage/) --------------


def test_silent_swallow_rejected_in_server_tree(tmp_path, monkeypatch):
    source = (
        "try:\n"
        "    x = 1\n"
        "except Exception:\n"
        "    pass\n"
    )
    problems = _check(tmp_path, monkeypatch, "xaynet_tpu/server/foo.py", source)
    assert any("silent broad-exception swallow" in p for p in problems)


def test_silent_swallow_rejected_in_storage_tree_tuple_and_continue(tmp_path, monkeypatch):
    source = (
        "for i in range(3):\n"
        "    try:\n"
        "        x = 1\n"
        "    except (ValueError, BaseException):\n"
        "        continue\n"
    )
    problems = _check(tmp_path, monkeypatch, "xaynet_tpu/storage/foo.py", source)
    assert any("silent broad-exception swallow" in p for p in problems)


def test_narrow_logged_and_allowlisted_swallows_pass(tmp_path, monkeypatch):
    source = (
        "import logging\n"
        "try:\n"
        "    x = 1\n"
        "except ValueError:\n"  # narrow: allowed
        "    pass\n"
        "try:\n"
        "    x = 2\n"
        "except Exception as e:\n"  # handled: allowed
        "    logging.warning('boom %s', e)\n"
        "try:\n"
        "    x = 3\n"
        "except Exception:  # lint: swallow-ok\n"  # annotated: allowed
        "    pass\n"
    )
    problems = _check(tmp_path, monkeypatch, "xaynet_tpu/server/foo.py", source)
    assert not any("swallow" in p for p in problems)


def test_swallow_rule_scoped_to_server_and_storage(tmp_path, monkeypatch):
    source = "try:\n    x = 1\nexcept Exception:\n    pass\n"
    for rel in ("xaynet_tpu/parallel/foo.py", "tools/foo.py", "xaynet_tpu/ingest/foo.py"):
        problems = _check(tmp_path, monkeypatch, rel, source)
        assert not any("swallow" in p for p in problems), rel


# --- the raw-HTTP/socket SDK transport rule ----------------------------------


def test_raw_http_rejected_in_sdk_tree(tmp_path, monkeypatch):
    source = (
        "import asyncio\n"
        "import socket\n"
        "import urllib.request\n"
        "async def a():\n"
        "    r, w = await asyncio.open_connection('h', 80)\n"
        "def b():\n"
        "    urllib.request.urlopen('http://h')\n"
        "def c():\n"
        "    socket.create_connection(('h', 80))\n"
    )
    problems = _check(tmp_path, monkeypatch, "xaynet_tpu/sdk/foo.py", source)
    assert sum("resilient client wrapper" in p for p in problems) == 3


def test_raw_http_allowlisted_and_out_of_tree_pass(tmp_path, monkeypatch):
    annotated = (
        "import asyncio\n"
        "async def a():\n"
        "    r, w = await asyncio.open_connection('h', 80)  # lint: raw-http-ok\n"
    )
    problems = _check(tmp_path, monkeypatch, "xaynet_tpu/sdk/foo.py", annotated)
    assert not any("resilient client wrapper" in p for p in problems)

    bare = (
        "import socket\n"
        "def c():\n"
        "    socket.create_connection(('h', 80))\n"
    )
    for rel in ("xaynet_tpu/server/foo.py", "tools/foo.py", "tests/foo.py"):
        problems = _check(tmp_path, monkeypatch, rel, bare)
        assert not any("resilient client wrapper" in p for p in problems), rel


def test_sdk_tree_is_clean_under_raw_http_rule():
    target = REPO / "xaynet_tpu" / "sdk"
    problems = []
    for path in sorted(target.rglob("*.py")):
        problems.extend(xn_lint.check_file(path))
    assert problems == []


# --- edge fold-accounting rule ---------------------------------------------


def test_direct_fold_rejected_in_edge_tree(tmp_path, monkeypatch):
    source = (
        "def f(agg, obj, stack, units, ol):\n"
        "    agg.aggregate(obj)\n"
        "    agg.aggregate_batch(stack, units)\n"
        "    mod_add(stack, stack, ol)\n"
        "    agg.fold_partial(obj, 3)\n"
    )
    problems = _check(tmp_path, monkeypatch, "xaynet_tpu/edge/foo.py", source)
    assert sum("partial-aggregate accounting path" in p for p in problems) == 4


def test_fold_allowlisted_and_out_of_tree_pass(tmp_path, monkeypatch):
    annotated = (
        "def f(agg, obj):\n"
        "    agg.aggregate(obj)  # lint: fold-ok\n"
    )
    problems = _check(tmp_path, monkeypatch, "xaynet_tpu/edge/foo.py", annotated)
    assert not any("accounting path" in p for p in problems)

    bare = "def f(agg, obj):\n    agg.aggregate(obj)\n"
    for rel in ("xaynet_tpu/server/foo.py", "xaynet_tpu/core/foo.py", "tools/foo.py"):
        problems = _check(tmp_path, monkeypatch, rel, bare)
        assert not any("accounting path" in p for p in problems), rel


def test_edge_tree_is_clean_under_fold_rule():
    target = REPO / "xaynet_tpu" / "edge"
    problems = []
    for path in sorted(target.rglob("*.py")):
        problems.extend(xn_lint.check_file(path))
    assert problems == []


# --- fold-worker blocking-sync rule ----------------------------------------


def test_blocking_sync_rejected_in_parallel_worker_paths(tmp_path, monkeypatch):
    source = (
        "import numpy as np\n"
        "import jax\n"
        "def _process(item):\n"
        "    return np.asarray(item)\n"
        "def submit_batch(stack):\n"
        "    jax.block_until_ready(stack)\n"
        "def _fold_payload(x):\n"
        "    x.block_until_ready()\n"
    )
    problems = _check(tmp_path, monkeypatch, "xaynet_tpu/parallel/foo.py", source)
    assert sum("blocking host sync" in p for p in problems) == 3


def test_blocking_sync_drain_and_allowlist_exempt(tmp_path, monkeypatch):
    source = (
        "import numpy as np\n"
        "import jax\n"
        "def drain(pending):\n"
        "    return [np.asarray(t) for t in pending]\n"
        "def _drain_sharded(acc):\n"
        "    jax.block_until_ready(acc)\n"
        "def _fold_shard_item(payload):\n"
        "    piece = np.asarray(payload)  # host-kernel view  # lint: sync-ok\n"
        "    return piece\n"
        "def helper(x):\n"
        "    return np.asarray(x)\n"
    )
    problems = _check(tmp_path, monkeypatch, "xaynet_tpu/parallel/foo.py", source)
    assert not any("blocking host sync" in p for p in problems)


def test_sync_rule_scoped_to_parallel_tree(tmp_path, monkeypatch):
    source = (
        "import numpy as np\n"
        "def _process(item):\n"
        "    return np.asarray(item)\n"
    )
    for rel in ("xaynet_tpu/server/foo.py", "xaynet_tpu/ops/foo.py", "tools/foo.py"):
        problems = _check(tmp_path, monkeypatch, rel, source)
        assert not any("blocking host sync" in p for p in problems), rel


def test_parallel_tree_is_clean_under_sync_rule():
    target = REPO / "xaynet_tpu" / "parallel"
    problems = []
    for path in sorted(target.rglob("*.py")):
        problems.extend(xn_lint.check_file(path))
    assert problems == []


def test_host_roundtrip_rejected_in_sim_program_bodies(tmp_path, monkeypatch):
    source = (
        "import numpy as np\n"
        "from xaynet_tpu.ops import limbs as host_limbs\n"
        "def _prog_round(x):\n"
        "    a = np.asarray(x)\n"
        "    b = host_limbs.limbs_to_int(a)\n"
        "    c = int(b)\n"
        "    d = x.block_until_ready()\n"
        "    e = x.item()\n"
        "    return a, b, c, d, e\n"
    )
    problems = _check(tmp_path, monkeypatch, "xaynet_tpu/sim/foo.py", source)
    assert sum("host round-trip in sim program body" in p for p in problems) == 5


def test_host_roundtrip_allowlist_and_host_boundary_pass(tmp_path, monkeypatch):
    source = (
        "import numpy as np\n"
        "from xaynet_tpu.ops import limbs as host_limbs\n"
        "def _prog_round(x):\n"
        "    return np.asarray(x)  # lint: sync-ok\n"
        "def run(x):\n"
        "    # the host boundary lives outside _prog* bodies\n"
        "    v = np.asarray(x)\n"
        "    return host_limbs.limbs_to_int(v)\n"
    )
    problems = _check(tmp_path, monkeypatch, "xaynet_tpu/sim/foo.py", source)
    assert not any("host round-trip" in p for p in problems)


def test_sim_roundtrip_rule_scoped_to_sim_tree(tmp_path, monkeypatch):
    source = (
        "import numpy as np\n"
        "def _prog_round(x):\n"
        "    return np.asarray(x)\n"
    )
    for rel in ("xaynet_tpu/ops/foo.py", "xaynet_tpu/server/foo.py", "tools/foo.py"):
        problems = _check(tmp_path, monkeypatch, rel, source)
        assert not any("host round-trip" in p for p in problems), rel


def test_sim_tree_is_clean_under_roundtrip_rule():
    target = REPO / "xaynet_tpu" / "sim"
    problems = []
    for path in sorted(target.rglob("*.py")):
        problems.extend(xn_lint.check_file(path))
    assert problems == []


# --- width rule (wire/pack width single source of truth, DESIGN §17) -------


def test_width_expr_rejected_outside_codec_module(tmp_path, monkeypatch):
    source = (
        "def f(order):\n"
        "    bpn = (order.bit_length() + 7) // 8\n"
        "    limbs = (bpn + 3) // 4\n"
        "    return bpn, limbs\n"
    )
    problems = _check(tmp_path, monkeypatch, "xaynet_tpu/core/foo.py", source)
    assert sum("hand-computed wire/pack width" in p for p in problems) == 2


def test_width_expr_commuted_addition_still_rejected(tmp_path, monkeypatch):
    problems = _check(
        tmp_path,
        monkeypatch,
        "xaynet_tpu/server/foo.py",
        "def f(bits):\n    return (7 + bits) // 8\n",
    )
    assert any("hand-computed wire/pack width" in p for p in problems)


def test_width_codec_module_and_allowlist_pass(tmp_path, monkeypatch):
    # the codec module itself is the single source of truth
    problems = _check(
        tmp_path,
        monkeypatch,
        "xaynet_tpu/ops/limbs.py",
        "def wire_width_for(order):\n    return ((order - 1).bit_length() + 7) // 8\n",
    )
    assert not any("hand-computed wire/pack width" in p for p in problems)
    # annotated non-wire byte-length math passes anywhere
    problems = _check(
        tmp_path,
        monkeypatch,
        "xaynet_tpu/core/bar.py",
        "def f(n):\n    return (n.bit_length() + 7) // 8  # lint: width-ok\n",
    )
    assert not any("hand-computed wire/pack width" in p for p in problems)


def test_width_rule_scoped_to_package_tree(tmp_path, monkeypatch):
    # tools/tests stay free to compute widths (oracles recompute deliberately)
    problems = _check(
        tmp_path,
        monkeypatch,
        "tools/foo.py",
        "def f(order):\n    return (order.bit_length() + 7) // 8\n",
    )
    assert not any("hand-computed wire/pack width" in p for p in problems)


def test_width_unrelated_floordivs_pass(tmp_path, monkeypatch):
    source = (
        "def f(x):\n"
        "    a = (x + 1) // 8\n"
        "    b = (x + 7) // 16\n"
        "    c = (x.bit_length() + 31) // 32\n"
        "    return a + b + c\n"
    )
    problems = _check(tmp_path, monkeypatch, "xaynet_tpu/core/baz.py", source)
    assert not any("hand-computed wire/pack width" in p for p in problems)


# --- the ingress zero-copy (wirecopy) rule -----------------------------------


def test_wirecopy_bytes_materialization_rejected_in_ingest(tmp_path, monkeypatch):
    problems = _check(
        tmp_path,
        monkeypatch,
        "xaynet_tpu/ingest/foo.py",
        "def intake(view):\n    return bytes(view)\n",
    )
    assert any("whole-body copy on the ingress path" in p for p in problems)


def test_wirecopy_tobytes_rejected_in_rest(tmp_path, monkeypatch):
    problems = _check(
        tmp_path,
        monkeypatch,
        "xaynet_tpu/server/rest.py",
        "def handler(arr):\n    return arr.tobytes()\n",
    )
    assert any(".tobytes() export" in p for p in problems)


def test_wirecopy_payload_slice_rejected(tmp_path, monkeypatch):
    source = (
        "def parse(body, header_len):\n"
        "    head = body[:header_len]\n"
        "    return head\n"
    )
    problems = _check(tmp_path, monkeypatch, "xaynet_tpu/ingest/foo.py", source)
    assert any("slice-copy of payload buffer 'body'" in p for p in problems)


def test_wirecopy_non_payload_slice_and_index_pass(tmp_path, monkeypatch):
    source = (
        "def parse(result, body):\n"
        "    status = result[:3]\n"  # tuple destructure, not a payload
        "    first = body[0]\n"  # single-byte index, not a slice-copy
        "    return status, first\n"
    )
    problems = _check(tmp_path, monkeypatch, "xaynet_tpu/ingest/foo.py", source)
    assert not any("whole-body copy" in p for p in problems)


def test_wirecopy_allowlist_and_scope(tmp_path, monkeypatch):
    allow = _check(
        tmp_path,
        monkeypatch,
        "xaynet_tpu/ingest/foo.py",
        "def seal(view):\n    return bytes(view)  # lint: wirecopy-ok\n",
    )
    assert not any("whole-body copy" in p for p in allow)
    # the rule stops at the ingress path: SDK/client code copies freely
    elsewhere = _check(
        tmp_path,
        monkeypatch,
        "xaynet_tpu/sdk/foo.py",
        "def pack(view):\n    return bytes(view) + view.tobytes()\n",
    )
    assert not any("whole-body copy" in p for p in elsewhere)
    # server tree outside rest.py is out of scope too (state machine code
    # owns decrypted plaintext, not wire bodies)
    server_other = _check(
        tmp_path,
        monkeypatch,
        "xaynet_tpu/server/coordinator.py",
        "def snapshot(buf):\n    return bytes(buf)\n",
    )
    assert not any("whole-body copy" in p for p in server_other)
