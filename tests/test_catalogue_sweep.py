"""Full-catalogue conformance sweep: every one of the 240 mask configs.

The per-family grids in test_masking.py mirror the reference's macro tests
(M3 capacity); this sweep additionally walks EVERY catalogue entry —
all GroupType x DataType x BoundType x ModelType combinations — through
wire serialization and the full mask -> derive -> unmask round trip, so a
regression in any single order/shift entry (or any width-dependent code
path: 6-byte through 268-byte elements) is caught by name.
"""

import random
from fractions import Fraction

import pytest

from xaynet_tpu.core.mask import (
    Aggregation,
    Masker,
    MaskConfig,
    MaskSeed,
    Model,
    Scalar,
)
from xaynet_tpu.core.mask._orders_data import ORDERS
from xaynet_tpu.core.mask.config import (
    _BOUND_KEY,
    _DATA_KEY,
    _GROUP_KEY,
    _MODEL_KEY,
    BoundType,
    DataType,
)
from xaynet_tpu.core.mask.serialization import parse_mask_object, serialize_mask_object

_G = {v: k for k, v in _GROUP_KEY.items()}
_D = {v: k for k, v in _DATA_KEY.items()}
_B = {v: k for k, v in _BOUND_KEY.items()}
_M = {v: k for k, v in _MODEL_KEY.items()}

CATALOGUE = sorted(ORDERS)


def _weights(rng, dtype: DataType, bound: BoundType, n: int):
    bounds = {
        BoundType.B0: 1,
        BoundType.B2: 100,
        BoundType.B4: 10_000,
        BoundType.B6: 1_000_000,
    }
    if bound is BoundType.BMAX:
        b = {DataType.F32: 1e30, DataType.F64: 1e200, DataType.I32: 2**30, DataType.I64: 2**62}[
            dtype
        ]
    else:
        b = bounds[bound]
    if dtype in (DataType.I32, DataType.I64):
        return [rng.randint(-int(b), int(b)) for _ in range(n)]
    import numpy as np

    ws = [rng.uniform(-b, b) for _ in range(n)]
    if dtype is DataType.F32:
        ws = [float(np.float32(w)) for w in ws]
    return ws


@pytest.mark.parametrize("key", CATALOGUE, ids=lambda k: "-".join(k))
def test_catalogue_entry_roundtrip(key):
    g, d, b, m = key
    config = MaskConfig(_G[g], _D[d], _B[b], _M[m])
    assert config.order == ORDERS[key]  # catalogue lookup is the entry itself

    rng = random.Random(hash(key) & 0xFFFFFF)
    n = 3
    weights = _weights(rng, config.data_type, config.bound_type, n)
    model = Model.from_primitives(weights, config.data_type)

    masker = Masker(config.pair(), MaskSeed(bytes(rng.randrange(256) for _ in range(32))))
    seed, masked = masker.mask(Scalar.unit(), model)
    assert masked.is_valid()

    # wire round trip at this entry's exact element width
    wire = serialize_mask_object(masked)
    parsed, consumed = parse_mask_object(wire)
    assert consumed == len(wire)
    assert parsed == masked

    mask = seed.derive_mask(n, config.pair())
    agg = Aggregation.from_object(parsed)
    agg.validate_unmasking(mask)
    unmasked = agg.unmask(mask)
    tol = Fraction(1, config.exp_shift)
    for w, u in zip(model, unmasked):
        assert abs(w - u) <= tol, (key, float(w), float(u))
