"""Interpreter-free native participant (libxaynet_participant.so).

The library embeds NO Python: crypto via libsodium, wire building, masking,
sum2 mask aggregation and the FSM are all C++ (native analogue of the
reference's xaynet-mobile, participant.rs:129-353 + ffi/). These tests
validate byte-level interop with the Python stack (sealed boxes, Ed25519,
eligibility) and drive native participants through a FULL round against the
in-process Python coordinator over ctypes transport callbacks.
"""

import asyncio
import ctypes
import os
import subprocess
import threading
from fractions import Fraction

import numpy as np

from xaynet_tpu.core.crypto.encrypt import EncryptKeyPair
from xaynet_tpu.core.crypto.sign import SigningKeyPair, is_eligible, verify_detached
from xaynet_tpu.core.mask.config import (
    BoundType,
    DataType,
    GroupType,
    MaskConfig,
    ModelType,
)
from xaynet_tpu.sdk.simulation import keys_for_task

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_LIB = os.path.join(_NATIVE_DIR, "libxaynet_participant.so")

TRANSPORT_FN = ctypes.CFUNCTYPE(
    ctypes.c_int,
    ctypes.c_void_p,  # user
    ctypes.c_char_p,  # request
    ctypes.POINTER(ctypes.c_uint8),  # body
    ctypes.c_uint64,  # body_len
    ctypes.c_void_p,  # XnBuffer* out
)


class XnBuffer(ctypes.Structure):
    _fields_ = [("data", ctypes.c_void_p), ("len", ctypes.c_uint64)]


def _load():
    if not os.path.exists(_LIB):
        subprocess.run(["make", "-s", "libxaynet_participant.so"], cwd=_NATIVE_DIR, check=True)
    lib = ctypes.CDLL(_LIB)
    lib.xaynet_ffi_abi_version.restype = ctypes.c_uint32
    lib.xaynet_ffi_crypto_init.restype = ctypes.c_int
    lib.xaynet_ffi_participant_new.restype = ctypes.c_void_p
    lib.xaynet_ffi_participant_new.argtypes = [
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_uint32,
        TRANSPORT_FN,
        ctypes.c_void_p,
    ]
    lib.xaynet_ffi_participant_tick.argtypes = [ctypes.c_void_p]
    lib.xaynet_ffi_participant_tick.restype = ctypes.c_int
    lib.xaynet_ffi_participant_task.argtypes = [ctypes.c_void_p]
    lib.xaynet_ffi_participant_should_set_model.argtypes = [ctypes.c_void_p]
    lib.xaynet_ffi_participant_set_model.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_float),
        ctypes.c_uint64,
    ]
    lib.xaynet_ffi_participant_global_model.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_double)),
    ]
    lib.xaynet_ffi_participant_global_model.restype = ctypes.c_int64
    lib.xaynet_ffi_participant_save.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.xaynet_ffi_participant_save.restype = ctypes.c_int
    lib.xaynet_ffi_participant_restore.restype = ctypes.c_void_p
    lib.xaynet_ffi_participant_restore.argtypes = [
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_uint64,
        TRANSPORT_FN,
        ctypes.c_void_p,
    ]
    lib.xaynet_ffi_participant_destroy.argtypes = [ctypes.c_void_p]
    lib.xaynet_ffi_seal.argtypes = [
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.xaynet_ffi_seal_open.argtypes = list(lib.xaynet_ffi_seal.argtypes)
    lib.xaynet_ffi_sign.argtypes = [
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint8),
    ]
    lib.xaynet_ffi_is_eligible.argtypes = [ctypes.POINTER(ctypes.c_uint8), ctypes.c_double]
    lib.xaynet_ffi_is_eligible.restype = ctypes.c_int
    assert lib.xaynet_ffi_crypto_init() == 0
    return lib


def _u8(data: bytes):
    return (ctypes.c_uint8 * len(data)).from_buffer_copy(data)


def test_library_has_no_python_dependency():
    lib = _load()  # ensure built
    assert lib.xaynet_ffi_abi_version() == 2
    out = subprocess.run(["ldd", _LIB], capture_output=True, text=True).stdout
    assert "python" not in out.lower()
    assert "sodium" in out


def test_sealed_box_interop_both_directions():
    lib = _load()
    pair = EncryptKeyPair.generate()
    msg = b"the quick brown fox" * 3

    # native seal -> python open
    out = (ctypes.c_uint8 * (len(msg) + 48))()
    out_len = ctypes.c_uint64()
    rc = lib.xaynet_ffi_seal(_u8(msg), len(msg), _u8(pair.public.as_bytes()), out, ctypes.byref(out_len))
    assert rc == 0 and out_len.value == len(msg) + 48
    assert pair.secret.decrypt(bytes(out[: out_len.value])) == msg

    # python seal -> native open
    sealed = pair.public.encrypt(msg)
    plain = (ctypes.c_uint8 * len(sealed))()
    plain_len = ctypes.c_uint64()
    rc = lib.xaynet_ffi_seal_open(
        _u8(sealed), len(sealed), _u8(pair.secret.as_bytes()), plain, ctypes.byref(plain_len)
    )
    assert rc == 0 and bytes(plain[: plain_len.value]) == msg


def test_signature_and_eligibility_interop():
    lib = _load()
    keys = SigningKeyPair.generate()
    msg = b"round-seed" + b"sum"
    sig = (ctypes.c_uint8 * 64)()
    lib.xaynet_ffi_sign(_u8(keys.secret), _u8(msg), len(msg), sig)
    sig_bytes = bytes(sig)
    # native signature verifies under the python Ed25519 (same seed -> same pk)
    assert verify_detached(keys.public, sig_bytes, msg)
    # and equals the python signature (Ed25519 is deterministic)
    assert sig_bytes == keys.sign(msg).as_bytes()

    # eligibility parity across thresholds incl. awkward ones
    for t in (0.0, 1e-12, 0.25, 0.5, 0.7, 1.0 - 1e-12, 1.0):
        for i in range(24):
            s = bytes([(i * 37 + j) % 256 for j in range(64)])
            assert lib.xaynet_ffi_is_eligible(_u8(s), t) == int(is_eligible(s, t)), (t, i)


class _Bridge:
    """Routes native transport callbacks into the in-process coordinator."""

    def __init__(self, fetcher, handler, loop):
        self.fetcher = fetcher
        self.handler = handler
        self.loop = loop  # coordinator's loop (background thread)
        self.libc = ctypes.CDLL(None)
        self.libc.malloc.restype = ctypes.c_void_p
        self.libc.malloc.argtypes = [ctypes.c_size_t]
        self.cb = TRANSPORT_FN(self._call)

    def _reply(self, out_ptr, payload: bytes) -> int:
        if not payload:
            return 1
        buf = ctypes.cast(out_ptr, ctypes.POINTER(XnBuffer))
        mem = self.libc.malloc(len(payload))
        ctypes.memmove(mem, payload, len(payload))
        buf.contents.data = mem
        buf.contents.len = len(payload)
        return 0

    def _call(self, user, request, body, body_len, out_ptr) -> int:
        import json

        try:
            req = request.decode()
            if req == "GET /params":
                return self._reply(
                    out_ptr, json.dumps(self.fetcher.round_params().to_dict()).encode()
                )
            if req == "GET /sums":
                sums = self.fetcher.sum_dict()
                if not sums:
                    return 1
                return self._reply(
                    out_ptr, json.dumps({k.hex(): v.hex() for k, v in sums.items()}).encode()
                )
            if req.startswith("GET /seeds?pk="):
                pk = bytes.fromhex(req.split("=", 1)[1])
                seeds = self.fetcher.seeds_for(pk)
                if not seeds:
                    return 1
                return self._reply(
                    out_ptr,
                    json.dumps({k.hex(): v.as_bytes().hex() for k, v in seeds.items()}).encode(),
                )
            if req == "GET /model":
                model = self.fetcher.model()
                if model is None:
                    return 1
                return self._reply(out_ptr, np.asarray(model, dtype=np.float64).tobytes())
            if req == "POST /message":
                data = bytes(ctypes.cast(body, ctypes.POINTER(ctypes.c_uint8 * body_len)).contents)
                fut = asyncio.run_coroutine_threadsafe(self._post(data), self.loop)
                fut.result(timeout=30)
                return 1
            return -1
        except Exception:
            return -1

    async def _post(self, data: bytes) -> None:
        try:
            await self.handler.handle_message(data)
        except Exception:
            pass  # drops are logged server-side; clients watch round progress


def _run_native_round(lib, cfg: MaskConfig, model_len: int, set_models, expect,
                      after_round=None, max_message_size=400):
    """Drives 1 native summer + 3 native updaters through a full round
    against the in-process Python coordinator; asserts the global model.
    ``after_round(lib, handles, bridge)`` runs before handles are destroyed."""
    import time

    from xaynet_tpu.server.services import Fetcher, PetMessageHandler
    from xaynet_tpu.server.settings import CountSettings, Settings
    from xaynet_tpu.server.state_machine import StateMachineInitializer
    from xaynet_tpu.storage.memory import (
        InMemoryCoordinatorStorage,
        InMemoryModelStorage,
        NoOpTrustAnchor,
    )
    from xaynet_tpu.storage.traits import Store

    settings = Settings.default()
    settings.mask.group_type = cfg.group_type
    settings.mask.data_type = cfg.data_type
    settings.mask.bound_type = cfg.bound_type
    settings.mask.model_type = cfg.model_type
    settings.model.length = model_len
    settings.pet.sum.count = CountSettings(1, 1)
    settings.pet.update.count = CountSettings(3, 3)
    settings.pet.sum2.count = CountSettings(1, 1)
    for ph in (settings.pet.sum, settings.pet.update, settings.pet.sum2):
        ph.time.min = 0.0
        ph.time.max = 60.0

    store = Store(InMemoryCoordinatorStorage(), InMemoryModelStorage(), NoOpTrustAnchor())
    loop = asyncio.new_event_loop()
    stop_evt = threading.Event()
    state = {}

    def run_coordinator():
        asyncio.set_event_loop(loop)

        async def main():
            machine, tx, events = await StateMachineInitializer(settings, store).init()
            state["handler"] = PetMessageHandler(events, tx)
            state["fetcher"] = Fetcher(events)
            state["events"] = events
            task = asyncio.create_task(machine.run())
            while not stop_evt.is_set():
                await asyncio.sleep(0.02)
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass

        loop.run_until_complete(main())

    thread = threading.Thread(target=run_coordinator, daemon=True)
    thread.start()
    handles = []
    try:
        for _ in range(300):
            if "fetcher" in state:
                break
            time.sleep(0.02)
        events = state["events"]
        while events.phase.get_latest().event.value != "sum":
            time.sleep(0.02)
        params = events.params.get_latest().event
        seed = params.seed.as_bytes()

        bridge = _Bridge(state["fetcher"], state["handler"], loop)
        sum_keys = keys_for_task(seed, params.sum, params.update, "sum")
        upd_keys, start = [], 0
        while len(upd_keys) < 3:
            k = keys_for_task(seed, params.sum, params.update, "update", start=start)
            start += 100000
            if all(k.public != u.public for u in upd_keys):
                upd_keys.append(k)

        summer = lib.xaynet_ffi_participant_new(
            _u8(sum_keys.secret), 1, 3, max_message_size, bridge.cb, None
        )
        assert summer
        handles.append(summer)
        for i, k in enumerate(upd_keys):
            h = lib.xaynet_ffi_participant_new(
                _u8(k.secret), 1, 3, max_message_size, bridge.cb, None
            )
            assert h
            set_models(lib, h, i)
            handles.append(h)

        out_ptr = ctypes.POINTER(ctypes.c_double)()
        n = 0
        for _ in range(400):
            for h in handles:
                lib.xaynet_ffi_participant_tick(h)
            n = lib.xaynet_ffi_participant_global_model(handles[0], ctypes.byref(out_ptr))
            if n > 0:
                break
            time.sleep(0.01)
        assert n == model_len, f"round did not complete (n={n})"
        got = np.ctypeslib.as_array(out_ptr, shape=(model_len,)).copy()
        expect(got)
        if after_round is not None:
            after_round(lib, handles, bridge)
    finally:
        for h in handles:
            lib.xaynet_ffi_participant_destroy(h)
        stop_evt.set()
        thread.join(timeout=10)


def test_native_round_i64_config():
    """Full round on an INTEGER data type (i64/B2): exercises the exact
    __int128 masking path instead of the fused f32 kernel."""
    lib = _load()
    cfg = MaskConfig(GroupType.INTEGER, DataType.I64, BoundType.B2, ModelType.M3)
    vals = [[-3, 7, 0, 25], [5, -1, 2, -25], [1, 0, 4, 9]]

    def set_models(lib, h, i):
        arr = np.asarray(vals[i] * 4, dtype=np.int64)  # model_len 16
        rc = lib.xaynet_ffi_participant_set_model_i64(
            h, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), 16
        )
        assert rc == 0

    def expect(got):
        want = np.mean(np.asarray([v * 4 for v in vals], dtype=np.float64), axis=0)
        assert np.allclose(got, want, atol=1e-9), (got[:4], want[:4])

    lib.xaynet_ffi_participant_set_model_i64.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_uint64,
    ]
    _run_native_round(lib, cfg, 16, set_models, expect)


def test_native_round_f32_b2_config():
    """Full round on f32/B2 — pins the bound->add_shift mapping for the
    non-B0 wire values (B2=2, B4=4, B6=6, not consecutive indices)."""
    lib = _load()
    cfg = MaskConfig(GroupType.PRIME, DataType.F32, BoundType.B2, ModelType.M3)
    vals = [12.5, -40.25, 3.75]

    def set_models(lib, h, i):
        arr = np.full(8, vals[i], dtype=np.float32)
        assert lib.xaynet_ffi_participant_set_model(
            h, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), 8
        ) == 0

    def expect(got):
        assert np.allclose(got, np.mean(vals), atol=1e-7), got[:3]

    _run_native_round(lib, cfg, 8, set_models, expect)


def test_native_f64_encode_matches_fraction_oracle():
    """The 192-bit exact f64 fixed-point encode equals the reference
    semantics (Fraction oracle) across random weights, subnormals, clamp
    boundaries and every bounded A/E combination."""
    import random

    lib = _load()
    lib.xaynet_ffi_encode_f64.argtypes = [
        ctypes.c_double,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_uint64,
        ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_uint8),
    ]
    lib.xaynet_ffi_encode_f64.restype = ctypes.c_int

    def native_encode(w, num, den, a, e_pow10):
        out = (ctypes.c_uint8 * 16)()
        assert lib.xaynet_ffi_encode_f64(w, num, den, a, e_pow10, out) == 0
        return int.from_bytes(bytes(out), "little")

    def oracle(w, num, den, a, e):
        s = Fraction(num, den)
        c = max(Fraction(-a), min(Fraction(a), s * Fraction(w)))
        t = c + a
        return (t.numerator * e) // t.denominator

    rng = random.Random(17)
    for _ in range(2000):
        a = rng.choice([1, 100, 10**4, 10**6])
        e_pow = rng.choice([10, 20])
        num = rng.choice([0, 1, 3, 2**31 - 1, rng.randrange(1, 2**31)])
        den = rng.choice([1, 3, 1000, 2**31 - 1, rng.randrange(1, 2**31)])
        kind = rng.random()
        if kind < 0.4:
            w = rng.uniform(-2 * a, 2 * a)
        elif kind < 0.6:
            w = rng.uniform(-1e-10, 1e-10)
        elif kind < 0.8:
            w = float(np.ldexp(rng.uniform(0.5, 1), rng.randrange(-1074, 1020))) * rng.choice([-1, 1])
        else:
            w = rng.choice([0.0, -0.0, float(a), -float(a), 5e-324, -5e-324, 1e308])
        if not np.isfinite(w):
            continue
        assert native_encode(w, num, den, a, e_pow) == oracle(w, num, den, a, 10**e_pow), (
            w.hex(), num, den, a, e_pow,
        )


def test_native_bmax_encode_matches_fraction_oracle():
    """The arbitrary-width Bmax float encode (A = f32max/f64max,
    E = 10^45/10^324) equals the Fraction oracle."""
    import random

    lib = _load()
    lib.xaynet_ffi_encode_bmax.argtypes = [
        ctypes.c_double,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_uint64,
    ]
    lib.xaynet_ffi_encode_bmax.restype = ctypes.c_int64

    F32_MAX, F64_MAX = 2**128 - 2**104, 2**1024 - 2**971

    def native_encode(w, num, den, is_f64):
        cap = 600
        out = (ctypes.c_uint8 * cap)()
        assert lib.xaynet_ffi_encode_bmax(w, num, den, is_f64, out, cap) == cap
        return int.from_bytes(bytes(out), "little")

    def oracle(w, num, den, a, e):
        s = Fraction(num, den)
        c = max(Fraction(-a), min(Fraction(a), s * Fraction(w)))
        t = c + a
        return (t.numerator * e) // t.denominator

    rng = random.Random(5)
    for _ in range(600):
        is_f64 = rng.random() < 0.5
        a, e = (F64_MAX, 10**324) if is_f64 else (F32_MAX, 10**45)
        num = rng.choice([0, 1, 3, 2**31 - 1, rng.randrange(1, 2**31)])
        den = rng.choice([1, 3, 1000, rng.randrange(1, 2**31)])
        kind = rng.random()
        if kind < 0.5:
            w = float(np.ldexp(rng.uniform(0.5, 1), rng.randrange(-1074, 1023))) * rng.choice([-1, 1])
        elif kind < 0.75:
            w = rng.uniform(-1e6, 1e6)
        else:
            w = rng.choice([0.0, 1e308, -1e308, 5e-324, -5e-324, 3.4028234e38])
        if not np.isfinite(w):
            continue
        assert native_encode(w, num, den, is_f64) == oracle(w, num, den, a, e), (
            w.hex(), num, den, is_f64,
        )


def test_native_round_f32_bmax_config():
    """Full round on f32/Bmax: the bignum masking path end-to-end — with
    this, the native FSM covers the whole catalogue."""
    lib = _load()
    cfg = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.BMAX, ModelType.M3)
    vals = [1.5e10, -2.25e12, 7.75e8]

    def set_models(lib, h, i):
        arr = np.full(6, vals[i], dtype=np.float32)
        assert lib.xaynet_ffi_participant_set_model(
            h, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), 6
        ) == 0

    def expect(got):
        want = np.mean(np.asarray(vals, dtype=np.float32).astype(np.float64))
        assert np.allclose(got, want, rtol=1e-10), (got[:3], want)

    _run_native_round(lib, cfg, 6, set_models, expect)


def test_native_round_f64_bmax_config():
    """Full round on f64/Bmax: ~264-byte elements through chunked messaging
    and the bignum unit path at full f64 widths."""
    lib = _load()
    lib.xaynet_ffi_participant_set_model_f64.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_double),
        ctypes.c_uint64,
    ]
    cfg = MaskConfig(GroupType.PRIME, DataType.F64, BoundType.BMAX, ModelType.M3)
    vals = [3.5e200, -1.25e190, 6.0e150]

    def set_models(lib, h, i):
        arr = np.full(4, vals[i], dtype=np.float64)
        assert lib.xaynet_ffi_participant_set_model_f64(
            h, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), 4
        ) == 0

    def expect(got):
        assert np.allclose(got, np.mean(vals), rtol=1e-12), got[:3]

    _run_native_round(lib, cfg, 4, set_models, expect, max_message_size=4096)


def test_native_round_f64_config():
    """Full round on f64/B2: the exact 192-bit masking path end-to-end."""
    lib = _load()
    lib.xaynet_ffi_participant_set_model_f64.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_double),
        ctypes.c_uint64,
    ]
    cfg = MaskConfig(GroupType.PRIME, DataType.F64, BoundType.B2, ModelType.M3)
    vals = [12.25e-5, -40.125, 3.0625]

    def set_models(lib, h, i):
        arr = np.full(8, vals[i], dtype=np.float64)
        assert lib.xaynet_ffi_participant_set_model_f64(
            h, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), 8
        ) == 0

    def expect(got):
        # f64 config: 1/exp_shift = 1e-20 tolerance, i.e. exact to f64 eps
        assert np.allclose(got, np.mean(vals), rtol=1e-12, atol=1e-15), got[:3]

    _run_native_round(lib, cfg, 8, set_models, expect)


def test_native_participants_complete_full_round():
    """1 native summer + 3 native updaters complete a PET round against the
    Python coordinator; the global model equals the exact mean. The small
    max_message_size forces the native multipart encoder + the server's
    streaming reassembly; afterwards save/restore round-trips (including
    tolerance for blobs without the trailing int-model field)."""
    lib = _load()
    cfg = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M3)
    vals = [0.25, -0.5, 0.75]

    def set_models(lib, h, i):
        model = np.full(24, vals[i], dtype=np.float32)
        assert lib.xaynet_ffi_participant_set_model(
            h, model.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), 24
        ) == 0

    def expect(got):
        assert np.allclose(got, np.mean(vals), atol=1e-7), got[:4]

    def after_round(lib, handles, bridge):
        buf = ctypes.POINTER(ctypes.c_uint8)()
        blen = ctypes.c_uint64()
        assert lib.xaynet_ffi_participant_save(
            handles[0], ctypes.byref(buf), ctypes.byref(blen)
        ) == 0
        blob = bytes(ctypes.cast(buf, ctypes.POINTER(ctypes.c_uint8 * blen.value)).contents)
        restored = lib.xaynet_ffi_participant_restore(buf, blen.value, bridge.cb, None)
        assert restored
        lib.xaynet_ffi_participant_destroy(restored)
        # old-format blob (no trailing int-model LV) still restores
        trimmed = blob[: len(blob) - 4]  # drop the empty trailing LV
        restored2 = lib.xaynet_ffi_participant_restore(_u8(trimmed), len(trimmed), bridge.cb, None)
        assert restored2
        lib.xaynet_ffi_participant_destroy(restored2)

    _run_native_round(lib, cfg, 24, set_models, expect, after_round=after_round)


# --- built-in HTTP transport: no Python, no caller transport ---------------


def _build_http_demo() -> bool:
    try:
        subprocess.run(
            ["make", "-s", "http_demo"], cwd=_NATIVE_DIR, check=True, capture_output=True
        )
        return True
    except (subprocess.CalledProcessError, FileNotFoundError):
        return False


def _self_signed_cert(dirpath: str, name: str):
    """(cert_path, key_path) for a self-signed cert with SAN IP 127.0.0.1."""
    import datetime
    import ipaddress

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    subject = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, name)])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(subject)
        .issuer_name(subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(
            x509.SubjectAlternativeName([x509.IPAddress(ipaddress.ip_address("127.0.0.1"))]),
            critical=False,
        )
        .sign(key, hashes.SHA256())
    )
    cert_path = os.path.join(dirpath, f"{name}.pem")
    key_path = os.path.join(dirpath, f"{name}.key")
    with open(cert_path, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
    with open(key_path, "wb") as f:
        f.write(
            key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.TraditionalOpenSSL,
                serialization.NoEncryption(),
            )
        )
    return cert_path, key_path


def test_native_round_over_builtin_http_transport():
    """Full PET round: 4 native participants (1 sum + 3 update) as separate
    OS processes using the bundled raw-socket HTTP transport
    (native/xaynet_http_transport.c) against the real coordinator socket.

    The reference's xaynet-mobile bundles an HTTP client
    (reqwest_client.rs); this is its parity proof — the client side runs
    no Python and no caller-written transport (VERDICT r02 item 8).
    """
    _native_http_round(tls_dir=None)


def test_native_round_over_tls_with_pinned_root_and_client_cert(tmp_path):
    """Same round, but over TLS terminated IN the bundled transport: the
    native participants pin the coordinator's root cert and present a
    client certificate the coordinator requires (mutual TLS) — parity with
    the reference's in-process reqwest TLS config
    (rust/xaynet-mobile/src/reqwest_client.rs:58-71). A participant pinned
    to the wrong root must fail the handshake and exit non-zero.
    """
    _native_http_round(tls_dir=str(tmp_path))


def _native_http_round(tls_dir):
    if not _build_http_demo():
        import pytest as _pytest

        _pytest.skip("C toolchain unavailable")

    import ssl as ssl_mod

    from xaynet_tpu.sdk.client import HttpClient
    from xaynet_tpu.server.rest import RestServer
    from xaynet_tpu.server.services import Fetcher, PetMessageHandler
    from xaynet_tpu.server.settings import (
        CountSettings,
        PhaseSettings,
        PetSettings,
        Settings,
        Sum2Settings,
        TimeSettings,
    )
    from xaynet_tpu.server.state_machine import StateMachineInitializer
    from xaynet_tpu.storage.memory import (
        InMemoryCoordinatorStorage,
        InMemoryModelStorage,
        NoOpTrustAnchor,
    )
    from xaynet_tpu.storage.traits import Store

    MODEL_LEN = 32
    SUM_PROB, UPDATE_PROB = 0.5, 0.9
    values = [0.25, 0.5, 1.0]

    settings = Settings(
        pet=PetSettings(
            sum=PhaseSettings(
                prob=SUM_PROB, count=CountSettings(1, 1), time=TimeSettings(0, 60)
            ),
            update=PhaseSettings(
                prob=UPDATE_PROB, count=CountSettings(3, 3), time=TimeSettings(0, 60)
            ),
            sum2=Sum2Settings(count=CountSettings(1, 1), time=TimeSettings(0, 60)),
        )
    )
    settings.model.length = MODEL_LEN

    server_tls = None
    demo_env = dict(os.environ)
    if tls_dir is not None:
        server_cert, server_key = _self_signed_cert(tls_dir, "server")
        client_cert, client_key = _self_signed_cert(tls_dir, "client")
        wrong_ca, _ = _self_signed_cert(tls_dir, "wrong-ca")
        server_tls = ssl_mod.SSLContext(ssl_mod.PROTOCOL_TLS_SERVER)
        server_tls.load_cert_chain(server_cert, server_key)
        # require the participants' client certificate (mutual TLS)
        server_tls.verify_mode = ssl_mod.CERT_REQUIRED
        server_tls.load_verify_locations(client_cert)
        demo_env["XN_TLS_CA"] = server_cert  # pinned root
        demo_env["XN_TLS_CERT"] = client_cert
        demo_env["XN_TLS_KEY"] = client_key

    info, started = {}, threading.Event()

    def run_server():
        async def amain():
            store = Store(
                InMemoryCoordinatorStorage(), InMemoryModelStorage(), NoOpTrustAnchor()
            )
            machine, tx, events = await StateMachineInitializer(settings, store).init()
            rest = RestServer(Fetcher(events), PetMessageHandler(events, tx))
            host, port = await rest.start("127.0.0.1", 0, tls=server_tls)
            info["host"], info["port"] = host, port
            machine_task = asyncio.create_task(machine.run())
            info["loop"] = asyncio.get_running_loop()
            info["machine_task"] = machine_task
            started.set()
            try:
                await machine_task
            except asyncio.CancelledError:
                pass
            await rest.stop()

        asyncio.run(amain())

    server_thread = threading.Thread(target=run_server, daemon=True)
    server_thread.start()
    assert started.wait(15)
    host, port = info["host"], info["port"]

    procs = []
    try:
        if tls_dir is None:
            params = asyncio.run(HttpClient(f"http://{host}:{port}").get_round_params())
        else:
            ctx = ssl_mod.create_default_context(cafile=demo_env["XN_TLS_CA"])
            ctx.load_cert_chain(demo_env["XN_TLS_CERT"], demo_env["XN_TLS_KEY"])
            params = asyncio.run(
                HttpClient(f"https://{host}:{port}", tls_context=ctx).get_round_params()
            )
        seed = params.seed.as_bytes()

        demo = os.path.join(_NATIVE_DIR, "http_demo")

        if tls_dir is not None:
            # pinning must REJECT a coordinator whose cert chains to another root
            bad_env = dict(demo_env)
            bad_env["XN_TLS_CA"] = wrong_ca
            bad_keys = keys_for_task(seed, SUM_PROB, UPDATE_PROB, "update", start=90_000)
            bad = subprocess.run(
                [demo, host, str(port), bad_keys.secret.hex(), str(MODEL_LEN), "0.1"],
                env=bad_env,
                capture_output=True,
                text=True,
                timeout=30,
            )
            assert bad.returncode != 0, "wrong pinned root must fail the handshake"

        sum_keys = keys_for_task(seed, SUM_PROB, UPDATE_PROB, "sum")
        procs.append(
            subprocess.Popen(
                [demo, host, str(port), sum_keys.secret.hex(), str(MODEL_LEN)],
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                env=demo_env,
            )
        )
        for i, v in enumerate(values):
            keys = keys_for_task(seed, SUM_PROB, UPDATE_PROB, "update", start=(30 + i) * 1000)
            procs.append(
                subprocess.Popen(
                    [demo, host, str(port), keys.secret.hex(), str(MODEL_LEN), str(v)],
                    stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE,
                    text=True,
                    env=demo_env,
                )
            )

        outs = []
        for p in procs:
            out, err = p.communicate(timeout=90)
            outs.append(out)
            assert p.returncode == 0, f"native participant failed:\nstdout:{out}\nstderr:{err}"
    finally:
        # cleanup must not mask the real failure: kill stragglers first,
        # then drain the coordinator (a live daemon machine would keep
        # logging phase failures after the pytest summary)
        for p in procs:
            if p.poll() is None:
                p.kill()
                try:
                    p.communicate(timeout=10)
                except Exception:
                    pass
        try:
            if not info["loop"].is_closed():
                info["loop"].call_soon_threadsafe(info["machine_task"].cancel)
        except RuntimeError:
            pass  # loop already closed between the check and the call
        server_thread.join(timeout=10)

    expected = float(np.mean(values))
    for out in outs:
        line = [ln for ln in out.splitlines() if ln.startswith("global-model")]
        assert line, out
        n = int(line[0].split("n=")[1].split()[0])
        first = float(line[0].split("first=")[1])
        assert n == MODEL_LEN
        assert abs(first - expected) < 1e-6
    # the three updaters each submitted a model
    assert sum("model-set" in o for o in outs) == 3


def test_http_transport_handles_chunked_responses():
    """A proxy may re-frame responses as Transfer-Encoding: chunked; the
    bundled client must de-chunk (and honor Content-Length) correctly."""
    import socket

    subprocess.run(
        ["make", "-s", "libxaynet_http_transport.so"],
        cwd=_NATIVE_DIR,
        check=True,
        capture_output=True,
    )
    lib = ctypes.CDLL(os.path.join(_NATIVE_DIR, "libxaynet_http_transport.so"))
    lib.xn_http_client_new.restype = ctypes.c_void_p
    lib.xn_http_client_new.argtypes = [ctypes.c_char_p, ctypes.c_uint16]
    lib.xn_http_transport.restype = ctypes.c_int
    lib.xn_http_transport.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_uint64,
        ctypes.POINTER(XnBuffer),
    ]
    lib.xn_http_client_free.argtypes = [ctypes.c_void_p]

    payload = b"A" * 5 + b"B" * 7 + b"C" * 3
    responses = {
        b"GET /chunked": (
            b"HTTP/1.1 200 OK\r\nTransfer-Encoding: chunked\r\n\r\n"
            b"5\r\nAAAAA\r\n7;ext=1\r\nBBBBBBB\r\n3\r\nCCC\r\n0\r\n\r\n"
        ),
        b"GET /plain": (
            b"HTTP/1.1 200 OK\r\nContent-Length: 15\r\n\r\n" + payload + b"TRAILING-JUNK"
        ),
        b"GET /empty": b"HTTP/1.1 204 No Content\r\nContent-Length: 0\r\n\r\n",
        b"GET /boom": b"HTTP/1.1 500 Internal Server Error\r\nContent-Length: 0\r\n\r\n",
    }

    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)
    port = srv.getsockname()[1]

    def serve():
        while True:
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            with conn:
                data = b""
                while b"\r\n\r\n" not in data:
                    chunk = conn.recv(4096)
                    if not chunk:
                        break
                    data += chunk
                first = data.split(b"\r\n", 1)[0]
                key = b" ".join(first.split(b" ")[:2])
                conn.sendall(responses.get(key, responses[b"GET /boom"]))

    t = threading.Thread(target=serve, daemon=True)
    t.start()

    client = lib.xn_http_client_new(b"127.0.0.1", port)
    assert client

    def call(req):
        buf = XnBuffer(None, 0)
        rc = lib.xn_http_transport(client, req, None, 0, ctypes.byref(buf))
        data = ctypes.string_at(buf.data, buf.len) if buf.data else b""
        return rc, data

    rc, data = call(b"GET /chunked")
    assert rc == 0 and data == payload  # extensions skipped, exact re-assembly
    rc, data = call(b"GET /plain")
    assert rc == 0 and data == payload  # Content-Length bounds the body
    rc, _ = call(b"GET /empty")
    assert rc == 1  # 204 -> empty
    rc, _ = call(b"GET /boom")
    assert rc == -500  # error status surfaces as negative
    lib.xn_http_client_free(client)
    srv.close()
