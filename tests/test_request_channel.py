"""Request-channel lifecycle: depth gauge accuracy, shutdown, purge.

Regression coverage for the ``xaynet_request_queue_depth`` gauge (it must
move on enqueue, dequeue, phase-end purge AND close — drift here hides a
phase falling behind its ingest) and for the channel edge cases: a closed
channel must fail in-flight ``request()`` calls instead of hanging them,
and stale-phase envelopes are rejected at phase end (including every member
of a coalesced micro-batch).
"""

import asyncio

import pytest

from xaynet_tpu.server.events import PhaseName
from xaynet_tpu.server.phases.base import PhaseState, Shared, _Counter
from xaynet_tpu.server.requests import (
    ChannelClosed,
    CoalescedUpdates,
    RequestError,
    RequestReceiver,
    SumRequest,
    UpdateRequest,
)
from xaynet_tpu.telemetry.registry import get_registry


def _depth(tenant: str = "default") -> float:
    return get_registry().sample_value(
        "xaynet_request_queue_depth", {"tenant": tenant}
    )


def _req(i: int = 0) -> SumRequest:
    return SumRequest(participant_pk=bytes([i]) * 32, ephm_pk=b"\x01" * 32)


def _update_req(i: int = 0) -> UpdateRequest:
    return UpdateRequest(participant_pk=bytes([i]) * 32, local_seed_dict={}, masked_model=None)


def test_depth_gauge_tracks_enqueue_dequeue_and_purge():
    async def run():
        rx = RequestReceiver()
        tx = rx.sender()
        futs = [asyncio.ensure_future(tx.request(_req(i))) for i in range(3)]
        await asyncio.sleep(0)  # let the sends enqueue
        assert _depth() == 3  # enqueue moves the gauge

        env = await rx.next_request()
        assert _depth() == 2  # dequeue moves the gauge
        env.response.set_result(None)

        # phase-end purge: reject everything still queued
        shared = Shared(
            state=None, request_rx=rx, events=None, store=None, settings=None, metrics=None
        )
        phase = PhaseState(shared)
        await phase.purge_outdated_requests()
        assert _depth() == 0  # purge moves the gauge

        await futs[0]
        for fut in futs[1:]:
            with pytest.raises(RequestError, match="phase ended"):
                await fut

    asyncio.run(run())


def test_close_never_counts_the_sentinel_and_zeroes_the_gauge():
    async def run():
        rx = RequestReceiver()
        tx = rx.sender()
        fut = asyncio.ensure_future(tx.request(_req()))
        await asyncio.sleep(0)
        assert _depth() == 1
        rx.close()
        assert _depth() == 0  # queued request rejected; sentinel not counted
        with pytest.raises(RequestError, match="shut down"):
            await fut
        with pytest.raises(ChannelClosed):
            await rx.next_request()
        assert _depth() == 0

    asyncio.run(run())


def test_close_fails_in_flight_request_instead_of_hanging():
    async def run():
        rx = RequestReceiver()
        tx = rx.sender()
        in_flight = asyncio.ensure_future(tx.request(_req()))
        await asyncio.sleep(0)  # request is enqueued, nobody consuming
        rx.close()
        with pytest.raises(RequestError, match="shut down"):
            await asyncio.wait_for(in_flight, timeout=1.0)
        # sends after close are refused immediately
        with pytest.raises(RequestError, match="shut down"):
            await tx.request(_req(1))

    asyncio.run(run())


def test_bounded_channel_rejects_overflow():
    async def run():
        rx = RequestReceiver(maxsize=2)
        tx = rx.sender()
        futs = [asyncio.ensure_future(tx.request(_req(i))) for i in range(2)]
        await asyncio.sleep(0)
        with pytest.raises(RequestError, match="channel full"):
            await tx.request(_req(9))
        rx.close()
        for fut in futs:
            with pytest.raises(RequestError):
                await fut

    asyncio.run(run())


def test_purge_rejects_stale_phase_envelopes_including_coalesced_members():
    """Envelopes left over when a phase ends are rejected — and a coalesced
    micro-batch resolves EVERY member future, not just the envelope."""

    async def run():
        rx = RequestReceiver()
        tx = rx.sender()
        loop = asyncio.get_running_loop()
        members = [_update_req(1), _update_req(2)]
        responses = [loop.create_future() for _ in members]
        batch = CoalescedUpdates(members=members, responses=responses)
        batch_fut = asyncio.ensure_future(tx.request(batch))
        stale = asyncio.ensure_future(tx.request(_update_req(3)))
        await asyncio.sleep(0)
        assert _depth() == 2  # one coalesced envelope + one plain envelope

        shared = Shared(
            state=None, request_rx=rx, events=None, store=None, settings=None, metrics=None
        )
        await PhaseState(shared).purge_outdated_requests()
        assert _depth() == 0

        with pytest.raises(RequestError, match="phase ended"):
            await batch_fut
        with pytest.raises(RequestError, match="phase ended"):
            await stale
        for member in responses:
            assert member.done()
            with pytest.raises(RequestError, match="phase ended"):
                member.result()

    asyncio.run(run())


def test_infrastructure_failure_mid_coalesced_batch_resolves_every_future():
    """A non-protocol exception on member k must still resolve member k
    (INTERNAL), every later member, and the envelope — a dangling future
    would wedge the coalescer's shard worker for the life of the process."""

    class BoomPhase(PhaseState):
        NAME = PhaseName.UPDATE

        async def handle_request(self, req):
            if req.participant_pk[0] == 2:
                raise RuntimeError("storage outage")

    async def run():
        rx = RequestReceiver()
        tx = rx.sender()
        loop = asyncio.get_running_loop()
        members = [_update_req(1), _update_req(2), _update_req(3)]
        responses = [loop.create_future() for _ in members]
        batch_fut = asyncio.ensure_future(
            tx.request(CoalescedUpdates(members=members, responses=responses))
        )
        await asyncio.sleep(0)
        env = await rx.next_request()
        shared = Shared(
            state=None, request_rx=rx, events=None, store=None, settings=None, metrics=None
        )
        with pytest.raises(RuntimeError, match="storage outage"):
            await BoomPhase(shared)._process_single(env, _Counter(0, 10))
        assert all(fut.done() for fut in responses)
        assert responses[0].exception() is None  # accepted before the outage
        with pytest.raises(RequestError, match="storage outage"):
            responses[1].result()
        with pytest.raises(RequestError, match="storage outage"):
            responses[2].result()
        with pytest.raises(RequestError, match="storage outage"):
            await batch_fut

    asyncio.run(run())


def test_cancellation_mid_coalesced_batch_resolves_every_future():
    """The phase window expiring (wait_for cancellation) mid-batch must
    resolve the envelope and every member future, same as an exception."""

    class HangPhase(PhaseState):
        NAME = PhaseName.UPDATE

        async def handle_request(self, req):
            if req.participant_pk[0] == 2:
                await asyncio.Event().wait()  # parks until cancelled

    async def run():
        rx = RequestReceiver()
        tx = rx.sender()
        loop = asyncio.get_running_loop()
        members = [_update_req(1), _update_req(2), _update_req(3)]
        responses = [loop.create_future() for _ in members]
        batch_fut = asyncio.ensure_future(
            tx.request(
                CoalescedUpdates(members=members, responses=responses, request_ids=list("abc"))
            )
        )
        await asyncio.sleep(0)
        env = await rx.next_request()
        shared = Shared(
            state=None, request_rx=rx, events=None, store=None, settings=None, metrics=None
        )
        worker = asyncio.ensure_future(HangPhase(shared)._process_single(env, _Counter(0, 10)))
        await asyncio.sleep(0.05)  # member 1 accepted, member 2 parked
        worker.cancel()
        with pytest.raises(asyncio.CancelledError):
            await worker
        assert all(fut.done() for fut in responses)
        assert responses[0].exception() is None
        for parked in responses[1:]:
            with pytest.raises(RequestError):
                parked.result()
        with pytest.raises(RequestError):
            await asyncio.wait_for(batch_fut, timeout=1.0)

    asyncio.run(run())


def test_close_rejects_coalesced_members():
    async def run():
        rx = RequestReceiver()
        tx = rx.sender()
        loop = asyncio.get_running_loop()
        responses = [loop.create_future()]
        batch_fut = asyncio.ensure_future(
            tx.request(CoalescedUpdates(members=[_update_req()], responses=responses))
        )
        await asyncio.sleep(0)
        rx.close()
        with pytest.raises(RequestError, match="shut down"):
            await batch_fut
        with pytest.raises(RequestError, match="shut down"):
            responses[0].result()

    asyncio.run(run())
