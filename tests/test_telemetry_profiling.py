"""Kernel profiling hooks: device-synced timing records, the round-stat
window, the enable toggle, and the reporter integration (crypto-free)."""

import json

import jax.numpy as jnp

from xaynet_tpu.telemetry import BridgedMetrics, RoundReporter, get_registry
from xaynet_tpu.telemetry import profiling


def _kernel_calls(op: str) -> float:
    return get_registry().sample_value("xaynet_kernel_calls_total", {"op": op}) or 0


def _kernel_elements(op: str) -> float:
    return get_registry().sample_value("xaynet_kernel_elements_total", {"op": op}) or 0


def test_timed_kernel_records_and_syncs_device_work():
    profiling.drain_round_stats()  # fresh window
    calls0 = _kernel_calls("t_fold")
    elements0 = _kernel_elements("t_fold")

    out = profiling.timed_kernel("t_fold", 1024, lambda: jnp.arange(1024) * 2)
    assert int(out[3]) == 6  # result passes through, already synced

    assert _kernel_calls("t_fold") == calls0 + 1
    assert _kernel_elements("t_fold") == elements0 + 1024
    rate = get_registry().sample_value(
        "xaynet_kernel_elements_per_second", {"op": "t_fold"}
    )
    assert rate is not None and rate > 0
    hist = get_registry().get("xaynet_kernel_seconds").labels(op="t_fold")
    assert hist.count >= 1

    stats = profiling.drain_round_stats()
    assert stats["t_fold"]["calls"] == 1
    assert stats["t_fold"]["elements"] == 1024
    assert stats["t_fold"]["seconds"] > 0
    assert stats["t_fold"]["elements_per_sec"] > 0
    # the window resets on drain
    assert "t_fold" not in profiling.drain_round_stats()


def test_profiling_disable_is_pass_through(monkeypatch):
    monkeypatch.setenv("XAYNET_KERNEL_PROFILE", "0")
    assert not profiling.enabled()
    calls0 = _kernel_calls("t_off")
    result = profiling.timed_kernel("t_off", 10, lambda: "unchanged")
    assert result == "unchanged"
    assert _kernel_calls("t_off") == calls0  # nothing recorded
    monkeypatch.setenv("XAYNET_KERNEL_PROFILE", "1")
    assert profiling.enabled()


def test_measure_and_calibration_gauge():
    out, seconds = profiling.measure(lambda: jnp.ones(16).sum())
    assert float(out) == 16.0
    assert seconds >= 0
    profiling.record_calibration("xla", 0.025)
    assert (
        get_registry().sample_value("xaynet_kernel_calibration_seconds", {"kernel": "xla"})
        == 0.025
    )
    assert 'xaynet_kernel_calibration_seconds{kernel="xla"} 0.025' in get_registry().render()


def test_first_call_gauge_marks_compile_outlier():
    calls_before = _kernel_calls("t_cold")
    assert calls_before == 0  # op name unique to this test
    profiling.record("t_cold", 2.5, 10)  # first call: slow (compile-like)
    profiling.record("t_cold", 0.1, 10)
    assert (
        get_registry().sample_value("xaynet_kernel_first_call_seconds", {"op": "t_cold"})
        == 2.5
    )
    assert _kernel_calls("t_cold") == 2  # both still count in the main series


def test_bad_report_path_never_raises(tmp_path):
    reporter = RoundReporter(str(tmp_path / "no_such_dir" / "rounds.jsonl"))
    m = BridgedMetrics(reporter=reporter)
    m.round_total(1)
    m.phase_duration(1, "sum", 0.1)
    m.close()  # flush must swallow the OSError, not take the caller down
    assert reporter.last_report["round_id"] == 1


def test_round_report_includes_kernel_stats(tmp_path):
    profiling.drain_round_stats()  # isolate from other tests' windows
    path = str(tmp_path / "rounds.jsonl")
    m = BridgedMetrics(reporter=RoundReporter(path))
    m.round_total(7)
    m.phase(7, "update")
    profiling.record("masked_add", 0.5, 1_000_000)
    m.phase_duration(7, "update", 1.5)
    m.message_accepted(7, "update")
    m.close()  # flushes the in-flight round

    with open(path) as f:
        reports = [json.loads(line) for line in f if line.strip()]
    assert len(reports) == 1
    report = reports[0]
    assert report["round_id"] == 7
    assert report["phases"] == ["update"]
    assert report["phase_durations"]["update"] == 1.5
    assert report["messages"]["update"]["accepted"] == 1
    assert report["kernels"]["masked_add"]["calls"] == 1
    assert report["kernels"]["masked_add"]["elements"] == 1_000_000
    assert report["kernels"]["masked_add"]["elements_per_sec"] == 2_000_000
