"""C FFI: a pure-C host drives a participant against a live coordinator."""

import asyncio
import os
import subprocess
import threading

_COORDINATORS: list = []


import pytest as _pytest


@_pytest.fixture(autouse=True)
def _stop_coordinators():
    yield
    while _COORDINATORS:
        info = _COORDINATORS.pop()
        loop, task = info.get("loop"), info.get("task")
        if loop is not None and task is not None:
            try:
                loop.call_soon_threadsafe(task.cancel)
            except Exception:
                pass


import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEMO = os.path.join(REPO, "native", "ffi_demo")


def _build_demo() -> bool:
    try:
        subprocess.run(
            ["make", "-s", "-C", os.path.join(REPO, "native"), "ffi", "ffi_demo"],
            check=True,
            capture_output=True,
            timeout=180,
        )
        return os.path.exists(DEMO)
    except Exception:
        return False


pytestmark = pytest.mark.skipif(not _build_demo(), reason="C toolchain/libpython unavailable")


def _start_coordinator():
    from xaynet_tpu.server.rest import RestServer
    from xaynet_tpu.server.services import Fetcher, PetMessageHandler
    from xaynet_tpu.server.settings import Settings
    from xaynet_tpu.server.state_machine import StateMachineInitializer
    from xaynet_tpu.storage.memory import (
        InMemoryCoordinatorStorage,
        InMemoryModelStorage,
        NoOpTrustAnchor,
    )
    from xaynet_tpu.storage.traits import Store

    settings = Settings.default()
    settings.model.length = 4
    info, started = {}, threading.Event()

    def run():
        async def main():
            store = Store(InMemoryCoordinatorStorage(), InMemoryModelStorage(), NoOpTrustAnchor())
            machine, tx, events = await StateMachineInitializer(settings, store).init()
            rest = RestServer(Fetcher(events), PetMessageHandler(events, tx))
            host, port = await rest.start("127.0.0.1", 0)
            info["url"] = f"http://{host}:{port}"
            info["loop"] = asyncio.get_running_loop()
            task = asyncio.ensure_future(machine.run())
            info["task"] = task
            started.set()
            try:
                await task
            except asyncio.CancelledError:
                pass

        asyncio.run(main())

    threading.Thread(target=run, daemon=True).start()
    assert started.wait(10)
    _COORDINATORS.append(info)
    return info["url"]


def test_c_host_drives_participant():
    url = _start_coordinator()
    env = dict(os.environ, JAX_PLATFORMS="cpu", XAYNET_TPU_NO_NATIVE="")
    result = subprocess.run(
        [DEMO, url, REPO], capture_output=True, text=True, timeout=120, env=env
    )
    assert result.returncode == 0, result.stderr[-800:]
    out = result.stdout
    assert "abi=1" in out
    assert "tick=4" in out
    assert "set_model=ok" in out
    assert "saved=" in out
    assert "restored_tick=ok" in out
    assert "done" in out
