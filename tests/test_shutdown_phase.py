"""Shutdown phase: in-flight requests are drained/rejected, the channel
closes exactly once, and a second close is a harmless no-op."""

import asyncio

import pytest

from xaynet_tpu.server.events import EventPublisher, PhaseName
from xaynet_tpu.server.phases.base import Shared
from xaynet_tpu.server.phases.shutdown import Shutdown
from xaynet_tpu.server.requests import (
    CoalescedUpdates,
    RequestError,
    RequestReceiver,
    SumRequest,
    UpdateRequest,
)
from xaynet_tpu.server.settings import Settings


def _shared(rx: RequestReceiver) -> Shared:
    class _State:
        round_id = 3

    events = EventPublisher(3, None, None, PhaseName.SUM)
    return Shared(
        state=_State(), request_rx=rx, events=events, store=None,
        settings=Settings.default(),
    )


def test_shutdown_drains_inflight_and_closes_channel_exactly_once():
    async def run():
        rx = RequestReceiver()
        shared = _shared(rx)
        sender = rx.sender()
        loop = asyncio.get_running_loop()

        # three queued singles + one coalesced micro-batch, all in flight
        singles = [
            asyncio.create_task(sender.request(SumRequest(bytes([i]) * 4, b"e")))
            for i in range(3)
        ]
        members = [
            UpdateRequest(b"u1" * 16, {}, None),
            UpdateRequest(b"u2" * 16, {}, None),
        ]
        member_futs = [loop.create_future() for _ in members]
        batch = asyncio.create_task(
            sender.request(CoalescedUpdates(members=members, responses=member_futs))
        )
        await asyncio.sleep(0)  # let every request enqueue

        close_calls = []
        orig_close = rx.close

        def counting_close():
            close_calls.append(1)
            orig_close()

        rx.close = counting_close

        result = await Shutdown(shared).run_phase()
        assert result is None  # the machine terminates after Shutdown

        # the phase closed the channel exactly once
        assert close_calls == [1]

        # every queued request was rejected, none left hanging
        for task in singles + [batch]:
            with pytest.raises(RequestError) as ei:
                await task
            assert ei.value.kind == RequestError.Kind.INTERNAL
        for fut in member_futs:
            assert fut.done()
            assert isinstance(fut.exception(), RequestError)

        # the drain consumed the shutdown sentinel and left nothing queued
        assert rx.try_recv() is None

        # second close: idempotent no-op (no double sentinel, no error)
        rx.close()
        assert close_calls == [1, 1]
        assert rx.try_recv() is None

        # post-shutdown submissions fail fast instead of hanging
        with pytest.raises(RequestError) as ei:
            await sender.request(SumRequest(b"late" * 8, b"e"))
        assert ei.value.kind == RequestError.Kind.INTERNAL

    asyncio.run(asyncio.wait_for(run(), 20))


def test_shutdown_on_empty_channel_is_clean():
    async def run():
        rx = RequestReceiver()
        shared = _shared(rx)
        assert await Shutdown(shared).run_phase() is None
        # sentinel consumed, queue empty, channel refuses new work
        assert rx.try_recv() is None
        with pytest.raises(RequestError):
            await rx.sender().request(SumRequest(b"x" * 32, b"e"))

    asyncio.run(asyncio.wait_for(run(), 20))
