"""Protocol edge behavior: window semantics, mask election, oversized input."""

import asyncio

import numpy as np
import pytest

from xaynet_tpu.core.crypto.prng import uniform_ints
from xaynet_tpu.core.mask import (
    Aggregation,
    BoundType,
    DataType,
    GroupType,
    MaskConfig,
    MaskObject,
    ModelType,
    UnmaskingError,
)
from xaynet_tpu.server.phases.base import PhaseState, PhaseTimeout, Shared
from xaynet_tpu.server.requests import RequestError, RequestReceiver, SumRequest
from xaynet_tpu.server.settings import CountSettings, PhaseSettings, TimeSettings

CFG = MaskConfig(GroupType.PRIME, DataType.F32, BoundType.B0, ModelType.M3)


class _AcceptAll(PhaseState):
    from xaynet_tpu.server.events import PhaseName

    NAME = PhaseName.SUM

    async def handle_request(self, req):
        if getattr(req, "participant_pk", b"") == b"reject":
            raise RequestError(RequestError.Kind.MESSAGE_REJECTED, "test")


def _shared():
    from xaynet_tpu.server.events import EventPublisher, PhaseName
    from xaynet_tpu.server.settings import Settings

    class _State:
        round_id = 1

    settings = Settings.default()
    events = EventPublisher(1, None, None, PhaseName.SUM)
    return Shared(
        state=_State(), request_rx=RequestReceiver(), events=events,
        store=None, settings=settings,
    )


def _params(cmin, cmax, tmin, tmax):
    return PhaseSettings(
        prob=0.5, count=CountSettings(cmin, cmax), time=TimeSettings(tmin, tmax)
    )


def test_window_discards_beyond_count_max():
    """During [0, time.min], requests beyond count.max are discarded."""

    async def run():
        shared = _shared()
        phase = _AcceptAll(shared)
        sender = shared.request_rx.sender()

        outcomes = []

        async def send(pk):
            try:
                await sender.request(SumRequest(pk, b"e"))
                outcomes.append("accepted")
            except RequestError as e:
                outcomes.append(e.kind.value)

        senders = [asyncio.create_task(send(bytes([i]) * 4)) for i in range(5)]
        await phase.process_requests(_params(1, 2, 0.3, 5.0))
        await asyncio.gather(*senders)
        assert outcomes.count("accepted") == 2
        assert outcomes.count(RequestError.Kind.MESSAGE_DISCARDED.value) == 3

    asyncio.run(asyncio.wait_for(run(), 20))


def test_window_timeout_when_below_count_min():
    async def run():
        shared = _shared()
        phase = _AcceptAll(shared)
        sender = shared.request_rx.sender()
        task = asyncio.create_task(sender.request(SumRequest(b"a", b"e")))
        with pytest.raises(PhaseTimeout):
            await phase.process_requests(_params(3, 5, 0.0, 0.4))
        await task  # the single accepted request still gets its response

    asyncio.run(asyncio.wait_for(run(), 20))


def test_window_rejected_does_not_count():
    async def run():
        shared = _shared()
        phase = _AcceptAll(shared)
        sender = shared.request_rx.sender()

        async def send(pk):
            try:
                await sender.request(SumRequest(pk, b"e"))
                return "ok"
            except RequestError as e:
                return e.kind.value

        tasks = [asyncio.create_task(send(b"reject")) for _ in range(3)]
        tasks.append(asyncio.create_task(send(b"good")))
        await phase.process_requests(_params(1, 5, 0.0, 5.0))
        results = await asyncio.gather(*tasks)
        assert results.count("ok") == 1

    asyncio.run(asyncio.wait_for(run(), 20))


def _rand_mask(seed, n=6):
    ints = uniform_ints(bytes([seed]) * 32, n + 1, CFG.order)
    return MaskObject.new(CFG.pair(), ints[1:], ints[0])


def test_wrong_mask_unmasks_to_garbage_but_safely():
    """A structurally valid but wrong winning mask yields garbage, not a crash
    (the reference documents the same property: validity checks are about
    structure; correctness comes from the mask election)."""
    masked = _rand_mask(1)
    right = _rand_mask(1)  # identical derivation = the true mask
    wrong = _rand_mask(2)
    agg = Aggregation.from_object(masked)
    agg.validate_unmasking(wrong)  # passes structural checks
    out_wrong = agg.unmask_array(wrong)
    assert np.all(np.isfinite(out_wrong))

    agg2 = Aggregation.from_object(_rand_mask(1))
    out_right = agg2.unmask_array(right)
    # unmasking with the true mask gives exact zeros-shifted values;
    # with the wrong mask it differs
    assert not np.allclose(out_wrong, out_right)


def test_unmask_length_mismatch_rejected():
    masked = _rand_mask(1, n=6)
    short_mask = _rand_mask(2, n=5)
    agg = Aggregation.from_object(masked)
    with pytest.raises(UnmaskingError):
        agg.validate_unmasking(short_mask)


def test_rest_rejects_oversized_body():
    async def run():
        from xaynet_tpu.server.rest import RestServer

        server = RestServer(fetcher=None, handler=None)
        host, port = await server.start("127.0.0.1", 0)
        try:
            reader, writer = await asyncio.open_connection(host, port)
            writer.write(
                f"POST /message HTTP/1.1\r\nHost: x\r\nContent-Length: {1 << 33}\r\n\r\n".encode()
            )
            await writer.drain()
            status = await reader.readline()
            assert b"413" in status
            writer.close()
        finally:
            await server.stop()

    asyncio.run(asyncio.wait_for(run(), 20))
