"""Resilient SDK client: typed error taxonomy, retry wrapper, participant
state-machine recovery, and the participant-side chaos sites.

Pins the PR-5 SDK contracts:

1. **typed errors** — HTTP statuses map onto the
   ``ClientShedError``/``ClientTransientError``/``ClientPermanentError``
   hierarchy (429 carrying ``Retry-After``), so callers classify without
   string-matching;
2. **retry wrapper** — transient failures retry on the decorrelated-jitter
   schedule with the server's ``Retry-After`` as a floor, permanent ones
   fail on the first attempt;
3. **same-round recovery** — a transient failure inside a phase step keeps
   the participant IN its phase (resumed next tick), while a permanent
   send rejection abandons the upload instead of retrying forever;
4. **chaos sites** — ``sdk.drop`` loses a send on the wire,
   ``sdk.straggle`` delays it, ``sdk.send`` fails attempts (retried), and
   the ``flood`` dropout/straggler knobs are deterministic per seed.
"""

import asyncio
import random
import time
from fractions import Fraction

import numpy as np
import pytest

from xaynet_tpu.core.common import RoundParameters, RoundSeed
from xaynet_tpu.core.mask.config import (
    BoundType,
    DataType,
    GroupType,
    MaskConfig,
    ModelType,
)
from xaynet_tpu.resilience import FaultPlan, RetryPolicy, clear_plan, install_plan
from xaynet_tpu.sdk.client import (
    ClientPermanentError,
    ClientShedError,
    ClientTransientError,
    ResilientClient,
    classify_status,
)
from xaynet_tpu.sdk.simulation import flood, plan_churn
from xaynet_tpu.sdk.state_machine import (
    PetSettings,
    PhaseKind,
    StateMachine,
    TransitionOutcome,
)
from xaynet_tpu.sdk.traits import ModelStore, XaynetClient


@pytest.fixture(autouse=True)
def _no_leftover_fault_plan():
    clear_plan()
    yield
    clear_plan()


def _fast_policy(attempts=4) -> RetryPolicy:
    return RetryPolicy(
        max_attempts=attempts,
        base_delay_s=0.001,
        max_delay_s=0.005,
        deadline_s=5.0,
        rng=random.Random(3),
    )


# --------------------------------------------------------------------------
# Typed status mapping
# --------------------------------------------------------------------------


def test_classify_status_hierarchy():
    shed = classify_status(429, 2.5, "POST /message")
    assert isinstance(shed, ClientShedError) and shed.transient
    assert shed.retry_after == 2.5 and shed.status == 429

    # any 5xx except 501 is transient — proxies in front of a coordinator
    # emit plenty beyond the 502/503/504 gateway family
    for status in (408, 425, 500, 502, 503, 504, 507, 520, 529, 599):
        err = classify_status(status, None, "GET /params")
        assert isinstance(err, ClientTransientError) and err.transient
        assert not isinstance(err, ClientShedError)

    for status in (400, 403, 404, 413, 501):
        err = classify_status(status, None, "GET /params")
        assert isinstance(err, ClientPermanentError) and not err.transient

    # 503 + Retry-After keeps the server's floor
    assert classify_status(503, 1.5, "GET /sums").retry_after == 1.5

    # typed markers drive the shared transient classifier
    from xaynet_tpu.resilience.policy import is_transient

    assert is_transient(ClientTransientError("x"))
    assert not is_transient(ClientPermanentError("x"))


def test_redirects_are_errors_not_success():
    """The client never follows redirects, so a 3xx is a failed call (a
    misconfigured proxy), never a silent success that loses the upload."""
    from xaynet_tpu.sdk.client import HttpClient

    client = HttpClient("http://h")
    for status in (301, 302, 307, 308):
        err = classify_status(status, None, "GET /params")
        assert isinstance(err, ClientPermanentError) and not err.transient
        with pytest.raises(ClientPermanentError):
            client._raise_for_status(status, {}, "GET /params")
    client._raise_for_status(200, {}, "GET /params")  # 2xx passes


def test_http_client_stalled_peer_times_out_transient():
    """A peer that sends the status line then stalls mid-body must surface
    as a fast ClientTransientError (idle read timeout), not hang the
    participant forever."""

    async def run():
        async def handler(reader, writer):
            await reader.readline()
            writer.write(b"HTTP/1.1 200 OK\r\nContent-Length: 10\r\n\r\n")
            await writer.drain()
            await asyncio.sleep(10)  # the body never arrives

        server = await asyncio.start_server(handler, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        from xaynet_tpu.sdk.client import HttpClient

        client = HttpClient(f"http://127.0.0.1:{port}", timeout=0.2)
        t0 = time.monotonic()
        with pytest.raises(ClientTransientError):
            await client.get_model()
        assert time.monotonic() - t0 < 5.0  # idle timeout, not the 10s stall
        server.close()
        await server.wait_closed()

    asyncio.run(asyncio.wait_for(run(), 20))


def test_fsm_transient_classifier_excludes_local_oserrors():
    """The FSM's stay-in-phase retry must not spin forever on a LOCAL
    fault: generic OSErrors (a model store's FileNotFoundError) propagate;
    typed markers and connection/timeout builtins stay transient."""
    from xaynet_tpu.sdk.state_machine import _is_transient_client_error

    assert _is_transient_client_error(ClientTransientError("x"))
    assert _is_transient_client_error(ConnectionResetError())
    assert _is_transient_client_error(asyncio.TimeoutError())
    assert not _is_transient_client_error(ClientPermanentError("x"))
    assert not _is_transient_client_error(FileNotFoundError("model.npz"))
    assert not _is_transient_client_error(PermissionError("denied"))


# --------------------------------------------------------------------------
# ResilientClient
# --------------------------------------------------------------------------


class _FlakyClient(XaynetClient):
    """Scripted inner client: pops one error per call until the script is
    exhausted, then succeeds."""

    def __init__(self, errors=()):
        self.errors = list(errors)
        self.calls = {"params": 0, "sums": 0, "seeds": 0, "model": 0, "send": 0}
        self.sent = []

    def _maybe_fail(self, endpoint):
        self.calls[endpoint] += 1
        if self.errors:
            raise self.errors.pop(0)

    async def get_round_params(self):
        self._maybe_fail("params")
        return "params"

    async def get_sums(self):
        self._maybe_fail("sums")
        return {}

    async def get_seeds(self, pk):
        self._maybe_fail("seeds")
        return {}

    async def get_model(self):
        self._maybe_fail("model")
        return None

    async def send_message(self, encrypted):
        self._maybe_fail("send")
        self.sent.append(encrypted)


def test_resilient_client_retries_transient_then_succeeds():
    inner = _FlakyClient([ClientTransientError("a"), ClientTransientError("b")])
    client = ResilientClient(inner, policy=_fast_policy())
    assert asyncio.run(client.get_round_params()) == "params"
    assert inner.calls["params"] == 3


def test_resilient_client_permanent_fails_on_first_attempt():
    inner = _FlakyClient([ClientPermanentError("no", status=404)])
    client = ResilientClient(inner, policy=_fast_policy())
    with pytest.raises(ClientPermanentError):
        asyncio.run(client.get_model())
    assert inner.calls["model"] == 1


def test_resilient_client_honors_retry_after_floor():
    floor = 0.15
    inner = _FlakyClient([ClientShedError("shed", status=429, retry_after=floor)])
    client = ResilientClient(inner, policy=_fast_policy())
    t0 = time.monotonic()
    asyncio.run(client.send_message(b"x"))
    elapsed = time.monotonic() - t0
    assert elapsed >= floor  # jitter delay (~1ms) was floored by Retry-After
    assert inner.sent == [b"x"]


def test_resilient_client_gives_up_after_policy_and_raises_last():
    inner = _FlakyClient([ClientTransientError(f"t{i}") for i in range(10)])
    client = ResilientClient(inner, policy=_fast_policy(attempts=3))
    with pytest.raises(ClientTransientError) as ei:
        asyncio.run(client.get_sums())
    assert str(ei.value) == "t2"  # the LAST error propagates
    assert inner.calls["sums"] == 3


def test_sdk_fault_sites_drop_straggle_send():
    install_plan(
        FaultPlan.parse(
            "seed=5;sdk.drop:error,nth=1;sdk.straggle:latency,delay=0.1,nth=2;"
            "sdk.send:error,nth=1"
        )
    )
    inner = _FlakyClient()
    client = ResilientClient(inner, policy=_fast_policy())

    # send 1: dropped on the wire — "succeeds" but the inner never sees it
    asyncio.run(client.send_message(b"one"))
    assert inner.sent == []

    # send 2: straggles 0.1s, then the first ATTEMPT hits sdk.send and is
    # retried transparently — the message still lands exactly once
    t0 = time.monotonic()
    asyncio.run(client.send_message(b"two"))
    assert time.monotonic() - t0 >= 0.1
    assert inner.sent == [b"two"]

    # send 3: clean
    asyncio.run(client.send_message(b"three"))
    assert inner.sent == [b"two", b"three"]


# --------------------------------------------------------------------------
# Participant state machine recovery
# --------------------------------------------------------------------------

_CFG = MaskConfig(GroupType.PRIME, DataType.F32, BoundType.B0, ModelType.M3)


def _round_params(seed=b"\x07" * 32, sum_prob=0.0, update_prob=0.999):
    return RoundParameters(
        pk=b"\x01" * 32,
        sum=sum_prob,
        update=update_prob,
        seed=RoundSeed(seed),
        mask_config=_CFG.pair(),
        model_length=4,
    )


class _ScriptedClient(XaynetClient):
    def __init__(self, params, sums_errors=(), send_errors=()):
        self.params = params
        self.sums_errors = list(sums_errors)
        self.send_errors = list(send_errors)
        self.sums_calls = 0
        self.sent = []

    async def get_round_params(self):
        return self.params

    async def get_sums(self):
        self.sums_calls += 1
        if self.sums_errors:
            raise self.sums_errors.pop(0)
        return {b"\x02" * 32: b"\x03" * 32}

    async def get_seeds(self, pk):
        return None

    async def get_model(self):
        return None

    async def send_message(self, encrypted):
        if self.send_errors:
            raise self.send_errors.pop(0)
        self.sent.append(encrypted)


class _ArrayStore(ModelStore):
    def __init__(self, model):
        self.model = model

    async def load_model(self):
        return self.model


def _update_machine(client):
    """A machine whose key takes the UPDATE task for the scripted round."""
    from xaynet_tpu.sdk.simulation import keys_for_task

    params = client.params
    keys = keys_for_task(params.seed.as_bytes(), params.sum, params.update, "update")
    return StateMachine(
        PetSettings(keys=keys, scalar=Fraction(1, 1), max_message_size=None),
        client,
        _ArrayStore(np.zeros(4, dtype=np.float32)),
    )


def test_sm_stays_in_phase_on_transient_failure_and_resumes():
    async def run():
        client = _ScriptedClient(
            _round_params(), sums_errors=[ClientTransientError("conn reset")]
        )
        sm = _update_machine(client)
        # tick 1: fresh params -> NEW_ROUND handler -> UPDATE task
        assert await sm.transition() == TransitionOutcome.COMPLETE
        assert sm.phase == PhaseKind.UPDATE
        # transient get_sums failure: PENDING, SAME phase, signatures kept
        sig_before = sm.update_signature
        assert await sm.transition() == TransitionOutcome.PENDING
        assert sm.phase == PhaseKind.UPDATE
        assert sm.update_signature == sig_before
        # next tick resumes within the round and uploads
        assert await sm.transition() == TransitionOutcome.COMPLETE
        assert client.sent, "update never uploaded after recovery"
        assert sm.phase == PhaseKind.AWAITING

    asyncio.run(asyncio.wait_for(run(), 30))


def test_sm_abandons_send_on_permanent_rejection():
    async def run():
        client = _ScriptedClient(
            _round_params(),
            send_errors=[ClientPermanentError("payload too large", status=413)],
        )
        sm = _update_machine(client)
        await sm.transition()  # fresh params -> NEW_ROUND -> UPDATE task
        assert sm.phase == PhaseKind.UPDATE
        outcome = await sm.transition()  # trains, masks, send -> 413
        assert outcome == TransitionOutcome.COMPLETE
        assert sm.phase == PhaseKind.AWAITING  # upload abandoned, not looped
        assert sm._pending is None
        assert client.sent == []
        # later ticks idle instead of resending the rejected payload
        assert await sm.transition() == TransitionOutcome.PENDING
        assert client.sent == []

    asyncio.run(asyncio.wait_for(run(), 30))


def test_sm_retries_send_on_transient_rejection():
    async def run():
        client = _ScriptedClient(
            _round_params(), send_errors=[ClientTransientError("broken pipe")]
        )
        sm = _update_machine(client)
        await sm.transition()  # fresh params -> NEW_ROUND -> UPDATE task
        assert sm.phase == PhaseKind.UPDATE
        assert await sm.transition() == TransitionOutcome.PENDING  # send failed
        assert sm.phase == PhaseKind.UPDATE and sm._pending is not None
        assert await sm.transition() == TransitionOutcome.COMPLETE  # resent
        assert len(client.sent) == 1
        assert sm.phase == PhaseKind.AWAITING

    asyncio.run(asyncio.wait_for(run(), 30))


# --------------------------------------------------------------------------
# flood churn knobs
# --------------------------------------------------------------------------


def test_plan_churn_deterministic_and_disjoint():
    d1, s1 = plan_churn(10, 0.3, 2, seed=42)
    d2, s2 = plan_churn(10, 0.3, 2, seed=42)
    assert d1 == d2 and s1 == s2
    assert len(d1) == 3 and len(s1) == 2
    assert not (d1 & s1)  # stragglers are drawn from the survivors
    d3, _ = plan_churn(10, 0.3, 2, seed=43)
    assert d3 != d1 or plan_churn(10, 0.3, 2, seed=43)[1] != s1

    with pytest.raises(ValueError):
        plan_churn(10, 1.0, 0, seed=1)


def test_flood_dropout_withholds_and_stragglers_delay():
    received = []

    async def sink(blob: bytes) -> None:
        received.append(blob)

    async def run():
        return await flood(
            sink,
            _round_params(),
            {b"\x02" * 32: b"\x03" * 32},
            8,
            dropout_rate=0.25,
            stragglers=2,
            straggle_delay_s=0.05,
            churn_seed=11,
            build=lambda i: bytes([i]),  # payload = index, no crypto needed
        )

    stats = asyncio.run(asyncio.wait_for(run(), 30))
    assert stats.dropped == 2 and len(stats.dropped_indices) == 2
    assert stats.straggled == 2
    assert stats.sent == 6 and stats.accepted == 6
    # exactly the survivors were delivered
    assert sorted(b[0] for b in received) == [
        i for i in range(8) if i not in stats.dropped_indices
    ]

    asyncio.run(asyncio.sleep(0))
