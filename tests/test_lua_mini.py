"""The mini-Lua interpreter that executes the Redis EVAL scripts.

These tests prove the *actual Lua text* in ``storage/redis.py`` parses and
runs (a syntax error there fails here), and pin the Lua 5.1 semantics the
scripts rely on: 1-based tables, ``#``, number/string non-coercing ``==``,
``and``/``or`` returning operands, numeric ``for`` with step, and the Redis
EVAL type-conversion rules.
"""

import pytest
from redis_commands import DictRedisCommands

from xaynet_tpu.storage.redis import (
    ADD_LOCAL_SEED_DICT,
    ADD_SUM_PARTICIPANT,
    INCR_MASK_SCORE,
)
from xaynet_tpu.utils import lua_mini
from xaynet_tpu.utils.lua_mini import LuaError, LuaTable, parse, run_script, to_redis


def run(src, keys=(), argv=(), call=None):
    return run_script(
        src if isinstance(src, bytes) else src.encode(),
        list(keys),
        list(argv),
        call or (lambda *a: None),
    )


# --- language semantics ----------------------------------------------------


def test_return_number_truncates_like_redis():
    assert run("return 7 / 2") == 3  # 3.5 -> integer truncation


def test_tables_are_one_based_and_length():
    assert run("return ARGV[1]", argv=[b"first", b"second"]) == b"first"
    assert run("return #ARGV", argv=[b"a", b"b", b"c"]) == 3
    assert run("return ARGV[#ARGV]", argv=[b"a", b"z"]) == b"z"


def test_out_of_range_index_is_nil():
    assert run("if ARGV[5] == nil then return 1 end return 0", argv=[b"x"]) == 1


def test_number_string_equality_never_coerces():
    # Lua ==: different types are never equal
    assert run('if 1 == "1" then return 1 end return 0') == 0
    assert run('if 1 ~= "1" then return 1 end return 0') == 1


def test_and_or_return_operands():
    assert run("return 0 and 2") == 2  # 0 is truthy in Lua!
    assert run("return nil or 7") == 7
    assert run("return false or nil") is None


def test_numeric_for_with_step():
    src = """
    local acc = 0
    for i = 2, #ARGV, 2 do
      acc = acc + tonumber(ARGV[i])
    end
    return acc
    """
    assert run(src, argv=[b"9", b"1", b"9", b"2", b"9", b"3"]) == 6


def test_for_loop_descending_and_break():
    src = """
    local n = 0
    for i = 10, 1, -1 do
      n = n + 1
      if i == 8 then break end
    end
    return n
    """
    assert run(src) == 3


def test_while_loop():
    src = """
    local i = 0
    while i < 5 do
      i = i + 1
    end
    return i
    """
    assert run(src) == 5


def test_concat_coerces_numbers():
    assert run('return "seed_dict:" .. 42') == b"seed_dict:42"
    assert run("return ARGV[1] .. ARGV[2]", argv=[b"ab", b"cd"]) == b"abcd"


def test_arithmetic_on_string_coerces():
    # Lua arithmetic coerces numeric strings (unlike ==)
    assert run('return "4" + 1') == 5


def test_modulo_matches_lua():
    assert run("return -3 % 5") == 2  # Lua: a - floor(a/b)*b


def test_comments_and_string_escapes():
    assert run('-- leading comment\nreturn "a\\"b" -- trailing') == b'a"b'


def test_scope_shadowing():
    src = """
    local x = 1
    if true then
      local x = 2
    end
    return x
    """
    assert run(src) == 1


def test_table_constructor_and_assignment():
    src = """
    local t = {}
    t[1] = "a"
    t[2] = "b"
    return #t
    """
    assert run(src) == 2


# --- error detection (the reason this interpreter exists) ------------------


def test_syntax_error_missing_end():
    with pytest.raises(LuaError):
        parse(b'if 1 == 1 then return 1')


def test_syntax_error_bad_operator():
    with pytest.raises(LuaError):
        parse(b"return 1 != 2")  # != is not Lua


def test_unreachable_code_after_return():
    with pytest.raises(LuaError):
        parse(b"return 1\nlocal x = 2")


def test_undefined_variable_is_runtime_error():
    with pytest.raises(LuaError):
        run("return undefined_thing")


def test_compare_number_with_string_raises():
    with pytest.raises(LuaError):
        run('return 1 < "2"')


def test_unsupported_construct_rejected():
    with pytest.raises(LuaError):
        parse(b"local function f() return 1 end return f()")


def test_call_error_propagates_like_redis_call():
    def boom(*a):
        raise LuaError("WRONGTYPE")

    with pytest.raises(LuaError):
        run('return redis.call("GET", "k")', call=boom)


# --- Redis conversion rules ------------------------------------------------


def test_to_redis_conversions():
    assert to_redis(None) is None
    assert to_redis(False) is None  # false -> nil
    assert to_redis(True) == 1
    assert to_redis(3.9) == 3  # truncation
    assert to_redis(b"x") == b"x"
    assert to_redis(LuaTable([1.0, b"a", None, 2.0])) == [1, b"a"]  # nil ends array


def test_nil_reply_becomes_false_in_lua():
    # RESP nil -> Lua false: scripts branch on it
    assert run('if redis.call("GET", "k") == false then return 1 end return 0') == 1


def test_status_reply_passthrough():
    assert run('return redis.call("SET", "k", "v")', call=lambda *a: b"OK") == b"OK"


# --- the real scripts, executed as Lua -------------------------------------


class MiniStore(DictRedisCommands):
    """The shared dict-backed command handlers, plus call recording."""

    def __init__(self):
        super().__init__()
        self.calls = []

    def __call__(self, *parts):
        self.calls.append(parts)
        return super().__call__(*parts)


def _seed_entries(pks):
    argv = [b"updater-1"]
    for pk in pks:
        argv += [pk, b"seed-for-" + pk]
    return argv


def test_add_sum_participant_script():
    store = MiniStore()
    assert run_script(ADD_SUM_PARTICIPANT, [b"sum_dict"], [b"pk1", b"ephm1"], store) == 1
    # duplicate pk refused atomically by HSETNX
    assert run_script(ADD_SUM_PARTICIPANT, [b"sum_dict"], [b"pk1", b"other"], store) == 0
    assert store.hashes[b"sum_dict"] == {b"pk1": b"ephm1"}


def test_add_local_seed_dict_script_error_codes():
    store = MiniStore()
    # KEYS[3]: the key-prefixed seed-dict base — the script builds every
    # per-sum-pk hash key from it so tenant prefixes scope the writes too
    keys = [b"sum_dict", b"update_participants", b"seed_dict:"]
    store.hashes[b"sum_dict"] = {b"s1": b"e1", b"s2": b"e2"}

    # -1: length mismatch (only one entry for two sum participants)
    assert run_script(ADD_LOCAL_SEED_DICT, keys, _seed_entries([b"s1"]), store) == -1
    # -2: unknown sum pk
    assert run_script(ADD_LOCAL_SEED_DICT, keys, _seed_entries([b"s1", b"nope"]), store) == -2
    # 0: success writes every per-sum-pk hash and marks the updater
    assert run_script(ADD_LOCAL_SEED_DICT, keys, _seed_entries([b"s1", b"s2"]), store) == 0
    assert store.hashes[b"seed_dict:s1"][b"updater-1"] == b"seed-for-s1"
    assert store.hashes[b"seed_dict:s2"][b"updater-1"] == b"seed-for-s2"
    assert b"updater-1" in store.sets[b"update_participants"]
    # -3: same updater again
    assert run_script(ADD_LOCAL_SEED_DICT, keys, _seed_entries([b"s1", b"s2"]), store) == -3


def test_add_local_seed_dict_partial_submission_detected():
    # -4: updater not in the set but already present in some seed hash
    # (the replay-hazard state after a lost reply)
    store = MiniStore()
    keys = [b"sum_dict", b"update_participants", b"seed_dict:"]
    store.hashes[b"sum_dict"] = {b"s1": b"e1"}
    store.hashes[b"seed_dict:s1"] = {b"updater-1": b"old"}
    assert run_script(ADD_LOCAL_SEED_DICT, keys, _seed_entries([b"s1"]), store) == -4


def test_incr_mask_score_script():
    store = MiniStore()
    keys = [b"sum_dict", b"mask_submitted", b"mask_dict"]
    store.hashes[b"sum_dict"] = {b"s1": b"e1"}

    # -1: not a sum participant
    assert run_script(INCR_MASK_SCORE, keys, [b"intruder", b"mask-a"], store) == -1
    # 0: accepted, mask scored
    assert run_script(INCR_MASK_SCORE, keys, [b"s1", b"mask-a"], store) == 0
    assert store.zsets[b"mask_dict"][b"mask-a"] == 1.0
    # -2: double submission
    assert run_script(INCR_MASK_SCORE, keys, [b"s1", b"mask-a"], store) == -2


def test_scripts_parse_cleanly():
    # pure parse check: any future syntax slip in storage/redis.py fails here
    for script in (ADD_SUM_PARTICIPANT, ADD_LOCAL_SEED_DICT, INCR_MASK_SCORE):
        assert lua_mini.parse(script)


def test_mutated_script_fails_to_parse():
    # the old content-matching fake would happily "run" a broken script;
    # the interpreter must not
    broken = ADD_LOCAL_SEED_DICT.replace(b"then", b"thn", 1)
    with pytest.raises(LuaError):
        lua_mini.parse(broken)


def test_error_reply_raises_and_status_reply_passes():
    with pytest.raises(LuaError, match="wrong state"):
        run('return redis.error_reply("wrong state")')
    assert run('return redis.status_reply("OK")') == b"OK"
