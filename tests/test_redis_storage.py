"""Redis backend against an in-process fake RESP server.

No Redis binary ships in the image, so a miniature RESP2 server implements
the command subset the backend uses. The three Lua scripts are EXECUTED AS
REAL LUA TEXT by ``xaynet_tpu.utils.lua_mini`` against primitive command
handlers — a Lua syntax or semantics error in ``storage/redis.py`` fails
these tests (VERDICT r02 missing item 2). This exercises the real protocol
encoding, the data model and the conditional-insert semantics.

Set ``XAYNET_REDIS=host:port`` to additionally run the data-model tests
against a live Redis server (CI runs them in a redis service container);
the crash/restart fault-injection tests always use the fake, whose process
lifecycle the test controls.
"""

import asyncio
import os

import pytest

from xaynet_tpu.utils import lua_mini

from xaynet_tpu.core.crypto.prng import uniform_ints
from xaynet_tpu.core.mask import BoundType, DataType, GroupType, MaskConfig, MaskObject, ModelType
from xaynet_tpu.storage.redis import RedisCoordinatorStorage
from xaynet_tpu.storage.traits import LocalSeedDictAddError, MaskScoreIncrError, SumPartAddError

CFG = MaskConfig(GroupType.PRIME, DataType.F32, BoundType.B0, ModelType.M3)


from redis_commands import DictRedisCommands


class FakeRedis:
    """Tiny RESP2 server over asyncio streams (test double)."""

    def __init__(self):
        self.strings: dict[bytes, bytes] = {}
        self._commands = DictRedisCommands()
        self._server = None
        self._writers: set = set()
        # fault injection: execute the next EVAL but sever the connection
        # before the reply goes out (the replay-hazard window)
        self.kill_next_eval_reply = False

    async def start(self, port: int = 0):
        """Binds (``port=0`` = ephemeral); data survives stop/start cycles,
        like a Redis that was restarted with persistence."""
        self._server = await asyncio.start_server(self._conn, "127.0.0.1", port)
        return self._server.sockets[0].getsockname()[1]

    async def stop(self):
        """Stops listening AND severs live connections (a real crash)."""
        self._server.close()
        for w in list(self._writers):
            try:
                w.close()
            except Exception:
                pass
        self._writers.clear()
        await self._server.wait_closed()

    async def _conn(self, reader, writer):
        self._writers.add(writer)
        try:
            while True:
                line = await reader.readline()
                if not line:
                    break
                assert line[:1] == b"*"
                n = int(line[1:-2])
                parts = []
                for _ in range(n):
                    ln = await reader.readline()
                    assert ln[:1] == b"$"
                    size = int(ln[1:-2])
                    data = await reader.readexactly(size + 2)
                    parts.append(data[:-2])
                reply = self._dispatch(parts)
                if parts[0].upper() == b"EVAL" and self.kill_next_eval_reply:
                    self.kill_next_eval_reply = False
                    break  # executed, but the reply is lost
                writer.write(reply)
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            self._writers.discard(writer)
            writer.close()

    # --- encoding helpers -------------------------------------------------

    @staticmethod
    def _int(v):
        return b":%d\r\n" % v

    @staticmethod
    def _bulk(v):
        if v is None:
            return b"$-1\r\n"
        return b"$%d\r\n%s\r\n" % (len(v), v)

    @classmethod
    def _array(cls, items):
        return b"*%d\r\n" % len(items) + b"".join(cls._bulk(i) for i in items)

    # --- command dispatch -------------------------------------------------

    def _dispatch(self, parts):
        cmd = parts[0].upper()
        if cmd == b"PING":
            return b"+PONG\r\n"
        if cmd == b"SET":
            self.strings[parts[1]] = parts[2]
            return b"+OK\r\n"
        if cmd == b"GET":
            return self._bulk(self.strings.get(parts[1]))
        if cmd == b"HGETALL":
            h = self.hashes.get(parts[1], {})
            flat = []
            for k, v in h.items():
                flat += [k, v]
            return self._array(flat)
        if cmd == b"HKEYS":
            return self._array(list(self.hashes.get(parts[1], {})))
        if cmd == b"DEL":
            n = 0
            for key in parts[1:]:
                n += int(
                    self.strings.pop(key, None) is not None
                    or self.hashes.pop(key, None) is not None
                    or self.sets.pop(key, None) is not None
                    or self.zsets.pop(key, None) is not None
                )
            return self._int(n)
        if cmd == b"FLUSHDB":
            self.strings.clear()
            self.hashes.clear()
            self.sets.clear()
            self.zsets.clear()
            return b"+OK\r\n"
        if cmd == b"ZCARD":
            return self._int(len(self.zsets.get(parts[1], {})))
        if cmd == b"ZREVRANGE":
            z = self.zsets.get(parts[1], {})
            ranked = sorted(z.items(), key=lambda kv: kv[1], reverse=True)
            lo, hi = int(parts[2]), int(parts[3])
            flat = []
            for member, score in ranked[lo : hi + 1]:
                flat += [member, str(int(score)).encode()]
            return self._array(flat)
        if cmd == b"SCAN":
            # single-pass cursor walk (cursor 0 -> everything -> cursor 0),
            # MATCH limited to the "prefix*" shape the tenant-scoped
            # delete_coordinator_data issues
            assert parts[2].upper() == b"MATCH" and parts[3].endswith(b"*")
            prefix = parts[3][:-1]
            keys = [
                k
                for space in (self.strings, self.hashes, self.sets, self.zsets)
                for k in space
                if k.startswith(prefix)
            ]
            return b"*2\r\n" + self._bulk(b"0") + self._array(keys)
        if cmd == b"EVAL":
            return self._eval(parts[1], parts)
        raise AssertionError(f"unsupported command {cmd!r}")

    # state views shared with the plain-command dispatch below
    @property
    def hashes(self):
        return self._commands.hashes

    @property
    def sets(self):
        return self._commands.sets

    @property
    def zsets(self):
        return self._commands.zsets

    @classmethod
    def _to_resp(cls, value):
        if value is None:
            return b"$-1\r\n"
        if isinstance(value, int):
            return cls._int(value)
        if isinstance(value, bytes):
            return cls._bulk(value)
        if isinstance(value, list):
            return b"*%d\r\n" % len(value) + b"".join(cls._to_resp(v) for v in value)
        raise AssertionError(f"unsupported reply {value!r}")

    def _eval(self, script, parts):
        nkeys = int(parts[2])
        keys = parts[3 : 3 + nkeys]
        argv = parts[3 + nkeys :]
        try:
            result = lua_mini.run_script(script, keys, argv, self._commands)
        except lua_mini.LuaError as e:
            return b"-ERR Error running script: %s\r\n" % str(e).encode()
        return self._to_resp(result)


def _mask(seed=1, n=4) -> MaskObject:
    ints = uniform_ints(bytes([seed]) * 32, n + 1, CFG.order)
    return MaskObject.new(CFG.pair(), ints[1:], ints[0])


class _Backend:
    """One storage backend for a data-model test: the in-process fake, or a
    live Redis at ``XAYNET_REDIS=host:port`` (flushed before each test)."""

    def __init__(self, kind: str):
        self.kind = kind
        self.fake = None
        self.store = None

    async def __aenter__(self) -> RedisCoordinatorStorage:
        if self.kind == "live":
            host, _, port = os.environ["XAYNET_REDIS"].partition(":")
            self.store = RedisCoordinatorStorage(host=host, port=int(port or 6379))
        else:
            self.fake = FakeRedis()
            port = await self.fake.start()
            self.store = RedisCoordinatorStorage(port=port)
        await self.store.client.command(b"FLUSHDB")
        return self.store

    async def __aexit__(self, *exc):
        await self.store.client.close()
        if self.fake is not None:
            await self.fake.stop()


def _backend_params():
    params = ["fake"]
    if os.environ.get("XAYNET_REDIS"):
        params.append("live")
    return params


@pytest.fixture(params=_backend_params())
def backend_kind(request):
    return request.param


def test_redis_reconnect_after_server_restart():
    """A dropped connection transparently reconnects (ConnectionManager
    analogue, reference redis/mod.rs:95-103): the server dies after a
    successful session, comes back on the same port, and the next command
    succeeds without the caller doing anything."""

    async def run():
        fake = FakeRedis()
        port = await fake.start()
        store = RedisCoordinatorStorage(port=port)
        try:
            await store.set_coordinator_state(b"before-crash")
            # kill the server: the client's socket goes dead
            await fake.stop()
            # restart on the same port (state survives, as with AOF persistence)
            await fake.start(port)
            # next command must reconnect-and-succeed, not raise
            assert await store.coordinator_state() == b"before-crash"
            await store.set_coordinator_state(b"after-restart")
            assert await store.coordinator_state() == b"after-restart"
        finally:
            await store.client.close()
            await fake.stop()

    asyncio.run(run())


def test_redis_backoff_retries_while_server_briefly_down():
    """Commands retry with backoff while the server is away and succeed the
    moment it returns within the retry budget."""

    async def run():
        fake = FakeRedis()
        port = await fake.start()
        store = RedisCoordinatorStorage(port=port)
        store.client.RETRY_BASE_DELAY = 0.05
        try:
            await store.set_coordinator_state(b"x")
            await fake.stop()

            async def resurrect():
                await asyncio.sleep(0.12)  # within the backoff budget
                await fake.start(port)

            task = asyncio.create_task(resurrect())
            assert await store.coordinator_state() == b"x"  # survives the outage
            await task
        finally:
            await store.client.close()
            await fake.stop()

    asyncio.run(run())


def test_redis_unreachable_raises_storage_error():
    from xaynet_tpu.storage.traits import StorageError

    async def run():
        fake = FakeRedis()
        port = await fake.start()
        await fake.stop()  # nothing listening on that port now
        store = RedisCoordinatorStorage(port=port)
        store.client.RETRY_BASE_DELAY = 0.01
        with pytest.raises(StorageError, match="unreachable"):
            await store.is_ready()

    asyncio.run(run())


def test_redis_conditional_insert_not_replayed_on_lost_reply():
    """An EVAL that executed but lost its reply must surface a StorageError
    (-> Failure phase), NOT be silently replayed — a replay would return a
    dedup error for a write that landed, desynchronizing the seed dict from
    the model aggregate."""
    from xaynet_tpu.storage.traits import StorageError

    async def run():
        fake = FakeRedis()
        port = await fake.start()
        store = RedisCoordinatorStorage(port=port)
        store.client.RETRY_BASE_DELAY = 0.01
        try:
            # prime the connection with a replay-safe command
            await store.set_coordinator_state(b"x")
            fake.kill_next_eval_reply = True
            with pytest.raises(StorageError, match="not replayed"):
                await store.add_sum_participant(b"s1" * 16, b"e1" * 16)
            # the write DID land server-side (that's the hazard)
            assert (b"s1" * 16) in fake.hashes.get(b"sum_dict", {})
            # the client recovers for subsequent commands
            assert await store.coordinator_state() == b"x"
        finally:
            await store.client.close()
            await fake.stop()

    asyncio.run(run())


def test_redis_best_masks_ordering_and_ties(backend_kind):
    """best_masks returns the top-2 by score in descending order
    (reference integration matrix: redis/mod.rs best-masks ordering)."""

    async def run():
        async with _Backend(backend_kind) as store:
            for i in range(1, 6):
                assert await store.add_sum_participant(bytes([i]) * 32, b"e" * 32) is None
            m1, m2, m3 = _mask(1), _mask(2), _mask(3)
            # m1: 3 votes, m2: 1 vote, m3: 1 vote
            assert await store.incr_mask_score(bytes([1]) * 32, m1) is None
            assert await store.incr_mask_score(bytes([2]) * 32, m1) is None
            assert await store.incr_mask_score(bytes([3]) * 32, m1) is None
            assert await store.incr_mask_score(bytes([4]) * 32, m2) is None
            assert await store.incr_mask_score(bytes([5]) * 32, m3) is None
            assert await store.number_of_unique_masks() == 3

            best = await store.best_masks()
            assert len(best) == 2
            assert best[0] == (m1, 3)
            assert best[1][1] == 1  # runner-up has the tied lower score
            assert best[1][0] in (m2, m3)

    asyncio.run(run())


def test_redis_backend_full_cycle(backend_kind):
    async def run():
        async with _Backend(backend_kind) as store:
            await store.is_ready()

            # coordinator state
            await store.set_coordinator_state(b"state-1")
            assert await store.coordinator_state() == b"state-1"

            # sum dict with duplicate rejection
            assert await store.add_sum_participant(b"s1" * 16, b"e1" * 16) is None
            assert await store.add_sum_participant(b"s2" * 16, b"e2" * 16) is None
            assert (
                await store.add_sum_participant(b"s1" * 16, b"e3" * 16)
                is SumPartAddError.ALREADY_EXISTS
            )
            sums = await store.sum_dict()
            assert set(sums) == {b"s1" * 16, b"s2" * 16}

            # seed dicts: length mismatch, unknown pk, dedup, success
            seed80 = b"\x07" * 80
            assert (
                await store.add_local_seed_dict(b"u1" * 16, {b"s1" * 16: seed80})
                is LocalSeedDictAddError.LENGTH_MISMATCH
            )
            assert (
                await store.add_local_seed_dict(
                    b"u1" * 16, {b"s1" * 16: seed80, b"zz" * 16: seed80}
                )
                is LocalSeedDictAddError.UNKNOWN_SUM_PARTICIPANT
            )
            full = {b"s1" * 16: seed80, b"s2" * 16: seed80}
            assert await store.add_local_seed_dict(b"u1" * 16, full) is None
            assert (
                await store.add_local_seed_dict(b"u1" * 16, full)
                is LocalSeedDictAddError.UPDATE_PK_ALREADY_SUBMITTED
            )
            seeds = await store.seed_dict()
            assert set(seeds) == {b"s1" * 16, b"s2" * 16}
            assert seeds[b"s1" * 16][b"u1" * 16].as_bytes() == seed80

            # mask scores: membership, single submission, best-mask ranking
            m1, m2 = _mask(1), _mask(2)
            assert (
                await store.incr_mask_score(b"??" * 16, m1) is MaskScoreIncrError.UNKNOWN_SUM_PK
            )
            assert await store.incr_mask_score(b"s1" * 16, m1) is None
            assert (
                await store.incr_mask_score(b"s1" * 16, m1)
                is MaskScoreIncrError.MASK_ALREADY_SUBMITTED
            )
            assert await store.incr_mask_score(b"s2" * 16, m1) is None
            assert await store.number_of_unique_masks() == 1
            best = await store.best_masks()
            assert len(best) == 1 and best[0][1] == 2 and best[0][0] == m1

            # latest model pointer + dict deletion keeps state
            await store.set_latest_global_model_id("7_cafe")
            assert await store.latest_global_model_id() == "7_cafe"
            await store.delete_dicts()
            assert await store.sum_dict() is None
            assert await store.coordinator_state() == b"state-1"
            await store.delete_coordinator_data()
            assert await store.coordinator_state() is None

    asyncio.run(run())


def test_redis_checkpoint_and_dicts_are_tenant_prefix_scoped():
    """Regression mirroring the file-backend tenant-scope test
    (test_tenancy.py): with per-tenant ``t:<id>:`` key prefixes sharing
    one redis db, the round journal, the Lua-scripted seed dicts and the
    prefix-scoped delete must each stay inside their own tenant's
    namespace — tenant B's restart sees no journal entry, and flushing
    tenant A leaves tenant B's round state intact."""
    from xaynet_tpu.core.mask.seed import EncryptedMaskSeed

    async def run():
        fake = FakeRedis()
        port = await fake.start()
        try:
            store_a = RedisCoordinatorStorage(port=port, key_prefix="t:alpha:")
            store_b = RedisCoordinatorStorage(port=port, key_prefix="t:beta:")
            await store_a.client.command(b"FLUSHDB")

            blob_a, blob_b = b"alpha journal entry", b"beta journal entry"
            await store_a.set_round_checkpoint(blob_a)
            await store_b.set_round_checkpoint(blob_b)
            assert await store_a.round_checkpoint() == blob_a
            assert await store_b.round_checkpoint() == blob_b

            # the Lua seed-dict insert builds its per-sum hash keys from the
            # PREFIXED base: each tenant's seed dict is invisible to the other
            seed80 = bytes(range(80 // 4)) * 4
            for store, upk in ((store_a, b"ua" * 16), (store_b, b"ub" * 16)):
                assert await store.add_sum_participant(b"s1" * 16, b"e" * 32) is None
                assert (
                    await store.add_local_seed_dict(
                        upk, {b"s1" * 16: EncryptedMaskSeed(seed80)}
                    )
                    is None
                )
            seeds_a = await store_a.seed_dict()
            seeds_b = await store_b.seed_dict()
            assert set(seeds_a[b"s1" * 16]) == {b"ua" * 16}
            assert set(seeds_b[b"s1" * 16]) == {b"ub" * 16}

            # prefix-scoped delete: flushing alpha keeps beta whole
            await store_a.delete_round_checkpoint()
            assert await store_a.round_checkpoint() is None
            assert await store_b.round_checkpoint() == blob_b
            await store_a.delete_coordinator_data()
            assert await store_a.sum_dict() is None
            assert await store_b.round_checkpoint() == blob_b
            assert set((await store_b.seed_dict())[b"s1" * 16]) == {b"ub" * 16}
            await store_a.client.close()
            await store_b.client.close()
        finally:
            await fake.stop()

    asyncio.run(run())
