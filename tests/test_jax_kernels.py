"""Device kernels vs numpy host oracle (run on a virtual 8-device CPU mesh)."""

import random

import numpy as np
import pytest

from xaynet_tpu.core.crypto.prng import StreamSampler, uniform_ints
from xaynet_tpu.core.mask import (
    Aggregation,
    BoundType,
    DataType,
    GroupType,
    Masker,
    MaskConfig,
    MaskSeed,
    ModelType,
    Scalar,
)
from xaynet_tpu.ops import chacha_jax, limbs as host_limbs, limbs_jax, masking_jax

CFG = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M6)
ORDERS = [20_000_000_000_001, 2**45, 2**96, 200_000_000_000_000_000_000_000_000_017]


@pytest.mark.parametrize("order", ORDERS)
def test_mod_add_sub_device(order):
    rng = random.Random(11)
    n_limb = host_limbs.n_limbs_for_order(order)
    ol = host_limbs.order_limbs_for(order)
    a = [rng.randrange(order) for _ in range(64)]
    b = [rng.randrange(order) for _ in range(64)]
    aa = host_limbs.ints_to_limbs(a, n_limb)
    bb = host_limbs.ints_to_limbs(b, n_limb)

    got_add = np.asarray(limbs_jax.mod_add(aa, bb, ol))
    assert np.array_equal(got_add, host_limbs.mod_add(aa, bb, ol))
    got_sub = np.asarray(limbs_jax.mod_sub(aa, bb, ol))
    assert np.array_equal(got_sub, host_limbs.mod_sub(aa, bb, ol))


@pytest.mark.parametrize("k", [1, 2, 5, 16, 33])
def test_batch_mod_sum_device(k):
    order = ORDERS[0]
    rng = random.Random(k)
    n_limb = host_limbs.n_limbs_for_order(order)
    ol = host_limbs.order_limbs_for(order)
    stack = np.stack(
        [host_limbs.ints_to_limbs([rng.randrange(order) for _ in range(24)], n_limb) for _ in range(k)]
    )
    got = np.asarray(limbs_jax.batch_mod_sum(stack, ol))
    assert np.array_equal(got, host_limbs.batch_mod_sum(stack, ol))


def test_device_keystream_matches_host():
    from xaynet_tpu.core.crypto.chacha import keystream_blocks
    import jax.numpy as jnp

    key = bytes(range(32))
    words = chacha_jax.keystream_words(jnp.asarray(np.frombuffer(key, dtype="<u4")), 0, 8)
    host = np.frombuffer(bytes(keystream_blocks(key, 0, 8)), dtype="<u4").reshape(8, 16)
    assert np.array_equal(np.asarray(words), host)


@pytest.mark.slow  # minutes on the CPU-emulated mesh
@pytest.mark.parametrize("order", ORDERS)
def test_device_sampler_matches_host(order):
    seed = b"\x05" * 32
    got = host_limbs.limbs_to_ints(np.asarray(chacha_jax.derive_uniform_limbs(seed, 200, order)))
    assert got == uniform_ints(seed, 200, order)


def test_device_sampler_with_offset():
    seed = b"\x09" * 32
    order = CFG.order
    sampler = StreamSampler(seed)
    sampler.draw_limbs(1, MaskConfig(GroupType.PRIME, DataType.F64, BoundType.B2, ModelType.M3).order)
    offset = sampler.consumed_bytes
    expected = host_limbs.limbs_to_ints(sampler.draw_limbs(50, order))
    got = host_limbs.limbs_to_ints(
        np.asarray(chacha_jax.derive_uniform_limbs(seed, 50, order, byte_offset=offset))
    )
    assert got == expected


@pytest.mark.slow  # minutes on the CPU-emulated mesh
def test_device_sampler_chunked_multi_chunk():
    """A tiny chunk size forces many chunks; result must stay bit-exact."""
    seed = b"\x0c" * 32
    for order in (ORDERS[0], ORDERS[2]):
        want = host_limbs.limbs_to_ints(StreamSampler(seed).draw_limbs(500, order))
        got = host_limbs.limbs_to_ints(
            np.asarray(chacha_jax.derive_uniform_limbs(seed, 500, order, chunk_candidates=97))
        )
        assert got == want


def test_device_sampler_chunked_memory_bound():
    """Chunk size is capped independently of count (the Sum2 memory fix)."""
    order = ORDERS[0]
    bpn = (order.bit_length() + 7) // 8
    assert chacha_jax._CHUNK_BYTES_CAP // bpn < chacha_jax.provision_candidates(10**9, order)


@pytest.mark.slow  # minutes on the CPU-emulated mesh
def test_derive_mask_device_matches_host():
    seed = MaskSeed(b"\x21" * 32)
    mask_host = seed.derive_mask(100, CFG.pair())
    unit, vect = masking_jax.derive_mask_limbs(seed.as_bytes(), 100, CFG.pair())
    assert np.array_equal(unit, mask_host.unit.data)
    assert np.array_equal(np.asarray(vect), mask_host.vect.data)


def test_sharded_aggregator_full_round():
    """Masked updates -> sharded aggregation -> unmask == host Aggregation."""
    from xaynet_tpu.parallel.aggregator import ShardedAggregator

    n, k = 103, 9  # deliberately not divisible by 8 devices
    rng = np.random.default_rng(2)
    cfg = CFG
    agg_host = Aggregation(cfg.pair(), n)
    mask_agg = Aggregation(cfg.pair(), n)
    stacks = []
    for _ in range(k):
        w = rng.uniform(-1, 1, size=n).astype(np.float32)
        seed, masked = Masker(cfg.pair()).mask(Scalar(1, k), w)
        mask = seed.derive_mask(n, cfg.pair())
        agg_host.aggregate(masked)
        mask_agg.aggregate(mask)
        stacks.append(masked.vect.data)

    dev = ShardedAggregator(cfg, n)
    dev.add_batch(np.stack(stacks[:4]))
    dev.add_batch(np.stack(stacks[4:]))
    assert dev.nb_models == k
    assert np.array_equal(dev.snapshot(), agg_host.object.vect.data)

    unmasked_limbs = dev.unmask_limbs(mask_agg.object.vect.data)
    host_limbs_ref, _ = agg_host._unmasked_limbs(mask_agg.object)
    assert np.array_equal(unmasked_limbs, host_limbs_ref)


@pytest.mark.slow  # minutes on the CPU-emulated mesh
def test_sum_masks_device():
    seeds = [bytes([i]) * 32 for i in range(1, 6)]
    n = 40
    got_unit, got_vect = masking_jax.sum_masks(seeds, n, CFG.pair(), kernel="host-chunked")

    agg = Aggregation(CFG.pair(), n)
    for s in seeds:
        agg.aggregate(MaskSeed(s).derive_mask(n, CFG.pair()))
    assert np.array_equal(got_unit, agg.object.unit.data)
    assert np.array_equal(np.asarray(got_vect), agg.object.vect.data)


@pytest.mark.slow  # minutes on the CPU-emulated mesh
def test_sum_masks_device_multi_group():
    """More seeds than one seed_batch: the group-accumulate path (sum2 at
    protocol scale runs #updates/seed_batch of these)."""
    seeds = [bytes([i, i ^ 0x5A]) * 16 for i in range(1, 20)]
    n = 33
    got_unit, got_vect = masking_jax.sum_masks(
        seeds, n, CFG.pair(), seed_batch=4, kernel="host-chunked"
    )

    agg = Aggregation(CFG.pair(), n)
    for s in seeds:
        agg.aggregate(MaskSeed(s).derive_mask(n, CFG.pair()))
    assert np.array_equal(got_unit, agg.object.unit.data)
    assert np.array_equal(np.asarray(got_vect), agg.object.vect.data)


@pytest.mark.slow  # minutes on the CPU-emulated mesh
def test_derive_uniform_limbs_batch_matches_single():
    """Each row of the batched derivation is bit-identical to the single-seed
    kernel at the same byte offset, including the multi-chunk case."""
    order = CFG.order
    seeds = [bytes([7 + i]) * 32 for i in range(5)]
    offsets = [0, 10, 64, 130, 7]
    n = 700
    # small chunks force several chunk rounds with per-seed cursors
    got = np.asarray(
        chacha_jax.derive_uniform_limbs_batch(
            seeds, n, order, byte_offsets=offsets, chunk_candidates=256
        )
    )
    for i, (s, off) in enumerate(zip(seeds, offsets)):
        want = np.asarray(chacha_jax.derive_uniform_limbs(s, n, order, byte_offset=off))
        assert np.array_equal(got[i], want), f"seed {i} diverges from single-seed derive"


@pytest.mark.parametrize(
    "cfg",
    [
        MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M6),
        MaskConfig(GroupType.POWER2, DataType.I32, BoundType.BMAX, ModelType.M9),  # 2^96
        MaskConfig(GroupType.PRIME, DataType.F64, BoundType.B6, ModelType.M3),
    ],
)
@pytest.mark.parametrize("k", [1, 2, 13, 64])
def test_fold_planar_batch(cfg, k):
    """Single-pass lazy-carry fold == python big-int oracle."""
    import jax.numpy as jnp

    from xaynet_tpu.ops.fold_jax import fold_planar_batch, wire_to_planar

    order = cfg.order
    n_limb = host_limbs.n_limbs_for_order(order)
    rng = random.Random(k)
    n = 50
    rows = [[rng.randrange(order) for _ in range(n)] for _ in range(k)]
    stack = np.stack([host_limbs.ints_to_limbs(r, n_limb) for r in rows])
    acc0 = [rng.randrange(order) for _ in range(n)]
    acc = jnp.asarray(wire_to_planar(host_limbs.ints_to_limbs(acc0, n_limb)))

    out = fold_planar_batch(acc, jnp.asarray(wire_to_planar(stack)), order)
    got = host_limbs.limbs_to_ints(np.ascontiguousarray(np.asarray(out).T))
    want = [(acc0[j] + sum(rows[i][j] for i in range(k))) % order for j in range(n)]
    assert got == want


@pytest.mark.parametrize("k", [1, 2, 13])
def test_fold_pallas_matches_oracle(k):
    """Pallas fold (interpret mode on CPU) == python big-int oracle."""
    import jax.numpy as jnp

    from xaynet_tpu.ops.fold_jax import wire_to_planar
    from xaynet_tpu.ops.fold_pallas import fold_planar_batch_pallas

    cfg = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M6)
    order = cfg.order
    n_limb = host_limbs.n_limbs_for_order(order)
    rng = random.Random(k)
    n = 256
    rows = [[rng.randrange(order) for _ in range(n)] for _ in range(k)]
    stack = np.stack([host_limbs.ints_to_limbs(r, n_limb) for r in rows])
    acc0 = [rng.randrange(order) for _ in range(n)]
    acc = jnp.asarray(wire_to_planar(host_limbs.ints_to_limbs(acc0, n_limb)))

    out = fold_planar_batch_pallas(acc, jnp.asarray(wire_to_planar(stack)), order, interpret=True)
    got = host_limbs.limbs_to_ints(np.ascontiguousarray(np.asarray(out).T))
    want = [(acc0[j] + sum(rows[i][j] for i in range(k))) % order for j in range(n)]
    assert got == want


@pytest.mark.parametrize(
    "cfg",
    [
        MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M6),  # 2 limbs, bpn 6
        MaskConfig(GroupType.POWER2, DataType.I32, BoundType.BMAX, ModelType.M9),  # 2^96 boundary
        MaskConfig(GroupType.PRIME, DataType.F64, BoundType.B6, ModelType.M3),  # multi-limb
    ],
)
def test_wire_bytes_to_planar_matches_host_parse(cfg):
    """Device wire unpack == host parser limb-for-limb (raw element block)."""
    import random as pyrandom

    import jax.numpy as jnp

    from xaynet_tpu.core.mask.object import MaskVect
    from xaynet_tpu.core.mask.serialization import (
        parse_mask_vect,
        serialize_mask_vect,
        vect_element_block,
    )
    from xaynet_tpu.ops.fold_jax import wire_to_planar

    order = cfg.order
    n_limb = host_limbs.n_limbs_for_order(order)
    bpn = cfg.bytes_per_number
    rng = pyrandom.Random(3)
    n = 57
    rows = [rng.randrange(order) for _ in range(n)]
    wire = serialize_mask_vect(MaskVect(cfg, host_limbs.ints_to_limbs(rows, n_limb)))
    raw = vect_element_block(wire)
    assert raw.shape[0] == n * bpn

    got = np.asarray(limbs_jax.wire_bytes_to_planar(jnp.asarray(raw), n, bpn))
    want_limbs, _ = parse_mask_vect(wire)
    assert np.array_equal(got[: n_limb], wire_to_planar(want_limbs.data)), (
        "device unpack diverges from host parse"
    )
    # validity kernel agrees with the host rule (the 2^(32L) boundary case
    # is owned inside the kernel, like limbs.elements_lt_order)
    assert bool(limbs_jax.planar_all_lt_const(got[:n_limb], order))


def test_vect_element_block_rejects_malformed_wire():
    """The device-ingest entry point validates at the parse boundary, like
    parse_mask_vect (truncated buffers and over-long MaskObject wires fail
    with DecodeError, not as shape errors downstream)."""
    from xaynet_tpu.core.mask.object import MaskVect
    from xaynet_tpu.core.mask.serialization import (
        DecodeError,
        serialize_mask_vect,
        vect_element_block,
    )

    wire = serialize_mask_vect(
        MaskVect(CFG, host_limbs.ints_to_limbs([1, 2, 3], host_limbs.n_limbs_for_order(CFG.order)))
    )
    assert vect_element_block(wire).shape == (3 * CFG.bytes_per_number,)
    with pytest.raises(DecodeError, match="too short"):
        vect_element_block(wire[:5])
    with pytest.raises(DecodeError, match="framed element count"):
        vect_element_block(wire[:-1])  # truncated element block
    with pytest.raises(DecodeError, match="framed element count"):
        vect_element_block(wire + b"\x00\x00")  # trailing bytes (e.g. unit part)
    with pytest.raises(DecodeError, match="invalid mask config"):
        vect_element_block(b"\xff\xff\xff\xff" + wire[4:])


def test_sharded_aggregator_wire_ingest():
    """add_wire_batch (device unpack+validity+fold) == host parse + host agg."""
    from xaynet_tpu.core.mask.object import MaskVect
    from xaynet_tpu.core.mask.serialization import serialize_mask_vect, vect_element_block
    from xaynet_tpu.parallel.aggregator import ShardedAggregator

    n, k = 103, 5  # not divisible by the 8-device mesh
    rng = np.random.default_rng(5)
    cfg = CFG
    bpn = cfg.bytes_per_number
    agg_host = Aggregation(cfg.pair(), n)
    raws = []
    for _ in range(k):
        w = rng.uniform(-1, 1, size=n).astype(np.float32)
        _, masked = Masker(cfg.pair()).mask(Scalar(1, k), w)
        agg_host.aggregate(masked)
        wire = serialize_mask_vect(masked.vect)
        raws.append(vect_element_block(wire))

    dev = ShardedAggregator(cfg, n)
    ok = dev.add_wire_batch(np.stack(raws[:2]))
    assert ok.tolist() == [True, True]
    ok = dev.add_wire_batch(np.stack(raws[2:]))
    assert ok.tolist() == [True, True, True]
    assert dev.nb_models == k
    assert np.array_equal(dev.snapshot(), agg_host.object.vect.data)

    # per-update rejection: an update with an element >= order is excluded
    # from the fold and the count, and the others in the batch still land —
    # the aggregate must equal the host aggregate of only the valid ones
    dev2 = ShardedAggregator(cfg, n)
    bad = np.stack([raws[0], raws[1].copy(), raws[2]])
    bad[1, :bpn] = 0xFF  # max fixed-width value >= every non-boundary order
    ok = dev2.add_wire_batch(bad)
    assert ok.tolist() == [True, False, True]
    assert dev2.nb_models == 2
    # the aggregate equals the host aggregate of only the two valid updates
    from xaynet_tpu.core.mask.serialization import parse_mask_vect

    host2 = Aggregation(cfg.pair(), n)
    valid_limbs = []
    for r in (raws[0], raws[2]):
        wire = cfg.to_bytes() + (len(r) // bpn).to_bytes(4, "big") + r.tobytes()
        valid_limbs.append(parse_mask_vect(wire)[0].data)
    unit_l = host_limbs.n_limbs_for_order(cfg.pair().unit.order)
    host2.aggregate_batch(np.stack(valid_limbs), np.zeros((2, unit_l), dtype=np.uint32))
    assert np.array_equal(dev2.snapshot(), host2.object.vect.data)


def test_sharded_aggregator_wire_ingest_fused(monkeypatch):
    """The accelerator-only FUSED ingest jit (unpack+validity+fold in one
    XLA program) — forced on via a monkeypatched backend, same stand-in
    pattern as test_kernel_auto — matches the host aggregate and keeps the
    per-update exclusion semantics."""
    import jax

    from xaynet_tpu.core.mask.serialization import serialize_mask_vect, vect_element_block
    from xaynet_tpu.parallel.aggregator import ShardedAggregator

    n, k = 103, 4
    rng = np.random.default_rng(9)
    cfg = CFG
    bpn = cfg.bytes_per_number
    raws = []
    for _ in range(k):
        w = rng.uniform(-1, 1, size=n).astype(np.float32)
        _, masked = Masker(cfg.pair()).mask(Scalar(1, k), w)
        raws.append((vect_element_block(serialize_mask_vect(masked.vect)), masked))

    dev = ShardedAggregator(cfg, n)
    dev.add_wire_batch(np.stack([r for r, _ in raws[:2]]))  # two-step (resolve)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    bad = np.stack([raws[2][0], raws[3][0].copy()])
    bad[1, -bpn:] = 0xFF  # invalid in the fused batch
    ok = dev.add_wire_batch(bad)  # fused path
    assert ok.tolist() == [True, False]
    assert dev.nb_models == 3

    host = Aggregation(cfg.pair(), n)
    unit_l = host_limbs.n_limbs_for_order(cfg.pair().unit.order)
    host.aggregate_batch(
        np.stack([m.vect.data for _, m in raws[:3]]), np.zeros((3, unit_l), dtype=np.uint32)
    )
    assert np.array_equal(dev.snapshot(), host.object.vect.data)


def test_multihost_initialize_noop_and_mesh():
    """Single-process: initialize is a no-op and the global mesh spans all
    devices (the 2-process path is covered by tests/test_multihost.py)."""
    from xaynet_tpu.parallel import multihost

    multihost.initialize()  # no-op without num_processes
    mesh = multihost.global_mesh()
    assert mesh.devices.size >= 1
