"""Ingest pipeline integration: full PET round, shed path, flood stress.

Acceptance contract of the subsystem:

- a full round through REST -> admission -> shards -> batched decrypt ->
  coalescer -> state machine produces a BYTE-IDENTICAL aggregate to the
  per-message direct path, with intake occupancy never above the configured
  bound and FEWER aggregator dispatches than update messages;
- a saturated intake answers 429 + Retry-After, counts
  ``xaynet_ingest_shed_total``, flips /healthz to saturated, and recovers
  (200s resume) once drained.
"""

import asyncio
from fractions import Fraction

import numpy as np
import pytest

from xaynet_tpu.ingest import IngestPipeline
from xaynet_tpu.sdk.client import HttpClient
from xaynet_tpu.sdk.simulation import build_update_message, flood, keys_for_task
from xaynet_tpu.sdk.state_machine import PetSettings, StateMachine as ParticipantSM
from xaynet_tpu.sdk.traits import ModelStore
from xaynet_tpu.server.aggregation import StagedAggregator
from xaynet_tpu.server.rest import RestServer
from xaynet_tpu.server.services import Fetcher, PetMessageHandler
from xaynet_tpu.server.settings import (
    CountSettings,
    IngestSettings,
    PhaseSettings,
    PetSettings as ServerPet,
    Settings,
    Sum2Settings,
    TimeSettings,
)
from xaynet_tpu.server.state_machine import StateMachineInitializer
from xaynet_tpu.storage.memory import (
    InMemoryCoordinatorStorage,
    InMemoryModelStorage,
    NoOpTrustAnchor,
)
from xaynet_tpu.storage.traits import Store
from xaynet_tpu.telemetry.registry import get_registry

N_SUM, N_UPDATE, MODEL_LEN = 1, 4, 7
SUM_PROB, UPDATE_PROB = 0.4, 0.5
QUEUE_BOUND = 4


class ArrayModelStore(ModelStore):
    def __init__(self, model):
        self.model = model

    async def load_model(self):
        return self.model


def _settings(ingest: IngestSettings, phase_max: float = 30.0) -> Settings:
    settings = Settings(
        pet=ServerPet(
            sum=PhaseSettings(
                prob=SUM_PROB,
                count=CountSettings(N_SUM, N_SUM),
                time=TimeSettings(0, phase_max),
            ),
            update=PhaseSettings(
                prob=UPDATE_PROB,
                count=CountSettings(N_UPDATE, N_UPDATE),
                time=TimeSettings(0, phase_max),
            ),
            sum2=Sum2Settings(
                count=CountSettings(N_SUM, N_SUM), time=TimeSettings(0, phase_max)
            ),
        )
    )
    settings.model.length = MODEL_LEN
    settings.ingest = ingest
    return settings


class _Coordinator:
    """One in-process coordinator + REST server (pipeline optional)."""

    def __init__(self, settings: Settings):
        self.settings = settings

    async def __aenter__(self):
        store = Store(InMemoryCoordinatorStorage(), InMemoryModelStorage(), NoOpTrustAnchor())
        machine, request_tx, events = await StateMachineInitializer(
            self.settings, store
        ).init()
        self.handler = PetMessageHandler(events, request_tx)
        self.fetcher = Fetcher(events)
        self.events = events
        self.request_tx = request_tx
        self.pipeline = None
        if self.settings.ingest.enabled:
            self.pipeline = IngestPipeline(
                self.handler, request_tx, events, self.settings.ingest
            )
            await self.pipeline.start()
        self.rest = RestServer(self.fetcher, self.handler, pipeline=self.pipeline)
        self.host, self.port = await self.rest.start("127.0.0.1", 0)
        self.machine_task = asyncio.create_task(machine.run())
        return self

    async def __aexit__(self, *exc):
        self.machine_task.cancel()
        await self.rest.stop()
        if self.pipeline is not None:
            await self.pipeline.stop()
        try:
            await self.machine_task
        except (asyncio.CancelledError, Exception):
            pass

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    async def wait_phase(self, name: str) -> None:
        while self.fetcher.phase().value != name:
            await asyncio.sleep(0.01)


def _count_fold_dispatches(monkeypatch) -> list:
    """Counts StagedAggregator flushes that actually dispatch a fold."""
    dispatches = []
    orig = StagedAggregator.flush

    def counting(self):
        if self.pending > 0:
            dispatches.append(self.pending)
        return orig(self)

    monkeypatch.setattr(StagedAggregator, "flush", counting)
    return dispatches


async def _drive_round(coord: _Coordinator, models: list, dispatches: list) -> np.ndarray:
    """One full PET round: SDK sum participant + flood-built update uploads."""
    probe = HttpClient(coord.url)
    await coord.wait_phase("sum")
    params = await probe.get_round_params()
    seed = params.seed.as_bytes()

    sum_keys = keys_for_task(seed, SUM_PROB, UPDATE_PROB, "sum", start=0)
    summer = ParticipantSM(PetSettings(keys=sum_keys), HttpClient(coord.url), ArrayModelStore(None))

    async def drive_summer():
        for _ in range(2000):
            try:
                await summer.transition()
            except Exception:
                pass
            model = await probe.get_model()
            if model is not None and summer.phase.value == "awaiting":
                return
            await asyncio.sleep(0.01)

    summer_task = asyncio.create_task(drive_summer())
    try:
        await coord.wait_phase("update")
        sum_dict = None
        while not sum_dict:
            sum_dict = await probe.get_sums()
            await asyncio.sleep(0.01)

        sealed = [
            build_update_message(
                params,
                keys_for_task(seed, SUM_PROB, UPDATE_PROB, "update", start=(20 + i) * 1000),
                sum_dict,
                models[i],
                Fraction(1, N_UPDATE),
            )
            for i in range(N_UPDATE)
        ]
        if coord.pipeline is not None:
            # park the workers so all uploads are queued together — the
            # coalescing then provably groups them instead of relying on
            # network timing
            await coord.pipeline.stop()
        client = HttpClient(coord.url)
        await asyncio.gather(*(client.send_message(blob) for blob in sealed))
        if coord.pipeline is not None:
            assert coord.pipeline.intake.occupancy == N_UPDATE
            await coord.pipeline.start()

        await asyncio.wait_for(summer_task, timeout=60)
    finally:
        if not summer_task.done():
            summer_task.cancel()
    model = await probe.get_model()
    assert model is not None
    return np.asarray(model)


def test_full_round_through_ingest_pipeline_matches_direct_path(monkeypatch):
    """(a) occupancy never exceeds the bound, (b) fewer fold dispatches than
    update messages, (c) byte-identical aggregate vs. the per-message path."""

    async def run():
        rng = np.random.default_rng(5)
        models = [rng.uniform(-1, 1, MODEL_LEN).astype(np.float32) for _ in range(N_UPDATE)]
        expected = sum(m.astype(np.float64) for m in models) / N_UPDATE

        dispatches = _count_fold_dispatches(monkeypatch)
        ingest_on = IngestSettings(
            enabled=True,
            shards=2,
            queue_bound=QUEUE_BOUND,
            high_watermark=1.0,
            low_watermark=0.5,
            coalesce=True,
            coalesce_max_batch=8,
            coalesce_linger_ms=50.0,
        )
        async with _Coordinator(_settings(ingest_on)) as coord:
            got_pipeline = await asyncio.wait_for(_drive_round(coord, models, dispatches), 90)
            # (a) the bounded intake never grew past its configured bound
            assert 0 < coord.pipeline.intake.max_occupancy <= QUEUE_BOUND
            for shard in coord.pipeline.intake.shards:
                assert shard.max_occupancy <= QUEUE_BOUND
            # (b) coalescing amortized the fold: fewer dispatches than
            # update messages (one stacked masked_add per micro-batch)
            pipeline_dispatches = len(dispatches)
            assert coord.pipeline.coalescer.members_sent == N_UPDATE
            assert 1 <= pipeline_dispatches < N_UPDATE
            assert sum(dispatches) == N_UPDATE

        np.testing.assert_allclose(got_pipeline, expected, atol=1e-9)

        dispatches.clear()
        async with _Coordinator(_settings(IngestSettings(enabled=False))) as coord:
            got_direct = await asyncio.wait_for(_drive_round(coord, models, dispatches), 90)
        np.testing.assert_allclose(got_direct, expected, atol=1e-9)

        # (c) the batched path computes the exact same aggregate
        assert got_pipeline.tobytes() == got_direct.tobytes()

    asyncio.run(run())


async def _http_post(host, port, path, body: bytes):
    reader, writer = await asyncio.open_connection(host, port)
    writer.write(
        (
            f"POST {path} HTTP/1.1\r\nHost: t\r\nContent-Length: {len(body)}\r\n"
            "Connection: close\r\n\r\n"
        ).encode()
        + body
    )
    await writer.drain()
    status = int((await reader.readline()).split()[1])
    headers = {}
    while True:
        line = await reader.readline()
        if line in (b"\r\n", b""):
            break
        name, _, value = line.decode().partition(":")
        headers[name.strip().lower()] = value.strip()
    writer.close()
    return status, headers


async def _http_get_json(host, port, path):
    import json

    reader, writer = await asyncio.open_connection(host, port)
    writer.write(f"GET {path} HTTP/1.1\r\nHost: t\r\nConnection: close\r\n\r\n".encode())
    await writer.drain()
    raw = await reader.read()
    writer.close()
    body = raw.split(b"\r\n\r\n", 1)[1]
    return json.loads(body.decode())


def test_saturated_intake_sheds_429_and_recovers():
    async def run():
        registry = get_registry()

        def shed_total():
            return registry.sample_value("xaynet_ingest_shed_total") or 0

        ingest = IngestSettings(
            enabled=True,
            shards=1,
            queue_bound=QUEUE_BOUND,
            high_watermark=0.5,  # saturate at 2 of 4
            low_watermark=0.25,
            retry_after_seconds=1.0,
        )
        async with _Coordinator(_settings(ingest)) as coord:
            await coord.wait_phase("sum")
            # park the worker: nothing drains, so occupancy climbs
            await coord.pipeline.stop()
            shed_before = shed_total()
            garbage = b"\x00" * 400

            s1, _ = await _http_post(coord.host, coord.port, "/message", garbage)
            s2, _ = await _http_post(coord.host, coord.port, "/message", garbage)
            assert (s1, s2) == (200, 200)
            # high watermark crossed: the next arrival is shed
            s3, h3 = await _http_post(coord.host, coord.port, "/message", garbage)
            assert s3 == 429
            assert int(h3["retry-after"]) >= 1
            assert shed_total() == shed_before + 1

            health = await _http_get_json(coord.host, coord.port, "/healthz")
            assert health["status"] == "saturated"
            assert health["ingest"]["saturated"] is True
            assert health["ingest"]["occupancy"] == 2

            # recovery: workers drain the garbage (decrypt drops), the
            # hysteresis clears, and POSTs answer 200 again
            await coord.pipeline.start()
            for _ in range(500):
                if coord.pipeline.intake.occupancy == 0:
                    break
                await asyncio.sleep(0.01)
            s4, _ = await _http_post(coord.host, coord.port, "/message", garbage)
            assert s4 == 200
            health = await _http_get_json(coord.host, coord.port, "/healthz")
            assert health["status"] == "ok"
            assert health["ingest"]["saturated"] is False
            dropped = registry.sample_value(
                "xaynet_ingest_rejected_total", {"stage": "decrypt"}
            )
            assert dropped and dropped >= 2

    asyncio.run(run())


@pytest.mark.slow
def test_flood_stress_shed_and_admit_paths():
    """Load-generate against both targets: valid updates through the raw
    ``PetMessageHandler`` (accept/reject verdicts) and a paused pipeline
    (admission verdicts) — then verify the pipeline drains and recovers."""

    async def run():
        ingest = IngestSettings(
            enabled=True,
            shards=2,
            queue_bound=8,  # capacity 16
            high_watermark=0.5,  # saturate at 8
            low_watermark=0.25,
        )
        settings = _settings(ingest, phase_max=60.0)
        # the phase completes at count.min accepted (time.min = 0), so pin
        # min == max == 8: exactly 8 of the 12 flooded updates are taken
        settings.pet.update.count = CountSettings(8, 8)
        async with _Coordinator(settings) as coord:
            probe = HttpClient(coord.url)
            await coord.wait_phase("sum")
            params = await probe.get_round_params()
            seed = params.seed.as_bytes()
            summer = ParticipantSM(
                PetSettings(keys=keys_for_task(seed, SUM_PROB, UPDATE_PROB, "sum", start=0)),
                HttpClient(coord.url),
                ArrayModelStore(None),
            )
            while coord.fetcher.phase().value == "sum":
                try:
                    await summer.transition()
                except Exception:
                    pass
                await asyncio.sleep(0.01)
            await coord.wait_phase("update")
            sum_dict = None
            while not sum_dict:
                sum_dict = await probe.get_sums()
                await asyncio.sleep(0.01)

            # leg 1: valid uploads against the raw handler — protocol
            # verdicts (accepts up to count.max=8, discards beyond)
            stats = await flood(
                coord.handler, params, sum_dict, 12, key_start=100_000, concurrency=8
            )
            assert stats.sent == 12
            assert stats.accepted == 8  # count.max, the rest discarded/stale
            assert stats.rejected == 4

            # leg 2: admission verdicts on a parked pipeline — garbage of
            # valid length floods the intake until admission sheds
            await coord.pipeline.stop()
            stats = await flood(
                coord.pipeline,
                params,
                sum_dict,
                40,
                build=lambda i: bytes([i % 251]) * 300,
                concurrency=16,
            )
            assert stats.sent == 40
            assert stats.accepted >= 8  # up to the high watermark
            assert stats.shed > 0  # and shedding beyond it
            assert stats.accepted + stats.shed + stats.rejected == 40
            assert coord.pipeline.admission.saturated

            # recovery: drain clears saturation, floods admit again
            await coord.pipeline.start()
            for _ in range(1000):
                if coord.pipeline.intake.occupancy == 0:
                    break
                await asyncio.sleep(0.01)
            assert coord.pipeline.intake.occupancy == 0
            stats = await flood(
                coord.pipeline,
                params,
                sum_dict,
                4,
                build=lambda i: bytes([i % 251]) * 300,
            )
            assert stats.accepted == 4 and stats.shed == 0

    asyncio.run(asyncio.wait_for(run(), timeout=300))
