"""tools/analysis: the pass-based static-analysis framework (ISSUE 9).

Fixture-corpus tests per deep pass (positive finding, suppressed finding,
baseline-masked finding), the PR-7 race-pattern acceptance fixture for the
lock-discipline lint, the regression fixture proving the old `_prog*`
name-prefix heuristic missed helpers one call deep (and the call-graph
pass catches them), the result cache, `--changed` plumbing, and the
self-gate: the real tree analyzes clean with the checked-in baseline.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.analysis import (  # noqa: E402
    Baseline,
    CallGraph,
    Finding,
    ResultCache,
    SourceCache,
    SymbolTable,
    check_file_info,
    driver,
    suppressed,
)
from tools.analysis import invariants, locks, metricscheck, purity, taint  # noqa: E402


def _graph(tmp_path, files: dict[str, str]) -> CallGraph:
    for rel, source in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(source)
    cache = SourceCache(tmp_path)
    infos = [cache.get(tmp_path / rel) for rel in files]
    return CallGraph(SymbolTable(infos))


# --- lock-discipline pass ---------------------------------------------------

# The PR-7 access pattern, distilled: per-shard accumulators annotated as
# guarded by the device-dispatch lock, a worker thread folding a shard and
# writing the accumulator slot OUTSIDE the lock. The 1,425-trial stress
# hunt becomes a compile-time finding.
PR7_RACE = """
import threading

class Plan:
    def __init__(self):
        self.accs = [0, 0]  # guarded-by: _dispatch_lock
        self._dispatch_lock = threading.Lock()

class Pipeline:
    def __init__(self, plan: Plan):
        self.plan = plan
        self._queue = []

    def start(self):
        self._worker = threading.Thread(target=self._worker_loop)

    def _worker_loop(self):
        for item in self._queue:
            self._fold_shard(item)

    def _fold_shard(self, item):
        d, batch = item
        plan = self.plan
        new_acc = plan.accs[d] + batch   # read outside the lock
        plan.accs[d] = new_acc           # torn-slice write outside the lock
"""


def test_lock_pass_reports_pr7_race_pattern(tmp_path):
    graph = _graph(tmp_path, {"xaynet_tpu/parallel/foo.py": PR7_RACE})
    findings = locks.run(graph)
    msgs = [f.message for f in findings]
    assert any("Plan.accs" in m and "_dispatch_lock" in m for m in msgs)
    # both the unlocked read and the unlocked write are reported
    assert len([f for f in findings if "Plan.accs" in f.message]) >= 2


def test_lock_pass_quiet_when_lock_held(tmp_path):
    fixed = PR7_RACE.replace(
        """        plan = self.plan
        new_acc = plan.accs[d] + batch   # read outside the lock
        plan.accs[d] = new_acc           # torn-slice write outside the lock""",
        """        plan = self.plan
        with plan._dispatch_lock:
            new_acc = plan.accs[d] + batch
            plan.accs[d] = new_acc""",
    )
    assert fixed != PR7_RACE
    graph = _graph(tmp_path, {"xaynet_tpu/parallel/foo.py": fixed})
    assert locks.run(graph) == []


def test_lock_pass_suppression_requires_rationale(tmp_path):
    bare = PR7_RACE.replace(
        "plan.accs[d] = new_acc           # torn-slice write outside the lock",
        "plan.accs[d] = new_acc  # lint: guarded-ok",
    )
    graph = _graph(tmp_path, {"xaynet_tpu/parallel/foo.py": bare})
    store_findings = [f for f in locks.run(graph) if "missing its rationale" in f.message]
    assert store_findings, "a bare guarded-ok must not suppress"

    with_rationale = PR7_RACE.replace(
        "plan.accs[d] = new_acc           # torn-slice write outside the lock",
        "plan.accs[d] = new_acc  # lint: guarded-ok: single-owner slot",
    ).replace(
        "new_acc = plan.accs[d] + batch   # read outside the lock",
        "new_acc = plan.accs[d] + batch  # lint: guarded-ok: single-owner slot",
    )
    graph = _graph(tmp_path, {"xaynet_tpu/parallel/foo.py": with_rationale})
    assert locks.run(graph) == []


def test_lock_pass_event_loop_guard(tmp_path):
    source = """
import threading

class Controller:
    def __init__(self):
        self.depth = 0  # guarded-by: event-loop

    def observe(self):
        self.depth += 1

def _sync_worker(ctl: Controller):
    ctl.observe()

async def _coro_worker(ctl: Controller):
    ctl.observe()

def spawn(ctl):
    threading.Thread(target=_sync_worker, args=(ctl,))

def spawn_loop_host(loop, ctl):
    # a thread that runs an event loop: its coroutines execute ON the loop
    threading.Thread(target=lambda: loop.run_until_complete(_coro_worker(ctl)))
"""
    graph = _graph(tmp_path, {"xaynet_tpu/ingest/foo.py": source})
    findings = locks.run(graph)
    # the sync chain is a foreign-thread touch; the coroutine chain is not
    assert any("event-loop-confined" in f.message for f in findings)
    assert all("_coro_worker" not in f.message for f in findings)


# --- call-graph host-sync/purity pass ---------------------------------------

# The old heuristic's documented false negative: tools/lint.py only walked
# functions whose NAME starts with _prog, so a module-level helper called
# FROM a program body escaped the purity check entirely.
SIM_HELPER_LEAK = """
import numpy as np
import jax.numpy as jnp

def leaky_helper(x):
    return np.asarray(x)  # host sync, one call deep

def traced_helper(x):
    return jnp.asarray(x)  # trace-safe: jax.numpy, not numpy

def _prog_round(x):
    a = leaky_helper(x)
    b = traced_helper(x)
    return a, b
"""


def test_old_prefix_heuristic_misses_helper_one_call_deep(tmp_path):
    """Regression fixture: the per-file rule (the pre-framework check)
    reports NOTHING for a host sync inside a helper called from a _prog*
    body — the false negative ISSUE 9 closes with the call-graph pass."""
    path = tmp_path / "xaynet_tpu/sim/leak.py"
    path.parent.mkdir(parents=True)
    path.write_text(SIM_HELPER_LEAK)
    info = SourceCache(tmp_path).get(path)
    assert not [f for f in check_file_info(info) if f.rule == "sync"]


def test_callgraph_purity_pass_catches_the_helper(tmp_path):
    graph = _graph(tmp_path, {"xaynet_tpu/sim/leak.py": SIM_HELPER_LEAK})
    findings = purity.run(graph)
    assert any(
        f.rule == "sync" and "leaky_helper" in f.message for f in findings
    ), findings
    # jnp.asarray is trace-safe and must NOT be flagged
    assert not any("traced_helper" in f.message for f in findings)


def test_bare_name_resolution_not_shadowed_by_out_of_scope_nested_def(tmp_path):
    """A nested def in an UNRELATED method must not capture a bare-name
    call (closure scoping is dot-boundary, not startswith) — otherwise a
    module-level host-syncing helper called from a program body resolves
    to the wrong function and the purity finding is silently lost."""
    source = """
import numpy as np

def helper(x):
    return np.asarray(x)  # the real callee: a host sync

class SimRound:
    def other(self):
        def helper():  # same name, different (unreachable) scope
            return 1
        return helper()

    def _prog_body(self, x):
        return helper(x)  # must bind to the MODULE-level helper
"""
    findings = purity.run(_graph(tmp_path, {"xaynet_tpu/sim/shadow.py": source}))
    assert any(
        f.rule == "sync" and "'helper'" in f.message for f in findings
    ), findings


def test_purity_pass_cross_file_and_suppression(tmp_path):
    files = {
        "xaynet_tpu/sim/round.py": (
            "from xaynet_tpu.ops.helpers import deep_helper\n"
            "def _prog_round(x):\n"
            "    return deep_helper(x)\n"
        ),
        "xaynet_tpu/ops/helpers.py": (
            "def deep_helper(x):\n"
            "    return x.item()\n"
        ),
    }
    findings = purity.run(_graph(tmp_path, files))
    assert any(
        f.file == "xaynet_tpu/ops/helpers.py" and f.rule == "sync" for f in findings
    ), findings

    files["xaynet_tpu/ops/helpers.py"] = (
        "def deep_helper(x):\n"
        "    return x.item()  # lint: sync-ok\n"
    )
    assert purity.run(_graph(tmp_path, files)) == []


def test_purity_fold_worker_leg(tmp_path):
    source = """
import threading
import numpy as np

class Pipe:
    def start(self):
        threading.Thread(target=self._loop)

    def _loop(self):
        self.helper_with_odd_name()

    def helper_with_odd_name(self):
        return np.asarray([1])  # matches no worker prefix: old rule missed it

    def drain(self):
        return np.asarray([2])  # the sanctioned sync point
"""
    findings = purity.run(_graph(tmp_path, {"xaynet_tpu/parallel/pipe.py": source}))
    assert any("helper_with_odd_name" in f.message for f in findings)
    assert not any("'Pipe.drain'" in f.message for f in findings)


# The Pallas-kernel leg (ISSUE 11): kernel bodies (*_kernel defs in ops
# files importing pallas) must stay pure traced code down the call graph —
# a host sync there lowers nowhere on real hardware, but interpret mode
# would silently run it, so the CPU CI has to catch it statically.
PALLAS_KERNEL_LEAK = """
import numpy as np
from jax.experimental import pallas as pl

def _chunk_helper(x):
    return np.asarray(x)  # host sync, one call deep from a kernel body

def _my_fold_kernel(ref, out):
    out[...] = _chunk_helper(ref[...])
"""


def test_purity_pallas_kernel_leg(tmp_path):
    files = {"xaynet_tpu/ops/fold_pallas.py": PALLAS_KERNEL_LEAK}
    findings = purity.run(_graph(tmp_path, files))
    assert any(
        f.rule == "sync" and "_chunk_helper" in f.message and "Pallas" in f.message
        for f in findings
    ), findings

    # suppression: an annotated trace-time constant passes
    files["xaynet_tpu/ops/fold_pallas.py"] = PALLAS_KERNEL_LEAK.replace(
        "np.asarray(x)  # host sync, one call deep from a kernel body",
        "np.asarray(x)  # lint: sync-ok",
    )
    leg = [
        f
        for f in purity.run(_graph(tmp_path, files))
        if "_chunk_helper" in f.message
    ]
    assert leg == []


def test_purity_pallas_leg_ignores_files_without_pallas_import(tmp_path):
    """The *_kernel name alone (e.g. an XLA jit builder) must not root the
    leg — only files that import jax.experimental.pallas hold kernel
    bodies."""
    source = (
        "import numpy as np\n"
        "def _aggregate_batch_kernel(acc, order_tuple):\n"
        "    return np.asarray(order_tuple)\n"
    )
    findings = purity.run(_graph(tmp_path, {"xaynet_tpu/ops/limbs_x.py": source}))
    assert not any("_aggregate_batch_kernel" in f.message for f in findings)


# --- accounting-invariant pass ----------------------------------------------


def test_invariant_pass_flags_unsanctioned_nb_models_mutation(tmp_path):
    source = (
        "def sneak_credit(agg, k):\n"
        "    agg.nb_models += k\n"
    )
    findings = invariants.run(_graph(tmp_path, {"xaynet_tpu/server/sneak.py": source}))
    assert any(f.rule == "invariant" and "nb_models" in f.message for f in findings)


def test_invariant_pass_respects_whitelist_and_suppression(tmp_path):
    # a whitelisted (file, qualname) site — mirrors the real masking.py entry
    ok = (
        "class Aggregation:\n"
        "    def aggregate(self, obj):\n"
        "        self.nb_models += 1\n"
    )
    findings = invariants.run(
        _graph(tmp_path, {"xaynet_tpu/core/mask/masking.py": ok})
    )
    assert findings == []

    suppressed_src = (
        "def experiment(agg):\n"
        "    agg.nb_models = 0  # lint: invariant-ok: scratch probe, not a round path\n"
    )
    findings = invariants.run(
        _graph(tmp_path, {"xaynet_tpu/server/x.py": suppressed_src})
    )
    assert findings == []


def test_invariant_pass_watches_edge_watermarks(tmp_path):
    source = (
        "def rewind(shared, edge):\n"
        "    shared.edge_watermarks[edge] = 0\n"
        "def wipe(shared):\n"
        "    shared.edge_watermarks.clear()\n"
    )
    findings = invariants.run(_graph(tmp_path, {"xaynet_tpu/server/wm.py": source}))
    assert len([f for f in findings if "watermark" in f.message]) == 2


# --- metrics cross-check ----------------------------------------------------


def _metrics_fixture(tmp_path, code: str, doc_rows: str):
    src = tmp_path / "xaynet_tpu/mod.py"
    src.parent.mkdir(parents=True, exist_ok=True)
    src.write_text(code)
    design = tmp_path / "DESIGN.md"
    design.write_text(
        "<!-- metrics-table:begin -->\n| Series | Type |\n|---|---|\n"
        + doc_rows
        + "\n<!-- metrics-table:end -->\n"
    )
    info = SourceCache(tmp_path).get(src)
    return metricscheck.run([info], design)


def test_metrics_parity_ok(tmp_path):
    code = (
        "from reg import get_registry\n"
        "A = get_registry().counter('xaynet_foo_total', 'help')\n"
        "B = get_registry().gauge('xaynet_bar_depth', 'help', ('shard',))\n"
    )
    rows = "| `xaynet_foo_total` | counter |\n| `xaynet_bar_depth{shard}` | gauge |"
    assert _metrics_fixture(tmp_path, code, rows) == []


def test_metrics_undocumented_and_stale_and_duplicate(tmp_path):
    code = (
        "from reg import get_registry\n"
        "A = get_registry().counter('xaynet_foo_total', 'help')\n"
        "B = get_registry().counter('xaynet_foo_total', 'help again')\n"
    )
    rows = "| `xaynet_gone_total` | counter |"
    findings = _metrics_fixture(tmp_path, code, rows)
    msgs = " | ".join(f.message for f in findings)
    assert "registered more than once" in msgs
    assert "not in the DESIGN.md metric tables" in msgs
    assert "xaynet_gone_total" in msgs and "not registered" in msgs


def test_metrics_brace_shorthand_expansion(tmp_path):
    code = (
        "from reg import get_registry\n"
        "A = get_registry().gauge('xaynet_s_depth', 'h')\n"
        "B = get_registry().gauge('xaynet_s_ratio', 'h')\n"
    )
    rows = "| `xaynet_s_{depth,ratio}` | gauge |"
    assert _metrics_fixture(tmp_path, code, rows) == []


# --- span-discipline pass (ISSUE 12) ----------------------------------------


def _spans_fixture(tmp_path, code: str, doc_rows: str):
    from tools.analysis import spans

    src = tmp_path / "xaynet_tpu/mod.py"
    src.parent.mkdir(parents=True, exist_ok=True)
    src.write_text(code)
    design = tmp_path / "DESIGN.md"
    design.write_text(
        "<!-- span-table:begin -->\n| Span | Where |\n|---|---|\n"
        + doc_rows
        + "\n<!-- span-table:end -->\n"
    )
    info = SourceCache(tmp_path).get(src)
    return spans.run([info], design)


def test_span_parity_and_with_discipline_ok(tmp_path):
    code = (
        "from ..telemetry import tracing as trace\n"
        "S = trace.declare_span('mod.work')\n"
        "def f():\n"
        "    with trace.get_tracer().span(S, batch=1):\n"
        "        pass\n"
        "    tracer = trace.get_tracer()\n"
        "    with tracer.span('mod.work'):\n"
        "        pass\n"
    )
    rows = "| `mod.work` | mod.py |"
    assert _spans_fixture(tmp_path, code, rows) == []


def test_span_bare_call_and_undeclared_flagged(tmp_path):
    code = (
        "from ..telemetry import tracing as trace\n"
        "S = trace.declare_span('mod.work')\n"
        "def f():\n"
        "    h = trace.get_tracer().span(S)\n"  # not a with-item
        "    with trace.get_tracer().span('mod.undeclared'):\n"
        "        pass\n"
    )
    rows = "| `mod.work` | mod.py |\n| `mod.undeclared` | nowhere |"
    msgs = " | ".join(f.message for f in _spans_fixture(tmp_path, code, rows))
    assert "must be used as a `with` item" in msgs
    assert "never declared" in msgs
    assert "not declared anywhere" in msgs  # the stale doc row for mod.undeclared


def test_span_duplicate_declaration_and_table_drift(tmp_path):
    code = (
        "from ..telemetry import tracing as trace\n"
        "A = trace.declare_span('mod.dup')\n"
        "B = trace.declare_span('mod.dup')\n"
        "C = trace.declare_span('mod.solo')\n"
    )
    rows = "| `mod.dup` | mod.py |"
    msgs = " | ".join(f.message for f in _spans_fixture(tmp_path, code, rows))
    assert "declared more than once" in msgs
    assert "'mod.solo' is not in the DESIGN.md §16 span table" in msgs


def test_span_brace_shorthand_rows(tmp_path):
    code = (
        "from ..telemetry import tracing as trace\n"
        "A = trace.declare_span('mod.one')\n"
        "B = trace.declare_span('mod.two')\n"
        "def f():\n"
        "    with trace.get_tracer().span(A):\n"
        "        with trace.get_tracer().span(B):\n"
        "            pass\n"
    )
    rows = "| `mod.{one,two}` | mod.py |"
    assert _spans_fixture(tmp_path, code, rows) == []


# --- secret-flow taint pass (ISSUE 14) ---------------------------------------

# The acceptance fixture: a mask seed formatted by one helper, emitted by
# another — the leak crosses TWO interprocedural hops before it reaches
# the logging call, which is exactly what a lexical grep can never see.
TAINT_2HOP_LEAK = """
import logging
logger = logging.getLogger("x")

def fmt(tag, material):
    return f"{tag}: {material.hex()}"

def emit(line):
    logger.warning("phase note: %s", line)

def close_window():
    seed = MaskSeed.generate()
    emit(fmt("seed", seed.as_bytes()))
"""


def test_taint_catches_planted_seed_to_log_through_two_hops(tmp_path):
    graph = _graph(tmp_path, {"xaynet_tpu/server/phases/leak.py": TAINT_2HOP_LEAK})
    findings = taint.run(graph)
    assert any(
        f.rule == "taint"
        and "mask seed" in f.message
        and "logging call" in f.message
        and "via emit" in f.message
        for f in findings
    ), findings


def test_taint_container_and_attr_propagation_across_methods(tmp_path):
    # the seed-dict shape: a secret stored into a container attribute in
    # one method leaks through a sibling method's log call
    source = """
import logging
logger = logging.getLogger("x")

class SeedVault:
    def __init__(self):
        self.seeds = {}

    def remember(self, pk):
        self.seeds[pk] = MaskSeed.generate()

    def debug_dump(self):
        logger.info("vault contents: %s", self.seeds)
"""
    findings = taint.run(_graph(tmp_path, {"xaynet_tpu/server/vault.py": source}))
    assert any(
        f.rule == "taint" and "logging call" in f.message for f in findings
    ), findings


def test_taint_sink_variety(tmp_path):
    source = """
import json
from ..telemetry import tracing as trace
from ..telemetry.recorder import flight_dump

def spans(tracer):
    s = MaskSeed.generate()
    with tracer.span("x.y", batch=1) as h:
        h.set(leaked=s.as_bytes())

def flights():
    s = MaskSeed.generate()
    flight_dump("trigger", detail=s.as_bytes().hex())

def labels(counter):
    s = MaskSeed.generate()
    counter.labels(trigger=s.as_bytes().hex()).inc()

def dumps():
    s = MaskSeed.generate()
    return json.dumps({"seed": s.as_bytes().hex()})

def raises():
    s = MaskSeed.generate()
    raise ValueError(f"bad seed {s.as_bytes().hex()}")
"""
    findings = taint.run(_graph(tmp_path, {"xaynet_tpu/server/sinks.py": source}))
    msgs = " | ".join(f.message for f in findings)
    assert "span attribute" in msgs
    assert "flight-recorder" in msgs
    assert "metric label" in msgs
    assert "serialized JSON dump" in msgs
    assert "exception message" in msgs


def test_taint_log_sink_catches_chained_and_attr_loggers(tmp_path):
    # logging.getLogger(...).warning(...) and self.logger.warning(...)
    # are log sinks too — not just the bound module-level `logger` name
    source = """
import logging

class Phase:
    def __init__(self):
        self.logger = logging.getLogger("x")

    def chained(self):
        s = MaskSeed.generate()
        logging.getLogger("x").warning("s=%s", s.as_bytes().hex())

    def attr(self):
        s = MaskSeed.generate()
        self.logger.info("s=%s", s.as_bytes().hex())
"""
    findings = taint.run(_graph(tmp_path, {"xaynet_tpu/server/chain.py": source}))
    lines = {f.line for f in findings if "logging call" in f.message}
    assert len(lines) == 2, findings


def test_taint_scrub_attrs_is_not_a_declassifier(tmp_path):
    # scrub_attrs only redacts deny-listed KEYS: a secret under a
    # non-denied key passes through verbatim, so taint must survive it
    source = """
import json
from ..telemetry.redact import scrub_attrs

def export(fh):
    s = MaskSeed.generate()
    json.dump(scrub_attrs({"d": s.as_bytes().hex()}, "x"), fh)
"""
    findings = taint.run(_graph(tmp_path, {"xaynet_tpu/server/scrub.py": source}))
    assert any("serialized JSON dump" in f.message for f in findings), findings


def test_taint_exception_sink_scoped_to_server_sdk_edge(tmp_path):
    source = """
def raises():
    s = MaskSeed.generate()
    raise ValueError(f"bad seed {s.as_bytes().hex()}")
"""
    # core/ raises are not an attacker/operator-facing surface (ISSUE 14)
    findings = taint.run(_graph(tmp_path, {"xaynet_tpu/core/mask/x.py": source}))
    assert not any("exception message" in f.message for f in findings)


def test_taint_declassifiers_terminate_flows(tmp_path):
    source = """
import logging
from .hash import sha256
from ..telemetry.redact import redact
logger = logging.getLogger("x")

def ok_projections(pk):
    seed = MaskSeed.generate()
    logger.info("seed: %d bytes, digest %s", len(seed.as_bytes()),
                sha256(seed.as_bytes()).hex())
    logger.warning("redacted: %s", redact(seed.as_bytes()))
    logger.info("sealed: %s", pk.encrypt(seed.as_bytes()).hex())
"""
    findings = taint.run(_graph(tmp_path, {"xaynet_tpu/server/clean.py": source}))
    assert findings == [], findings


def test_taint_suppression_requires_rationale(tmp_path):
    bare = """
import logging
logger = logging.getLogger("x")

def leak():
    s = MaskSeed.generate()
    logger.info("s=%s", s.as_bytes().hex())  # lint: taint-ok
"""
    findings = taint.run(_graph(tmp_path, {"xaynet_tpu/server/supp.py": bare}))
    assert any(f.rule == "taint" for f in findings), "a bare taint-ok must not suppress"
    assert any("missing its rationale" in f.message for f in findings)

    with_rationale = bare.replace(
        "# lint: taint-ok", "# lint: taint-ok: test fixture, sanctioned"
    )
    findings = taint.run(_graph(tmp_path, {"xaynet_tpu/server/supp.py": with_rationale}))
    assert findings == [], findings


def test_taint_source_suppression_sanctions_downstream_flow(tmp_path):
    # suppressing at the SOURCE read declares a declassification boundary:
    # the durable-state idiom (one reviewed suppression, no cascade)
    source = """
import json
import logging
logger = logging.getLogger("x")

def save(self):
    blob = json.dumps({"seed": MaskSeed.generate().as_bytes().hex()})  # lint: taint-ok: durable blob
    return blob.encode()

def caller(self, store):
    logger.info("saving %d bytes", len(save(self)))
    store.put(save(self))
"""
    findings = taint.run(_graph(tmp_path, {"xaynet_tpu/server/state.py": source}))
    assert findings == [], findings


def test_taint_known_clean_fixture_zero_findings(tmp_path):
    # representative telemetry usage over non-secret values: must be silent
    source = """
import json
import logging
logger = logging.getLogger("x")

class Phase:
    def __init__(self, tracer):
        self.tracer = tracer
        self.accepted = 0

    def handle(self, envelope):
        self.accepted += 1
        with self.tracer.span("phase.fold", members=len(envelope)) as h:
            h.set(outcome="folded")
        logger.info("round note: %d accepted", self.accepted)

    def report(self):
        return json.dumps({"accepted": self.accepted})
"""
    findings = taint.run(_graph(tmp_path, {"xaynet_tpu/server/phases/clean.py": source}))
    assert findings == [], findings


def _taint_design(tmp_path, sources_rows=None, declass_rows=None, sink_rows=None):
    reg = taint._registry_tokens()

    def rows(kind, override):
        if override is not None:
            return override
        return "\n".join(f"| `{t}` | doc |" for t in sorted(reg[kind]))

    design = tmp_path / "DESIGN.md"
    design.write_text(
        "<!-- taint-source-table:begin -->\n| Token | What |\n|---|---|\n"
        + rows("source", sources_rows)
        + "\n<!-- taint-source-table:end -->\n"
        "<!-- taint-declassifier-table:begin -->\n| Callee | Why |\n|---|---|\n"
        + rows("declassifier", declass_rows)
        + "\n<!-- taint-declassifier-table:end -->\n"
        "<!-- taint-sink-table:begin -->\n| Token | Surface |\n|---|---|\n"
        + rows("sink", sink_rows)
        + "\n<!-- taint-sink-table:end -->\n"
    )
    return design


def test_taint_design_parity_ok(tmp_path):
    graph = _graph(tmp_path, {"xaynet_tpu/empty.py": "x = 1\n"})
    assert taint.run(graph, _taint_design(tmp_path)) == []


def test_taint_design_parity_drift_both_directions(tmp_path):
    graph = _graph(tmp_path, {"xaynet_tpu/empty.py": "x = 1\n"})
    # a stale doc row and a missing registry row, in one table
    rows = "\n".join(
        f"| `{t}` | doc |"
        for t in sorted(taint._registry_tokens()["sink"] - {"log-call"})
    ) + "\n| `carrier-pigeon` | doc |"
    findings = taint.run(graph, _taint_design(tmp_path, sink_rows=rows))
    msgs = " | ".join(f.message for f in findings)
    assert "taint sink 'log-call'" in msgs and "is not in the DESIGN.md" in msgs
    assert "'carrier-pigeon' is not in the tools/analysis/taint.py registry" in msgs


def test_taint_cold_and_warm_timing_pins_the_gate():
    """The <1s warm full-tree budget (ISSUE 9, re-pinned by ISSUE 14): a
    cached re-verification of the whole tree — taint artifacts included —
    stays under a second; the cold deep passes stay within CI sanity."""
    import time

    # cold-ish: force the deep passes to run in-process (no result cache)
    t0 = time.perf_counter()
    rc = driver.run(REPO, strict=True, use_cache=False)
    cold = time.perf_counter() - t0
    assert rc == 0
    assert cold < 120.0, f"cold full-tree analysis took {cold:.1f}s"

    # warm: the persistent cache answers; best-of-two damps machine noise
    walls = []
    for _ in range(2):
        t0 = time.perf_counter()
        rc = driver.run(REPO, strict=True)
        walls.append(time.perf_counter() - t0)
        assert rc == 0
    warm = min(walls)
    assert warm < 1.0, f"warm cached gate took {warm:.2f}s (budget: <1s)"


# --- suppression / baseline mechanics ---------------------------------------


def test_legacy_suppression_tokens_still_work():
    assert suppressed("telemetry", "t = perf_counter()  # telemetry-exempt")
    assert suppressed("sync", "x = np.asarray(y)  # lint: sync-ok")
    assert not suppressed("guarded", "x = 1  # lint: guarded-ok")  # no rationale
    assert suppressed("guarded", "x = 1  # lint: guarded-ok: single owner")


def test_baseline_masks_known_findings(tmp_path):
    f1 = Finding("sync", "a.py", 10, "host sync in helper")
    f2 = Finding("sync", "a.py", 20, "host sync in helper")  # same key, 2nd slot
    f3 = Finding("guarded", "b.py", 5, "unguarded access")
    path = tmp_path / "baseline.json"
    Baseline.write(path, [f1, f2])
    baseline = Baseline.load(path)
    new, masked = baseline.split([f1, f2, f3])
    assert masked == [f1, f2] and new == [f3]
    # one slot consumed per occurrence: a third identical finding is NEW
    new, masked = baseline.split([f1, f2, Finding("sync", "a.py", 30, "host sync in helper")])
    assert len(masked) == 2 and len(new) == 1


def test_baseline_masked_findings_do_not_fail_the_driver(tmp_path, capsys):
    repo = tmp_path / "repo"
    (repo / "pkg").mkdir(parents=True)
    (repo / "pkg" / "bad.py").write_text("import os\n")  # unused import
    baseline = tmp_path / "baseline.json"
    # first run fails, records the baseline, then passes
    assert (
        driver.run(repo, ["pkg"], use_cache=False, baseline_path=baseline) == 1
    )
    assert (
        driver.run(
            repo, ["pkg"], use_cache=False, baseline_path=baseline, update_baseline=True
        )
        == 0
    )
    assert driver.run(repo, ["pkg"], use_cache=False, baseline_path=baseline) == 0
    out = capsys.readouterr()
    assert "unused import" in out.out


# --- result cache -----------------------------------------------------------


def test_result_cache_roundtrip_and_invalidation(tmp_path):
    cache_path = tmp_path / "cache.json"
    cache = ResultCache(cache_path)
    finding = Finding("fmt", "x.py", 3, "trailing whitespace")
    cache.put_file("x.py", "key1", [finding])
    cache.put_project("treekey", [])
    cache.save()

    fresh = ResultCache(cache_path)
    assert fresh.get_file("x.py", "key1") == [finding]
    assert fresh.get_file("x.py", "key2") is None  # content changed
    assert fresh.get_project("treekey") == []
    assert fresh.get_project("other") is None

    disabled = ResultCache(cache_path, enabled=False)
    assert disabled.get_file("x.py", "key1") is None


def test_cached_run_is_fast_and_identical(tmp_path, capsys):
    repo = tmp_path / "repo"
    (repo / "pkg").mkdir(parents=True)
    (repo / "pkg" / "a.py").write_text("import os\nx = 1\n")
    baseline = tmp_path / "baseline.json"
    rc1 = driver.run(repo, ["pkg"], baseline_path=baseline)
    first = capsys.readouterr().out
    rc2 = driver.run(repo, ["pkg"], baseline_path=baseline)
    second = capsys.readouterr().out
    assert (rc1, first) == (rc2, second)
    assert (repo / ".lint-cache.json").exists()


# --- --changed mode ---------------------------------------------------------


def test_changed_files_sees_worktree_and_commit_diffs(tmp_path):
    import shutil
    import subprocess

    if shutil.which("git") is None:
        import pytest

        pytest.skip("git unavailable")

    repo = tmp_path / "r"
    repo.mkdir()

    def git(*args):
        subprocess.run(
            ["git", *args], cwd=repo, check=True, capture_output=True,
            env={"HOME": str(tmp_path), "PATH": "/usr/bin:/bin:/usr/local/bin",
                 "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
                 "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@t"},
        )

    git("init", "-q")
    (repo / "a.py").write_text("x = 1\n")
    git("add", "a.py")
    git("commit", "-qm", "seed")
    (repo / "a.py").write_text("x = 2\n")  # modified vs HEAD
    (repo / "b.py").write_text("y = 1\n")  # untracked
    changed = driver.changed_files(repo)
    assert changed is not None and {"a.py", "b.py"} <= changed


# --- the self-gate ----------------------------------------------------------


def test_repo_tree_analyzes_clean_with_checked_in_baseline(capsys):
    """The acceptance gate: the real tree passes --strict with zero
    unsuppressed findings (and the checked-in baseline is empty, so they
    are not baseline-masked either)."""
    baseline = json.loads((REPO / "tools" / "analysis" / "baseline.json").read_text())
    assert baseline["findings"] == {}, "the checked-in baseline must stay empty"
    rc = driver.run(REPO, strict=True)
    out = capsys.readouterr()
    assert rc == 0, f"tree not clean:\n{out.out}"


def test_strict_cli_flag_parses():
    assert driver.main(["--strict"], repo=REPO) == 0


# --- tenant-scope pass (docs/DESIGN.md §19) --------------------------------

from tools.analysis import tenantscope  # noqa: E402

_TENANT_UNKEYED = """
class Phase:
    def handle(self, shared, req):
        last = shared.edge_watermarks.get(req.edge_id)
        return last
"""

_TENANT_KEYED = """
class Phase:
    def handle(self, shared, req):
        last = shared.edge_watermarks.get(req.edge_id)
        log(shared.tenant, last)
        return last
"""


def test_tenant_pass_flags_unkeyed_scoped_state_read(tmp_path):
    graph = _graph(tmp_path, {"xaynet_tpu/server/phases/foo.py": _TENANT_UNKEYED})
    findings = tenantscope.run(graph)
    assert any("edge_watermarks" in f.message and "tenant key" in f.message
               for f in findings)


def test_tenant_pass_quiet_with_tenant_key_in_scope(tmp_path):
    graph = _graph(tmp_path, {"xaynet_tpu/server/phases/foo.py": _TENANT_KEYED})
    assert tenantscope.run(graph) == []
    # a `tenant` PARAMETER also keys the scope
    param = _TENANT_UNKEYED.replace(
        "def handle(self, shared, req):", "def handle(self, shared, req, tenant):"
    )
    graph = _graph(tmp_path, {"xaynet_tpu/server/phases/foo.py": param})
    assert tenantscope.run(graph) == []


def test_tenant_pass_scoped_to_server_and_parallel_trees(tmp_path):
    # the same read under sim/ (not a coordinator tree) is not a finding
    graph = _graph(tmp_path, {"xaynet_tpu/sim/foo.py": _TENANT_UNKEYED})
    assert tenantscope.run(graph) == []


def test_tenant_pass_suppression_requires_rationale(tmp_path):
    bare = _TENANT_UNKEYED.replace(
        "last = shared.edge_watermarks.get(req.edge_id)",
        "last = shared.edge_watermarks.get(req.edge_id)  # lint: tenant-ok",
    )
    graph = _graph(tmp_path, {"xaynet_tpu/server/phases/foo.py": bare})
    assert any("missing its rationale" in f.message for f in tenantscope.run(graph))
    with_rationale = _TENANT_UNKEYED.replace(
        "last = shared.edge_watermarks.get(req.edge_id)",
        "last = shared.edge_watermarks.get(req.edge_id)  # lint: tenant-ok: per-tenant Shared",
    )
    graph = _graph(tmp_path, {"xaynet_tpu/server/phases/foo.py": with_rationale})
    assert tenantscope.run(graph) == []


_LEASE_ROGUE = """
def grab(pool):
    return pool.lease_host("t", (4, 4), "uint32")
"""


def test_tenant_pass_lease_site_whitelist(tmp_path):
    # a lease call outside the sanctioned sites is the static half of the
    # leases == releases round invariant
    graph = _graph(tmp_path, {"xaynet_tpu/parallel/rogue.py": _LEASE_ROGUE})
    findings = tenantscope.run(graph)
    assert any("lease_host" in f.message and "sanctioned" in f.message
               for f in findings)
    # the whitelist covers the real sites (file + qualname exact)
    graph = _graph(
        tmp_path,
        {"xaynet_tpu/parallel/shards.py":
         "class ShardPlan:\n    def _alloc(self, pool):\n"
         "        return pool.lease_host(self.tenant, (4, 4), 'uint32')\n"},
    )
    assert tenantscope.run(graph) == []
    # pool-internal code is exempt wholesale
    graph = _graph(tmp_path, {"xaynet_tpu/tenancy/pool.py": _LEASE_ROGUE})
    assert tenantscope.run(graph) == []


# --- tenant-scope pass: admin-path lock discipline (leg 3, §23) -------------

_ADMIN_UNLOCKED = """
class TenantLifecycle:
    def teardown(self, tenant):
        self.routes.pop(tenant, None)
        self.registry.remove(tenant)
"""

_ADMIN_LOCKED = """
class TenantLifecycle:
    def teardown(self, tenant):
        with self._lock:
            self.routes.pop(tenant, None)
            self.registry.remove(tenant)
"""


def test_tenant_pass_admin_mutation_outside_lock_flagged(tmp_path):
    graph = _graph(tmp_path, {"xaynet_tpu/tenancy/lifecycle.py": _ADMIN_UNLOCKED})
    findings = tenantscope.run(graph)
    assert any("pop()" in f.message and "admin-path" in f.message for f in findings)
    assert any("remove()" in f.message for f in findings)


def test_tenant_pass_admin_mutation_under_lock_quiet(tmp_path):
    graph = _graph(tmp_path, {"xaynet_tpu/tenancy/lifecycle.py": _ADMIN_LOCKED})
    assert tenantscope.run(graph) == []


def test_tenant_pass_admin_guarded_by_annotation_quiet(tmp_path):
    annotated = _ADMIN_UNLOCKED.replace(
        "self.registry.remove(tenant)",
        "self.registry.remove(tenant)  # guarded-by: registry._lock",
    ).replace(
        "self.routes.pop(tenant, None)",
        "self.routes.pop(tenant, None)  # guarded-by: _lock",
    )
    graph = _graph(tmp_path, {"xaynet_tpu/tenancy/lifecycle.py": annotated})
    assert tenantscope.run(graph) == []


def test_tenant_pass_admin_locked_suffix_exempt(tmp_path):
    # *_locked helpers run with the caller already holding the lock — the
    # repo-wide convention the pool/scheduler use too
    code = (
        "class TenantLifecycle:\n"
        "    def _set_state_locked(self, tenant, state):\n"
        "        self._states.pop(tenant, None)\n"
    )
    graph = _graph(tmp_path, {"xaynet_tpu/tenancy/lifecycle.py": code})
    assert tenantscope.run(graph) == []


def test_tenant_pass_admin_leg_only_covers_lifecycle(tmp_path):
    # the same unlocked mutations in another tenancy module are that
    # module's own discipline (locks pass), not the admin leg's
    graph = _graph(tmp_path, {"xaynet_tpu/tenancy/registry.py": _ADMIN_UNLOCKED})
    assert tenantscope.run(graph) == []


# --- tenant-scope pass: sanctioned migration sites (leg 4, §23) -------------

_MIGRATOR_ROGUE = """
def pin(pool, lease):
    pool.set_migrator(lease, None)
"""


def test_tenant_pass_migration_site_whitelist(tmp_path):
    graph = _graph(tmp_path, {"xaynet_tpu/parallel/rogue.py": _MIGRATOR_ROGUE})
    findings = tenantscope.run(graph)
    assert any("set_migrator" in f.message and "sanctioned" in f.message
               for f in findings)
    # a direct .migrator store is the same hole
    store = "def pin(lease):\n    lease.migrator = None\n"
    graph = _graph(tmp_path, {"xaynet_tpu/parallel/rogue.py": store})
    assert any(".migrator" in f.message for f in tenantscope.run(graph))
    # the real ring sites are whitelisted (file + qualname exact)
    ring = (
        "class _StagingRing:\n"
        "    def acquire(self, timeout=None):\n"
        "        lease = self._free.get(timeout=timeout)\n"
        "        self._pool.set_migrator(lease, None)\n"
        "        return lease.array\n"
    )
    graph = _graph(tmp_path, {"xaynet_tpu/parallel/streaming.py": ring})
    assert tenantscope.run(graph) == []
    # pool-internal code is exempt wholesale
    graph = _graph(tmp_path, {"xaynet_tpu/tenancy/pool.py": _MIGRATOR_ROGUE})
    assert tenantscope.run(graph) == []
