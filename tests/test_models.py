"""Model families: construction, jitted train steps, flatten round trips."""

import jax
import numpy as np

from xaynet_tpu.models import mlp, lenet, lora, lstm, resnet
from xaynet_tpu.models.mlp import flatten_params, unflatten_params


def test_mlp_trains():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 13)).astype(np.float32)
    w = rng.normal(size=13).astype(np.float32)
    y = x @ w
    params = mlp.init_params(jax.random.PRNGKey(0), 13)
    model, tx, step = mlp.make_train_step()
    opt_state = tx.init(params)
    jit_step = jax.jit(step)
    first = None
    for i in range(60):
        params, opt_state, loss = jit_step(params, opt_state, x, y)
        if first is None:
            first = float(loss)
    assert float(loss) < first * 0.5


def test_flatten_roundtrip():
    params = mlp.init_params(jax.random.PRNGKey(1), 13)
    flat = flatten_params(params)
    back = unflatten_params(params, flat)
    for a, b in zip(jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(back)):
        np.testing.assert_allclose(np.asarray(a, np.float32), np.asarray(b), rtol=1e-6)


def test_lenet_step():
    rng = np.random.default_rng(1)
    x = rng.normal(size=(8, 32, 32, 3)).astype(np.float32)
    y = rng.integers(0, 10, size=8)
    params = lenet.init_params(jax.random.PRNGKey(0))
    _, tx, step = lenet.make_train_step()
    opt_state = tx.init(params)
    p2, _, loss = step(params, opt_state, x, y)
    assert np.isfinite(float(loss))
    assert flatten_params(p2).shape == flatten_params(params).shape


def test_lstm_step():
    rng = np.random.default_rng(2)
    tokens = rng.integers(0, 80, size=(4, 20)).astype(np.int32)
    targets = rng.integers(0, 80, size=(4, 20)).astype(np.int32)
    params = lstm.init_params(jax.random.PRNGKey(0), seq_len=20, hidden=32)
    _, tx, step = lstm.make_train_step(hidden=32)
    opt_state = tx.init(params)
    _, _, loss = step(params, opt_state, tokens, targets)
    assert np.isfinite(float(loss))


def test_resnet50_param_count():
    """The stress model must be in the ~25M-parameter class."""
    params = resnet.init_params(jax.random.PRNGKey(0), image_shape=(32, 32, 3), num_classes=1000)
    n = resnet.param_count(params)
    assert 20_000_000 < n < 30_000_000, n


def test_lora_quantize_roundtrip():
    spec = lora.LoraSpec(targets={"q": (64, 64), "v": (64, 64)}, rank=4)
    adapters = lora.init_adapters(jax.random.PRNGKey(0), spec)
    q = lora.quantize_deltas(adapters, scale=10**6)
    back = lora.dequantize_deltas(q, adapters, scale=10**6)
    for a, b in zip(jax.tree_util.tree_leaves(adapters), jax.tree_util.tree_leaves(back)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_lora_masking_i32():
    """Quantized LoRA deltas federate through the I32 masking pipeline."""
    from xaynet_tpu.core.mask import (
        Aggregation,
        BoundType,
        DataType,
        GroupType,
        Masker,
        MaskConfig,
        Model,
        ModelType,
        Scalar,
    )

    spec = lora.LoraSpec(targets={"q": (8, 8)}, rank=2)
    adapters = lora.init_adapters(jax.random.PRNGKey(1), spec)
    q = lora.quantize_deltas(adapters, scale=10**4)
    config = MaskConfig(GroupType.PRIME, DataType.I32, BoundType.B6, ModelType.M3)
    model = Model.from_primitives([int(v) for v in q], DataType.I32)
    seed, masked = Masker(config.pair()).mask(Scalar.unit(), model)
    mask = seed.derive_mask(len(model), config.pair())
    unmasked = Aggregation.from_object(masked).unmask(mask)
    got = np.asarray(unmasked.into_primitives(DataType.I32))
    np.testing.assert_array_equal(got, q)
