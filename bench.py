"""Headline benchmark: masked-update aggregation throughput @ 25M params.

North star (BASELINE.json): aggregate 10k masked 25M-parameter updates in
< 60 s on TPU — i.e. >= 166.7 updates/s. The reference aggregates with a
sequential per-update big-int loop on one CPU core
(rust/xaynet-core/src/mask/masking.rs:292-316); here updates are planar
uint32 limb tensors folded into an HBM-resident accumulator with the
single-pass lazy-carry kernel (xaynet_tpu/ops/fold_jax.py).

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "updates/s", "vs_baseline": N}
``vs_baseline`` is the speedup over the 166.7 updates/s target.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def _sync(x) -> None:
    # device->host fetch: reliable completion barrier on every backend
    np.asarray(x[:1, :8])


def _device_probe_ok(timeout: float = 180.0, attempts: int = 3) -> bool:
    """Probe the accelerator in a subprocess (a wedged tunnel hangs forever).

    Retries with fresh subprocesses: a tunnel that is briefly down at t=0
    must not silently turn a TPU run into a CPU run. Probe stderr is echoed
    so a dead tunnel is diagnosable from the bench log.
    """
    import subprocess

    code = (
        "import jax, jax.numpy as jnp, numpy as np;"
        "d = jax.devices();"
        "x = jax.device_put(np.ones(8, np.float32));"
        "print('probe-platform:', d[0].platform, float(jnp.sum(x)))"
    )
    for i in range(attempts):
        try:
            r = subprocess.run(
                [sys.executable, "-c", code], capture_output=True, timeout=timeout, text=True
            )
            # success requires a NON-cpu platform: a fast-failing accelerator
            # init that silently falls back to CPU must count as a failed
            # probe, not as success (this function is only called when an
            # accelerator is expected)
            if (
                r.returncode == 0
                and "8.0" in r.stdout
                and "probe-platform:" in r.stdout
                and "probe-platform: cpu" not in r.stdout
            ):
                print(f"probe attempt {i + 1}: OK — {r.stdout.strip()}", file=sys.stderr)
                return True
            tail = (r.stderr or "")[-2000:]
            print(
                f"probe attempt {i + 1}: rc={r.returncode} stdout={r.stdout.strip()!r} "
                f"stderr tail:\n{tail}",
                file=sys.stderr,
            )
        except subprocess.TimeoutExpired as e:
            tail = (e.stderr or b"")[-2000:] if e.stderr else b""
            print(
                f"probe attempt {i + 1}: TIMEOUT after {timeout}s "
                f"(backend init hung — tunnel likely dead) stderr tail:\n"
                f"{tail.decode(errors='replace') if isinstance(tail, bytes) else tail}",
                file=sys.stderr,
            )
    return False


def main() -> None:
    import os

    if os.environ.get("JAX_PLATFORMS", "") != "cpu" and not _device_probe_ok():
        print(
            "accelerator unreachable after retries; falling back to CPU "
            "(headline JSON will be tagged platform=cpu)",
            file=sys.stderr,
        )
        os.environ["JAX_PLATFORMS"] = "cpu"

    # Pin the native fold thread config BEFORE anything touches the kernel,
    # and RECORD it in the headline JSON: BENCH_r05 re-measured 29.46
    # updates/s where r03 recorded ~49 on the same code path purely because
    # the implicit 2x-cores default resolved differently across container
    # migrations — a pinned, recorded config makes same-series comparisons
    # meaningful and lets bench_gate treat a config change as a NEW series.
    default_threads = str(min(16, 2 * (os.cpu_count() or 1)))
    os.environ.setdefault("XAYNET_NATIVE_THREADS", default_threads)
    # per-shard budget for the mesh fold legs: the full budget per shard
    # (measured faster than a split budget on cgroup-limited CPUs — the
    # oversubscription hides per-thread DRAM stalls, same rationale as the
    # 2x-cores default inside the kernel)
    os.environ.setdefault(
        "XAYNET_NATIVE_SHARD_THREADS", os.environ["XAYNET_NATIVE_THREADS"]
    )
    native_threads = int(os.environ["XAYNET_NATIVE_THREADS"])
    shard_threads = int(os.environ["XAYNET_NATIVE_SHARD_THREADS"])

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # the CPU fallback measures the multi-device story on a virtual
        # mesh: force 8 host devices before jax initializes so the mesh=8
        # shard-parallel leg below has real (if virtual) devices to shard
        # over (the single-device headline keeps using device 0 only)
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()

    import jax
    import jax.numpy as jnp

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    # persistent compile cache — accelerator runs only: a brief tunnel-up
    # window must not be spent recompiling kernels a previous capture
    # already built (~20-40s each). On CPU the cache is a net negative: the
    # shared-container fleet migrates between host types, so a cached CPU
    # executable regularly fails XLA's machine-feature check and every load
    # spews the multi-KB "CPU compilation doesn't match the machine type
    # ... could lead to execution errors such as SIGILL" warning over the
    # bench tail and kernel-selection log, while CPU kernels recompile in
    # seconds anyway.
    if os.environ.get("JAX_PLATFORMS", "") != "cpu":
        try:
            cache_dir = os.environ.get("XAYNET_JAX_CACHE", "/tmp/xaynet_jax_cache")
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        except Exception as e:  # cache is an optimization, never a failure
            print(f"compilation cache unavailable: {e}", file=sys.stderr)
    else:
        # ACTIVELY disable: skipping the enable was not enough (the image's
        # sitecustomize / an inherited cache dir can switch it on), and a
        # stale cross-machine cache entry spews the SIGILL warning wall
        from xaynet_tpu.utils.jaxcache import silence_cpu_cache

        silence_cpu_cache(jax)

    from xaynet_tpu.core.mask.config import BoundType, DataType, GroupType, MaskConfig, ModelType
    from xaynet_tpu.ops import limbs as host_limbs
    from xaynet_tpu.ops.fold_jax import fold_planar_batch

    platform = jax.devices()[0].platform
    on_tpu = platform != "cpu"
    # M6 allows up to 1e6 aggregated models (covers the 10k target)
    config = MaskConfig(GroupType.INTEGER, DataType.F32, BoundType.B0, ModelType.M6)
    n_limb = host_limbs.n_limbs_for_order(config.order)
    order = config.order

    if on_tpu:
        model_len, k, n_batches = 25_000_000, 16, 24
    else:
        # CPU fallback: measure the REAL 25M-param case when the host has
        # room for it (stack is k*n_limb*25M*4B twice: numpy + jax copies),
        # so the headline number needs no "scaled from a smaller model"
        # caveat; only tiny machines drop to the scaled 1M smoke.
        try:
            with open("/proc/meminfo") as f:
                avail_kb = next(
                    int(line.split()[1]) for line in f if line.startswith("MemAvailable:")
                )
        except (OSError, StopIteration):
            # non-Linux hosts: estimate from total physical pages rather
            # than silently dropping a well-provisioned box to the scaled
            # 1M smoke (ADVICE r3)
            try:
                avail_kb = (
                    os.sysconf("SC_PHYS_PAGES") * os.sysconf("SC_PAGE_SIZE") // 1024 // 2
                )
            except (ValueError, OSError, AttributeError):
                avail_kb = 0
                print("meminfo unavailable; falling back to scaled smoke", file=sys.stderr)
        if avail_kb >= 16 * 1024 * 1024:
            # k=16 amortizes the accumulator read/write against the
            # mandatory one-read-of-the-batch (measured +10% vs k=8)
            model_len, k, n_batches = 25_000_000, 16, 3
        else:
            model_len, k, n_batches = 1_000_000, 8, 4
    warmup = 2

    # Synthesize K masked updates host-side in the planar device layout
    # (uniform group elements are exactly what masked updates look like).
    rng = np.random.default_rng(0)
    host_stack = rng.integers(0, 2**32, size=(k, n_limb, model_len), dtype=np.uint32)
    host_stack[:, n_limb - 1, :] &= np.uint32((1 << 20) - 1)
    if on_tpu:
        # transfer per-update slices (~200 MB each @25M), never one multi-GB
        # RPC: the round-3 tunnel window died with UNAVAILABLE inside a
        # single 3.2 GB device_put before any kernel ran
        slices = []
        for i in range(k):
            s = jax.device_put(host_stack[i])
            jax.block_until_ready(s)
            slices.append(s)
            print(f"staged update {i + 1}/{k}", file=sys.stderr)
        stack = jnp.stack(slices)
        jax.block_until_ready(stack)
        del slices
    else:
        # local CPU device: one copy, no RPC to protect against (the 16 GB
        # gate above is sized for exactly numpy + jax copies of the stack)
        stack = jax.device_put(host_stack)
        host_stack_np = host_stack  # the native candidate reads it directly
    del host_stack

    # candidate kernels: XLA fold, (on real accelerators) the Pallas fold at
    # several tile sizes, and (on CPU) the native single-pass u64 fold;
    # calibrate quickly and measure with the fastest. Each candidate carries
    # its own initial-accumulator factory so host kernels run on numpy.
    def _zero_acc_jax():
        return jnp.zeros((n_limb, model_len), dtype=jnp.uint32)

    candidates = {"xla": (lambda a, s: fold_planar_batch(a, s, order), _zero_acc_jax)}
    if on_tpu:
        try:
            from xaynet_tpu.ops.fold_pallas import fold_planar_batch_pallas

            for tile in (1024, 2048, 4096, 8192):

                def _pallas(a, s, _t=tile):
                    return fold_planar_batch_pallas(a, s, order, tile_size=_t)

                candidates[f"pallas-t{tile}"] = (_pallas, _zero_acc_jax)
        except Exception:
            pass
    else:
        from xaynet_tpu.utils import native as native_lib

        order_limbs = host_limbs.order_limbs_for(order)
        _native_spare = {"buf": None}

        def _native(a, s):
            # ping-pong the result buffer: a fresh 200 MB np.empty per fold
            # costs ~0.15 s of page faults — the dropped accumulator becomes
            # the next spare (same trick as the aggregator's native kernel)
            out = host_limbs.fold_planar_batch_host(
                a, host_stack_np, order_limbs, out=_native_spare["buf"]
            )
            reusable = out is not a and isinstance(a, np.ndarray) and a.flags.writeable
            _native_spare["buf"] = a if reusable else None
            return out

        def _zero_acc_np():
            return np.zeros((n_limb, model_len), dtype=np.uint32)

        # only register when the C kernel is actually loadable — the label
        # in the headline JSON must never claim 'native' for a numpy run
        if native_lib.load() is not None:
            candidates["native-u64"] = (_native, _zero_acc_np)

    def calibrate(fn, make_acc):
        acc = make_acc()
        acc = fn(acc, stack)  # compile
        _sync(acc)
        t0 = time.perf_counter()
        for _ in range(2):
            acc = fn(acc, stack)
        _sync(acc)
        return time.perf_counter() - t0

    timings = {}
    for name, (fn, make_acc) in candidates.items():
        try:
            timings[name] = calibrate(fn, make_acc)
        except Exception as e:  # a kernel variant failing must not sink the bench
            print(f"kernel {name} unavailable: {type(e).__name__}: {e}", file=sys.stderr)
    best = min(timings, key=timings.get)
    fold, make_acc = candidates[best]
    print(f"kernel selection: {timings} -> {best}", file=sys.stderr)

    acc = make_acc()
    acc = fold(acc, stack)  # compile against the zeroed accumulator shape
    _sync(acc)

    for _ in range(warmup):
        acc = fold(acc, stack)
    _sync(acc)

    # median of >=3 repetitions with min/max spread (VERDICT r04 weak 1):
    # the r4 headline (26.4) sat 17% under a same-code mid-round draw (30.8)
    # purely from shared-container noise — one draw is not defensible. CPU
    # reps are ~1s each, so take 5 there (two bad draws can no longer drag
    # the median); TPU reps stay at 3 (tunnel-window budget)
    reps = 3 if on_tpu else 5
    rep_ups = []
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(n_batches):
            acc = fold(acc, stack)
        _sync(acc)
        dt = time.perf_counter() - t0
        rep_ups.append(k * n_batches / dt)
    ups = float(np.median(rep_ups))

    # --- mesh=8 shard-parallel fold headline (CPU fallback) ---------------
    # The SAME fold-only measurement as the single-device headline above
    # (pre-staged batch, repeated folds, no staging in the timed loop), but
    # through the production multi-device path: a ShardedAggregator over
    # every virtual device, kernel=auto racing mesh-XLA against the
    # per-shard native fold (one concurrent strided kernel call per shard
    # under the pinned per-shard thread budget). ROADMAP item 1's exit
    # criterion: this number must beat the best single-device native-u64
    # headline in BENCH_HISTORY.
    mesh8 = None
    n_dev = len(jax.devices())
    if not on_tpu and n_dev > 1:
        try:
            del acc, stack  # free the single-device copies first
            from xaynet_tpu.parallel.aggregator import ShardedAggregator
            from xaynet_tpu.parallel.mesh import make_mesh

            agg8 = ShardedAggregator(config, model_len, mesh=make_mesh(), kernel="auto")
            staged8 = jax.device_put(host_stack_np, agg8._batch_sharding)
            agg8.add_planar_batch(staged8)  # resolve (XLA vs per-shard native) + warm
            if agg8.kernel_used == "native-u64":
                # the host kernel reads the host batch in place — the
                # device copy only existed for the calibration race
                batch8 = host_stack_np
                del staged8
            else:
                batch8 = staged8
            agg8.add_planar_batch(batch8)
            _sync(np.asarray(agg8.acc))
            m_ups = []
            for _ in range(reps):
                t0 = time.perf_counter()
                for _ in range(n_batches):
                    agg8.add_planar_batch(batch8)
                _sync(np.asarray(agg8.acc))
                m_ups.append(k * n_batches / (time.perf_counter() - t0))
            mesh8 = {
                "value_raw": float(np.median(m_ups)),
                "mesh": n_dev,
                "kernel": agg8.kernel_used,
                "min_raw": float(min(m_ups)),
                "max_raw": float(max(m_ups)),
                "median_of": reps,
            }
            print(
                f"mesh={n_dev} shard-parallel fold: "
                f"{mesh8['value_raw']:.2f} updates/s "
                f"(kernel {agg8.kernel_used}, shard_threads {shard_threads}) "
                f"vs single-device {ups:.2f}",
                file=sys.stderr,
            )
            del agg8, batch8
        except Exception as e:  # the mesh leg must never sink the headline
            print(f"mesh8 leg unavailable: {type(e).__name__}: {e}", file=sys.stderr)
    # streaming vs sync: the SAME staged-per-batch aggregation through the
    # production ShardedAggregator — sequential add_batch (stage then fold,
    # serialized) vs the streaming pipeline (ring-buffer staging of batch
    # N+1 overlapping the fold of batch N). The headline above measures the
    # bare fold; this field tracks what the pipeline overlap buys on the
    # full stage+fold path. CPU-only: the TPU capture path never holds a
    # host-side wire copy of the stack (per-slice staging, tunnel limits).
    streaming_vs_sync = None
    bytes_per_fold = None
    if not on_tpu:
        try:
            # the comparison runs at half the headline batch so its extra
            # footprint (wire copy + 2 ring buffers + a second aggregator,
            # ~3x one half-batch) stays well inside the remaining headroom;
            # a cgroup OOM kill here would lose the headline JSON entirely,
            # which a try/except cannot catch — so gate on CURRENT
            # MemAvailable and skip rather than gamble
            k_s = max(2, k // 2)
            extra_kb = int(3.5 * k_s * n_limb * model_len * 4) // 1024
            try:
                with open("/proc/meminfo") as f:
                    avail_now_kb = next(
                        int(line.split()[1])
                        for line in f
                        if line.startswith("MemAvailable:")
                    )
            except (OSError, StopIteration):
                avail_now_kb = extra_kb * 2  # no meminfo: proceed (tiny smoke)
            if avail_now_kb < extra_kb * 2:
                raise MemoryError(
                    f"skipping: {avail_now_kb // 1024} MB available, "
                    f"comparison needs ~{extra_kb // 1024} MB"
                )
            from xaynet_tpu.parallel.aggregator import ShardedAggregator
            from xaynet_tpu.parallel.streaming import StreamingAggregator

            wire_stack = np.ascontiguousarray(host_stack_np[:k_s].transpose(0, 2, 1))
            b_batches = 3
            seq = ShardedAggregator(config, model_len, kernel="auto")
            seq.add_batch(wire_stack)  # resolve kernel + warm
            t0 = time.perf_counter()
            for _ in range(b_batches):
                seq.add_batch(wire_stack)
            _sync(np.asarray(seq.acc))
            t_sync = time.perf_counter() - t0
            stream_agg = ShardedAggregator(config, model_len, kernel=seq.kernel_used)
            stream = StreamingAggregator(
                stream_agg, staging_buffers=2, dispatch_ahead=2, max_batch=k_s
            )
            stream.submit_batch(wire_stack)
            stream.drain()  # warm (kernel resolve + ring page-in)
            t0 = time.perf_counter()
            for _ in range(b_batches):
                stream.submit_batch(wire_stack)
            stream.drain()
            t_stream = time.perf_counter() - t0
            stream.close()
            streaming_vs_sync = round(t_sync / t_stream, 3)
            print(
                f"streaming_vs_sync: sync {t_sync:.2f}s vs streaming {t_stream:.2f}s "
                f"-> {streaming_vs_sync}x (kernel {seq.kernel_used}, k={k_s}, "
                f"mesh={len(jax.devices())})",
                file=sys.stderr,
            )
            # --- bytes moved per fold: packed vs unpacked staging ---------
            # The packed-reduction exit metric (ROADMAP item 3): drive the
            # SAME wire batch through the production streaming pipeline with
            # packed staging on and off, and read the telemetry byte
            # counters (staging copies + cross-shard combine traffic) the
            # pipeline itself maintains. Lower is better; bench_gate.py
            # gates this family with inverted floor logic.
            from xaynet_tpu.parallel.aggregator import BYTES_REDUCED
            from xaynet_tpu.parallel.streaming import BYTES_STAGED

            def _bytes_sample():
                staged = sum(
                    BYTES_STAGED.labels(layout=lay).value
                    for lay in ("packed", "unpacked", "wire")
                )
                reduced = sum(
                    BYTES_REDUCED.labels(path=p).value for p in ("scatter", "gather")
                )
                return staged + reduced

            bytes_per_fold = {}
            for packed_mode in (False, True):
                bagg = ShardedAggregator(config, model_len, kernel=seq.kernel_used)
                bstream = StreamingAggregator(
                    bagg, staging_buffers=2, dispatch_ahead=2, max_batch=k_s,
                    packed=packed_mode,
                )
                bstream.submit_batch(wire_stack)
                bstream.drain()  # warm
                before = _bytes_sample()
                for _ in range(b_batches):
                    bstream.submit_batch(wire_stack)
                bstream.drain()
                bagg.snapshot()  # the final model download (gather leg)
                moved = _bytes_sample() - before
                bstream.close()
                bytes_per_fold["packed" if packed_mode else "unpacked"] = int(
                    moved / b_batches
                )
                bytes_per_fold["kernel"] = bagg.kernel_used
                del bagg, bstream
            print(
                f"bytes moved per fold (k={k_s}): "
                f"unpacked {bytes_per_fold['unpacked']:,} vs packed "
                f"{bytes_per_fold['packed']:,} "
                f"({1 - bytes_per_fold['packed'] / max(1, bytes_per_fold['unpacked']):.1%} saved)",
                file=sys.stderr,
            )
            del wire_stack
        except Exception as e:  # diagnostics must never sink the headline
            print(f"streaming_vs_sync unavailable: {type(e).__name__}: {e}", file=sys.stderr)

    # --- multi-tenant interleaved fold (2 tenants, one mesh) --------------
    # Two tenants with DIFFERENT model sizes fold concurrently through the
    # production streaming pipelines over the shared paged accumulator pool
    # and the tenant fold-batch scheduler (docs/DESIGN.md §19): tenant A at
    # the full 25M headline size, tenant B at a quarter of it. The headline
    # is combined 25M-equivalent updates/s (tenant B's updates scaled by
    # its length fraction); the scheduler's fairness split is recorded next
    # to it so a starved tenant is visible in the history, and both
    # tenants' pool leases must balance at the end (zero leaks).
    multi_tenant = None
    if not on_tpu:
        try:
            import threading as _threading

            from xaynet_tpu.parallel.aggregator import ShardedAggregator
            from xaynet_tpu.parallel.streaming import StreamingAggregator
            from xaynet_tpu.tenancy import get_pool, get_scheduler

            k_mt, b_mt = max(2, k // 2), 3
            len_b = model_len // 4
            wire_a = np.ascontiguousarray(host_stack_np[:k_mt].transpose(0, 2, 1))
            wire_b = np.ascontiguousarray(
                host_stack_np[:k_mt, :, :len_b].transpose(0, 2, 1)
            )
            sched = get_scheduler()
            streams = {}
            for tenant, (mlen, wire) in {
                "bench-a": (model_len, wire_a),
                "bench-b": (len_b, wire_b),
            }.items():
                agg_t = ShardedAggregator(config, mlen, kernel="auto")
                streams[tenant] = (
                    agg_t,
                    StreamingAggregator(
                        agg_t, staging_buffers=2, dispatch_ahead=2,
                        max_batch=k_mt, tenant=tenant,
                    ),
                    wire,
                )
                streams[tenant][1].submit_batch(wire)  # resolve + warm
                streams[tenant][1].drain()
            # capture AFTER the warm-up drains: the recorded fairness split
            # must cover exactly the measured window's grants
            split_before = sched.split()
            walls = {}

            def run_tenant(tenant: str) -> None:
                _agg, stream, wire = streams[tenant]
                t0 = time.perf_counter()
                for _ in range(b_mt):
                    stream.submit_batch(wire)
                stream.drain()
                walls[tenant] = time.perf_counter() - t0

            t0 = time.perf_counter()
            threads = [
                _threading.Thread(target=run_tenant, args=(t,)) for t in streams
            ]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            wall = time.perf_counter() - t0
            equivalent = (
                k_mt * b_mt  # tenant A at the reference 25M size
                + k_mt * b_mt * (len_b / model_len)  # tenant B, scaled
            ) / wall
            split_after = sched.split()
            fairness = {
                t: split_after.get(t, 0) - split_before.get(t, 0)
                for t in streams
            }
            kernel_mt = streams["bench-a"][0].kernel_used
            pool = get_pool()
            for tenant, (agg_t, stream, _wire) in streams.items():
                stream.close()
                agg_t.release_plan_pages()
                assert pool.balanced(tenant), f"{tenant} leaked pool leases"
            multi_tenant = {
                "value_raw": equivalent,
                "tenants": 2,
                "model_lens": [model_len, len_b],
                "kernel": kernel_mt,
                "mesh": len(jax.devices()),
                "fairness": fairness,
                "walls_s": {t: round(w, 2) for t, w in walls.items()},
            }
            print(
                f"multi-tenant interleaved fold: {equivalent:.2f} equivalent "
                f"updates/s over {wall:.2f}s (25M + {len_b / 1e6:.1f}M params, "
                f"kernel {kernel_mt}, fairness {fairness})",
                file=sys.stderr,
            )
            del streams, wire_a, wire_b
        except Exception as e:  # the tenancy leg must never sink the headline
            print(f"multi-tenant leg unavailable: {type(e).__name__}: {e}", file=sys.stderr)

    # --- sim headline: whole federated rounds as ONE jitted program -------
    # A genuinely different workload from the fold headline above: per-
    # participant ChaCha mask derivation + masked-model generation +
    # aggregation + sum-mask reconstruction + unmask, all in-graph
    # (xaynet_tpu/sim/, DESIGN §13), measured end-to-end (host fixed-point
    # encode/decode included) in simulated participants per second. The
    # series identity is (model size, participants, block, mesh) — a
    # population-shape change starts a NEW series for tools/bench_gate.py.
    sim_out = None
    try:
        from fractions import Fraction

        from xaynet_tpu.parallel.mesh import make_mesh
        from xaynet_tpu.sim import SimRound, SimSpec, seeds_for

        sim_len, sim_p, sim_block = 1000, 2048, 256
        sim_cfg = config.pair()
        sim_seeds = seeds_for(sim_p, root=42)
        sim_rng = np.random.default_rng(42)
        sim_weights = sim_rng.uniform(-1, 1, (sim_p, sim_len)).astype(np.float32)
        sim_scalar = Fraction(1, sim_p)
        sim_legs = {}
        meshes = {1: None}
        if n_dev > 1:
            # unlike the mesh8 FOLD leg (deliberately CPU-only: its point
            # is the virtual-mesh production path), the sim mesh leg runs
            # on real accelerators too — that is the only place the
            # participant-axis sharding story produces a meaningful number
            meshes[n_dev] = make_mesh()
        for mesh_size, mesh in meshes.items():
            simr = SimRound(SimSpec(sim_cfg, sim_len, block_size=sim_block), mesh=mesh)
            simr.run(sim_seeds, sim_weights, scalar=sim_scalar)  # compile + warm
            pps = []
            for _ in range(3):
                t0 = time.perf_counter()
                simr.run(sim_seeds, sim_weights, scalar=sim_scalar)
                pps.append(sim_p / (time.perf_counter() - t0))
            sim_legs[mesh_size] = {
                "value": round(float(np.median(pps)), 2),
                "unit": "participants/s",
                "model_len": sim_len,
                "participants": sim_p,
                "block": sim_block,
                "mesh": mesh_size,
                "spread": {
                    "median_of": 3,
                    "min": round(min(pps), 2),
                    "max": round(max(pps), 2),
                },
            }
            print(
                f"sim round (mesh={mesh_size}): {sim_legs[mesh_size]['value']:.2f} "
                f"participants/s @n={sim_len} P={sim_p} block={sim_block}",
                file=sys.stderr,
            )
        sim_out = sim_legs
        # the sim series appends to BENCH_HISTORY.jsonl directly (same
        # contract as the mesh8 fold series: the driver only captures the
        # single fold-headline JSON line)
        try:
            hist = os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "BENCH_HISTORY.jsonl"
            )
            # the gate follows the LATEST record's series per family: append
            # the single-device leg last so the default sim gate tracks the
            # leg that is meaningful on every host (the mesh leg only says
            # something on real devices)
            with open(hist, "a") as f:
                for mesh_size, leg in sorted(sim_legs.items(), reverse=True):
                    record = {
                        "ts": time.time(),
                        "source": "bench.py:sim",
                        "parsed": {
                            "metric": (
                                f"sim round throughput @{sim_len} params "
                                "(in-graph federated round)"
                            ),
                            "platform": platform,
                            # rate series split on host core count (gate)
                            "cpus": os.cpu_count(),
                            **leg,
                        },
                    }
                    f.write(json.dumps(record) + "\n")
        except Exception as e:  # history append must never sink the bench
            print(f"BENCH_HISTORY sim append failed: {e}", file=sys.stderr)
    except Exception as e:  # the sim leg must never sink the fold headline
        print(f"sim leg unavailable: {type(e).__name__}: {e}", file=sys.stderr)

    # scale CPU smoke runs to the 25M-param metric so the number is comparable
    scale = model_len / 25_000_000
    scaled_ups = ups * scale
    baseline = 10_000 / 60.0  # north-star floor: 10k updates in 60s
    if on_tpu:
        metric = "masked-update aggregation throughput @25M params (PET update phase)"
    elif model_len == 25_000_000:
        metric = (
            "masked-update aggregation throughput @25M params, CPU fallback "
            "(PET update phase)"
        )
    else:
        metric = (
            f"masked-update aggregation throughput, CPU fallback @{model_len} params "
            "scaled to the 25M metric (PET update phase)"
        )
    mesh8_out = None
    if mesh8 is not None:
        mesh8_out = {
            "value": round(mesh8["value_raw"] * scale, 2),
            "unit": "updates/s",
            "vs_baseline": round(mesh8["value_raw"] * scale / baseline, 3),
            "mesh": mesh8["mesh"],
            "kernel": mesh8["kernel"],
            "beats_single_device": mesh8["value_raw"] > ups,
            "spread": {
                "median_of": mesh8["median_of"],
                "min": round(mesh8["min_raw"] * scale, 2),
                "max": round(mesh8["max_raw"] * scale, 2),
            },
        }
    multi_tenant_out = None
    if multi_tenant is not None:
        multi_tenant_out = {
            "value": round(multi_tenant["value_raw"], 2),
            "unit": "updates/s",
            "tenants": multi_tenant["tenants"],
            "model_lens": multi_tenant["model_lens"],
            "kernel": multi_tenant["kernel"],
            "mesh": multi_tenant["mesh"],
            "fairness": multi_tenant["fairness"],
            "walls_s": multi_tenant["walls_s"],
        }
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(scaled_ups, 2),
                "unit": "updates/s",
                "vs_baseline": round(scaled_ups / baseline, 3),
                "platform": platform,
                "kernel": best,
                "model_len": model_len,
                "native_threads": native_threads,
                "shard_threads": shard_threads,
                "streaming_vs_sync": streaming_vs_sync,
                "bytes_per_fold": bytes_per_fold,
                "mesh8": mesh8_out,
                "multi_tenant": multi_tenant_out,
                "sim": sim_out,
                "spread": {
                    "median_of": reps,
                    "min": round(min(rep_ups) * scale, 2),
                    "max": round(max(rep_ups) * scale, 2),
                },
            }
        )
    )
    # The mesh=8 series is appended to BENCH_HISTORY.jsonl directly: the
    # driver only captures the single JSON line above as the single-device
    # headline, and the tier-2 gate (tools/bench_gate.py) must cover the
    # sharded path as its own series from this round onward. ONLY the
    # canonical @25M run appends — the gate keys on the LATEST record's
    # series, so a scaled smoke run on a small host must not plant a
    # throwaway series as the newest line and de-gate the real one.
    # both layouts or neither: a failure between the two measurement legs
    # must not plant an unpaired record as the family's latest line (the
    # gate keys the gated series on the latest record)
    if (
        bytes_per_fold is not None
        and model_len == 25_000_000
        and all(lay in bytes_per_fold for lay in ("unpacked", "packed"))
    ):
        # the bytes-moved series (staging + cross-shard combine traffic per
        # fold, from the pipeline's own telemetry counters): packed staging
        # and its unpacked control are separate metrics of one
        # lower-is-better family (tools/bench_gate.py inverts the floor
        # logic for bytes/fold units)
        try:
            hist = os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "BENCH_HISTORY.jsonl"
            )
            with open(hist, "a") as f:
                for layout in ("unpacked", "packed"):
                    record = {
                        "ts": time.time(),
                        "source": "bench.py:bytes",
                        "parsed": {
                            "metric": (
                                f"bytes moved per fold @25M params ({layout} staging)"
                            ),
                            "value": bytes_per_fold[layout],
                            "unit": "bytes/fold",
                            "platform": platform,
                            "kernel": bytes_per_fold.get("kernel"),
                            "mesh": len(jax.devices()),
                            "model_len": model_len,
                            "native_threads": native_threads,
                            "shard_threads": shard_threads,
                        },
                    }
                    f.write(json.dumps(record) + "\n")
        except Exception as e:  # history append must never sink the bench
            print(f"BENCH_HISTORY bytes append failed: {e}", file=sys.stderr)
    if multi_tenant_out is not None and model_len == 25_000_000:
        # the multi-tenant interleaved series: 25M-equivalent updates/s of
        # two tenants folding concurrently through the paged pool + tenant
        # scheduler, with the fairness split recorded on the record (§19)
        try:
            hist = os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "BENCH_HISTORY.jsonl"
            )
            record = {
                "ts": time.time(),
                "source": "bench.py:multi_tenant",
                "parsed": {
                    "metric": "multi-tenant interleaved fold @25M params (2 tenants)",
                    "value": multi_tenant_out["value"],
                    "unit": "updates/s",
                    "platform": platform,
                    "kernel": multi_tenant_out["kernel"],
                    "mesh": multi_tenant_out["mesh"],
                    "model_len": model_len,
                    "native_threads": native_threads,
                    "shard_threads": shard_threads,
                    "cpus": os.cpu_count(),
                    "tenants": multi_tenant_out["tenants"],
                    "model_lens": multi_tenant_out["model_lens"],
                    "fairness": multi_tenant_out["fairness"],
                },
            }
            with open(hist, "a") as f:
                f.write(json.dumps(record) + "\n")
        except Exception as e:  # history append must never sink the bench
            print(f"BENCH_HISTORY multi-tenant append failed: {e}", file=sys.stderr)
    if mesh8_out is not None and model_len == 25_000_000:
        mesh8_metric = (
            f"masked-update aggregation throughput @25M params, "
            f"mesh={mesh8['mesh']} CPU fallback (PET update phase)"
        )
        try:
            hist = os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "BENCH_HISTORY.jsonl"
            )
            record = {
                "ts": time.time(),
                "source": "bench.py:mesh8",
                "parsed": {
                    "metric": mesh8_metric,
                    "value": mesh8_out["value"],
                    "unit": "updates/s",
                    "vs_baseline": mesh8_out["vs_baseline"],
                    "platform": platform,
                    "kernel": mesh8_out["kernel"],
                    "mesh": mesh8_out["mesh"],
                    "model_len": model_len,
                    "native_threads": native_threads,
                    "shard_threads": shard_threads,
                    "cpus": os.cpu_count(),
                    "spread": mesh8_out["spread"],
                },
            }
            with open(hist, "a") as f:
                f.write(json.dumps(record) + "\n")
        except Exception as e:  # history append must never sink the bench
            print(f"BENCH_HISTORY append failed: {e}", file=sys.stderr)


if __name__ == "__main__":
    sys.exit(main())
