"""Process-sharded loadgen entry point: ``python -m xaynet_tpu.loadgen.runner``.

One run = one round's worth of forged update traffic against a live
coordinator. The parent only does bookkeeping; every DRIVER is a spawned
process that independently (no cross-process pickling of round state):

1. fetches ``GET /params`` and polls ``GET /sums`` over the same REST
   boundary a participant uses, so the forge sees exactly the negotiated
   round (wire format included);
2. forges its participant range — the signing-key search space is
   partitioned by cumulative participant offset (``key_start + offset *
   key_spacing``, same rule as ``sdk.flood``) so shards never collide;
3. replays the shard through the event-driven driver against its target
   set (coordinator root, ``/t/<tenant>/`` routes, or edge-runner URLs);
4. reports a ``DriverStats`` dict back through a queue.

Defaults mirror the ``[loadgen]`` section of the coordinator TOML
(``server.settings.LoadgenSettings``) so one config file describes a
whole soak; every knob is also a CLI flag for ad-hoc runs.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import multiprocessing as mp
import sys
import time
from fractions import Fraction

from .build import forge_population
from .driver import DriverStats, ReplayDriver
from .schedule import ChurnSpec, ReplaySchedule

# forge key-space stride per participant (sdk.flood's spacing): wide
# enough that the per-participant signing-key search never runs past its
# neighbour's range
KEY_SPACING = 1000


def shard_sizes(participants: int, drivers: int) -> list[int]:
    """Participant count per driver: near-even, deterministic, sums to n."""
    base, extra = divmod(participants, drivers)
    return [base + (1 if d < extra else 0) for d in range(drivers)]


def targets_for(url: str, tenants: str) -> list[str]:
    """Target URLs for a run: tenant routes if given, else the root."""
    names = [t.strip() for t in tenants.split(",") if t.strip()]
    return [f"{url.rstrip('/')}/t/{t}" for t in names] if names else [url]


async def _fetch_round(target: str, timeout: float, sum_wait_s: float):
    """GET /params + poll /sums over the participant REST boundary — each
    driver sees exactly the negotiated round, wire format included."""
    from ..sdk.client import HttpClient

    client = HttpClient(target, timeout=timeout)
    try:
        params = await client.get_round_params()
        deadline = time.monotonic() + sum_wait_s
        while True:
            sums = await client.get_sums()
            if sums:
                return params, sums
            if time.monotonic() >= deadline:
                raise RuntimeError(
                    f"{target}: no sum dict before deadline — is the "
                    "coordinator in the update phase?"
                )
            await asyncio.sleep(0.25)
    finally:
        client.close()


async def _shard_main(shard: int, cfg: dict) -> dict:
    """One driver's whole life: fetch round(s), forge the shard, replay.

    Every TARGET (tenant route or edge endpoint pointing at a distinct
    coordinator round) is its own PET round with its own params, sum dict
    and signing-key population — so the shard forges one sub-population
    per target against that target's negotiated round, then replays them
    concurrently under one shared pacing clock. Global participant ``g``
    belongs to target ``g % T`` and signing-key range ``key_start + g *
    KEY_SPACING`` — the assignment depends only on (participants,
    drivers, targets), so re-sharding the tier never collides keys and a
    control run can rebuild any slice."""
    sizes = shard_sizes(cfg["participants"], cfg["drivers"])
    shard_n = sizes[shard]
    if shard_n == 0:
        return DriverStats().to_dict()
    # participants before this shard -> this shard's global index offset
    offset = sum(sizes[:shard])
    # explicit target list (edge-runner URLs) beats the tenant expansion
    targets = list(cfg.get("targets") or ()) or targets_for(
        cfg["url"], cfg["tenants"]
    )
    n_t = len(targets)
    # shared_round: every target fronts the SAME coordinator round (edge
    # fan-in) — one population, one scalar; unshared targets (tenant
    # routes) are each their own round with their own sub-population
    shared = bool(cfg.get("shared_round"))
    wire = {"auto": None, "packed": True, "legacy": False}[cfg["wire"]]

    async def one_target(t_idx: int, target: str) -> DriverStats:
        # this target's global indices within the shard: g ≡ t_idx (mod T)
        first = offset + ((t_idx - offset) % n_t)
        count = len(range(first, offset + shard_n, n_t))
        if count == 0:
            return DriverStats()
        params, sums = await _fetch_round(
            target, cfg["timeout"], cfg["sum_wait_s"]
        )
        population = forge_population(
            params,
            sums,
            count,
            # the scalar is a POPULATION property of the target's round:
            # 1/(that round's total updaters across ALL drivers), never
            # 1/shard — a shard-local default would change the aggregate
            # whenever the tier is re-sharded
            scalar=Fraction(
                1,
                cfg["participants"]
                if shared
                else len(range(t_idx, cfg["participants"], n_t)),
            ),
            model_length=cfg["model_length"],
            block_size=cfg["block_size"],
            key_start=cfg["key_start"] + first * KEY_SPACING,
            key_spacing=n_t * KEY_SPACING,
            rng_seed=cfg["seed"] + shard * n_t + t_idx,
            wire_planar=wire,
        )
        schedule = ReplaySchedule(
            count,
            ChurnSpec(
                dropout_rate=cfg["dropout_rate"],
                stragglers=cfg["stragglers"],
                straggle_delay_s=cfg["straggle_delay_ms"] / 1000.0,
                seed=cfg["seed"] + shard * n_t + t_idx,
            ),
            ramp_s=cfg["ramp_s"],
        )
        driver = ReplayDriver(
            [target],
            concurrency=max(1, cfg["concurrency"] // n_t),
            timeout=cfg["timeout"],
            max_shed_retries=cfg["max_shed_retries"],
        )
        t0 = time.time()
        try:
            return await driver.replay(population.messages, schedule), t0, time.time()
        finally:
            driver.close()

    results = [r for r in await asyncio.gather(
        *(one_target(i, t) for i, t in enumerate(targets))
    ) if isinstance(r, tuple)]
    merged = DriverStats()
    for r, _, _ in results:
        merged.merge(r)
    out = merged.to_dict()
    # epoch replay window (forge time excluded) so the parent can compute
    # the TIER's replay wall — drivers overlap; summing or walling the
    # whole parent run would fold forge/compile time into the rate
    if results:
        out["replay_start"] = min(t0 for _, t0, _ in results)
        out["replay_end"] = max(t1 for _, _, t1 in results)
    return out


def _shard_entry(shard: int, cfg: dict, queue) -> None:
    """Spawned-process entry (top level so the spawn context can pickle
    it); ships a result or an error marker — the parent never hangs."""
    try:
        queue.put((shard, _run_shard(shard, cfg), None))
    except BaseException as exc:  # noqa: BLE001 - report, don't swallow
        queue.put((shard, None, f"{type(exc).__name__}: {exc}"))


def _run_shard(shard: int, cfg: dict) -> dict:
    return asyncio.run(_shard_main(shard, cfg))


def run(cfg: dict) -> dict:
    """Run the whole driver tier; returns the merged stats dict.

    Always process-sharded (spawn context): each driver owns its own JAX
    runtime and socket pool, so forging scales across cores and a driver
    crash cannot take the parent down.
    """
    ctx = mp.get_context("spawn")
    queue = ctx.Queue()
    procs = [
        ctx.Process(target=_shard_entry, args=(shard, cfg, queue), daemon=True)
        for shard in range(cfg["drivers"])
    ]
    start = time.monotonic()
    for p in procs:
        p.start()
    merged = DriverStats()
    failures = []
    per_shard = {}
    window = []
    for _ in procs:
        shard, stats, err = queue.get()
        if err is not None:
            failures.append(f"driver {shard}: {err}")
        else:
            per_shard[shard] = stats
            if "replay_start" in stats:
                window.append((stats["replay_start"], stats["replay_end"]))
            partial = DriverStats(
                **{
                    k: v
                    for k, v in stats.items()
                    if k not in ("accepted_per_s", "replay_start", "replay_end")
                }
            )
            merged.merge(partial)
    for p in procs:
        p.join()
    if failures:
        raise RuntimeError("; ".join(failures))
    # the headline rate is accepted / TIER replay wall: the union of the
    # drivers' replay windows (they overlap), NOT the parent wall — that
    # would fold per-driver forge + jit-compile time into the REST rate
    if window:
        merged.wall_s = max(t1 for _, t1 in window) - min(t0 for t0, _ in window)
    else:
        merged.wall_s = time.monotonic() - start
    out = merged.to_dict()
    out["total_wall_s"] = round(time.monotonic() - start, 3)
    out["drivers"] = {str(k): per_shard[k] for k in sorted(per_shard)}
    return out


def default_cfg() -> dict:
    """The CLI defaults, importable by harnesses (``tools/loadgen_soak``)."""
    from ..server.settings import LoadgenSettings

    s = LoadgenSettings()
    return {
        "url": "http://127.0.0.1:8080",
        "participants": s.participants,
        "drivers": s.drivers,
        "block_size": s.block_size,
        "tenants": s.tenants,
        "wire": s.wire,
        "dropout_rate": s.dropout_rate,
        "stragglers": s.stragglers,
        "straggle_delay_ms": s.straggle_delay_ms,
        "concurrency": s.concurrency,
        "seed": s.seed,
        "ramp_s": 0.0,
        "model_length": None,
        "key_start": 0,
        "timeout": 30.0,
        "sum_wait_s": 120.0,
        "max_shed_retries": 3,
        "targets": None,
        "shared_round": False,
    }


def main(argv=None) -> int:
    d = default_cfg()
    ap = argparse.ArgumentParser(
        prog="xaynet_tpu.loadgen.runner",
        description="replay forged PET update traffic against a coordinator",
    )
    ap.add_argument("--url", default=d["url"], help="coordinator base URL")
    ap.add_argument("--participants", type=int, default=d["participants"])
    ap.add_argument("--drivers", type=int, default=d["drivers"])
    ap.add_argument("--block-size", type=int, default=d["block_size"])
    ap.add_argument(
        "--tenants",
        default=d["tenants"],
        help="csv tenant ids; spread across /t/<tenant>/ routes",
    )
    ap.add_argument("--wire", choices=("auto", "packed", "legacy"), default=d["wire"])
    ap.add_argument("--dropout", type=float, default=d["dropout_rate"])
    ap.add_argument("--stragglers", type=int, default=d["stragglers"])
    ap.add_argument(
        "--straggle-delay-ms", type=float, default=d["straggle_delay_ms"]
    )
    ap.add_argument("--ramp-s", type=float, default=d["ramp_s"])
    ap.add_argument("--concurrency", type=int, default=d["concurrency"])
    ap.add_argument("--seed", type=int, default=d["seed"])
    ap.add_argument(
        "--model-length",
        type=int,
        default=None,
        help="override the round's model length (mismatch tests only)",
    )
    ap.add_argument("--key-start", type=int, default=d["key_start"])
    ap.add_argument("--timeout", type=float, default=d["timeout"])
    ap.add_argument("--sum-wait-s", type=float, default=d["sum_wait_s"])
    ap.add_argument(
        "--max-shed-retries",
        type=int,
        default=d["max_shed_retries"],
        help="per-upload 429 retries before abandoning (soaks that must "
        "land every update set this high and let Retry-After pace them)",
    )
    ap.add_argument(
        "--target",
        action="append",
        dest="targets",
        default=None,
        metavar="URL",
        help="explicit target URL (repeatable; e.g. edge-runner endpoints)"
        " — overrides the --url/--tenants expansion",
    )
    ap.add_argument(
        "--shared-round",
        action="store_true",
        help="all targets front the SAME coordinator round (edge fan-in):"
        " one population scalar instead of one round per target",
    )
    args = ap.parse_args(argv)

    cfg = dict(
        d,
        url=args.url,
        participants=args.participants,
        drivers=args.drivers,
        block_size=args.block_size,
        tenants=args.tenants,
        wire=args.wire,
        dropout_rate=args.dropout,
        stragglers=args.stragglers,
        straggle_delay_ms=args.straggle_delay_ms,
        ramp_s=args.ramp_s,
        concurrency=args.concurrency,
        seed=args.seed,
        model_length=args.model_length,
        key_start=args.key_start,
        timeout=args.timeout,
        sum_wait_s=args.sum_wait_s,
        max_shed_retries=args.max_shed_retries,
        targets=args.targets,
        shared_round=args.shared_round,
    )
    stats = run(cfg)
    json.dump(stats, sys.stdout, indent=2)
    print()
    return 0 if stats["accepted"] > 0 else 1


if __name__ == "__main__":
    sys.exit(main())
