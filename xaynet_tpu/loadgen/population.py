"""The population engine: blocks of valid masked updates from ONE jitted call.

A production update participant computes ``masked = encode(model) +
derive_mask(seed)`` in the group. The engine runs exactly that — the PR-8
in-graph ChaCha mask derivation (``ops.masking_jax.derive_mask_ingraph``,
byte-identical to the host ``MaskSeed.derive_mask``) plus the production
fixed-point encode (``encode_models_batch``) — vmapped over a block of
participants, so one compiled program emits thousands of *valid* masked
updates per call instead of one ``Masker.mask`` per participant on the
host. The output rows are ordinary ``uint32`` limb tensors; the forge
(``loadgen.build``) runs them through the production serialization, so
the wire bytes are what a real SDK would have sent for the same
(seed, model, scalar) — byte-correct traffic, not fuzz.
"""

from __future__ import annotations

from fractions import Fraction

import jax
import jax.numpy as jnp
import numpy as np

from ..core.mask.config import MaskConfigPair
from ..ops import limbs as host_limbs, limbs_jax
from ..ops.masking_jax import (
    derive_chunk_budgets,
    derive_mask_ingraph,
    encode_models_batch,
    seed_words,
)
from ..telemetry import profiling


class PopulationEngine:
    """One compiled masked-update generator for a fixed (config, length).

    ``emit(seeds, weights, scalar)`` returns the whole population's masked
    vect/unit limbs; internally the population is processed in
    ``block_size`` lanes per program call (device memory ~ block_size x
    keystream chunk, the same provisioning rule as the sim), and every
    call after the first reuses the compiled program.
    """

    def __init__(self, config: MaskConfigPair, model_length: int, block_size: int = 512):
        if model_length < 1:
            raise ValueError("model_length must be >= 1")
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.config = config
        self.model_length = model_length
        self.block_size = block_size
        self._ol_v = np.asarray(host_limbs.order_limbs_for(config.vect.order), np.uint32)
        self._ol_u = np.asarray(host_limbs.order_limbs_for(config.unit.order), np.uint32)
        unit_chunk, vect_chunk = derive_chunk_budgets(model_length, config, block_size)
        n = model_length

        def _one(kw):
            return derive_mask_ingraph(kw, n, config, unit_chunk, vect_chunk)

        ol_v, ol_u = self._ol_v, self._ol_u

        def _block(kw, enc, unit_enc):
            """One participant block: derive + mask. ``kw`` uint32[B, 8]
            seed words, ``enc`` uint32[B, n, L] encoded models,
            ``unit_enc`` uint32[L1] (shared — homogeneous scalar)."""
            units, vects = jax.vmap(_one)(kw)
            masked = limbs_jax.mod_add(enc, vects, ol_v)
            unit_b = jnp.broadcast_to(unit_enc, units.shape)
            masked_units = limbs_jax.mod_add(unit_b, units, ol_u)
            return masked, masked_units

        self._program = jax.jit(_block)
        self.program_calls = 0  # one per BLOCK, never per participant

    def emit(
        self,
        seeds: list[bytes] | np.ndarray,
        weights: np.ndarray,
        scalar: Fraction = Fraction(1),
    ) -> tuple[np.ndarray, np.ndarray]:
        """Masked updates for the whole population.

        ``seeds`` are 32-byte mask seeds (or ``uint32[P, 8]`` key words),
        ``weights`` the ``[P, model_length]`` local models; every
        participant shares ``scalar`` (the homogeneous-population shape a
        soak uses: ``1/P``). Returns ``(masked_vects uint32[P, n, L],
        masked_units uint32[P, L1])`` — the exact limbs ``Masker.mask``
        would produce per participant.
        """
        if isinstance(seeds, np.ndarray):
            kw = np.asarray(seeds, dtype=np.uint32)
        else:
            kw = seed_words(list(seeds))
        if kw.ndim != 2 or kw.shape[1] != 8:
            raise ValueError("seeds must be 32-byte strings or uint32[P, 8] key words")
        p = kw.shape[0]
        if p < 1:
            raise ValueError("need at least one participant")
        weights = np.asarray(weights)
        if weights.shape != (p, self.model_length):
            raise ValueError(
                f"weights must be [{p}, {self.model_length}], got {weights.shape}"
            )
        unit_enc, enc = encode_models_batch(weights, scalar, self.config)
        out_v = np.empty_like(enc)
        out_u = np.empty((p, unit_enc.shape[-1]), dtype=np.uint32)
        block = self.block_size
        for start in range(0, p, block):
            kw_b = kw[start : start + block]
            enc_b = enc[start : start + block]
            pad = block - kw_b.shape[0]
            if pad:
                # the compiled program has one static block shape; the tail
                # block pads with zero lanes and slices them off below
                kw_b = np.concatenate([kw_b, np.zeros((pad, 8), np.uint32)])
                enc_b = np.concatenate(
                    [enc_b, np.zeros((pad, *enc_b.shape[1:]), np.uint32)]
                )
            masked, masked_units = profiling.timed_kernel(
                "loadgen_emit",
                kw_b.shape[0] * self.model_length,
                lambda kw_b=kw_b, enc_b=enc_b: self._program(
                    jnp.asarray(kw_b), jnp.asarray(enc_b), jnp.asarray(unit_enc)
                ),
            )
            self.program_calls += 1
            stop = min(start + block, p)
            out_v[start:stop] = np.asarray(masked)[: stop - start]
            out_u[start:stop] = np.asarray(masked_units)[: stop - start]
        return out_v, out_u
