"""Replay schedules: who sends when, who never does, who is late.

A soak is only as honest as its arrival process. The schedule turns a
forged population into a deterministic EVENT LIST — ``(send_offset_s,
participant_index)`` — with three chaos knobs layered on the same
``plan_churn`` assignment the in-process flood uses (``sdk.simulation``),
so a loadgen run and its byte-identity control agree on the exact
survivor set:

- **ramp**: arrivals spread uniformly over ``ramp_s`` (with deterministic
  per-participant jitter) instead of a thundering herd at t=0;
- **dropout**: that fraction of participants trained and vanished — their
  uploads never happen (the coordinator's quorum logic is what's under
  test);
- **straggle**: that many of the survivors send ``straggle_delay_s`` after
  their slot — late-but-valid arrivals that must still be accepted while
  the update window is open.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..sdk.simulation import plan_churn


@dataclass(frozen=True)
class ChurnSpec:
    """Chaos knobs for one replay; all deterministic per ``seed``."""

    dropout_rate: float = 0.0
    stragglers: int = 0
    straggle_delay_s: float = 0.0
    seed: int = 1


class ReplaySchedule:
    """Deterministic arrival plan for ``n`` forged participants."""

    def __init__(self, n: int, churn: ChurnSpec = ChurnSpec(), ramp_s: float = 0.0):
        if n < 1:
            raise ValueError("need at least one participant")
        if ramp_s < 0:
            raise ValueError("ramp must be >= 0")
        self.n = n
        self.churn = churn
        self.ramp_s = ramp_s
        self.dropped, self.straggled = plan_churn(
            n, churn.dropout_rate, churn.stragglers, churn.seed
        )
        rng = np.random.default_rng(churn.seed)
        # uniform arrival offsets over the ramp window; drawn for ALL n so
        # the offsets of surviving participants do not depend on who
        # dropped (control runs with dropout 0 replay the same timeline)
        offsets = rng.uniform(0.0, ramp_s, n) if ramp_s > 0 else np.zeros(n)
        self._events = sorted(
            (
                float(offsets[i])
                + (churn.straggle_delay_s if i in self.straggled else 0.0),
                i,
            )
            for i in range(n)
            if i not in self.dropped
        )

    def events(self) -> list[tuple[float, int]]:
        """``(send_offset_s, index)`` ascending — the replay's event feed."""
        return list(self._events)

    @property
    def senders(self) -> int:
        return len(self._events)
