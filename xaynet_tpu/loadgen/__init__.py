"""Sim-fed load generation: million-participant ingress traffic (§21).

The PR-8 in-graph simulation proved a whole PET round is a pure function
of (config, seeds, models). This package turns that program into a
TRAFFIC SOURCE: the population engine derives masks for thousands of
participants per jitted call (``population``), the forge wraps each row in
the byte-exact production message encoding — fixed-point encode, wire
v1/v2 element layout, seed-dict sealed boxes, Ed25519 signatures, sealed
envelope (``build``) — and the event-driven replay driver plays the
resulting uploads against a real coordinator's REST boundary under
churn/dropout/straggle schedules (``schedule``, ``driver``), optionally
spread over multiple tenants and/or an edge fan-in tier, and process-
sharded for scale (``runner``).

Everything is deterministic per seed: a loadgen round and a
participant-state-machine control round produce byte-identical global
models (asserted by ``tools/loadgen_soak.py``).
"""

from .build import UpdateForge, forge_population
from .driver import DriverStats, ReplayDriver
from .population import PopulationEngine
from .schedule import ChurnSpec, ReplaySchedule

__all__ = [
    "ChurnSpec",
    "DriverStats",
    "PopulationEngine",
    "ReplayDriver",
    "ReplaySchedule",
    "UpdateForge",
    "forge_population",
]
