"""The event-driven replay driver: forged uploads over real REST.

One driver process owns one population shard and replays it against the
coordinator's actual HTTP boundary — real sockets, real admission control,
real 429s — pacing sends by the schedule's event feed under a concurrency
gate. Multi-tenant spread assigns participants round-robin across the
``/t/<tenant>/`` routes; pointing ``targets`` at edge-runner URLs instead
of the coordinator exercises the two-tier fan-in (the edge API is
coordinator-shaped, ``edge.rest``). Shed uploads retry with the server's
Retry-After (bounded), which is what a real SDK's resilient client does.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field

from ..sdk.client import ClientError, ClientShedError, HttpClient


@dataclass
class DriverStats:
    """Outcome counts of one replay (per driver process)."""

    sent: int = 0
    accepted: int = 0  # 200 — taken at the REST boundary
    shed: int = 0  # 429 verdicts observed (retries may still land)
    abandoned: int = 0  # gave up after max_shed_retries
    errors: int = 0  # transport/protocol failures
    wall_s: float = 0.0
    by_target: dict = field(default_factory=dict)

    def merge(self, other: "DriverStats") -> "DriverStats":
        self.sent += other.sent
        self.accepted += other.accepted
        self.shed += other.shed
        self.abandoned += other.abandoned
        self.errors += other.errors
        self.wall_s = max(self.wall_s, other.wall_s)
        for k, v in other.by_target.items():
            self.by_target[k] = self.by_target.get(k, 0) + v
        return self

    def to_dict(self) -> dict:
        return {
            "sent": self.sent,
            "accepted": self.accepted,
            "shed": self.shed,
            "abandoned": self.abandoned,
            "errors": self.errors,
            "wall_s": round(self.wall_s, 3),
            "accepted_per_s": round(self.accepted / self.wall_s, 2)
            if self.wall_s > 0
            else 0.0,
            "by_target": dict(self.by_target),
        }


class ReplayDriver:
    """Replays one shard's sealed messages against one or more targets."""

    def __init__(
        self,
        targets: list[str] | str,
        *,
        concurrency: int = 64,
        timeout: float = 30.0,
        max_shed_retries: int = 3,
    ):
        if isinstance(targets, str):
            targets = [targets]
        if not targets:
            raise ValueError("need at least one target URL")
        # one pooled client per target: tenant routes ("host:port/t/a") and
        # edge endpoints are both just base URLs to the driver
        self._clients = [(url, HttpClient(url, timeout=timeout)) for url in targets]
        self.concurrency = max(1, concurrency)
        self.max_shed_retries = max(0, max_shed_retries)

    def close(self) -> None:
        for _, client in self._clients:
            client.close()

    async def replay(self, messages: list, schedule=None) -> DriverStats:
        """Send every message at its scheduled offset; returns the stats.

        ``schedule`` is a ``ReplaySchedule`` (or anything with
        ``events()``); ``None`` sends everything immediately (pure
        throughput shape). Participant ``i`` goes to target ``i % len``.
        """
        events = (
            schedule.events()
            if schedule is not None
            else [(0.0, i) for i in range(len(messages))]
        )
        stats = DriverStats()
        gate = asyncio.Semaphore(self.concurrency)
        start = time.monotonic()

        async def one(offset: float, index: int) -> None:
            delay = offset - (time.monotonic() - start)
            if delay > 0:
                # outside the gate: a paced sender must not hold a slot
                # while it waits for its own arrival time
                await asyncio.sleep(delay)
            url, client = self._clients[index % len(self._clients)]
            async with gate:
                stats.sent += 1
                for attempt in range(self.max_shed_retries + 1):
                    try:
                        await client.send_message(messages[index])
                        stats.accepted += 1
                        stats.by_target[url] = stats.by_target.get(url, 0) + 1
                        return
                    except ClientShedError as err:
                        stats.shed += 1
                        if attempt >= self.max_shed_retries:
                            stats.abandoned += 1
                            return
                        await asyncio.sleep(min(2.0, err.retry_after or 0.1))
                    except (ClientError, OSError, asyncio.TimeoutError):
                        stats.errors += 1
                        return

        await asyncio.gather(*(one(offset, i) for offset, i in events))
        stats.wall_s = time.monotonic() - start
        return stats
