"""The forge: engine rows -> sealed, signed, byte-exact update uploads.

Everything below the limb tensors is the PRODUCTION encode path — the same
``Update`` payload, wire v1/v2 element serialization, seed-dict sealed
boxes and Ed25519 signatures the SDK state machine emits — so a forged
upload is indistinguishable (byte-for-byte, given the same inputs) from a
real participant's. The only departures from the state machine are
organizational: masks were derived in blocks on the accelerator
(``loadgen.population``) instead of one host ``Masker.mask`` per
participant, and signing keys come from the deterministic
``keys_for_task`` search so every forged participant really holds the
update task for the round.
"""

from __future__ import annotations

from dataclasses import dataclass
from fractions import Fraction
from typing import Optional

import numpy as np

from ..core.common import RoundParameters
from ..core.crypto.encrypt import PublicEncryptKey
from ..core.crypto.sign import SigningKeyPair
from ..core.mask.object import MaskObject, MaskUnit, MaskVect
from ..core.mask.seed import MaskSeed
from ..core.message import Message, Update
from ..sdk.simulation import keys_for_task
from .population import PopulationEngine


class UpdateForge:
    """Seals engine rows into wire-ready update messages for one round."""

    def __init__(
        self,
        params: RoundParameters,
        sum_dict: dict,
        wire_planar: Optional[bool] = None,
    ):
        self.params = params
        self.coordinator_pk = PublicEncryptKey(params.pk)
        self._ephm = {pk: PublicEncryptKey(e) for pk, e in sum_dict.items()}
        # None follows the round's negotiated wire format, like the SDK
        self.wire_planar = params.wire_format >= 2 if wire_planar is None else wire_planar
        self._round_seed = params.seed.as_bytes()

    def seal(
        self,
        keys: SigningKeyPair,
        mask_seed: bytes,
        masked_vect: np.ndarray,
        masked_unit: np.ndarray,
    ) -> bytes:
        """One sealed upload: the participant's seed dict (its mask seed
        encrypted to every sum participant's ephemeral key), the masked
        model rows, both task signatures, the sealed envelope."""
        cfg = self.params.mask_config
        masked = MaskObject(
            MaskVect(cfg.vect, np.asarray(masked_vect, dtype=np.uint32)),
            MaskUnit(cfg.unit, np.asarray(masked_unit, dtype=np.uint32)),
        )
        seed = MaskSeed(bytes(mask_seed))
        payload = Update(
            sum_signature=keys.sign(self._round_seed + b"sum").as_bytes(),
            update_signature=keys.sign(self._round_seed + b"update").as_bytes(),
            masked_model=masked,
            local_seed_dict={pk: seed.encrypt(e) for pk, e in self._ephm.items()},
            wire_planar=self.wire_planar,
        )
        message = Message(
            participant_pk=keys.public, coordinator_pk=self.params.pk, payload=payload
        )
        return self.coordinator_pk.encrypt(message.to_bytes(keys.secret))


@dataclass
class ForgedPopulation:
    """One shard's worth of ready-to-replay uploads + the ground truth a
    byte-identity control needs to reproduce them."""

    messages: list  # sealed bytes, participant order
    weights: np.ndarray  # float32[P, n] — the local models
    scalar: Fraction
    mask_seeds: list  # 32-byte mask seeds, participant order
    key_starts: list  # keys_for_task search starts, participant order


def forge_population(
    params: RoundParameters,
    sum_dict: dict,
    n: int,
    *,
    model_length: Optional[int] = None,
    block_size: int = 512,
    key_start: int = 0,
    key_spacing: int = 1000,
    rng_seed: int = 7,
    scalar: Optional[Fraction] = None,
    wire_planar: Optional[bool] = None,
    engine: Optional[PopulationEngine] = None,
) -> ForgedPopulation:
    """Forge ``n`` valid update uploads for the current round.

    Deterministic per (round seed, key_start, rng_seed): a control run
    can rebuild the identical population. ``key_start``/``key_spacing``
    partition the signing-key search space exactly like ``sdk.flood`` so
    shards never collide on participant keys. The mask derivation runs in
    ``block_size`` jitted blocks; the per-participant crypto (signatures,
    seed boxes, sealed envelope) is the host-side cost a real fleet pays
    too — process-shard the forge (``runner``) to scale it.
    """
    length = model_length if model_length is not None else params.model_length
    rng = np.random.default_rng(rng_seed)
    weights = rng.uniform(-1, 1, (n, length)).astype(np.float32)
    mask_seeds = [rng.bytes(32) for _ in range(n)]
    scalar = scalar if scalar is not None else Fraction(1, max(1, n))

    eng = engine or PopulationEngine(params.mask_config, length, block_size=block_size)
    masked_vects, masked_units = eng.emit(mask_seeds, weights, scalar)

    forge = UpdateForge(params, sum_dict, wire_planar=wire_planar)
    round_seed = params.seed.as_bytes()
    messages = []
    key_starts = []
    for i in range(n):
        start = key_start + i * key_spacing
        keys = keys_for_task(
            round_seed, params.sum, params.update, "update", start=start
        )
        key_starts.append(start)
        messages.append(forge.seal(keys, mask_seeds[i], masked_vects[i], masked_units[i]))
    return ForgedPopulation(
        messages=messages,
        weights=weights,
        scalar=scalar,
        mask_seeds=mask_seeds,
        key_starts=key_starts,
    )
