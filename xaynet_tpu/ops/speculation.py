"""Speculative sum2 mask derivation (docs/DESIGN.md §22).

The sum2 mask aggregate depends only on the mask seeds — and a seed is
known long before the sum2 phase opens: the sum dictionary seals at the
sum→update transition, and each accepted update's mask seed arrives
during the update window. A :class:`SpeculativeMaskSession` exploits
that: offered seeds are derived and folded by a background worker while
the update-phase folds still run, so by the time sum2 needs the mask
aggregate most (often all) of the derive work has already been hidden
under the update wall and ``settle()`` reduces to reconciliation.

Byte-identity is unconditional because mask aggregation is a modular
sum over a finite group — order-independent, and exactly invertible:

- a **hit** (speculated seed that did arrive) is already folded;
- a **miss** (seed never offered, or the worker didn't reach it) derives
  on demand at settle time, exactly the serial path;
- a **discard** (mis-speculation: an offered seed whose participant
  dropped before sum2 — PR-5 churn) re-derives that seed's mask and
  subtracts it back out (``mod_sub`` is the group inverse), leaving the
  accumulator bit-identical to never having folded it.

The worker takes *idle* scheduler slots (``TenantScheduler.
try_acquire_idle``) so speculation never delays a real fold batch, and
every derive group is recorded as an ``overlap.spec_derive`` span
(home phase ``update``) so the timeline fold (telemetry/timeline.py)
measures the hidden seconds as negative slack.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..core.mask.config import MaskConfigPair
from ..telemetry import tracing as trace
from ..telemetry.timeline import record_overlap, record_spec_outcomes
from . import limbs as host_limbs

SPAN_SPEC_DERIVE = trace.declare_span("overlap.spec_derive")


class SpeculativeMaskSession:
    """Background derive+fold of sum2 masks for seeds offered early.

    One session per (tenant, round). ``offer()`` enqueues seeds the
    moment they are known; ``settle(actual_seeds)`` stops the worker,
    reconciles hits/misses/discards and returns ``(unit limbs, vector
    wire limbs)`` byte-identical to ``masking_jax.sum_masks(actual_seeds,
    ...)``. ``close()`` abandons the session (all work discarded).
    """

    def __init__(
        self,
        length: int,
        config: MaskConfigPair,
        kernel: str | None = None,
        mesh=None,
        group: int = 8,
        tenant: str = "default",
        scheduler=None,
        seed_batch: int = 8,
    ):
        self.length = length
        self.config = config
        self.kernel = kernel
        self.mesh = mesh
        self.group = max(1, group)
        self.tenant = tenant
        self.seed_batch = seed_batch
        self._sched = scheduler
        self._owner = scheduler.new_owner() if scheduler is not None else None
        self._ol_v = host_limbs.order_limbs_for(config.vect.order)
        self._ol_u = host_limbs.order_limbs_for(config.unit.order)
        n_limb_v = host_limbs.n_limbs_for_order(config.vect.order)
        n_limb_u = host_limbs.n_limbs_for_order(config.unit.order)
        self._vect_acc = np.zeros((length, n_limb_v), dtype=np.uint32)
        self._unit_acc = np.zeros(n_limb_u, dtype=np.uint32)
        self._lock = threading.Lock()
        self._queue: list[bytes] = []  # guarded-by: _lock
        self._queued: set[bytes] = set()  # guarded-by: _lock
        self._folded: set[bytes] = set()  # guarded-by: _lock
        self._closed = False  # guarded-by: _lock
        self._wake = threading.Event()
        self._derive_seconds = 0.0  # guarded-by: _lock
        self._worker: threading.Thread | None = None

    # -- producer side -----------------------------------------------------

    def offer(self, seeds) -> None:
        """Enqueue seeds for speculative derivation (idempotent per seed)."""
        start_worker = False
        with self._lock:
            if self._closed:
                return
            for seed in seeds:
                if seed not in self._queued:
                    self._queued.add(seed)
                    self._queue.append(seed)
            start_worker = self._worker is None and bool(self._queue)
            if start_worker:
                self._worker = threading.Thread(
                    target=self._run, name="xn-spec-derive", daemon=True
                )
        self._wake.set()
        if start_worker:
            self._worker.start()

    def speculated(self) -> int:
        """Seeds folded into the speculative accumulator so far."""
        with self._lock:
            return len(self._folded)

    # -- worker ------------------------------------------------------------

    def _take_group(self) -> list[bytes] | None:
        with self._lock:
            if self._closed:
                return None
            if not self._queue:
                return []
            group, self._queue = self._queue[: self.group], self._queue[self.group :]
            return group

    def _run(self) -> None:
        while True:
            group = self._take_group()
            if group is None:
                return
            if not group:
                # idle: wait for more offers (settle/close wakes us too)
                self._wake.clear()
                self._wake.wait(timeout=0.05)
                with self._lock:
                    if self._closed and not self._queue:
                        return
                continue
            granted = True
            if self._sched is not None:
                # an IDLE slot only: never delay a real fold batch. Denied
                # slots requeue the group — the seeds become misses at
                # settle if the window stays busy, which is the serial
                # path, not an error.
                granted = self._sched.try_acquire_idle(self.tenant, self._owner)
            if not granted:
                with self._lock:
                    closing = self._closed
                    if not closing:
                        self._queue = group + self._queue
                if closing:
                    return
                self._wake.clear()
                self._wake.wait(timeout=0.02)
                continue
            try:
                self._derive_group(group)
            except BaseException:
                # fail-soft: un-derived seeds fall back to the on-demand
                # path at settle; speculation must never fail a round
                with self._lock:
                    self._queued.difference_update(group)
            finally:
                if self._sched is not None:
                    self._sched.release(self._owner)

    def _derive_group(self, group: list[bytes]) -> None:
        from . import masking_jax

        t0 = time.monotonic()
        unit, vect = masking_jax.sum_masks(
            group,
            self.length,
            self.config,
            seed_batch=self.seed_batch,
            kernel=self.kernel,
            mesh=self.mesh,
        )
        vect = np.asarray(vect)
        dt = time.monotonic() - t0
        with self._lock:
            if self._closed:
                return
            self._vect_acc = host_limbs.mod_add(self._vect_acc, vect, self._ol_v)
            self._unit_acc = host_limbs.mod_add(
                self._unit_acc[None, :], np.asarray(unit)[None, :], self._ol_u
            )[0]
            self._folded.update(group)
            self._derive_seconds += dt
        trace.get_tracer().record_span(
            SPAN_SPEC_DERIVE,
            start=t0,
            duration=dt,
            phase="update",
            tenant=self.tenant,
            seeds=len(group),
        )

    # -- consumer side -----------------------------------------------------

    def _stop_worker(self) -> None:
        with self._lock:
            self._closed = True
        self._wake.set()
        worker = self._worker
        if worker is not None:
            worker.join()

    def close(self) -> None:
        """Abandon the session; all speculative work is discarded."""
        self._stop_worker()
        if self._sched is not None:
            self._sched.release_owner(self._owner)

    def settle(self, seeds: list[bytes]) -> tuple[np.ndarray, np.ndarray]:
        """Reconcile against the ACTUAL sum2 seed set and return the mask
        aggregate ``(unit limbs, vector wire limbs)`` — byte-identical to
        the non-speculative ``sum_masks(seeds, ...)``."""
        from . import masking_jax

        if not seeds:
            raise ValueError("no seeds to aggregate")
        self._stop_worker()
        try:
            wanted = set(seeds)
            if len(wanted) != len(seeds):
                # duplicate seeds (never produced by the protocol's seed
                # dict, but sum_masks accepts them): speculation folded
                # each seed once, so fall back to the serial path outright
                record_spec_outcomes(misses=len(seeds))
                return masking_jax.sum_masks(
                    seeds,
                    self.length,
                    self.config,
                    seed_batch=self.seed_batch,
                    kernel=self.kernel,
                    mesh=self.mesh,
                )
            with self._lock:
                folded = set(self._folded)
                vect_acc, unit_acc = self._vect_acc, self._unit_acc
                spec_seconds = self._derive_seconds
            hits = wanted & folded
            discards = sorted(folded - wanted)
            misses = [s for s in seeds if s not in folded]  # keep offer order
            for group, sub in ((discards, True), (misses, False)):
                if not group:
                    continue
                unit, vect = masking_jax.sum_masks(
                    group,
                    self.length,
                    self.config,
                    seed_batch=self.seed_batch,
                    kernel=self.kernel,
                    mesh=self.mesh,
                )
                op = host_limbs.mod_sub if sub else host_limbs.mod_add
                vect_acc = op(vect_acc, np.asarray(vect), self._ol_v)
                unit_acc = op(
                    unit_acc[None, :], np.asarray(unit)[None, :], self._ol_u
                )[0]
            record_spec_outcomes(
                hits=len(hits), misses=len(misses), discards=len(discards)
            )
            if spec_seconds > 0:
                record_overlap(
                    "spec_derive",
                    spec_seconds,
                    tenant=self.tenant,
                    hits=len(hits),
                    misses=len(misses),
                    discards=len(discards),
                )
            return unit_acc, vect_acc
        finally:
            if self._sched is not None:
                self._sched.release_owner(self._owner)
