"""Finite-group limb arithmetic as JAX/XLA device kernels.

Device counterpart of ``xaynet_tpu.ops.limbs`` (the numpy oracle): masked
models are ``uint32[n, L]`` limb tensors; modular addition is a carry chain
(statically unrolled over the small limb count) plus a conditional subtract
of the group order — flat, branch-free elementwise code that XLA fuses into
a single memory-bound kernel. The batch reducer pads to a power of two and
tree-halves, so aggregating K updates costs ``ceil(log2 K)`` fused
elementwise passes over HBM.

These kernels implement the coordinator hot loop the reference runs as
sequential big-int loops (reference: rust/xaynet-core/src/mask/masking.rs:292-316).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

_U32 = jnp.uint32


def _as_order(order_limbs) -> np.ndarray:
    # trace-time constant: the tiny host-side order tuple, never a traced
    # value — not a device sync even inside a jitted caller
    return np.asarray(order_limbs, dtype=np.uint32)  # lint: sync-ok


def add_limbs(a: jax.Array, b: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Limbwise ``a + b`` with carry propagation; returns (sum, carry_out)."""
    n_limb = a.shape[-1]
    outs = []
    carry = jnp.zeros(a.shape[:-1], dtype=_U32)
    for j in range(n_limb):
        s1 = a[..., j] + b[..., j]  # wraps mod 2^32
        c1 = (s1 < a[..., j]).astype(_U32)
        s2 = s1 + carry
        c2 = (s2 < s1).astype(_U32)
        outs.append(s2)
        carry = c1 | c2
    return jnp.stack(outs, axis=-1), carry


def sub_limbs(a: jax.Array, b: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Limbwise ``a - b`` with borrow propagation; returns (diff, borrow_out)."""
    n_limb = a.shape[-1]
    outs = []
    borrow = jnp.zeros(a.shape[:-1], dtype=_U32)
    for j in range(n_limb):
        d1 = a[..., j] - b[..., j]
        b1 = (a[..., j] < b[..., j]).astype(_U32)
        d2 = d1 - borrow
        b2 = (d1 < borrow).astype(_U32)
        outs.append(d2)
        borrow = b1 | b2
    return jnp.stack(outs, axis=-1), borrow


def lt_const(a: jax.Array, order_limbs: np.ndarray) -> jax.Array:
    """Lexicographic ``a < order`` over the trailing limb axis."""
    order_limbs = _as_order(order_limbs)
    lt = jnp.zeros(a.shape[:-1], dtype=bool)
    decided = jnp.zeros(a.shape[:-1], dtype=bool)
    for j in range(a.shape[-1] - 1, -1, -1):
        col = a[..., j]
        o = _U32(int(order_limbs[j]))
        lt = lt | (~decided & (col < o))
        decided = decided | (col != o)
    return lt


def mod_add(a: jax.Array, b: jax.Array, order_limbs: np.ndarray) -> jax.Array:
    """``(a + b) mod order`` assuming ``a, b < order`` (branch-free).

    Works for the ``order == 2^(32L)`` boundary case too: the order limbs are
    all zero there, so ``lt_const`` is always false and the subtract of zero
    is the identity — reduction degenerates to the natural wraparound.
    """
    order_limbs = _as_order(order_limbs)
    s, carry = add_limbs(a, b)
    ge = (carry != 0) | ~lt_const(s, order_limbs)
    o = jnp.asarray(order_limbs, dtype=_U32)
    d, _ = sub_limbs(s, jnp.broadcast_to(o, s.shape))
    return jnp.where(ge[..., None], d, s)


def mod_sub(a: jax.Array, b: jax.Array, order_limbs: np.ndarray) -> jax.Array:
    """``(a - b) mod order`` assuming ``a, b < order``."""
    order_limbs = _as_order(order_limbs)
    d, borrow = sub_limbs(a, b)
    o = jnp.asarray(order_limbs, dtype=_U32)
    d2, _ = add_limbs(d, jnp.broadcast_to(o, d.shape))
    return jnp.where((borrow != 0)[..., None], d2, d)


def batch_mod_sum(stack: jax.Array, order_limbs: np.ndarray) -> jax.Array:
    """Modular sum over axis 0 of ``uint32[K, n, L]`` via pow2 tree reduce.

    Zero rows are valid group elements, so padding K to a power of two with
    zeros keeps every level exact; shapes stay static for jit.
    """
    k = stack.shape[0]
    if k == 0:
        raise ValueError("empty batch")
    k2 = 1 << (k - 1).bit_length()
    if k2 != k:
        pad = jnp.zeros((k2 - k, *stack.shape[1:]), dtype=stack.dtype)
        stack = jnp.concatenate([stack, pad], axis=0)
    while stack.shape[0] > 1:
        half = stack.shape[0] // 2
        stack = mod_add(stack[:half], stack[half:], order_limbs)
    return stack[0]


@partial(jax.jit, static_argnames=("order_tuple",), donate_argnums=(0,))
def _aggregate_batch_kernel(acc: jax.Array, stack: jax.Array, order_tuple: tuple[int, ...]) -> jax.Array:
    order_limbs = np.asarray(order_tuple, dtype=np.uint32)
    batch = batch_mod_sum(stack, order_limbs)
    return mod_add(acc, batch, order_limbs)


def aggregate_batch(acc: jax.Array, stack: jax.Array, order_limbs: np.ndarray) -> jax.Array:
    """Fold ``uint32[K, n, L]`` updates into the running accumulator (jitted)."""
    return _aggregate_batch_kernel(acc, stack, tuple(int(x) for x in _as_order(order_limbs)))


def wire_bytes_to_planar(data: jax.Array, count: int, bpn: int) -> jax.Array:
    """Wire element block ``uint8[..., count*bpn]`` -> planar ``uint32[..., L, count]``.

    The wire layout is ``count`` fixed-width little-endian integers of
    ``bpn`` bytes each (serialization.py / reference vect.rs:24-80). Pure
    byte shuffling — reshape + shifts — so the coordinator can ship RAW
    wire bytes to the device (``bpn/(4L)`` of the limb-tensor size, e.g.
    6/8 for the f32/B0/M3 configs, 7/8 for M6) and never pay a host-side
    parse. Designed to run inside a jitted caller.
    """
    from . import limbs as host_limbs

    out_limbs = host_limbs.n_limbs_for_bytes(bpn)
    b = data.reshape(*data.shape[:-1], count, bpn).astype(_U32)
    limbs = []
    for j in range(out_limbs):
        w = b[..., 4 * j]
        for i in range(1, min(4, bpn - 4 * j)):
            w = w | (b[..., 4 * j + i] << _U32(8 * i))
        limbs.append(w)
    return jnp.stack(limbs, axis=-2)


def packed_planar_to_limbs(packed: jax.Array, n_limbs: int) -> jax.Array:
    """Packed byte-planar ``uint8[..., bpn, n]`` -> planar ``uint32[..., L, n]``.

    Device twin of ``limbs.unpack_planar`` (the packed staging codec): limb
    j assembles from byte-planes ``4j .. min(4j+4, bpn)`` with the same
    shift-or chain as :func:`wire_bytes_to_planar`, but every read is a
    CONTIGUOUS plane (the byte-planar layout keeps the model axis minor).
    Pure byte shuffling — designed to run inside a jitted caller so the
    packed bytes, not the 4L-byte planar, are what crosses host->device.
    """
    from . import limbs as host_limbs

    bpn = packed.shape[-2]
    if n_limbs < host_limbs.n_limbs_for_bytes(bpn):
        raise ValueError("limb width too small for the packed width")
    b = packed.astype(_U32)
    limbs = []
    for j in range(n_limbs):
        if 4 * j >= bpn:
            limbs.append(jnp.zeros(packed.shape[:-2] + packed.shape[-1:], dtype=_U32))
            continue
        w = b[..., 4 * j, :]
        for i in range(1, min(4, bpn - 4 * j)):
            w = w | (b[..., 4 * j + i, :] << _U32(8 * i))
        limbs.append(w)
    return jnp.stack(limbs, axis=-2)


# standalone jitted entry for callers that unpack OUTSIDE their own jit
# (e.g. ahead of the Pallas shard fold, whose kernel wants planar input):
# one shared trace cache, keyed on shape + the static limb count
packed_planar_to_limbs_jit = jax.jit(packed_planar_to_limbs, static_argnums=(1,))


def planar_all_lt_const(planar: jax.Array, order: int) -> jax.Array:
    """``all(element < order)`` per leading index over planar ``[..., L, n]``.

    The device version of the wire parser's element-validity check, one
    bool per leading index (per update for a ``[K, L, n]`` batch; a scalar
    for a single ``[L, n]`` tensor). Owns the ``order == 2^(32 L)``
    boundary case (every bit pattern valid) exactly like the host
    ``limbs.elements_lt_order`` — callers never special-case it.
    """
    from . import limbs as host_limbs

    n_limb = planar.shape[-2]
    if order == 1 << (32 * n_limb):
        return jnp.ones(planar.shape[:-2], dtype=bool)
    order_limbs = host_limbs.int_to_limbs(order, n_limb)
    return jnp.all(lt_const(jnp.moveaxis(planar, -2, -1), order_limbs), axis=-1)
