"""Compute kernels for the PET hot loops.

Host (numpy, the conformance oracle) and device (JAX/XLA + Pallas)
implementations of what the reference runs as sequential big-int loops
(reference: rust/xaynet-core/src/mask/masking.rs, crypto/prng.rs):

- ``limbs`` / ``limbs_jax`` — modular limb arithmetic
- ``fold_jax`` / ``fold_pallas`` — single-pass lazy-carry batch aggregation
- ``chacha_jax`` — device ChaCha20 mask expansion
- ``masking_jax`` — protocol-level device ops (derive/sum masks, unmask)
- ``dd`` — vectorized double-double arithmetic for fixed-point codecs
"""
