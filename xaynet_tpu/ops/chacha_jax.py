"""ChaCha20 keystream and mask expansion as JAX device kernels.

Device counterpart of ``xaynet_tpu.core.crypto.chacha`` /
``prng.StreamSampler``. ChaCha20 is pure 32-bit add/xor/rotate — ideal VPU
work — and mask derivation (seed -> ``len`` uniform group elements,
reference: rust/xaynet-core/src/mask/seed.rs:61-78) becomes:

1. generate a fixed-size chunk of keystream blocks (all blocks in parallel:
   lanes = blocks);
2. chop into fixed-width little-endian candidates;
3. rejection-filter (candidate < order) with a scatter compaction instead of
   a data-dependent loop, keeping shapes static under jit;
4. repeat from the next keystream byte offset until ``count`` accepted.

Each chunk consumes exactly ``chunk_candidates * bpn`` keystream bytes
regardless of how many candidates are accepted, so the byte-offset handoff
between chunks is deterministic; only the number accepted so far (one scalar
per chunk) syncs to the host. Memory is bounded by the chunk size, never by
``count`` — a 25M-element mask derives in ~4M-candidate steps instead of one
31M-candidate allocation. The per-chunk size is provisioned so that small
draws complete in a single chunk with probability > 1 - 2^-60.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

_U32 = jnp.uint32
_CONSTANTS = (0x61707865, 0x3320646E, 0x79622D32, 0x6B206574)


def _rotl(x, n):
    return (x << _U32(n)) | (x >> _U32(32 - n))


def _quarter(s, a, b, c, d):
    s[a] = s[a] + s[b]
    s[d] = _rotl(s[d] ^ s[a], 16)
    s[c] = s[c] + s[d]
    s[b] = _rotl(s[b] ^ s[c], 12)
    s[a] = s[a] + s[b]
    s[d] = _rotl(s[d] ^ s[a], 8)
    s[c] = s[c] + s[d]
    s[b] = _rotl(s[b] ^ s[c], 7)
    return s


@partial(jax.jit, static_argnames=("nblocks",))
def keystream_words(key_words: jax.Array, block_start, nblocks: int) -> jax.Array:
    """ChaCha20 keystream as ``uint32[nblocks, 16]`` little-endian words.

    ``block_start`` may be a traced uint32 scalar (chunked derivation passes
    a fresh offset every chunk without recompiling).
    """
    # 64-bit block counter in words 12-13; counters stay below 2^32 here
    # (2^32 blocks = 256 GiB of keystream per seed), so word 13 is constant.
    counters = jnp.asarray(block_start, dtype=_U32) + jnp.arange(nblocks, dtype=_U32)
    state = [jnp.broadcast_to(_U32(c), (nblocks,)) for c in _CONSTANTS]
    state += [jnp.broadcast_to(key_words[i], (nblocks,)) for i in range(8)]
    state.append(counters)
    state += [jnp.zeros(nblocks, dtype=_U32)] * 3

    w = list(state)
    for _ in range(10):
        w = _quarter(w, 0, 4, 8, 12)
        w = _quarter(w, 1, 5, 9, 13)
        w = _quarter(w, 2, 6, 10, 14)
        w = _quarter(w, 3, 7, 11, 15)
        w = _quarter(w, 0, 5, 10, 15)
        w = _quarter(w, 1, 6, 11, 12)
        w = _quarter(w, 2, 7, 8, 13)
        w = _quarter(w, 3, 4, 9, 14)
    out = [wi + si for wi, si in zip(w, state)]
    return jnp.stack(out, axis=-1)  # [nblocks, 16]


def _words_to_bytes(words: jax.Array) -> jax.Array:
    """uint32[..., W] little-endian words -> uint8[..., W*4]."""
    b0 = (words & _U32(0xFF)).astype(jnp.uint8)
    b1 = ((words >> _U32(8)) & _U32(0xFF)).astype(jnp.uint8)
    b2 = ((words >> _U32(16)) & _U32(0xFF)).astype(jnp.uint8)
    b3 = ((words >> _U32(24)) & _U32(0xFF)).astype(jnp.uint8)
    return jnp.stack([b0, b1, b2, b3], axis=-1).reshape(*words.shape[:-1], -1)


def provision_candidates(count: int, order: int) -> int:
    """Candidates to draw so that P(accepted < count) < ~2^-60."""
    bpn = (order.bit_length() + 7) // 8
    # int/int true division is correctly rounded at any magnitude
    p = order / (1 << (8 * bpn))
    p = max(min(p, 1.0), 1e-9)
    # Chernoff: need C with C*p - 7*sqrt(C*p*(1-p)) >= count
    c = count / p
    c += 7.0 * math.sqrt(max(c * (1 - p), 1.0)) / p + 64
    return int(c)


# Per-chunk keystream budget: bounds device memory independently of `count`.
# 32 MiB of candidate bytes ≈ 5.6M candidates at the common bpn=6.
_CHUNK_BYTES_CAP = 32 * 1024 * 1024


def _derive_chunk_impl(
    out: jax.Array,
    base: jax.Array,
    key_words: jax.Array,
    block_start: jax.Array,
    intra: jax.Array,
    n_cand: int,
    bpn: int,
    out_limbs: int,
    order_tuple: tuple[int, ...],
) -> tuple[jax.Array, jax.Array]:
    """One fixed-size chunk of keystream -> accepted limbs scattered into ``out``.

    ``base`` (elements accepted by previous chunks), ``block_start`` and
    ``intra`` (keystream cursor) are traced scalars, so every chunk reuses one
    compiled kernel. Accepted candidate ``i`` lands at ``out[base + rank(i)]``;
    rejected candidates and overflow past ``len(out)`` are scatter-dropped.
    """
    nbytes = n_cand * bpn
    nblocks = nbytes // 64 + 2  # +2 covers any intra-block offset in [0, 64)
    words = keystream_words(key_words, block_start, nblocks)
    stream = _words_to_bytes(words).reshape(-1)
    stream = jax.lax.dynamic_slice(stream, (intra,), (nbytes,))

    cand_limbs = max(1, (bpn + 3) // 4)
    padded = jnp.zeros((n_cand, cand_limbs * 4), dtype=jnp.uint8)
    padded = padded.at[:, :bpn].set(stream.reshape(n_cand, bpn))
    # little-endian bytes -> uint32 limbs
    quads = padded.reshape(n_cand, cand_limbs, 4).astype(_U32)
    cand = (
        quads[..., 0]
        | (quads[..., 1] << _U32(8))
        | (quads[..., 2] << _U32(16))
        | (quads[..., 3] << _U32(24))
    )

    # acceptance: lexicographic candidate < order
    lt = jnp.zeros(n_cand, dtype=bool)
    decided = jnp.zeros(n_cand, dtype=bool)
    for j in range(cand_limbs - 1, -1, -1):
        col = cand[:, j]
        o = _U32(int(order_tuple[j]))
        lt = lt | (~decided & (col < o))
        decided = decided | (col != o)

    count = out.shape[0]
    rank = jnp.cumsum(lt.astype(jnp.int32)) - 1
    slot = jnp.where(lt, base + rank, count)  # rejected -> dropped
    out = out.at[slot].set(cand[:, :out_limbs], mode="drop")
    n_accepted = rank[-1] + 1
    return out, n_accepted


_derive_chunk = partial(
    jax.jit,
    static_argnames=("n_cand", "bpn", "out_limbs", "order_tuple"),
    donate_argnums=(0,),
)(_derive_chunk_impl)


@partial(
    jax.jit,
    static_argnames=("n_cand", "bpn", "out_limbs", "order_tuple"),
    donate_argnums=(0,),
)
def _derive_chunk_batch(
    out: jax.Array,
    base: jax.Array,
    key_words: jax.Array,
    block_start: jax.Array,
    intra: jax.Array,
    n_cand: int,
    bpn: int,
    out_limbs: int,
    order_tuple: tuple[int, ...],
) -> tuple[jax.Array, jax.Array]:
    """``_derive_chunk_impl`` vmapped over a leading seed axis.

    One launch derives a chunk for every seed in the batch (the sum2
    participant loop is ``#updates`` independent seeds — VPU work that would
    otherwise dispatch per seed); per-seed cursors/bases ride in as vectors.
    """

    def one(o, b, kw, bs, it):
        return _derive_chunk_impl(o, b, kw, bs, it, n_cand, bpn, out_limbs, order_tuple)

    return jax.vmap(one)(out, base, key_words, block_start, intra)


def _derive_params(
    count: int, order: int, chunk_candidates: int | None, n_seeds: int = 1
) -> tuple[int, int, tuple[int, ...], int]:
    """Shared derivation setup: (bpn, out_limbs, order candidate limbs,
    per-seed chunk size). The chunk cap divides by ``n_seeds`` so a batched
    launch stays inside the same ``_CHUNK_BYTES_CAP`` device-memory budget
    the single-seed path was designed around."""
    from . import limbs as host_limbs

    bpn = (order.bit_length() + 7) // 8
    cand_limbs = max(1, (bpn + 3) // 4)
    out_limbs = host_limbs.n_limbs_for_order(order)
    order_cl = tuple(int(x) for x in host_limbs.int_to_limbs(order, cand_limbs))
    if chunk_candidates is None:
        chunk_candidates = provision_candidates(count, order)
    chunk_candidates = max(64, min(chunk_candidates, _CHUNK_BYTES_CAP // bpn // max(1, n_seeds)))
    return bpn, out_limbs, order_cl, chunk_candidates


def derive_uniform_limbs(
    seed: bytes,
    count: int,
    order: int,
    byte_offset: int = 0,
    chunk_candidates: int | None = None,
) -> jax.Array:
    """Device mask expansion: ``count`` uniform elements below ``order``.

    Bit-identical to the host ``StreamSampler`` (same keystream, same
    rejection rule, same acceptance order), derived in fixed-size keystream
    chunks so device memory is bounded by the chunk size, not by ``count``.
    Small draws are provisioned to finish in one chunk w.p. > 1 - 2^-60; the
    loop simply continues on the next chunk otherwise, so the result is
    unconditionally exact with no host fallback.
    """
    bpn, out_limbs, order_cl, chunk_candidates = _derive_params(count, order, chunk_candidates)

    key_words = jnp.asarray(np.frombuffer(seed, dtype="<u4"))
    out = jnp.zeros((count, out_limbs), dtype=_U32)
    base, offset = 0, byte_offset
    while base < count:
        block_start, intra = divmod(offset, 64)
        if block_start + chunk_candidates * bpn // 64 + 2 > 0xFFFFFFFF:
            raise ValueError("keystream longer than 2^32 blocks is not supported on device")
        out, n_acc = _derive_chunk(
            out,
            jnp.asarray(base, dtype=jnp.int32),
            key_words,
            jnp.asarray(block_start, dtype=_U32),
            jnp.asarray(intra, dtype=jnp.int32),
            chunk_candidates,
            bpn,
            out_limbs,
            order_cl,
        )
        base += int(n_acc)
        offset += chunk_candidates * bpn
    return out


def derive_uniform_limbs_batch(
    seeds: list[bytes],
    count: int,
    order: int,
    byte_offsets: list[int] | None = None,
    chunk_candidates: int | None = None,
) -> jax.Array:
    """``derive_uniform_limbs`` for many seeds in one kernel series.

    Returns ``uint32[len(seeds), count, out_limbs]``; each row is
    bit-identical to the single-seed derivation with that seed/offset (same
    keystream, same rejection rule, same acceptance order). Chunk rounds run
    until the slowest seed completes; seeds already done keep scattering
    into dropped slots (their ``base`` is clamped at ``count``), which costs
    keystream FLOPs but never correctness — with the 2^-60 provisioning all
    seeds complete in the first round except vanishingly rarely.
    """
    if not seeds:
        raise ValueError("no seeds")
    bpn, out_limbs, order_cl, chunk_candidates = _derive_params(
        count, order, chunk_candidates, n_seeds=len(seeds)
    )

    b = len(seeds)
    key_words = jnp.asarray(np.stack([np.frombuffer(s, dtype="<u4") for s in seeds]))
    out = jnp.zeros((b, count, out_limbs), dtype=_U32)
    base = np.zeros(b, dtype=np.int64)
    offsets = np.asarray(byte_offsets if byte_offsets is not None else [0] * b, dtype=np.int64)
    while (base < count).any():
        block_start, intra = np.divmod(offsets, 64)
        if int(block_start.max()) + chunk_candidates * bpn // 64 + 2 > 0xFFFFFFFF:
            raise ValueError("keystream longer than 2^32 blocks is not supported on device")
        out, n_acc = _derive_chunk_batch(
            out,
            jnp.asarray(base, dtype=jnp.int32),
            key_words,
            jnp.asarray(block_start, dtype=_U32),
            jnp.asarray(intra, dtype=jnp.int32),
            chunk_candidates,
            bpn,
            out_limbs,
            order_cl,
        )
        base = np.minimum(base + np.asarray(n_acc, dtype=np.int64), count)
        offsets += chunk_candidates * bpn
    return out
