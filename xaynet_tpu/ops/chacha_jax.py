"""ChaCha20 keystream and mask expansion as JAX device kernels.

Device counterpart of ``xaynet_tpu.core.crypto.chacha`` /
``prng.StreamSampler``. ChaCha20 is pure 32-bit add/xor/rotate — ideal VPU
work — and mask derivation (seed -> ``len`` uniform group elements,
reference: rust/xaynet-core/src/mask/seed.rs:61-78) becomes:

1. generate a fixed-size chunk of keystream blocks (all blocks in parallel:
   lanes = blocks);
2. chop into fixed-width little-endian candidates;
3. rejection-filter (candidate < order) with a scatter compaction instead of
   a data-dependent loop, keeping shapes static under jit;
4. repeat from the next keystream byte offset until ``count`` accepted.

Each chunk consumes exactly ``chunk_candidates * bpn`` keystream bytes
regardless of how many candidates are accepted, so the byte-offset handoff
between chunks is deterministic; only the number accepted so far (one scalar
per chunk) syncs to the host. Memory is bounded by the chunk size, never by
``count`` — a 25M-element mask derives in ~4M-candidate steps instead of one
31M-candidate allocation. The per-chunk size is provisioned so that small
draws complete in a single chunk with probability > 1 - 2^-60.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

_U32 = jnp.uint32
_CONSTANTS = (0x61707865, 0x3320646E, 0x79622D32, 0x6B206574)


def _rotl(x, n):
    return (x << _U32(n)) | (x >> _U32(32 - n))


def _quarter(s, a, b, c, d):
    s[a] = s[a] + s[b]
    s[d] = _rotl(s[d] ^ s[a], 16)
    s[c] = s[c] + s[d]
    s[b] = _rotl(s[b] ^ s[c], 12)
    s[a] = s[a] + s[b]
    s[d] = _rotl(s[d] ^ s[a], 8)
    s[c] = s[c] + s[d]
    s[b] = _rotl(s[b] ^ s[c], 7)
    return s


@partial(jax.jit, static_argnames=("nblocks",))
def keystream_words(key_words: jax.Array, block_start, nblocks: int) -> jax.Array:
    """ChaCha20 keystream as ``uint32[nblocks, 16]`` little-endian words.

    ``block_start`` may be a traced uint32 scalar (chunked derivation passes
    a fresh offset every chunk without recompiling).
    """
    # 64-bit block counter in words 12-13; counters stay below 2^32 here
    # (2^32 blocks = 256 GiB of keystream per seed), so word 13 is constant.
    counters = jnp.asarray(block_start, dtype=_U32) + jnp.arange(nblocks, dtype=_U32)
    state = [jnp.broadcast_to(_U32(c), (nblocks,)) for c in _CONSTANTS]
    state += [jnp.broadcast_to(key_words[i], (nblocks,)) for i in range(8)]
    state.append(counters)
    state += [jnp.zeros(nblocks, dtype=_U32)] * 3

    w = list(state)
    for _ in range(10):
        w = _quarter(w, 0, 4, 8, 12)
        w = _quarter(w, 1, 5, 9, 13)
        w = _quarter(w, 2, 6, 10, 14)
        w = _quarter(w, 3, 7, 11, 15)
        w = _quarter(w, 0, 5, 10, 15)
        w = _quarter(w, 1, 6, 11, 12)
        w = _quarter(w, 2, 7, 8, 13)
        w = _quarter(w, 3, 4, 9, 14)
    out = [wi + si for wi, si in zip(w, state)]
    return jnp.stack(out, axis=-1)  # [nblocks, 16]


def _quarter_rows(a, b, c, d):
    """One quarter-round over whole state-matrix rows (``uint32[4, N]``)."""
    a = a + b
    d = _rotl(d ^ a, 16)
    c = c + d
    b = _rotl(b ^ c, 12)
    a = a + b
    d = _rotl(d ^ a, 8)
    c = c + d
    b = _rotl(b ^ c, 7)
    return a, b, c, d


def keystream_words_rolled(key_words: jax.Array, block_start, nblocks: int) -> jax.Array:
    """``keystream_words``, bit-identical, with the round loop ROLLED.

    The unrolled kernel above emits ~1k HLO ops (10 double rounds x 8
    quarters x a dozen ops), which costs ~25s of XLA CPU compile time
    *every time it is inlined into a new enclosing program*. That is fine
    for the standalone jitted host-chunk kernels (compiled once per
    process), but the in-graph derivation (``derive_uniform_limbs_ingraph``)
    inlines the keystream into every simulation program variant. This
    variant keeps the ChaCha state as the classic 4x4 word matrix (rows
    ``uint32[4, nblocks]``), runs the column+diagonal double round as ONE
    vectorized quarter over whole rows (diagonals via axis-0 rolls), and
    folds the 10 double rounds under ``lax.fori_loop`` — ~25x fewer ops to
    compile, same arithmetic per element, same output word order.
    """
    counters = jnp.asarray(block_start, dtype=_U32) + jnp.arange(nblocks, dtype=_U32)
    r0 = jnp.stack([jnp.broadcast_to(_U32(c), (nblocks,)) for c in _CONSTANTS])
    r1 = jnp.stack([jnp.broadcast_to(key_words[i], (nblocks,)) for i in range(4)])
    r2 = jnp.stack([jnp.broadcast_to(key_words[i], (nblocks,)) for i in range(4, 8)])
    zeros = jnp.zeros((nblocks,), dtype=_U32)
    r3 = jnp.stack([counters, zeros, zeros, zeros])
    init = (r0, r1, r2, r3)

    def double_round(_, s):
        a, b, c, d = s
        a, b, c, d = _quarter_rows(a, b, c, d)  # column round
        b = jnp.roll(b, -1, axis=0)
        c = jnp.roll(c, -2, axis=0)
        d = jnp.roll(d, -3, axis=0)
        a, b, c, d = _quarter_rows(a, b, c, d)  # diagonal round
        b = jnp.roll(b, 1, axis=0)
        c = jnp.roll(c, 2, axis=0)
        d = jnp.roll(d, 3, axis=0)
        return a, b, c, d

    a, b, c, d = jax.lax.fori_loop(0, 10, double_round, init)
    out = jnp.concatenate(
        [a + init[0], b + init[1], c + init[2], d + init[3]], axis=0
    )  # [16, nblocks], row-major word order
    return jnp.transpose(out)  # [nblocks, 16]


def _words_to_bytes(words: jax.Array) -> jax.Array:
    """uint32[..., W] little-endian words -> uint8[..., W*4]."""
    b0 = (words & _U32(0xFF)).astype(jnp.uint8)
    b1 = ((words >> _U32(8)) & _U32(0xFF)).astype(jnp.uint8)
    b2 = ((words >> _U32(16)) & _U32(0xFF)).astype(jnp.uint8)
    b3 = ((words >> _U32(24)) & _U32(0xFF)).astype(jnp.uint8)
    return jnp.stack([b0, b1, b2, b3], axis=-1).reshape(*words.shape[:-1], -1)


def provision_candidates(count: int, order: int) -> int:
    """Candidates to draw so that P(accepted < count) < ~2^-60."""
    from . import limbs as host_limbs

    bpn = host_limbs.draw_width_for(order)
    # int/int true division is correctly rounded at any magnitude
    p = order / (1 << (8 * bpn))
    p = max(min(p, 1.0), 1e-9)
    # Chernoff: need C with C*p - 7*sqrt(C*p*(1-p)) >= count
    c = count / p
    c += 7.0 * math.sqrt(max(c * (1 - p), 1.0)) / p + 64
    return int(c)


# Per-chunk keystream budget: bounds device memory independently of `count`.
# 32 MiB of candidate bytes ≈ 5.6M candidates at the common bpn=6.
_CHUNK_BYTES_CAP = 32 * 1024 * 1024


def _derive_chunk_impl(
    out: jax.Array,
    base: jax.Array,
    key_words: jax.Array,
    block_start: jax.Array,
    intra: jax.Array,
    n_cand: int,
    bpn: int,
    out_limbs: int,
    order_tuple: tuple[int, ...],
) -> tuple[jax.Array, jax.Array]:
    """One fixed-size chunk of keystream -> accepted limbs scattered into ``out``.

    ``base`` (elements accepted by previous chunks), ``block_start`` and
    ``intra`` (keystream cursor) are traced scalars, so every chunk reuses one
    compiled kernel. Accepted candidate ``i`` lands at ``out[base + rank(i)]``;
    rejected candidates and overflow past ``len(out)`` are scatter-dropped.
    """
    nbytes = n_cand * bpn
    nblocks = nbytes // 64 + 2  # +2 covers any intra-block offset in [0, 64)
    words = keystream_words(key_words, block_start, nblocks)
    stream = _words_to_bytes(words).reshape(-1)
    stream = jax.lax.dynamic_slice(stream, (intra,), (nbytes,))
    out, csum = _chop_reject_scatter(out, base, stream, n_cand, bpn, out_limbs, order_tuple)
    return out, csum[-1]


def _chop_reject_scatter(
    out: jax.Array,
    base: jax.Array,
    stream: jax.Array,
    n_cand: int,
    bpn: int,
    out_limbs: int,
    order_tuple: tuple[int, ...],
) -> tuple[jax.Array, jax.Array]:
    """Chop a keystream slice into ``n_cand`` fixed-width candidates, apply
    the rejection rule, scatter accepted limbs at ``out[base + rank]``.

    THE single source of truth for the acceptance criterion (little-endian
    chop + lexicographic ``candidate < order``, bit-identical to the host
    ``StreamSampler`` / the Rust reference) — both the host-chunked and the
    fully-traced derivation paths call it, so the rule cannot silently
    diverge between them. Rejected candidates and accepted ones past
    ``len(out)`` are scatter-dropped. Returns ``(out, csum)`` where
    ``csum[i]`` counts acceptances among attempts ``0..i`` (``csum[-1]`` =
    acceptances in this chunk).
    """
    from . import limbs as host_limbs

    cand_limbs = host_limbs.n_limbs_for_bytes(bpn)
    padded = jnp.zeros((n_cand, cand_limbs * 4), dtype=jnp.uint8)
    padded = padded.at[:, :bpn].set(stream.reshape(n_cand, bpn))
    # little-endian bytes -> uint32 limbs
    quads = padded.reshape(n_cand, cand_limbs, 4).astype(_U32)
    cand = (
        quads[..., 0]
        | (quads[..., 1] << _U32(8))
        | (quads[..., 2] << _U32(16))
        | (quads[..., 3] << _U32(24))
    )

    # acceptance: lexicographic candidate < order
    lt = jnp.zeros(n_cand, dtype=bool)
    decided = jnp.zeros(n_cand, dtype=bool)
    for j in range(cand_limbs - 1, -1, -1):
        col = cand[:, j]
        o = _U32(int(order_tuple[j]))
        lt = lt | (~decided & (col < o))
        decided = decided | (col != o)

    count = out.shape[0]
    csum = jnp.cumsum(lt.astype(jnp.int32))
    slot = jnp.where(lt, base + csum - 1, count)  # rejected -> dropped
    out = out.at[slot].set(cand[:, :out_limbs], mode="drop")
    return out, csum


_derive_chunk = partial(
    jax.jit,
    static_argnames=("n_cand", "bpn", "out_limbs", "order_tuple"),
    donate_argnums=(0,),
)(_derive_chunk_impl)


@partial(
    jax.jit,
    static_argnames=("n_cand", "bpn", "out_limbs", "order_tuple"),
    donate_argnums=(0,),
)
def _derive_chunk_batch(
    out: jax.Array,
    base: jax.Array,
    key_words: jax.Array,
    block_start: jax.Array,
    intra: jax.Array,
    n_cand: int,
    bpn: int,
    out_limbs: int,
    order_tuple: tuple[int, ...],
) -> tuple[jax.Array, jax.Array]:
    """``_derive_chunk_impl`` vmapped over a leading seed axis.

    One launch derives a chunk for every seed in the batch (the sum2
    participant loop is ``#updates`` independent seeds — VPU work that would
    otherwise dispatch per seed); per-seed cursors/bases ride in as vectors.
    """

    def one(o, b, kw, bs, it):
        return _derive_chunk_impl(o, b, kw, bs, it, n_cand, bpn, out_limbs, order_tuple)

    return jax.vmap(one)(out, base, key_words, block_start, intra)


def _derive_params(
    count: int, order: int, chunk_candidates: int | None, n_seeds: int = 1
) -> tuple[int, int, tuple[int, ...], int]:
    """Shared derivation setup: (bpn, out_limbs, order candidate limbs,
    per-seed chunk size). The chunk cap divides by ``n_seeds`` so a batched
    launch stays inside the same ``_CHUNK_BYTES_CAP`` device-memory budget
    the single-seed path was designed around."""
    from . import limbs as host_limbs

    bpn = host_limbs.draw_width_for(order)
    cand_limbs = host_limbs.n_limbs_for_bytes(bpn)
    out_limbs = host_limbs.n_limbs_for_order(order)
    # trace-time limb math on the STATIC order int (a Python argument of
    # the jitted derivation, never a traced value)
    order_cl = tuple(int(x) for x in host_limbs.int_to_limbs(order, cand_limbs))  # lint: sync-ok
    if chunk_candidates is None:
        chunk_candidates = provision_candidates(count, order)
    chunk_candidates = max(64, min(chunk_candidates, _CHUNK_BYTES_CAP // bpn // max(1, n_seeds)))
    return bpn, out_limbs, order_cl, chunk_candidates


def _chunk_step_traced(
    out: jax.Array,
    base: jax.Array,
    key_words: jax.Array,
    offset: jax.Array,
    n_cand: int,
    bpn: int,
    out_limbs: int,
    order_tuple: tuple[int, ...],
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One fixed-size chunk with a TRACED byte cursor: scatter accepted
    candidates into ``out`` and advance ``offset`` with exact
    ``StreamSampler`` semantics — the cursor stops at the byte after the
    attempt that produced the ``count``-th acceptance, so a later draw on
    the same stream (the unit -> vector handoff) resumes bit-identically.

    Unlike ``_derive_chunk_impl`` (whose host caller syncs the accepted
    count every chunk), this body is pure traced code: it composes under
    ``lax.while_loop`` and ``vmap``, which is what makes a whole federated
    round expressible as ONE jitted program (see ``xaynet_tpu.sim``).
    Already-finished lanes keep scattering into dropped slots and freeze
    their cursor, so running extra iterations under a batched while_loop is
    harmless.
    """
    count = out.shape[0]
    block_start = (offset // 64).astype(_U32)
    intra = offset % 64
    nbytes = n_cand * bpn
    nblocks = nbytes // 64 + 2  # +2 covers any intra-block offset in [0, 64)
    # rolled keystream (bit-identical): the unrolled kernel would cost
    # ~25s of XLA CPU compile per enclosing program (see its docstring)
    words = keystream_words_rolled(key_words, block_start, nblocks)
    stream = _words_to_bytes(words).reshape(-1)
    stream = jax.lax.dynamic_slice(stream, (intra,), (nbytes,))
    out, csum = _chop_reject_scatter(out, base, stream, n_cand, bpn, out_limbs, order_tuple)
    n_acc = csum[-1]
    need = count - base
    finishes = n_acc >= need
    # attempt index (within this chunk) of the need-th acceptance; the
    # cursor semantics are chunking-independent because attempts consume
    # exactly bpn bytes each, accepted or not
    pos = jnp.argmax(csum >= need)
    new_offset = jnp.where(finishes, offset + (pos + 1) * bpn, offset + n_cand * bpn)
    done = base >= count
    new_base = jnp.minimum(base + n_acc, count)
    return (
        out,
        jnp.where(done, base, new_base),
        jnp.where(done, offset, new_offset),
    )


def provisioned_chunk(count: int, order: int, n_seeds: int = 1) -> int:
    """The per-seed chunk size a batched in-graph derivation should use so
    ``n_seeds`` concurrent lanes stay inside the shared
    ``_CHUNK_BYTES_CAP`` device-memory budget (vmap multiplies the chunk
    footprint by the lane count; the while_loop simply runs more
    iterations when the cap bites)."""
    return _derive_params(count, order, None, n_seeds)[3]


def derive_uniform_limbs_ingraph(
    key_words: jax.Array,
    byte_offset: jax.Array,
    count: int,
    order: int,
    chunk_candidates: int | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Fully in-graph mask expansion: jit/vmap-composable, no host syncs.

    Returns ``(uint32[count, L] limbs, int32 end cursor)`` — bit-identical
    to the host ``StreamSampler`` draws from the same ``byte_offset``
    (same keystream, same rejection rule, same acceptance order, same
    consumed-bytes handoff). ``key_words`` is ``uint32[8]`` (the seed as
    little-endian words) and may be batched via ``vmap``; ``byte_offset``
    is a traced scalar. The chunk loop is a ``lax.while_loop`` (trip count
    1 except vanishingly rarely, by the 2^-60 provisioning), so the whole
    derivation lives inside a single jitted program — this is the kernel
    the federated simulation (``xaynet_tpu.sim``) vmaps across its
    participant axis.

    Keystream byte offsets ride in int32: derivations beyond ~2^31 bytes
    per seed (≈ 350M f32-config elements) are out of scope here — the
    chunked host API (``derive_uniform_limbs``) covers those.
    """
    bpn, out_limbs, order_cl, chunk_candidates = _derive_params(count, order, chunk_candidates)
    if count * bpn * 2 + 64 > 0x7FFFFFFF:
        raise ValueError("in-graph derivation cursor would overflow int32; use the host API")

    out0 = jnp.zeros((count, out_limbs), dtype=_U32)

    def cond(carry):
        return carry[1] < count

    def body(carry):
        out, base, offset = carry
        return _chunk_step_traced(
            out, base, key_words, offset, chunk_candidates, bpn, out_limbs, order_cl
        )

    out, _, offset = jax.lax.while_loop(
        cond, body, (out0, jnp.int32(0), jnp.asarray(byte_offset, jnp.int32))
    )
    return out, offset


def derive_uniform_limbs(
    seed: bytes,
    count: int,
    order: int,
    byte_offset: int = 0,
    chunk_candidates: int | None = None,
) -> jax.Array:
    """Device mask expansion: ``count`` uniform elements below ``order``.

    Bit-identical to the host ``StreamSampler`` (same keystream, same
    rejection rule, same acceptance order), derived in fixed-size keystream
    chunks so device memory is bounded by the chunk size, not by ``count``.
    Small draws are provisioned to finish in one chunk w.p. > 1 - 2^-60; the
    loop simply continues on the next chunk otherwise, so the result is
    unconditionally exact with no host fallback.
    """
    bpn, out_limbs, order_cl, chunk_candidates = _derive_params(count, order, chunk_candidates)

    key_words = jnp.asarray(np.frombuffer(seed, dtype="<u4"))
    out = jnp.zeros((count, out_limbs), dtype=_U32)
    base, offset = 0, byte_offset
    while base < count:
        block_start, intra = divmod(offset, 64)
        if block_start + chunk_candidates * bpn // 64 + 2 > 0xFFFFFFFF:
            raise ValueError("keystream longer than 2^32 blocks is not supported on device")
        out, n_acc = _derive_chunk(
            out,
            jnp.asarray(base, dtype=jnp.int32),
            key_words,
            jnp.asarray(block_start, dtype=_U32),
            jnp.asarray(intra, dtype=jnp.int32),
            chunk_candidates,
            bpn,
            out_limbs,
            order_cl,
        )
        base += int(n_acc)
        offset += chunk_candidates * bpn
    return out


def derive_uniform_limbs_batch(
    seeds: list[bytes],
    count: int,
    order: int,
    byte_offsets: list[int] | None = None,
    chunk_candidates: int | None = None,
) -> jax.Array:
    """``derive_uniform_limbs`` for many seeds in one kernel series.

    Returns ``uint32[len(seeds), count, out_limbs]``; each row is
    bit-identical to the single-seed derivation with that seed/offset (same
    keystream, same rejection rule, same acceptance order). Chunk rounds run
    until the slowest seed completes; seeds already done keep scattering
    into dropped slots (their ``base`` is clamped at ``count``), which costs
    keystream FLOPs but never correctness — with the 2^-60 provisioning all
    seeds complete in the first round except vanishingly rarely.
    """
    if not seeds:
        raise ValueError("no seeds")
    bpn, out_limbs, order_cl, chunk_candidates = _derive_params(
        count, order, chunk_candidates, n_seeds=len(seeds)
    )

    b = len(seeds)
    key_words = jnp.asarray(np.stack([np.frombuffer(s, dtype="<u4") for s in seeds]))
    out = jnp.zeros((b, count, out_limbs), dtype=_U32)
    base = np.zeros(b, dtype=np.int64)
    offsets = np.asarray(byte_offsets if byte_offsets is not None else [0] * b, dtype=np.int64)
    while (base < count).any():
        block_start, intra = np.divmod(offsets, 64)
        if int(block_start.max()) + chunk_candidates * bpn // 64 + 2 > 0xFFFFFFFF:
            raise ValueError("keystream longer than 2^32 blocks is not supported on device")
        out, n_acc = _derive_chunk_batch(
            out,
            jnp.asarray(base, dtype=jnp.int32),
            key_words,
            jnp.asarray(block_start, dtype=_U32),
            jnp.asarray(intra, dtype=jnp.int32),
            chunk_candidates,
            bpn,
            out_limbs,
            order_cl,
        )
        base = np.minimum(base + np.asarray(n_acc, dtype=np.int64), count)
        offsets += chunk_candidates * bpn
    return out
