"""ChaCha20 keystream and mask expansion as JAX device kernels.

Device counterpart of ``xaynet_tpu.core.crypto.chacha`` /
``prng.StreamSampler``. ChaCha20 is pure 32-bit add/xor/rotate — ideal VPU
work — and mask derivation (seed -> ``len`` uniform group elements,
reference: rust/xaynet-core/src/mask/seed.rs:61-78) becomes:

1. generate a statically over-provisioned batch of keystream blocks
   (all blocks in parallel: lanes = blocks);
2. chop into fixed-width little-endian candidates;
3. rejection-filter (candidate < order) with a scatter compaction instead of
   a data-dependent loop, keeping shapes static under jit.

The over-provisioning factor is chosen so the probability of producing fewer
than ``count`` accepted candidates is < 2^-60; the (astronomically rare)
shortfall is detected by the caller and falls back to the host sampler,
preserving bit-exactness unconditionally.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

_U32 = jnp.uint32
_CONSTANTS = (0x61707865, 0x3320646E, 0x79622D32, 0x6B206574)


def _rotl(x, n):
    return (x << _U32(n)) | (x >> _U32(32 - n))


def _quarter(s, a, b, c, d):
    s[a] = s[a] + s[b]
    s[d] = _rotl(s[d] ^ s[a], 16)
    s[c] = s[c] + s[d]
    s[b] = _rotl(s[b] ^ s[c], 12)
    s[a] = s[a] + s[b]
    s[d] = _rotl(s[d] ^ s[a], 8)
    s[c] = s[c] + s[d]
    s[b] = _rotl(s[b] ^ s[c], 7)
    return s


@partial(jax.jit, static_argnames=("nblocks", "block_start"))
def keystream_words(key_words: jax.Array, block_start: int, nblocks: int) -> jax.Array:
    """ChaCha20 keystream as ``uint32[nblocks, 16]`` little-endian words."""
    # 64-bit block counter in words 12-13; counters stay below 2^32 here
    # (2^32 blocks = 256 GiB of keystream per seed), so word 13 is constant.
    if block_start + nblocks > 0xFFFFFFFF:
        raise ValueError("keystream longer than 2^32 blocks is not supported on device")
    counters = _U32(block_start) + jnp.arange(nblocks, dtype=_U32)
    state = [jnp.broadcast_to(_U32(c), (nblocks,)) for c in _CONSTANTS]
    state += [jnp.broadcast_to(key_words[i], (nblocks,)) for i in range(8)]
    state.append(counters)
    state += [jnp.zeros(nblocks, dtype=_U32)] * 3

    w = list(state)
    for _ in range(10):
        w = _quarter(w, 0, 4, 8, 12)
        w = _quarter(w, 1, 5, 9, 13)
        w = _quarter(w, 2, 6, 10, 14)
        w = _quarter(w, 3, 7, 11, 15)
        w = _quarter(w, 0, 5, 10, 15)
        w = _quarter(w, 1, 6, 11, 12)
        w = _quarter(w, 2, 7, 8, 13)
        w = _quarter(w, 3, 4, 9, 14)
    out = [wi + si for wi, si in zip(w, state)]
    return jnp.stack(out, axis=-1)  # [nblocks, 16]


def _words_to_bytes(words: jax.Array) -> jax.Array:
    """uint32[..., W] little-endian words -> uint8[..., W*4]."""
    b0 = (words & _U32(0xFF)).astype(jnp.uint8)
    b1 = ((words >> _U32(8)) & _U32(0xFF)).astype(jnp.uint8)
    b2 = ((words >> _U32(16)) & _U32(0xFF)).astype(jnp.uint8)
    b3 = ((words >> _U32(24)) & _U32(0xFF)).astype(jnp.uint8)
    return jnp.stack([b0, b1, b2, b3], axis=-1).reshape(*words.shape[:-1], -1)


def provision_candidates(count: int, order: int) -> int:
    """Candidates to draw so that P(accepted < count) < ~2^-60."""
    bpn = (order.bit_length() + 7) // 8
    # int/int true division is correctly rounded at any magnitude
    p = order / (1 << (8 * bpn))
    p = max(min(p, 1.0), 1e-9)
    # Chernoff: need C with C*p - 7*sqrt(C*p*(1-p)) >= count
    c = count / p
    c += 7.0 * math.sqrt(max(c * (1 - p), 1.0)) / p + 64
    return int(c)


@partial(
    jax.jit,
    static_argnames=("count", "n_cand", "bpn", "out_limbs", "order_tuple", "byte_offset"),
)
def _derive_kernel(
    key_words: jax.Array,
    count: int,
    n_cand: int,
    bpn: int,
    out_limbs: int,
    order_tuple: tuple[int, ...],
    byte_offset: int = 0,
) -> tuple[jax.Array, jax.Array]:
    """Keystream -> candidates -> compacted accepted limbs (static shapes).

    ``byte_offset`` skips keystream bytes already consumed by earlier draws
    on the same stream (e.g. the unit draw preceding the vector draws).
    """
    nbytes = n_cand * bpn
    block_start = byte_offset // 64
    intra = byte_offset % 64
    nblocks = -(-(intra + nbytes) // 64)
    words = keystream_words(key_words, block_start, nblocks)
    stream = _words_to_bytes(words).reshape(-1)[intra : intra + nbytes]

    cand_limbs = max(1, (bpn + 3) // 4)
    padded = jnp.zeros((n_cand, cand_limbs * 4), dtype=jnp.uint8)
    padded = padded.at[:, :bpn].set(stream.reshape(n_cand, bpn))
    # little-endian bytes -> uint32 limbs
    quads = padded.reshape(n_cand, cand_limbs, 4).astype(_U32)
    cand = (
        quads[..., 0]
        | (quads[..., 1] << _U32(8))
        | (quads[..., 2] << _U32(16))
        | (quads[..., 3] << _U32(24))
    )

    # acceptance: lexicographic candidate < order
    order_arr = np.asarray(order_tuple, dtype=np.uint32)
    lt = jnp.zeros(n_cand, dtype=bool)
    decided = jnp.zeros(n_cand, dtype=bool)
    for j in range(cand_limbs - 1, -1, -1):
        col = cand[:, j]
        o = _U32(int(order_arr[j]))
        lt = lt | (~decided & (col < o))
        decided = decided | (col != o)

    # compaction: accepted candidate i goes to slot rank(i); drop overflow
    rank = jnp.cumsum(lt.astype(jnp.int32)) - 1
    slot = jnp.where(lt, rank, count)  # rejected -> out-of-range slot
    out = jnp.zeros((count + 1, cand_limbs), dtype=_U32)
    out = out.at[slot].set(cand, mode="drop")
    n_accepted = rank[-1] + 1
    return out[:count, :out_limbs], n_accepted


def derive_uniform_limbs(
    seed: bytes, count: int, order: int, byte_offset: int = 0
) -> jax.Array:
    """Device mask expansion: ``count`` uniform elements below ``order``.

    Bit-identical to the host ``StreamSampler`` (same keystream, same
    rejection rule). Falls back to the host sampler on the ~2^-60 shortfall.
    """
    from ..core.crypto import prng as host_prng
    from . import limbs as host_limbs

    bpn = (order.bit_length() + 7) // 8
    cand_limbs = max(1, (bpn + 3) // 4)
    out_limbs = host_limbs.n_limbs_for_order(order)
    order_cl = host_limbs.int_to_limbs(order, cand_limbs)
    n_cand = provision_candidates(count, order)
    key_words = jnp.asarray(np.frombuffer(seed, dtype="<u4"))
    out, n_accepted = _derive_kernel(
        key_words,
        count,
        n_cand,
        bpn,
        out_limbs,
        tuple(int(x) for x in order_cl),
        byte_offset,
    )
    if int(n_accepted) < count:  # pragma: no cover — probability < 2^-60
        sampler = host_prng.StreamSampler(seed)
        if byte_offset:
            sampler.skip_bytes(byte_offset)
        return jnp.asarray(sampler.draw_limbs(count, order))
    return out
