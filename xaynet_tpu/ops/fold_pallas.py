"""Pallas TPU kernel for the lazy-carry batch fold.

Fuses the whole aggregation fold (16-bit split -> K-sum -> carry propagate
-> modular reduce -> accumulate) into one kernel so the staged batch makes
exactly one HBM->VMEM trip per tile with no intermediate HBM materialization.
Grid: one program per model-axis tile; each program loops the K updates of
its tile in VMEM.

Equivalent to ``fold_jax.fold_planar_batch`` (the XLA version, which remains
the fallback and the CPU/interpret oracle). Layouts match: planar
``uint32[K, L, n]`` batch, ``uint32[L, n]`` accumulator.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .fold_jax import MAX_LAZY_BATCH

_U32 = jnp.uint32

TILE = 2048  # model-axis elements per grid program (VMEM-friendly)


def _limbs(value: int, n_limbs: int) -> tuple[int, ...]:
    return tuple((value >> (32 * i)) & 0xFFFFFFFF for i in range(n_limbs))


def _fold_kernel(acc_ref, stack_ref, out_ref, *, k: int, n_limb: int, order: int):
    """One model-axis tile: sum K updates lazily, reduce, accumulate."""
    # 16-bit column sums over K (values < K * 2^16 <= 2^32)
    lo = jnp.zeros((n_limb, stack_ref.shape[2]), dtype=_U32)
    hi = jnp.zeros((n_limb, stack_ref.shape[2]), dtype=_U32)
    for i in range(k):  # statically unrolled; stack tile lives in VMEM
        limbs = stack_ref[i]
        lo = lo + (limbs & _U32(0xFFFF))
        hi = hi + (limbs >> _U32(16))

    # carry-propagate into an (L+1)-limb value < K * order
    carry = jnp.zeros((stack_ref.shape[2],), dtype=_U32)
    value = []
    for j in range(n_limb):
        t_lo = lo[j] + carry
        t_hi = hi[j] + (t_lo >> _U32(16))
        value.append((t_lo & _U32(0xFFFF)) | (t_hi << _U32(16)))
        carry = t_hi >> _U32(16)
    value.append(carry)

    # conditional subtracts of order << b
    kbits = max(1, (k - 1).bit_length())
    for b in range(kbits - 1, -1, -1):
        const = _limbs(order << b, n_limb + 1)
        lt = jnp.zeros_like(value[0], dtype=jnp.bool_)
        decided = jnp.zeros_like(lt)
        for j in range(n_limb, -1, -1):
            o = _U32(const[j])
            lt = lt | (~decided & (value[j] < o))
            decided = decided | (value[j] != o)
        ge = ~lt
        borrow = jnp.zeros_like(value[0])
        new_value = []
        for j in range(n_limb + 1):
            d1 = value[j] - _U32(const[j])
            b1 = (value[j] < _U32(const[j])).astype(_U32)
            d2 = d1 - borrow
            b2 = (d1 < borrow).astype(_U32)
            new_value.append(jnp.where(ge, d2, value[j]))
            borrow = b1 | b2
        value = new_value

    # modular add into the accumulator (top limb of value is now zero)
    acc = acc_ref[:]
    carry = jnp.zeros_like(value[0])
    summed = []
    for j in range(n_limb):
        s1 = acc[j] + value[j]
        c1 = (s1 < acc[j]).astype(_U32)
        s2 = s1 + carry
        c2 = (s2 < s1).astype(_U32)
        summed.append(s2)
        carry = c1 | c2
    if order == 1 << (32 * n_limb):
        out_ref[:] = jnp.stack(summed)
        return
    ol = _limbs(order, n_limb)
    lt = jnp.zeros_like(summed[0], dtype=jnp.bool_)
    decided = jnp.zeros_like(lt)
    for j in range(n_limb - 1, -1, -1):
        o = _U32(ol[j])
        lt = lt | (~decided & (summed[j] < o))
        decided = decided | (summed[j] != o)
    ge = (carry != 0) | ~lt
    borrow = jnp.zeros_like(summed[0])
    reduced = []
    for j in range(n_limb):
        d1 = summed[j] - _U32(ol[j])
        b1 = (summed[j] < _U32(ol[j])).astype(_U32)
        d2 = d1 - borrow
        b2 = (d1 < borrow).astype(_U32)
        reduced.append(jnp.where(ge, d2, summed[j]))
        borrow = b1 | b2
    out_ref[:] = jnp.stack(reduced)


@partial(jax.jit, static_argnames=("order", "interpret", "tile_size"), donate_argnums=(0,))
def fold_planar_batch_pallas(
    acc, stack_planar, order: int, interpret: bool = False, tile_size: int | None = None
):
    """Pallas version of ``fold_jax.fold_planar_batch`` (same contract).

    Model lengths that don't divide the tile are zero-padded internally
    (zeros are valid group elements) and sliced back afterwards.
    ``tile_size`` overrides the default tile (bench.py sweeps it on real
    hardware to pick the fastest VMEM blocking for the chip).
    """
    k, n_limb, n = stack_planar.shape
    if k > MAX_LAZY_BATCH:
        raise ValueError(f"batch of {k} exceeds lazy-carry headroom {MAX_LAZY_BATCH}")
    tile = min(tile_size if tile_size else TILE, n)
    padded_n = -(-n // tile) * tile
    if padded_n != n:
        pad = padded_n - n
        acc = jnp.pad(acc, ((0, 0), (0, pad)))
        stack_planar = jnp.pad(stack_planar, ((0, 0), (0, 0), (0, pad)))
    grid = (padded_n // tile,)
    out = pl.pallas_call(
        partial(_fold_kernel, k=k, n_limb=n_limb, order=order),
        out_shape=jax.ShapeDtypeStruct((n_limb, padded_n), jnp.uint32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((n_limb, tile), lambda i: (0, i)),
            pl.BlockSpec((k, n_limb, tile), lambda i: (0, 0, i)),
        ],
        out_specs=pl.BlockSpec((n_limb, tile), lambda i: (0, i)),
        interpret=interpret,
    )(acc, stack_planar)
    return out[:, :n] if padded_n != n else out
