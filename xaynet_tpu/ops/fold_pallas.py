"""Pallas TPU kernels: the lazy-carry batch fold and the fused mask pipeline.

**Batch fold** (``fold_planar_batch_pallas``): fuses the whole aggregation
fold (16-bit split -> K-sum -> carry propagate -> modular reduce ->
accumulate) into one kernel so the staged batch makes exactly one HBM->VMEM
trip per tile with no intermediate HBM materialization. Grid: one program
per model-axis tile; each program loops the K updates of its tile in VMEM.
Equivalent to ``fold_jax.fold_planar_batch`` (the XLA version, which remains
the fallback and the CPU/interpret oracle). Layouts match: planar
``uint32[K, L, n]`` batch, ``uint32[L, n]`` accumulator.

**Fused mask pipeline** (``mask_fold_planar_pallas``): the Sum2 hot loop —
keystream generation -> lexicographic rejection sampling -> modular add —
as ONE kernel over the planar mask accumulator. Each launch folds a whole
seed group: per seed, the ChaCha keystream is generated and
rejection-sampled with the exact ``StreamSampler`` semantics
(``chacha_jax.derive_uniform_limbs_ingraph`` traced INSIDE the kernel body,
so the acceptance rule has one source of truth) and the accepted limbs are
modularly added straight into the accumulator held in VMEM — the per-seed
mask itself is a kernel-local value and never materializes in HBM. The
rejection cursor is inherently sequential along the keystream, so the fused
kernel batches over SEEDS (the model axis of one mask cannot shard without
deriving its prefix); the interpret route is the CPU/CI path and the real
Mosaic lowering stays behind the mask-kernel auto-calibration race
(``ops.masking_jax``), which falls back to the XLA batch route when the
compile fails or loses.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .fold_jax import MAX_LAZY_BATCH

_U32 = jnp.uint32

TILE = 2048  # model-axis elements per grid program (VMEM-friendly)


def _limbs(value: int, n_limbs: int) -> tuple[int, ...]:
    return tuple((value >> (32 * i)) & 0xFFFFFFFF for i in range(n_limbs))


def _fold_kernel(acc_ref, stack_ref, out_ref, *, k: int, n_limb: int, order: int):
    """One model-axis tile: sum K updates lazily, reduce, accumulate."""
    # 16-bit column sums over K (values < K * 2^16 <= 2^32)
    lo = jnp.zeros((n_limb, stack_ref.shape[2]), dtype=_U32)
    hi = jnp.zeros((n_limb, stack_ref.shape[2]), dtype=_U32)
    for i in range(k):  # statically unrolled; stack tile lives in VMEM
        limbs = stack_ref[i]
        lo = lo + (limbs & _U32(0xFFFF))
        hi = hi + (limbs >> _U32(16))

    # carry-propagate into an (L+1)-limb value < K * order
    carry = jnp.zeros((stack_ref.shape[2],), dtype=_U32)
    value = []
    for j in range(n_limb):
        t_lo = lo[j] + carry
        t_hi = hi[j] + (t_lo >> _U32(16))
        value.append((t_lo & _U32(0xFFFF)) | (t_hi << _U32(16)))
        carry = t_hi >> _U32(16)
    value.append(carry)

    # conditional subtracts of order << b
    kbits = max(1, (k - 1).bit_length())
    for b in range(kbits - 1, -1, -1):
        const = _limbs(order << b, n_limb + 1)
        lt = jnp.zeros_like(value[0], dtype=jnp.bool_)
        decided = jnp.zeros_like(lt)
        for j in range(n_limb, -1, -1):
            o = _U32(const[j])
            lt = lt | (~decided & (value[j] < o))
            decided = decided | (value[j] != o)
        ge = ~lt
        borrow = jnp.zeros_like(value[0])
        new_value = []
        for j in range(n_limb + 1):
            d1 = value[j] - _U32(const[j])
            b1 = (value[j] < _U32(const[j])).astype(_U32)
            d2 = d1 - borrow
            b2 = (d1 < borrow).astype(_U32)
            new_value.append(jnp.where(ge, d2, value[j]))
            borrow = b1 | b2
        value = new_value

    # modular add into the accumulator (top limb of value is now zero)
    acc = acc_ref[:]
    carry = jnp.zeros_like(value[0])
    summed = []
    for j in range(n_limb):
        s1 = acc[j] + value[j]
        c1 = (s1 < acc[j]).astype(_U32)
        s2 = s1 + carry
        c2 = (s2 < s1).astype(_U32)
        summed.append(s2)
        carry = c1 | c2
    if order == 1 << (32 * n_limb):
        out_ref[:] = jnp.stack(summed)
        return
    ol = _limbs(order, n_limb)
    lt = jnp.zeros_like(summed[0], dtype=jnp.bool_)
    decided = jnp.zeros_like(lt)
    for j in range(n_limb - 1, -1, -1):
        o = _U32(ol[j])
        lt = lt | (~decided & (summed[j] < o))
        decided = decided | (summed[j] != o)
    ge = (carry != 0) | ~lt
    borrow = jnp.zeros_like(summed[0])
    reduced = []
    for j in range(n_limb):
        d1 = summed[j] - _U32(ol[j])
        b1 = (summed[j] < _U32(ol[j])).astype(_U32)
        d2 = d1 - borrow
        b2 = (d1 < borrow).astype(_U32)
        reduced.append(jnp.where(ge, d2, summed[j]))
        borrow = b1 | b2
    out_ref[:] = jnp.stack(reduced)


@partial(jax.jit, static_argnames=("order", "interpret", "tile_size"), donate_argnums=(0,))
def fold_planar_batch_pallas(
    acc, stack_planar, order: int, interpret: bool = False, tile_size: int | None = None
):
    """Pallas version of ``fold_jax.fold_planar_batch`` (same contract).

    Model lengths that don't divide the tile are zero-padded internally
    (zeros are valid group elements) and sliced back afterwards.
    ``tile_size`` overrides the default tile (bench.py sweeps it on real
    hardware to pick the fastest VMEM blocking for the chip).
    """
    k, n_limb, n = stack_planar.shape
    if k > MAX_LAZY_BATCH:
        raise ValueError(f"batch of {k} exceeds lazy-carry headroom {MAX_LAZY_BATCH}")
    tile = min(tile_size if tile_size else TILE, n)
    padded_n = -(-n // tile) * tile
    if padded_n != n:
        pad = padded_n - n
        acc = jnp.pad(acc, ((0, 0), (0, pad)))
        stack_planar = jnp.pad(stack_planar, ((0, 0), (0, 0), (0, pad)))
    grid = (padded_n // tile,)
    out = pl.pallas_call(
        partial(_fold_kernel, k=k, n_limb=n_limb, order=order),
        out_shape=jax.ShapeDtypeStruct((n_limb, padded_n), jnp.uint32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((n_limb, tile), lambda i: (0, i)),
            pl.BlockSpec((k, n_limb, tile), lambda i: (0, 0, i)),
        ],
        out_specs=pl.BlockSpec((n_limb, tile), lambda i: (0, i)),
        interpret=interpret,
    )(acc, stack_planar)
    return out[:, :n] if padded_n != n else out


# --- fused mask pipeline: keystream -> reject-sample -> modular add --------


def _mask_fold_kernel(
    kw_ref, off_ref, acc_ref, out_acc_ref, out_off_ref, *, count, order, chunk_candidates
):
    """Fold every seed's freshly-derived mask into the planar accumulator.

    The whole body is pure traced code: the derivation reuses the in-graph
    sampler (same keystream, same rejection rule, same count-th-accept
    cursor handoff as the scalar ``StreamSampler``), the per-seed mask is a
    loop-carried value (VMEM-resident, never written back), and only the
    accumulator and the end cursors leave the kernel.
    """
    from . import chacha_jax
    from .fold_jax import p_mod_add

    kws = kw_ref[...]  # [B, 8] seed key words
    offs = off_ref[...]  # [B] byte cursors (post unit draw)
    acc = acc_ref[...]  # [L, count] planar mask accumulator

    def one_seed(b, carry):
        acc, ends = carry
        kw = jax.lax.dynamic_index_in_dim(kws, b, keepdims=False)
        mask, end = chacha_jax.derive_uniform_limbs_ingraph(
            kw, offs[b], count, order, chunk_candidates
        )
        acc = p_mod_add(acc, jnp.transpose(mask), order)
        return acc, ends.at[b].set(end)

    acc, ends = jax.lax.fori_loop(
        0, kws.shape[0], one_seed, (acc, jnp.zeros(kws.shape[0], jnp.int32))
    )
    out_acc_ref[...] = acc
    out_off_ref[...] = ends


@partial(
    jax.jit,
    static_argnames=("count", "order", "chunk_candidates", "interpret"),
    donate_argnums=(0,),
)
def mask_fold_planar_pallas(
    acc,
    key_words,
    byte_offsets,
    count: int,
    order: int,
    chunk_candidates: int | None = None,
    interpret: bool = False,
):
    """Derive + modularly fold a seed group's masks into ``acc`` in ONE kernel.

    ``acc`` is the planar ``uint32[L, count]`` mask accumulator (donated),
    ``key_words`` ``uint32[B, 8]``, ``byte_offsets`` ``int32[B]`` the
    keystream cursors each seed's vector draw resumes at (the unit draw's
    consumed-bytes handoff). Returns ``(new_acc, end_offsets int32[B])``;
    every seed's contribution is bit-identical to
    ``MaskSeed.derive_mask(...).vect`` folded with a modular add, but the
    mask tensor itself never exists outside the kernel. ``chunk_candidates``
    bounds the per-trip keystream footprint (tiny budgets force the
    multi-trip rejection path — the golden tests pin that case).
    """
    if key_words.ndim != 2 or key_words.shape[1] != 8:
        raise ValueError("key_words must be uint32[B, 8]")
    b = key_words.shape[0]
    out = pl.pallas_call(
        partial(
            _mask_fold_kernel, count=count, order=order, chunk_candidates=chunk_candidates
        ),
        out_shape=(
            jax.ShapeDtypeStruct(acc.shape, jnp.uint32),
            jax.ShapeDtypeStruct((b,), jnp.int32),
        ),
        input_output_aliases={2: 0},
        interpret=interpret,
    )(key_words, jnp.asarray(byte_offsets, jnp.int32), acc)
    return out
