"""Single-pass lazy-carry batch aggregation (the fast device fold).

The naive way to aggregate K masked updates is a pairwise tree of modular
adds — ``log2 K`` full passes over HBM. This kernel does it in ONE pass over
the staged batch:

1. split each uint32 limb into its 16-bit halves *inside the reduction* (XLA
   fuses the elementwise split into the reduce input, so the batch is read
   exactly once);
2. plain-sum the halves over K — sums of 16-bit values stay below 2^32 for
   K <= 65535, so no carries are needed during the reduction;
3. carry-propagate the 16-bit column sums into an (L+1)-limb value
   (``value < K * order``);
4. reduce modulo the order with ``ceil(log2 K)`` conditional subtracts of
   ``order << b`` (tiny passes over the [L+1, n] result);
5. fold into the running accumulator with one modular add.

Device arrays are **planar**: ``uint32[L, n]`` (limb-major), so the model
axis is the innermost dimension and maps onto the full VPU lane width — a
wire-layout ``[n, L]`` device array with a trailing dim of 2-3 tiles
catastrophically on TPU (the (8,128) tile pads the minor dim ~64x). The
wire->planar transpose is a cheap host-side memcpy (``wire_to_planar``)
done once per staged update during ingest.

Replaces the reference's per-update sequential big-int loop
(rust/xaynet-core/src/mask/masking.rs:292-316).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

_U32 = jnp.uint32

MAX_LAZY_BATCH = 65535  # 16-bit lazy-carry headroom


def _int_to_limbs_list(value: int, n_limbs: int) -> tuple[int, ...]:
    return tuple((value >> (32 * i)) & 0xFFFFFFFF for i in range(n_limbs))


# --- planar helpers: arrays are uint32[L, n] ------------------------------


def p_add(a, b):
    """Planar limbwise add with carry; returns (sum, carry)."""
    outs = []
    carry = jnp.zeros_like(a[0])
    for j in range(a.shape[0]):
        s1 = a[j] + b[j]
        c1 = (s1 < a[j]).astype(_U32)
        s2 = s1 + carry
        c2 = (s2 < s1).astype(_U32)
        outs.append(s2)
        carry = c1 | c2
    return jnp.stack(outs), carry


def p_sub(a, b):
    """Planar limbwise subtract with borrow; returns (diff, borrow)."""
    outs = []
    borrow = jnp.zeros_like(a[0])
    for j in range(a.shape[0]):
        d1 = a[j] - b[j]
        b1 = (a[j] < b[j]).astype(_U32)
        d2 = d1 - borrow
        b2 = (d1 < borrow).astype(_U32)
        outs.append(d2)
        borrow = b1 | b2
    return jnp.stack(outs), borrow


def p_lt_const(a, const_limbs: tuple[int, ...]):
    lt = jnp.zeros(a.shape[1:], dtype=bool)
    decided = jnp.zeros(a.shape[1:], dtype=bool)
    for j in range(a.shape[0] - 1, -1, -1):
        o = _U32(const_limbs[j])
        lt = lt | (~decided & (a[j] < o))
        decided = decided | (a[j] != o)
    return lt


def p_cond_sub_const(a, const_limbs: tuple[int, ...]):
    """Subtract the constant wherever ``a >= const`` (one fused pass)."""
    ge = ~p_lt_const(a, const_limbs)
    c = jnp.stack([jnp.full(a.shape[1:], cl, dtype=_U32) for cl in const_limbs])
    d, _ = p_sub(a, c)
    return jnp.where(ge[None, :], d, a)


def p_mod_add(a, b, order: int):
    """Planar ``(a + b) mod order`` for ``a, b < order`` (handles 2^(32L))."""
    n_limb = a.shape[0]
    s, carry = p_add(a, b)
    if order == 1 << (32 * n_limb):
        return s  # wraparound IS the reduction
    ol = _int_to_limbs_list(order, n_limb)
    ge = (carry != 0) | ~p_lt_const(s, ol)
    c = jnp.stack([jnp.full(s.shape[1:], x, dtype=_U32) for x in ol])
    d, _ = p_sub(s, c)
    return jnp.where(ge[None, :], d, s)


def p_mod_sub(a, b, order: int):
    """Planar ``(a - b) mod order`` for ``a, b < order``."""
    n_limb = a.shape[0]
    d, borrow = p_sub(a, b)
    if order == 1 << (32 * n_limb):
        return d
    ol = _int_to_limbs_list(order, n_limb)
    c = jnp.stack([jnp.full(d.shape[1:], x, dtype=_U32) for x in ol])
    d2, _ = p_add(d, c)
    return jnp.where((borrow != 0)[None, :], d2, d)


# --- the fold -------------------------------------------------------------


@partial(jax.jit, static_argnames=("order",), donate_argnums=(0,))
def fold_planar_batch(acc, stack_planar, order: int):
    """Fold planar ``uint32[K, L, n]`` updates into the planar ``[L, n]`` acc.

    Single full pass over the batch: the uint32 limbs are bitcast to uint16
    halves (free) and summed over K with ONE widening reduction whose minor
    dimension is the model axis — full VPU lane utilization, no relayout.
    """
    k, n_limb, n = stack_planar.shape
    if k > MAX_LAZY_BATCH:
        raise ValueError(f"batch of {k} exceeds lazy-carry headroom {MAX_LAZY_BATCH}")
    halves = jax.lax.bitcast_convert_type(stack_planar, jnp.uint16)  # [K, L, n, 2]
    # merge the u16 pair axis into the model axis BEFORE the reduction: a
    # materialized tensor with a minor dimension of 2 tiles catastrophically
    # on TPU (lane padding), while [.., 2n] keeps lanes full. The reshape is
    # free (contiguous dims merge) and the batch is read exactly once.
    sums = jnp.sum(halves.reshape(k, n_limb, n * 2), axis=0, dtype=_U32)  # [L, 2n]
    lo = sums[:, 0::2]
    hi = sums[:, 1::2]
    carry = jnp.zeros(n, dtype=_U32)
    limbs32 = []
    for j in range(n_limb):
        t_lo = lo[j] + carry
        t_hi = hi[j] + (t_lo >> _U32(16))
        limbs32.append((t_lo & _U32(0xFFFF)) | (t_hi << _U32(16)))
        carry = t_hi >> _U32(16)
    limbs32.append(carry)
    value = jnp.stack(limbs32)
    kbits = max(1, (k - 1).bit_length())
    for b in range(kbits - 1, -1, -1):
        value = p_cond_sub_const(value, _int_to_limbs_list(order << b, n_limb + 1))
    return p_mod_add(acc, value[:n_limb], order)


@partial(jax.jit, static_argnames=("n_limbs", "order"), donate_argnums=(0,))
def fold_packed_batch(acc, packed, n_limbs: int, order: int):
    """Fold PACKED byte-planar ``uint8[K, bpn, n]`` updates into the planar
    ``[L, n]`` accumulator: in-graph unpack (``limbs_jax.packed_planar_to_limbs``)
    fused with the lazy-carry fold in ONE jit, so the 4L-byte planar tensor
    never crosses host->device — only the ``bpn``-byte packed planes do
    (the EQuARX insight applied to the staging transfer)."""
    from .limbs_jax import packed_planar_to_limbs

    planar = packed_planar_to_limbs(packed, n_limbs)
    return fold_planar_batch(acc, planar, order)


def wire_to_planar(stack: np.ndarray) -> np.ndarray:
    """Host: wire-layout ``[K, n, L]`` (or ``[n, L]``) -> planar ``[K, L, n]``."""
    stack = np.asarray(stack, dtype=np.uint32)
    if stack.ndim == 2:
        return np.ascontiguousarray(stack.T)
    return np.ascontiguousarray(stack.transpose(0, 2, 1))


def planar_to_wire(planar: np.ndarray) -> np.ndarray:
    """Host: planar ``[L, n]`` -> wire-layout ``[n, L]``."""
    return np.ascontiguousarray(np.asarray(planar).T)
