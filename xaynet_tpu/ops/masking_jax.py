"""Device-side masking operations: mask expansion, aggregation, unmask.

Device counterparts of the reference hot loops (reference:
rust/xaynet-core/src/mask/seed.rs:61-78 derive_mask,
rust/xaynet-sdk/src/state_machine/phases/sum2.rs:170-193 mask aggregation,
rust/xaynet-server/src/state_machine/phases/unmask.rs unmask subtract).

Composes the ChaCha20 and limb kernels into the protocol-level device ops the
coordinator and sum participants run:

- ``derive_mask_limbs``: seed -> (unit element, vector limb tensor), the
  device version of ``MaskSeed.derive_mask`` (bit-identical keystream
  consumption: one unit draw on the host cursor, vector draws on device from
  the handed-off byte offset);
- ``unmask_vect_limbs``: modular subtract of the aggregated mask from the
  aggregated masked model (the Unmask-phase kernel);
- ``sum_masks``: aggregate many seed-derived masks (the Sum2 participant hot
  loop: #updates x model_length group elements). Since the fused-pipeline
  promotion this routes through one of the ``MASK_KERNELS``
  (``utils.kernels``): the in-graph batched derive streamed through the
  PR-7 shard pipeline, the fused Pallas keystream→reject→fold kernel, or
  the pre-promotion host-chunked path — ``auto`` races them once per
  process on a probe group and memoizes the winner, exactly like the fold
  kernels' auto-calibration.
"""

from __future__ import annotations

import logging
import os
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from ..core.crypto.prng import StreamSampler
from ..core.mask.config import MaskConfigPair
from ..core.mask.encode import clamp_scalar, encode_unit, encode_vect_limbs
from ..telemetry import profiling, report as round_report
from ..telemetry import tracing as trace
from ..telemetry.registry import get_registry
from ..utils.kernels import MASK_KERNELS
from . import chacha_jax, limbs as host_limbs, limbs_jax

logger = logging.getLogger(__name__)

SPAN_MASK_CALIBRATE = trace.declare_span("mask.calibrate")
SPAN_MASK_SUM = trace.declare_span("mask.sum")

# Compiled-program cache bound for the pow2-lane batched derive (and the
# other jitted mask-pipeline builders below). Each entry retains a full XLA
# executable specialized on (length, config, lane bucket); an unbounded
# cache on a long-running participant serving many round shapes would
# retain one program per shape forever.
_COMPILE_CACHE_MAX = 16

MASK_DERIVE_COMPILE_CACHE = get_registry().gauge(
    "xaynet_mask_derive_compile_cache",
    "Compiled mask-derivation programs currently held by the bounded "
    "pow2-lane lru caches (batched derive + unit-draw + planarize).",
)


def derive_mask_limbs(
    seed: bytes, length: int, config: MaskConfigPair
) -> tuple[np.ndarray, jax.Array]:
    """Expand a 32-byte seed into (unit limbs [L1], vector limbs [length, L])."""
    sampler = StreamSampler(seed)
    unit = sampler.draw_limbs(1, config.unit.order)[0]
    offset = sampler.consumed_bytes
    vect = chacha_jax.derive_uniform_limbs(seed, length, config.vect.order, byte_offset=offset)
    return unit, vect


def derive_mask_ingraph(
    key_words: jax.Array,
    length: int,
    config: MaskConfigPair,
    unit_chunk: int | None = None,
    vect_chunk: int | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Fully in-graph ``MaskSeed.derive_mask``: (unit [L1], vect [length, L]).

    Pure traced code (no host syncs), composable under ``jit``/``vmap`` —
    the per-participant kernel the federated simulation maps across its
    participant axis. Keystream semantics are bit-identical to the scalar
    ``core/mask/seed.py`` reference: one unit-order draw first, then the
    vector draws resume at the traced in-graph byte cursor the unit draw
    handed off. The chunk knobs bound per-lane device memory; pass
    ``chacha_jax.provisioned_chunk(length, order, n_lanes)`` when vmapping
    ``n_lanes`` participants so the batch stays inside the chunk budget.
    """
    unit, offset = chacha_jax.derive_uniform_limbs_ingraph(
        key_words, jnp.int32(0), 1, config.unit.order, unit_chunk
    )
    vect, _ = chacha_jax.derive_uniform_limbs_ingraph(
        key_words, offset, length, config.vect.order, vect_chunk
    )
    return unit[0], vect


def seed_words(seeds: list[bytes]) -> np.ndarray:
    """32-byte seeds -> ``uint32[B, 8]`` little-endian ChaCha key words."""
    if not seeds:
        return np.zeros((0, 8), dtype=np.uint32)
    return np.stack([np.frombuffer(s, dtype="<u4") for s in seeds])


def derive_chunk_budgets(
    length: int, config: MaskConfigPair, lanes: int
) -> tuple[int, int]:
    """(unit_chunk, vect_chunk) keystream budgets for ``lanes`` concurrent
    in-graph derivations — the ONE provisioning rule shared by the batched
    production derive and the simulation's participant-axis vmap."""
    return (
        chacha_jax.provisioned_chunk(1, config.unit.order, lanes),
        chacha_jax.provisioned_chunk(length, config.vect.order, lanes),
    )


def _publish_compile_cache_gauge() -> None:
    MASK_DERIVE_COMPILE_CACHE.set(
        _mask_batch_fn.cache_info().currsize
        + _unit_offsets_fn.cache_info().currsize
        + _planarize_fn.cache_info().currsize
    )


@lru_cache(maxsize=_COMPILE_CACHE_MAX)
def _mask_batch_fn(length: int, config: MaskConfigPair, lane_bucket: int):
    unit_chunk, vect_chunk = derive_chunk_budgets(length, config, lane_bucket)

    def one(kw):
        return derive_mask_ingraph(kw, length, config, unit_chunk, vect_chunk)

    return jax.jit(jax.vmap(one))


@lru_cache(maxsize=_COMPILE_CACHE_MAX)
def _unit_offsets_fn(config: MaskConfigPair):
    """Jitted batched unit draw: ``uint32[B, 8]`` key words ->
    (unit limbs ``uint32[B, L1]``, byte cursors ``int32[B]`` the vector
    draws resume at) — the in-graph replacement for the per-seed host
    ``StreamSampler`` unit loop."""
    unit_chunk = chacha_jax.provisioned_chunk(1, config.unit.order, 1)

    def one(kw):
        unit, off = chacha_jax.derive_uniform_limbs_ingraph(
            kw, jnp.int32(0), 1, config.unit.order, unit_chunk
        )
        return unit[0], off

    return jax.jit(jax.vmap(one))


@lru_cache(maxsize=_COMPILE_CACHE_MAX)
def _planarize_fn(length: int, padded: int):
    """Jitted wire ``[B, len, L]`` -> planar padded ``[B, L, padded]``
    relayout (the shard pipeline's batch shape), done on device so the
    derived masks never round-trip the host before folding."""

    def f(vects):
        planar = jnp.transpose(vects, (0, 2, 1))
        if padded != length:
            planar = jnp.pad(planar, ((0, 0), (0, 0), (0, padded - length)))
        return planar

    return jax.jit(f)


def derive_mask_limbs_batch(
    seeds: list[bytes], length: int, config: MaskConfigPair
) -> tuple[jax.Array, jax.Array]:
    """``derive_mask_limbs`` for many seeds in ONE jitted program.

    Returns (units ``uint32[B, L1]``, vects ``uint32[B, length, L]``);
    every row is bit-identical to ``MaskSeed.derive_mask`` with that seed
    (golden-pinned in tests/test_sim_round.py). Unlike ``sum_masks`` this
    never walks the seeds on the host — unit draws, cursor handoffs and
    vector draws are all in-graph — so it is the building block for
    whole-round simulation rather than the Sum2 aggregate.

    Compiled programs are cached per (length, config, pow2 lane bucket);
    the lane bucket also scales the chunk budget so large batches don't
    multiply the keystream footprint past the device-memory cap.
    """
    if not seeds:
        raise ValueError("no seeds")
    lane_bucket = 1 << (len(seeds) - 1).bit_length()
    fn = _mask_batch_fn(length, config, lane_bucket)
    return fn(jnp.asarray(seed_words(seeds)))


def encode_models_batch(
    weights: np.ndarray, scalar, config: MaskConfigPair
) -> tuple[np.ndarray, np.ndarray]:
    """Fixed-point encode a population of models in ONE vectorized pass.

    ``weights`` is ``[B, length]`` (every participant shares ``scalar``, the
    homogeneous-simulation shape); returns (unit limbs ``uint32[L1]`` — the
    encoded clamped scalar, identical for every lane — and vect limbs
    ``uint32[B, length, L]``). Byte-identical to ``B`` independent
    ``Masker.mask`` encodes because the fixed-point map is elementwise: the
    flattened array goes through the SAME production ``encode_vect_limbs``
    (double-double fast path for bounded f32, exact Fractions otherwise)
    that a single participant runs, then reshapes. Pinned against the
    scalar path in tests/test_sim_round.py.
    """
    weights = np.asarray(weights)
    if weights.ndim != 2:
        raise ValueError("weights must be [participants, length]")
    s_clamped = clamp_scalar(scalar, config.unit)
    flat = encode_vect_limbs(weights.reshape(-1), s_clamped, config.vect)
    vect = flat.reshape(weights.shape[0], weights.shape[1], -1)
    unit_int = encode_unit(s_clamped, config.unit)
    unit = host_limbs.int_to_limbs(unit_int, host_limbs.n_limbs_for_order(config.unit.order))
    return unit, vect


def unmask_vect_limbs(
    masked: jax.Array, mask: jax.Array, order: int
) -> jax.Array:
    """``(masked - mask) mod order`` elementwise over limb tensors."""
    return limbs_jax.mod_sub(masked, mask, host_limbs.order_limbs_for(order))


# -- promoted Sum2 pipeline: kernel routing + auto-calibration --------------

# auto verdicts, process-wide (the fold kernels' `_AUTO_KERNEL_CACHE` idiom):
# a participant resolves the route once per (backend, shape) and every later
# Sum2 leg reuses it
_MASK_KERNEL_CACHE: dict[tuple, str] = {}
# observability: the route the last sum_masks call actually took
_LAST_MASK_KERNEL: str | None = None

# auto-calibration probe: candidates race on a seed group derived at
# min(length, _PROBE_LENGTH) elements. Unlike the fold race (which times the
# real first batch it must fold anyway), re-deriving a 25M-element group per
# candidate would triple the first Sum2 leg — the relative kernel speeds are
# shape-stable well below that, so the probe caps the one-time cost.
_PROBE_LENGTH = 1 << 18


def resolved_mask_kernel() -> str | None:
    """The mask kernel the last ``sum_masks`` call used (bench/telemetry)."""
    return _LAST_MASK_KERNEL


def calibrate_mask_kernel(
    seeds, length: int, config: MaskConfigPair, seed_batch: int = 8, mesh=None
) -> str:
    """Resolve (and memoize) the auto route for this shape NOW.

    ``sum_masks(kernel="auto")`` calibrates lazily inside its first call;
    steady-state measurements (tools/bench_round.py) call this first so the
    one-time probe race stays out of the per-round wall — exactly how a
    long-running participant amortizes it."""
    return _resolve_mask_kernel(seeds, length, config, seed_batch, mesh)


def _acc_unit(unit_acc, group_unit: np.ndarray, ol_u: np.ndarray) -> np.ndarray:
    """Fold one group's unit-limb sum into the running unit accumulator —
    the ONE accumulate idiom every route shares."""
    if unit_acc is None:
        return group_unit
    return host_limbs.mod_add(unit_acc[None, :], group_unit[None, :], ol_u)[0]


def _host_sampler_threads(n_items: int, default_cap: int) -> int:
    """Thread budget for the host sampler routes. An explicit
    ``XAYNET_NATIVE_THREADS`` pin wins OUTRIGHT (bounded only by the item
    count): it is the thread key the bench records in the gated
    BENCH_HISTORY series, so the code silently second-guessing it would
    relabel the experiment (the BENCH_r05 lesson) — and the operator who
    pins it owns any memory trade. The default is the core count capped
    at ``default_cap`` (the fused route passes a small cap because each
    thread holds an ``8 * length``-byte u64 partial accumulator, ~200 MB
    at 25M params)."""
    env = os.environ.get("XAYNET_NATIVE_THREADS", "")
    if env:
        try:
            return max(1, min(int(env), n_items))
        except ValueError:
            logger.warning("ignoring non-integer XAYNET_NATIVE_THREADS=%r", env)
    return max(1, min(os.cpu_count() or 1, n_items, default_cap))


def _mask_route(used: str, seeds, length, config, seed_batch, mesh):
    if used == "host-chunked":
        return _sum_masks(seeds, length, config, seed_batch)
    if used == "host-threaded":
        return _sum_masks_host_threaded(seeds, length, config, seed_batch)
    if used in ("fused-pallas", "fused-pallas-interpret"):
        return _sum_masks_fused(
            seeds, length, config, seed_batch, interpret=used == "fused-pallas-interpret"
        )
    return _sum_masks_batched(seeds, length, config, seed_batch, mesh)


def _resolve_mask_kernel(
    seeds, length: int, config: MaskConfigPair, seed_batch: int, mesh
) -> str:
    backend = jax.default_backend()
    bucket = min(max(1, seed_batch), len(seeds))
    # the mesh is part of the verdict's identity: the batch route's cost is
    # mesh-dependent, so a winner probed without a mesh must not be reused
    # for mesh-sharded calls (and vice versa)
    mesh_key = (
        None
        if mesh is None
        else (tuple(mesh.devices.shape), tuple(int(d.id) for d in mesh.devices.flat))
    )
    key = (backend, length, config, bucket, mesh_key)
    cached = _MASK_KERNEL_CACHE.get(key)
    if cached is not None:
        return cached
    # disk tier (utils.calibcache): a winner raced by a previous process
    # under the same environment fingerprint skips the probe race
    from ..utils import calibcache

    warm = calibcache.get("mask", key)
    if warm is not None:
        _MASK_KERNEL_CACHE[key] = warm
        logger.info("mask kernel resolved: %s (auto, persisted verdict)", warm)
        return warm
    probe_len = min(length, _PROBE_LENGTH)
    probe = list(seeds[:bucket])
    if backend == "cpu":
        # the interpret route is the CPU/CI leg of the fused kernel — raced
        # for real, so the fused pipeline stays continuously exercised and
        # wins exactly when it is actually faster; the threaded native
        # sampler is the CPU incumbent the in-graph routes must beat
        candidates = ["host-threaded", "batch", "fused-pallas-interpret"]
    else:
        candidates = ["batch", "fused-pallas", "host-threaded"]
    timings: dict[str, float] = {}
    with trace.get_tracer().span(
        SPAN_MASK_CALIBRATE, backend=backend, length=length, probe=probe_len
    ) as span:
        for name in candidates:
            try:
                fn = lambda name=name: _mask_route(name, probe, probe_len, config, seed_batch, mesh)
                fn()  # compile / first touch
                _, dt = profiling.measure(fn)
                timings[name] = dt
                profiling.record_calibration(f"mask-{name}", dt)
            except Exception as e:  # Mosaic/compile failure -> keep the others
                logger.warning(
                    "mask kernel %s unavailable: %s: %s", name, type(e).__name__, e
                )
        winner = min(timings, key=timings.get) if timings else "host-chunked"
        span.set(winner=winner)
    _MASK_KERNEL_CACHE[key] = winner
    from ..utils import calibcache

    calibcache.put("mask", key, winner)
    # the verdict is round-report material: a headline shift caused by a
    # verdict flip must be auditable from the report, not require a re-run
    round_report.record_mask_calibration(
        {
            "winner": winner,
            "backend": backend,
            "length": length,
            "bucket": bucket,
            "mesh": None if mesh_key is None else list(mesh_key[0]),
            "probe_length": probe_len,
            "probe_walls": {k: round(v, 6) for k, v in timings.items()},
        }
    )
    logger.info(
        "mask kernel auto-calibration (%s backend, probe %d): %s -> %s",
        backend,
        probe_len,
        {k: round(v, 4) for k, v in timings.items()},
        winner,
    )
    return winner


def sum_masks(
    seeds: list[bytes],
    length: int,
    config: MaskConfigPair,
    seed_batch: int = 8,
    kernel: str | None = None,
    mesh=None,
) -> tuple[np.ndarray, jax.Array]:
    """Derive and modularly sum the masks of many seeds (Sum2 hot loop).

    Returns (unit limbs, vector limbs) of the aggregated mask; every route
    is bit-identical to folding ``MaskSeed.derive_mask`` per seed.

    ``kernel`` picks the route (``utils.kernels.MASK_KERNELS``; ``None``
    honors ``XAYNET_MASK_KERNEL`` then defaults to ``auto``):

    - ``batch`` — ALL of a seed group's derivations (unit draws, cursor
      handoffs, vector draws) run in ONE jitted in-graph program
      (``derive_mask_limbs_batch``), and the resulting mask planes stream
      through the PR-7 shard pipeline (per-shard fold workers on a mesh);
    - ``fused-pallas[-interpret]`` — the Pallas keystream→reject→fold
      kernel: masks never materialize in HBM
      (``fold_pallas.mask_fold_planar_pallas``);
    - ``host-chunked`` — the pre-promotion path (host unit draws + chunked
      device vector derivation + ``aggregate_batch`` folds);
    - ``auto`` — races the candidates once per (backend, shape) on a probe
      group and memoizes the winner process-wide.

    Device memory is bounded by ``seed_batch * length`` mask elements
    (``batch``), one mask's chunk budget (``fused``), and device-synced
    timing is recorded as the ``mask_expand`` kernel op either way.
    """
    if not seeds:
        raise ValueError("no seeds to aggregate")
    if kernel is None:
        kernel = os.environ.get("XAYNET_MASK_KERNEL") or "auto"
    if kernel not in MASK_KERNELS:
        raise ValueError(f"kernel must be one of {MASK_KERNELS}, got {kernel!r}")
    if kernel == "auto":
        kernel = _resolve_mask_kernel(seeds, length, config, seed_batch, mesh)
    global _LAST_MASK_KERNEL
    _LAST_MASK_KERNEL = kernel
    with trace.get_tracer().span(
        SPAN_MASK_SUM, kernel=kernel, seeds=len(seeds), length=length
    ):
        return profiling.timed_kernel(
            "mask_expand",
            len(seeds) * length,
            lambda: _mask_route(kernel, seeds, length, config, seed_batch, mesh),
        )


def _sum_masks_batched(
    seeds: list[bytes], length: int, config: MaskConfigPair, seed_batch: int, mesh
) -> tuple[np.ndarray, np.ndarray]:
    """The promoted route: one jitted in-graph program per seed group, mask
    planes streamed through the PR-7 shard pipeline.

    Each group's units/cursors/vectors derive in ONE compiled program (no
    per-seed host loop), the group's wire-layout masks relayout to planar
    on device, and the shard pipeline folds them into the (mesh-sharded)
    planar accumulator — on a multi-device mesh each device folds its own
    model-axis slice, so the aggregated mask is reduced on-shard exactly
    like the update fold."""
    from ..parallel.aggregator import ShardedAggregator
    from ..parallel.streaming import StreamingAggregator

    step = max(1, seed_batch)
    agg = ShardedAggregator(config.vect, length, mesh=mesh, kernel="xla")
    stream = StreamingAggregator(agg, max_batch=max(2, step))
    ol_u = host_limbs.order_limbs_for(config.unit.order)
    unit_acc: np.ndarray | None = None
    try:
        for g0 in range(0, len(seeds), step):
            group = seeds[g0 : g0 + step]
            units, vects = derive_mask_limbs_batch(group, length, config)
            planar = _planarize_fn(length, agg.padded_length)(vects)
            _publish_compile_cache_gauge()
            stream.fold_planar_stack_now(planar)
            group_unit = host_limbs.batch_mod_sum(np.asarray(units)[:, None, :], ol_u)[0]
            unit_acc = _acc_unit(unit_acc, group_unit, ol_u)
        stream.drain()
        vect = agg.snapshot()
    finally:
        stream.close()
    assert unit_acc is not None
    return unit_acc, vect


def _sum_masks_host_fused(
    seeds: list[bytes], length: int, config: MaskConfigPair
) -> tuple[np.ndarray, np.ndarray] | None:
    """The native twin of the Pallas fused kernel: ``xn_sample_fold_u64``
    rejection-samples each seed's mask straight INTO a u64 accumulator —
    no mask bytes, no bytes→limbs pass, no stack, no separate fold read.
    Seeds split across threads with per-thread partial accumulators
    (disjoint memory; the GIL is released inside the native call), merged
    with the exact limb ``mod_add``. Returns ``None`` when the entry
    doesn't apply (no library, order wider than 8 bytes) so the caller
    falls back to the materializing wave path."""
    from ..utils import native

    lib = native.load()
    order = config.vect.order
    bpn = host_limbs.draw_width_for(order)
    # order > 2^63 can't even hold residual + one fold in u64 (2*order - 2
    # wraps), so the wave path serves those
    if (
        lib is None
        or bpn > 8
        or order > (1 << 63)
        or not hasattr(lib, "xn_sample_fold_u64")
    ):
        return None
    from concurrent.futures import ThreadPoolExecutor

    order_le = order.to_bytes(bpn, "little")
    ol_u = host_limbs.order_limbs_for(config.unit.order)
    n_limb = host_limbs.n_limbs_for_order(order)
    # u64 lazy-reduction headroom: the unreduced partial holds one reduced
    # residual (< order) plus up to `reduce_every` folds (< order each), so
    # (reduce_every + 1) * order must stay below 2^64 (>= 1 for any
    # order <= 2^63; huge for typical orders)
    reduce_every = max(1, (1 << 64) // order - 2)
    nt = _host_sampler_threads(len(seeds), default_cap=4)
    chunks = [seeds[i::nt] for i in range(nt)]

    def run_chunk(chunk: list[bytes]):
        acc = np.zeros(length, dtype=np.uint64)
        units = []
        since_reduce = 0
        for seed in chunk:
            sampler = StreamSampler(seed)
            units.append(sampler.draw_limbs(1, config.unit.order)[0])
            if since_reduce >= reduce_every:
                np.mod(acc, np.uint64(order), out=acc)
                since_reduce = 1
            else:
                since_reduce += 1
            end = lib.xn_sample_fold_u64(
                native.as_u8p(seed),
                sampler.consumed_bytes,
                length,
                native.as_u8p(order_le),
                bpn,
                native.np_u64p(acc),
            )
            if end == 0:  # out-of-range order: caller takes the wave path
                return None
        np.mod(acc, np.uint64(order), out=acc)
        return acc, units

    with ThreadPoolExecutor(max_workers=nt) as pool:
        results = list(pool.map(run_chunk, chunks))
    if any(r is None for r in results):
        return None

    def to_limbs(acc64: np.ndarray) -> np.ndarray:
        lo = (acc64 & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        if n_limb == 1:
            return lo[:, None]
        hi = (acc64 >> np.uint64(32)).astype(np.uint32)
        return np.stack([lo, hi], axis=1)

    ol_v = host_limbs.order_limbs_for(order)
    vect_acc: np.ndarray | None = None
    unit_acc: np.ndarray | None = None
    for acc64, units in results:
        part = to_limbs(acc64)
        vect_acc = part if vect_acc is None else host_limbs.mod_add(vect_acc, part, ol_v)
        for u in units:
            unit_acc = _acc_unit(unit_acc, u, ol_u)
    assert unit_acc is not None and vect_acc is not None
    return unit_acc, vect_acc


def _sum_masks_host_threaded(
    seeds: list[bytes], length: int, config: MaskConfigPair, seed_batch: int
) -> tuple[np.ndarray, np.ndarray]:
    """The CPU incumbent: the fused native sample+fold when it applies
    (``_sum_masks_host_fused`` — the mask never materializes), else
    per-seed derivations on the native (AVX2) ``StreamSampler`` across a
    GIL-released thread pool, folded per wave with the single-pass native
    batch fold. Memory stays bounded by ``seed_batch * length`` mask
    elements (one wave at a time) — the shape that lets 10k-seed Sum2
    legs run on a laptop."""
    fused = _sum_masks_host_fused(seeds, length, config)
    if fused is not None:
        return fused
    from concurrent.futures import ThreadPoolExecutor

    ol_v = host_limbs.order_limbs_for(config.vect.order)
    ol_u = host_limbs.order_limbs_for(config.unit.order)
    step = max(1, seed_batch)

    def derive(seed: bytes) -> tuple[np.ndarray, np.ndarray]:
        sampler = StreamSampler(seed)
        unit = sampler.draw_limbs(1, config.unit.order)[0]
        return unit, sampler.draw_limbs(length, config.vect.order)

    unit_acc: np.ndarray | None = None
    vect_acc: np.ndarray | None = None
    with ThreadPoolExecutor(max_workers=_host_sampler_threads(len(seeds), default_cap=8)) as pool:
        for g0 in range(0, len(seeds), step):
            group = seeds[g0 : g0 + step]
            pairs = list(pool.map(derive, group))
            units = np.stack([u for u, _ in pairs])
            vects = np.stack([v for _, v in pairs])
            pairs.clear()
            group_unit = host_limbs.batch_mod_sum(units[:, None, :], ol_u)[0]
            if vect_acc is None:
                vect_acc = host_limbs.batch_mod_sum(vects, ol_v)
                unit_acc = group_unit
            else:
                # batch + running accumulator in one native read; tree
                # fallback only for orders outside the single-pass kernels
                fast = host_limbs.fold_wire_batch_host(vect_acc, vects, ol_v)
                vect_acc = (
                    fast
                    if fast is not None
                    else host_limbs.mod_add(
                        vect_acc, host_limbs.batch_mod_sum(vects, ol_v), ol_v
                    )
                )
                unit_acc = _acc_unit(unit_acc, group_unit, ol_u)
    assert unit_acc is not None and vect_acc is not None
    return unit_acc, vect_acc


def _sum_masks_fused(
    seeds: list[bytes],
    length: int,
    config: MaskConfigPair,
    seed_batch: int,
    interpret: bool,
    chunk_candidates: int | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """The fused route: keystream→reject→fold in one Pallas kernel per seed
    group; the per-seed masks never materialize in HBM. Unit draws and the
    byte-cursor handoff run in-graph (``_unit_offsets_fn``) — no scalar
    host derivation anywhere on this path."""
    from . import fold_pallas
    from .fold_jax import planar_to_wire

    n_limb = host_limbs.n_limbs_for_order(config.vect.order)
    ol_u = host_limbs.order_limbs_for(config.unit.order)
    step = max(1, seed_batch)
    # seeds fold sequentially inside the kernel, so one seed's chunk budget
    # is the whole keystream footprint
    chunk = (
        chunk_candidates
        if chunk_candidates is not None
        else chacha_jax.provisioned_chunk(length, config.vect.order, 1)
    )
    acc = jnp.zeros((n_limb, length), dtype=jnp.uint32)
    unit_acc: np.ndarray | None = None
    unit_fn = _unit_offsets_fn(config)
    _publish_compile_cache_gauge()
    for g0 in range(0, len(seeds), step):
        group = seeds[g0 : g0 + step]
        kw = jnp.asarray(seed_words(group))
        units, offsets = unit_fn(kw)
        acc, _ends = fold_pallas.mask_fold_planar_pallas(
            acc,
            kw,
            offsets,
            length,
            config.vect.order,
            chunk_candidates=chunk,
            interpret=interpret,
        )
        group_unit = host_limbs.batch_mod_sum(np.asarray(units)[:, None, :], ol_u)[0]
        unit_acc = _acc_unit(unit_acc, group_unit, ol_u)
    assert unit_acc is not None
    return unit_acc, planar_to_wire(np.asarray(acc))


def _sum_masks(
    seeds: list[bytes], length: int, config: MaskConfigPair, seed_batch: int
) -> tuple[np.ndarray, jax.Array]:
    order_limbs_u = host_limbs.order_limbs_for(config.unit.order)
    order_limbs_v = host_limbs.order_limbs_for(config.vect.order)

    unit_acc: np.ndarray | None = None
    vect_acc: jax.Array | None = None
    for g0 in range(0, len(seeds), max(1, seed_batch)):
        group = seeds[g0 : g0 + max(1, seed_batch)]
        units, offsets = [], []
        for seed in group:
            # host unit draw first, exactly as MaskSeed.derive_mask orders
            # the keystream; the vector draw continues at the handed-off
            # byte cursor
            sampler = StreamSampler(seed)
            units.append(sampler.draw_limbs(1, config.unit.order)[0])
            offsets.append(sampler.consumed_bytes)
        vects = chacha_jax.derive_uniform_limbs_batch(
            group, length, config.vect.order, byte_offsets=offsets
        )
        group_unit = units[0]
        for u in units[1:]:
            group_unit = host_limbs.mod_add(group_unit[None, :], u[None, :], order_limbs_u)[0]
        if vect_acc is None:
            vect_acc = (
                limbs_jax.batch_mod_sum(vects, order_limbs_v) if len(group) > 1 else vects[0]
            )
            unit_acc = group_unit
        else:
            # one jitted kernel: tree-sum the group and fold it into the
            # donated accumulator (aggregate_batch), instead of eager
            # batch_mod_sum + mod_add dispatches per group
            vect_acc = limbs_jax.aggregate_batch(vect_acc, vects, order_limbs_v)
            unit_acc = host_limbs.mod_add(
                unit_acc[None, :], group_unit[None, :], order_limbs_u
            )[0]
    assert unit_acc is not None and vect_acc is not None
    return unit_acc, vect_acc
