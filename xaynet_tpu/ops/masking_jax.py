"""Device-side masking operations: mask expansion, aggregation, unmask.

Device counterparts of the reference hot loops (reference:
rust/xaynet-core/src/mask/seed.rs:61-78 derive_mask,
rust/xaynet-sdk/src/state_machine/phases/sum2.rs:170-193 mask aggregation,
rust/xaynet-server/src/state_machine/phases/unmask.rs unmask subtract).

Composes the ChaCha20 and limb kernels into the protocol-level device ops the
coordinator and sum participants run:

- ``derive_mask_limbs``: seed -> (unit element, vector limb tensor), the
  device version of ``MaskSeed.derive_mask`` (bit-identical keystream
  consumption: one unit draw on the host cursor, vector draws on device from
  the handed-off byte offset);
- ``unmask_vect_limbs``: modular subtract of the aggregated mask from the
  aggregated masked model (the Unmask-phase kernel);
- ``sum_masks``: aggregate many seed-derived masks (the Sum2 participant hot
  loop: #updates x model_length group elements).
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from ..core.crypto.prng import StreamSampler
from ..core.mask.config import MaskConfigPair
from ..core.mask.encode import clamp_scalar, encode_unit, encode_vect_limbs
from ..telemetry import profiling
from . import chacha_jax, limbs as host_limbs, limbs_jax


def derive_mask_limbs(
    seed: bytes, length: int, config: MaskConfigPair
) -> tuple[np.ndarray, jax.Array]:
    """Expand a 32-byte seed into (unit limbs [L1], vector limbs [length, L])."""
    sampler = StreamSampler(seed)
    unit = sampler.draw_limbs(1, config.unit.order)[0]
    offset = sampler.consumed_bytes
    vect = chacha_jax.derive_uniform_limbs(seed, length, config.vect.order, byte_offset=offset)
    return unit, vect


def derive_mask_ingraph(
    key_words: jax.Array,
    length: int,
    config: MaskConfigPair,
    unit_chunk: int | None = None,
    vect_chunk: int | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Fully in-graph ``MaskSeed.derive_mask``: (unit [L1], vect [length, L]).

    Pure traced code (no host syncs), composable under ``jit``/``vmap`` —
    the per-participant kernel the federated simulation maps across its
    participant axis. Keystream semantics are bit-identical to the scalar
    ``core/mask/seed.py`` reference: one unit-order draw first, then the
    vector draws resume at the traced in-graph byte cursor the unit draw
    handed off. The chunk knobs bound per-lane device memory; pass
    ``chacha_jax.provisioned_chunk(length, order, n_lanes)`` when vmapping
    ``n_lanes`` participants so the batch stays inside the chunk budget.
    """
    unit, offset = chacha_jax.derive_uniform_limbs_ingraph(
        key_words, jnp.int32(0), 1, config.unit.order, unit_chunk
    )
    vect, _ = chacha_jax.derive_uniform_limbs_ingraph(
        key_words, offset, length, config.vect.order, vect_chunk
    )
    return unit[0], vect


def seed_words(seeds: list[bytes]) -> np.ndarray:
    """32-byte seeds -> ``uint32[B, 8]`` little-endian ChaCha key words."""
    if not seeds:
        return np.zeros((0, 8), dtype=np.uint32)
    return np.stack([np.frombuffer(s, dtype="<u4") for s in seeds])


@lru_cache(maxsize=32)
def _mask_batch_fn(length: int, config: MaskConfigPair, lane_bucket: int):
    unit_chunk = chacha_jax.provisioned_chunk(1, config.unit.order, lane_bucket)
    vect_chunk = chacha_jax.provisioned_chunk(length, config.vect.order, lane_bucket)

    def one(kw):
        return derive_mask_ingraph(kw, length, config, unit_chunk, vect_chunk)

    return jax.jit(jax.vmap(one))


def derive_mask_limbs_batch(
    seeds: list[bytes], length: int, config: MaskConfigPair
) -> tuple[jax.Array, jax.Array]:
    """``derive_mask_limbs`` for many seeds in ONE jitted program.

    Returns (units ``uint32[B, L1]``, vects ``uint32[B, length, L]``);
    every row is bit-identical to ``MaskSeed.derive_mask`` with that seed
    (golden-pinned in tests/test_sim_round.py). Unlike ``sum_masks`` this
    never walks the seeds on the host — unit draws, cursor handoffs and
    vector draws are all in-graph — so it is the building block for
    whole-round simulation rather than the Sum2 aggregate.

    Compiled programs are cached per (length, config, pow2 lane bucket);
    the lane bucket also scales the chunk budget so large batches don't
    multiply the keystream footprint past the device-memory cap.
    """
    if not seeds:
        raise ValueError("no seeds")
    lane_bucket = 1 << (len(seeds) - 1).bit_length()
    fn = _mask_batch_fn(length, config, lane_bucket)
    return fn(jnp.asarray(seed_words(seeds)))


def encode_models_batch(
    weights: np.ndarray, scalar, config: MaskConfigPair
) -> tuple[np.ndarray, np.ndarray]:
    """Fixed-point encode a population of models in ONE vectorized pass.

    ``weights`` is ``[B, length]`` (every participant shares ``scalar``, the
    homogeneous-simulation shape); returns (unit limbs ``uint32[L1]`` — the
    encoded clamped scalar, identical for every lane — and vect limbs
    ``uint32[B, length, L]``). Byte-identical to ``B`` independent
    ``Masker.mask`` encodes because the fixed-point map is elementwise: the
    flattened array goes through the SAME production ``encode_vect_limbs``
    (double-double fast path for bounded f32, exact Fractions otherwise)
    that a single participant runs, then reshapes. Pinned against the
    scalar path in tests/test_sim_round.py.
    """
    weights = np.asarray(weights)
    if weights.ndim != 2:
        raise ValueError("weights must be [participants, length]")
    s_clamped = clamp_scalar(scalar, config.unit)
    flat = encode_vect_limbs(weights.reshape(-1), s_clamped, config.vect)
    vect = flat.reshape(weights.shape[0], weights.shape[1], -1)
    unit_int = encode_unit(s_clamped, config.unit)
    unit = host_limbs.int_to_limbs(unit_int, host_limbs.n_limbs_for_order(config.unit.order))
    return unit, vect


def unmask_vect_limbs(
    masked: jax.Array, mask: jax.Array, order: int
) -> jax.Array:
    """``(masked - mask) mod order`` elementwise over limb tensors."""
    return limbs_jax.mod_sub(masked, mask, host_limbs.order_limbs_for(order))


def sum_masks(
    seeds: list[bytes], length: int, config: MaskConfigPair, seed_batch: int = 8
) -> tuple[np.ndarray, jax.Array]:
    """Derive and modularly sum the masks of many seeds (Sum2 hot loop).

    Returns (unit limbs, vector limbs) of the aggregated mask.

    Seeds derive in groups of ``seed_batch`` through one vmapped keystream
    kernel per chunk round (``chacha_jax.derive_uniform_limbs_batch``), then
    each group folds with one ``batch_mod_sum`` pass — at the reference's
    10k-updates scale that is #updates/seed_batch kernel series instead of
    #updates (sum2.rs:170-193 is the per-seed loop this replaces). Device
    memory is bounded by ``seed_batch * length`` mask elements.

    Device-synced timing is recorded as the ``mask_expand`` kernel op
    (#seeds x length elements expanded and folded per call).
    """
    if not seeds:
        raise ValueError("no seeds to aggregate")
    return profiling.timed_kernel(
        "mask_expand", len(seeds) * length, lambda: _sum_masks(seeds, length, config, seed_batch)
    )


def _sum_masks(
    seeds: list[bytes], length: int, config: MaskConfigPair, seed_batch: int
) -> tuple[np.ndarray, jax.Array]:
    order_limbs_u = host_limbs.order_limbs_for(config.unit.order)
    order_limbs_v = host_limbs.order_limbs_for(config.vect.order)

    unit_acc: np.ndarray | None = None
    vect_acc: jax.Array | None = None
    for g0 in range(0, len(seeds), max(1, seed_batch)):
        group = seeds[g0 : g0 + max(1, seed_batch)]
        units, offsets = [], []
        for seed in group:
            # host unit draw first, exactly as MaskSeed.derive_mask orders
            # the keystream; the vector draw continues at the handed-off
            # byte cursor
            sampler = StreamSampler(seed)
            units.append(sampler.draw_limbs(1, config.unit.order)[0])
            offsets.append(sampler.consumed_bytes)
        vects = chacha_jax.derive_uniform_limbs_batch(
            group, length, config.vect.order, byte_offsets=offsets
        )
        group_unit = units[0]
        for u in units[1:]:
            group_unit = host_limbs.mod_add(group_unit[None, :], u[None, :], order_limbs_u)[0]
        if vect_acc is None:
            vect_acc = (
                limbs_jax.batch_mod_sum(vects, order_limbs_v) if len(group) > 1 else vects[0]
            )
            unit_acc = group_unit
        else:
            # one jitted kernel: tree-sum the group and fold it into the
            # donated accumulator (aggregate_batch), instead of eager
            # batch_mod_sum + mod_add dispatches per group
            vect_acc = limbs_jax.aggregate_batch(vect_acc, vects, order_limbs_v)
            unit_acc = host_limbs.mod_add(
                unit_acc[None, :], group_unit[None, :], order_limbs_u
            )[0]
    assert unit_acc is not None and vect_acc is not None
    return unit_acc, vect_acc
