"""Multi-limb finite-group arithmetic over numpy arrays (host path).

The reference stores masked models as ``Vec<BigUint>`` and aggregates them
with per-element big-integer modular adds (reference:
rust/xaynet-core/src/mask/masking.rs:292-316). The TPU-native design instead
represents a mask object as a fixed-width limb tensor

    ``uint32[n, L]``  (limb 0 = least-significant 32 bits)

so that aggregation is a flat, branch-free, vectorizable elementwise kernel:
limb add with carry propagation followed by a conditional subtract of the
group order. This module is the numpy host implementation and the conformance
oracle for the JAX/Pallas device kernels in ``xaynet_tpu.ops.limbs_jax``.
"""

from __future__ import annotations

import numpy as np

_U32 = np.uint32
_U64 = np.uint64
_MASK32 = np.uint64(0xFFFFFFFF)


def wire_width_for(order: int) -> int:
    """THE wire/pack width of one group element, in bytes:
    ``bytes_per_number = ceil(bits(order - 1) / 8)``.

    This module is the single source of truth for width math — the packed
    planar codec, the wire serializers, ``MaskConfig.bytes_per_number`` and
    the device unpack all derive from here, and the ``width`` lint rule
    (tools/analysis) rejects hand-computed copies of the expression
    anywhere else under ``xaynet_tpu/``.
    """
    return max(1, ((order - 1).bit_length() + 7) // 8)  # lint: width-ok


def draw_width_for(order: int) -> int:
    """The rejection-sampler DRAW width in bytes: the byte length of the
    order *itself* (the reference sizes its candidate buffer with
    ``max_int.to_bytes_le()``), which exceeds :func:`wire_width_for` when
    the order is a power of two at a byte boundary (e.g. 2^88, 2^96)."""
    return (order.bit_length() + 7) // 8  # lint: width-ok


def n_limbs_for_bytes(nbytes: int) -> int:
    """Byte width -> uint32 limb count (whole limbs)."""
    return max(1, (nbytes + 3) // 4)  # lint: width-ok


def n_limbs_for_order(order: int) -> int:
    """Number of 32-bit limbs for elements of the group of this order.

    Matches the wire width: ``bytes_per_number = ceil(bits(order - 1) / 8)``
    rounded up to whole limbs.
    """
    return n_limbs_for_bytes(wire_width_for(order))


def order_limbs_for(order: int) -> np.ndarray:
    """Group order as an L-limb constant for the modular kernels.

    When the order is exactly ``2^(32L)`` (e.g. 2^96 from the catalogue) it
    does not fit L limbs; the kernels then see all-zero limbs, which is
    correct: the reduction condition degenerates to the carry bit and the
    conditional subtract becomes the natural wraparound.
    """
    n_limb = n_limbs_for_order(order)
    if order == 1 << (32 * n_limb):
        return np.zeros(n_limb, dtype=_U32)
    return int_to_limbs(order, n_limb)


def all_lt_order(data: np.ndarray, order: int) -> bool:
    """``bool(np.all(elements_lt_order(data, order)))`` without the bool
    temporaries — native single-pass count of out-of-group elements (the
    per-update validity check on the coordinator's ingest path)."""
    n_limb = n_limbs_for_order(order)
    if order == 1 << (32 * n_limb):
        return True
    flat = np.ascontiguousarray(data.reshape(-1, n_limb), dtype=_U32)
    from ..utils import native

    lib = native.load()
    if lib is not None:
        ol = np.ascontiguousarray(int_to_limbs(order, n_limb))
        bad = lib.xn_count_ge(
            native.np_u32p(flat), flat.shape[0], n_limb, native.np_u32p(ol)
        )
        return bad == 0
    return bool(np.all(lt_const(flat, int_to_limbs(order, n_limb))))


def elements_lt_order(data: np.ndarray, order: int) -> np.ndarray:
    """Per-row validity ``element < order`` handling the 2^(32L) boundary."""
    n_limb = n_limbs_for_order(order)
    if order == 1 << (32 * n_limb):
        return np.ones(data.shape[:-1], dtype=bool)
    return lt_const(data, int_to_limbs(order, n_limb))


def int_to_limbs(value: int, n_limbs: int) -> np.ndarray:
    out = np.zeros(n_limbs, dtype=_U32)
    for i in range(n_limbs):
        out[i] = (value >> (32 * i)) & 0xFFFFFFFF
    if value >> (32 * n_limbs):
        raise OverflowError("value does not fit in the limb width")
    return out


def limbs_to_int(limbs: np.ndarray) -> int:
    value = 0
    for i in range(limbs.shape[-1] - 1, -1, -1):
        value = (value << 32) | int(limbs[..., i])
    return value


def ints_to_limbs(values, n_limbs: int) -> np.ndarray:
    """Convert an iterable of python ints to a ``uint32[n, L]`` limb array."""
    values = list(values)
    out = np.zeros((len(values), n_limbs), dtype=_U32)
    for i, v in enumerate(values):
        for j in range(n_limbs):
            out[i, j] = (v >> (32 * j)) & 0xFFFFFFFF
        if v >> (32 * n_limbs):
            raise OverflowError("value does not fit in the limb width")
    return out


def limbs_to_ints(arr: np.ndarray) -> list[int]:
    arr = np.asarray(arr, dtype=_U32)
    n, n_limb = arr.shape
    out = [0] * n
    for j in range(n_limb - 1, -1, -1):
        col = arr[:, j]
        for i in range(n):
            out[i] = (out[i] << 32) | int(col[i])
    return out


def bytes_le_to_limbs(buf: bytes | np.ndarray, count: int, bytes_per_number: int) -> np.ndarray:
    """Parse ``count`` fixed-width little-endian integers into ``uint32[count, L]``.

    Native single-pass codec when available (~memory bandwidth; the numpy
    pad/slice path measures ~370 MB/s and parse sits on the coordinator's
    per-update critical path — one 25M-param update is a 150 MB payload).
    """
    n_limb = n_limbs_for_bytes(bytes_per_number)
    raw = np.frombuffer(buf, dtype=np.uint8, count=count * bytes_per_number)
    from ..utils import native

    lib = native.load()
    if lib is not None and count > 0:
        raw_c = np.ascontiguousarray(raw)
        out = np.empty((count, n_limb), dtype=_U32)
        lib.xn_wire_to_limbs(
            native.np_u8p(raw_c), count, bytes_per_number, n_limb, native.np_u32p(out)
        )
        return out
    padded = np.zeros((count, n_limb * 4), dtype=np.uint8)
    padded[:, :bytes_per_number] = raw.reshape(count, bytes_per_number)
    return padded.view("<u4").astype(_U32, copy=False)


def limbs_to_bytes_le(arr: np.ndarray, bytes_per_number: int) -> bytes:
    """Serialize ``uint32[n, L]`` limbs as fixed-width little-endian integers."""
    arr = np.ascontiguousarray(np.asarray(arr, dtype=_U32))
    n = arr.shape[0]
    from ..utils import native

    lib = native.load()
    # native codec assumes the wire width and limb count agree (L == ceil(bpn/4))
    if lib is not None and n > 0 and arr.shape[1] == n_limbs_for_bytes(bytes_per_number):
        out = np.empty(n * bytes_per_number, dtype=np.uint8)
        lib.xn_limbs_to_wire(
            native.np_u32p(arr), n, bytes_per_number, arr.shape[1], native.np_u8p(out)
        )
        return out.tobytes()
    raw = arr.astype("<u4").view(np.uint8).reshape(n, -1)
    return raw[:, :bytes_per_number].tobytes()


def lt_const(a: np.ndarray, order_limbs: np.ndarray) -> np.ndarray:
    """Lexicographic ``a < order`` per element, over the trailing limb axis."""
    shape = a.shape[:-1]
    lt = np.zeros(shape, dtype=bool)
    decided = np.zeros(shape, dtype=bool)
    for j in range(a.shape[-1] - 1, -1, -1):
        col = a[..., j]
        o = order_limbs[j]
        lt |= (~decided) & (col < o)
        decided |= col != o
    return lt


def add_limbs(a: np.ndarray, b: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Limbwise ``a + b`` with carry propagation; returns (sum, carry_out)."""
    n_limb = a.shape[-1]
    out = np.empty_like(a)
    carry = np.zeros(a.shape[:-1], dtype=_U64)
    for j in range(n_limb):
        s = a[..., j].astype(_U64) + b[..., j].astype(_U64) + carry
        out[..., j] = (s & _MASK32).astype(_U32)
        carry = s >> np.uint64(32)
    return out, carry.astype(_U32)


def sub_limbs(a: np.ndarray, b: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Limbwise ``a - b`` with borrow propagation; returns (diff, borrow_out)."""
    n_limb = a.shape[-1]
    out = np.empty_like(a)
    borrow = np.zeros(a.shape[:-1], dtype=_U64)
    for j in range(n_limb):
        d = a[..., j].astype(_U64) - b[..., j].astype(_U64) - borrow
        out[..., j] = (d & _MASK32).astype(_U32)
        borrow = (d >> np.uint64(63)) & np.uint64(1)  # underflow wraps in u64
    return out, borrow.astype(_U32)


def _native_binop(name: str, a: np.ndarray, b: np.ndarray, order_limbs: np.ndarray):
    """Run an elementwise modular op in the native library when possible.

    Any leading batch dimensions flatten into the element axis (the op is
    elementwise over rows of L limbs).
    """
    if a.ndim < 2 or a.shape != b.shape or a.shape[-1] != order_limbs.shape[0]:
        return None
    from ..utils import native

    lib = native.load()
    if lib is None:
        return None
    shape = a.shape
    a = np.ascontiguousarray(a, dtype=_U32).reshape(-1, shape[-1])
    b = np.ascontiguousarray(b, dtype=_U32).reshape(-1, shape[-1])
    ol = np.ascontiguousarray(order_limbs, dtype=_U32)
    out = np.empty_like(a)
    getattr(lib, name)(
        native.np_u32p(a),
        native.np_u32p(b),
        native.np_u32p(out),
        a.shape[0],
        a.shape[1],
        native.np_u32p(ol),
    )
    return out.reshape(shape)


def mod_add(a: np.ndarray, b: np.ndarray, order_limbs: np.ndarray) -> np.ndarray:
    """``(a + b) mod order`` assuming ``a, b < order`` (branch-free)."""
    fast = _native_binop("xn_mod_add", a, b, order_limbs)
    if fast is not None:
        return fast
    s, carry = add_limbs(a, b)
    # sum >= order  <=>  carry set (sum overflowed the limb width) or s >= order
    ge = carry.astype(bool) | ~lt_const(s, order_limbs)
    d, _ = sub_limbs(s, np.broadcast_to(order_limbs, s.shape))
    return np.where(ge[..., None], d, s)


def mod_sub(a: np.ndarray, b: np.ndarray, order_limbs: np.ndarray) -> np.ndarray:
    """``(a - b) mod order`` assuming ``a, b < order``."""
    fast = _native_binop("xn_mod_sub", a, b, order_limbs)
    if fast is not None:
        return fast
    d, borrow = sub_limbs(a, b)
    d2, _ = add_limbs(d, np.broadcast_to(order_limbs, d.shape))
    return np.where(borrow.astype(bool)[..., None], d2, d)


def batch_mod_sum(stack: np.ndarray, order_limbs: np.ndarray) -> np.ndarray:
    """Modular sum over axis 0 of ``uint32[K, n, L]``.

    Native single-pass fold when available (u64 kernel for <=2-limb
    orders, generic n-limb kernel for the rest); numpy pairwise tree
    reduce otherwise — each pairwise step keeps every element ``< order``,
    so the depth is ``ceil(log2 K)`` and every level is a flat elementwise
    kernel.
    """
    if stack.shape[0] > 1:
        fast = fold_wire_batch_host(
            np.zeros_like(stack[0]), stack, order_limbs
        )
        if fast is not None:
            return fast
    while stack.shape[0] > 1:
        k = stack.shape[0]
        half = k // 2
        merged = mod_add(stack[:half], stack[half : 2 * half], order_limbs)
        if k % 2:
            merged = np.concatenate([merged, stack[2 * half :]], axis=0)
        stack = merged
    return stack[0]


def native_fold_threads() -> int:
    """The native library's process-wide fold worker budget
    (``XAYNET_NATIVE_THREADS`` or its 2x-cores default), or 1 when the
    library is unavailable. The shard planner divides this into per-shard
    budgets instead of re-implementing the policy in Python."""
    from ..utils import native

    lib = native.load()
    return int(lib.xn_fold_threads()) if lib is not None else 1


def u64_fold_applicable(k: int, n_limb: int, order_limbs: np.ndarray) -> bool:
    """Whether the native single-pass u64 fold is exact for this shape: a
    <= 2-limb order whose K+1-term running sum fits u64 (pow2-boundary
    orders — all-zero limbs — wrap exactly for any K)."""
    if n_limb > 2:
        return False
    if not np.any(order_limbs):
        return True
    order = limbs_to_int(order_limbs)
    return (k + 1) <= ((1 << 64) // order)


def fold_planar_slice_host(
    acc: np.ndarray,
    stack: np.ndarray,
    out: np.ndarray,
    col0: int,
    col1: int,
    order_limbs: np.ndarray,
    n_threads: int = 0,
    acc_cols: int | None = None,
) -> bool:
    """Fold the model-axis column slice ``[col0, col1)`` of the planar
    ``uint32[K, L, n]`` batch into the same slice of ``acc``, writing
    ``out`` — reading the batch IN PLACE through its strides, so one
    shard's fold touches zero bytes outside its slice and the staged batch
    is never copied per shard.

    ``acc``/``out`` are either full-width ``[L, n]`` buffers (the slice is
    addressed at ``col0``) or contiguous per-shard ``[L, col1-col0]``
    buffers (pass ``acc_cols=col1-col0``; the slice starts at column 0 —
    the donated per-shard accumulators of the sharded streaming fold).
    ``n_threads`` > 0 pins this call's native worker count (the per-shard
    budget when shard folds run concurrently); 0 keeps the process default.

    Returns False when no native path applies (caller falls back to a
    copy + :func:`fold_planar_batch_host`); requirements otherwise match
    the u64 kernel (use :func:`u64_fold_applicable`).
    """
    k, n_limb, n = stack.shape
    width = col1 - col0
    a_cols = acc_cols if acc_cols is not None else n
    if acc.shape != (n_limb, a_cols) or out.shape != acc.shape:
        raise ValueError("accumulator/out shape mismatch")
    if not (acc.flags.c_contiguous and out.flags.c_contiguous and stack.flags.c_contiguous):
        raise ValueError("slice fold requires C-contiguous buffers")
    if out is acc:
        raise ValueError("out must not alias acc")
    if not u64_fold_applicable(k, n_limb, order_limbs):
        return False
    from ..utils import native

    lib = native.load()
    if lib is None:
        return False
    off = 0 if acc_cols is not None else col0
    lib.xn_fold_planar_u64_strided(
        native.np_u32p_at(acc, off),
        native.np_u32p_at(stack, col0),
        native.np_u32p_at(out, off),
        width,
        a_cols,  # acc/out plane stride
        n,  # stack row (limb-plane) stride
        n_limb * n,  # stack batch (update) stride
        n_limb,
        k,
        native.np_u32p(np.ascontiguousarray(order_limbs, dtype=_U32)),
        max(0, int(n_threads)),
    )
    return True


def fold_planar_batch_host(
    acc: np.ndarray, stack: np.ndarray, order_limbs: np.ndarray,
    out: np.ndarray | None = None, n_threads: int = 0,
) -> np.ndarray:
    """Single-pass host fold of planar ``uint32[K, L, n]`` updates into the
    planar ``uint32[L, n]`` accumulator (host analogue of
    ``ops.fold_jax.fold_planar_batch``; reference hot loop:
    rust/xaynet-core/src/mask/masking.rs:292-316).

    Native fast path for orders that fit 64 bits (every 1-2 limb config) —
    reads the batch once instead of XLA-CPU's strided half-word reduction
    or the ``ceil(log2 K)``-pass pairwise tree. Falls back to the pairwise
    numpy tree otherwise.

    ``out`` optionally receives the result (contiguous, same shape/dtype as
    ``acc``, not aliasing ``acc``): at 25M params a fresh 200 MB result
    buffer costs ~0.15 s of page faults per fold, so steady-state callers
    (the aggregator's native kernel) ping-pong two buffers instead. Only
    the native path honors it; callers must use the RETURNED array either
    way. ``n_threads`` > 0 pins the native worker count for this call (the
    per-shard budget of the sharded streaming fold); 0 keeps the process
    default.
    """
    k, n_limb, n = stack.shape
    if acc.shape != (n_limb, n):
        raise ValueError("accumulator/batch shape mismatch")
    if u64_fold_applicable(k, n_limb, order_limbs):
        from ..utils import native

        lib = native.load()
        if lib is not None:
            acc_c = np.ascontiguousarray(acc, dtype=_U32)
            stack_c = np.ascontiguousarray(stack, dtype=_U32)
            if (
                out is not None
                and out.shape == acc_c.shape
                and out.dtype == _U32
                and out.flags.c_contiguous
                and out is not acc_c
            ):
                pass  # reuse the caller's spare buffer
            else:
                out = np.empty_like(acc_c)
            lib.xn_fold_planar_u64_strided(
                native.np_u32p(acc_c),
                native.np_u32p(stack_c),
                native.np_u32p(out),
                n,
                n,  # acc/out plane stride (full width)
                n,  # stack row stride
                n_limb * n,  # stack batch stride
                n_limb,
                k,
                native.np_u32p(np.ascontiguousarray(order_limbs, dtype=_U32)),
                max(0, int(n_threads)),
            )
            return out
    # fallback: wire layout pairwise tree (exact for any limb count)
    wire = np.ascontiguousarray(stack.transpose(0, 2, 1))
    folded = batch_mod_sum(wire, order_limbs)
    acc_wire = np.ascontiguousarray(acc.T)
    return np.ascontiguousarray(mod_add(acc_wire, folded, order_limbs).T)


# ---------------------------------------------------------------------------
# packed planar codec
#
# Masked limb CONTENTS are uniform-random and incompressible, but the
# REPRESENTATION is not: group orders rarely fill their uint32 limbs, so a
# planar ``uint32[..., L, n]`` tensor packs losslessly to the wire width
# ``bpn = wire_width_for(order)`` bytes per element (6 instead of 8 for the
# standard 2-limb f32 configs — a 25% cut in staged/transferred bytes).
# The packed layout is BYTE-PLANAR ``uint8[..., bpn, n]``: byte-plane b
# holds byte b of every element, so pack/unpack are strided plane copies
# (no per-element gather), the device unpack is the same shift-or chain as
# the wire unpack but over contiguous planes, and the native packed fold
# streams bpn unit-stride byte planes exactly like the planar u64 fold
# streams its limb planes. Lossless iff every element < 2^(8*bpn) — true
# for every validated group element (element < order <= 2^(8*bpn)).
# ---------------------------------------------------------------------------


def pack_planar(planar: np.ndarray, bpn: int, out: np.ndarray | None = None) -> np.ndarray:
    """Planar ``uint32[..., L, n]`` -> packed byte-planar ``uint8[..., bpn, n]``.

    ``out`` optionally receives the result (the streaming pipeline packs
    straight into its ring buffers). Elements must be < 2^(8*bpn) (i.e.
    validated group elements); higher bytes are DROPPED by design.
    """
    planar = np.asarray(planar, dtype=_U32)
    n_limb, n = planar.shape[-2], planar.shape[-1]
    if bpn > 4 * n_limb:
        raise ValueError("pack width exceeds the limb width")
    if out is None:
        out = np.empty((*planar.shape[:-2], bpn, n), dtype=np.uint8)
    if (
        planar.ndim == 2
        and planar.flags.c_contiguous
        and out.ndim == 2
        and out.strides[-1] == 1
        and _native_pack_planar(planar, bpn, out)
    ):
        return out
    if planar.flags.c_contiguous:
        # little-endian u32 planes viewed as bytes: element i's byte b lives
        # at [..., b // 4, 4 * i + (b % 4)] — one strided plane copy per
        # byte-plane, no arithmetic temporaries
        raw = planar.view(np.uint8)
        for b in range(bpn):
            out[..., b, :] = raw[..., b // 4, b % 4 :: 4]
    else:
        # strided views (a transposed wire slice): shift-and-mask per plane
        for b in range(bpn):
            out[..., b, :] = (
                (planar[..., b // 4, :] >> _U32(8 * (b % 4))) & _U32(0xFF)
            ).astype(np.uint8)
    return out


def _native_pack_planar(planar: np.ndarray, bpn: int, out: np.ndarray) -> bool:
    """Native plane pack of one contiguous planar ``[L, n]`` into byte-planar
    ``out[bpn, *]`` (row stride from ``out.strides[0]``)."""
    from ..utils import native

    lib = native.load()
    if lib is None or not hasattr(lib, "xn_pack_planar_planes"):
        return False
    lib.xn_pack_planar_planes(
        native.np_u32p(planar),
        planar.shape[-1],
        planar.shape[-1],  # input plane stride
        bpn,
        native.np_u8p(out),
        out.strides[0],
        0,
    )
    return True


def pack_planar_slice(
    planar: np.ndarray,
    lo: int,
    hi: int,
    bpn: int,
    out: np.ndarray,
    n_threads: int = 0,
) -> np.ndarray:
    """Pack the column slice ``[lo, hi)`` of one contiguous planar
    ``uint32[L, n]`` row into byte-planar ``out[bpn, >= hi-lo]`` in place
    (native plane kernel: unit-stride reads AND writes; shift-and-mask
    numpy fallback)."""
    n_limb, n = planar.shape
    width = hi - lo
    if bpn > 4 * n_limb:
        raise ValueError("pack width exceeds the limb width")
    view = out[:, :width]
    from ..utils import native

    lib = native.load()
    if (
        lib is not None
        and hasattr(lib, "xn_pack_planar_planes")
        and planar.flags.c_contiguous
        and out.strides[-1] == 1
    ):
        lib.xn_pack_planar_planes(
            native.np_u32p_at(planar, lo),
            width,
            n,  # input plane stride
            bpn,
            native.np_u8p(view),
            out.strides[0],
            max(0, int(n_threads)),
        )
        return view
    for b in range(bpn):
        view[b, :] = (
            (planar[b // 4, lo:hi] >> _U32(8 * (b % 4))) & _U32(0xFF)
        ).astype(np.uint8)
    return view


def pack_wire_slice(
    stack: np.ndarray,
    lo: int,
    hi: int,
    bpn: int,
    out: np.ndarray,
    n_threads: int = 0,
) -> np.ndarray:
    """Pack the element-column slice ``[lo, hi)`` of a wire-layout
    ``uint32[K, n, L]`` batch into byte-planar ``out[K, bpn, >= hi-lo]``
    IN PLACE through its strides — the per-shard staging-ring pack of the
    streaming pipeline. Native kernel when available (plane-major
    unit-stride writes, ~memcpy speed; numpy's byte gather for the same
    copy measures ~3x a planar transpose), strided numpy copy otherwise.
    """
    k, n, n_limb = stack.shape
    width = hi - lo
    if bpn > 4 * n_limb:
        raise ValueError("pack width exceeds the limb width")
    if not stack.flags.c_contiguous:
        stack = np.ascontiguousarray(stack, dtype=_U32)
    from ..utils import native

    lib = native.load()
    view = out[:, :, :width]
    if (
        lib is not None
        and hasattr(lib, "xn_pack_wire_planes")
        and out.strides[-1] == 1
    ):
        for i in range(k):
            lib.xn_pack_wire_planes(
                native.np_u32p_at(stack, (i * n + lo) * n_limb),
                width,
                n_limb,
                bpn,
                native.np_u8p_at(out, i * out.strides[0]),
                out.strides[1],
                max(0, int(n_threads)),
            )
        return view
    raw = stack.view(np.uint8)  # [K, n, 4L]
    view[...] = np.moveaxis(raw[:, lo:hi, :bpn], -1, -2)
    return view


def pack_wire(stack: np.ndarray, bpn: int, out: np.ndarray | None = None) -> np.ndarray:
    """Wire-layout ``uint32[..., n, L]`` -> packed byte-planar
    ``uint8[..., bpn, n]`` (the staging-ring pack for wire-layout submit
    paths: byte b of element i is byte ``b`` of its little-endian wire
    row). Native plane-pack kernel for the 3-D batch shape, one strided
    numpy transpose copy otherwise."""
    stack = np.ascontiguousarray(stack, dtype=_U32)
    n_limb = stack.shape[-1]
    if bpn > 4 * n_limb:
        raise ValueError("pack width exceeds the limb width")
    if out is None:
        out = np.empty((*stack.shape[:-2], bpn, stack.shape[-2]), dtype=np.uint8)
    if stack.ndim == 3 and out.ndim == 3:
        return pack_wire_slice(stack, 0, stack.shape[1], bpn, out)
    raw = stack.view(np.uint8)  # [..., n, 4L]
    out[...] = np.moveaxis(raw[..., :bpn], -1, -2)
    return out


def unpack_planar(packed: np.ndarray, n_limbs: int, out: np.ndarray | None = None) -> np.ndarray:
    """Packed byte-planar ``uint8[..., bpn, n]`` -> planar ``uint32[..., L, n]``."""
    packed = np.asarray(packed, dtype=np.uint8)
    bpn, n = packed.shape[-2], packed.shape[-1]
    if n_limbs < n_limbs_for_bytes(bpn):
        raise ValueError("limb width too small for the packed width")
    if out is None or not out.flags.c_contiguous:
        out = np.zeros((*packed.shape[:-2], n_limbs, n), dtype=_U32)
    else:
        out[...] = 0
    raw = out.view(np.uint8)
    for b in range(bpn):
        raw[..., b // 4, b % 4 :: 4] = packed[..., b, :]
    return out


def fold_packed_slice_host(
    acc: np.ndarray,
    packed: np.ndarray,
    out: np.ndarray,
    col0: int,
    col1: int,
    order_limbs: np.ndarray,
    n_threads: int = 0,
    acc_cols: int | None = None,
) -> bool:
    """Fold the column slice ``[col0, col1)`` of a PACKED byte-planar
    ``uint8[K, bpn, n]`` batch into the planar ``uint32[L, *]`` accumulator
    slice — the native single-pass u64 fold reading the packed bytes in
    place (25% less batch traffic at bpn=6 vs the unpacked planar fold).

    Buffer addressing matches :func:`fold_planar_slice_host`; returns False
    when no native path applies (caller unpacks and takes the planar fold).
    Requirements: u64-applicable order (<= 2 limbs, K+1 headroom) and
    ``bpn <= 8``.
    """
    k, bpn, n = packed.shape
    width = col1 - col0
    n_limb = acc.shape[0]
    a_cols = acc_cols if acc_cols is not None else n
    if acc.shape != (n_limb, a_cols) or out.shape != acc.shape:
        raise ValueError("accumulator/out shape mismatch")
    if not (acc.flags.c_contiguous and out.flags.c_contiguous and packed.flags.c_contiguous):
        raise ValueError("packed slice fold requires C-contiguous buffers")
    if out is acc:
        raise ValueError("out must not alias acc")
    if bpn > 8 or not u64_fold_applicable(k, n_limb, order_limbs):
        return False
    from ..utils import native

    lib = native.load()
    if lib is None or not hasattr(lib, "xn_fold_packed_u64_strided"):
        return False
    off = 0 if acc_cols is not None else col0
    lib.xn_fold_packed_u64_strided(
        native.np_u32p_at(acc, off),
        native.np_u8p_at(packed, col0),
        native.np_u32p_at(out, off),
        width,
        a_cols,  # acc/out plane stride (elements)
        n,  # packed byte-plane stride (bytes)
        bpn * n,  # packed batch (update) stride (bytes)
        n_limb,
        bpn,
        k,
        native.np_u32p(np.ascontiguousarray(order_limbs, dtype=_U32)),
        max(0, int(n_threads)),
    )
    return True


def fold_packed_batch_host(
    acc: np.ndarray,
    packed: np.ndarray,
    order_limbs: np.ndarray,
    out: np.ndarray | None = None,
    n_threads: int = 0,
) -> np.ndarray:
    """Single-pass host fold of PACKED byte-planar ``uint8[K, bpn, n]``
    updates into the planar ``uint32[L, n]`` accumulator.

    Native fast path reads the packed bytes directly (the fold's dominant
    cost is the one mandatory read of the batch, and packed planes are
    ``bpn / 4L`` of the unpacked bytes); without it the batch unpacks once
    on the host and takes :func:`fold_planar_batch_host`. ``out``/
    ``n_threads`` behave exactly like the planar fold's.
    """
    k, bpn, n = packed.shape
    n_limb = acc.shape[0]
    if acc.shape != (n_limb, n):
        raise ValueError("accumulator/batch shape mismatch")
    acc_c = np.ascontiguousarray(acc, dtype=_U32)
    packed_c = np.ascontiguousarray(packed, dtype=np.uint8)
    if (
        out is not None
        and out.shape == acc_c.shape
        and out.dtype == _U32
        and out.flags.c_contiguous
        and out is not acc_c
    ):
        pass  # reuse the caller's spare buffer
    else:
        out = np.empty_like(acc_c)
    if fold_packed_slice_host(
        acc_c, packed_c, out, 0, n, order_limbs, n_threads=n_threads
    ):
        return out
    # no native packed path: one host unpack, then the planar fold (which
    # may still take its own native or pairwise route)
    planar = unpack_planar(packed_c, n_limb)
    return fold_planar_batch_host(acc_c, planar, order_limbs, out=out, n_threads=n_threads)


def fold_wire_batch_host(
    acc: np.ndarray, stack: np.ndarray, order_limbs: np.ndarray
) -> np.ndarray | None:
    """Native single-pass fold over wire-layout ``uint32[K, n, L]`` into the
    wire ``uint32[n, L]`` accumulator; None when no native path applies
    (callers fall back to the pairwise tree).

    For 2-limb configs a wire row is one little-endian u64, so every access
    is a contiguous 8-byte load; multi-limb orders (f64 families through
    the 44-limb Bmax) take the generic blocked n-limb kernel. Either way:
    no transposes, one read of the batch.
    """
    k, n, n_limb = stack.shape
    if acc.shape != (n, n_limb):
        return None
    from ..utils import native

    lib = native.load()
    if lib is None:
        return None
    order = limbs_to_int(order_limbs) or (1 << (32 * n_limb))
    # generic single-pass kernel for any limb count (f64 families through
    # the 44-limb Bmax order) and for 2-limb orders whose running sum
    # overflows u64; the u64 kernel otherwise
    generic = n_limb > 2 or (np.any(order_limbs) and (k + 1) > ((1 << 64) // order))
    if generic and (n_limb > 63 or k > 65535):
        return None
    acc_c = np.ascontiguousarray(acc, dtype=_U32)
    stack_c = np.ascontiguousarray(stack, dtype=_U32)
    out = np.empty_like(acc_c)
    args = (
        native.np_u32p(acc_c),
        native.np_u32p(stack_c),
        native.np_u32p(out),
        n,
        n_limb,
        k,
        native.np_u32p(np.ascontiguousarray(order_limbs, dtype=_U32)),
    )
    if generic:
        return out if lib.xn_fold_wire_nlimb(*args) == 0 else None
    lib.xn_fold_wire_u64(*args)
    return out
