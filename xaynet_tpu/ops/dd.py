"""Vectorized double-double (~106-bit) arithmetic over numpy float64.

The reference does its fixed-point conversions in exact big-rational
arithmetic (reference: rust/xaynet-core/src/mask/masking.rs:358-404). The
TPU-native fast path instead computes the conversion in double-double
precision: plain f64 would lose up to ~4e-7 absolute on the worst bounded-f32
configs (value range 4e19, tolerance 1e-7), while double-double keeps the
error ~1e-23 — far below the protocol tolerance of ``1/exp_shift``.

Representation: a value is ``(hi, lo)`` with ``hi + lo`` the value and
``|lo| <= ulp(hi)/2``. All functions are elementwise over numpy arrays.
"""

from __future__ import annotations

import numpy as np

_SPLITTER = 134217729.0  # 2^27 + 1


def two_sum(a, b):
    s = a + b
    bb = s - a
    err = (a - (s - bb)) + (b - bb)
    return s, err


def quick_two_sum(a, b):
    """Requires |a| >= |b|."""
    s = a + b
    err = b - (s - a)
    return s, err


def _split(a):
    c = _SPLITTER * a
    hi = c - (c - a)
    lo = a - hi
    return hi, lo


def two_prod(a, b):
    p = a * b
    ah, al = _split(a)
    bh, bl = _split(b)
    err = ((ah * bh - p) + ah * bl + al * bh) + al * bl
    return p, err


def dd(hi, lo=0.0):
    hi64 = np.asarray(hi, dtype=np.float64)
    return hi64, np.asarray(lo, dtype=np.float64) * np.ones_like(hi64)


def from_fraction(f) -> tuple[float, float]:
    """Scalar Fraction/int -> double-double (exact to ~106 bits)."""
    from fractions import Fraction

    f = Fraction(f)
    hi = float(f)
    lo = float(f - Fraction(hi))
    return hi, lo


def from_fraction_scaled(f) -> tuple[float, float, int]:
    """Scalar Fraction -> (m_hi, m_lo, k) with value = (m_hi + m_lo) * 2^k.

    The mantissa is normalized into [1/2, 2), so fractions whose magnitude
    over- or under-flows float64 (e.g. the reciprocal of a BMAX exp_shift)
    are still represented exactly to ~106 bits; the caller applies ``2^k``
    via ldexp after its multiplications.
    """
    from fractions import Fraction

    f = Fraction(f)
    if f == 0:
        return 0.0, 0.0, 0
    k = f.numerator.bit_length() - f.denominator.bit_length()
    m = f / Fraction(2) ** k  # |m| in [1/2, 2)
    hi = float(m)
    lo = float(m - Fraction(hi))
    return hi, lo, k


def add(a_hi, a_lo, b_hi, b_lo):
    s, e = two_sum(a_hi, b_hi)
    e = e + a_lo + b_lo
    return quick_two_sum(s, e)


def sub(a_hi, a_lo, b_hi, b_lo):
    return add(a_hi, a_lo, -b_hi, -b_lo)


def add_f(a_hi, a_lo, f):
    s, e = two_sum(a_hi, f)
    e = e + a_lo
    return quick_two_sum(s, e)


def mul(a_hi, a_lo, b_hi, b_lo):
    p, e = two_prod(a_hi, b_hi)
    e = e + a_hi * b_lo + a_lo * b_hi
    return quick_two_sum(p, e)


def mul_f(a_hi, a_lo, f):
    p, e = two_prod(a_hi, f)
    e = e + a_lo * f
    return quick_two_sum(p, e)


def div(a_hi, a_lo, b_hi, b_lo):
    q1 = a_hi / b_hi
    # r = a - b*q1
    p_hi, p_lo = mul_f(b_hi, b_lo, q1)
    r_hi, r_lo = sub(a_hi, a_lo, p_hi, p_lo)
    q2 = r_hi / b_hi
    p_hi, p_lo = mul_f(b_hi, b_lo, q2)
    r_hi, r_lo = sub(r_hi, r_lo, p_hi, p_lo)
    q3 = r_hi / b_hi
    q_hi, q_lo = quick_two_sum(q1, q2)
    return add_f(q_hi, q_lo, q3)


def floor(a_hi, a_lo):
    """Elementwise floor of a double-double, returned as f64 (exact integer)."""
    f = np.floor(a_hi)
    frac = (a_hi - f) + a_lo  # a_hi - f is exact
    return f + np.floor(frac)


def to_float(a_hi, a_lo):
    return a_hi + a_lo
