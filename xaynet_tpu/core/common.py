"""Shared protocol types: round parameters and round seed.

Reference: rust/xaynet-core/src/common.rs:8-47 and the dictionary type
aliases in rust/xaynet-core/src/lib.rs:40-93.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict

from .mask.config import MaskConfigPair
from .mask.seed import EncryptedMaskSeed

ROUND_SEED_LENGTH = 32

# type aliases mirroring the reference's dictionaries
SumDict = Dict[bytes, bytes]  # sum pk -> ephemeral pk
LocalSeedDict = Dict[bytes, EncryptedMaskSeed]  # sum pk -> encrypted seed
UpdateSeedDict = Dict[bytes, EncryptedMaskSeed]  # update pk -> encrypted seed
SeedDict = Dict[bytes, UpdateSeedDict]  # sum pk -> {update pk -> seed}


@dataclass(frozen=True)
class RoundSeed:
    bytes_: bytes

    def __post_init__(self):
        if len(self.bytes_) != ROUND_SEED_LENGTH:
            raise ValueError("round seed must be 32 bytes")

    @classmethod
    def generate(cls) -> "RoundSeed":
        return cls(os.urandom(ROUND_SEED_LENGTH))

    @classmethod
    def zeroed(cls) -> "RoundSeed":
        return cls(b"\x00" * ROUND_SEED_LENGTH)

    def as_bytes(self) -> bytes:
        return self.bytes_


@dataclass
class RoundParameters:
    """Public parameters of one PET round."""

    pk: bytes  # coordinator's round-fresh encryption public key
    sum: float  # sum-task selection probability
    update: float  # update-task selection probability
    seed: RoundSeed
    mask_config: MaskConfigPair
    model_length: int
    # negotiated upload wire format: 1 = legacy interleaved element blocks,
    # 2 = packed byte-planar (serialization.WIRE_PLANAR_FLAG). Advertised to
    # participants via /params; the server parse auto-detects per message,
    # so a v1 client against a v2 round (and vice versa) stays valid.
    wire_format: int = 1

    def to_dict(self) -> dict:
        c = self.mask_config.vect
        u = self.mask_config.unit
        return {
            "pk": self.pk.hex(),
            "sum": self.sum,
            "update": self.update,
            "seed": self.seed.as_bytes().hex(),
            "mask_config": {
                "vect": list(c.to_bytes()),
                "unit": list(u.to_bytes()),
            },
            "model_length": self.model_length,
            "wire_format": self.wire_format,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "RoundParameters":
        from .mask.config import MaskConfig

        return cls(
            pk=bytes.fromhex(d["pk"]),
            sum=float(d["sum"]),
            update=float(d["update"]),
            seed=RoundSeed(bytes.fromhex(d["seed"])),
            mask_config=MaskConfigPair(
                vect=MaskConfig.from_bytes(bytes(d["mask_config"]["vect"])),
                unit=MaskConfig.from_bytes(bytes(d["mask_config"]["unit"])),
            ),
            model_length=int(d["model_length"]),
            wire_format=int(d.get("wire_format", 1)),
        )

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, RoundParameters)
            and self.pk == other.pk
            and self.sum == other.sum
            and self.update == other.update
            and self.seed == other.seed
            and self.mask_config == other.mask_config
            and self.model_length == other.model_length
            and self.wire_format == other.wire_format
        )
