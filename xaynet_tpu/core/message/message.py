"""Signed message envelope for the PET protocol.

Header layout, 136 bytes (reference:
rust/xaynet-core/src/message/message.rs:24-49):

    signature(64) ‖ participant_pk(32) ‖ coordinator_pk(32) ‖
    length(u32 BE, whole message incl. header) ‖ tag(1) ‖ flags(1) ‖
    reserved(2) ‖ payload

The Ed25519 signature covers ``bytes[64:length]`` (everything after the
signature, message.rs:336-358). Tags: Sum=1, Update=2, Sum2=3
(message.rs:441-468); flag bit 0 marks multipart messages.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from enum import IntEnum, IntFlag

from ..crypto import sign as crypto_sign
from ..mask.serialization import DecodeError
from .payloads import Chunk, Payload, Sum, Sum2, Update, parse_payload

SIGNATURE_LENGTH = 64
PK_LENGTH = 32
HEADER_LENGTH = SIGNATURE_LENGTH + 2 * PK_LENGTH + 4 + 1 + 1 + 2  # 136

# protocol minimums per round (reference: message.rs:18-21)
SUM_COUNT_MIN = 1
UPDATE_COUNT_MIN = 3


class Tag(IntEnum):
    SUM = 1
    UPDATE = 2
    SUM2 = 3


class Flags(IntFlag):
    NONE = 0
    MULTIPART = 1


@dataclass
class Message:
    """A signed PET message (header + payload)."""

    participant_pk: bytes
    coordinator_pk: bytes
    payload: Payload
    tag: Tag | None = None
    is_multipart: bool = False
    signature: bytes | None = None

    def __post_init__(self):
        if self.tag is None:
            self.tag = _payload_tag(self.payload)

    def payload_length(self) -> int:
        return self.payload.serialized_length()

    def serialized_length(self) -> int:
        return HEADER_LENGTH + self.payload_length()

    def to_bytes(self, secret_signing_key: bytes | None = None) -> bytes:
        """Serialize; signs on serialize when a secret key is given."""
        total = self.serialized_length()
        buf = bytearray(total)
        buf[SIGNATURE_LENGTH : SIGNATURE_LENGTH + PK_LENGTH] = self.participant_pk
        buf[SIGNATURE_LENGTH + PK_LENGTH : SIGNATURE_LENGTH + 2 * PK_LENGTH] = self.coordinator_pk
        struct.pack_into(">I", buf, SIGNATURE_LENGTH + 2 * PK_LENGTH, total)
        buf[SIGNATURE_LENGTH + 2 * PK_LENGTH + 4] = int(self.tag)
        buf[SIGNATURE_LENGTH + 2 * PK_LENGTH + 5] = (
            int(Flags.MULTIPART) if self.is_multipart else 0
        )
        # reserved bytes stay zero
        buf[HEADER_LENGTH:] = self.payload.to_bytes()
        if secret_signing_key is not None:
            # memoryview: signing a 150 MB update must not copy the payload
            sig = crypto_sign.sign_detached(
                secret_signing_key, memoryview(buf)[SIGNATURE_LENGTH:total]
            )
            buf[:SIGNATURE_LENGTH] = sig
        elif self.signature is not None:
            buf[:SIGNATURE_LENGTH] = self.signature
        return bytes(buf)

    @classmethod
    def from_bytes(cls, data: bytes, verify: bool = True, lazy_update_vect: bool = False) -> "Message":
        """Parse and (by default) verify the signature.

        ``lazy_update_vect``: device-ingest coordinators defer the Update
        payload's element parse/validity to the accelerator (see
        ``parse_mask_vect``); all other payloads parse eagerly."""
        if len(data) < HEADER_LENGTH:
            raise DecodeError("message shorter than header")
        signature = data[:SIGNATURE_LENGTH]
        participant_pk = data[SIGNATURE_LENGTH : SIGNATURE_LENGTH + PK_LENGTH]
        coordinator_pk = data[SIGNATURE_LENGTH + PK_LENGTH : SIGNATURE_LENGTH + 2 * PK_LENGTH]
        (length,) = struct.unpack_from(">I", data, SIGNATURE_LENGTH + 2 * PK_LENGTH)
        if length < HEADER_LENGTH or length > len(data):
            raise DecodeError("invalid message length field")
        tag_raw = data[SIGNATURE_LENGTH + 2 * PK_LENGTH + 4]
        flags_raw = data[SIGNATURE_LENGTH + 2 * PK_LENGTH + 5]
        try:
            tag = Tag(tag_raw)
        except ValueError as e:
            raise DecodeError(f"invalid tag {tag_raw}") from e
        is_multipart = bool(flags_raw & Flags.MULTIPART)
        if verify and not crypto_sign.verify_detached(
            participant_pk, signature, memoryview(data)[SIGNATURE_LENGTH:length]
        ):
            raise DecodeError("invalid message signature")
        payload = parse_payload(
            tag, is_multipart, data[HEADER_LENGTH:length], lazy_update_vect=lazy_update_vect
        )
        return cls(
            participant_pk=participant_pk,
            coordinator_pk=coordinator_pk,
            payload=payload,
            tag=tag,
            is_multipart=is_multipart,
            signature=signature,
        )

    def verify_signature(self, data: bytes) -> bool:
        (length,) = struct.unpack_from(">I", data, SIGNATURE_LENGTH + 2 * PK_LENGTH)
        return crypto_sign.verify_detached(
            data[SIGNATURE_LENGTH : SIGNATURE_LENGTH + PK_LENGTH],
            data[:SIGNATURE_LENGTH],
            data[SIGNATURE_LENGTH:length],
        )


def _payload_tag(payload: Payload) -> Tag:
    if isinstance(payload, Sum):
        return Tag.SUM
    if isinstance(payload, Update):
        return Tag.UPDATE
    if isinstance(payload, Sum2):
        return Tag.SUM2
    if isinstance(payload, Chunk):
        return payload.tag
    raise TypeError(f"unknown payload type {type(payload)}")


def peek_header(data: bytes) -> tuple[bytes, Tag, bool]:
    """Cheap header inspection without payload parsing or verification.

    Returns (participant_pk, tag, is_multipart) — what the phase filter
    needs before paying for signature verification.
    """
    if len(data) < HEADER_LENGTH:
        raise DecodeError("message shorter than header")
    tag_raw = data[SIGNATURE_LENGTH + 2 * PK_LENGTH + 4]
    try:
        tag = Tag(tag_raw)
    except ValueError as e:
        raise DecodeError(f"invalid tag {tag_raw}") from e
    flags_raw = data[SIGNATURE_LENGTH + 2 * PK_LENGTH + 5]
    return (
        data[SIGNATURE_LENGTH : SIGNATURE_LENGTH + PK_LENGTH],
        tag,
        bool(flags_raw & Flags.MULTIPART),
    )
