"""PET wire protocol: signed message envelope and payloads.

Reference surface: rust/xaynet-core/src/message/.
"""

from .message import (
    HEADER_LENGTH,
    SUM_COUNT_MIN,
    UPDATE_COUNT_MIN,
    DecodeError,
    Flags,
    Message,
    Tag,
    peek_header,
)
from .payloads import (
    CHUNK_HEADER_LENGTH,
    SEED_DICT_ENTRY_LENGTH,
    Chunk,
    Payload,
    Sum,
    Sum2,
    Update,
    lv_decode,
    lv_encode,
    parse_local_seed_dict,
    parse_payload,
    serialize_local_seed_dict,
)

__all__ = [
    "HEADER_LENGTH",
    "SUM_COUNT_MIN",
    "UPDATE_COUNT_MIN",
    "DecodeError",
    "Flags",
    "Message",
    "Tag",
    "peek_header",
    "CHUNK_HEADER_LENGTH",
    "SEED_DICT_ENTRY_LENGTH",
    "Chunk",
    "Payload",
    "Sum",
    "Sum2",
    "Update",
    "lv_decode",
    "lv_encode",
    "parse_local_seed_dict",
    "parse_payload",
    "serialize_local_seed_dict",
]
