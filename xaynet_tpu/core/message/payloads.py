"""PET message payloads: Sum, Update, Sum2, Chunk.

Layouts (reference: rust/xaynet-core/src/message/payload/):

- Sum (sum.rs): sum_signature(64) ‖ ephm_pk(32)
- Update (update.rs): sum_signature(64) ‖ update_signature(64) ‖
  masked model (MaskObject) ‖ local seed dict (LV-encoded, 112 B/entry)
- Sum2 (sum2.rs): sum_signature(64) ‖ aggregated mask (MaskObject)
- Chunk (chunk.rs): id(u16 BE) ‖ message_id(u16 BE) ‖ flags(1, bit0 =
  LAST_CHUNK) ‖ reserved(3) ‖ data

Length-Value items use a 4-byte big-endian length that *includes* the
length field itself (reference: rust/xaynet-core/src/message/traits.rs:126-160).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Union

from ..mask.object import MaskObject
from ..mask.seed import ENCRYPTED_MASK_SEED_LENGTH, EncryptedMaskSeed
from ..mask.serialization import (
    DecodeError,
    parse_mask_object,
    parse_mask_unit_stream,
    parse_mask_vect_stream,
    serialize_mask_object,
)

SIGNATURE_LENGTH = 64
PK_LENGTH = 32
SEED_DICT_ENTRY_LENGTH = PK_LENGTH + ENCRYPTED_MASK_SEED_LENGTH  # 112
CHUNK_HEADER_LENGTH = 8

LocalSeedDict = dict  # bytes (sum pk, 32) -> EncryptedMaskSeed


# --- Length-Value helpers ---------------------------------------------------


def lv_encode(value: bytes) -> bytes:
    return struct.pack(">I", len(value) + 4) + value


def lv_decode(data: bytes, offset: int = 0) -> tuple[bytes, int]:
    """Returns (value, total bytes consumed incl. the length field)."""
    if len(data) - offset < 4:
        raise DecodeError("LV item truncated (no length field)")
    (length,) = struct.unpack_from(">I", data, offset)
    if length < 4:
        raise DecodeError("LV length below minimum")
    if len(data) - offset < length:
        raise DecodeError("LV value truncated")
    return data[offset + 4 : offset + length], length


def serialize_local_seed_dict(seed_dict: dict) -> bytes:
    body = bytearray()
    for pk, seed in seed_dict.items():
        if len(pk) != PK_LENGTH:
            raise ValueError("seed dict key must be a 32-byte public key")
        seed_bytes = seed.as_bytes() if isinstance(seed, EncryptedMaskSeed) else bytes(seed)
        if len(seed_bytes) != ENCRYPTED_MASK_SEED_LENGTH:
            raise ValueError("seed dict value must be an 80-byte encrypted seed")
        body += pk + seed_bytes
    return lv_encode(bytes(body))


def parse_local_seed_dict(data: bytes, offset: int = 0) -> tuple[dict, int]:
    value, consumed = lv_decode(data, offset)
    return _seed_dict_from_value(value), consumed


def _seed_dict_from_value(value: bytes) -> dict:
    if len(value) % SEED_DICT_ENTRY_LENGTH != 0:
        raise DecodeError("seed dict length not a multiple of the entry size")
    out: dict = {}
    for i in range(0, len(value), SEED_DICT_ENTRY_LENGTH):
        pk = value[i : i + PK_LENGTH]
        seed = EncryptedMaskSeed(value[i + PK_LENGTH : i + SEED_DICT_ENTRY_LENGTH])
        if pk in out:
            raise DecodeError("duplicate sum pk in seed dict")
        out[pk] = seed
    return out


def parse_local_seed_dict_stream(reader) -> dict:
    (length,) = struct.unpack(">I", reader.read(4))
    if length < 4:
        raise DecodeError("LV length below minimum")
    if length - 4 > reader.remaining:
        raise DecodeError("LV value truncated")
    return _seed_dict_from_value(reader.read(length - 4))


# --- payloads ---------------------------------------------------------------


@dataclass
class Sum:
    sum_signature: bytes
    ephm_pk: bytes

    def serialized_length(self) -> int:
        return SIGNATURE_LENGTH + PK_LENGTH

    def to_bytes(self) -> bytes:
        return self.sum_signature + self.ephm_pk

    @classmethod
    def from_bytes(cls, data: bytes) -> "Sum":
        if len(data) < SIGNATURE_LENGTH + PK_LENGTH:
            raise DecodeError("sum payload too short")
        return cls(
            sum_signature=data[:SIGNATURE_LENGTH],
            ephm_pk=data[SIGNATURE_LENGTH : SIGNATURE_LENGTH + PK_LENGTH],
        )


@dataclass
class Update:
    sum_signature: bytes
    update_signature: bytes
    masked_model: MaskObject
    local_seed_dict: dict
    # serialize the masked model's vector part in the v2 byte-planar wire
    # layout (negotiated via RoundParameters.wire_format; the parse side
    # auto-detects from the count-word flag, so this only shapes to_bytes)
    wire_planar: bool = False

    def serialized_length(self) -> int:
        from ..mask.serialization import serialized_object_length

        return (
            2 * SIGNATURE_LENGTH
            + serialized_object_length(self.masked_model.config, len(self.masked_model))
            + 4
            + SEED_DICT_ENTRY_LENGTH * len(self.local_seed_dict)
        )

    def to_bytes(self) -> bytes:
        return (
            self.sum_signature
            + self.update_signature
            + serialize_mask_object(self.masked_model, planar_vect=self.wire_planar)
            + serialize_local_seed_dict(self.local_seed_dict)
        )

    @classmethod
    def from_bytes(cls, data: bytes, lazy_vect: bool = False) -> "Update":
        if len(data) < 2 * SIGNATURE_LENGTH:
            raise DecodeError("update payload too short")
        masked, consumed = parse_mask_object(data, 2 * SIGNATURE_LENGTH, lazy_vect=lazy_vect)
        seed_dict, _ = parse_local_seed_dict(data, 2 * SIGNATURE_LENGTH + consumed)
        return cls(
            sum_signature=data[:SIGNATURE_LENGTH],
            update_signature=data[SIGNATURE_LENGTH : 2 * SIGNATURE_LENGTH],
            masked_model=masked,
            local_seed_dict=seed_dict,
            wire_planar=bool(getattr(masked.vect, "planar", False)),
        )

    @classmethod
    def from_stream(cls, reader, lazy_vect: bool = False) -> "Update":
        sigs = reader.read(2 * SIGNATURE_LENGTH)
        vect = parse_mask_vect_stream(reader, lazy=lazy_vect)
        unit = parse_mask_unit_stream(reader)
        seed_dict = parse_local_seed_dict_stream(reader)
        return cls(
            sum_signature=sigs[:SIGNATURE_LENGTH],
            update_signature=sigs[SIGNATURE_LENGTH:],
            masked_model=MaskObject(vect, unit),
            local_seed_dict=seed_dict,
            wire_planar=bool(getattr(vect, "planar", False)),
        )


@dataclass
class Sum2:
    sum_signature: bytes
    model_mask: MaskObject

    def serialized_length(self) -> int:
        from ..mask.serialization import serialized_object_length

        return SIGNATURE_LENGTH + serialized_object_length(
            self.model_mask.config, len(self.model_mask)
        )

    def to_bytes(self) -> bytes:
        return self.sum_signature + serialize_mask_object(self.model_mask)

    @classmethod
    def from_bytes(cls, data: bytes) -> "Sum2":
        if len(data) < SIGNATURE_LENGTH:
            raise DecodeError("sum2 payload too short")
        mask, _ = parse_mask_object(data, SIGNATURE_LENGTH)
        return cls(sum_signature=data[:SIGNATURE_LENGTH], model_mask=mask)

    @classmethod
    def from_stream(cls, reader) -> "Sum2":
        sig = reader.read(SIGNATURE_LENGTH)
        vect = parse_mask_vect_stream(reader)
        unit = parse_mask_unit_stream(reader)
        return cls(sum_signature=sig, model_mask=MaskObject(vect, unit))


@dataclass
class Chunk:
    """One part of a multipart message.

    ``tag`` carries the enclosing message's tag (the type of the message
    being reassembled).
    """

    id: int
    message_id: int
    last: bool
    data: bytes
    tag: "object" = None  # Tag; typed loosely to avoid a circular import

    def serialized_length(self) -> int:
        return CHUNK_HEADER_LENGTH + len(self.data)

    def to_bytes(self) -> bytes:
        return (
            struct.pack(">HHB3x", self.id & 0xFFFF, self.message_id & 0xFFFF, 1 if self.last else 0)
            + self.data
        )

    @classmethod
    def from_bytes(cls, data: bytes, tag=None) -> "Chunk":
        if len(data) < CHUNK_HEADER_LENGTH:
            raise DecodeError("chunk payload too short")
        cid, mid, flags = struct.unpack_from(">HHB", data)
        return cls(id=cid, message_id=mid, last=bool(flags & 1), data=data[CHUNK_HEADER_LENGTH:], tag=tag)


Payload = Union[Sum, Update, Sum2, Chunk]


def parse_payload(
    tag, is_multipart: bool, data: bytes, lazy_update_vect: bool = False
) -> Payload:
    if is_multipart:
        return Chunk.from_bytes(data, tag=tag)
    from .message import Tag  # local import to avoid cycle

    if tag == Tag.SUM:
        return Sum.from_bytes(data)
    if tag == Tag.UPDATE:
        return Update.from_bytes(data, lazy_vect=lazy_update_vect)
    if tag == Tag.SUM2:
        return Sum2.from_bytes(data)
    raise DecodeError(f"unknown tag {tag}")


def parse_payload_stream(tag, reader, lazy_update_vect: bool = False) -> Payload:
    """Streaming payload parse from a ``ChunkReader`` (multipart reassembly).

    Reference analogue: the stream variants of ``FromBytes``
    (rust/xaynet-core/src/message/traits.rs) used by the multipart service.
    """
    from .message import Tag  # local import to avoid cycle

    try:
        if tag == Tag.SUM:
            return Sum.from_bytes(reader.read(reader.remaining))
        if tag == Tag.UPDATE:
            return Update.from_stream(reader, lazy_vect=lazy_update_vect)
        if tag == Tag.SUM2:
            return Sum2.from_stream(reader)
    except ValueError as e:
        if isinstance(e, DecodeError):
            raise
        raise DecodeError(str(e)) from e
    raise DecodeError(f"unknown tag {tag}")
