"""Message encoding with multipart chunking and streaming reassembly.

Reference behavior (rust/xaynet-sdk/src/message_encoder/encoder.rs:14-180):
a payload larger than ``max_payload_size`` is split into signed ``Chunk``
messages (8-byte chunk header, shared random ``message_id``, ascending
chunk ids, LAST_CHUNK flag on the final part); each part is an
independently signed PET message carrying the original tag with the
MULTIPART flag set. The receiver reassembles by (participant_pk,
message_id) and re-parses the payload *incrementally* through a
``ChunkReader`` — the analogue of the reference's chunkable byte-iterator
(rust/xaynet-core/src/message/utils/chunkable_iterator.rs:17-60): chunk
buffers are consumed (and freed) as the parser advances, so a payload near
the protocol's 4 GiB message ceiling never needs a second contiguous copy.
"""

from __future__ import annotations

import os
import struct
from collections import deque
from typing import Iterator

import numpy as np

from .message import HEADER_LENGTH, Message
from .payloads import CHUNK_HEADER_LENGTH, Chunk

# minimum sensible ceiling: header + chunk header + 1 byte of progress
# (reference: rust/xaynet-sdk/src/settings/max_message_size.rs:4-80)
MIN_MESSAGE_SIZE = HEADER_LENGTH + CHUNK_HEADER_LENGTH + 1
DEFAULT_MAX_MESSAGE_SIZE = 4096


def max_payload_size(max_message_size: int) -> int:
    return max_message_size - HEADER_LENGTH


# the wire chunk id is u16 with id 0 reserved (reference chunk layout)
MAX_CHUNKS = 0xFFFF


class MessageEncoder:
    """Encodes (and signs) a message, chunking it when oversized.

    Parts are produced ON DEMAND (``part(i)``): a paused/retried multipart
    send holds one payload copy plus the index, never the full list of
    signed+sealed parts.
    """

    def __init__(
        self,
        message: Message,
        secret_signing_key: bytes,
        max_message_size: int | None = DEFAULT_MAX_MESSAGE_SIZE,
        message_id: int | None = None,  # pin when restoring an in-flight send
    ):
        self.message = message
        self.secret_signing_key = secret_signing_key
        self.max_message_size = max_message_size
        self._payload_bytes = message.payload.to_bytes()
        if (
            max_message_size is None
            or HEADER_LENGTH + len(self._payload_bytes) <= max_message_size
        ):
            self._budget = None
            self.n_parts = 1
        else:
            self._budget = max(max_message_size - HEADER_LENGTH - CHUNK_HEADER_LENGTH, 1)
            self.n_parts = -(-len(self._payload_bytes) // self._budget)
            if self.n_parts > MAX_CHUNKS:
                # the u16 chunk id cannot address more parts; wrapping would
                # corrupt reassembly silently — refuse loudly instead
                raise ValueError(
                    f"payload needs {self.n_parts} chunks but the wire chunk id "
                    f"is u16 (max {MAX_CHUNKS}); raise max_message_size "
                    f"(>= {HEADER_LENGTH + CHUNK_HEADER_LENGTH + -(-len(self._payload_bytes) // MAX_CHUNKS)})"
                )
            self.message_id = (
                message_id if message_id is not None else struct.unpack(">H", os.urandom(2))[0]
            )

    def part(self, i: int) -> bytes:
        """The ``i``-th signed wire part (0-based)."""
        if not 0 <= i < self.n_parts:
            raise IndexError(i)
        if self._budget is None:
            return self.message.to_bytes(self.secret_signing_key)
        chunk = Chunk(
            id=i + 1,
            message_id=self.message_id,
            last=(i == self.n_parts - 1),
            data=self._payload_bytes[i * self._budget : (i + 1) * self._budget],
            tag=self.message.tag,
        )
        part = Message(
            participant_pk=self.message.participant_pk,
            coordinator_pk=self.message.coordinator_pk,
            payload=chunk,
            tag=self.message.tag,
            is_multipart=True,
        )
        return part.to_bytes(self.secret_signing_key)

    def __iter__(self) -> Iterator[bytes]:
        for i in range(self.n_parts):
            yield self.part(i)


class ChunkReader:
    """Sequential reader over an ordered sequence of chunk buffers.

    The streaming-parse analogue of the reference's ``ChunkableIterator``
    (rust/xaynet-core/src/message/utils/chunkable_iterator.rs:17-60): small
    header reads may join a few bytes across a chunk boundary, but bulk
    element blocks are copied chunk-by-chunk straight into their destination
    array (``read_into``), and consumed chunks are dropped immediately — the
    payload is never materialized contiguously a second time.
    """

    def __init__(self, chunks: list[bytes]):
        self._chunks: deque[bytes] = deque(chunks)
        self._pos = 0  # read offset within the head chunk
        self.remaining = sum(len(c) for c in chunks)

    def _advance(self, take: int) -> None:
        self._pos += take
        self.remaining -= take
        if self._pos >= len(self._chunks[0]):
            self._chunks.popleft()  # frees the consumed chunk buffer
            self._pos = 0

    def read(self, n: int) -> bytes:
        """``n`` bytes as a (small) contiguous value — for headers/dicts."""
        if n > self.remaining:
            raise ValueError(f"chunk stream truncated: need {n}, have {self.remaining}")
        parts = []
        while n > 0:
            head = self._chunks[0]
            take = min(n, len(head) - self._pos)
            parts.append(head[self._pos : self._pos + take])
            self._advance(take)
            n -= take
        return parts[0] if len(parts) == 1 else b"".join(parts)

    def read_into(self, out: np.ndarray) -> None:
        """Fill a preallocated ``uint8[n]`` array — for bulk element blocks."""
        n = out.size
        if n > self.remaining:
            raise ValueError(f"chunk stream truncated: need {n}, have {self.remaining}")
        off = 0
        while off < n:
            head = self._chunks[0]
            take = min(n - off, len(head) - self._pos)
            out[off : off + take] = np.frombuffer(head, np.uint8, take, self._pos)
            self._advance(take)
            off += take


class MessageBuilder:
    """Server-side reassembly of one multipart message's chunks.

    Chunks may arrive out of order; they are keyed by chunk id and the
    message completes when the LAST_CHUNK id is known and all lower ids are
    present (reference: xaynet-server multipart/buffer.rs:8-60).
    """

    def __init__(self):
        self._chunks: dict[int, bytes] = {}
        self._last_id: int | None = None

    def add(self, chunk: Chunk) -> bool:
        """Adds a chunk; returns True when the message is complete."""
        self._chunks[chunk.id] = chunk.data
        if chunk.last:
            self._last_id = chunk.id
        return self.is_complete()

    def is_complete(self) -> bool:
        if self._last_id is None:
            return False
        return all(i in self._chunks for i in range(1, self._last_id + 1))

    def take_reader(self) -> ChunkReader:
        """Hand the buffered chunks off to a streaming reader.

        The builder's own references are dropped so each chunk's memory is
        owned solely by the reader and freed as parsing consumes it.
        """
        if not self.is_complete():
            raise ValueError("message is not complete")
        assert self._last_id is not None
        chunks = [self._chunks.pop(i) for i in range(1, self._last_id + 1)]
        return ChunkReader(chunks)

    def payload_bytes(self) -> bytes:
        if not self.is_complete():
            raise ValueError("message is not complete")
        assert self._last_id is not None
        return b"".join(self._chunks[i] for i in range(1, self._last_id + 1))
