"""Wire serialization of mask objects.

Layouts (reference: rust/xaynet-core/src/mask/object/serialization/):

- ``MaskVect``: config(4) ‖ count(u32 BE) ‖ count fixed-width little-endian
  integers of ``bytes_per_number`` each (vect.rs:24-80);
- ``MaskUnit``: config(4) ‖ one fixed-width little-endian integer (unit.rs);
- ``MaskObject``: vect ‖ unit (mod.rs).

The element block converts directly between wire bytes and the uint32 limb
tensors (a vectorized numpy pad/view — no per-element loop), which is what
makes parsing a 25M-element update a memcpy-class operation.

Wire format v2 (packed planar, docs/DESIGN.md §21): the top bit of the
count word (``WIRE_PLANAR_FLAG``) marks the element block as BYTE-PLANAR —
``bytes_per_number`` contiguous planes of ``count`` bytes each, plane ``b``
holding byte ``b`` of every element — instead of the v1 interleaved
per-element layout. Same byte budget, but the planar block is already the
PR-13 packed staging layout, so a device-ingest coordinator uploads it
without the byte-gather relayout and never materializes uint32 limbs.
Element counts are bounded far below 2^31 (``MAX_BODY`` caps the message),
so the flag bit can never collide with a real count.
"""

from __future__ import annotations

import struct

import numpy as np

from ...ops import limbs as limb_ops
from .config import MASK_CONFIG_LENGTH, MaskConfig
from .object import MaskObject, MaskUnit, MaskVect


class DecodeError(ValueError):
    """Malformed wire bytes."""


# config(4) + count(u32 BE): everything before the element block
VECT_HEADER_LENGTH = MASK_CONFIG_LENGTH + 4

# top bit of the count word: element block is byte-planar (wire format v2)
WIRE_PLANAR_FLAG = 0x8000_0000


def _split_count_word(word: int) -> tuple[int, bool]:
    """(element count, planar?) from the wire count word."""
    return word & ~WIRE_PLANAR_FLAG, bool(word & WIRE_PLANAR_FLAG)


def planar_to_interleaved(block: np.ndarray, count: int, bpn: int) -> np.ndarray:
    """Byte-planar element block ``uint8[bpn * count]`` -> the v1 interleaved
    layout (one materializing transpose — the lazy path's host FALLBACK; the
    device path consumes the planar block directly)."""
    return np.ascontiguousarray(
        np.asarray(block).reshape(bpn, count).T
    ).reshape(-1)


def serialized_vect_length(config: MaskConfig, count: int) -> int:
    return VECT_HEADER_LENGTH + count * config.bytes_per_number


def vect_element_block(wire: bytes) -> np.ndarray:
    """The raw fixed-width element block of a serialized MaskVect as a
    zero-copy uint8 view — the device-ingest input
    (``ShardedAggregator.add_wire_batch``).

    Validates the header and the exact framed length like
    ``parse_mask_vect`` does (a truncated buffer or a full MaskObject
    wire — vect ‖ unit — raises ``DecodeError`` here, at the parse
    boundary, not as an opaque shape error downstream)."""
    if len(wire) < VECT_HEADER_LENGTH:
        raise DecodeError("mask vector buffer too short")
    try:
        config = MaskConfig.from_bytes(wire[:MASK_CONFIG_LENGTH])
    except ValueError as e:
        raise DecodeError(f"invalid mask config: {e}") from e
    (word,) = struct.unpack_from(">I", wire, MASK_CONFIG_LENGTH)
    count, planar = _split_count_word(word)
    if planar:
        raise DecodeError("planar (v2) element block where interleaved expected")
    if len(wire) != VECT_HEADER_LENGTH + count * config.bytes_per_number:
        raise DecodeError("wire length does not match the framed element count")
    return np.frombuffer(wire, dtype=np.uint8)[VECT_HEADER_LENGTH:]


def serialize_mask_vect(vect: MaskVect, planar: bool = False) -> bytes:
    bpn = vect.config.bytes_per_number
    if planar:
        from .object import LazyWireMaskVect

        if isinstance(vect, LazyWireMaskVect) and vect.planar and not vect.materialized:
            # parsed-from-planar-wire and never touched: re-emit the block
            block = np.asarray(vect.wire_block).tobytes()
        else:
            interleaved = limb_ops.limbs_to_bytes_le(vect.data, bpn)
            block = np.ascontiguousarray(
                np.frombuffer(interleaved, dtype=np.uint8).reshape(len(vect), bpn).T
            ).tobytes()
        return (
            vect.config.to_bytes()
            + struct.pack(">I", len(vect) | WIRE_PLANAR_FLAG)
            + block
        )
    return (
        vect.config.to_bytes()
        + struct.pack(">I", len(vect))
        + limb_ops.limbs_to_bytes_le(vect.data, bpn)
    )


def parse_mask_vect(data: bytes, offset: int = 0, lazy: bool = False) -> tuple[MaskVect, int]:
    """Parse a MaskVect at ``offset``; returns (vect, bytes consumed).

    ``lazy=True`` (device-ingest coordinators) skips the host limb
    materialization AND the host element-validity check, returning a
    ``LazyWireMaskVect`` that carries the raw element block; element
    validity then happens on device in ``validate_aggregation`` (or on
    first host materialization), one stage later than the eager parse's
    ``DecodeError``.
    """
    if len(data) - offset < MASK_CONFIG_LENGTH + 4:
        raise DecodeError("mask vector buffer too short")
    try:
        config = MaskConfig.from_bytes(data[offset : offset + MASK_CONFIG_LENGTH])
    except ValueError as e:
        raise DecodeError(f"invalid mask config: {e}") from e
    (word,) = struct.unpack_from(">I", data, offset + MASK_CONFIG_LENGTH)
    count, planar = _split_count_word(word)
    bpn = config.bytes_per_number
    start = offset + MASK_CONFIG_LENGTH + 4
    end = start + count * bpn
    if len(data) < end:
        raise DecodeError("mask vector data truncated")
    raw = np.frombuffer(data, dtype=np.uint8, count=count * bpn, offset=start)
    if lazy:
        from .object import LazyWireMaskVect

        return LazyWireMaskVect(config, raw, count, planar=planar), end - offset
    if planar:
        raw = planar_to_interleaved(raw, count, bpn)
    limbs = limb_ops.bytes_le_to_limbs(raw, count, bpn)
    vect = MaskVect(config, limbs)
    if not vect.is_valid():
        raise DecodeError("mask vector element >= group order")
    return vect, end - offset


def serialize_mask_unit(unit: MaskUnit) -> bytes:
    bpn = unit.config.bytes_per_number
    return unit.config.to_bytes() + limb_ops.limbs_to_bytes_le(unit.data[None, :], bpn)


def parse_mask_unit(data: bytes, offset: int = 0) -> tuple[MaskUnit, int]:
    if len(data) - offset < MASK_CONFIG_LENGTH:
        raise DecodeError("mask unit buffer too short")
    try:
        config = MaskConfig.from_bytes(data[offset : offset + MASK_CONFIG_LENGTH])
    except ValueError as e:
        raise DecodeError(f"invalid mask config: {e}") from e
    bpn = config.bytes_per_number
    start = offset + MASK_CONFIG_LENGTH
    if len(data) < start + bpn:
        raise DecodeError("mask unit data truncated")
    limbs = limb_ops.bytes_le_to_limbs(
        np.frombuffer(data, dtype=np.uint8, count=bpn, offset=start), 1, bpn
    )
    unit = MaskUnit(config, limbs[0])
    if not unit.is_valid():
        raise DecodeError("mask unit element >= group order")
    return unit, MASK_CONFIG_LENGTH + bpn


def parse_mask_vect_stream(reader, lazy: bool = False) -> MaskVect:
    """Streaming MaskVect parse from a ``ChunkReader``.

    The element block is copied chunk-by-chunk into one staging array
    (consumed chunk buffers are freed as the reader advances), so peak
    memory is ~1x the element block instead of the 2x of a concatenate-
    then-parse (reference streaming parse:
    rust/xaynet-core/src/mask/object/serialization/vect.rs + traits.rs).

    ``lazy=True``: the element bytes are gathered with ONE bounded-memory
    byte copy (no limb conversion, no host validity — a plain memcpy
    instead of the parse hot loop) into a ``LazyWireMaskVect`` for the
    device-ingest coordinator; see ``parse_mask_vect``.
    """
    head = reader.read(MASK_CONFIG_LENGTH + 4)
    try:
        config = MaskConfig.from_bytes(head[:MASK_CONFIG_LENGTH])
    except ValueError as e:
        raise DecodeError(f"invalid mask config: {e}") from e
    (word,) = struct.unpack_from(">I", head, MASK_CONFIG_LENGTH)
    count, planar = _split_count_word(word)
    bpn = config.bytes_per_number
    nbytes = count * bpn
    if nbytes > reader.remaining:
        raise DecodeError("mask vector data truncated")
    if lazy or planar:
        # planar blocks gather as one byte copy either way: the segmented
        # interleaved convert below walks element-major segments, which a
        # plane-major block cannot feed without a full-block staging anyway
        raw = np.empty(nbytes, dtype=np.uint8)
        reader.read_into(raw)
        if lazy:
            from .object import LazyWireMaskVect

            return LazyWireMaskVect(config, raw, count, planar=planar)
        limbs = limb_ops.bytes_le_to_limbs(
            planar_to_interleaved(raw, count, bpn), count, bpn
        )
        vect = MaskVect(config, limbs)
        if not vect.is_valid():
            raise DecodeError("mask vector element >= group order")
        return vect
    # segmented convert: fixed-size wire segments go straight into the limb
    # tensor, so the transient staging is bounded (never O(payload))
    n_limb = limb_ops.n_limbs_for_bytes(bpn)
    limbs = np.empty((count, n_limb), dtype=np.uint32)
    seg_elems = max(1, (2 << 20) // max(bpn, 1))
    for s in range(0, count, seg_elems):
        k = min(seg_elems, count - s)
        staging = np.empty(k * bpn, dtype=np.uint8)
        reader.read_into(staging)
        limbs[s : s + k] = limb_ops.bytes_le_to_limbs(staging, k, bpn)
    vect = MaskVect(config, limbs)
    if not vect.is_valid():
        raise DecodeError("mask vector element >= group order")
    return vect


def parse_mask_unit_stream(reader) -> MaskUnit:
    """Streaming MaskUnit parse from a ``ChunkReader``."""
    head = reader.read(MASK_CONFIG_LENGTH)
    try:
        config = MaskConfig.from_bytes(head)
    except ValueError as e:
        raise DecodeError(f"invalid mask config: {e}") from e
    bpn = config.bytes_per_number
    if bpn > reader.remaining:
        raise DecodeError("mask unit data truncated")
    data = np.frombuffer(reader.read(bpn), dtype=np.uint8)
    limbs = limb_ops.bytes_le_to_limbs(data, 1, bpn)
    unit = MaskUnit(config, limbs[0])
    if not unit.is_valid():
        raise DecodeError("mask unit element >= group order")
    return unit


def serialize_mask_object(obj: MaskObject, planar_vect: bool = False) -> bytes:
    """``planar_vect`` emits the VECTOR part in the v2 byte-planar layout
    (the unit part is one element — planes would be a no-op relabel)."""
    return serialize_mask_vect(obj.vect, planar=planar_vect) + serialize_mask_unit(obj.unit)


def parse_mask_object(
    data: bytes, offset: int = 0, lazy_vect: bool = False
) -> tuple[MaskObject, int]:
    vect, n1 = parse_mask_vect(data, offset, lazy=lazy_vect)
    unit, n2 = parse_mask_unit(data, offset + n1)
    return MaskObject(vect, unit), n1 + n2


def serialized_object_length(config, count: int) -> int:
    return (
        serialized_vect_length(config.vect, count)
        + MASK_CONFIG_LENGTH
        + config.unit.bytes_per_number
    )
