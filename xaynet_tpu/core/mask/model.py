"""Model representation and primitive conversions.

The reference represents a model as ``Vec<Ratio<BigInt>>`` — exact rational
weights (reference: rust/xaynet-core/src/mask/model.rs:25,94-160). This port
keeps the exact representation (`fractions.Fraction`) for the protocol
surface and conformance tests, and adds zero-copy numpy bridges
(``from_array`` / ``to_array``) that the TPU fast path uses so 25M-parameter
models never materialize as python objects.
"""

from __future__ import annotations

import math
from fractions import Fraction
from typing import Iterable, Iterator

import numpy as np

from .config import DataType

_F32_MAX = float(np.finfo(np.float32).max)
_F64_MAX = float(np.finfo(np.float64).max)
_INT_BOUNDS = {DataType.I32: 2**31, DataType.I64: 2**63}


class ModelCastError(ValueError):
    """A weight is not representable in the requested primitive type."""


class PrimitiveCastError(ValueError):
    """A primitive value (non-finite float) cannot become an exact weight."""


class Model:
    """A numerical model: a sequence of exact rational weights."""

    __slots__ = ("weights",)

    def __init__(self, weights: Iterable[Fraction]):
        self.weights: list[Fraction] = list(weights)

    def __len__(self) -> int:
        return len(self.weights)

    def __iter__(self) -> Iterator[Fraction]:
        return iter(self.weights)

    def __getitem__(self, i):
        return self.weights[i]

    def __eq__(self, other) -> bool:
        return isinstance(other, Model) and self.weights == other.weights

    def __repr__(self) -> str:
        return f"Model(len={len(self.weights)})"

    # --- primitive conversions (reference-parity surface) ---------------

    @classmethod
    def from_primitives(cls, values: Iterable, data_type: DataType) -> "Model":
        """Exact conversion; raises ``PrimitiveCastError`` on non-finite floats."""
        if data_type in (DataType.I32, DataType.I64):
            return cls(Fraction(int(v)) for v in values)
        out = []
        for v in values:
            f = float(np.float32(v)) if data_type is DataType.F32 else float(v)
            if not math.isfinite(f):
                raise PrimitiveCastError(f"non-finite value {v!r}")
            out.append(Fraction(f))
        return cls(out)

    @classmethod
    def from_primitives_bounded(cls, values: Iterable, data_type: DataType) -> "Model":
        """Clamping conversion: infinities to +/-max, NaN to zero."""
        if data_type in (DataType.I32, DataType.I64):
            return cls(Fraction(int(v)) for v in values)
        fmax = _F32_MAX if data_type is DataType.F32 else _F64_MAX
        out = []
        for v in values:
            f = float(np.float32(v)) if data_type is DataType.F32 else float(v)
            if math.isnan(f):
                out.append(Fraction(0))
            else:
                out.append(Fraction(min(max(f, -fmax), fmax)))
        return cls(out)

    def into_primitives(self, data_type: DataType) -> list:
        """Convert to primitives; raises ``ModelCastError`` when out of range."""
        if data_type in (DataType.I32, DataType.I64):
            bound = _INT_BOUNDS[data_type]
            out = []
            for w in self.weights:
                i = int(w)  # truncates toward zero, like Ratio::to_integer
                if not (-bound <= i < bound):
                    raise ModelCastError(f"weight {w} out of range for {data_type.name}")
                out.append(i)
            return out
        fmax = _F32_MAX if data_type is DataType.F32 else _F64_MAX
        out = []
        for w in self.weights:
            if w < -Fraction(fmax) or w > Fraction(fmax):
                raise ModelCastError(f"weight {w} out of range for {data_type.name}")
            f = float(w)  # correctly rounded
            out.append(float(np.float32(f)) if data_type is DataType.F32 else f)
        return out

    # --- numpy bridges (fast path) ---------------------------------------

    @classmethod
    def from_array(cls, arr: np.ndarray) -> "Model":
        dt = DataType.F32 if arr.dtype == np.float32 else DataType.F64
        if arr.dtype in (np.int32, np.int64):
            return cls(Fraction(int(v)) for v in arr.tolist())
        return cls.from_primitives(arr.tolist(), dt)

    def to_array(self, data_type: DataType = DataType.F32) -> np.ndarray:
        dtype = {
            DataType.F32: np.float32,
            DataType.F64: np.float64,
            DataType.I32: np.int32,
            DataType.I64: np.int64,
        }[data_type]
        return np.asarray(self.into_primitives(data_type), dtype=dtype)


class Scalar:
    """A non-negative rational scaling factor (e.g. 1/N for FedAvg)."""

    __slots__ = ("value",)

    def __init__(self, numer: int, denom: int = 1):
        if numer < 0 or denom <= 0:
            raise ValueError("scalar must be a non-negative ratio")
        self.value = Fraction(numer, denom)

    @classmethod
    def unit(cls) -> "Scalar":
        return cls(1, 1)

    @classmethod
    def from_fraction(cls, f: Fraction) -> "Scalar":
        if f < 0:
            raise ValueError("scalar must be non-negative")
        s = cls(0, 1)
        s.value = f
        return s

    @classmethod
    def from_float(cls, f: float) -> "Scalar":
        """Exact conversion; raises on non-finite or negative values."""
        if not math.isfinite(f) or f < 0:
            raise ValueError(f"invalid scalar {f!r}")
        return cls.from_fraction(Fraction(f))

    @classmethod
    def from_float_bounded(cls, f: float) -> "Scalar":
        """Clamping conversion: +inf to f64::MAX, negatives and NaN to zero."""
        if math.isnan(f) or f < 0:
            return cls(0, 1)
        return cls.from_fraction(Fraction(min(f, _F64_MAX)))

    def to_float(self) -> float:
        """Correctly-rounded primitive conversion (IntoPrimitive analogue)."""
        return float(self.value)

    def __eq__(self, other) -> bool:
        return isinstance(other, Scalar) and self.value == other.value

    def __repr__(self) -> str:
        return f"Scalar({self.value})"
