"""Masking / aggregation math: the PET protocol kernel.

Reference surface: rust/xaynet-core/src/mask/ (config, model, scalar, object,
seed, masking). TPU-native representation: group elements are fixed-width
``uint32`` limb tensors; the hot loops (mask expansion, modular aggregation,
unmasking) have numpy host implementations here and JAX/Pallas device
implementations in ``xaynet_tpu.ops``.
"""

from .config import (
    MASK_CONFIG_LENGTH,
    BoundType,
    DataType,
    GroupType,
    InvalidMaskConfigError,
    MaskConfig,
    MaskConfigPair,
    ModelType,
)
from .masking import Aggregation, AggregationError, Masker, UnmaskingError
from .model import Model, ModelCastError, PrimitiveCastError, Scalar
from .object import InvalidMaskObjectError, MaskObject, MaskUnit, MaskVect
from .seed import ENCRYPTED_MASK_SEED_LENGTH, MASK_SEED_LENGTH, EncryptedMaskSeed, MaskSeed

__all__ = [
    "MASK_CONFIG_LENGTH",
    "BoundType",
    "DataType",
    "GroupType",
    "InvalidMaskConfigError",
    "MaskConfig",
    "MaskConfigPair",
    "ModelType",
    "Aggregation",
    "AggregationError",
    "Masker",
    "UnmaskingError",
    "Model",
    "ModelCastError",
    "PrimitiveCastError",
    "Scalar",
    "InvalidMaskObjectError",
    "MaskObject",
    "MaskUnit",
    "MaskVect",
    "ENCRYPTED_MASK_SEED_LENGTH",
    "MASK_SEED_LENGTH",
    "EncryptedMaskSeed",
    "MaskSeed",
]
