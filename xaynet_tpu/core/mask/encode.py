"""Fixed-point encode/decode between weights and finite-group elements.

The masking pipeline (reference: rust/xaynet-core/src/mask/masking.rs:358-404)
maps a weight ``w`` to a group element:

    shifted = floor((clamp(scalar * w, -A, A) + A) * E)

with ``A = add_shift`` and ``E = exp_shift``; unmasking inverts it
(masking.rs:190-231):

    w = ((n / E) - nb_models * A) / scalar_sum

The reference computes this in exact big-rational arithmetic per weight. Here:

- **fast path** (f32 data, bounded B0-B6 — every practical config): vectorized
  numpy double-double arithmetic (error ~1e-23 ≪ the 1e-10 protocol
  tolerance), producing int64 fixed-point values that convert straight into
  limb tensors;
- **exact path** (f64 / integer data types, Bmax): python-int / Fraction math,
  bit-identical to the reference semantics.
"""

from __future__ import annotations

from fractions import Fraction

import numpy as np

from ...ops import dd
from ...ops import limbs as limb_ops
from .config import BoundType, DataType, MaskConfig

# ---------------------------------------------------------------------------
# encode
# ---------------------------------------------------------------------------


def clamp_scalar(scalar: Fraction, unit_config: MaskConfig) -> Fraction:
    """Clamp the scalar from above by the unit config's add_shift."""
    a1 = unit_config.add_shift
    return a1 if scalar > a1 else scalar


def has_fast_path(config: MaskConfig) -> bool:
    return config.data_type is DataType.F32 and config.bound_type is not BoundType.BMAX


def encode_unit(scalar_clamped: Fraction, unit_config: MaskConfig) -> int:
    """Fixed-point encode of the (clamped) scalar — always exact (one value)."""
    t = scalar_clamped + unit_config.add_shift
    return (t.numerator * unit_config.exp_shift) // t.denominator


def encode_vect_exact(weights, scalar_clamped: Fraction, config: MaskConfig) -> list[int]:
    """Exact reference-semantics encode (python Fractions)."""
    a = config.add_shift
    e = config.exp_shift
    out = []
    for w in weights:
        # numpy scalars (e.g. float32) are not Rational; unwrap to python
        scaled = scalar_clamped * Fraction(w.item() if hasattr(w, "item") else w)
        c = -a if scaled < -a else (a if scaled > a else scaled)
        t = c + a
        out.append((t.numerator * e) // t.denominator)
    return out


def encode_vect_fast(weights: np.ndarray, scalar_clamped: Fraction, config: MaskConfig) -> np.ndarray:
    """Vectorized double-double encode for bounded-f32 configs -> int64."""
    assert has_fast_path(config)
    w = np.asarray(weights, dtype=np.float64)  # f32 -> f64 is exact
    s_hi, s_lo = dd.from_fraction(scalar_clamped)
    a = float(int(config.add_shift))  # 1, 100, 1e4, 1e6 — exact
    e = float(config.exp_shift)  # 1e10 — exact in f64

    hi, lo = dd.mul_f(np.full_like(w, s_hi), np.full_like(w, s_lo), w)
    # clamp to [-a, a]
    over = (hi > a) | ((hi == a) & (lo > 0))
    under = (hi < -a) | ((hi == -a) & (lo < 0))
    hi = np.where(over, a, np.where(under, -a, hi))
    lo = np.where(over | under, 0.0, lo)
    # (c + a) * e, floored
    hi, lo = dd.add_f(hi, lo, a)
    hi, lo = dd.mul_f(hi, lo, e)
    shifted = dd.floor(hi, lo)  # integer-valued f64, <= 2*1e6*1e10 < 2^53
    return np.maximum(shifted, 0.0).astype(np.int64)


def encode_vect_limbs(weights, scalar_clamped: Fraction, config: MaskConfig) -> np.ndarray:
    """Encode weights into ``uint32[n, L]`` limb tensors (unmasked)."""
    n_limb = limb_ops.n_limbs_for_order(config.order)
    if has_fast_path(config) and isinstance(weights, np.ndarray) and weights.dtype in (
        np.float32,
        np.float64,
    ):
        shifted = encode_vect_fast(weights, scalar_clamped, config)
        out = np.zeros((shifted.shape[0], n_limb), dtype=np.uint32)
        out[:, 0] = (shifted & 0xFFFFFFFF).astype(np.uint32)
        if n_limb > 1:
            out[:, 1] = (shifted >> 32).astype(np.uint32)
        return out
    values = encode_vect_exact(weights, scalar_clamped, config)
    return limb_ops.ints_to_limbs(values, n_limb)


# ---------------------------------------------------------------------------
# decode (unmask)
# ---------------------------------------------------------------------------


def decode_scalar_sum(unit_value: int, unit_config: MaskConfig, nb_models: int) -> Fraction:
    """Recover the aggregated scalar sum from the unmasked unit — exact."""
    return Fraction(unit_value, unit_config.exp_shift) - nb_models * unit_config.add_shift


def decode_vect_exact(
    values: list[int], config: MaskConfig, nb_models: int, scalar_sum: Fraction
) -> list[Fraction]:
    a = config.add_shift
    e = config.exp_shift
    shift = nb_models * a
    return [(Fraction(v, e) - shift) / scalar_sum for v in values]


def _decode_native(limbs: np.ndarray, c_int: int, recip: Fraction):
    """Native double-double decode; None when unavailable/out of range."""
    from ...utils import native

    lib = native.load()
    n, n_limb = limbs.shape
    if (
        lib is None
        or not hasattr(lib, "xn_decode_f64")
        or n_limb > 4
        or c_int < 0
        or c_int.bit_length() > 120
    ):
        return None
    inv_hi, inv_lo = dd.from_fraction(recip)
    c_le = c_int.to_bytes(limb_ops.draw_width_for(c_int) or 1, "little")
    arr = np.ascontiguousarray(limbs, dtype=np.uint32)
    out = np.empty(n, dtype=np.float64)
    import ctypes

    rc = lib.xn_decode_f64(
        native.np_u32p(arr),
        n,
        n_limb,
        native.as_u8p(c_le),
        len(c_le),
        ctypes.c_double(inv_hi),
        ctypes.c_double(inv_lo),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
    )
    return out if rc == 0 else None


def decode_vect_any(
    limbs: np.ndarray, config: MaskConfig, nb_models: int, scalar_sum: Fraction
) -> np.ndarray:
    """Unmask decode -> float64 for ANY config family (arbitrary limb width).

    Replaces the per-element ``Fraction`` loop for i32/i64/f64/Bmax configs:
    the cancellation-prone step ``v - nb_models * A * E`` is done in exact
    multi-limb integer arithmetic (native C++ when available, vectorized
    numpy otherwise); the cancellation-free difference is then decoded from
    its top three 32-bit limbs in double-double. Worst-case relative error
    ~2^-64 (when the leading limb is small), far below both the 1/exp_shift
    protocol tolerance and the float64 output rounding that follows
    (reference: rust/xaynet-core/src/mask/masking.rs:190-231).
    """
    n, n_limb = limbs.shape
    c_int = nb_models * int(config.add_shift) * config.exp_shift
    recip = Fraction(1, 1) / (config.exp_shift * scalar_sum)
    c_nlimbs = max(1, (c_int.bit_length() + 31) // 32)
    c_limbs = limb_ops.int_to_limbs(c_int, c_nlimbs)
    # normalized mantissa + exponent: BMAX reciprocals don't fit float64
    inv_hi, inv_lo, inv_exp = dd.from_fraction_scaled(recip)

    from ...utils import native

    lib = native.load()
    if lib is not None and hasattr(lib, "xn_decode_exact") and n_limb <= 96 and c_nlimbs <= 96:
        arr = np.ascontiguousarray(limbs, dtype=np.uint32)
        c_arr = np.ascontiguousarray(c_limbs, dtype=np.uint32)
        out = np.empty(n, dtype=np.float64)
        import ctypes

        rc = lib.xn_decode_exact(
            native.np_u32p(arr),
            n,
            n_limb,
            native.np_u32p(c_arr),
            c_nlimbs,
            ctypes.c_double(inv_hi),
            ctypes.c_double(inv_lo),
            ctypes.c_int32(inv_exp),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        )
        if rc == 0:
            return out

    # numpy fallback: exact vectorized limb subtract, then top-96-bit decode
    ell = max(n_limb, c_nlimbs) + 1
    c_ext = limb_ops.int_to_limbs(c_int, ell)
    d = np.zeros((n, ell), dtype=np.uint32)
    borrow = np.zeros(n, dtype=np.int64)
    for j in range(ell):
        vj = limbs[:, j].astype(np.int64) if j < n_limb else np.zeros(n, dtype=np.int64)
        s = vj - int(c_ext[j]) - borrow
        d[:, j] = (s & 0xFFFFFFFF).astype(np.uint32)
        borrow = (s < 0).astype(np.int64)
    neg = borrow == 1
    if neg.any():  # two's-complement negate the negative rows
        carry = neg.astype(np.int64)
        for j in range(ell):
            inv = np.where(neg, (~d[:, j]).astype(np.int64) & 0xFFFFFFFF, d[:, j].astype(np.int64))
            s = inv + carry
            d[:, j] = (s & 0xFFFFFFFF).astype(np.uint32)
            carry = s >> 32
    # top three limbs -> <= 96-bit double-double, exponent applied via ldexp
    # (same scheme as the native kernel: no intermediate over/underflow)
    rows = np.arange(n)
    t = ell - 1 - np.argmax((d != 0)[:, ::-1], axis=1)  # top nonzero limb (0 if none)
    l0 = d[rows, t].astype(np.float64)
    l1 = np.where(t >= 1, d[rows, np.maximum(t - 1, 0)], 0).astype(np.float64)
    l2 = np.where(t >= 2, d[rows, np.maximum(t - 2, 0)], 0).astype(np.float64)
    hi = l0 * 18446744073709551616.0  # * 2^64, exact
    hi, lo = dd.add_f(hi, np.zeros(n), l1 * 4294967296.0)  # + l1 * 2^32, exact
    hi, lo = dd.add(hi, lo, l2, np.zeros(n))
    hi, lo = dd.mul(hi, lo, np.full(n, inv_hi), np.full(n, inv_lo))
    exp = (32 * (t.astype(np.int64) - 2) + inv_exp).astype(np.int32)
    # Bmax extremes can exceed float64 range; inf is the intended result
    # there (oracle-checked in tests/test_decode_exact.py), not an error
    with np.errstate(over="ignore"):
        out = np.ldexp(hi, exp) + np.ldexp(lo, exp)
    return np.where(neg, -out, out)


def decode_vect_fast(
    limbs: np.ndarray, config: MaskConfig, nb_models: int, scalar_sum: Fraction
) -> np.ndarray:
    """Vectorized double-double decode -> float64 array (f32-accurate+).

    Structured for memory-bandwidth: scaling by 2^32 is exact on both dd
    components (no renormalization pass), constants broadcast as scalars,
    and the division by ``E * scalar_sum`` becomes one dd multiply by a
    precomputed dd reciprocal (~1e-32 relative, far below tolerance).
    """
    assert has_fast_path(config)
    n, n_limb = limbs.shape
    c_int = nb_models * int(config.add_shift) * config.exp_shift
    recip = Fraction(1, 1) / (config.exp_shift * scalar_sum)
    native_out = _decode_native(limbs, c_int, recip)
    if native_out is not None:
        return native_out
    # limbs -> double-double value (high to low; power-of-two scaling exact)
    hi = limbs[:, n_limb - 1].astype(np.float64)
    lo = np.zeros(n)
    for j in range(n_limb - 2, -1, -1):
        hi = hi * 4294967296.0
        lo = lo * 4294967296.0
        hi, lo = dd.add_f(hi, lo, limbs[:, j].astype(np.float64))
    # subtract nb_models * A * E (exact integer; scalar dd constant)
    c_hi, c_lo = dd.from_fraction(nb_models * int(config.add_shift) * config.exp_shift)
    hi, lo = dd.add(hi, lo, -c_hi, -c_lo)
    # multiply by the dd reciprocal of E * scalar_sum
    r_hi, r_lo = dd.from_fraction(Fraction(1, 1) / (config.exp_shift * scalar_sum))
    hi, lo = dd.mul(hi, lo, r_hi, r_lo)
    return dd.to_float(hi, lo)
