"""Mask objects: masked models / masks as fixed-width limb tensors.

Reference shape (rust/xaynet-core/src/mask/object/mod.rs:24,65,117):
``MaskVect`` (vector of group elements) + ``MaskUnit`` (one group element for
the masked scalar) compose a ``MaskObject``. Validity means every element is
below the configured group order.

TPU-native representation: elements live as ``uint32[n, L]`` limb arrays
(little-endian limb order) — the exact layout the aggregation kernels and the
wire codec consume — instead of python bignums.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ...ops import limbs as limb_ops
from .config import MaskConfig, MaskConfigPair


class InvalidMaskObjectError(ValueError):
    """Mask object data does not satisfy its masking configuration."""


def _order_limbs(config: MaskConfig) -> np.ndarray:
    return limb_ops.order_limbs_for(config.order)


@dataclass
class MaskVect:
    """A vector of finite-group elements with its masking configuration."""

    config: MaskConfig
    data: np.ndarray  # uint32[n, L]

    @classmethod
    def from_ints(cls, config: MaskConfig, values) -> "MaskVect":
        n_limb = limb_ops.n_limbs_for_order(config.order)
        return cls(config, limb_ops.ints_to_limbs(values, n_limb))

    @classmethod
    def new(cls, config: MaskConfig, values) -> "MaskVect":
        obj = cls.from_ints(config, values) if not isinstance(values, np.ndarray) else cls(config, values)
        if not obj.is_valid():
            raise InvalidMaskObjectError("mask vector element >= group order")
        return obj

    def to_ints(self) -> list[int]:
        return limb_ops.limbs_to_ints(self.data)

    def __len__(self) -> int:
        return self.data.shape[0]

    def is_valid(self) -> bool:
        if self.data.ndim != 2:
            return False
        n_limb = limb_ops.n_limbs_for_order(self.config.order)
        if self.data.shape[1] != n_limb:
            return False
        return limb_ops.all_lt_order(self.data, self.config.order)

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, MaskVect)
            and self.config == other.config
            and self.data.shape == other.data.shape
            and bool(np.array_equal(self.data, other.data))
        )


@dataclass
class MaskUnit:
    """A single finite-group element (the masked scalar) with its config."""

    config: MaskConfig
    data: np.ndarray  # uint32[L]

    @classmethod
    def from_int(cls, config: MaskConfig, value: int) -> "MaskUnit":
        n_limb = limb_ops.n_limbs_for_order(config.order)
        return cls(config, limb_ops.int_to_limbs(value, n_limb))

    @classmethod
    def new(cls, config: MaskConfig, value: int) -> "MaskUnit":
        obj = cls.from_int(config, value)
        if not obj.is_valid():
            raise InvalidMaskObjectError("mask unit element >= group order")
        return obj

    def to_int(self) -> int:
        return limb_ops.limbs_to_int(self.data)

    def is_valid(self) -> bool:
        n_limb = limb_ops.n_limbs_for_order(self.config.order)
        if self.data.shape != (n_limb,):
            return False
        return bool(limb_ops.elements_lt_order(self.data[None, :], self.config.order)[0])

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, MaskUnit)
            and self.config == other.config
            and bool(np.array_equal(self.data, other.data))
        )


@dataclass
class MaskObject:
    """A masked model (or mask): vector part + unit (scalar) part."""

    vect: MaskVect
    unit: MaskUnit

    @classmethod
    def new(cls, config: MaskConfigPair, vect_values, unit_value: int) -> "MaskObject":
        return cls(MaskVect.new(config.vect, vect_values), MaskUnit.new(config.unit, unit_value))

    @classmethod
    def empty(cls, config: MaskConfigPair, size: int) -> "MaskObject":
        n_limb_v = limb_ops.n_limbs_for_order(config.vect.order)
        n_limb_u = limb_ops.n_limbs_for_order(config.unit.order)
        return cls(
            MaskVect(config.vect, np.zeros((size, n_limb_v), dtype=np.uint32)),
            MaskUnit(config.unit, np.zeros(n_limb_u, dtype=np.uint32)),
        )

    @property
    def config(self) -> MaskConfigPair:
        return MaskConfigPair(vect=self.vect.config, unit=self.unit.config)

    def __len__(self) -> int:
        return len(self.vect)

    def is_valid(self) -> bool:
        return self.vect.is_valid() and self.unit.is_valid()

    def __eq__(self, other) -> bool:
        return isinstance(other, MaskObject) and self.vect == other.vect and self.unit == other.unit
