"""Mask objects: masked models / masks as fixed-width limb tensors.

Reference shape (rust/xaynet-core/src/mask/object/mod.rs:24,65,117):
``MaskVect`` (vector of group elements) + ``MaskUnit`` (one group element for
the masked scalar) compose a ``MaskObject``. Validity means every element is
below the configured group order.

TPU-native representation: elements live as ``uint32[n, L]`` limb arrays
(little-endian limb order) — the exact layout the aggregation kernels and the
wire codec consume — instead of python bignums.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ...ops import limbs as limb_ops
from .config import MaskConfig, MaskConfigPair


class InvalidMaskObjectError(ValueError):
    """Mask object data does not satisfy its masking configuration."""


def _order_limbs(config: MaskConfig) -> np.ndarray:
    return limb_ops.order_limbs_for(config.order)


@dataclass
class MaskVect:
    """A vector of finite-group elements with its masking configuration."""

    config: MaskConfig
    data: np.ndarray  # uint32[n, L]

    @classmethod
    def from_ints(cls, config: MaskConfig, values) -> "MaskVect":
        n_limb = limb_ops.n_limbs_for_order(config.order)
        return cls(config, limb_ops.ints_to_limbs(values, n_limb))

    @classmethod
    def new(cls, config: MaskConfig, values) -> "MaskVect":
        obj = cls.from_ints(config, values) if not isinstance(values, np.ndarray) else cls(config, values)
        if not obj.is_valid():
            raise InvalidMaskObjectError("mask vector element >= group order")
        return obj

    def to_ints(self) -> list[int]:
        return limb_ops.limbs_to_ints(self.data)

    def __len__(self) -> int:
        return self.data.shape[0]

    def is_valid(self) -> bool:
        if self.data.ndim != 2:
            return False
        n_limb = limb_ops.n_limbs_for_order(self.config.order)
        if self.data.shape[1] != n_limb:
            return False
        return limb_ops.all_lt_order(self.data, self.config.order)

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, MaskVect)
            and self.config == other.config
            and self.data.shape == other.data.shape
            and bool(np.array_equal(self.data, other.data))
        )


class LazyWireMaskVect(MaskVect):
    """A ``MaskVect`` parsed from wire with limb materialization DEFERRED.

    Carries the raw fixed-width element block (``wire_block``, a zero-copy
    uint8 view) so a device-ingest coordinator can unpack + validity-check
    + fold on the accelerator without ever running the host element parse
    (the second hot loop after the fold). Any host access to ``data``
    materializes the limbs exactly like the eager parse would have;
    ``is_valid()`` then applies the same element rule. The eager parse
    rejects invalid elements with ``DecodeError`` at parse time; the lazy
    path defers that rejection to ``validate_aggregation`` (device) or the
    first host materialization — same update rejected, one stage later.
    """

    def __init__(
        self, config: MaskConfig, wire_block: np.ndarray, count: int, planar: bool = False
    ):
        self.config = config
        self.wire_block = wire_block  # uint8[count * bytes_per_number]
        self._count = count
        # wire format v2: the block is byte-planar (bpn planes of count
        # bytes) instead of interleaved — already the packed staging layout
        self.planar = planar
        self._data: np.ndarray | None = None
        # device planar cached by StagedAggregator.validate_aggregation so
        # stage() never re-uploads; _wire_invalid is the cached REJECTED
        # verdict from a batch prevalidation (validate_aggregation raises
        # on it without another device round-trip)
        self._staged_planar = None
        self._wire_invalid = False

    @property
    def materialized(self) -> bool:
        return self._data is not None

    @property
    def planar_block(self) -> np.ndarray:
        """Zero-copy ``uint8[bpn, count]`` view of a v2 planar element block
        (the shape the packed staging rings and the device planar-unpack
        consume directly)."""
        if not self.planar:
            raise ValueError("planar_block on an interleaved (v1) wire vect")
        return np.asarray(self.wire_block).reshape(
            self.config.bytes_per_number, self._count
        )

    @property  # type: ignore[override]
    def data(self) -> np.ndarray:
        if self._data is None:
            block = np.asarray(self.wire_block)
            if self.planar:
                from .serialization import planar_to_interleaved

                block = planar_to_interleaved(
                    block, self._count, self.config.bytes_per_number
                )
            self._data = limb_ops.bytes_le_to_limbs(
                block, self._count, self.config.bytes_per_number
            )
        return self._data

    @data.setter
    def data(self, value) -> None:  # dataclass-compat (never used in practice)
        self._data = value

    def __len__(self) -> int:
        return self._count


@dataclass
class MaskUnit:
    """A single finite-group element (the masked scalar) with its config."""

    config: MaskConfig
    data: np.ndarray  # uint32[L]

    @classmethod
    def from_int(cls, config: MaskConfig, value: int) -> "MaskUnit":
        n_limb = limb_ops.n_limbs_for_order(config.order)
        return cls(config, limb_ops.int_to_limbs(value, n_limb))

    @classmethod
    def new(cls, config: MaskConfig, value: int) -> "MaskUnit":
        obj = cls.from_int(config, value)
        if not obj.is_valid():
            raise InvalidMaskObjectError("mask unit element >= group order")
        return obj

    def to_int(self) -> int:
        return limb_ops.limbs_to_int(self.data)

    def is_valid(self) -> bool:
        n_limb = limb_ops.n_limbs_for_order(self.config.order)
        if self.data.shape != (n_limb,):
            return False
        return bool(limb_ops.elements_lt_order(self.data[None, :], self.config.order)[0])

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, MaskUnit)
            and self.config == other.config
            and bool(np.array_equal(self.data, other.data))
        )


@dataclass
class MaskObject:
    """A masked model (or mask): vector part + unit (scalar) part."""

    vect: MaskVect
    unit: MaskUnit

    @classmethod
    def new(cls, config: MaskConfigPair, vect_values, unit_value: int) -> "MaskObject":
        return cls(MaskVect.new(config.vect, vect_values), MaskUnit.new(config.unit, unit_value))

    @classmethod
    def empty(cls, config: MaskConfigPair, size: int) -> "MaskObject":
        n_limb_v = limb_ops.n_limbs_for_order(config.vect.order)
        n_limb_u = limb_ops.n_limbs_for_order(config.unit.order)
        return cls(
            MaskVect(config.vect, np.zeros((size, n_limb_v), dtype=np.uint32)),
            MaskUnit(config.unit, np.zeros(n_limb_u, dtype=np.uint32)),
        )

    @property
    def config(self) -> MaskConfigPair:
        return MaskConfigPair(vect=self.vect.config, unit=self.unit.config)

    def __len__(self) -> int:
        return len(self.vect)

    def is_valid(self) -> bool:
        return self.vect.is_valid() and self.unit.is_valid()

    def __eq__(self, other) -> bool:
        return isinstance(other, MaskObject) and self.vect == other.vect and self.unit == other.unit
