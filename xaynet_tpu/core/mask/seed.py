"""Mask seeds and their sealed-box encryption.

Reference: rust/xaynet-core/src/mask/seed.rs:48-136. A 32-byte seed expands
(via the ChaCha20 rejection sampler) into a full mask object; update
participants encrypt their seed for every sum participant's ephemeral key.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from ..crypto.encrypt import DecryptError, PublicEncryptKey, SecretEncryptKey, SEALBYTES
from ..crypto.prng import StreamSampler
from .config import MaskConfigPair
from .object import MaskObject, MaskUnit, MaskVect

MASK_SEED_LENGTH = 32
ENCRYPTED_MASK_SEED_LENGTH = SEALBYTES + MASK_SEED_LENGTH  # 80


@dataclass(frozen=True)
class MaskSeed:
    bytes_: bytes

    def __post_init__(self):
        if len(self.bytes_) != MASK_SEED_LENGTH:
            raise ValueError("mask seed must be 32 bytes")

    @classmethod
    def generate(cls) -> "MaskSeed":
        return cls(os.urandom(MASK_SEED_LENGTH))

    def as_bytes(self) -> bytes:
        return self.bytes_

    def encrypt(self, pk: PublicEncryptKey) -> "EncryptedMaskSeed":
        return EncryptedMaskSeed(pk.encrypt(self.bytes_))

    def derive_mask(self, length: int, config: MaskConfigPair) -> MaskObject:
        """Expand this seed into a mask: 1 unit draw, then ``length`` vector draws."""
        sampler = StreamSampler(self.bytes_)
        unit = sampler.draw_limbs(1, config.unit.order)[0]
        vect = sampler.draw_limbs(length, config.vect.order)
        return MaskObject(MaskVect(config.vect, vect), MaskUnit(config.unit, unit))


@dataclass(frozen=True)
class EncryptedMaskSeed:
    bytes_: bytes

    def __post_init__(self):
        if len(self.bytes_) != ENCRYPTED_MASK_SEED_LENGTH:
            raise ValueError("encrypted mask seed must be 80 bytes")

    def as_bytes(self) -> bytes:
        return self.bytes_

    def decrypt(self, sk: SecretEncryptKey, pk: PublicEncryptKey | None = None) -> MaskSeed:
        try:
            plain = sk.decrypt(self.bytes_, pk)
        except DecryptError:
            raise
        if len(plain) != MASK_SEED_LENGTH:
            raise DecryptError("decrypted mask seed has invalid length")
        return MaskSeed(plain)


# --- batched seed fan-out wire format (GET /seeds?fmt=bin, §21) -----------
#
# count(u32 BE) ‖ count x [ participant pk(32) ‖ encrypted seed(80) ]
#
# Fixed 112-byte entries: a sum participant fetching a 100k-update seed
# slice downloads ~11 MB of raw entries instead of ~22 MB of JSON hex, and
# both ends slice instead of parsing. The JSON shape stays the default —
# the binary body is opt-in per request and byte-equivalent in content.

SEED_ENTRY_PK_LENGTH = 32
SEED_ENTRY_LENGTH = SEED_ENTRY_PK_LENGTH + ENCRYPTED_MASK_SEED_LENGTH  # 112


def pack_seed_entries(seed_dict: dict) -> bytes:
    """Serialize an UpdateSeedDict slice ``{pk: EncryptedMaskSeed}`` into
    the batched binary fan-out body (deterministic: entries sorted by pk,
    so identical dicts serialize identically)."""
    parts = [len(seed_dict).to_bytes(4, "big")]
    for pk in sorted(seed_dict):
        if len(pk) != SEED_ENTRY_PK_LENGTH:
            raise ValueError("seed-dict pk must be 32 bytes")
        parts.append(pk)
        parts.append(seed_dict[pk].as_bytes())
    return b"".join(parts)


def unpack_seed_entries(data) -> dict:
    """Parse a batched binary fan-out body back into
    ``{pk: EncryptedMaskSeed}``. Accepts any buffer; slices views, never
    copies the body. Raises ``ValueError`` on a malformed frame."""
    view = memoryview(data)
    if len(view) < 4:
        raise ValueError("seed fan-out body too short")
    count = int.from_bytes(view[:4], "big")
    if len(view) != 4 + count * SEED_ENTRY_LENGTH:
        raise ValueError("seed fan-out length does not match the framed count")
    out = {}
    for i in range(count):
        start = 4 + i * SEED_ENTRY_LENGTH
        pk = bytes(view[start : start + SEED_ENTRY_PK_LENGTH])
        seed = bytes(
            view[start + SEED_ENTRY_PK_LENGTH : start + SEED_ENTRY_LENGTH]
        )
        out[pk] = EncryptedMaskSeed(seed)
    return out
