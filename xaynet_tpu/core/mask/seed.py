"""Mask seeds and their sealed-box encryption.

Reference: rust/xaynet-core/src/mask/seed.rs:48-136. A 32-byte seed expands
(via the ChaCha20 rejection sampler) into a full mask object; update
participants encrypt their seed for every sum participant's ephemeral key.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from ..crypto.encrypt import DecryptError, PublicEncryptKey, SecretEncryptKey, SEALBYTES
from ..crypto.prng import StreamSampler
from .config import MaskConfigPair
from .object import MaskObject, MaskUnit, MaskVect

MASK_SEED_LENGTH = 32
ENCRYPTED_MASK_SEED_LENGTH = SEALBYTES + MASK_SEED_LENGTH  # 80


@dataclass(frozen=True)
class MaskSeed:
    bytes_: bytes

    def __post_init__(self):
        if len(self.bytes_) != MASK_SEED_LENGTH:
            raise ValueError("mask seed must be 32 bytes")

    @classmethod
    def generate(cls) -> "MaskSeed":
        return cls(os.urandom(MASK_SEED_LENGTH))

    def as_bytes(self) -> bytes:
        return self.bytes_

    def encrypt(self, pk: PublicEncryptKey) -> "EncryptedMaskSeed":
        return EncryptedMaskSeed(pk.encrypt(self.bytes_))

    def derive_mask(self, length: int, config: MaskConfigPair) -> MaskObject:
        """Expand this seed into a mask: 1 unit draw, then ``length`` vector draws."""
        sampler = StreamSampler(self.bytes_)
        unit = sampler.draw_limbs(1, config.unit.order)[0]
        vect = sampler.draw_limbs(length, config.vect.order)
        return MaskObject(MaskVect(config.vect, vect), MaskUnit(config.unit, unit))


@dataclass(frozen=True)
class EncryptedMaskSeed:
    bytes_: bytes

    def __post_init__(self):
        if len(self.bytes_) != ENCRYPTED_MASK_SEED_LENGTH:
            raise ValueError("encrypted mask seed must be 80 bytes")

    def as_bytes(self) -> bytes:
        return self.bytes_

    def decrypt(self, sk: SecretEncryptKey, pk: PublicEncryptKey | None = None) -> MaskSeed:
        try:
            plain = sk.decrypt(self.bytes_, pk)
        except DecryptError:
            raise
        if len(plain) != MASK_SEED_LENGTH:
            raise DecryptError("decrypted mask seed has invalid length")
        return MaskSeed(plain)
