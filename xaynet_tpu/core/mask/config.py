"""Masking configurations: finite-group catalogue and fixed-point shifts.

Reimplements the reference's `MaskConfig` surface (reference:
rust/xaynet-core/src/mask/config/mod.rs:41-231): the
(GroupType x DataType x BoundType x ModelType) grid, the derived
``add_shift`` (weight bound), ``exp_shift`` (fixed-point scale),
``bytes_per_number`` (wire width) and the 240-entry group-order catalogue
(protocol constants, generated into ``_orders_data.py``).

Wire encoding is 4 bytes: [group, data, bound, model] (reference:
rust/xaynet-core/src/mask/config/serialization.rs:19-23).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from enum import IntEnum
from fractions import Fraction
from functools import cached_property

from ._orders_data import ORDERS

MASK_CONFIG_LENGTH = 4

_F32_MAX = int(2**128 - 2**104)  # f32::MAX is an exact integer
_F64_MAX = int(2**1024 - 2**971)  # f64::MAX is an exact integer


class InvalidMaskConfigError(ValueError):
    """A serialized masking configuration field is out of range."""


class GroupType(IntEnum):
    INTEGER = 0
    PRIME = 1
    POWER2 = 2


class DataType(IntEnum):
    F32 = 0
    F64 = 1
    I32 = 2
    I64 = 3


class BoundType(IntEnum):
    B0 = 0
    B2 = 2
    B4 = 4
    B6 = 6
    BMAX = 255


class ModelType(IntEnum):
    M3 = 3
    M6 = 6
    M9 = 9
    M12 = 12

    @property
    def max_nb_models(self) -> int:
        return 10**int(self)


_GROUP_KEY = {GroupType.INTEGER: "Integer", GroupType.PRIME: "Prime", GroupType.POWER2: "Power2"}
_DATA_KEY = {DataType.F32: "F32", DataType.F64: "F64", DataType.I32: "I32", DataType.I64: "I64"}
_BOUND_KEY = {
    BoundType.B0: "B0",
    BoundType.B2: "B2",
    BoundType.B4: "B4",
    BoundType.B6: "B6",
    BoundType.BMAX: "Bmax",
}
_MODEL_KEY = {ModelType.M3: "M3", ModelType.M6: "M6", ModelType.M9: "M9", ModelType.M12: "M12"}


@dataclass(frozen=True)
class MaskConfig:
    """A masking configuration (hashable, usable as a dict key)."""

    group_type: GroupType
    data_type: DataType
    bound_type: BoundType
    model_type: ModelType

    @cached_property
    def order(self) -> int:
        """The finite-group order (protocol constant)."""
        return ORDERS[
            (
                _GROUP_KEY[self.group_type],
                _DATA_KEY[self.data_type],
                _BOUND_KEY[self.bound_type],
                _MODEL_KEY[self.model_type],
            )
        ]

    @cached_property
    def add_shift(self) -> Fraction:
        """Additive shift bound: weights are clamped to [-add_shift, add_shift]."""
        if self.bound_type is BoundType.B0:
            return Fraction(1)
        if self.bound_type is BoundType.B2:
            return Fraction(100)
        if self.bound_type is BoundType.B4:
            return Fraction(10_000)
        if self.bound_type is BoundType.B6:
            return Fraction(1_000_000)
        # BMAX: the data type's maximum absolute value, exactly
        if self.data_type is DataType.F32:
            return Fraction(_F32_MAX)
        if self.data_type is DataType.F64:
            return Fraction(_F64_MAX)
        if self.data_type is DataType.I32:
            return Fraction(2**31)
        return Fraction(2**63)

    @cached_property
    def exp_shift(self) -> int:
        """Fixed-point scale: weights are quantized to 1/exp_shift steps."""
        if self.data_type is DataType.F32:
            return 10**45 if self.bound_type is BoundType.BMAX else 10**10
        if self.data_type is DataType.F64:
            return 10**324 if self.bound_type is BoundType.BMAX else 10**20
        return 10**10

    @cached_property
    def bytes_per_number(self) -> int:
        """Fixed wire width of one group element."""
        return ((self.order - 1).bit_length() + 7) // 8

    @property
    def max_nb_models(self) -> int:
        return self.model_type.max_nb_models

    # --- wire format -----------------------------------------------------

    def to_bytes(self) -> bytes:
        return struct.pack(
            "BBBB",
            int(self.group_type),
            int(self.data_type),
            int(self.bound_type),
            int(self.model_type),
        )

    @classmethod
    def from_bytes(cls, data: bytes) -> "MaskConfig":
        if len(data) < MASK_CONFIG_LENGTH:
            raise InvalidMaskConfigError("mask config buffer too short")
        g, d, b, m = struct.unpack_from("BBBB", data)
        try:
            return cls(GroupType(g), DataType(d), BoundType(b), ModelType(m))
        except ValueError as e:
            raise InvalidMaskConfigError(str(e)) from e

    def pair(self) -> "MaskConfigPair":
        return MaskConfigPair(vect=self, unit=self)


@dataclass(frozen=True)
class MaskConfigPair:
    """Masking configurations for (vector of weights, unit scalar)."""

    vect: MaskConfig
    unit: MaskConfig

    def to_bytes(self) -> bytes:
        return self.vect.to_bytes() + self.unit.to_bytes()

    @classmethod
    def from_bytes(cls, data: bytes) -> "MaskConfigPair":
        return cls(
            vect=MaskConfig.from_bytes(data[:MASK_CONFIG_LENGTH]),
            unit=MaskConfig.from_bytes(data[MASK_CONFIG_LENGTH : 2 * MASK_CONFIG_LENGTH]),
        )
