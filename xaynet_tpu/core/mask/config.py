"""Masking configurations: finite-group catalogue and fixed-point shifts.

Reimplements the reference's `MaskConfig` surface (reference:
rust/xaynet-core/src/mask/config/mod.rs:41-231): the
(GroupType x DataType x BoundType x ModelType) grid, the derived
``add_shift`` (weight bound), ``exp_shift`` (fixed-point scale),
``bytes_per_number`` (wire width) and the 240-entry group-order catalogue
(protocol constants, generated into ``_orders_data.py``).

Wire encoding is 4 bytes: [group, data, bound, model] (reference:
rust/xaynet-core/src/mask/config/serialization.rs:19-23).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from enum import IntEnum
from fractions import Fraction
from functools import cached_property

from ._orders_data import ORDERS

MASK_CONFIG_LENGTH = 4

_F32_MAX = int(2**128 - 2**104)  # f32::MAX is an exact integer
_F64_MAX = int(2**1024 - 2**971)  # f64::MAX is an exact integer


class InvalidMaskConfigError(ValueError):
    """A serialized masking configuration field is out of range."""


class GroupType(IntEnum):
    INTEGER = 0
    PRIME = 1
    POWER2 = 2


class DataType(IntEnum):
    F32 = 0
    F64 = 1
    I32 = 2
    I64 = 3


class BoundType(IntEnum):
    B0 = 0
    B2 = 2
    B4 = 4
    B6 = 6
    BMAX = 255


class ModelType(IntEnum):
    M3 = 3
    M6 = 6
    M9 = 9
    M12 = 12

    @property
    def max_nb_models(self) -> int:
        return 10**int(self)


_GROUP_KEY = {GroupType.INTEGER: "Integer", GroupType.PRIME: "Prime", GroupType.POWER2: "Power2"}
_DATA_KEY = {DataType.F32: "F32", DataType.F64: "F64", DataType.I32: "I32", DataType.I64: "I64"}
_BOUND_KEY = {
    BoundType.B0: "B0",
    BoundType.B2: "B2",
    BoundType.B4: "B4",
    BoundType.B6: "B6",
    BoundType.BMAX: "Bmax",
}
_MODEL_KEY = {ModelType.M3: "M3", ModelType.M6: "M6", ModelType.M9: "M9", ModelType.M12: "M12"}


def _is_probable_prime(n: int) -> bool:
    """Deterministic Miller-Rabin for n < 3.3e24 (fixed witness set); the
    same fixed witnesses above that — a strong-probable-prime test. The
    protocol property that matters is DETERMINISM (coordinator and every
    participant compute the identical order from the same config bytes);
    the witness set is exhaustive for every f32/i32 quantized order."""
    if n < 2:
        return False
    for p in (2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37):
        if n % p == 0:
            return n == p
    d, r = n - 1, 0
    while d % 2 == 0:
        d //= 2
        r += 1
    for a in (2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37):
        x = pow(a, d, n)
        if x in (1, n - 1):
            continue
        for _ in range(r - 1):
            x = x * x % n
            if x == n - 1:
                break
        else:
            return False
    return True


def _next_prime(n: int) -> int:
    if n <= 2:
        return 2
    c = n | 1  # first odd >= n
    while not _is_probable_prime(c):
        c += 2
    return c


@dataclass(frozen=True)
class MaskConfig:
    """A masking configuration (hashable, usable as a dict key).

    ``quant`` is the pre-mask quantization level (docs/DESIGN.md §17):
    level q divides the fixed-point scale ``exp_shift`` by ``10^q``, which
    shrinks the derived group order — and with it the limb count, the wire
    width, the mask derivation cost and every fold/transfer byte —
    proportionally, at the price of ``10^q`` coarser weights. ``quant = 0``
    (the default) is the exact catalogue config; quantized orders are
    DERIVED from the reference's own construction (Integer: the exact
    range product; Prime: next prime; Power2: next power of two).
    """

    group_type: GroupType
    data_type: DataType
    bound_type: BoundType
    model_type: ModelType
    quant: int = 0

    def __post_init__(self) -> None:
        # the scale ceiling (exp_shift would underflow past it) AND the
        # wire ceiling (quant rides a nibble in to_bytes, so levels > 15
        # are unannouncable — only BMAX scales are deep enough to hit it)
        ceiling = min(15, self._exp_shift_pow())
        if not (0 <= self.quant <= ceiling):
            raise InvalidMaskConfigError(
                f"quant must be in [0, {ceiling}] for this "
                f"data/bound type, got {self.quant}"
            )

    def _exp_shift_pow(self) -> int:
        """log10 of the UNQUANTIZED fixed-point scale (the quant ceiling)."""
        if self.data_type is DataType.F32:
            return 45 if self.bound_type is BoundType.BMAX else 10
        if self.data_type is DataType.F64:
            return 324 if self.bound_type is BoundType.BMAX else 20
        return 10

    @cached_property
    def order(self) -> int:
        """The finite-group order (protocol constant; derived for
        quantized configs)."""
        if self.quant == 0:
            return ORDERS[
                (
                    _GROUP_KEY[self.group_type],
                    _DATA_KEY[self.data_type],
                    _BOUND_KEY[self.bound_type],
                    _MODEL_KEY[self.model_type],
                )
            ]
        # the reference's order construction (mod.rs:234-635) at the
        # quantized scale: the group must represent every aggregate of
        # max_nb_models encoded values in [0, 2 * add_shift * exp_shift]
        base = 2 * int(self.add_shift) * self.exp_shift * self.max_nb_models + 1
        if self.group_type is GroupType.INTEGER:
            return base
        if self.group_type is GroupType.POWER2:
            return 1 << (base - 1).bit_length()
        return _next_prime(base)

    @cached_property
    def add_shift(self) -> Fraction:
        """Additive shift bound: weights are clamped to [-add_shift, add_shift]."""
        if self.bound_type is BoundType.B0:
            return Fraction(1)
        if self.bound_type is BoundType.B2:
            return Fraction(100)
        if self.bound_type is BoundType.B4:
            return Fraction(10_000)
        if self.bound_type is BoundType.B6:
            return Fraction(1_000_000)
        # BMAX: the data type's maximum absolute value, exactly
        if self.data_type is DataType.F32:
            return Fraction(_F32_MAX)
        if self.data_type is DataType.F64:
            return Fraction(_F64_MAX)
        if self.data_type is DataType.I32:
            return Fraction(2**31)
        return Fraction(2**63)

    @cached_property
    def exp_shift(self) -> int:
        """Fixed-point scale: weights are quantized to 1/exp_shift steps
        (divided by ``10^quant`` for quantized rounds)."""
        return 10 ** (self._exp_shift_pow() - self.quant)

    @cached_property
    def bytes_per_number(self) -> int:
        """Fixed wire width of one group element (the single source of
        truth lives in ops/limbs.wire_width_for)."""
        from ...ops.limbs import wire_width_for

        return wire_width_for(self.order)

    @property
    def max_nb_models(self) -> int:
        return self.model_type.max_nb_models

    # --- wire format -----------------------------------------------------

    def to_bytes(self) -> bytes:
        # the quant level rides the unused high nibble of the model byte
        # (ModelType values are 3..12): quant = 0 serializes byte-identically
        # to the reference wire format, so unquantized golden vectors and
        # old readers are untouched. Levels > 15 are unrepresentable;
        # __post_init__ enforces the same ceiling at construction, so this
        # is a defensive invariant, not a reachable path.
        if self.quant > 15:
            raise InvalidMaskConfigError("quant > 15 has no wire encoding")
        return struct.pack(
            "BBBB",
            int(self.group_type),
            int(self.data_type),
            int(self.bound_type),
            int(self.model_type) | (self.quant << 4),
        )

    @classmethod
    def from_bytes(cls, data: bytes) -> "MaskConfig":
        if len(data) < MASK_CONFIG_LENGTH:
            raise InvalidMaskConfigError("mask config buffer too short")
        g, d, b, m = struct.unpack_from("BBBB", data)
        try:
            return cls(
                GroupType(g), DataType(d), BoundType(b), ModelType(m & 0x0F), m >> 4
            )
        except ValueError as e:
            raise InvalidMaskConfigError(str(e)) from e

    def pair(self) -> "MaskConfigPair":
        return MaskConfigPair(vect=self, unit=self)


@dataclass(frozen=True)
class MaskConfigPair:
    """Masking configurations for (vector of weights, unit scalar)."""

    vect: MaskConfig
    unit: MaskConfig

    def to_bytes(self) -> bytes:
        return self.vect.to_bytes() + self.unit.to_bytes()

    @classmethod
    def from_bytes(cls, data: bytes) -> "MaskConfigPair":
        return cls(
            vect=MaskConfig.from_bytes(data[:MASK_CONFIG_LENGTH]),
            unit=MaskConfig.from_bytes(data[MASK_CONFIG_LENGTH : 2 * MASK_CONFIG_LENGTH]),
        )
