"""Masking, aggregation and unmasking of models.

Functional port of the reference engine (reference:
rust/xaynet-core/src/mask/masking.rs:74-418) over the TPU-native limb
representation:

- ``Masker.mask``: clamp/scale/shift weights into the finite group (see
  ``encode``), then add ChaCha20-derived random group elements — the random
  draws are bit-identical to the reference so sum participants and the
  coordinator derive identical masks from the same seed;
- ``Aggregation.aggregate``: elementwise modular addition over ``uint32[n,L]``
  limb tensors (the coordinator hot loop; device version in
  ``xaynet_tpu.ops.limbs_jax``);
- ``Aggregation.unmask``: modular subtract of the aggregated mask, then
  fixed-point decode and scalar-sum correction.
"""

from __future__ import annotations

from typing import Union

import numpy as np

from ...ops import limbs as limb_ops
from ..crypto.prng import StreamSampler
from .config import MaskConfig, MaskConfigPair
from .encode import (
    clamp_scalar,
    decode_scalar_sum,
    decode_vect_any,
    decode_vect_exact,
    decode_vect_fast,
    encode_unit,
    encode_vect_limbs,
    has_fast_path,
)
from fractions import Fraction
from .model import Model, Scalar
from .object import MaskObject, MaskUnit, MaskVect
from .seed import MaskSeed


class AggregationError(ValueError):
    """Aggregation validation failure; ``kind`` mirrors the reference enum."""

    def __init__(self, kind: str):
        super().__init__(f"aggregation error: {kind}")
        self.kind = kind


class UnmaskingError(ValueError):
    """Unmasking validation failure; ``kind`` mirrors the reference enum."""

    def __init__(self, kind: str):
        super().__init__(f"unmasking error: {kind}")
        self.kind = kind


def _order_limbs(config: MaskConfig) -> np.ndarray:
    return limb_ops.order_limbs_for(config.order)


def _mask_native(seed: bytes, sampler: StreamSampler, weights: np.ndarray,
                 s_clamped: Fraction, config: MaskConfig):
    """Fused native mask (draw + dd encode + mod add); None when unavailable."""
    from ...ops import dd
    from ...utils import native

    lib = native.load()
    if lib is None or not hasattr(lib, "xn_mask_f32"):
        return None
    order = config.order
    draw_nbytes = limb_ops.draw_width_for(order)
    elem_nbytes = config.bytes_per_number
    if draw_nbytes > 16:
        return None
    import ctypes

    n = weights.shape[0]
    s_hi, s_lo = dd.from_fraction(s_clamped)
    out = np.empty(n * elem_nbytes, dtype=np.uint8)
    w = np.ascontiguousarray(weights, dtype=np.float32)
    new_offset = lib.xn_mask_f32(
        native.as_u8p(seed),
        sampler.consumed_bytes,
        w.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n,
        native.as_u8p(order.to_bytes(draw_nbytes, "little")),
        draw_nbytes,
        elem_nbytes,
        ctypes.c_double(float(int(config.add_shift))),
        ctypes.c_double(float(config.exp_shift)),
        ctypes.c_double(s_hi),
        ctypes.c_double(s_lo),
        native.np_u8p(out),
    )
    if new_offset == 0:
        return None
    sampler.skip_bytes(new_offset - sampler.consumed_bytes)
    return limb_ops.bytes_le_to_limbs(out, n, elem_nbytes)


class Masker:
    """Masks a model with a (possibly given) random 32-byte seed."""

    def __init__(self, config: MaskConfigPair, seed: MaskSeed | None = None):
        self.config = config
        self.seed = seed if seed is not None else MaskSeed.generate()

    def mask(self, scalar: Scalar, model: Union[Model, np.ndarray]) -> tuple[MaskSeed, MaskObject]:
        """Mask ``model``; returns (seed, masked object).

        ``model`` may be an exact ``Model`` or a numpy float array (fast path
        for bounded-f32 configs).
        """
        config_n, config_1 = self.config.vect, self.config.unit
        sampler = StreamSampler(self.seed.as_bytes())
        # draw order matters: one unit draw first, then the vector draws
        rand_1 = sampler.draw_limbs(1, config_1.order)[0]
        length = len(model)

        s_clamped = clamp_scalar(scalar.value, config_1)

        weights = model if isinstance(model, np.ndarray) else model.weights
        masked_vect = None
        if (
            isinstance(weights, np.ndarray)
            and weights.dtype == np.float32
            and has_fast_path(config_n)
        ):
            masked_vect = _mask_native(
                self.seed.as_bytes(), sampler, weights, s_clamped, config_n
            )
        if masked_vect is None:
            rand_n = sampler.draw_limbs(length, config_n.order)
            encoded = encode_vect_limbs(weights, s_clamped, config_n)
            masked_vect = limb_ops.mod_add(encoded, rand_n, _order_limbs(config_n))

        shifted_1 = encode_unit(s_clamped, config_1)
        n_limb_1 = limb_ops.n_limbs_for_order(config_1.order)
        masked_unit = limb_ops.mod_add(
            limb_ops.int_to_limbs(shifted_1, n_limb_1)[None, :],
            rand_1[None, :],
            _order_limbs(config_1),
        )[0]

        obj = MaskObject(MaskVect(config_n, masked_vect), MaskUnit(config_1, masked_unit))
        return self.seed, obj


class Aggregation:
    """An aggregator for masks and masked models (modular accumulation)."""

    def __init__(self, config: MaskConfigPair, object_size: int):
        self.nb_models = 0
        self.object = MaskObject.empty(config, object_size)
        self.object_size = object_size

    @classmethod
    def from_object(cls, obj: MaskObject) -> "Aggregation":
        agg = cls(obj.config, len(obj))
        agg.aggregate(obj)
        return agg

    def __len__(self) -> int:
        return self.object_size

    @property
    def config(self) -> MaskConfigPair:
        return self.object.config

    # --- validation (reference: masking.rs:142-169, 253-279) -------------

    def validate_unmasking(self, mask: MaskObject) -> None:
        if self.nb_models == 0:
            raise UnmaskingError("NoModel")
        if self.nb_models > self.object.vect.config.max_nb_models:
            raise UnmaskingError("TooManyModels")
        if self.nb_models > self.object.unit.config.max_nb_models:
            raise UnmaskingError("TooManyScalars")
        if self.object.vect.config != mask.vect.config or self.object_size != len(mask.vect):
            raise UnmaskingError("MaskManyMismatch")
        if self.object.unit.config != mask.unit.config:
            raise UnmaskingError("MaskOneMismatch")
        if not mask.is_valid():
            raise UnmaskingError("InvalidMask")

    def validate_aggregation(self, obj: MaskObject) -> None:
        if self.object.vect.config != obj.vect.config:
            raise AggregationError("ModelMismatch")
        if self.object.unit.config != obj.unit.config:
            raise AggregationError("ScalarMismatch")
        if self.object_size != len(obj.vect):
            raise AggregationError("ModelMismatch")
        if self.nb_models >= self.object.vect.config.max_nb_models:
            raise AggregationError("TooManyModels")
        if self.nb_models >= self.object.unit.config.max_nb_models:
            raise AggregationError("TooManyScalars")
        if not obj.is_valid():
            raise AggregationError("InvalidObject")

    # --- aggregation (reference: masking.rs:292-316) ----------------------

    def aggregate(self, obj: MaskObject) -> None:
        if self.nb_models == 0:
            # fresh containers so later accumulation never mutates the
            # caller's object (the reference takes ownership by move)
            self.object = MaskObject(
                MaskVect(obj.vect.config, obj.vect.data),
                MaskUnit(obj.unit.config, obj.unit.data),
            )
            self.nb_models = 1
            return
        config_n, config_1 = self.object.vect.config, self.object.unit.config
        self.object.vect.data = limb_ops.mod_add(
            self.object.vect.data, obj.vect.data, _order_limbs(config_n)
        )
        self.object.unit.data = limb_ops.mod_add(
            self.object.unit.data[None, :], obj.unit.data[None, :], _order_limbs(config_1)
        )[0]
        self.nb_models += 1

    def aggregate_batch(self, stack: np.ndarray, unit_stack: np.ndarray) -> None:
        """Aggregate ``K`` updates at once: ``uint32[K, n, L]`` + ``uint32[K, L]``.

        Tree-reduces the batch (log2 K flat kernels) then folds into the
        accumulator — the staging-friendly shape for the device path.
        """
        k = stack.shape[0]
        if k == 0:
            return
        config_n, config_1 = self.object.vect.config, self.object.unit.config
        ol_n = _order_limbs(config_n)
        batch_u = limb_ops.batch_mod_sum(unit_stack[:, None, :], _order_limbs(config_1))[0]
        # vector part: native single-pass fold (batch + accumulator in one
        # read) — u64 kernel for <=2-limb orders, generic n-limb kernel for
        # the rest; numpy pairwise tree only without the native library
        acc_v = self.object.vect.data if self.nb_models else np.zeros_like(stack[0])
        fast = limb_ops.fold_wire_batch_host(acc_v, stack, ol_n)
        if fast is not None:
            self.object.vect.data = fast
        else:
            batch_v = limb_ops.batch_mod_sum(stack, ol_n)
            if self.nb_models == 0:
                self.object.vect.data = batch_v
            else:
                self.object.vect.data = limb_ops.mod_add(
                    self.object.vect.data, batch_v, ol_n
                )
        if self.nb_models == 0:
            self.object.unit.data = batch_u
        else:
            self.object.unit.data = limb_ops.mod_add(
                self.object.unit.data[None, :], batch_u[None, :], _order_limbs(config_1)
            )[0]
        self.nb_models += k

    def aggregate_partial(self, obj: MaskObject, nb_models: int) -> None:
        """Fold a pre-aggregated PARTIAL — the modular sum of ``nb_models``
        already-masked updates — as one addition.

        Masked aggregation is modular addition (associative and
        commutative), so an edge-side partial folded here is byte-identical
        to folding its member updates individually; only the model count
        must advance by the partial's member count instead of one.
        """
        if nb_models < 1:
            raise AggregationError("EmptyPartial")
        remaining = min(
            self.object.vect.config.max_nb_models, self.object.unit.config.max_nb_models
        ) - self.nb_models
        if nb_models > remaining:
            raise AggregationError("TooManyModels")
        self.aggregate(obj)
        self.nb_models += nb_models - 1

    # --- unmasking (reference: masking.rs:190-231) ------------------------

    def _unmasked_limbs(self, mask_obj: MaskObject) -> tuple[np.ndarray, int]:
        config_n, config_1 = self.object.vect.config, self.object.unit.config
        n_vect = limb_ops.mod_sub(self.object.vect.data, mask_obj.vect.data, _order_limbs(config_n))
        n_unit = limb_ops.mod_sub(
            self.object.unit.data[None, :], mask_obj.unit.data[None, :], _order_limbs(config_1)
        )[0]
        return n_vect, limb_ops.limbs_to_int(n_unit)

    def unmask(self, mask_obj: MaskObject) -> Model:
        """Exact unmasking -> ``Model`` of rational weights (reference parity)."""
        config_n, config_1 = self.object.vect.config, self.object.unit.config
        n_vect, n_unit = self._unmasked_limbs(mask_obj)
        scalar_sum = decode_scalar_sum(n_unit, config_1, self.nb_models)
        values = limb_ops.limbs_to_ints(n_vect)
        return Model(decode_vect_exact(values, config_n, self.nb_models, scalar_sum))

    def unmask_array(self, mask_obj: MaskObject) -> np.ndarray:
        """Fast unmasking -> float64 numpy array (double-double decode)."""
        config_n, config_1 = self.object.vect.config, self.object.unit.config
        n_vect, n_unit = self._unmasked_limbs(mask_obj)
        scalar_sum = decode_scalar_sum(n_unit, config_1, self.nb_models)
        if has_fast_path(config_n):
            return decode_vect_fast(n_vect, config_n, self.nb_models, scalar_sum)
        return decode_vect_any(n_vect, config_n, self.nb_models, scalar_sum)
