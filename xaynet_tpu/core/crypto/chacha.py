"""ChaCha20 keystream, bit-compatible with the reference's PRNG.

The reference expands mask seeds with Rust's ``rand_chacha::ChaCha20Rng``
(reference: rust/xaynet-core/src/crypto/prng.rs:16-27,
rust/xaynet-core/src/mask/seed.rs:61-78). That RNG is the original djb
ChaCha20 variant: 256-bit key (the seed), 64-bit block counter starting at 0,
64-bit nonce/stream 0, with the keystream consumed as a flat little-endian
byte stream. Sum2 participants and the coordinator must derive *identical*
masks from the same seed, so this implementation is bit-exact (pinned by
golden tests in tests/test_prng.py).

This is the host (numpy, vectorized over blocks) implementation; the device
kernels live in ``xaynet_tpu.ops.chacha_jax``.
"""

from __future__ import annotations

import numpy as np

CHACHA_CONSTANTS = (0x61707865, 0x3320646E, 0x79622D32, 0x6B206574)
BLOCK_BYTES = 64

_U32 = np.uint32


def _rotl(x: np.ndarray, n: int) -> np.ndarray:
    return (x << _U32(n)) | (x >> _U32(32 - n))


def _quarter(s: np.ndarray, a: int, b: int, c: int, d: int) -> None:
    s[a] += s[b]
    s[d] = _rotl(s[d] ^ s[a], 16)
    s[c] += s[d]
    s[b] = _rotl(s[b] ^ s[c], 12)
    s[a] += s[b]
    s[d] = _rotl(s[d] ^ s[a], 8)
    s[c] += s[d]
    s[b] = _rotl(s[b] ^ s[c], 7)


def keystream_blocks(key: bytes, block_start: int, nblocks: int) -> np.ndarray:
    """ChaCha20 keystream blocks ``[block_start, block_start + nblocks)``.

    Returns a flat ``uint8`` array of ``nblocks * 64`` keystream bytes.
    All blocks are computed in one vectorized pass (lanes = blocks).
    """
    if nblocks <= 0:
        return np.zeros(0, dtype=np.uint8)
    key_words = np.frombuffer(key, dtype="<u4")
    if key_words.shape != (8,):
        raise ValueError("ChaCha20 key must be 32 bytes")

    counters = block_start + np.arange(nblocks, dtype=np.uint64)
    state = np.zeros((16, nblocks), dtype=_U32)
    state[0:4] = np.asarray(CHACHA_CONSTANTS, dtype=_U32)[:, None]
    state[4:12] = key_words.astype(_U32)[:, None]
    state[12] = (counters & np.uint64(0xFFFFFFFF)).astype(_U32)
    state[13] = (counters >> np.uint64(32)).astype(_U32)
    # state[14:16] stay 0: nonce / stream id 0

    w = state.copy()
    with np.errstate(over="ignore"):
        for _ in range(10):  # 20 rounds = 10 double rounds
            _quarter(w, 0, 4, 8, 12)
            _quarter(w, 1, 5, 9, 13)
            _quarter(w, 2, 6, 10, 14)
            _quarter(w, 3, 7, 11, 15)
            _quarter(w, 0, 5, 10, 15)
            _quarter(w, 1, 6, 11, 12)
            _quarter(w, 2, 7, 8, 13)
            _quarter(w, 3, 4, 9, 14)
        w += state

    # [16, B] words -> per-block 16 LE words -> flat bytes
    return np.frombuffer(np.ascontiguousarray(w.T).astype("<u4").tobytes(), dtype=np.uint8)


class ChaChaStream:
    """Sequential byte view of a ChaCha20 keystream (one RNG instance).

    Mirrors ``ChaCha20Rng::from_seed(seed)`` + repeated ``fill_bytes``: a
    plain byte stream with no per-call alignment.
    """

    def __init__(self, seed: bytes):
        if len(seed) != 32:
            raise ValueError("seed must be 32 bytes")
        self._key = bytes(seed)
        self._block = 0
        self._buf = b""
        self._pos = 0

    def read(self, n: int) -> bytes:
        out = bytearray()
        while n > 0:
            avail = len(self._buf) - self._pos
            if avail == 0:
                # Refill: at least n bytes, rounded up to whole blocks, and
                # at least 4 blocks to amortize (rand_chacha's buffer size).
                nblocks = max(4, -(-n // BLOCK_BYTES))
                self._buf = bytes(keystream_blocks(self._key, self._block, nblocks))
                self._block += nblocks
                self._pos = 0
                continue
            take = min(avail, n)
            out += self._buf[self._pos : self._pos + take]
            self._pos += take
            n -= take
        return bytes(out)
