"""Pure-stdlib fallback primitives: X25519, Ed25519, ChaCha20-Poly1305, HKDF.

``encrypt.py`` and ``sign.py`` prefer the ``cryptography`` wheel (native,
constant-time). Images without that wheel — CI sandboxes, minimal TPU pod
images — previously could not even *import* the server stack, because the
sealed-box and signature modules imported ``cryptography`` at module scope.
This module provides functionally identical, RFC-conformant implementations
on Python big ints + the repo's existing vectorized ChaCha20 core
(``chacha.keystream_blocks``), so every protocol path stays runnable.

NOT constant-time: timing side channels are out of scope for the fallback —
it exists for test/simulation environments, and the module docstrings of
the callers say so. Conformance is pinned by RFC test vectors in
``tests/test_purecrypto.py`` (RFC 7748 §5.2, RFC 8032 §7.1, RFC 8439 §2.8.2,
RFC 5869 A.1), so an environment *with* the wheel computes byte-identical
results to one without.
"""

from __future__ import annotations

import functools
import hashlib
import hmac

import numpy as np

from .chacha import CHACHA_CONSTANTS, _quarter

# --- curve25519 field / group constants -------------------------------------

_P = 2**255 - 19
_L = 2**252 + 27742317777372353535851937790883648493  # ed25519 group order
_D = (-121665 * pow(121666, -1, _P)) % _P  # edwards d
_SQRT_M1 = pow(2, (_P - 1) // 4, _P)  # sqrt(-1) mod p

# ed25519 base point
_B_Y = 4 * pow(5, -1, _P) % _P


def _recover_x(y: int, sign: int) -> int:
    """Point decompression (RFC 8032 §5.1.3)."""
    if y >= _P:
        raise ValueError("invalid point encoding")
    x2 = (y * y - 1) * pow(_D * y * y + 1, -1, _P) % _P
    if x2 == 0:
        if sign:
            raise ValueError("invalid point encoding")
        return 0
    x = pow(x2, (_P + 3) // 8, _P)
    if (x * x - x2) % _P != 0:
        x = x * _SQRT_M1 % _P
    if (x * x - x2) % _P != 0:
        raise ValueError("invalid point encoding")
    if x & 1 != sign:
        x = _P - x
    return x


_B = (_recover_x(_B_Y, 0), _B_Y)

# extended homogeneous coordinates (X, Y, Z, T) with x=X/Z, y=Y/Z, xy=T/Z
_IDENT = (0, 1, 1, 0)


def _to_ext(pt: tuple[int, int]) -> tuple[int, int, int, int]:
    x, y = pt
    return (x, y, 1, x * y % _P)


def _ext_add(p, q):
    """RFC 8032 §5.1.4 point addition."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % _P
    b = (y1 + x1) * (y2 + x2) % _P
    c = 2 * t1 * t2 * _D % _P
    d = 2 * z1 * z2 % _P
    e, f, g, h = b - a, d - c, d + c, b + a
    return (e * f % _P, g * h % _P, f * g % _P, e * h % _P)


def _ext_double(p):
    return _ext_add(p, p)


def _scalar_mult(scalar: int, pt: tuple[int, int, int, int]):
    out = _IDENT
    while scalar:
        if scalar & 1:
            out = _ext_add(out, pt)
        pt = _ext_double(pt)
        scalar >>= 1
    return out


def _build_base_table():
    table, pt = [], None
    pt_ext = _to_ext(_B)
    for _ in range(256):
        table.append(pt_ext)
        pt_ext = _ext_double(pt_ext)
    del pt
    return table


_B_TABLE = _build_base_table()


def _scalar_mult_base(scalar: int):
    """``scalar * B`` via the precomputed doubling table — additions only,
    which makes sign/public-key derivation ~2x the generic ladder (the hot
    path of ``keys_for_task`` rejection sampling in simulations)."""
    out = _IDENT
    i = 0
    while scalar:
        if scalar & 1:
            out = _ext_add(out, _B_TABLE[i])
        scalar >>= 1
        i += 1
    return out


def _ext_encode(p) -> bytes:
    x, y, z, _ = p
    zinv = pow(z, -1, _P)
    x, y = x * zinv % _P, y * zinv % _P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def _ext_decode(data: bytes):
    if len(data) != 32:
        raise ValueError("point encoding must be 32 bytes")
    raw = int.from_bytes(data, "little")
    sign = raw >> 255
    y = raw & ((1 << 255) - 1)
    return _to_ext((_recover_x(y, sign), y))


def _ext_equal(p, q) -> bool:
    # X1/Z1 == X2/Z2  <=>  X1 Z2 == X2 Z1 (and same for Y)
    x1, y1, z1, _ = p
    x2, y2, z2, _ = q
    return (x1 * z2 - x2 * z1) % _P == 0 and (y1 * z2 - y2 * z1) % _P == 0


# --- Ed25519 (RFC 8032) ------------------------------------------------------


def _ed_secret_expand(seed: bytes) -> tuple[int, bytes]:
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:]


@functools.lru_cache(maxsize=4096)
def _expanded(seed: bytes) -> tuple[int, bytes, bytes]:
    """(scalar, prefix, public key) per seed — one key signs many messages
    in a PET round, so the base-point mult is paid once per key."""
    a, prefix = _ed_secret_expand(seed)
    return a, prefix, _ext_encode(_scalar_mult_base(a))


def ed25519_public(seed: bytes) -> bytes:
    """Public key for a 32-byte private seed."""
    return _expanded(bytes(seed))[2]


def ed25519_sign(seed: bytes, msg: bytes) -> bytes:
    a, prefix, pk = _expanded(bytes(seed))
    r = int.from_bytes(hashlib.sha512(prefix + msg).digest(), "little") % _L
    r_enc = _ext_encode(_scalar_mult_base(r))
    k = int.from_bytes(hashlib.sha512(r_enc + pk + msg).digest(), "little") % _L
    s = (r + k * a) % _L
    return r_enc + s.to_bytes(32, "little")


def ed25519_verify(pk: bytes, sig: bytes, msg: bytes) -> bool:
    if len(sig) != 64:
        return False
    try:
        a_pt = _ext_decode(pk)
        r_pt = _ext_decode(sig[:32])
    except ValueError:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= _L:
        return False
    k = int.from_bytes(hashlib.sha512(sig[:32] + pk + msg).digest(), "little") % _L
    return _ext_equal(
        _scalar_mult_base(s),
        _ext_add(r_pt, _scalar_mult(k, a_pt)),
    )


# --- X25519 (RFC 7748) -------------------------------------------------------

_A24 = 121665


def _x_decode_scalar(k: bytes) -> int:
    b = bytearray(k)
    b[0] &= 248
    b[31] &= 127
    b[31] |= 64
    return int.from_bytes(b, "little")


def x25519(k: bytes, u: bytes) -> bytes:
    """Scalar multiplication on the montgomery curve (the DH primitive)."""
    if len(k) != 32 or len(u) != 32:
        raise ValueError("x25519 operands must be 32 bytes")
    scalar = _x_decode_scalar(k)
    x1 = int.from_bytes(u, "little") & ((1 << 255) - 1)
    x2, z2, x3, z3 = 1, 0, x1, 1
    swap = 0
    for t in range(254, -1, -1):
        k_t = (scalar >> t) & 1
        if swap ^ k_t:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = k_t
        a = (x2 + z2) % _P
        aa = a * a % _P
        b = (x2 - z2) % _P
        bb = b * b % _P
        e = (aa - bb) % _P
        c = (x3 + z3) % _P
        d = (x3 - z3) % _P
        da = d * a % _P
        cb = c * b % _P
        x3 = (da + cb) % _P
        x3 = x3 * x3 % _P
        z3 = (da - cb) % _P
        z3 = x1 * z3 * z3 % _P
        x2 = aa * bb % _P
        z2 = e * (aa + _A24 * e) % _P
    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    out = x2 * pow(z2, _P - 2, _P) % _P
    return out.to_bytes(32, "little")


_X_BASE = (9).to_bytes(32, "little")


def x25519_public(k: bytes) -> bytes:
    """Public key (scalar times the base point u=9)."""
    return x25519(k, _X_BASE)


# --- ChaCha20-Poly1305 AEAD (RFC 8439) ---------------------------------------


def _ietf_keystream(key: bytes, nonce: bytes, counter: int, nblocks: int) -> bytes:
    """IETF-variant keystream: 32-bit block counter + 96-bit nonce.

    Same vectorized core as ``chacha.keystream_blocks`` (which pins the djb
    variant the PRNG needs); only the counter/nonce words differ.
    """
    key_words = np.frombuffer(key, dtype="<u4")
    if key_words.shape != (8,):
        raise ValueError("key must be 32 bytes")
    nonce_words = np.frombuffer(nonce, dtype="<u4")
    if nonce_words.shape != (3,):
        raise ValueError("nonce must be 12 bytes")
    state = np.zeros((16, nblocks), dtype=np.uint32)
    state[0:4] = np.asarray(CHACHA_CONSTANTS, dtype=np.uint32)[:, None]
    state[4:12] = key_words.astype(np.uint32)[:, None]
    state[12] = (counter + np.arange(nblocks, dtype=np.uint64)).astype(np.uint32)
    state[13:16] = nonce_words.astype(np.uint32)[:, None]
    w = state.copy()
    with np.errstate(over="ignore"):
        for _ in range(10):
            _quarter(w, 0, 4, 8, 12)
            _quarter(w, 1, 5, 9, 13)
            _quarter(w, 2, 6, 10, 14)
            _quarter(w, 3, 7, 11, 15)
            _quarter(w, 0, 5, 10, 15)
            _quarter(w, 1, 6, 11, 12)
            _quarter(w, 2, 7, 8, 13)
            _quarter(w, 3, 4, 9, 14)
        w += state
    return np.ascontiguousarray(w.T).astype("<u4").tobytes()


def _xor_keystream(key: bytes, nonce: bytes, counter: int, data: bytes) -> bytes:
    nblocks = -(-len(data) // 64)
    ks = np.frombuffer(_ietf_keystream(key, nonce, counter, nblocks)[: len(data)], dtype=np.uint8)
    return (np.frombuffer(data, dtype=np.uint8) ^ ks).tobytes()


def poly1305(key: bytes, msg: bytes) -> bytes:
    """One-time authenticator (RFC 8439 §2.5)."""
    if len(key) != 32:
        raise ValueError("poly1305 key must be 32 bytes")
    r = int.from_bytes(key[:16], "little") & 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF
    s = int.from_bytes(key[16:], "little")
    p = (1 << 130) - 5
    acc = 0
    for i in range(0, len(msg), 16):
        block = msg[i : i + 16]
        n = int.from_bytes(block, "little") + (1 << (8 * len(block)))
        acc = (acc + n) * r % p
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


def _pad16(data: bytes) -> bytes:
    return data + b"\x00" * (-len(data) % 16)


def _poly_input(aad: bytes, ct: bytes) -> bytes:
    return (
        _pad16(aad)
        + _pad16(ct)
        + len(aad).to_bytes(8, "little")
        + len(ct).to_bytes(8, "little")
    )


class AeadTagError(ValueError):
    """AEAD authentication failed (the fallback's ``InvalidTag``)."""


def chacha20poly1305_encrypt(key: bytes, nonce: bytes, plaintext: bytes, aad: bytes = b"") -> bytes:
    otk = _ietf_keystream(key, nonce, 0, 1)[:32]
    ct = _xor_keystream(key, nonce, 1, plaintext)
    return ct + poly1305(otk, _poly_input(aad, ct))


def chacha20poly1305_decrypt(key: bytes, nonce: bytes, sealed: bytes, aad: bytes = b"") -> bytes:
    if len(sealed) < 16:
        raise AeadTagError("ciphertext shorter than the tag")
    ct, tag = sealed[:-16], sealed[-16:]
    otk = _ietf_keystream(key, nonce, 0, 1)[:32]
    if not hmac.compare_digest(poly1305(otk, _poly_input(aad, ct)), tag):
        raise AeadTagError("authentication failed")
    return _xor_keystream(key, nonce, 1, ct)


# --- HKDF-SHA256 (RFC 5869) --------------------------------------------------


def hkdf_sha256(ikm: bytes, info: bytes, length: int = 32, salt: bytes = b"") -> bytes:
    if not salt:
        salt = b"\x00" * 32
    prk = hmac.new(salt, ikm, hashlib.sha256).digest()
    out, block = b"", b""
    counter = 1
    while len(out) < length:
        block = hmac.new(prk, block + info + bytes([counter]), hashlib.sha256).digest()
        out += block
        counter += 1
    return out[:length]
