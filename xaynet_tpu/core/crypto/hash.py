"""SHA-256 wrapper (reference: rust/xaynet-core/src/crypto/hash.rs:30-53)."""

from __future__ import annotations

import hashlib

DIGEST_LENGTH = 32


def sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()
