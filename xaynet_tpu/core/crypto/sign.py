"""Ed25519 signatures and the PET task-eligibility check.

Reference: rust/xaynet-core/src/crypto/sign.rs:21-232. The eligibility rule
(`Signature::is_eligible`, sign.rs:186-202) decides whether a participant is
selected for the sum/update task of a round:

    int_le(sha256(signature)) / (2^256 - 1) <= threshold

evaluated exactly (the threshold f64 is converted to an exact rational).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from fractions import Fraction

try:  # native Ed25519 when the wheel is present, pure-stdlib fallback otherwise
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )

    _HAVE_CRYPTO = True
except ImportError:
    from . import _purecrypto

    _HAVE_CRYPTO = False

from .hash import sha256

PUBLIC_KEY_LENGTH = 32
SECRET_KEY_LENGTH = 32  # stored as the 32-byte seed
SIGNATURE_LENGTH = 64
SEED_LENGTH = 32

_DENOM = (1 << 256) - 1


@dataclass(frozen=True)
class Signature:
    bytes_: bytes

    def __post_init__(self):
        if len(self.bytes_) != SIGNATURE_LENGTH:
            raise ValueError("signature must be 64 bytes")

    def as_bytes(self) -> bytes:
        return self.bytes_

    def is_eligible(self, threshold: float) -> bool:
        return is_eligible(self.bytes_, threshold)


def is_eligible(signature: bytes, threshold: float) -> bool:
    """Exact eligibility check as specified by the reference."""
    if threshold < 0.0:
        return False
    if threshold > 1.0:
        return True
    numer = int.from_bytes(sha256(signature), "little")
    return Fraction(numer, _DENOM) <= Fraction(threshold)


@dataclass(frozen=True)
class SigningKeyPair:
    public: bytes  # 32-byte Ed25519 public key
    secret: bytes  # 32-byte seed / private key

    @classmethod
    def generate(cls) -> "SigningKeyPair":
        return cls.derive_from_seed(os.urandom(SEED_LENGTH))

    @classmethod
    def derive_from_seed(cls, seed: bytes) -> "SigningKeyPair":
        if len(seed) != SEED_LENGTH:
            raise ValueError("seed must be 32 bytes")
        if not _HAVE_CRYPTO:
            return cls(public=_purecrypto.ed25519_public(seed), secret=seed)
        sk = Ed25519PrivateKey.from_private_bytes(seed)
        return cls(public=sk.public_key().public_bytes_raw(), secret=seed)

    def sign(self, data: bytes) -> Signature:
        return Signature(sign_detached(self.secret, data))


def sign_detached(secret: bytes, data: bytes) -> bytes:
    if not _HAVE_CRYPTO:
        return _purecrypto.ed25519_sign(secret, data)
    return Ed25519PrivateKey.from_private_bytes(secret).sign(data)


def verify_detached(public: bytes, signature: bytes, data: bytes) -> bool:
    if not _HAVE_CRYPTO:
        try:
            return _purecrypto.ed25519_verify(public, signature, data)
        except ValueError:
            return False
    try:
        Ed25519PublicKey.from_public_bytes(public).verify(signature, data)
        return True
    except (InvalidSignature, ValueError):
        return False
