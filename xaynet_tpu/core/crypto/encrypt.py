"""Sealed-box asymmetric encryption (C25519).

Functional port of the reference's `EncryptKeyPair` /
`PublicEncryptKey::encrypt` / `SecretEncryptKey::decrypt` (reference:
rust/xaynet-core/src/crypto/encrypt.rs:16-164). A sealed box is anonymous
public-key encryption: an ephemeral X25519 key agrees a shared secret with
the recipient's public key; the ephemeral public key travels in the
ciphertext header.

Construction: ``eph_pk(32) || ChaCha20Poly1305(msg)`` with
``key = HKDF-SHA256(X25519(eph_sk, pk), info = eph_pk || pk)`` and a zero
nonce (the key is single-use). Overhead = 32 + 16 = 48 bytes = SEALBYTES,
matching the reference's wire constant.

Backend: the ``cryptography`` wheel when importable, otherwise the
pure-stdlib RFC-conformant fallback (``_purecrypto``) — byte-identical
output, not constant-time; fine for tests/simulation, pip the wheel for
production coordinators.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

try:  # native primitives when the wheel is present ...
    from cryptography.exceptions import InvalidTag
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric.x25519 import X25519PrivateKey, X25519PublicKey
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
    from cryptography.hazmat.primitives.kdf.hkdf import HKDF

    _HAVE_CRYPTO = True
except ImportError:  # ... pure-stdlib fallback otherwise (see _purecrypto)
    from . import _purecrypto

    _HAVE_CRYPTO = False

SEALBYTES = 48
PUBLIC_KEY_LENGTH = 32
SECRET_KEY_LENGTH = 32
SEED_LENGTH = 32

_ZERO_NONCE = b"\x00" * 12


class DecryptError(ValueError):
    """Sealed box could not be opened."""


def _derive_key(shared: bytes, eph_pk: bytes, recipient_pk: bytes) -> bytes:
    info = b"xaynet-tpu-sealedbox" + eph_pk + recipient_pk
    if not _HAVE_CRYPTO:
        return _purecrypto.hkdf_sha256(shared, info, 32)
    hkdf = HKDF(algorithm=hashes.SHA256(), length=32, salt=None, info=info)
    return hkdf.derive(shared)


@dataclass(frozen=True)
class PublicEncryptKey:
    bytes_: bytes

    def __post_init__(self):
        if len(self.bytes_) != PUBLIC_KEY_LENGTH:
            raise ValueError("public encrypt key must be 32 bytes")

    def as_bytes(self) -> bytes:
        return self.bytes_

    def encrypt(self, message: bytes) -> bytes:
        """Seal ``message`` for this public key (anyone can seal)."""
        if _HAVE_CRYPTO:
            eph_sk = X25519PrivateKey.generate()
            eph_pk = eph_sk.public_key().public_bytes_raw()
            shared = eph_sk.exchange(X25519PublicKey.from_public_bytes(self.bytes_))
            key = _derive_key(shared, eph_pk, self.bytes_)
            ct = ChaCha20Poly1305(key).encrypt(_ZERO_NONCE, message, None)
            return eph_pk + ct
        eph_seed = os.urandom(32)
        eph_pk = _purecrypto.x25519_public(eph_seed)
        shared = _purecrypto.x25519(eph_seed, self.bytes_)
        key = _derive_key(shared, eph_pk, self.bytes_)
        return eph_pk + _purecrypto.chacha20poly1305_encrypt(key, _ZERO_NONCE, message)


@dataclass(frozen=True)
class SecretEncryptKey:
    bytes_: bytes

    def __post_init__(self):
        if len(self.bytes_) != SECRET_KEY_LENGTH:
            raise ValueError("secret encrypt key must be 32 bytes")

    def as_bytes(self) -> bytes:
        return self.bytes_

    def public_key(self) -> PublicEncryptKey:
        if _HAVE_CRYPTO:
            sk = X25519PrivateKey.from_private_bytes(self.bytes_)
            return PublicEncryptKey(sk.public_key().public_bytes_raw())
        return PublicEncryptKey(_purecrypto.x25519_public(self.bytes_))

    def decrypt(self, sealed: bytes, pk: "PublicEncryptKey | None" = None) -> bytes:
        """Open a sealed box addressed to this key.

        ``pk`` (our own public key) is accepted for reference API parity; it
        is recomputed when omitted.
        """
        if len(sealed) < SEALBYTES:
            raise DecryptError("sealed box too short")
        my_pk = pk.as_bytes() if pk is not None else self.public_key().as_bytes()
        eph_pk, ct = sealed[:32], sealed[32:]
        if _HAVE_CRYPTO:
            sk = X25519PrivateKey.from_private_bytes(self.bytes_)
            shared = sk.exchange(X25519PublicKey.from_public_bytes(eph_pk))
            key = _derive_key(shared, eph_pk, my_pk)
            try:
                return ChaCha20Poly1305(key).decrypt(_ZERO_NONCE, ct, None)
            except InvalidTag as e:
                raise DecryptError("sealed box authentication failed") from e
        shared = _purecrypto.x25519(self.bytes_, eph_pk)
        key = _derive_key(shared, eph_pk, my_pk)
        try:
            return _purecrypto.chacha20poly1305_decrypt(key, _ZERO_NONCE, ct)
        except _purecrypto.AeadTagError as e:
            raise DecryptError("sealed box authentication failed") from e


@dataclass(frozen=True)
class EncryptKeyPair:
    public: PublicEncryptKey
    secret: SecretEncryptKey

    @classmethod
    def generate(cls) -> "EncryptKeyPair":
        if not _HAVE_CRYPTO:
            return cls.derive_from_seed(os.urandom(SEED_LENGTH))
        sk = X25519PrivateKey.generate()
        return cls(
            public=PublicEncryptKey(sk.public_key().public_bytes_raw()),
            secret=SecretEncryptKey(sk.private_bytes_raw()),
        )

    @classmethod
    def derive_from_seed(cls, seed: bytes) -> "EncryptKeyPair":
        """Deterministic keypair from a 32-byte seed."""
        if len(seed) != SEED_LENGTH:
            raise ValueError("seed must be 32 bytes")
        if not _HAVE_CRYPTO:
            return cls(
                public=PublicEncryptKey(_purecrypto.x25519_public(seed)),
                secret=SecretEncryptKey(bytes(seed)),
            )
        sk = X25519PrivateKey.from_private_bytes(seed)
        return cls(
            public=PublicEncryptKey(sk.public_key().public_bytes_raw()),
            secret=SecretEncryptKey(sk.private_bytes_raw()),
        )


def generate_seed() -> bytes:
    return os.urandom(SEED_LENGTH)
