"""Crypto primitives for the PET protocol.

Mirrors the reference's crypto surface (reference:
rust/xaynet-core/src/crypto/mod.rs:36-78): asymmetric sealed-box encryption,
Ed25519 signatures with the task-eligibility check, SHA-256, and the
ChaCha20-based PRNG for mask expansion.

The reference binds to libsodium; this implementation uses the Python
``cryptography`` package (X25519 + ChaCha20Poly1305 sealed boxes, Ed25519).
Wire sizes match the reference exactly (SEALBYTES = 48, 32-byte keys,
64-byte signatures); the sealed-box bytes are not libsodium-compatible —
both protocol ends are this framework.
"""

from .encrypt import SEALBYTES, EncryptKeyPair, PublicEncryptKey, SecretEncryptKey
from .hash import sha256
from .sign import Signature, SigningKeyPair, is_eligible, sign_detached, verify_detached

__all__ = [
    "SEALBYTES",
    "EncryptKeyPair",
    "PublicEncryptKey",
    "SecretEncryptKey",
    "sha256",
    "Signature",
    "SigningKeyPair",
    "is_eligible",
    "sign_detached",
    "verify_detached",
]
