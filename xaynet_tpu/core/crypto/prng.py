"""Uniform group-element sampling from the ChaCha20 keystream.

Bit-exact port of the reference's rejection sampler (reference:
rust/xaynet-core/src/crypto/prng.rs:16-27): each attempt draws
``len(order.to_bytes_le())`` bytes from the stream, interprets them
little-endian and rejects values ``>= order``. The byte stream is consumed
per *attempt*, so the accepted sequence equals ``filter(candidate < order)``
over the chopped keystream — which is exactly what the vectorized sampler
exploits: generate a chunk of keystream, chop into fixed-width candidates,
keep the ones below the order (a compaction, not a sequential loop).

``derive_mask`` draws one unit-order element and then the vector elements
from the *same* stream (reference: rust/xaynet-core/src/mask/seed.rs:61-78),
so the sampler is a stateful cursor: leftover keystream bytes carry over
between draws of different orders.
"""

from __future__ import annotations

from fractions import Fraction

import numpy as np

from ...ops import limbs as limb_ops
from .chacha import BLOCK_BYTES, ChaChaStream, keystream_blocks


def generate_integer(stream: ChaChaStream, max_int: int) -> int:
    """Sequential oracle, one draw (reference semantics, python ints)."""
    if max_int == 0:
        return 0
    nbytes = limb_ops.draw_width_for(max_int)
    value = max_int
    while value >= max_int:
        value = int.from_bytes(stream.read(nbytes), "little")
    return value


class StreamSampler:
    """Vectorized rejection sampler over one seed's keystream."""

    def __init__(self, seed: bytes):
        if len(seed) != 32:
            raise ValueError("seed must be 32 bytes")
        self._seed = bytes(seed)
        self._block = 0
        self._leftover = np.zeros(0, dtype=np.uint8)

    @property
    def consumed_bytes(self) -> int:
        """Bytes of keystream consumed so far (for device-kernel handoff)."""
        return self._block * BLOCK_BYTES - len(self._leftover)

    def skip_bytes(self, n: int) -> None:
        """Advance the stream cursor by ``n`` bytes without drawing.

        Whole blocks are skipped by advancing the counter (ChaCha20 is
        seekable); only a trailing partial block is generated.
        """
        take = min(n, len(self._leftover))
        self._leftover = self._leftover[take:]
        n -= take
        if n <= 0:
            return
        self._block += n // BLOCK_BYTES
        intra = n % BLOCK_BYTES
        if intra:
            blk = keystream_blocks(self._seed, self._block, 1)
            self._block += 1
            self._leftover = blk[intra:]

    def _more_keystream(self, nbytes: int) -> np.ndarray:
        nblocks = max(4, -(-nbytes // BLOCK_BYTES))
        ks = keystream_blocks(self._seed, self._block, nblocks)
        self._block += nblocks
        return ks

    def draw_limbs(self, count: int, order: int) -> np.ndarray:
        """First ``count`` accepted draws below ``order`` as ``uint32[count, L]``.

        Consumes the same keystream prefix as ``count`` sequential
        ``generate_integer`` calls. Uses the native C++ sampler when the
        library is available (bit-identical byte-stream semantics).
        """
        out_limbs = limb_ops.n_limbs_for_order(order)
        if count == 0:
            return np.zeros((0, out_limbs), dtype=np.uint32)
        from ...utils import native

        lib = native.load()
        if lib is not None:
            return self._draw_limbs_native(lib, count, order, out_limbs)
        # Draw width is the byte length of the *order itself* (the reference
        # sizes the buffer with `max_int.to_bytes_le()`), which exceeds the
        # element width when the order is a power of two at a byte boundary
        # (e.g. 2^88, 2^96 from the catalogue).
        bpn = limb_ops.draw_width_for(order)
        cand_limbs = limb_ops.n_limbs_for_bytes(bpn)
        order_cl = limb_ops.int_to_limbs(order, cand_limbs)
        accept_rate = float(Fraction(order, 1 << (8 * bpn)))  # handles huge orders

        accepted: list[np.ndarray] = []
        got = 0
        while got < count:
            need = count - got
            target = int(need * bpn / max(accept_rate, 1e-6) * 1.15) + 4 * BLOCK_BYTES
            if len(self._leftover):
                buf = np.concatenate(
                    [self._leftover, self._more_keystream(target - len(self._leftover))]
                )
            else:
                buf = self._more_keystream(target)
            n_cand = len(buf) // bpn
            cand = limb_ops.bytes_le_to_limbs(buf[: n_cand * bpn], n_cand, bpn)
            keep_mask = limb_ops.lt_const(cand, order_cl)
            n_keep = int(keep_mask.sum())
            if n_keep >= need:
                # find the attempt index of the `need`-th acceptance; bytes
                # after it stay in the stream for the next draw
                idx = np.nonzero(keep_mask)[0]
                last = int(idx[need - 1])
                self._leftover = buf[(last + 1) * bpn :]
                keep = cand[idx[:need]]
            else:
                self._leftover = buf[n_cand * bpn :]
                keep = cand[keep_mask]
            if keep.shape[0]:
                # accepted values are < order, so they fit the element width
                accepted.append(keep[:, :out_limbs])
                got += keep.shape[0]
        return accepted[0] if len(accepted) == 1 else np.concatenate(accepted, axis=0)

    def _draw_limbs_native(self, lib, count: int, order: int, out_limbs: int) -> np.ndarray:
        from ...utils import native

        bpn = limb_ops.draw_width_for(order)
        order_le = order.to_bytes(bpn, "little")
        out = np.empty(count * bpn, dtype=np.uint8)
        new_offset = lib.xn_sample_uniform(
            native.as_u8p(self._seed),
            self.consumed_bytes,
            count,
            native.as_u8p(order_le),
            bpn,
            native.np_u8p(out),
        )
        # re-sync the numpy-side cursor so mixed native/numpy draws stay
        # on the same keystream byte offset
        self._block = new_offset // BLOCK_BYTES
        self._leftover = np.zeros(0, dtype=np.uint8)
        intra = new_offset % BLOCK_BYTES
        if intra:
            self._block += 1
            blk = keystream_blocks(self._seed, self._block - 1, 1)
            self._leftover = blk[intra:]
        return limb_ops.bytes_le_to_limbs(out, count, bpn)[:, :out_limbs]

    def draw_int(self, order: int) -> int:
        return limb_ops.limbs_to_ints(self.draw_limbs(1, order))[0]


def uniform_limbs(seed: bytes, count: int, order: int) -> np.ndarray:
    """One-shot vectorized sampling from a fresh stream."""
    return StreamSampler(seed).draw_limbs(count, order)


def uniform_ints(seed: bytes, count: int, order: int) -> list[int]:
    """Vectorized sampler returning python ints (small-scale convenience)."""
    return limb_ops.limbs_to_ints(uniform_limbs(seed, count, order))
