"""Storage interfaces for the coordinator.

Functional port of the reference's storage traits (reference:
rust/xaynet-server/src/storage/traits.rs:31-311): ``CoordinatorStorage``
(round dictionaries, mask scores, state), ``ModelStorage`` (global models),
``TrustAnchor`` (proof publication), and the typed *protocol* errors that
drive client-visible behavior (distinct from infrastructure errors, which
surface as exceptions).

All methods are async: backends range from the in-process dict store used
in single-process deployments and tests to external services.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from enum import Enum
from typing import Optional

from ..core.common import LocalSeedDict, SeedDict, SumDict
from ..core.mask.object import MaskObject


class StorageError(RuntimeError):
    """Infrastructure failure (connection lost, serialization bug, ...).

    ``transient`` drives the resilience layer's retry decision: ``True``
    means retry in place, ``False`` means fail immediately, ``None`` (the
    default) defers to ``resilience.policy.is_transient``'s heuristics.
    """

    transient: Optional[bool] = None


class TransientStorageError(StorageError):
    """A storage failure the backend knows is retryable (connection drop,
    timeout, throttling) — the resilience layer retries these in place.

    CONTRACT: transient means the operation was guaranteed NOT executed
    (or the operation is idempotent). A failure where the command may have
    executed server-side (reply lost mid-command) must be marked
    ``transient = False`` — replaying a conditional insert whose first
    attempt landed surfaces its dedup verdict and desyncs the seed
    dictionary from the model aggregate."""

    transient = True


class SumPartAddError(Enum):
    ALREADY_EXISTS = "sum participant already exists"


class LocalSeedDictAddError(Enum):
    LENGTH_MISMATCH = "local seed dict length != sum dict length"
    UNKNOWN_SUM_PARTICIPANT = "local dict contains an unknown sum participant"
    UPDATE_PK_ALREADY_SUBMITTED = "update participant already submitted an update"
    UPDATE_PK_ALREADY_EXISTS_IN_UPDATE_SEED_DICT = (
        "update participant already exists in the inner update seed dict"
    )


class MaskScoreIncrError(Enum):
    UNKNOWN_SUM_PK = "unknown sum participant"
    MASK_ALREADY_SUBMITTED = "sum participant submitted a mask already"


class CoordinatorStorage(ABC):
    """Round-state storage: dictionaries, mask scores, coordinator state.

    Protocol errors are *returned* (``Optional[...Error]``, ``None`` on
    success) rather than raised — they are expected per-request outcomes the
    state machine reports back to clients; raised exceptions mean the
    backend itself failed.
    """

    @abstractmethod
    async def set_coordinator_state(self, state: bytes) -> None: ...

    @abstractmethod
    async def coordinator_state(self) -> Optional[bytes]: ...

    @abstractmethod
    async def add_sum_participant(
        self, pk: bytes, ephm_pk: bytes
    ) -> Optional[SumPartAddError]: ...

    @abstractmethod
    async def sum_dict(self) -> Optional[SumDict]: ...

    @abstractmethod
    async def add_local_seed_dict(
        self, update_pk: bytes, local_seed_dict: LocalSeedDict
    ) -> Optional[LocalSeedDictAddError]: ...

    @abstractmethod
    async def seed_dict(self) -> Optional[SeedDict]: ...

    @abstractmethod
    async def incr_mask_score(
        self, pk: bytes, mask: MaskObject
    ) -> Optional[MaskScoreIncrError]: ...

    @abstractmethod
    async def best_masks(self) -> Optional[list[tuple[MaskObject, int]]]: ...

    @abstractmethod
    async def number_of_unique_masks(self) -> int: ...

    @abstractmethod
    async def delete_coordinator_data(self) -> None:
        """Delete all coordinator data including the coordinator state."""

    @abstractmethod
    async def delete_dicts(self) -> None:
        """Delete the round dictionaries (sum/seed/mask), keep the state."""

    @abstractmethod
    async def set_latest_global_model_id(self, model_id: str) -> None: ...

    @abstractmethod
    async def latest_global_model_id(self) -> Optional[str]: ...

    @abstractmethod
    async def is_ready(self) -> None:
        """Raises ``StorageError`` when the backend is unreachable."""

    # --- journal resume (resilience) --------------------------------------

    async def restore_round_dicts(self, sum_dict, seed_dicts, mask_votes) -> None:
        """Replay journaled round dictionaries through the protocol
        primitives — idempotent on EVERY backend: entries the store still
        holds answer with their conditional-insert protocol verdict, which
        is exactly the outcome a replay wants ignored. ``seed_dicts`` is
        the journal's ``{update_pk: {sum_pk: seed bytes}}`` replay form;
        ``mask_votes`` is ``[(sum_pk, serialized mask bytes)]``. Replay
        order matters: sum membership gates both seed-dict inserts and
        mask votes."""
        from ..core.mask.seed import EncryptedMaskSeed
        from ..core.mask.serialization import parse_mask_object

        for pk, ephm in sum_dict.items():
            await self.add_sum_participant(bytes(pk), bytes(ephm))
        for update_pk, local in seed_dicts.items():
            await self.add_local_seed_dict(
                bytes(update_pk),
                {bytes(spk): EncryptedMaskSeed(bytes(seed)) for spk, seed in local.items()},
            )
        for pk, blob in mask_votes:
            mask, _ = parse_mask_object(bytes(blob))
            await self.incr_mask_score(bytes(pk), mask)

    async def prune_update_participants(self, keep_pks) -> bool:
        """Drop update participants the store holds but the journal never
        recorded (accepted-but-unjournaled: the coordinator died between
        the seed-dict insert and the journal write, so the client never
        saw the ack and WILL retry — the prune makes that retry succeed).
        Returns False when the backend cannot prune; the caller's
        seed-watermark check then rejects the resume instead."""
        return False

    # --- mid-round checkpoint (resilience) --------------------------------
    # Concrete defaults: the checkpoint is round-volatile state with the
    # same lifetime as the dictionaries, so an in-process fallback is
    # correct for every backend; durable backends (file, redis) override
    # to persist it alongside the coordinator state.

    async def set_round_checkpoint(self, data: bytes) -> None:
        """Persist the serialized mid-round aggregate checkpoint."""
        self._round_checkpoint_mem = bytes(data)

    async def round_checkpoint(self) -> Optional[bytes]:
        """The last persisted checkpoint, or None."""
        return getattr(self, "_round_checkpoint_mem", None)

    async def delete_round_checkpoint(self) -> None:
        """Drop the checkpoint (new round, or invalidated resume)."""
        self._round_checkpoint_mem = None


class ModelStorage(ABC):
    """Global-model blob storage."""

    @staticmethod
    def create_global_model_id(round_id: int, round_seed: bytes) -> str:
        """Canonical id: ``{round_id}_{hex(round_seed)}`` (traits.rs:195-198)."""
        return f"{round_id}_{round_seed.hex()}"

    @abstractmethod
    async def set_global_model(
        self, round_id: int, round_seed: bytes, model_data: bytes
    ) -> str:
        """Stores the model; refuses to overwrite an existing id with
        DIFFERENT bytes. Re-storing identical bytes returns the id —
        a publish-window resume (the coordinator died after persisting
        the model but before retiring the journal entry) republishes
        the exact same model and must be an idempotent success."""

    @abstractmethod
    async def global_model(self, model_id: str) -> Optional[bytes]: ...

    @abstractmethod
    async def is_ready(self) -> None: ...


class TrustAnchor(ABC):
    """Publishes proofs of global models to an external anchor."""

    @abstractmethod
    async def publish_proof(self, model_data: bytes) -> None: ...

    @abstractmethod
    async def is_ready(self) -> None: ...


class Store:
    """Composition of the three storage interfaces (storage/store.rs:32-212)."""

    def __init__(
        self,
        coordinator: CoordinatorStorage,
        models: ModelStorage,
        trust_anchor: Optional[TrustAnchor] = None,
    ):
        self.coordinator = coordinator
        self.models = models
        self.trust_anchor = trust_anchor

    async def is_ready(self) -> None:
        await self.coordinator.is_ready()
        await self.models.is_ready()
        if self.trust_anchor is not None:
            await self.trust_anchor.is_ready()
