"""Redis coordinator-storage backend (RESP client from scratch).

Functional port of the reference's Redis backend (reference:
rust/xaynet-server/src/storage/coordinator_storage/redis/mod.rs): the same
data model (sum_dict hash, per-sum-pk seed hashes, update_participants set,
mask_submitted set, mask_dict sorted set keyed by the serialized mask) and
the same *atomic Lua scripts* for the conditional inserts
(redis/mod.rs:208-267 for seed dicts, :303-343 for mask scores).

No third-party client: a minimal RESP2 protocol implementation over asyncio
streams (`RespClient`). Use this backend when running several coordinator
replicas or when round state must survive a coordinator crash with an
external store; the in-process backend provides the same semantics for
single-process deployments.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from ..core.mask.object import MaskObject
from ..core.mask.seed import EncryptedMaskSeed
from ..core.mask.serialization import parse_mask_object, serialize_mask_object
from .traits import (
    CoordinatorStorage,
    LocalSeedDictAddError,
    MaskScoreIncrError,
    StorageError,
    SumPartAddError,
    TransientStorageError,
)

# --- RESP2 client ----------------------------------------------------------


class RespClient:
    """Minimal Redis protocol client (RESP2) over asyncio streams.

    Connection management mirrors the reference's ``ConnectionManager``
    (reference: redis/mod.rs:95-103): commands transparently reconnect with
    exponential backoff when the connection drops or the server is briefly
    away. Replay discipline: a command is only re-sent when either (a) the
    failure happened before any bytes went out (connect failure), or (b)
    the caller marked it ``replay_safe`` (reads and idempotent SETs). The
    conditional-insert Lua scripts are NOT replay safe — replaying one that
    executed but lost its reply would surface a dedup error for a write
    that actually landed, desynchronizing the seed dict from the model
    aggregate — so those surface a ``StorageError`` instead, which routes
    the round to the Failure phase exactly like the reference's failed
    in-flight commands.
    """

    RETRY_ATTEMPTS = 4
    RETRY_BASE_DELAY = 0.05  # seconds; doubles per attempt
    IDLE_PROBE_AFTER = 1.0  # validate connections idle longer than this

    def __init__(self, host: str = "127.0.0.1", port: int = 6379, db: int = 0):
        self.host, self.port, self.db = host, port, db
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._lock = asyncio.Lock()
        self._last_use = 0.0

    async def connect(self) -> None:
        self._reader, self._writer = await asyncio.open_connection(self.host, self.port)
        if self.db:
            await self.command(b"SELECT", str(self.db).encode())

    async def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except Exception:  # lint: swallow-ok (best-effort socket teardown)
                pass
        self._reader = self._writer = None

    async def command(self, *parts: bytes, replay_safe: bool = True):
        """Sends one command and decodes one reply (auto-reconnect + backoff).

        ``replay_safe=False``: once the request bytes may have reached the
        server, a connection failure raises instead of re-sending.
        """
        async with self._lock:
            last: Exception | None = None
            for attempt in range(self.RETRY_ATTEMPTS):
                sent = False
                try:
                    if (
                        not replay_safe
                        and self._writer is not None
                        and asyncio.get_running_loop().time() - self._last_use
                        > self.IDLE_PROBE_AFTER
                    ):
                        # validate a stale-looking idle connection first, so
                        # only genuine mid-command drops become hard failures
                        # (hot-path commands skip the probe entirely)
                        try:
                            await self._roundtrip((b"PING",))
                        except (ConnectionError, OSError, asyncio.IncompleteReadError):
                            self._drop_connection()
                    if self._writer is None:
                        await self._connect_locked()
                    sent = True  # _roundtrip writes before reading
                    result = await self._roundtrip(parts)
                    self._last_use = asyncio.get_running_loop().time()
                    return result
                except (ConnectionError, OSError, asyncio.IncompleteReadError) as e:
                    last = e
                    self._drop_connection()
                    if sent and not replay_safe:
                        # the command MAY have executed server-side: mark the
                        # error permanent so the resilience layer never
                        # retries it — a replayed conditional insert would
                        # surface ALREADY_* for our own landed write and
                        # desync the seed dict from the model aggregate
                        err = StorageError(
                            f"redis connection lost mid-command (not replayed): {e}"
                        )
                        err.transient = False
                        raise err from e
                    if attempt + 1 < self.RETRY_ATTEMPTS:
                        await asyncio.sleep(self.RETRY_BASE_DELAY * (2**attempt))
            raise TransientStorageError(
                f"redis unreachable after {self.RETRY_ATTEMPTS} attempts: {last}"
            )

    def _drop_connection(self) -> None:
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:  # lint: swallow-ok (best-effort socket teardown)
                pass
        self._reader = self._writer = None

    async def _connect_locked(self) -> None:
        self._reader, self._writer = await asyncio.open_connection(self.host, self.port)
        if self.db:
            await self._roundtrip((b"SELECT", str(self.db).encode()))

    async def _roundtrip(self, parts: tuple[bytes, ...]):
        assert self._writer is not None and self._reader is not None
        out = [b"*%d\r\n" % len(parts)]
        for p in parts:
            out.append(b"$%d\r\n%s\r\n" % (len(p), p))
        self._writer.write(b"".join(out))
        await self._writer.drain()
        return await self._read_reply()

    async def _read_reply(self):
        assert self._reader is not None
        line = await self._reader.readline()
        if not line:
            raise ConnectionError("redis connection closed")
        kind, rest = line[:1], line[1:-2]
        if kind == b"+":
            return rest
        if kind == b"-":
            raise StorageError(f"redis error: {rest.decode()}")
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n == -1:
                return None
            data = await self._reader.readexactly(n + 2)
            return data[:-2]
        if kind == b"*":
            n = int(rest)
            if n == -1:
                return None
            return [await self._read_reply() for _ in range(n)]
        raise StorageError(f"unexpected RESP reply type {kind!r}")


# --- Lua scripts (same validation logic as the reference's) ----------------

# KEYS[1]=sum_dict, ARGV[1]=pk, ARGV[2]=ephm_pk
ADD_SUM_PARTICIPANT = b"""
if redis.call("HSETNX", KEYS[1], ARGV[1], ARGV[2]) == 1 then
  return 1
end
return 0
"""

# KEYS[1]=sum_dict, KEYS[2]=update_participants, KEYS[3]=seed-dict key
# prefix (the tenant prefix + "seed_dict:" — built Lua-side so the per-sum
# hashes land under the SAME prefixed namespace seed_dict() reads and the
# prefix-scoped delete scans), ARGV[1]=update_pk, ARGV[2..]=alternating
# sum_pk, seed
ADD_LOCAL_SEED_DICT = b"""
local n_entries = (#ARGV - 1) / 2
if n_entries ~= redis.call("HLEN", KEYS[1]) then
  return -1
end
for i = 2, #ARGV, 2 do
  if redis.call("HEXISTS", KEYS[1], ARGV[i]) == 0 then
    return -2
  end
end
if redis.call("SISMEMBER", KEYS[2], ARGV[1]) == 1 then
  return -3
end
for i = 2, #ARGV, 2 do
  if redis.call("HEXISTS", KEYS[3] .. ARGV[i], ARGV[1]) == 1 then
    return -4
  end
end
for i = 2, #ARGV, 2 do
  redis.call("HSET", KEYS[3] .. ARGV[i], ARGV[1], ARGV[i + 1])
end
redis.call("SADD", KEYS[2], ARGV[1])
return 0
"""

# KEYS[1]=sum_dict, KEYS[2]=mask_submitted, KEYS[3]=mask_dict,
# ARGV[1]=pk, ARGV[2]=serialized mask
INCR_MASK_SCORE = b"""
if redis.call("HEXISTS", KEYS[1], ARGV[1]) == 0 then
  return -1
end
if redis.call("SISMEMBER", KEYS[2], ARGV[1]) == 1 then
  return -2
end
redis.call("SADD", KEYS[2], ARGV[1])
redis.call("ZINCRBY", KEYS[3], 1, ARGV[2])
return 0
"""

_K_STATE = b"coordinator_state"
_K_SUM_DICT = b"sum_dict"
_K_UPDATE_SET = b"update_participants"
_K_MASK_SUBMITTED = b"mask_submitted"
_K_MASK_DICT = b"mask_dict"
_K_LATEST_MODEL = b"latest_global_model_id"
_K_ROUND_CKPT = b"round_checkpoint"


class RedisCoordinatorStorage(CoordinatorStorage):
    """Coordinator storage over Redis with Lua-scripted atomicity."""

    def __init__(self, host: str = "127.0.0.1", port: int = 6379, db: int = 0,
                 key_prefix: str = ""):
        # `key_prefix` namespaces every round-state key (multi-tenant
        # coordinators share one redis db with per-tenant prefixes,
        # docs/DESIGN.md §19); "" keeps the historical flat keyspace
        self.client = RespClient(host, port, db)
        self._p = key_prefix.encode()

    def _k(self, key: bytes) -> bytes:
        return self._p + key

    async def set_coordinator_state(self, state: bytes) -> None:
        await self.client.command(b"SET", self._k(_K_STATE), state)

    async def coordinator_state(self) -> Optional[bytes]:
        return await self.client.command(b"GET", self._k(_K_STATE))

    async def add_sum_participant(self, pk: bytes, ephm_pk: bytes) -> Optional[SumPartAddError]:
        ok = await self.client.command(
            b"EVAL", ADD_SUM_PARTICIPANT, b"1", self._k(_K_SUM_DICT), pk, ephm_pk,
            replay_safe=False,
        )
        return None if ok == 1 else SumPartAddError.ALREADY_EXISTS

    async def sum_dict(self):
        flat = await self.client.command(b"HGETALL", self._k(_K_SUM_DICT))
        if not flat:
            return None
        return {flat[i]: flat[i + 1] for i in range(0, len(flat), 2)}

    async def add_local_seed_dict(
        self, update_pk: bytes, local_seed_dict
    ) -> Optional[LocalSeedDictAddError]:
        argv: list[bytes] = [update_pk]
        for sum_pk, seed in local_seed_dict.items():
            seed_bytes = seed.as_bytes() if isinstance(seed, EncryptedMaskSeed) else bytes(seed)
            argv += [sum_pk, seed_bytes]
        code = await self.client.command(
            b"EVAL", ADD_LOCAL_SEED_DICT, b"3",
            self._k(_K_SUM_DICT), self._k(_K_UPDATE_SET), self._k(b"seed_dict:"),
            *argv,
            replay_safe=False,
        )
        return {
            0: None,
            -1: LocalSeedDictAddError.LENGTH_MISMATCH,
            -2: LocalSeedDictAddError.UNKNOWN_SUM_PARTICIPANT,
            -3: LocalSeedDictAddError.UPDATE_PK_ALREADY_SUBMITTED,
            -4: LocalSeedDictAddError.UPDATE_PK_ALREADY_EXISTS_IN_UPDATE_SEED_DICT,
        }[int(code)]

    async def seed_dict(self):
        sums = await self.sum_dict()
        if not sums:
            return None
        out = {}
        for sum_pk in sums:
            flat = await self.client.command(b"HGETALL", self._k(b"seed_dict:") + sum_pk)
            out[sum_pk] = {
                flat[i]: EncryptedMaskSeed(flat[i + 1]) for i in range(0, len(flat), 2)
            }
        return out if any(out.values()) else None

    async def prune_update_participants(self, keep_pks) -> bool:
        # journal resume (docs/DESIGN.md §9): redis round state survives a
        # coordinator crash, so an update accepted between the last journal
        # write and the kill is still here — but its client never saw the
        # ack and will retry; dropping the orphan seeds + membership makes
        # that retry succeed instead of bouncing off ALREADY_SUBMITTED
        keep = set(keep_pks)
        members = await self.client.command(b"SMEMBERS", self._k(_K_UPDATE_SET)) or []
        orphans = [pk for pk in members if pk not in keep]
        if not orphans:
            return True
        sums = await self.client.command(b"HKEYS", self._k(_K_SUM_DICT)) or []
        for sum_pk in sums:
            await self.client.command(b"HDEL", self._k(b"seed_dict:") + sum_pk, *orphans)
        await self.client.command(b"SREM", self._k(_K_UPDATE_SET), *orphans)
        return True

    async def incr_mask_score(self, pk: bytes, mask: MaskObject) -> Optional[MaskScoreIncrError]:
        code = await self.client.command(
            b"EVAL",
            INCR_MASK_SCORE,
            b"3",
            self._k(_K_SUM_DICT),
            self._k(_K_MASK_SUBMITTED),
            self._k(_K_MASK_DICT),
            pk,
            serialize_mask_object(mask),
            replay_safe=False,
        )
        return {
            0: None,
            -1: MaskScoreIncrError.UNKNOWN_SUM_PK,
            -2: MaskScoreIncrError.MASK_ALREADY_SUBMITTED,
        }[int(code)]

    async def best_masks(self):
        reply = await self.client.command(
            b"ZREVRANGE", self._k(_K_MASK_DICT), b"0", b"1", b"WITHSCORES"
        )
        if not reply:
            return None
        out = []
        for i in range(0, len(reply), 2):
            mask, _ = parse_mask_object(reply[i])
            out.append((mask, int(float(reply[i + 1]))))
        return out

    async def number_of_unique_masks(self) -> int:
        return int(await self.client.command(b"ZCARD", self._k(_K_MASK_DICT)))

    async def delete_coordinator_data(self) -> None:
        if not self._p:
            await self.client.command(b"FLUSHDB")
            return
        # prefixed (multi-tenant) keyspaces: flush ONLY this tenant's keys
        # — FLUSHDB would wipe every other tenant sharing the db. Cursor
        # SCAN, not KEYS: a blocking full-keyspace walk would stall every
        # OTHER tenant's round operations on a shared production server
        cursor = b"0"
        while True:
            reply = await self.client.command(
                b"SCAN", cursor, b"MATCH", self._p + b"*", b"COUNT", b"500"
            )
            cursor, keys = reply[0], reply[1]
            if keys:
                await self.client.command(b"DEL", *keys)
            if cursor in (b"0", 0, "0"):
                break

    async def delete_dicts(self) -> None:
        sums = await self.client.command(b"HKEYS", self._k(_K_SUM_DICT)) or []
        keys = [self._k(_K_SUM_DICT), self._k(_K_UPDATE_SET), self._k(_K_MASK_SUBMITTED), self._k(_K_MASK_DICT)]
        keys += [self._k(b"seed_dict:") + pk for pk in sums]
        await self.client.command(b"DEL", *keys)

    async def set_latest_global_model_id(self, model_id: str) -> None:
        await self.client.command(b"SET", self._k(_K_LATEST_MODEL), model_id.encode())

    async def latest_global_model_id(self) -> Optional[str]:
        v = await self.client.command(b"GET", self._k(_K_LATEST_MODEL))
        return v.decode() if v is not None else None

    async def set_round_checkpoint(self, data: bytes) -> None:
        await self.client.command(b"SET", self._k(_K_ROUND_CKPT), data)

    async def round_checkpoint(self):
        return await self.client.command(b"GET", self._k(_K_ROUND_CKPT))

    async def delete_round_checkpoint(self) -> None:
        await self.client.command(b"DEL", self._k(_K_ROUND_CKPT))

    async def is_ready(self) -> None:
        pong = await self.client.command(b"PING")
        if pong != b"PONG":
            raise StorageError(f"unexpected PING reply {pong!r}")
