"""In-process storage backend.

Implements the same atomic conditional-insert semantics the reference
enforces with Redis Lua scripts (reference:
rust/xaynet-server/src/storage/coordinator_storage/redis/mod.rs:208-343):
seed-dict inserts validate length against the sum dict, membership and
single submission before writing; mask scores require sum membership and a
single submission per participant. Atomicity here comes from the asyncio
single-thread execution model (no awaits inside the critical sections).

Masks in the score dict are keyed by their serialized bytes, mirroring the
Redis sorted-set keyed by the serialized mask object.
"""

from __future__ import annotations

from typing import Optional

from ..core.common import LocalSeedDict, SeedDict, SumDict
from ..core.mask.object import MaskObject
from ..core.mask.serialization import parse_mask_object, serialize_mask_object
from .traits import (
    CoordinatorStorage,
    LocalSeedDictAddError,
    MaskScoreIncrError,
    ModelStorage,
    StorageError,
    SumPartAddError,
    TrustAnchor,
)


class InMemoryCoordinatorStorage(CoordinatorStorage):
    def __init__(self):
        self._state: Optional[bytes] = None
        self._sum_dict: dict[bytes, bytes] = {}
        self._seed_dict: dict[bytes, dict[bytes, object]] = {}
        self._update_submitted: set[bytes] = set()
        self._mask_scores: dict[bytes, int] = {}
        self._mask_submitted: set[bytes] = set()
        self._latest_global_model_id: Optional[str] = None

    async def set_coordinator_state(self, state: bytes) -> None:
        self._state = bytes(state)

    async def coordinator_state(self) -> Optional[bytes]:
        return self._state

    async def add_sum_participant(self, pk: bytes, ephm_pk: bytes) -> Optional[SumPartAddError]:
        if pk in self._sum_dict:
            return SumPartAddError.ALREADY_EXISTS
        self._sum_dict[pk] = ephm_pk
        return None

    async def sum_dict(self) -> Optional[SumDict]:
        return dict(self._sum_dict) if self._sum_dict else None

    async def add_local_seed_dict(
        self, update_pk: bytes, local_seed_dict: LocalSeedDict
    ) -> Optional[LocalSeedDictAddError]:
        # same validations as the reference's Lua script (redis/mod.rs:208-267)
        if len(local_seed_dict) != len(self._sum_dict):
            return LocalSeedDictAddError.LENGTH_MISMATCH
        if any(pk not in self._sum_dict for pk in local_seed_dict):
            return LocalSeedDictAddError.UNKNOWN_SUM_PARTICIPANT
        if update_pk in self._update_submitted:
            return LocalSeedDictAddError.UPDATE_PK_ALREADY_SUBMITTED
        for sum_pk in local_seed_dict:
            if update_pk in self._seed_dict.get(sum_pk, {}):
                return LocalSeedDictAddError.UPDATE_PK_ALREADY_EXISTS_IN_UPDATE_SEED_DICT
        for sum_pk, seed in local_seed_dict.items():
            self._seed_dict.setdefault(sum_pk, {})[update_pk] = seed
        self._update_submitted.add(update_pk)
        return None

    async def seed_dict(self) -> Optional[SeedDict]:
        if not self._seed_dict:
            return None
        return {sum_pk: dict(inner) for sum_pk, inner in self._seed_dict.items()}

    async def incr_mask_score(self, pk: bytes, mask: MaskObject) -> Optional[MaskScoreIncrError]:
        # same validations as the reference's Lua script (redis/mod.rs:303-343)
        if pk not in self._sum_dict:
            return MaskScoreIncrError.UNKNOWN_SUM_PK
        if pk in self._mask_submitted:
            return MaskScoreIncrError.MASK_ALREADY_SUBMITTED
        key = serialize_mask_object(mask)
        self._mask_scores[key] = self._mask_scores.get(key, 0) + 1
        self._mask_submitted.add(pk)
        return None

    async def best_masks(self) -> Optional[list[tuple[MaskObject, int]]]:
        if not self._mask_scores:
            return None
        top = sorted(self._mask_scores.items(), key=lambda kv: kv[1], reverse=True)[:2]
        return [(parse_mask_object(data)[0], score) for data, score in top]

    async def number_of_unique_masks(self) -> int:
        return len(self._mask_scores)

    async def delete_coordinator_data(self) -> None:
        self._state = None
        self._latest_global_model_id = None
        await self.delete_round_checkpoint()
        await self.delete_dicts()

    async def delete_dicts(self) -> None:
        self._sum_dict.clear()
        self._seed_dict.clear()
        self._update_submitted.clear()
        self._mask_scores.clear()
        self._mask_submitted.clear()

    async def set_latest_global_model_id(self, model_id: str) -> None:
        self._latest_global_model_id = model_id

    async def latest_global_model_id(self) -> Optional[str]:
        return self._latest_global_model_id

    async def prune_update_participants(self, keep_pks) -> bool:
        keep = set(keep_pks)
        for inner in self._seed_dict.values():
            for pk in [p for p in inner if p not in keep]:
                del inner[pk]
        self._update_submitted = {pk for pk in self._update_submitted if pk in keep}
        return True

    async def is_ready(self) -> None:
        return None


class InMemoryModelStorage(ModelStorage):
    def __init__(self):
        self._models: dict[str, bytes] = {}

    async def set_global_model(self, round_id: int, round_seed: bytes, model_data: bytes) -> str:
        model_id = self.create_global_model_id(round_id, round_seed)
        existing = self._models.get(model_id)
        if existing is not None:
            if existing == bytes(model_data):
                return model_id  # publish-window resume: idempotent republish
            raise StorageError(f"global model {model_id} already exists")
        self._models[model_id] = bytes(model_data)
        return model_id

    async def global_model(self, model_id: str) -> Optional[bytes]:
        return self._models.get(model_id)

    async def is_ready(self) -> None:
        return None


class FilesystemModelStorage(ModelStorage):
    """Model blobs on a local/NFS/FUSE path (the S3/Minio analogue)."""

    def __init__(self, root: str):
        import os

        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, model_id: str) -> str:
        import os

        safe = model_id.replace("/", "_")
        return os.path.join(self.root, safe + ".bin")

    async def set_global_model(self, round_id: int, round_seed: bytes, model_data: bytes) -> str:
        import os

        model_id = self.create_global_model_id(round_id, round_seed)
        path = self._path(model_id)
        if os.path.exists(path):
            with open(path, "rb") as f:
                if f.read() == bytes(model_data):
                    return model_id  # publish-window resume: idempotent republish
            raise StorageError(f"global model {model_id} already exists")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(model_data)
        os.replace(tmp, path)
        return model_id

    async def global_model(self, model_id: str) -> Optional[bytes]:
        import os

        path = self._path(model_id)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return f.read()

    async def is_ready(self) -> None:
        import os

        if not os.path.isdir(self.root):
            raise StorageError(f"model store root {self.root} missing")


class NoOpModelStorage(ModelStorage):
    """Persistence disabled (reference: model_storage/noop.rs)."""

    async def set_global_model(self, round_id: int, round_seed: bytes, model_data: bytes) -> str:
        return self.create_global_model_id(round_id, round_seed)

    async def global_model(self, model_id: str) -> Optional[bytes]:
        return None

    async def is_ready(self) -> None:
        return None


class NoOpTrustAnchor(TrustAnchor):
    async def publish_proof(self, model_data: bytes) -> None:
        return None

    async def is_ready(self) -> None:
        return None


class FileCoordinatorStorage(InMemoryCoordinatorStorage):
    """In-memory round dictionaries + file-persisted durable state.

    The reference keeps everything in Redis; for single-node deployments
    without an external store, the *durable* subset (coordinator state and
    the latest-global-model pointer — exactly what restore reads,
    reference: initializer.rs:162-271) persists to a JSON file. Round
    dictionaries live in memory only — but the round JOURNAL (the binary
    ``.ckpt`` sibling) carries its own copy of them, and a boot restore
    replays them back through ``restore_round_dicts``, so a crash
    anywhere in the round resumes instead of restarting it.
    """

    def __init__(self, path: str):
        super().__init__()
        import json
        import os

        self.path = path
        if os.path.exists(path):
            with open(path) as f:
                saved = json.load(f)
            if saved.get("state") is not None:
                self._state = bytes.fromhex(saved["state"])
            self._latest_global_model_id = saved.get("latest_global_model_id")

    def _persist(self) -> None:
        import json
        import os

        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "state": self._state.hex() if self._state else None,
                    "latest_global_model_id": self._latest_global_model_id,
                },
                f,
            )
        os.replace(tmp, self.path)

    async def set_coordinator_state(self, state: bytes) -> None:
        await super().set_coordinator_state(state)
        self._persist()

    async def set_latest_global_model_id(self, model_id: str) -> None:
        await super().set_latest_global_model_id(model_id)
        self._persist()

    async def delete_coordinator_data(self) -> None:
        await super().delete_coordinator_data()
        self._persist()

    # --- mid-round checkpoint: binary sibling file (the aggregate snapshot
    # can be model-sized; it does not belong hex-encoded inside the JSON) --

    def _ckpt_path(self) -> str:
        return self.path + ".ckpt"

    async def set_round_checkpoint(self, data: bytes) -> None:
        import asyncio

        # model-sized blob: the file write goes through the executor so the
        # event loop keeps serving the API during a checkpoint
        await asyncio.get_running_loop().run_in_executor(
            None, self._write_ckpt, data
        )

    def _write_ckpt(self, data: bytes) -> None:
        import os

        tmp = self._ckpt_path() + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, self._ckpt_path())

    async def round_checkpoint(self) -> Optional[bytes]:
        import asyncio

        return await asyncio.get_running_loop().run_in_executor(None, self._read_ckpt)

    def _read_ckpt(self) -> Optional[bytes]:
        import os

        if not os.path.exists(self._ckpt_path()):
            return None
        with open(self._ckpt_path(), "rb") as f:
            return f.read()

    async def delete_round_checkpoint(self) -> None:
        import os

        try:
            os.remove(self._ckpt_path())
        except FileNotFoundError:
            pass
