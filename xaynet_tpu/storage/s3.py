"""S3-compatible global-model storage (AWS Signature V4, no third-party SDK).

Functional port of the reference's S3/Minio model store (reference:
rust/xaynet-server/src/storage/model_storage/s3.rs:69-200): bucket creation,
refuse-overwrite on the canonical ``{round_id}_{hex(seed)}`` ids, typed
network/HTTP error taxonomy. Works against any S3-compatible endpoint
(Minio, GCS interop, AWS) using path-style addressing.

The HTTP layer is a minimal asyncio HTTP/1.1 client (the coordinator only
needs PUT/GET/HEAD with Content-Length bodies), and request signing is a
from-scratch SigV4 implementation — validated in tests against a fake S3
server that *recomputes and checks* every signature.
"""

from __future__ import annotations

import asyncio
import datetime
import hashlib
import hmac
import ssl as ssl_module
from typing import Optional
from urllib.parse import quote, urlsplit

from .traits import ModelStorage, StorageError, TransientStorageError

_EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def sign_v4(
    method: str,
    host: str,
    path: str,
    *,
    access_key: str,
    secret_key: str,
    region: str,
    payload_hash: str,
    amz_date: str,
    service: str = "s3",
) -> dict[str, str]:
    """AWS Signature V4 headers for a query-less S3 request.

    Returns the headers to send (including Authorization). Kept separate
    from the client so the test fake can recompute and verify signatures
    with the same code path inverted.
    """
    date_scope = amz_date[:8]
    headers = {
        "host": host,
        "x-amz-content-sha256": payload_hash,
        "x-amz-date": amz_date,
    }
    signed_headers = ";".join(sorted(headers))
    canonical_headers = "".join(f"{k}:{headers[k]}\n" for k in sorted(headers))
    canonical_request = "\n".join(
        [method, quote(path), "", canonical_headers, signed_headers, payload_hash]
    )
    scope = f"{date_scope}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join(
        [
            "AWS4-HMAC-SHA256",
            amz_date,
            scope,
            hashlib.sha256(canonical_request.encode()).hexdigest(),
        ]
    )
    k = _hmac(("AWS4" + secret_key).encode(), date_scope)
    k = _hmac(k, region)
    k = _hmac(k, service)
    k = _hmac(k, "aws4_request")
    signature = hmac.new(k, string_to_sign.encode(), hashlib.sha256).hexdigest()
    headers["authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={signed_headers}, Signature={signature}"
    )
    return headers


class _HttpResponse:
    def __init__(self, status: int, headers: dict[str, str], body: bytes):
        self.status = status
        self.headers = headers
        self.body = body


async def _http_request(
    endpoint: str,
    method: str,
    path: str,
    headers: dict[str, str],
    body: bytes = b"",
    timeout: float = 30.0,
) -> _HttpResponse:
    """One HTTP/1.1 request over asyncio streams (Content-Length bodies)."""
    u = urlsplit(endpoint)
    host = u.hostname or "127.0.0.1"
    use_tls = u.scheme == "https"
    port = u.port or (443 if use_tls else 80)
    ssl_ctx = ssl_module.create_default_context() if use_tls else None

    async def _go() -> _HttpResponse:
        reader, writer = await asyncio.open_connection(host, port, ssl=ssl_ctx)
        try:
            lines = [f"{method} {quote(path)} HTTP/1.1"]
            send_headers = dict(headers)
            send_headers.setdefault("content-length", str(len(body)))
            send_headers.setdefault("connection", "close")
            for k, v in send_headers.items():
                lines.append(f"{k}: {v}")
            writer.write(("\r\n".join(lines) + "\r\n\r\n").encode() + body)
            await writer.drain()

            status_line = await reader.readline()
            parts = status_line.decode().split(" ", 2)
            if len(parts) < 2 or not parts[1].isdigit():
                raise StorageError(f"malformed HTTP status line {status_line!r}")
            status = int(parts[1])
            resp_headers: dict[str, str] = {}
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                name, _, value = line.decode().partition(":")
                resp_headers[name.strip().lower()] = value.strip()
            if method == "HEAD":
                # HEAD carries Content-Length of the WOULD-BE body but no
                # body bytes; reading would hit EOF
                data = b""
            elif "chunked" in resp_headers.get("transfer-encoding", "").lower():
                # de-chunk (reverse proxies in front of Minio answer this way)
                parts = []
                while True:
                    size_line = await reader.readline()
                    size = int(size_line.split(b";")[0].strip() or b"0", 16)
                    if size == 0:
                        await reader.readline()  # trailing CRLF
                        break
                    parts.append(await reader.readexactly(size))
                    await reader.readexactly(2)  # chunk CRLF
                data = b"".join(parts)
            else:
                length = resp_headers.get("content-length")
                if length is not None:
                    data = await reader.readexactly(int(length))
                else:
                    data = await reader.read()
            return _HttpResponse(status, resp_headers, data)
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:  # lint: swallow-ok (best-effort socket teardown)
                pass

    try:
        return await asyncio.wait_for(_go(), timeout)
    except StorageError:
        raise
    except asyncio.TimeoutError as e:
        raise TransientStorageError(f"object store timeout after {timeout}s") from e
    except (OSError, asyncio.IncompleteReadError) as e:
        # IncompleteReadError is an EOFError, not an OSError: a connection
        # severed mid-body must still surface as the typed storage failure
        raise TransientStorageError(f"object store unreachable: {e}") from e
    except ValueError as e:  # malformed lengths/framing from a broken proxy
        raise StorageError(f"object store sent a malformed response: {e}") from e


class S3ModelStorage(ModelStorage):
    """Global models in an S3-compatible bucket (path-style addressing)."""

    def __init__(
        self,
        endpoint: str,
        bucket: str = "global-models",
        access_key: str = "",
        secret_key: str = "",
        region: str = "us-east-1",
    ):
        self.endpoint = endpoint.rstrip("/")
        self.bucket = bucket
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        u = urlsplit(self.endpoint)
        default = 443 if u.scheme == "https" else 80
        self._host = f"{u.hostname}:{u.port}" if u.port and u.port != default else str(u.hostname)

    # --- signing ---------------------------------------------------------

    def _request_headers(self, method: str, path: str, body: bytes) -> dict[str, str]:
        amz_date = datetime.datetime.now(datetime.timezone.utc).strftime("%Y%m%dT%H%M%SZ")
        payload_hash = hashlib.sha256(body).hexdigest() if body else _EMPTY_SHA256
        return sign_v4(
            method,
            self._host,
            path,
            access_key=self.access_key,
            secret_key=self.secret_key,
            region=self.region,
            payload_hash=payload_hash,
            amz_date=amz_date,
        )

    async def _request(
        self, method: str, path: str, body: bytes = b"", extra_headers: dict | None = None
    ) -> _HttpResponse:
        headers = self._request_headers(method, path, body)
        if extra_headers:
            headers.update(extra_headers)
        return await _http_request(self.endpoint, method, path, headers, body)

    # --- operations (reference: s3.rs:69-200) ----------------------------

    async def create_bucket(self) -> None:
        """Create the bucket; already-owned is not an error (s3.rs behavior)."""
        resp = await self._request("PUT", f"/{self.bucket}")
        if resp.status in (200, 204):
            return
        if resp.status == 409:  # BucketAlreadyOwnedByYou / BucketAlreadyExists
            return
        raise StorageError(f"create bucket failed: HTTP {resp.status} {resp.body[:200]!r}")

    async def set_global_model(self, round_id: int, round_seed: bytes, model_data: bytes) -> str:
        model_id = self.create_global_model_id(round_id, round_seed)
        key = f"/{self.bucket}/{model_id}"
        # cheap early refusal without uploading the body ...
        head = await self._request("HEAD", key)
        if head.status == 200:
            raise StorageError(f"global model {model_id} already exists")
        if head.status not in (404,):
            raise StorageError(f"object store HEAD failed: HTTP {head.status}")
        # ... and an ATOMIC conditional PUT closing the HEAD->PUT race
        # between concurrent writers (S3/Minio honor If-None-Match: *)
        resp = await self._request("PUT", key, model_data, {"if-none-match": "*"})
        if resp.status == 412:
            raise StorageError(f"global model {model_id} already exists")
        if resp.status not in (200, 201):
            raise StorageError(f"store model failed: HTTP {resp.status} {resp.body[:200]!r}")
        return model_id

    async def global_model(self, model_id: str) -> Optional[bytes]:
        resp = await self._request("GET", f"/{self.bucket}/{model_id}")
        if resp.status == 404:
            return None
        if resp.status != 200:
            raise StorageError(f"fetch model failed: HTTP {resp.status}")
        return resp.body

    async def is_ready(self) -> None:
        resp = await self._request("HEAD", f"/{self.bucket}")
        if resp.status not in (200, 204):
            raise StorageError(f"bucket {self.bucket} not ready: HTTP {resp.status}")
