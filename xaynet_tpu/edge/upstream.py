"""Edge -> coordinator client: round sync, envelope shipping, proxying.

Extends the SDK's keep-alive ``HttpClient`` with the edge-tier endpoints
(``GET /edge/round``, ``POST /edge/envelope``) and wraps them in the same
``ResilientClient`` retry semantics (decorrelated-jitter ``RetryPolicy``,
server-sent ``Retry-After`` as a backoff floor, typed transient/permanent
errors) — an edge lives or dies by its upstream link, so every
coordinator conversation flows through the resilient wrapper.

Proxy reads (``/sums``, ``/seeds``, ``/model`` forwarded for participants)
deliberately do a SINGLE attempt: the participant's own ResilientClient
already retries a 502, and stacking retry loops would amplify a
coordinator brown-out instead of shedding it.
"""

from __future__ import annotations

import json
from typing import Optional

from ..resilience.policy import RetryPolicy
from ..sdk.client import HttpClient, ResilientClient, default_client_policy
from ..telemetry import tracing as trace

EDGE_TOKEN_HEADER = "X-Edge-Token"

SPAN_EDGE_ROUND = trace.declare_span("sdk.edge_round")
SPAN_EDGE_ENVELOPE = trace.declare_span("sdk.edge_envelope")
SPAN_EDGE_FORWARD = trace.declare_span("sdk.edge_forward")


class UpstreamClient(HttpClient):
    """Raw transport to the upstream coordinator (edge endpoints added)."""

    def __init__(self, base_url: str, token: str = "", timeout: float = 30.0,
                 tls_context=None, keep_alive: bool = True):
        super().__init__(base_url, timeout=timeout, tls_context=tls_context,
                         keep_alive=keep_alive)
        self.token = token

    def _auth(self) -> Optional[dict]:
        return {EDGE_TOKEN_HEADER: self.token} if self.token else None

    async def get_edge_round(self) -> Optional[dict]:
        """Current round info for edges (params + round keys + phase);
        ``None`` while the coordinator has no round to serve (204)."""
        status, headers, body = await self._request(
            "GET", "/edge/round", headers=self._auth()
        )
        if status == 204:
            return None
        self._raise_for_status(status, headers, "GET /edge/round")
        return json.loads(body.decode())

    async def post_envelope(self, blob: bytes) -> None:
        """Ship one sealed partial-aggregate envelope; raises the typed
        hierarchy (409 -> permanent rejection: drop the envelope)."""
        status, headers, body = await self._request(
            "POST", "/edge/envelope", blob, headers=self._auth()
        )
        self._raise_for_status(
            status, headers, f"POST /edge/envelope: {body[:200]!r}"
        )

    async def forward_message(self, encrypted: bytes) -> None:
        """Relay a participant upload upstream unchanged (non-update
        phases, and the fallback when the local fold rejects a member)."""
        await self.send_message(encrypted)

    async def proxy_get(self, path: str) -> tuple[int, dict, bytes]:
        """One-shot read for the proxy routes; the raw (status, headers,
        body) triple is passed through to the participant."""
        return await self._request("GET", path)


class ResilientUpstream(ResilientClient):
    """Retry wrapper over :class:`UpstreamClient` (edge endpoints included)."""

    SPANS = {
        **ResilientClient.SPANS,
        "edge_round": SPAN_EDGE_ROUND,
        "edge_envelope": SPAN_EDGE_ENVELOPE,
        "edge_forward": SPAN_EDGE_FORWARD,
    }

    def __init__(self, inner: UpstreamClient, policy: Optional[RetryPolicy] = None):
        super().__init__(inner, policy if policy is not None else default_client_policy())

    async def get_edge_round(self) -> Optional[dict]:
        return await self._call("edge_round", self.inner.get_edge_round)

    async def post_envelope(self, blob: bytes) -> None:
        await self._call("edge_envelope", self.inner.post_envelope, blob)

    async def forward_message(self, encrypted: bytes) -> None:
        await self._call("edge_forward", self.inner.forward_message, encrypted)

    async def proxy_get(self, path: str) -> tuple[int, dict, bytes]:
        return await self.inner.proxy_get(path)  # single attempt, by design

    def close(self) -> None:
        self.inner.close()
