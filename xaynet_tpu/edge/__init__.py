"""Hierarchical edge pre-aggregation tier (docs/DESIGN.md §11).

Edge aggregators admit and decrypt/verify participant uploads near the
participants, fold accepted masked updates into a partial masked aggregate
(modular addition — byte-identical to folding centrally), and ship ONE
``PartialAggregate`` envelope upstream per linger window. The coordinator
ingress shrinks by the edge batch factor — the structural unlock for
million-participant rounds (ROADMAP item 2, NET-SA in PAPERS.md).
"""

from .aggregator import EdgeAdmitError as EdgeAdmitError
from .aggregator import EdgeAggregator as EdgeAggregator
from .api import EdgeCoordinatorApi as EdgeCoordinatorApi
from .envelope import EnvelopeError as EnvelopeError
from .envelope import PartialAggregateEnvelope as PartialAggregateEnvelope
from .service import EdgeService as EdgeService
from .upstream import ResilientUpstream as ResilientUpstream
from .upstream import UpstreamClient as UpstreamClient
