"""Partial-aggregate envelope: the edge -> coordinator wire format.

One envelope carries ONE pre-folded window: the modular sum of the
window's verified masked updates, the member pks in fold order, and every
member's local seed dict. The coordinator folds it as a single
``masked_add`` dispatch and advances ``nb_models`` by the member count —
byte-identical to folding the same updates centrally, because masked
aggregation is modular addition (associative and commutative).

Wire format (same family as the checkpoint blob, docs/DESIGN.md §11):
``XNEDGE1`` magic, u32-le JSON-header length, JSON header, then the raw
``serialize_mask_object`` bytes of the partial. The header carries the
envelope identity (edge id, window sequence, round seed), the member pks,
the per-member seed dicts, and a sha256 digest of the masked payload — a
torn or corrupted transfer fails parsing, never folds.
"""

from __future__ import annotations

import hashlib
import json
import struct
from dataclasses import dataclass

from ..core.common import LocalSeedDict
from ..core.mask.object import MaskObject
from ..core.mask.seed import EncryptedMaskSeed
from ..core.mask.serialization import parse_mask_object, serialize_mask_object

MAGIC = b"XNEDGE1"


class EnvelopeError(ValueError):
    """Corrupt or inconsistent partial-aggregate envelope."""


@dataclass
class PartialAggregateEnvelope:
    """Everything the coordinator needs to fold one edge window atomically."""

    edge_id: str
    window_seq: int
    round_seed: bytes
    members: list[bytes]  # update pks, fold order
    seed_dicts: dict[bytes, LocalSeedDict]  # update pk -> {sum pk -> seed}
    masked: MaskObject  # modular sum of the members' masked models
    # optional trace context ("trace_id-span_id") of the edge's seal span:
    # the coordinator's fold span adopts the trace id, so a two-tier round
    # stitches into ONE trace (docs/DESIGN.md §16). Absent on pre-tracing
    # envelopes — the wire format stays compatible both ways.
    trace: str | None = None

    def __len__(self) -> int:
        return len(self.members)

    def to_bytes(self) -> bytes:
        masked_raw = serialize_mask_object(self.masked)
        fields = {
            "edge_id": self.edge_id,
            "window_seq": self.window_seq,
            "round_seed": self.round_seed.hex(),
            "members": [pk.hex() for pk in self.members],
            "seed_dicts": {
                pk.hex(): {
                    sum_pk.hex(): seed.as_bytes().hex()
                    for sum_pk, seed in local.items()
                }
                for pk, local in self.seed_dicts.items()
            },
            "masked_sha256": hashlib.sha256(masked_raw).hexdigest(),
        }
        if self.trace:
            fields["trace"] = self.trace
        header = json.dumps(fields).encode()
        return MAGIC + struct.pack("<I", len(header)) + header + masked_raw

    @classmethod
    def from_bytes(cls, raw: bytes) -> "PartialAggregateEnvelope":
        if len(raw) < len(MAGIC) + 4 or raw[: len(MAGIC)] != MAGIC:
            raise EnvelopeError("bad magic")
        (header_len,) = struct.unpack_from("<I", raw, len(MAGIC))
        body_at = len(MAGIC) + 4 + header_len
        if body_at > len(raw):
            raise EnvelopeError("truncated header")
        try:
            header = json.loads(raw[len(MAGIC) + 4 : body_at].decode())
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise EnvelopeError(f"bad header: {e}") from e
        masked_raw = raw[body_at:]
        try:
            if hashlib.sha256(masked_raw).hexdigest() != header["masked_sha256"]:
                raise EnvelopeError("masked payload digest mismatch")
            members = [bytes.fromhex(pk) for pk in header["members"]]
            seed_dicts = {
                bytes.fromhex(pk): {
                    bytes.fromhex(sum_pk): EncryptedMaskSeed(bytes.fromhex(seed))
                    for sum_pk, seed in local.items()
                }
                for pk, local in header["seed_dicts"].items()
            }
            envelope = cls(
                edge_id=str(header["edge_id"]),
                window_seq=int(header["window_seq"]),
                round_seed=bytes.fromhex(header["round_seed"]),
                members=members,
                seed_dicts=seed_dicts,
                masked=parse_mask_object(masked_raw)[0],
                trace=str(header["trace"]) if header.get("trace") else None,
            )
        except EnvelopeError:
            raise
        except (KeyError, ValueError, TypeError) as e:
            raise EnvelopeError(f"malformed envelope: {e}") from e
        if not envelope.members:
            raise EnvelopeError("empty envelope")
        if sorted(envelope.seed_dicts) != sorted(envelope.members):
            raise EnvelopeError("seed dicts do not match the member list")
        return envelope
