"""Edge aggregator process wiring and entry point.

Run:  python -m xaynet_tpu.edge.runner -c configs/edge.toml

The config reuses the coordinator's loader: ``[edge]`` names the upstream
coordinator and the window bounds, ``[api]`` binds the participant-facing
socket, ``[ingest]`` tunes the reused admission/intake machinery and
``[log]`` the logging — everything else is ignored by the edge role.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal

from ..utils import tracing
from .rest import EdgeRestServer
from .service import EdgeService
from ..server.settings import Settings

logger = logging.getLogger("xaynet.edge")


async def serve(settings: Settings) -> None:
    settings.edge.validate_runner()
    logging.basicConfig(
        level=getattr(logging, settings.log.filter.upper(), logging.INFO),
        format="%(asctime)s %(name)s %(levelname)s [%(request_id)s] %(message)s",
    )
    for handler in logging.getLogger().handlers:
        if not any(isinstance(f, tracing.RequestIdFilter) for f in handler.filters):
            handler.addFilter(tracing.RequestIdFilter())

    service = EdgeService(settings)
    rest = EdgeRestServer(service)
    host, _, port = settings.api.bind_address.partition(":")
    bound_host, bound_port = await rest.start(host or "127.0.0.1", int(port or 8082))
    if not settings.edge.edge_id:
        # a stable-enough default identity: the bound participant socket
        service.edge_id = f"edge-{bound_host}:{bound_port}"
    await service.start()

    stop = asyncio.get_running_loop().create_future()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            asyncio.get_running_loop().add_signal_handler(sig, lambda: stop.cancel())
        except NotImplementedError:  # pragma: no cover (non-unix)
            pass
    try:
        await stop
    except asyncio.CancelledError:
        pass
    finally:
        await rest.stop()
        await service.stop()
        logger.info("edge %s stopped", service.edge_id)


def main() -> None:
    parser = argparse.ArgumentParser(description="xaynet-tpu edge aggregator")
    parser.add_argument("-c", "--config", help="TOML configuration file", default=None)
    parser.add_argument(
        "--upstream", help="override [edge] upstream_url", default=None
    )
    args = parser.parse_args()
    settings = Settings.load(args.config)
    if args.upstream:
        settings.edge.upstream_url = args.upstream
    asyncio.run(serve(settings))


if __name__ == "__main__":
    main()
