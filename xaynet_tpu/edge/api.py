"""Coordinator-side edge-tier API: round handoff + envelope intake.

``EdgeCoordinatorApi`` is what the coordinator's REST server exposes under
``/edge/*`` when ``[edge] enabled = true``:

- ``round_info`` hands a trusted edge everything it needs to act as a
  decrypt/verify tier for the current round — the public round parameters
  PLUS the round's encryption secret key. Edges are coordinator-operated
  infrastructure in the NET-SA sense (in-network aggregation nodes inside
  the operator's trust domain); the optional shared ``token`` gates the
  endpoint on open networks.
- ``submit_envelope`` parses a partial-aggregate envelope and forwards it
  to the state machine as ONE :class:`PartialAggregate` request; the
  update phase folds it atomically (docs/DESIGN.md §11).
"""

from __future__ import annotations

import hmac
import logging
from typing import Optional

from ..server.requests import PartialAggregate, RequestError, RequestSender
from .envelope import EnvelopeError, PartialAggregateEnvelope
from .upstream import EDGE_TOKEN_HEADER

logger = logging.getLogger("xaynet.edge")


class EdgeCoordinatorApi:
    """The coordinator's half of the edge-tier protocol."""

    def __init__(self, events, request_tx: RequestSender, token: str = ""):
        self.events = events
        self.request_tx = request_tx
        self.token = token

    def authorized(self, headers: dict) -> bool:
        """Shared-token check (no token configured = open network).

        Constant-time: the endpoint behind it hands out the round's secret
        key, so the comparison must not leak matching-prefix timing.
        """
        if not self.token:
            return True
        supplied = headers.get(EDGE_TOKEN_HEADER.lower()) or ""
        return hmac.compare_digest(supplied.encode(), self.token.encode())

    def round_info(self) -> dict:
        """Round handoff for the trusted edge tier: public params, the
        round's encryption keypair, and the coordinator's current phase."""
        params = self.events.params.get_latest().event
        keys = self.events.keys.get_latest().event
        return {
            "round_id": self.events.params.get_latest().round_id,
            "phase": self.events.phase.get_latest().event.value,
            "params": params.to_dict(),
            "secret_key": keys.secret.as_bytes().hex(),
        }

    async def submit_envelope(self, body: bytes) -> tuple[bool, Optional[str]]:
        """Parse + forward one envelope; returns ``(accepted, detail)``.

        ``accepted`` False with a detail means a PROTOCOL rejection (the
        edge must drop the envelope, not retry it); parse failures raise
        :class:`EnvelopeError` and infrastructure failures propagate.
        """
        envelope = PartialAggregateEnvelope.from_bytes(body)
        request = PartialAggregate(
            edge_id=envelope.edge_id,
            window_seq=envelope.window_seq,
            round_seed=envelope.round_seed,
            members=envelope.members,
            seed_dicts=envelope.seed_dicts,
            masked=envelope.masked,
            trace=envelope.trace,
        )
        try:
            await self.request_tx.request(request)
        except RequestError as err:
            if err.kind is RequestError.Kind.INTERNAL:
                raise  # channel closed / infrastructure: 503, edge retries
            logger.info(
                "edge envelope %s/%d rejected: %s",
                envelope.edge_id,
                envelope.window_seq,
                err,
            )
            return False, str(err)
        return True, None


__all__ = ["EdgeCoordinatorApi", "EnvelopeError"]
