"""Edge REST surface: a coordinator-shaped API for participants.

Participants point their SDK at an edge URL exactly as they would at a
coordinator — the API is the same. Behind it:

- ``POST /message`` during the update phase flows into the edge's OWN
  admission-controlled ingest pipeline (fold-locally path); during every
  other phase the opaque ciphertext is relayed upstream unchanged (sum and
  sum2 messages are per-message by construction — only updates
  pre-aggregate);
- ``GET /params`` serves the locally synced round parameters (identical
  bytes to upstream's — the edge learned them there);
- ``GET /sums`` / ``/seeds`` / ``/model`` proxy upstream one-shot (the
  participant's own resilient client retries a 502);
- ``GET /healthz`` carries the ``edge`` section (upstream link, window
  members, envelope backlog) through the shared ``health_extra`` hook;
- ``GET /metrics`` renders the process registry (``xaynet_edge_*``).
"""

from __future__ import annotations

import logging
import math

from ..sdk.client import ClientError, ClientShedError
from ..server.rest import RestServer
from ..server.services import Fetcher
from .service import EdgeService

logger = logging.getLogger("xaynet.edge")

# reads relayed verbatim from the upstream coordinator
_PROXY_PATHS = {"/sums", "/seeds", "/model"}


class EdgeRestServer(RestServer):
    """The participant-facing API of one edge process."""

    def __init__(self, service: EdgeService, registry=None):
        super().__init__(
            Fetcher(service.events_sub),
            service.handler,
            registry=registry,
            pipeline=service.pipeline,
            health_extra=service.health,
        )
        self.service = service

    async def _dispatch(self, method: str, path: str, query: str, body: bytes,
                        headers, routes):
        try:
            if method == "POST" and path == "/message":
                if self.service.accepting_updates:
                    # the local fold path: admission -> intake -> decrypt ->
                    # coalesce -> EdgeAggregator (super()'s pipeline branch)
                    return await super()._dispatch(
                        method, path, query, body, headers, routes
                    )
                return await self._forward(body)
            if method == "GET" and path in _PROXY_PATHS:
                return await self._proxy(path, query)
            if method == "GET" and path == "/params" and not self.service.synced:
                # no round learned yet: the local params are placeholders
                return await self._proxy(path, query)
        except Exception as err:  # proxy/forward faults must not 500-loop
            logger.warning("edge relay failed: %s %s: %s", method, path, err)
            return 502, str(err).encode(), "text/plain"
        return await super()._dispatch(method, path, query, body, headers, routes)

    async def _forward(self, body: bytes):
        """Relay an opaque upload upstream (non-update phases)."""
        try:
            await self.service.forward_upstream(body)
        except ClientShedError as err:
            retry = str(max(1, math.ceil(err.retry_after or 1.0)))
            return 429, b"upstream shedding; retry later", "text/plain", {
                "Retry-After": retry
            }
        except ClientError as err:
            return 502, f"upstream unavailable: {err}".encode(), "text/plain"
        return 200, b"", "text/plain"

    async def _proxy(self, path: str, query: str):
        """One-shot upstream read, status/body passed through verbatim."""
        target = path + (f"?{query}" if query else "")
        try:
            status, headers, payload = await self.service.upstream.proxy_get(target)
        except ClientError as err:
            return 502, f"upstream unavailable: {err}".encode(), "text/plain"
        return status, payload, headers.get("content-type", "application/octet-stream")
