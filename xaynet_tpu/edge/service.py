"""EdgeService: the long-running core of an edge aggregator process.

An edge is a lightweight decrypt/verify/fold tier between participants and
the coordinator (docs/DESIGN.md §11). It reuses the coordinator's own
machinery end to end:

- the **ingest pipeline** (admission watermarks, bounded intake shards,
  batched decrypt workers, the update coalescer) admits participant
  uploads exactly as a coordinator would — the edge just sits on the other
  end of the request channel;
- the **EdgeAggregator** folds verified updates into one partial masked
  aggregate per window through the accounting path;
- the **resilient upstream client** ships each sealed window upstream as
  ONE ``PartialAggregate`` envelope, in strict window order (the
  coordinator's per-edge watermark treats any sequence at/below the last
  folded one as a replay).

Round/phase state is learned by polling ``GET /edge/round`` upstream and
re-broadcast on a local event bus, so the reused components cannot tell
they are not inside a coordinator.
"""

from __future__ import annotations

import asyncio
import logging
import time

from ..core.common import RoundParameters, RoundSeed
from ..core.crypto.encrypt import EncryptKeyPair, PublicEncryptKey, SecretEncryptKey
from ..ingest import IngestPipeline
from ..sdk.client import ClientError, ClientPermanentError
from ..server.events import EventPublisher, PhaseName
from ..server.requests import (
    ChannelClosed,
    CoalescedUpdates,
    RequestError,
    RequestReceiver,
    UpdateRequest,
)
from ..server.services import PetMessageHandler
from ..server.settings import MaskSettings, Settings
from ..telemetry import tracing as trace
from ..telemetry.recorder import flight_dump
from ..telemetry.registry import get_registry
from .aggregator import EdgeAdmitError, EdgeAggregator
from .upstream import ResilientUpstream, UpstreamClient

logger = logging.getLogger("xaynet.edge")

SPAN_WINDOW = trace.declare_span("edge.window")
SPAN_SEAL = trace.declare_span("edge.seal")
SPAN_SHIP = trace.declare_span("edge.ship")

_registry = get_registry()
ENVELOPES_SHIPPED = _registry.counter(
    "xaynet_edge_envelopes_shipped_total",
    "Sealed envelopes this edge finished shipping, by outcome (accepted | "
    "rejected = coordinator protocol refusal | dropped = retries exhausted "
    "or round moved on).",
    ("outcome",),
)
ENVELOPE_BACKLOG = _registry.gauge(
    "xaynet_edge_envelope_backlog",
    "Sealed envelopes waiting to be shipped upstream (a stuck edge shows "
    "here and in /healthz).",
)
FORWARDED = _registry.counter(
    "xaynet_edge_forwarded_total",
    "Participant messages relayed upstream unchanged (non-update phases).",
)
WINDOW_MEMBERS_DROPPED = _registry.counter(
    "xaynet_edge_window_members_dropped_total",
    "Members of a never-sealed window dropped because the round moved on "
    "upstream (distinct from shipped-envelope outcomes: these envelopes "
    "never existed).",
)

# sealed-envelope ship queue bound: past this, sealing blocks — an edge
# that cannot reach its coordinator must stop absorbing uploads rather
# than buffer unbounded windows
_SHIP_QUEUE_BOUND = 64


class EdgeService:
    """Round sync + window fold + envelope shipping for one edge process."""

    def __init__(self, settings: Settings, upstream=None):
        self.settings = settings
        edge = settings.edge
        self.edge_id = edge.edge_id or f"edge-{id(self) & 0xFFFF:04x}"
        self.upstream = (
            upstream
            if upstream is not None
            else ResilientUpstream(UpstreamClient(edge.upstream_url, token=edge.token))
        )
        # local event bus: the reused coordinator components (pipeline,
        # message handler, REST fetcher) read round state from here; the
        # sync loop is the only writer
        self.events = EventPublisher(
            round_id=0,
            keys=EncryptKeyPair.generate(),  # placeholder until first sync
            params=RoundParameters(
                pk=b"\x00" * 32,
                sum=0.0,
                update=0.0,
                seed=RoundSeed.zeroed(),
                mask_config=MaskSettings().to_config().pair(),
                model_length=1,
            ),
            phase=PhaseName.IDLE,
        )
        self.events_sub = self.events.subscribe()
        self.request_rx = RequestReceiver()
        self.request_tx = self.request_rx.sender()
        self.handler = PetMessageHandler(self.events_sub, self.request_tx)
        self.pipeline = IngestPipeline(
            self.handler, self.request_tx, self.events_sub, settings.ingest
        )
        self.aggregator: EdgeAggregator | None = None
        self.round_id = 0
        self._round_seed: bytes | None = None
        self._phase = PhaseName.IDLE
        self._window_opened: float | None = None
        self._ship_q: asyncio.Queue = asyncio.Queue(_SHIP_QUEUE_BOUND)
        self._shipping = 0  # envelopes taken off the queue, not yet resolved
        self._tasks: list[asyncio.Task] = []
        self.shipped = 0
        self.rejected = 0
        self.dropped = 0

    # --- lifecycle --------------------------------------------------------

    @property
    def synced(self) -> bool:
        return self._round_seed is not None

    @property
    def accepting_updates(self) -> bool:
        """True while update uploads should fold LOCALLY (vs forward)."""
        return self.aggregator is not None and self._phase is PhaseName.UPDATE

    async def start(self) -> None:
        await self.pipeline.start()
        self._tasks = [
            asyncio.create_task(self._sync_loop(), name="edge-sync"),
            asyncio.create_task(self._consume_loop(), name="edge-consume"),
            asyncio.create_task(self._ship_loop(), name="edge-ship"),
            asyncio.create_task(self._linger_loop(), name="edge-linger"),
        ]
        logger.info(
            "edge %s up: upstream %s, window <= %d members / %.3fs linger",
            self.edge_id,
            self.settings.edge.upstream_url,
            self.settings.edge.max_members,
            self.settings.edge.linger_s,
        )

    async def stop(self) -> None:
        for task in self._tasks:
            task.cancel()
        for task in self._tasks:
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
        self._tasks = []
        # close the channel BEFORE stopping the pipeline: its coalescer's
        # final flush awaits verdicts the (cancelled) consume loop will
        # never deliver — a closed channel fails those fast instead of
        # deadlocking stop() (same order as server.runner.serve)
        self.request_tx.close()
        await self.pipeline.stop()
        self.upstream.close()

    # --- upstream round sync ----------------------------------------------

    async def _sync_loop(self) -> None:
        while True:
            try:
                await self._sync_once()
            except asyncio.CancelledError:
                raise
            except ClientError as err:
                logger.warning("edge %s: upstream sync failed: %s", self.edge_id, err)
            except Exception:
                logger.exception("edge %s: sync loop error", self.edge_id)
            await asyncio.sleep(self.settings.edge.poll_s)

    async def _sync_once(self) -> None:
        info = await self.upstream.get_edge_round()
        if info is None:
            return
        params = RoundParameters.from_dict(info["params"])
        phase = PhaseName(info["phase"])
        seed = params.seed.as_bytes()
        if seed != self._round_seed:
            if self.aggregator is not None and self.aggregator.pending:
                # the old round is gone; its unsealed window can never fold
                # (no envelope was sealed for it — keep the shipped-envelope
                # outcome counters consistent with envelopes_sealed_total)
                WINDOW_MEMBERS_DROPPED.inc(self.aggregator.pending)
                logger.warning(
                    "edge %s: dropping %d members of a stale round's window",
                    self.edge_id,
                    self.aggregator.pending,
                )
            keys = EncryptKeyPair(
                public=PublicEncryptKey(params.pk),
                secret=SecretEncryptKey(bytes.fromhex(info["secret_key"])),
            )
            self.aggregator = EdgeAggregator(
                params.mask_config,
                params.model_length,
                max_members=self.settings.edge.max_members,
                # wall-clock base: a restarted edge (same edge_id, same
                # round) must start PAST its crashed predecessor's shipped
                # sequences or the coordinator's watermark blackholes every
                # envelope it sends for the rest of the round. Window seals
                # are linger-paced (far slower than 1/ms), so a ms base from
                # a later process start always clears the old incarnation.
                start_seq=int(time.time() * 1000),
            )
            self._round_seed = seed
            self._window_opened = None
            self.round_id = int(info["round_id"])
            # the edge derives the SAME round trace id the coordinator and
            # the SDK derive from the public seed: its ingest/window/ship
            # spans stitch into the one distributed round trace, and its
            # upstream client stamps X-Xaynet-Trace accordingly
            trace.get_tracer().begin_round(self.round_id, trace.round_trace_id(seed))
            set_round_trace = getattr(self.upstream, "set_round_trace", None)
            if set_round_trace is not None:  # injected test doubles may lack it
                set_round_trace(seed)
            self.events.set_round_id(self.round_id)
            self.events.broadcast_keys(keys)
            self.events.broadcast_params(params)
            logger.info("edge %s: synced round %d", self.edge_id, self.round_id)
        if phase is not self._phase:
            if self._phase is PhaseName.UPDATE:
                # flush-on-phase-deadline: the update window upstream is
                # closing/closed — ship whatever is pending immediately
                # rather than sit out the linger
                await self._seal_pending()
            self._phase = phase
            self.events.broadcast_phase(phase)

    # --- the fold path ----------------------------------------------------

    async def _consume_loop(self) -> None:
        """Drain the request channel the reused ingest pipeline feeds."""
        while True:
            try:
                env = await self.request_rx.next_request()
            except ChannelClosed:
                return
            req = env.request
            if isinstance(req, CoalescedUpdates):
                for member in req.envelopes(env.request_id):
                    # a coalesced batch may straddle a window boundary: seal
                    # the full window mid-batch so the tail members open the
                    # next one instead of bouncing off "window-full" (a
                    # rejection the PR-5 participant FSM treats as final)
                    if self.aggregator is not None and self.aggregator.full:
                        await self._seal_pending()
                    self._admit_one(member)
                if not env.response.done():
                    env.response.set_result(None)
            else:
                if self.aggregator is not None and self.aggregator.full:
                    await self._seal_pending()
                self._admit_one(env)
            if self.aggregator is not None and self.aggregator.full:
                await self._seal_pending()

    def _admit_one(self, env) -> None:
        req = env.request
        if not isinstance(req, UpdateRequest) or not self.accepting_updates:
            self._resolve(
                env, RequestError(RequestError.Kind.MESSAGE_REJECTED, "edge folds updates only")
            )
            return
        try:
            if self.aggregator.pending == 0:
                self._window_opened = time.monotonic()
            self.aggregator.admit(req)
        except EdgeAdmitError as err:
            self._resolve(env, RequestError(RequestError.Kind.MESSAGE_REJECTED, str(err)))
            return
        self._resolve(env, None)

    @staticmethod
    def _resolve(env, error) -> None:
        if env.response.done():
            return
        if error is None:
            env.response.set_result(None)
        else:
            env.response.set_exception(error)

    async def _linger_loop(self) -> None:
        linger = self.settings.edge.linger_s
        tick = max(min(linger / 2 if linger > 0 else 0.05, 0.25), 0.01)
        while True:
            await asyncio.sleep(tick)
            if (
                self._window_opened is not None
                and time.monotonic() - self._window_opened >= linger
            ):
                await self._seal_pending()

    async def _seal_pending(self) -> None:
        if self.aggregator is None or not self.aggregator.pending:
            return
        opened = self._window_opened
        tracer = trace.get_tracer()
        with tracer.span(SPAN_SEAL, members=self.aggregator.pending) as seal_span:
            envelope = self.aggregator.seal(self.edge_id, self._round_seed)
            if seal_span.ctx is not None:
                # the envelope carries the seal span's context: the
                # coordinator's fold span adopts the trace and links back
                envelope.trace = trace.format_header(seal_span.ctx)
        if opened is not None:
            # the window's lifetime (first admit -> seal) as a retro span
            tracer.record_span(
                SPAN_WINDOW,
                start=opened,
                duration=time.monotonic() - opened,
                seq=envelope.window_seq,
                members=len(envelope),
            )
        self._window_opened = None
        await self._ship_q.put(envelope)  # blocks when the backlog is full
        ENVELOPE_BACKLOG.set(self._ship_q.qsize() + self._shipping)

    # --- shipping ---------------------------------------------------------

    async def _ship_loop(self) -> None:
        """Ship sealed envelopes upstream ONE at a time, in window order —
        the coordinator's watermark is strictly monotonic per edge, so an
        out-of-order ship would be rejected as a replay."""
        while True:
            envelope = await self._ship_q.get()
            self._shipping = 1
            ENVELOPE_BACKLOG.set(self._ship_q.qsize() + self._shipping)
            try:
                with trace.get_tracer().span(
                    SPAN_SHIP, seq=envelope.window_seq, members=len(envelope)
                ) as ship_span:
                    try:
                        await self.upstream.post_envelope(envelope.to_bytes())
                    except BaseException as err:
                        outcome = "dropped"
                        if isinstance(err, ClientPermanentError):
                            outcome = "rejected"
                        ship_span.set(outcome=outcome)
                        raise
                    ship_span.set(outcome="accepted")
                self.shipped += 1
                ENVELOPES_SHIPPED.labels(outcome="accepted").inc()
            except ClientPermanentError as err:
                # protocol rejection: the members fall out of this round
                # (they retry upstream directly on their next tick if the
                # window is still open — docs/DESIGN.md §11 failure modes)
                self.rejected += 1
                ENVELOPES_SHIPPED.labels(outcome="rejected").inc()
                logger.warning(
                    "edge %s: envelope %d rejected upstream: %s",
                    self.edge_id,
                    envelope.window_seq,
                    err,
                )
            except ClientError as err:
                self.dropped += 1
                ENVELOPES_SHIPPED.labels(outcome="dropped").inc()
                logger.warning(
                    "edge %s: envelope %d dropped (upstream unreachable): %s",
                    self.edge_id,
                    envelope.window_seq,
                    err,
                )
                # forensic bundle: the span ring holds the window, seal and
                # ship-retry spans that led up to losing this envelope
                flight_dump(
                    "edge-ship-drop",
                    f"edge {self.edge_id} window {envelope.window_seq} "
                    f"({len(envelope)} members): {err}",
                    edge_id=self.edge_id,
                    window_seq=envelope.window_seq,
                )
            except asyncio.CancelledError:
                raise
            finally:
                self._shipping = 0
                ENVELOPE_BACKLOG.set(self._ship_q.qsize())

    # --- relay + health ---------------------------------------------------

    async def forward_upstream(self, encrypted: bytes) -> None:
        """Relay one participant upload unchanged (non-update phases)."""
        FORWARDED.inc()
        await self.upstream.forward_message(encrypted)

    def health(self) -> dict:
        """The /healthz ``edge`` section: upstream link + backlog depth."""
        pending = self.aggregator.pending if self.aggregator is not None else 0
        backlog = self._ship_q.qsize() + self._shipping
        section = {
            "edge": {
                "edge_id": self.edge_id,
                "upstream": self.settings.edge.upstream_url,
                "synced": self.synced,
                "round_id": self.round_id,
                "phase": self._phase.value,
                "window_members": pending,
                "backlog_envelopes": backlog,
                "shipped": self.shipped,
                "rejected": self.rejected,
                "dropped": self.dropped,
            }
        }
        if not self.synced:
            section["status"] = "unsynced"
        elif backlog >= _SHIP_QUEUE_BOUND:
            section["status"] = "stuck"
        return section
