"""EdgeAggregator: the accounting fold path for one edge window.

Every masked update accepted by an edge MUST flow through ``admit`` — it
validates the update against the round's aggregation state, folds it into
the window's partial aggregate, and records the member pk + seed dict that
the envelope will carry. A fold without the matching accounting entry
would ship a partial whose ``nb_models`` undercounts its content and break
the coordinator's nb_models == seed-watermark unmask invariant, which is
why ``tools/lint.py`` rejects any other fold call under ``edge/`` (the one
legitimate site below is annotated ``# lint: fold-ok``).
"""

from __future__ import annotations

from ..core.mask.config import MaskConfigPair
from ..core.mask.masking import Aggregation, AggregationError
from ..server.requests import UpdateRequest
from ..telemetry.registry import get_registry
from .envelope import PartialAggregateEnvelope

_registry = get_registry()
WINDOW_MEMBERS = _registry.gauge(
    "xaynet_edge_window_members",
    "Masked updates folded into the current (unsealed) edge window.",
)
MEMBER_REJECTIONS = _registry.counter(
    "xaynet_edge_member_rejections_total",
    "Updates an edge refused to fold into its window, by reason.",
    ("reason",),
)
ENVELOPES_SEALED = _registry.counter(
    "xaynet_edge_envelopes_sealed_total",
    "Edge windows sealed into partial-aggregate envelopes.",
)


class EdgeAdmitError(Exception):
    """An update was rejected by the edge fold path; ``reason`` is the
    counter label (``duplicate`` | protocol kinds from AggregationError)."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(f"{reason}{': ' + detail if detail else ''}")
        self.reason = reason


class EdgeAggregator:
    """Folds verified updates into one partial aggregate per linger window."""

    def __init__(
        self,
        config: MaskConfigPair,
        object_size: int,
        max_members: int = 64,
        start_seq: int = 0,
    ):
        if max_members < 1:
            raise ValueError("max_members must be >= 1")
        self.config = config
        self.object_size = object_size
        self.max_members = max_members
        self._agg = Aggregation(config, object_size)
        self._members: list[bytes] = []
        self._seed_dicts: dict[bytes, dict] = {}
        # pks already shipped upstream THIS round: a participant retrying
        # through the same edge must not be folded twice (the coordinator
        # would reject the whole second envelope for the one duplicate)
        self._shipped_pks: set[bytes] = set()
        # `start_seq`: the coordinator's per-edge watermark only moves
        # forward within a round, so a RESTARTED edge process must start
        # past any sequence its crashed predecessor shipped — the service
        # passes a wall-clock-derived base (sequences need not be dense,
        # only strictly increasing per (edge_id, round))
        self.window_seq = start_seq

    # --- window state -----------------------------------------------------

    @property
    def pending(self) -> int:
        """Members folded into the current, not-yet-sealed window."""
        return len(self._members)

    @property
    def full(self) -> bool:
        return len(self._members) >= self.max_members

    # --- the accounting fold path -----------------------------------------

    def admit(self, req: UpdateRequest) -> None:
        """Validate + fold one verified update into the window.

        Raises :class:`EdgeAdmitError` on rejection; the caller answers the
        participant (who then falls back to uploading upstream directly).
        """
        pk = req.participant_pk
        if pk in self._seed_dicts or pk in self._shipped_pks:
            MEMBER_REJECTIONS.labels(reason="duplicate").inc()
            raise EdgeAdmitError("duplicate", "participant already folded this round")
        if self.full:
            MEMBER_REJECTIONS.labels(reason="window-full").inc()
            raise EdgeAdmitError("window-full", "seal the window first")
        try:
            self._agg.validate_aggregation(req.masked_model)
        except AggregationError as err:
            MEMBER_REJECTIONS.labels(reason=err.kind).inc()
            raise EdgeAdmitError(err.kind) from err
        # THE fold site: accounting (member + seed dict) and the modular
        # add commit together, so a sealed envelope can never ship a model
        # count that disagrees with its content
        self._agg.aggregate(req.masked_model)  # lint: fold-ok
        self._members.append(pk)
        self._seed_dicts[pk] = dict(req.local_seed_dict)
        WINDOW_MEMBERS.set(len(self._members))

    def seal(self, edge_id: str, round_seed: bytes) -> PartialAggregateEnvelope:
        """Close the window into an envelope and start a fresh one.

        The sealed members move to the shipped set — whatever happens to
        the envelope upstream, this edge will not fold them again.
        """
        if not self._members:
            raise ValueError("cannot seal an empty window")
        envelope = PartialAggregateEnvelope(
            edge_id=edge_id,
            window_seq=self.window_seq,
            round_seed=round_seed,
            members=list(self._members),
            seed_dicts=dict(self._seed_dicts),
            masked=self._agg.object,
        )
        self.window_seq += 1
        self._shipped_pks.update(self._members)
        self._agg = Aggregation(self.config, self.object_size)
        self._members = []
        self._seed_dicts = {}
        WINDOW_MEMBERS.set(0)
        ENVELOPES_SEALED.inc()
        return envelope
