"""Deterministic SIGKILL injection for the crash-anywhere chaos harness.

``tools/soak.py --kill-matrix`` boots the coordinator subprocess with
``XAYNET_KILL_POINT=<site>:<n>`` and the phases call :func:`maybe_kill`
at their journal commit points; the *n*-th visit of the named site kills
the process with SIGKILL — no atexit handlers, no flushes, exactly the
power-loss the journal must survive. Sites:

- ``sum`` / ``update`` / ``sum2``: after the n-th accepted (and
  journaled) message of that phase;
- ``unmask:publish``: after the global model is persisted but BEFORE the
  journal entry is deleted — the publish window.

Without the environment variable every call is a no-op (one dict lookup
on the accept path). The counter is per-site and process-local: a
restarted coordinator starts at zero, so the same spec never re-fires
after recovery unless the site is genuinely revisited n more times.
"""

from __future__ import annotations

import logging
import os
import signal

ENV = "XAYNET_KILL_POINT"

logger = logging.getLogger("xaynet.resilience")

_visits: dict[str, int] = {}


def maybe_kill(site: str) -> None:
    """SIGKILL this process on the configured visit of ``site`` (no-op
    unless ``XAYNET_KILL_POINT`` names it)."""
    spec = os.environ.get(ENV)
    if not spec:
        return
    want, _, index = spec.rpartition(":")
    if want != site:
        return
    _visits[site] = _visits.get(site, 0) + 1
    try:
        n = int(index)
    except ValueError:
        logger.warning("ignoring malformed %s=%r", ENV, spec)
        return
    if _visits[site] >= n:
        logger.warning("kill point %s reached (visit %d): SIGKILL", spec, _visits[site])
        logging.shutdown()
        os.kill(os.getpid(), signal.SIGKILL)
