"""Circuit breaker for storage backends.

A backend that fails every call should not absorb a full retry schedule
per request — that turns one outage into a pile-up of blocked phases and
hammered reconnects. The breaker counts consecutive failures; at the
threshold it OPENS and fail-fasts every call for ``reset_timeout_s``, then
lets a bounded number of HALF-OPEN probes through. A probe success closes
the circuit, a probe failure re-opens it.

State is exported on ``xaynet_resilience_breaker_state`` (0 = closed,
1 = half-open, 2 = open) so an open breaker is visible on ``/metrics``
before anyone reads the logs.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable

from ..telemetry.registry import get_registry

logger = logging.getLogger("xaynet.resilience")

_registry = get_registry()
BREAKER_STATE = _registry.gauge(
    "xaynet_resilience_breaker_state",
    "Circuit breaker state per component (0 = closed, 1 = half-open, 2 = open).",
    ("component",),
)
BREAKER_TRANSITIONS = _registry.counter(
    "xaynet_resilience_breaker_transitions_total",
    "Breaker state transitions, by component and target state.",
    ("component", "to"),
)

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"
_STATE_VALUE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class BreakerOpen(RuntimeError):
    """Fail-fast: the breaker is open and the call was not attempted.

    Deliberately NOT transient for the in-place retry policy — the point
    of the breaker is to stop hammering a dead backend; recovery goes
    through the half-open probe (``is_ready`` checks bypass the gate).
    """

    transient = False

    def __init__(self, component: str, retry_in: float):
        super().__init__(
            f"{component}: circuit open, retry in {max(retry_in, 0.0):.1f}s"
        )
        self.component = component


class CircuitBreaker:
    """Consecutive-failure breaker with half-open probing.

    Thread-safe: storage calls come from the asyncio loop, but chaos tests
    and the streaming worker may record from other threads. ``clock`` is
    injectable so lifecycle tests don't sleep.
    """

    def __init__(
        self,
        component: str = "store",
        failure_threshold: int = 5,
        reset_timeout_s: float = 10.0,
        half_open_max: int = 1,
        clock: Callable[[], float] = time.monotonic,
    ):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        if reset_timeout_s <= 0:
            raise ValueError("reset_timeout_s must be > 0")
        if half_open_max < 1:
            raise ValueError("half_open_max must be >= 1")
        self.component = component
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self.half_open_max = half_open_max
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._half_open_inflight = 0
        BREAKER_STATE.labels(component=component).set(0)

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open_locked()
            return self._state

    def _set_state_locked(self, state: str) -> None:
        if state == self._state:
            return
        self._state = state
        BREAKER_STATE.labels(component=self.component).set(_STATE_VALUE[state])
        BREAKER_TRANSITIONS.labels(component=self.component, to=state).inc()
        logger.warning("breaker %s -> %s", self.component, state)
        if state == OPEN:
            # forensic bundle: which calls burned the failure budget is in
            # the span ring / metric deltas (rate-limited + fail-soft, so
            # the write never extends the outage it documents)
            from ..telemetry.recorder import flight_dump

            flight_dump(
                "breaker-open",
                f"component {self.component} opened after "
                f"{self._failures} consecutive failures",
                component=self.component,
            )

    def _maybe_half_open_locked(self) -> None:
        if self._state == OPEN and self._clock() - self._opened_at >= self.reset_timeout_s:
            self._set_state_locked(HALF_OPEN)
            self._half_open_inflight = 0

    def guard(self, probe: bool = False) -> bool:
        """Raise :class:`BreakerOpen` unless a call may proceed.

        ``probe=True`` (readiness checks) always passes — it IS the
        recovery path, and its outcome still feeds :meth:`record`.
        Returns True when a half-open slot was consumed: the caller must
        hand that back via ``record(..., held_slot=True)`` (or
        ``release(True)`` on cancellation) — only the call that took a
        slot may free one, otherwise probes and pre-transition stragglers
        would let extra traffic hit a recovering backend.
        """
        with self._lock:
            self._maybe_half_open_locked()
            if probe or self._state == CLOSED:
                return False
            if self._state == HALF_OPEN:
                if self._half_open_inflight < self.half_open_max:
                    self._half_open_inflight += 1
                    return True
                raise BreakerOpen(self.component, self.reset_timeout_s)
            raise BreakerOpen(
                self.component,
                self.reset_timeout_s - (self._clock() - self._opened_at),
            )

    def release(self, held_slot: bool = True) -> None:
        """Release a guard-acquired half-open slot with NO verdict (the
        call was cancelled, not answered) — half-open must not leak slots."""
        with self._lock:
            if held_slot and self._half_open_inflight > 0:
                self._half_open_inflight -= 1

    def record(self, success: bool, held_slot: bool = False) -> None:
        with self._lock:
            if held_slot and self._half_open_inflight > 0:
                self._half_open_inflight -= 1
            if success:
                self._failures = 0
                self._set_state_locked(CLOSED)
                return
            self._failures += 1
            if self._state == HALF_OPEN or self._failures >= self.failure_threshold:
                self._opened_at = self._clock()
                self._set_state_locked(OPEN)
