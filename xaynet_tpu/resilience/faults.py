"""Deterministic fault injection: seeded plans, reproducible chaos.

A :class:`FaultPlan` is a seeded schedule of faults keyed by *site* — a
dotted string naming an injection point (``storage.coordinator.seed_dict``,
``ingest.worker.0``, ``streaming.fold``; participant side:
``sdk.send`` fails a send attempt, ``sdk.drop`` silently loses the message
on the wire, ``sdk.straggle`` delays it — see
``sdk.client.ResilientClient``). Sites consult the plan on every
call; whether the Nth call at a site faults depends only on the plan's
seed, its rules and N — never on wall clock, thread timing or hash
randomization — so a chaos scenario that fails in CI replays byte-for-byte
from its spec string.

Spec grammar (``;``-separated clauses)::

    seed=42;storage.coordinator.*:error,nth=2/5;streaming.fold:error,max=1
    ingest.worker.*:error,rate=0.1;storage.models.*:latency,delay=0.05

Each clause is ``<site-glob>:<kind>`` plus ``key=value`` options (the LAST
colon separates glob from kind, so tenant-scoped globs like
``t:t1:storage.*`` work unquoted):

- kind ``error``   — raise (transient by default; ``perm=1`` for permanent)
- kind ``latency`` — delay the call by ``delay`` seconds (default 0.05)
- kind ``partial`` — storage only: the write LANDS, then the caller sees a
  transient error (exercises retry idempotency). Aim it at IDEMPOTENT
  writes (``set_coordinator_state``, ``set_latest_global_model_id``); on a
  conditional insert it models a backend that violates the transient ⇒
  not-executed contract (see ``resilience.store``)
- ``nth=2/5/9``    — fire on exactly these 1-based call indices at the site
- ``rate=0.1``     — else fire per-call with this probability (per-site RNG)
- ``max=3``        — at most this many faults from this rule (per site)
- ``delay=0.05``   — latency seconds for kind ``latency``

Injection points are compiled out to a single ``is None`` check when no
plan is installed — the fault-free hot path stays fault-free.
"""

from __future__ import annotations

import fnmatch
import hashlib
import os
import random
import threading
from dataclasses import dataclass
from typing import Optional

from ..telemetry.registry import get_registry

_registry = get_registry()
FAULTS_INJECTED = _registry.counter(
    "xaynet_resilience_faults_injected_total",
    "Faults fired by the installed fault plan, by site and kind.",
    ("site", "kind"),
)


class InjectedFault(RuntimeError):
    """An error fired by the fault plan (non-storage sites)."""

    def __init__(self, site: str, index: int, transient: bool = True):
        super().__init__(f"injected {'transient' if transient else 'permanent'} "
                         f"fault at {site} (call #{index})")
        self.site = site
        self.index = index
        self.transient = transient


@dataclass
class FaultRule:
    pattern: str
    kind: str  # error | latency | partial
    nth: frozenset = frozenset()
    rate: float = 0.0
    max_faults: int = 1 << 30
    delay_s: float = 0.05
    permanent: bool = False

    def __post_init__(self) -> None:
        if self.kind not in ("error", "latency", "partial"):
            raise ValueError(f"unknown fault kind {self.kind!r}")
        if not (0.0 <= self.rate <= 1.0):
            raise ValueError("fault rate must be in [0, 1]")
        if self.delay_s < 0:
            raise ValueError("fault delay must be >= 0")


@dataclass
class FaultAction:
    """One decided fault: what the site should do to itself."""

    site: str
    kind: str
    index: int  # 1-based call index at the site
    delay_s: float = 0.0
    permanent: bool = False

    def to_error(self) -> InjectedFault:
        return InjectedFault(self.site, self.index, transient=not self.permanent)


class FaultPlan:
    """Seeded, per-site-deterministic fault schedule."""

    def __init__(self, seed: int, rules: list):
        self.seed = int(seed)
        self.rules = list(rules)
        self._lock = threading.Lock()
        self._counters: dict[str, int] = {}
        self._rngs: dict[str, random.Random] = {}
        self._fired: dict[tuple[str, int], int] = {}  # (site, rule idx) -> count

    # -- construction ------------------------------------------------------

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        seed = 0
        rules: list[FaultRule] = []
        for clause in spec.split(";"):
            clause = clause.strip()
            if not clause:
                continue
            if clause.startswith("seed="):
                seed = int(clause[5:])
                continue
            # rpartition, not partition: tenant-scoped site globs carry
            # colons of their own ("t:<id>:storage.*", docs/DESIGN.md §23),
            # while kinds and options never do — the LAST colon is always
            # the glob/kind separator
            pattern, sep, rest = clause.rpartition(":")
            if not sep:
                raise ValueError(f"fault clause {clause!r}: expected '<site-glob>:<kind>[,...]'")
            parts = rest.split(",")
            kw: dict = {"pattern": pattern.strip(), "kind": parts[0].strip()}
            for opt in parts[1:]:
                key, sep, value = opt.partition("=")
                key, value = key.strip(), value.strip()
                if not sep:
                    raise ValueError(f"fault option {opt!r}: expected key=value")
                if key == "nth":
                    kw["nth"] = frozenset(int(v) for v in value.split("/"))
                elif key == "rate":
                    kw["rate"] = float(value)
                elif key == "max":
                    kw["max_faults"] = int(value)
                elif key == "delay":
                    kw["delay_s"] = float(value)
                elif key == "perm":
                    kw["permanent"] = value not in ("0", "false", "")
                else:
                    raise ValueError(f"unknown fault option {key!r}")
            rules.append(FaultRule(**kw))
        return cls(seed, rules)

    def _site_rng(self, site: str) -> random.Random:
        rng = self._rngs.get(site)
        if rng is None:
            # sha256 (not hash()) so the per-site stream is stable across
            # processes and PYTHONHASHSEED values
            digest = hashlib.sha256(f"{self.seed}:{site}".encode()).digest()
            rng = self._rngs[site] = random.Random(int.from_bytes(digest[:8], "little"))
        return rng

    # -- decisions ---------------------------------------------------------

    def decide(self, site: str) -> Optional[FaultAction]:
        """Advance the site's call counter; return the fault to apply, if any.

        First matching rule wins. Rate draws consume the per-site RNG on
        every matching call, so the decision for call N is a pure function
        of (seed, rules, N).
        """
        with self._lock:
            index = self._counters.get(site, 0) + 1
            self._counters[site] = index
            for rule_idx, rule in enumerate(self.rules):
                if not fnmatch.fnmatchcase(site, rule.pattern):
                    continue
                fired = self._fired.get((site, rule_idx), 0)
                if rule.nth:
                    hit = index in rule.nth
                elif rule.rate > 0.0:
                    hit = self._site_rng(site).random() < rule.rate
                else:
                    # no trigger option: fire on every matching call,
                    # bounded by max= ("error,max=1" = fail the first call)
                    hit = True
                if not hit or fired >= rule.max_faults:
                    continue
                self._fired[(site, rule_idx)] = fired + 1
                FAULTS_INJECTED.labels(site=site, kind=rule.kind).inc()
                return FaultAction(
                    site=site,
                    kind=rule.kind,
                    index=index,
                    delay_s=rule.delay_s,
                    permanent=rule.permanent,
                )
            return None

    def schedule(self, site: str, n: int) -> list:
        """Preview the first ``n`` decisions for a site WITHOUT mutating this
        plan (tests assert determinism against this)."""
        clone = FaultPlan(self.seed, self.rules)
        return [clone.decide(site) for _ in range(n)]


# -- process-global installation ------------------------------------------

_PLAN: Optional[FaultPlan] = None
_ENV_CHECKED = False
_ENV_VAR = "XAYNET_FAULT_PLAN"


def install_plan(plan: Optional[FaultPlan]) -> None:
    global _PLAN, _ENV_CHECKED
    _PLAN = plan
    _ENV_CHECKED = True  # an explicit install (or clear) overrides the env


def clear_plan() -> None:
    """Definitively no plan: also pins the env var as consumed, so a test
    teardown cannot be silently re-armed by a leftover XAYNET_FAULT_PLAN
    in the developer's shell."""
    global _PLAN, _ENV_CHECKED
    _PLAN = None
    _ENV_CHECKED = True


def current_plan() -> Optional[FaultPlan]:
    """The installed plan; on first call, picks up ``XAYNET_FAULT_PLAN``
    from the environment (so subprocess harnesses like the soak can inject
    without touching settings plumbing)."""
    global _PLAN, _ENV_CHECKED
    if not _ENV_CHECKED:
        _ENV_CHECKED = True
        spec = os.environ.get(_ENV_VAR)
        if spec:
            _PLAN = FaultPlan.parse(spec)
    return _PLAN


def maybe_fail(site: str) -> None:
    """Synchronous injection point: raise/delay per the installed plan.

    For sites running on DEDICATED THREADS (the streaming fold worker):
    ``latency`` actions block the calling thread only. Event-loop sites
    must use :func:`maybe_fail_async` — a ``time.sleep`` there would stall
    the whole coordinator, measuring event-loop starvation instead of the
    intended fault.
    """
    plan = current_plan()
    if plan is None:
        return
    action = plan.decide(site)
    if action is None:
        return
    if action.kind == "latency":
        import time

        time.sleep(action.delay_s)
        return
    # 'partial' has no meaning outside storage writes; treat as error
    raise action.to_error()


async def maybe_fail_async(site: str) -> None:
    """Event-loop-safe injection point (asyncio tasks: ingest workers).
    ``latency`` delays only this task via ``asyncio.sleep``."""
    plan = current_plan()
    if plan is None:
        return
    action = plan.decide(site)
    if action is None:
        return
    if action.kind == "latency":
        import asyncio

        await asyncio.sleep(action.delay_s)
        return
    raise action.to_error()
