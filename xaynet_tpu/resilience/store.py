"""ResilientStore: retry + circuit breaker + fault injection over a Store.

Every ``CoordinatorStorage`` / ``ModelStorage`` / ``TrustAnchor`` call the
coordinator makes flows through a :class:`_ResilientProxy`:

1. **fault injection** — if a :class:`~.faults.FaultPlan` is installed and
   decides to fault the site, the proxy applies it (raise / delay /
   write-then-raise) *around* the real backend;
2. **breaker gate** — an open circuit fail-fasts with ``BreakerOpen``
   before touching the backend (``is_ready`` probes bypass the gate — they
   ARE the recovery path);
3. **retry** — transient failures (``is_transient``) are retried in place
   on the policy's backoff schedule; permanent errors and protocol-error
   *returns* pass through untouched.

Retry-safety contract: **transient means not-executed (or idempotent)**.
Backends must mark a failure where the command may have executed
server-side (reply lost mid-command) as ``transient = False`` — replaying
a conditional insert whose first attempt landed would surface its dedup
verdict (ALREADY_*) for our own write, and in the update phase that means
a seed dict entry with no staged masked model: an undetectably corrupt
round. The redis backend honors this (``RespClient.command`` with
``replay_safe=False``); docs/DESIGN.md §9 discusses it.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from ..storage.traits import Store, TransientStorageError
from .breaker import BreakerOpen, CircuitBreaker
from .faults import FaultAction, current_plan
from .policy import RetryPolicy

logger = logging.getLogger("xaynet.resilience")

# methods that probe backend health rather than serve round traffic: they
# bypass the breaker gate and their retries are pointless (the Failure
# phase already loops on them with its own backoff)
_PROBE_METHODS = frozenset({"is_ready"})


class _ResilientProxy:
    """Wraps one storage component; forwards non-coroutine attributes."""

    def __init__(
        self,
        inner,
        component: str,
        policy: RetryPolicy,
        breaker: CircuitBreaker,
        site_prefix: str = "",
    ):
        self._inner = inner
        self._component = component
        self._policy = policy
        self._breaker = breaker
        self._site_prefix = site_prefix
        self._wrapped: dict[str, object] = {}

    def __getattr__(self, name: str):
        attr = getattr(self._inner, name)
        if name.startswith("_") or not asyncio.iscoroutinefunction(attr):
            return attr
        cached = self._wrapped.get(name)
        if cached is None:
            cached = self._wrapped[name] = self._wrap(name, attr)
        return cached

    def _wrap(self, name: str, method):
        site = f"{self._site_prefix}storage.{self._component}.{name}"
        probe = name in _PROBE_METHODS

        async def attempt(*args, **kwargs):
            held = self._breaker.guard(probe=probe)
            plan = current_plan()
            action: Optional[FaultAction] = plan.decide(site) if plan is not None else None
            if action is not None and action.kind == "latency":
                await asyncio.sleep(action.delay_s)
                action = None
            if action is not None and action.kind == "error":
                # an injected error stands in for the backend failing, so
                # the breaker must see it like any real failure
                self._breaker.record(success=False, held_slot=held)
                if action.permanent:
                    raise _permanent(site, action.index)
                raise TransientStorageError(
                    f"injected transient fault at {site} (call #{action.index})"
                )
            try:
                result = await method(*args, **kwargs)
            except asyncio.CancelledError:
                # a phase window expiring mid-call is a control signal, not
                # a backend failure — no verdict, but give back any
                # half-open slot guard() handed us
                self._breaker.release(held_slot=held)
                raise
            except BaseException:
                self._breaker.record(success=False, held_slot=held)
                raise
            self._breaker.record(success=True, held_slot=held)
            if action is not None:  # 'partial': the write landed, caller errors
                raise TransientStorageError(
                    f"injected partial-write fault at {site} (call #{action.index})"
                )
            return result

        if probe:
            # no in-place retry for probes; the outer readiness loop paces them
            return attempt

        async def call(*args, **kwargs):
            return await self._policy.call_async(
                attempt, *args, site=site, no_retry=(BreakerOpen,), **kwargs
            )

        return call


def _permanent(site: str, index: int) -> Exception:
    from ..storage.traits import StorageError

    err = StorageError(f"injected permanent fault at {site} (call #{index})")
    err.transient = False
    return err


class ResilientStore(Store):
    """A :class:`Store` whose components retry, break and inject.

    Drop-in: phases keep calling ``store.coordinator.<method>`` /
    ``store.models.<method>`` exactly as before. Component breakers are
    independent — a dead model store must not fail coordinator-dict reads.
    """

    def __init__(
        self,
        inner: Store,
        policy: Optional[RetryPolicy] = None,
        breaker_threshold: int = 5,
        breaker_reset_s: float = 10.0,
        breaker_half_open_max: int = 1,
        tenant: str = "default",
    ):
        self.inner = inner
        policy = policy if policy is not None else RetryPolicy()
        # tenant-scoped fault/breaker sites (docs/DESIGN.md §23): a
        # non-default tenant's storage sites are "t:<id>:storage.*", so a
        # chaos plan can fault ONE tenant's backend while its neighbours'
        # stores stay byte-identical; the default tenant keeps the flat
        # site names every existing spec targets
        prefix = "" if tenant == "default" else f"t:{tenant}:"

        def breaker(component: str) -> CircuitBreaker:
            return CircuitBreaker(
                component=f"{prefix}{component}",
                failure_threshold=breaker_threshold,
                reset_timeout_s=breaker_reset_s,
                half_open_max=breaker_half_open_max,
            )

        super().__init__(
            coordinator=_ResilientProxy(
                inner.coordinator, "coordinator", policy, breaker("coordinator"),
                site_prefix=prefix,
            ),
            models=_ResilientProxy(
                inner.models, "models", policy, breaker("models"),
                site_prefix=prefix,
            ),
            trust_anchor=(
                _ResilientProxy(
                    inner.trust_anchor, "trust_anchor", policy,
                    breaker("trust_anchor"), site_prefix=prefix,
                )
                if inner.trust_anchor is not None
                else None
            ),
        )


def wrap_store(store: Store, resilience, tenant: str = "default") -> Store:
    """Wrap per ``ResilienceSettings`` (identity when disabled / already wrapped)."""
    if not resilience.enabled or isinstance(store, ResilientStore):
        return store
    from .policy import policy_from_settings

    return ResilientStore(
        store,
        policy=policy_from_settings(resilience),
        breaker_threshold=resilience.breaker_threshold,
        breaker_reset_s=resilience.breaker_reset_s,
        breaker_half_open_max=resilience.breaker_half_open_max,
        tenant=tenant,
    )
