"""Durable phase-tagged round journal.

The coordinator persists one journal entry per round through the store
(``set_round_checkpoint``), tagged with the phase it allows re-entering:

- ``sum``: the sum dictionary as it accumulates (one rewrite per accepted
  sum participant) — a restart mid-sum re-seeds the dictionary and runs a
  reduced window for the participants still missing;
- ``update``: the drained aggregate + the sealed sum dictionary + every
  journaled seed dict, written on the ``CheckpointManager`` cadence (and
  on every fold when ``checkpoint_every_batches = 1``, which makes the
  journal write part of the accept path: an acknowledged update is a
  journaled update);
- ``sum2``: the finished aggregate plus the mask-dict votes as they
  accumulate (one rewrite per accepted vote);
- ``unmask``: the drained-but-unpublished aggregate with the final votes —
  covering the publish window; the entry is deleted only AFTER the global
  model is persisted.

A journal entry is consistent exactly when its ``nb_models`` equals the
number of update participants whose seed dicts it carries — the PET unmask
step subtracts the mask sum over ALL seeds in the seed dictionary, so an
aggregate missing any seeded update (or containing an unseeded one) would
unmask to garbage. ``validate`` enforces that invariant plus the identity
of the round (id, seed, mask config, model length) before any resume; with
``reseed=True`` (boot restore) it first replays the journaled dictionaries
into the store through the normal protocol primitives (idempotent: the
conditional-insert verdicts of already-present entries are ignored) and
prunes update participants the store kept but the journal never recorded
(accepted-but-unjournaled: the client never saw the ack and will retry).

Wire format v2 (``XNCKPT2``): magic, u32-le JSON-header length, JSON
header, then raw payload sections in order — vector accumulator (uint32-le
wire ``[model_len, L]`` or packed per-shard planar planes), unit
accumulator, concatenated serialized mask votes. Every section's sha256 is
in the header — a torn write must fail validation, never resume. ``XNCKPT1``
blobs (update-only snapshots from older coordinators) still read.
"""

from __future__ import annotations

import hashlib
import json
import logging
import struct
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..telemetry.registry import get_registry

logger = logging.getLogger("xaynet.resilience")

_registry = get_registry()
CHECKPOINTS = _registry.counter(
    "xaynet_resilience_checkpoints_total",
    "Round journal entries written, by outcome.",
    ("outcome",),
)
CHECKPOINT_SECONDS = _registry.histogram(
    "xaynet_resilience_checkpoint_seconds",
    "Wall time of one checkpoint write (drain + snapshot + store).",
)
RESUMES = _registry.counter(
    "xaynet_resilience_round_resumes_total",
    "Round resume attempts from a mid-round checkpoint, by outcome.",
    ("outcome",),
)
RESUME_TOTAL = _registry.counter(
    "xaynet_resume_total",
    "Journal resume attempts, by the phase the entry re-enters and outcome "
    "(resumed | invalid | budget_exhausted).",
    ("phase", "outcome"),
)
RECOVERY_SECONDS = _registry.gauge(
    "xaynet_recovery_seconds",
    "Restart-to-serving wall of the last boot: process entry to the REST "
    "API accepting requests (includes store restore + journal resume).",
)
SAVE_FAILURES = _registry.counter(
    "xaynet_checkpoint_save_failures_total",
    "Journal writes abandoned after the storage retry policy was exhausted "
    "(the round continues; the journal lags until the next save).",
)

MAGIC = b"XNCKPT1"
MAGIC2 = b"XNCKPT2"

RESUMABLE_PHASES = ("sum", "update", "sum2", "unmask")


class CheckpointError(ValueError):
    """Corrupt or inconsistent checkpoint blob."""


@dataclass
class AggSnapshot:
    """One exact host copy of the aggregate, as the journal stores it:
    either the gathered wire layout or packed per-shard planar planes
    (``[(lo, hi, uint32[L, hi-lo])]`` in padded model-axis coordinates) —
    device rounds checkpoint shard-by-shard without a full gather."""

    nb_models: int
    unit: np.ndarray
    vect: Optional[np.ndarray] = None  # uint32 wire [model_len, L]
    planes: Optional[list] = None  # [(lo, hi, uint32[L, hi-lo])]


@dataclass
class RoundCheckpoint:
    """One phase-tagged journal entry: everything needed to re-enter
    ``phase`` with the round state restored."""

    round_id: int
    phase: str  # one of RESUMABLE_PHASES
    round_seed: bytes
    mask_config: list  # [vect enums..., unit enums...] by name
    model_length: int
    nb_models: int
    seed_watermark: int  # distinct update pks in the journaled seed dicts
    vect: np.ndarray  # uint32 wire layout [model_len, L]; may be empty
    unit: np.ndarray  # uint32 [L_unit]; may be empty
    version: int = 2
    # round dictionaries, in replay form (hex-safe bytes everywhere):
    sum_dict: dict = field(default_factory=dict)  # {sum_pk: ephm_pk}
    # {update_pk: {sum_pk: encrypted seed bytes}} — the LOCAL seed dict
    # shape add_local_seed_dict replays directly
    seed_dicts: dict = field(default_factory=dict)
    mask_votes: list = field(default_factory=list)  # [(sum_pk, mask bytes)]
    # packed per-shard planar planes [(lo, hi, uint32[L, hi-lo])]; when set,
    # ``vect`` is empty and ``wire_vect()`` reassembles on demand
    planes: Optional[list] = None

    # -- derived -----------------------------------------------------------

    def wire_vect(self) -> np.ndarray:
        """The aggregate in wire layout ``uint32[model_len, L]`` — assembled
        from the per-shard planes when the entry was written shard-packed
        (host restore path / validation; the device restore path consumes
        ``planes`` directly, shard by shard)."""
        if self.planes:
            rows = int(self.planes[0][2].shape[0])
            width = max(int(hi) for _, hi, _ in self.planes)
            planar = np.zeros((rows, width), dtype=np.uint32)
            for lo, hi, plane in self.planes:
                planar[:, int(lo) : int(hi)] = plane
            return np.ascontiguousarray(planar[:, : self.model_length].T)
        return self.vect

    # -- serialization -----------------------------------------------------

    def to_bytes(self) -> bytes:
        if self.version < 2:
            return self._to_bytes_v1()
        vect = np.ascontiguousarray(self.vect, dtype=np.uint32)
        unit = np.ascontiguousarray(self.unit, dtype=np.uint32)
        vect_raw = vect.tobytes()
        unit_raw = unit.tobytes()
        votes_raw = b"".join(bytes(mask) for _, mask in self.mask_votes)
        planes_meta = None
        planes_raw = b""
        if self.planes is not None:
            planes_meta = []
            chunks = []
            for lo, hi, plane in self.planes:
                plane = np.ascontiguousarray(plane, dtype=np.uint32)
                planes_meta.append([int(lo), int(hi), *map(int, plane.shape)])
                chunks.append(plane.tobytes())
            planes_raw = b"".join(chunks)
        header = json.dumps(  # lint: taint-ok: durable journal; seeds stay sealed, round seed is the identity check
            {
                "version": 2,
                "round_id": self.round_id,
                "phase": self.phase,
                "round_seed": self.round_seed.hex(),
                "mask_config": self.mask_config,
                "model_length": self.model_length,
                "nb_models": self.nb_models,
                "seed_watermark": self.seed_watermark,
                "vect_shape": list(vect.shape),
                "unit_shape": list(unit.shape),
                "vect_sha256": hashlib.sha256(vect_raw).hexdigest(),
                "unit_sha256": hashlib.sha256(unit_raw).hexdigest(),
                "sum_dict": {
                    pk.hex(): ephm.hex() for pk, ephm in self.sum_dict.items()
                },
                "seed_dicts": {
                    pk.hex(): {spk.hex(): bytes(seed).hex() for spk, seed in local.items()}
                    for pk, local in self.seed_dicts.items()
                },
                "votes": [[pk.hex(), len(bytes(mask))] for pk, mask in self.mask_votes],
                "votes_sha256": hashlib.sha256(votes_raw).hexdigest(),
                "planes": planes_meta,
                "planes_sha256": hashlib.sha256(planes_raw).hexdigest(),
            }
        ).encode()
        return (
            MAGIC2
            + struct.pack("<I", len(header))
            + header
            + vect_raw
            + unit_raw
            + votes_raw
            + planes_raw
        )

    def _to_bytes_v1(self) -> bytes:
        """The update-only XNCKPT1 snapshot (kept writable for the
        backward-compat tests; new entries always write v2)."""
        vect = np.ascontiguousarray(self.vect, dtype=np.uint32)
        unit = np.ascontiguousarray(self.unit, dtype=np.uint32)
        vect_raw = vect.tobytes()
        unit_raw = unit.tobytes()
        header = json.dumps(  # lint: taint-ok: durable journal (v1); round seed is the restore identity check
            {
                "round_id": self.round_id,
                "phase": self.phase,
                "round_seed": self.round_seed.hex(),
                "mask_config": self.mask_config,
                "model_length": self.model_length,
                "nb_models": self.nb_models,
                "seed_watermark": self.seed_watermark,
                "vect_shape": list(vect.shape),
                "unit_shape": list(unit.shape),
                "vect_sha256": hashlib.sha256(vect_raw).hexdigest(),
                "unit_sha256": hashlib.sha256(unit_raw).hexdigest(),
            }
        ).encode()
        return MAGIC + struct.pack("<I", len(header)) + header + vect_raw + unit_raw

    @classmethod
    def from_bytes(cls, blob: bytes) -> "RoundCheckpoint":
        if len(blob) < len(MAGIC) + 4:
            raise CheckpointError("bad checkpoint magic")
        magic = blob[: len(MAGIC)]
        if magic not in (MAGIC, MAGIC2):
            raise CheckpointError("bad checkpoint magic")
        off = len(magic)
        (hlen,) = struct.unpack_from("<I", blob, off)
        off += 4
        try:
            header = json.loads(blob[off : off + hlen].decode())
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise CheckpointError(f"bad checkpoint header: {e}") from e
        off += hlen
        vect_shape = tuple(header["vect_shape"])
        unit_shape = tuple(header["unit_shape"])
        vect_len = int(np.prod(vect_shape)) * 4 if vect_shape else 4
        unit_len = int(np.prod(unit_shape)) * 4 if unit_shape else 4
        if magic == MAGIC:
            if len(blob) != off + vect_len + unit_len:
                raise CheckpointError("truncated checkpoint payload")
            vect_raw = blob[off : off + vect_len]
            unit_raw = blob[off + vect_len :]
            votes_raw = b""
            planes_raw = b""
            votes_meta: list = []
            planes_meta = None
        else:
            # v2 sections may be genuinely empty (a sum-phase entry has no
            # aggregate): an empty shape means zero bytes, not one element
            vect_len = int(np.prod(vect_shape, initial=1)) * 4 if all(vect_shape) else 0
            unit_len = int(np.prod(unit_shape, initial=1)) * 4 if all(unit_shape) else 0
            votes_meta = header.get("votes") or []
            votes_len = sum(int(n) for _, n in votes_meta)
            planes_meta = header.get("planes")
            planes_len = (
                sum(int(r) * int(c) * 4 for _, _, r, c in planes_meta)
                if planes_meta
                else 0
            )
            if len(blob) != off + vect_len + unit_len + votes_len + planes_len:
                raise CheckpointError("truncated checkpoint payload")
            vect_raw = blob[off : off + vect_len]
            off += vect_len
            unit_raw = blob[off : off + unit_len]
            off += unit_len
            votes_raw = blob[off : off + votes_len]
            off += votes_len
            planes_raw = blob[off:]
        if hashlib.sha256(vect_raw).hexdigest() != header["vect_sha256"]:
            raise CheckpointError("vector accumulator digest mismatch")
        if hashlib.sha256(unit_raw).hexdigest() != header["unit_sha256"]:
            raise CheckpointError("unit accumulator digest mismatch")
        if magic == MAGIC2:
            if hashlib.sha256(votes_raw).hexdigest() != header["votes_sha256"]:
                raise CheckpointError("mask vote digest mismatch")
            if hashlib.sha256(planes_raw).hexdigest() != header["planes_sha256"]:
                raise CheckpointError("shard plane digest mismatch")
        mask_votes = []
        pos = 0
        for pk_hex, n in votes_meta:
            mask_votes.append((bytes.fromhex(pk_hex), votes_raw[pos : pos + int(n)]))
            pos += int(n)
        planes = None
        if planes_meta:
            planes = []
            pos = 0
            for lo, hi, r, c in planes_meta:
                n = int(r) * int(c) * 4
                planes.append(
                    (
                        int(lo),
                        int(hi),
                        np.frombuffer(planes_raw[pos : pos + n], dtype=np.uint32).reshape(
                            int(r), int(c)
                        ),
                    )
                )
                pos += n
        empty2 = np.zeros((0, 0), dtype=np.uint32)
        return cls(
            round_id=int(header["round_id"]),
            phase=str(header["phase"]),
            round_seed=bytes.fromhex(header["round_seed"]),
            mask_config=list(header["mask_config"]),
            model_length=int(header["model_length"]),
            nb_models=int(header["nb_models"]),
            seed_watermark=int(header["seed_watermark"]),
            vect=(
                np.frombuffer(vect_raw, dtype=np.uint32).reshape(vect_shape)
                if vect_raw
                else empty2
            ),
            unit=(
                np.frombuffer(unit_raw, dtype=np.uint32).reshape(unit_shape)
                if unit_raw
                else np.zeros((0,), dtype=np.uint32)
            ),
            version=1 if magic == MAGIC else 2,
            sum_dict={
                bytes.fromhex(pk): bytes.fromhex(ephm)
                for pk, ephm in (header.get("sum_dict") or {}).items()
            },
            seed_dicts={
                bytes.fromhex(pk): {
                    bytes.fromhex(spk): bytes.fromhex(seed)
                    for spk, seed in local.items()
                }
                for pk, local in (header.get("seed_dicts") or {}).items()
            },
            mask_votes=mask_votes,
            planes=planes,
        )


def mask_config_names(config_pair) -> list:
    """Stable identity of a ``MaskConfigPair`` for checkpoint validation."""
    out = []
    for cfg in (config_pair.vect, config_pair.unit):
        out.append(
            [cfg.group_type.name, cfg.data_type.name, cfg.bound_type.name, cfg.model_type.name]
        )
    return out


def seed_dict_watermark(seed_dict) -> int:
    """Distinct update participants present in a (possibly None) seed dict."""
    if not seed_dict:
        return 0
    pks: set = set()
    for inner in seed_dict.values():
        pks.update(inner.keys())
    return len(pks)


def invert_seed_dict(seed_dict) -> dict:
    """Store seed-dict form ``{sum_pk: {update_pk: seed}}`` -> the journal's
    replay form ``{update_pk: {sum_pk: seed bytes}}`` (each inner dict is
    exactly one ``add_local_seed_dict`` call)."""
    out: dict = {}
    if not seed_dict:
        return out
    for sum_pk, inner in seed_dict.items():
        for update_pk, seed in inner.items():
            raw = seed.as_bytes() if hasattr(seed, "as_bytes") else bytes(seed)
            out.setdefault(update_pk, {})[sum_pk] = raw
    return out


def entry(
    shared,
    phase: str,
    snap: Optional[AggSnapshot] = None,
    *,
    sum_dict=None,
    seed_dicts=None,
    mask_votes=None,
) -> RoundCheckpoint:
    """Build a journal entry for the CURRENT round from a (possibly absent)
    aggregate snapshot plus the round dictionaries in replay form."""
    state = shared.state
    seed_dicts = dict(seed_dicts or {})
    return RoundCheckpoint(
        round_id=shared.round_id,
        phase=phase,
        round_seed=state.round_params.seed.as_bytes(),
        mask_config=mask_config_names(state.round_params.mask_config),
        model_length=state.round_params.model_length,
        nb_models=snap.nb_models if snap is not None else 0,
        seed_watermark=len(seed_dicts),
        vect=(
            snap.vect
            if snap is not None and snap.vect is not None
            else np.zeros((0, 0), dtype=np.uint32)
        ),
        unit=snap.unit if snap is not None else np.zeros((0,), dtype=np.uint32),
        sum_dict=dict(sum_dict or {}),
        seed_dicts=seed_dicts,
        mask_votes=list(mask_votes or []),
        planes=snap.planes if snap is not None else None,
    )


async def write_entry(shared, ckpt: RoundCheckpoint) -> bool:
    """Serialize + persist one journal entry, fail-soft.

    The store call rides the ResilientStore retry policy (runner wraps
    every storage method); exhaustion lands on
    ``xaynet_checkpoint_save_failures_total`` and the round CONTINUES — a
    journal write must never fail the phase it exists to protect.
    """
    import asyncio

    try:
        loop = asyncio.get_running_loop()
        # serialization sha256-hashes the model-sized aggregate — CPU work
        # that must not stall the loop serving the API
        blob = await loop.run_in_executor(None, ckpt.to_bytes)
        await shared.store.coordinator.set_round_checkpoint(blob)
    except asyncio.CancelledError:
        raise
    except Exception as e:
        logger.warning(
            "round %d: journal write (%s) failed: %s", shared.round_id, ckpt.phase, e
        )
        CHECKPOINTS.labels(outcome="failed").inc()
        SAVE_FAILURES.inc()
        return False
    CHECKPOINTS.labels(outcome="saved").inc()
    return True


async def validate(
    ckpt: "RoundCheckpoint", state, store, *, reseed: bool = False
) -> Optional[str]:
    """None when the journal entry may be resumed; else the rejection reason.

    ``state`` is the restored ``CoordinatorState``; ``store`` the Store the
    round dictionaries live in. With ``reseed`` (boot restore: the process
    died, the store's round dictionaries may be gone or may hold
    accepted-but-unjournaled orphans) the journaled dictionaries are first
    replayed through the protocol primitives — idempotent, every backend —
    and orphan update participants pruned so their un-acked clients can
    retry. The watermark check is the consistency linchpin (see module
    docstring); it runs against the store AFTER any replay.
    """
    if ckpt.phase not in RESUMABLE_PHASES:
        return f"unsupported checkpoint phase {ckpt.phase!r}"
    if ckpt.version < 2 and ckpt.phase != "update":
        return f"v1 checkpoint cannot resume phase {ckpt.phase!r}"
    if ckpt.round_id != state.round_id:
        return f"checkpoint round {ckpt.round_id} != state round {state.round_id}"
    if ckpt.round_seed != state.round_params.seed.as_bytes():
        return "checkpoint round seed != state round seed"
    if ckpt.mask_config != mask_config_names(state.round_params.mask_config):
        return "checkpoint mask config != state mask config"
    if ckpt.model_length != state.round_params.model_length:
        return (
            f"checkpoint model length {ckpt.model_length} != configured "
            f"{state.round_params.model_length}"
        )
    if ckpt.nb_models:
        if ckpt.planes:
            if max(int(hi) for _, hi, _ in ckpt.planes) < ckpt.model_length:
                return "checkpoint shard planes narrower than the model"
        elif ckpt.vect.ndim != 2 or ckpt.vect.shape[0] != ckpt.model_length:
            return f"checkpoint vector shape {ckpt.vect.shape} inconsistent"
    if ckpt.nb_models != ckpt.seed_watermark:
        return (
            f"checkpoint nb_models {ckpt.nb_models} != seed watermark "
            f"{ckpt.seed_watermark}: the aggregate and the seed dicts diverged"
        )
    if ckpt.version >= 2 and len(ckpt.seed_dicts) != ckpt.seed_watermark:
        return "journaled seed dicts inconsistent with the watermark"
    if reseed and ckpt.version >= 2:
        await store.coordinator.restore_round_dicts(
            ckpt.sum_dict, ckpt.seed_dicts, ckpt.mask_votes
        )
        await store.coordinator.prune_update_participants(set(ckpt.seed_dicts))
    watermark = seed_dict_watermark(await store.coordinator.seed_dict())
    if watermark != ckpt.seed_watermark:
        return (
            f"seed-dict watermark {watermark} != checkpoint "
            f"{ckpt.seed_watermark} (nb_models {ckpt.nb_models}): updates were "
            "accepted after the last checkpoint; their masked models are lost"
        )
    if ckpt.version >= 2 and ckpt.sum_dict:
        store_sum = await store.coordinator.sum_dict() or {}
        if len(store_sum) < len(ckpt.sum_dict):
            return "store sum dictionary lost entries the journal recorded"
    return None


async def load(store) -> Optional["RoundCheckpoint"]:
    """Read + parse the persisted journal entry; None when absent or corrupt
    (a corrupt checkpoint must degrade to a round restart, never crash the
    initializer)."""
    try:
        blob = await store.coordinator.round_checkpoint()
    except Exception as e:
        logger.warning("checkpoint read failed: %s", e)
        return None
    if blob is None:
        return None
    try:
        return RoundCheckpoint.from_bytes(blob)
    except CheckpointError as e:
        logger.warning("discarding corrupt round checkpoint: %s", e)
        return None


class CheckpointManager:
    """Save-cadence policy for the update phase.

    ``maybe_save`` is called after every fold batch; it persists when
    ``every_batches`` batches have accumulated since the last save or
    ``every_s`` seconds have elapsed — whichever comes first. Saving is a
    synchronization point (the streaming pipeline drains so the snapshot is
    exact); the cadence bounds how much device work one checkpoint costs.
    A failed save is logged + metered and the round continues — losing a
    checkpoint must never fail the phase it exists to protect.
    """

    def __init__(self, shared, aggregator, every_batches: int, every_s: float):
        self.shared = shared
        self.aggregator = aggregator
        self.every_batches = max(1, int(every_batches))
        self.every_s = float(every_s)
        self._batches_since = 0
        self._last_save = None  # monotonic; set on first batch
        self.saves = 0

    async def maybe_save(self) -> bool:
        import time

        now = time.monotonic()
        if self._last_save is None:
            self._last_save = now
        self._batches_since += 1
        due = self._batches_since >= self.every_batches or (
            self.every_s > 0 and now - self._last_save >= self.every_s
        )
        if not due:
            return False
        return await self._save(now)

    async def save_now(self) -> bool:
        """Force one journal write NOW (graceful-signal flush: a SIGTERM
        between cadence points must not drop up to ``every_batches`` of
        accepted updates)."""
        import time

        return await self._save(time.monotonic())

    async def _save(self, now: float) -> bool:
        import asyncio

        self._batches_since = 0
        self._last_save = now
        try:
            with CHECKPOINT_SECONDS.time():
                loop = asyncio.get_running_loop()
                # drain + snapshot off the event loop: the drain blocks on
                # in-flight device folds
                snap = await loop.run_in_executor(
                    None, self.aggregator.snapshot_journal
                )
                coord = self.shared.store.coordinator
                seed_dict = await coord.seed_dict()
                sum_dict = await coord.sum_dict()
                ckpt = entry(
                    self.shared,
                    "update",
                    snap,
                    sum_dict=sum_dict,
                    seed_dicts=invert_seed_dict(seed_dict),
                )
                if not await write_entry(self.shared, ckpt):
                    return False
        except asyncio.CancelledError:
            raise
        except Exception as e:
            logger.warning("round %d: checkpoint save failed: %s", self.shared.round_id, e)
            CHECKPOINTS.labels(outcome="failed").inc()
            SAVE_FAILURES.inc()
            return False
        self.saves += 1
        logger.info(
            "round %d: journaled update aggregate (%d models, watermark %d)",
            self.shared.round_id,
            ckpt.nb_models,
            ckpt.seed_watermark,
        )
        return True
