"""Durable mid-round aggregate checkpoints.

The update phase periodically persists the in-flight aggregate so a
coordinator restart (or a phase failure) can RESUME the round instead of
restarting it at Idle and discarding every accepted masked update. A
checkpoint is consistent exactly when its ``nb_models`` equals the number
of update participants whose seed dicts are in the store — the PET unmask
step subtracts the mask sum over ALL seeds in the seed dictionary, so an
aggregate missing any seeded update (or containing an unseeded one) would
unmask to garbage. ``validate`` enforces that invariant plus the identity
of the round (id, seed, mask config, model length) before any resume.

Wire format: ``XNCKPT1`` magic, u32-le JSON-header length, JSON header,
then the raw vector-accumulator bytes (uint32-le wire layout
``[model_len, L]``) and unit-accumulator bytes (uint32-le ``[L_unit]``).
The header carries sha256 digests of both payloads — a torn write must
fail validation, never resume.
"""

from __future__ import annotations

import hashlib
import json
import logging
import struct
from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..telemetry.registry import get_registry

logger = logging.getLogger("xaynet.resilience")

_registry = get_registry()
CHECKPOINTS = _registry.counter(
    "xaynet_resilience_checkpoints_total",
    "Mid-round aggregate checkpoints written, by outcome.",
    ("outcome",),
)
CHECKPOINT_SECONDS = _registry.histogram(
    "xaynet_resilience_checkpoint_seconds",
    "Wall time of one checkpoint write (drain + snapshot + store).",
)
RESUMES = _registry.counter(
    "xaynet_resilience_round_resumes_total",
    "Round resume attempts from a mid-round checkpoint, by outcome.",
    ("outcome",),
)

MAGIC = b"XNCKPT1"


class CheckpointError(ValueError):
    """Corrupt or inconsistent checkpoint blob."""


@dataclass
class RoundCheckpoint:
    """Everything needed to re-enter Update with the aggregate restored."""

    round_id: int
    phase: str  # always "update" today; versioned for later phases
    round_seed: bytes
    mask_config: list  # [vect enums..., unit enums...] by name
    model_length: int
    nb_models: int
    seed_watermark: int  # distinct update pks in the seed dict at snapshot
    vect: np.ndarray  # uint32 wire layout [model_len, L]
    unit: np.ndarray  # uint32 [L_unit]

    # -- serialization -----------------------------------------------------

    def to_bytes(self) -> bytes:
        vect = np.ascontiguousarray(self.vect, dtype=np.uint32)
        unit = np.ascontiguousarray(self.unit, dtype=np.uint32)
        vect_raw = vect.tobytes()
        unit_raw = unit.tobytes()
        header = json.dumps(
            {
                "round_id": self.round_id,
                "phase": self.phase,
                "round_seed": self.round_seed.hex(),
                "mask_config": self.mask_config,
                "model_length": self.model_length,
                "nb_models": self.nb_models,
                "seed_watermark": self.seed_watermark,
                "vect_shape": list(vect.shape),
                "unit_shape": list(unit.shape),
                "vect_sha256": hashlib.sha256(vect_raw).hexdigest(),
                "unit_sha256": hashlib.sha256(unit_raw).hexdigest(),
            }
        ).encode()
        return MAGIC + struct.pack("<I", len(header)) + header + vect_raw + unit_raw

    @classmethod
    def from_bytes(cls, blob: bytes) -> "RoundCheckpoint":
        if len(blob) < len(MAGIC) + 4 or blob[: len(MAGIC)] != MAGIC:
            raise CheckpointError("bad checkpoint magic")
        off = len(MAGIC)
        (hlen,) = struct.unpack_from("<I", blob, off)
        off += 4
        try:
            header = json.loads(blob[off : off + hlen].decode())
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise CheckpointError(f"bad checkpoint header: {e}") from e
        off += hlen
        vect_shape = tuple(header["vect_shape"])
        unit_shape = tuple(header["unit_shape"])
        vect_len = int(np.prod(vect_shape)) * 4 if vect_shape else 4
        unit_len = int(np.prod(unit_shape)) * 4 if unit_shape else 4
        if len(blob) != off + vect_len + unit_len:
            raise CheckpointError("truncated checkpoint payload")
        vect_raw = blob[off : off + vect_len]
        unit_raw = blob[off + vect_len :]
        if hashlib.sha256(vect_raw).hexdigest() != header["vect_sha256"]:
            raise CheckpointError("vector accumulator digest mismatch")
        if hashlib.sha256(unit_raw).hexdigest() != header["unit_sha256"]:
            raise CheckpointError("unit accumulator digest mismatch")
        return cls(
            round_id=int(header["round_id"]),
            phase=str(header["phase"]),
            round_seed=bytes.fromhex(header["round_seed"]),
            mask_config=list(header["mask_config"]),
            model_length=int(header["model_length"]),
            nb_models=int(header["nb_models"]),
            seed_watermark=int(header["seed_watermark"]),
            vect=np.frombuffer(vect_raw, dtype=np.uint32).reshape(vect_shape),
            unit=np.frombuffer(unit_raw, dtype=np.uint32).reshape(unit_shape),
        )


def mask_config_names(config_pair) -> list:
    """Stable identity of a ``MaskConfigPair`` for checkpoint validation."""
    out = []
    for cfg in (config_pair.vect, config_pair.unit):
        out.append(
            [cfg.group_type.name, cfg.data_type.name, cfg.bound_type.name, cfg.model_type.name]
        )
    return out


def seed_dict_watermark(seed_dict) -> int:
    """Distinct update participants present in a (possibly None) seed dict."""
    if not seed_dict:
        return 0
    pks: set = set()
    for inner in seed_dict.values():
        pks.update(inner.keys())
    return len(pks)


async def validate(ckpt: "RoundCheckpoint", state, store) -> Optional[str]:
    """None when the checkpoint may be resumed; else the rejection reason.

    ``state`` is the restored ``CoordinatorState``; ``store`` the Store the
    round dictionaries live in. The watermark check is the consistency
    linchpin (see module docstring).
    """
    if ckpt.phase != "update":
        return f"unsupported checkpoint phase {ckpt.phase!r}"
    if ckpt.round_id != state.round_id:
        return f"checkpoint round {ckpt.round_id} != state round {state.round_id}"
    if ckpt.round_seed != state.round_params.seed.as_bytes():
        return "checkpoint round seed != state round seed"
    if ckpt.mask_config != mask_config_names(state.round_params.mask_config):
        return "checkpoint mask config != state mask config"
    if ckpt.model_length != state.round_params.model_length:
        return (
            f"checkpoint model length {ckpt.model_length} != configured "
            f"{state.round_params.model_length}"
        )
    if ckpt.vect.ndim != 2 or ckpt.vect.shape[0] != ckpt.model_length:
        return f"checkpoint vector shape {ckpt.vect.shape} inconsistent"
    watermark = seed_dict_watermark(await store.coordinator.seed_dict())
    if watermark != ckpt.seed_watermark or ckpt.nb_models != ckpt.seed_watermark:
        return (
            f"seed-dict watermark {watermark} != checkpoint "
            f"{ckpt.seed_watermark} (nb_models {ckpt.nb_models}): updates were "
            "accepted after the last checkpoint; their masked models are lost"
        )
    return None


async def load(store) -> Optional["RoundCheckpoint"]:
    """Read + parse the persisted checkpoint; None when absent or corrupt
    (a corrupt checkpoint must degrade to a round restart, never crash the
    initializer)."""
    try:
        blob = await store.coordinator.round_checkpoint()
    except Exception as e:
        logger.warning("checkpoint read failed: %s", e)
        return None
    if blob is None:
        return None
    try:
        return RoundCheckpoint.from_bytes(blob)
    except CheckpointError as e:
        logger.warning("discarding corrupt round checkpoint: %s", e)
        return None


class CheckpointManager:
    """Save-cadence policy for the update phase.

    ``maybe_save`` is called after every fold batch; it persists when
    ``every_batches`` batches have accumulated since the last save or
    ``every_s`` seconds have elapsed — whichever comes first. Saving is a
    synchronization point (the streaming pipeline drains so the snapshot is
    exact); the cadence bounds how much device work one checkpoint costs.
    A failed save is logged + metered and the round continues — losing a
    checkpoint must never fail the phase it exists to protect.
    """

    def __init__(self, shared, aggregator, every_batches: int, every_s: float):
        self.shared = shared
        self.aggregator = aggregator
        self.every_batches = max(1, int(every_batches))
        self.every_s = float(every_s)
        self._batches_since = 0
        self._last_save = None  # monotonic; set on first batch
        self.saves = 0

    async def maybe_save(self) -> bool:
        import time

        now = time.monotonic()
        if self._last_save is None:
            self._last_save = now
        self._batches_since += 1
        due = self._batches_since >= self.every_batches or (
            self.every_s > 0 and now - self._last_save >= self.every_s
        )
        if not due:
            return False
        return await self._save(now)

    async def _save(self, now: float) -> bool:
        import asyncio

        self._batches_since = 0
        self._last_save = now
        try:
            with CHECKPOINT_SECONDS.time():
                loop = asyncio.get_running_loop()
                # drain + snapshot off the event loop: the drain blocks on
                # in-flight device folds
                vect, unit, nb = await loop.run_in_executor(
                    None, self.aggregator.snapshot_state
                )
                seed_dict = await self.shared.store.coordinator.seed_dict()
                state = self.shared.state
                ckpt = RoundCheckpoint(
                    round_id=self.shared.round_id,
                    phase="update",
                    round_seed=state.round_params.seed.as_bytes(),
                    mask_config=mask_config_names(state.round_params.mask_config),
                    model_length=state.round_params.model_length,
                    nb_models=nb,
                    seed_watermark=seed_dict_watermark(seed_dict),
                    vect=vect,
                    unit=unit,
                )
                # serialization sha256-hashes the model-sized aggregate —
                # CPU work that must not stall the loop serving the API
                blob = await loop.run_in_executor(None, ckpt.to_bytes)
                await self.shared.store.coordinator.set_round_checkpoint(blob)
        except Exception as e:
            logger.warning("round %d: checkpoint save failed: %s", self.shared.round_id, e)
            CHECKPOINTS.labels(outcome="failed").inc()
            return False
        self.saves += 1
        CHECKPOINTS.labels(outcome="saved").inc()
        logger.info(
            "round %d: checkpointed update aggregate (%d models, watermark %d)",
            self.shared.round_id,
            ckpt.nb_models,
            ckpt.seed_watermark,
        )
        return True
