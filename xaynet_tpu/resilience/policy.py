"""Retry policy: capped exponential backoff with decorrelated jitter.

Transient infrastructure faults (a dropped Redis connection, an S3 5xx, a
filesystem hiccup) should be retried *in place* instead of failing the
phase and throwing away an entire round of accepted updates. The policy
here is the AWS "decorrelated jitter" variant: each delay is drawn
uniformly from ``[base, prev_delay * 3]``, clamped to ``[base, cap]`` —
retries spread out quickly without synchronizing across callers, and the
schedule is fully deterministic under a seeded RNG (chaos tests replay it).

Classification lives here too: :func:`is_transient` decides whether a
raised error is worth retrying. Storage backends can mark errors
explicitly (``TransientStorageError`` / an ``exc.transient`` attribute);
everything else falls back to a conservative type + message heuristic.
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass, field
from typing import Callable, Iterator, Optional

from ..storage.traits import StorageError
from ..telemetry.registry import get_registry

_registry = get_registry()
RETRIES = _registry.counter(
    "xaynet_resilience_retries_total",
    "Retried operations after a transient failure, by site.",
    ("site",),
)
GIVEUPS = _registry.counter(
    "xaynet_resilience_giveups_total",
    "Operations abandoned after exhausting the retry policy, by site.",
    ("site",),
)
RETRY_BACKOFF_SECONDS = _registry.counter(
    "xaynet_resilience_backoff_seconds_total",
    "Total seconds spent sleeping between retries, by site.",
    ("site",),
)

# message fragments that mark an unclassified error as worth retrying
_TRANSIENT_HINTS = (
    "connection",
    "timeout",
    "timed out",
    "temporarily",
    "unavailable",
    "unreachable",
    "reset",
    "broken pipe",
    "try again",
    "injected transient",
)


def is_transient(exc: BaseException) -> bool:
    """Is this raised error worth retrying in place?

    Explicit markers win: an ``exc.transient`` attribute (set by
    ``TransientStorageError`` and fault injection) is authoritative in both
    directions. Otherwise connection-ish builtin types are transient, and a
    ``StorageError`` is sniffed by message — better to retry a permanent
    error a few times than to throw away a round on a blip.
    """
    marker = getattr(exc, "transient", None)
    if marker is not None:
        return bool(marker)
    if isinstance(exc, (ConnectionError, TimeoutError, asyncio.TimeoutError)):
        return True
    if isinstance(exc, OSError):
        return True
    if isinstance(exc, StorageError):
        text = str(exc).lower()
        return any(hint in text for hint in _TRANSIENT_HINTS)
    return False


@dataclass
class RetryPolicy:
    """Decorrelated-jitter exponential backoff with attempt/deadline caps.

    ``max_attempts`` counts *calls* (1 = no retry at all). ``deadline_s``
    bounds the total time spent inside :meth:`call_async` including sleeps;
    when the next sleep would cross the deadline the policy gives up early.
    A seeded ``rng`` makes the schedule reproducible.
    """

    max_attempts: int = 4
    base_delay_s: float = 0.025
    max_delay_s: float = 2.0
    deadline_s: float = 30.0
    rng: random.Random = field(default_factory=random.Random)

    def __post_init__(self) -> None:
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if self.base_delay_s <= 0 or self.max_delay_s < self.base_delay_s:
            raise ValueError("need 0 < base_delay_s <= max_delay_s")
        if self.deadline_s <= 0:
            raise ValueError("deadline_s must be > 0")

    def delays(self) -> Iterator[float]:
        """The backoff schedule: one delay per retry (attempts - 1 total)."""
        prev = self.base_delay_s
        for _ in range(self.max_attempts - 1):
            prev = min(self.max_delay_s, self.rng.uniform(self.base_delay_s, prev * 3))
            yield prev

    async def call_async(
        self,
        fn: Callable,
        *args,
        site: str = "unnamed",
        classify: Callable[[BaseException], bool] = is_transient,
        no_retry: tuple = (),
        delay_floor: Optional[Callable[[BaseException], Optional[float]]] = None,
        **kwargs,
    ):
        """Run ``fn(*args, **kwargs)``, retrying transient failures.

        Non-transient errors (and ``no_retry`` types) propagate untouched on
        the first failure. When the policy is exhausted the LAST transient
        error propagates (not a wrapper): callers keep their existing
        except clauses, and the giveup is recorded on the metrics instead.
        ``delay_floor(err)`` may return a minimum for the next sleep — the
        SDK client feeds a server-sent ``Retry-After`` through it so a
        shedding coordinator is never hammered faster than it asked.
        """
        t0 = time.monotonic()
        attempts = 0
        schedule = self.delays()
        while True:
            attempts += 1
            try:
                return await fn(*args, **kwargs)
            except no_retry:
                raise
            except asyncio.CancelledError:
                # cancellation is a control signal, never a fault to retry
                # (no classify hook can override this)
                raise
            except BaseException as err:
                if not classify(err):
                    raise
                delay = next(schedule, None)
                if delay is not None and delay_floor is not None:
                    floor = delay_floor(err)
                    if floor:
                        delay = max(delay, float(floor))
                elapsed = time.monotonic() - t0
                if delay is None or elapsed + delay > self.deadline_s:
                    GIVEUPS.labels(site=site).inc()
                    raise
                RETRIES.labels(site=site).inc()
                RETRY_BACKOFF_SECONDS.labels(site=site).inc(delay)
                await asyncio.sleep(delay)


def policy_from_settings(resilience, rng: Optional[random.Random] = None) -> RetryPolicy:
    """Build the storage-call policy from a ``ResilienceSettings`` section."""
    return RetryPolicy(
        max_attempts=resilience.retry_max_attempts,
        base_delay_s=resilience.retry_base_ms / 1000.0,
        max_delay_s=resilience.retry_max_ms / 1000.0,
        deadline_s=resilience.retry_deadline_s,
        rng=rng if rng is not None else random.Random(),
    )
