"""Resilience subsystem: retries, circuit breaking, durable mid-round
checkpoints, and deterministic fault injection.

- ``policy``     — :class:`RetryPolicy` (decorrelated-jitter backoff,
  attempt/deadline caps) and transient/permanent error classification;
- ``breaker``    — :class:`CircuitBreaker` with half-open probing;
- ``store``      — :class:`ResilientStore`, the decorator wrapping every
  ``CoordinatorStorage``/``ModelStorage``/``TrustAnchor`` call;
- ``checkpoint`` — :class:`RoundCheckpoint` (the phase-tagged round
  journal) + the update-phase :class:`CheckpointManager` and resume
  validation;
- ``chaos``      — the ``XAYNET_KILL_POINT`` SIGKILL hook the kill-matrix
  harness drives;
- ``faults``     — seeded :class:`FaultPlan` driving reproducible chaos
  through storage, ingest and the streaming fold pipeline.
"""

from .breaker import BreakerOpen as BreakerOpen, CircuitBreaker as CircuitBreaker
from .chaos import maybe_kill as maybe_kill
from .checkpoint import (
    AggSnapshot as AggSnapshot,
    CheckpointManager as CheckpointManager,
    RoundCheckpoint as RoundCheckpoint,
)
from .faults import (
    FaultPlan as FaultPlan,
    InjectedFault as InjectedFault,
    clear_plan as clear_plan,
    current_plan as current_plan,
    install_plan as install_plan,
)
from .policy import RetryPolicy as RetryPolicy, is_transient as is_transient
from .store import ResilientStore as ResilientStore, wrap_store as wrap_store
