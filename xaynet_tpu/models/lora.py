"""Federated LoRA adapters — baseline config #5 (stretch).

Baseline analogue: BASELINE.md config #5.

Instead of masking a full LLM, each participant trains low-rank adapters
(A: [d, r], B: [r, k]) over frozen base weights and federates only the
adapter deltas. The deltas are quantized to int32 fixed-point before
masking (integer mask configs over quantized deltas), which shrinks the
masked payload and matches the I32 branch of the group catalogue.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax


@dataclass
class LoraSpec:
    """Shapes of the adapted matrices: name -> (d, k)."""

    targets: dict
    rank: int = 8
    alpha: float = 16.0


def init_adapters(rng, spec: LoraSpec):
    """A ~ N(0, 1/r), B = 0 (standard LoRA init)."""
    params = {}
    for name, (d, k) in spec.targets.items():
        rng, ra = jax.random.split(rng)
        params[name] = {
            "A": jax.random.normal(ra, (d, spec.rank), dtype=jnp.float32) / spec.rank,
            "B": jnp.zeros((spec.rank, k), dtype=jnp.float32),
        }
    return params


def apply_adapter(base_out, x, adapter, alpha: float, rank: int):
    """base_out + (alpha / r) * x @ A @ B — fused onto the MXU."""
    return base_out + (alpha / rank) * (x @ adapter["A"] @ adapter["B"])


def make_train_step(loss_fn: Callable, learning_rate: float = 1e-3):
    """Generic adapter training step: only adapter params receive gradients."""
    tx = optax.adam(learning_rate)

    @jax.jit
    def step(adapters, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(adapters, batch)
        updates, opt_state = tx.update(grads, opt_state, adapters)
        return optax.apply_updates(adapters, updates), opt_state, loss

    return tx, step


# --- quantized federation -----------------------------------------------


def quantize_deltas(adapters, scale: int = 10**6) -> np.ndarray:
    """Flatten adapters and quantize to int32 fixed-point for I32 masking."""
    leaves = jax.tree_util.tree_leaves(adapters)
    flat = np.concatenate([np.asarray(l).ravel() for l in leaves]).astype(np.float64)
    q = np.clip(np.rint(flat * scale), -(2**31) + 1, 2**31 - 1).astype(np.int64)
    return q


def dequantize_deltas(q: np.ndarray, template, scale: int = 10**6):
    """Inverse of ``quantize_deltas`` against a template pytree."""
    flat = np.asarray(q, dtype=np.float64) / scale
    leaves, treedef = jax.tree_util.tree_flatten(template)
    out, pos = [], 0
    for leaf in leaves:
        n = int(np.prod(leaf.shape)) if leaf.shape else 1
        out.append(jnp.asarray(flat[pos : pos + n], dtype=leaf.dtype).reshape(leaf.shape))
        pos += n
    return jax.tree_util.tree_unflatten(treedef, out)
