"""LeNet-style CNN — baseline config #2 (CIFAR-10, 100 participants).

Baseline analogue: BASELINE.md config #2 (the reference exposes models
through its python SDK; this family is the CIFAR-10 equivalent).

Convolutions run on the MXU; the local step is fully jittable and the
parameter vector plugs straight into the masking pipeline via
``flatten_params``.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax


class LeNet(nn.Module):
    """Classic conv-conv-dense classifier (CIFAR-10 shapes)."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, x):  # x: [B, 32, 32, 3]
        x = nn.relu(nn.Conv(6, (5, 5))(x))
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(16, (5, 5))(x))
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(120)(x))
        x = nn.relu(nn.Dense(84)(x))
        return nn.Dense(self.num_classes)(x)


def init_params(rng, image_shape=(32, 32, 3), num_classes: int = 10):
    model = LeNet(num_classes)
    return model.init(rng, jnp.zeros((1, *image_shape)))


def make_train_step(num_classes: int = 10, learning_rate: float = 1e-3):
    """(model, tx, jittable step): cross-entropy SGD on one batch."""
    model = LeNet(num_classes)
    tx = optax.sgd(learning_rate, momentum=0.9)

    def loss_fn(params, x, y):
        logits = model.apply(params, x)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    @jax.jit
    def step(params, opt_state, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return model, tx, step
