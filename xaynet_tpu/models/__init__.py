"""Baseline model families with JAX local training.

Covers the benchmark configurations (BASELINE.md):

1. house-prices regression MLP (``mlp``) — 10 participants;
2. LeNet / CIFAR-10 (``lenet``) — 100 simulated participants;
3. character LSTM next-token (``lstm``) — LEAF-Shakespeare shaped;
4. ResNet-50 (``resnet``) — the 25M-parameter aggregation stress model;
5. LoRA adapters (``lora``) — federated low-rank deltas (stretch config).

Every family exposes ``init_params`` and a jittable train step; the
``federated`` module glues any of them into a PET participant.
"""

from .mlp import MLP, flatten_params, unflatten_params

__all__ = ["MLP", "flatten_params", "unflatten_params"]
