"""ResNet-50 — baseline config #4 (the ~25M-parameter aggregation stress).

Standard bottleneck ResNet in flax; used to produce realistically-sized
update vectors for the aggregation benchmarks and for federated vision
training. bfloat16 activations keep the MXU fed; parameters stay f32 for
the masking pipeline.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax


class Bottleneck(nn.Module):
    features: int
    strides: int = 1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(nn.GroupNorm, num_groups=32, dtype=self.dtype)

        residual = x
        y = nn.relu(norm()(conv(self.features, (1, 1))(x)))
        y = nn.relu(norm()(conv(self.features, (3, 3), strides=(self.strides, self.strides))(y)))
        y = norm()(conv(self.features * 4, (1, 1))(y))
        if residual.shape != y.shape:
            residual = norm()(
                conv(self.features * 4, (1, 1), strides=(self.strides, self.strides))(residual)
            )
        return nn.relu(y + residual)


class ResNet50(nn.Module):
    num_classes: int = 1000
    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):  # [B, H, W, 3]
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        x = conv(64, (7, 7), strides=(2, 2))(x)
        x = nn.relu(nn.GroupNorm(num_groups=32, dtype=self.dtype)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = Bottleneck(64 * 2**i, strides=strides, dtype=self.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


def init_params(rng, image_shape=(64, 64, 3), num_classes: int = 1000,
                stage_sizes: Sequence[int] = (3, 4, 6, 3)):
    model = ResNet50(num_classes, stage_sizes=tuple(stage_sizes))
    return model.init(rng, jnp.zeros((1, *image_shape)))


def make_train_step(num_classes: int = 1000, learning_rate: float = 0.1,
                    stage_sizes: Sequence[int] = (3, 4, 6, 3)):
    model = ResNet50(num_classes, stage_sizes=tuple(stage_sizes))
    tx = optax.sgd(learning_rate, momentum=0.9)

    def loss_fn(params, x, y):
        logits = model.apply(params, x)
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    @jax.jit
    def step(params, opt_state, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return model, tx, step


def param_count(params) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
