"""Glue between JAX model families and the PET participant API.

``FederatedTrainer`` wraps (init_params, train_step, local data) into a
``ParticipantABC``: each round it deserializes the global model into
parameters, runs E local epochs (jitted), and returns the flattened weight
vector for masking — the analogue of the reference's keras participant
(reference: bindings/python/examples/keras_house_prices/).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import numpy as np

from ..sdk.api import ParticipantABC
from .mlp import flatten_params, unflatten_params


class FederatedTrainer(ParticipantABC):
    """Local trainer for any (params, step) JAX model."""

    def __init__(
        self,
        init_params_fn: Callable[[], object],
        make_step: Callable[[], tuple],
        data: tuple[np.ndarray, np.ndarray],
        epochs: int = 1,
        batch_size: int = 32,
        seed: int = 0,
    ):
        self.params = init_params_fn()
        _, self.tx, self.step = make_step()
        self.opt_state = self.tx.init(self.params)
        self.x, self.y = data
        self.epochs = epochs
        self.batch_size = batch_size
        self.rng = np.random.default_rng(seed)
        self.last_loss: Optional[float] = None

    def train_round(self, training_input):
        if training_input is not None:
            self.params = unflatten_params(self.params, np.asarray(training_input, np.float32))
            self.opt_state = self.tx.init(self.params)
        n = self.x.shape[0]
        for _ in range(self.epochs):
            order = self.rng.permutation(n)
            for start in range(0, n - self.batch_size + 1, self.batch_size):
                idx = order[start : start + self.batch_size]
                self.params, self.opt_state, loss = self.step(
                    self.params, self.opt_state, self.x[idx], self.y[idx]
                )
            self.last_loss = float(loss)
        return flatten_params(self.params)

    def deserialize_training_input(self, global_model):
        return np.asarray(global_model, dtype=np.float32)


def model_length(params) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
