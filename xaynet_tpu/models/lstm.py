"""Character-level LSTM — baseline config #3 (LEAF-Shakespeare shaped).

Baseline analogue: BASELINE.md config #3.

Next-character prediction over an 80-symbol vocabulary (the LEAF benchmark
shape): embedding -> 2-layer LSTM (via ``flax.linen.scan`` — compiler-
friendly sequence recurrence, no python loops under jit) -> projection.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

VOCAB_SIZE = 80  # LEAF Shakespeare symbol count


class CharLSTM(nn.Module):
    vocab_size: int = VOCAB_SIZE
    hidden: int = 256
    embed: int = 8

    @nn.compact
    def __call__(self, tokens):  # [B, T] int32
        x = nn.Embed(self.vocab_size, self.embed)(tokens)  # [B, T, E]
        for layer in range(2):
            cell = nn.OptimizedLSTMCell(self.hidden, name=f"lstm{layer}")
            scan = nn.RNN(cell)  # internally a lax.scan over T
            x = scan(x)
        return nn.Dense(self.vocab_size)(x)  # [B, T, V]


def init_params(rng, seq_len: int = 80, vocab_size: int = VOCAB_SIZE, hidden: int = 256):
    model = CharLSTM(vocab_size, hidden)
    return model.init(rng, jnp.zeros((1, seq_len), dtype=jnp.int32))


def make_train_step(vocab_size: int = VOCAB_SIZE, hidden: int = 256, learning_rate: float = 1e-3):
    model = CharLSTM(vocab_size, hidden)
    tx = optax.adam(learning_rate)

    def loss_fn(params, tokens, targets):
        logits = model.apply(params, tokens)
        return optax.softmax_cross_entropy_with_integer_labels(logits, targets).mean()

    @jax.jit
    def step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return model, tx, step
