"""Regression MLP — baseline config #1 (keras house-prices analogue).

The reference ships a 2-layer Keras MLP example trained by 10 federated
participants (reference: bindings/python/examples/keras_house_prices/). This
is the JAX/flax equivalent with a jittable local-training step; participants
run it inside ``train_round`` and hand the flattened weight vector to the
masking pipeline.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax


class MLP(nn.Module):
    """2-hidden-layer regression MLP (house-prices baseline)."""

    features: Sequence[int] = (64, 32)

    @nn.compact
    def __call__(self, x):
        for f in self.features:
            x = nn.relu(nn.Dense(f)(x))
        return nn.Dense(1)(x)


def init_params(rng, input_dim: int, features: Sequence[int] = (64, 32)):
    model = MLP(features)
    return model.init(rng, jnp.zeros((1, input_dim)))


def make_train_step(features: Sequence[int] = (64, 32), learning_rate: float = 1e-3):
    """Returns (jittable) ``step(params, opt_state, x, y) -> (params, opt_state, loss)``."""
    model = MLP(features)
    tx = optax.adam(learning_rate)

    def loss_fn(params, x, y):
        pred = model.apply(params, x)
        return jnp.mean((pred.squeeze(-1) - y) ** 2)

    def step(params, opt_state, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return model, tx, step


def flatten_params(params) -> np.ndarray:
    """Flatten a pytree of weights into one f32 vector (masking order)."""
    leaves = jax.tree_util.tree_leaves(params)
    return np.concatenate([np.asarray(l).ravel() for l in leaves]).astype(np.float32)


def unflatten_params(template, flat: np.ndarray):
    """Inverse of ``flatten_params`` against a template pytree."""
    leaves, treedef = jax.tree_util.tree_flatten(template)
    out = []
    pos = 0
    for leaf in leaves:
        n = int(np.prod(leaf.shape)) if leaf.shape else 1
        out.append(jnp.asarray(flat[pos : pos + n], dtype=leaf.dtype).reshape(leaf.shape))
        pos += n
    return jax.tree_util.tree_unflatten(treedef, out)
