"""Bridge: the reference ``Metrics`` recorder surface on top of the registry.

``BridgedMetrics`` implements the same eight-measurement recorder interface
as ``server.metrics.Metrics`` (duck-typed — no import of the server layer),
so it drops into ``Shared.metrics`` unchanged. Every measurement

- lands in the telemetry registry (phase-duration histograms, message
  outcome counters, round/mask gauges) for ``GET /metrics``;
- feeds the per-round JSON reporter, when one is attached;
- is forwarded verbatim to an optional inner sink (``JsonlMetrics``,
  ``InfluxLineMetrics``, ``InfluxHttpMetrics``, ...) — so the existing
  Influx line-protocol output is byte-for-byte what it was before the
  registry existed.

The telemetry design is one-registry-per-process: hot-path modules
(request queue, message pipeline, kernel profiling, dispatcher health)
bind their families to ``get_registry()`` at import time, and the
per-round kernel window in ``profiling`` is process-global. Passing a
custom ``registry`` here isolates only the bridge-owned families (useful
in unit tests); it does not re-home the module-level series, and two
coordinators in one process share the global series.
"""

from __future__ import annotations

from typing import Optional

from .registry import DEFAULT_BUCKETS, MetricsRegistry, get_registry
from .report import RoundReporter

# per-request handler latencies are ms-scale; phase windows minute-scale
_HANDLE_BUCKETS = tuple(b for b in DEFAULT_BUCKETS if b <= 10.0)


class BridgedMetrics:
    """Registry-first recorder with optional sink and round-report fan-out."""

    def __init__(
        self,
        sink=None,
        reporter: Optional[RoundReporter] = None,
        registry: Optional[MetricsRegistry] = None,
    ):
        self.sink = sink
        self.reporter = reporter
        self.registry = registry if registry is not None else get_registry()
        r = self.registry
        self._round_id = r.gauge("xaynet_round_id", "Current PET round id.")
        self._phase_transitions = r.counter(
            "xaynet_phase_transitions_total", "Phase entries by phase name.", ("phase",)
        )
        self._messages = r.counter(
            "xaynet_messages_total",
            "Requests handled by the state machine, by phase and outcome.",
            ("phase", "outcome"),
        )
        self._masks = r.gauge(
            "xaynet_masks_total", "Unique masks submitted in the current round."
        )
        self._phase_duration = r.histogram(
            "xaynet_phase_duration_seconds",
            "Wall time of one phase run (process + purge).",
            ("phase",),
        )
        self._handle_duration = r.histogram(
            "xaynet_request_handle_seconds",
            "State-machine handling time of one accepted/rejected request.",
            ("phase",),
            buckets=_HANDLE_BUCKETS,
        )
        self._events = r.counter(
            "xaynet_events_total", "Free-form coordinator events by kind.", ("kind",)
        )

    # --- the eight reference measurements ---------------------------------

    def phase(self, round_id: int, phase: str) -> None:
        self._phase_transitions.labels(phase=phase).inc()
        if self.reporter is not None:
            self.reporter.record_phase(phase)
        if self.sink is not None:
            self.sink.phase(round_id, phase)

    def round_total(self, round_id: int) -> None:
        self._round_id.set(round_id)
        if self.reporter is not None:
            self.reporter.begin_round(round_id)
        if self.sink is not None:
            self.sink.round_total(round_id)

    def message_accepted(self, round_id: int, phase: str) -> None:
        self._message(round_id, phase, "accepted")

    def message_rejected(self, round_id: int, phase: str) -> None:
        self._message(round_id, phase, "rejected")

    def message_discarded(self, round_id: int, phase: str) -> None:
        self._message(round_id, phase, "discarded")

    def message_purged(self, round_id: int, phase: str) -> None:
        """Phase-end purge (degraded-close stragglers included): its own
        outcome label so purge bursts don't pollute reject-rate panels."""
        self._message(round_id, phase, "purged")

    def _message(self, round_id: int, phase: str, outcome: str) -> None:
        self._messages.labels(phase=phase, outcome=outcome).inc()
        if self.reporter is not None:
            self.reporter.record_message(phase, outcome)
        if self.sink is not None:
            # sinks predating the purged outcome fold purges into rejects
            emit = getattr(self.sink, f"message_{outcome}", None) or self.sink.message_rejected
            emit(round_id, phase)

    def masks_total(self, round_id: int, count: int) -> None:
        self._masks.set(count)
        if self.reporter is not None:
            self.reporter.record_masks_total(count)
        if self.sink is not None:
            self.sink.masks_total(round_id, count)

    def phase_duration(self, round_id: int, phase: str, seconds: float) -> None:
        self._phase_duration.labels(phase=phase).observe(seconds)
        if self.reporter is not None:
            self.reporter.record_phase_duration(phase, seconds)
        if self.sink is not None:
            self.sink.phase_duration(round_id, phase, seconds)

    def event(self, round_id: int, kind: str, detail: str = "") -> None:
        self._events.labels(kind=kind).inc()
        if self.reporter is not None:
            self.reporter.record_event(kind, detail)
        if self.sink is not None:
            self.sink.event(round_id, kind, detail)

    # --- registry-only extensions (not part of the sink contract) ---------

    def request_handled(self, round_id: int, phase: str, seconds: float) -> None:
        """Per-request handler latency; too hot for the line-protocol sinks."""
        self._handle_duration.labels(phase=phase).observe(seconds)

    def close(self) -> None:
        """Flush the in-flight round report and drain the sink."""
        if self.reporter is not None:
            self.reporter.flush()
        if self.sink is not None:
            self.sink.close()
